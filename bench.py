#!/usr/bin/env python
"""Benchmark driver: batched ECDSA-P256 verification throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline metric per BASELINE.md: ECDSA-P256 verifies/sec/chip on the
device batch verifier vs the software CSP (`bccsp.sw`, backed by
OpenSSL via the `cryptography` package — the analog of the reference's
bccsp/sw, bccsp/sw/ecdsa.go:41-57).  The measured path is end-to-end
through TpuVerifier.verify_many: host DER decode + range checks +
limb marshalling + one jitted device program per bucket — the same
path the block validator uses, so the number is honest about host
overheads, not a kernel-only figure.

Baseline is measured in-process each run (same machine, same OpenSSL)
rather than hard-coded.  Diagnostics go to stderr; stdout carries
exactly the one JSON line the driver parses.
"""
import argparse
import hashlib
import json
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_items(n: int, n_keys: int = 64):
    """n real signatures (~0.4% deliberately invalid) as VerifyItems,
    from the shared fixture generator (sw-provider signing, so low-S
    normalized like production)."""
    from fabric_mod_tpu.utils.fixtures import make_verify_items

    return make_verify_items(n, n_keys=n_keys, invalid_every=256,
                             seed=b"bench")


def measure_sw(items, expect) -> float:
    from fabric_mod_tpu.bccsp.sw import SwCSP

    csp = SwCSP()
    sub = items[:256]
    t0 = time.perf_counter()
    got = csp.verify_batch(sub)
    dt = time.perf_counter() - t0
    if got != expect[:256]:
        raise AssertionError("sw baseline verdicts wrong")
    return len(sub) / dt


def measure_device(items, expect, reps: int) -> float:
    import jax

    from fabric_mod_tpu.bccsp.tpu import TpuVerifier

    log(f"jax platform: {jax.devices()[0].platform}, "
        f"{len(jax.devices())} device(s)")
    v = TpuVerifier()
    t0 = time.perf_counter()
    got = v.verify_many(items)          # includes compile on cold cache
    log(f"warm-up (incl. compile): {time.perf_counter() - t0:.1f}s")
    if list(got) != expect:
        bad = [i for i, (g, e) in enumerate(zip(got, expect)) if g != e]
        raise AssertionError(f"device verdicts wrong at {bad[:10]}")
    t0 = time.perf_counter()
    for _ in range(reps):
        v.verify_many(items)
    dt = time.perf_counter() - t0
    return len(items) * reps / dt


def _block_world(n_txs: int):
    """A 1000-tx-style block world: 3 orgs, 2-of-3 endorsement
    (BASELINE config #2; reference: txvalidator/v20/validator.go:182)."""
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder
    from fabric_mod_tpu.msp import ca as calib
    from fabric_mod_tpu.msp.identities import SigningIdentity
    from fabric_mod_tpu.msp.mspimpl import Msp, MspManager
    from fabric_mod_tpu.peer import TxValidator, ValidationInfoProvider
    from fabric_mod_tpu.policy import ApplicationPolicyEvaluator, from_string
    from fabric_mod_tpu.protos import messages as m
    from fabric_mod_tpu.protos import protoutil

    csp = SwCSP()
    msps, signers = [], {}
    for org in ("Org1", "Org2", "Org3"):
        ca = calib.CA(f"ca.{org.lower()}", org)
        msps.append(Msp(org, csp, [ca.cert]))
        cert, key = ca.issue(f"peer0.{org.lower()}", org, ous=["peer"])
        signers[org] = SigningIdentity(org, cert, calib.key_pem(key), csp)
        if org == "Org1":
            ccert, ckey = ca.issue("client@org1", org, ous=["client"])
            signers["client"] = SigningIdentity(
                org, ccert, calib.key_pem(ckey), csp)
    mgr = MspManager(msps)
    policy = m.ApplicationPolicy(signature_policy=from_string(
        "OutOf(2, 'Org1.peer', 'Org2.peer', 'Org3.peer')")).encode()

    envs = []
    for i in range(n_txs):
        b = RWSetBuilder()
        b.add_write("mycc", f"key{i}", b"val%d" % i)
        envs.append(protoutil.create_signed_tx(
            "bench", "mycc", b.build().encode(), signers["client"],
            [signers["Org1"], signers["Org2"]]))
    block = protoutil.new_block(0, b"", envs)

    def make_validator(verifier):
        return TxValidator("bench", mgr,
                           ApplicationPolicyEvaluator(mgr), verifier,
                           ValidationInfoProvider(policy))
    return block, make_validator


def measure_block(n_txs: int, reps: int) -> tuple:
    """Validated tx/s, device batch verifier vs sw provider.
    validate() mutates only the txflags metadata, so reps re-validate
    the same block object — no copying inside the timed loop."""
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier, TpuVerifier

    block, make_validator = _block_world(n_txs)
    V = 0  # TxValidationCode.VALID

    def run(validator, reps):
        t0 = time.perf_counter()
        for _ in range(reps):
            flags = validator.validate(block)
            if any(f != V for f in flags):
                raise AssertionError("bench block failed validation")
        return n_txs * reps / (time.perf_counter() - t0)

    sw_validator = make_validator(FakeBatchVerifier(SwCSP()))
    sw_rate = run(sw_validator, 1)
    log(f"sw block validation: {sw_rate:,.0f} tx/s")
    dev_validator = make_validator(TpuVerifier())
    run(dev_validator, 1)                   # warm-up/compile
    dev_rate = run(dev_validator, reps)
    log(f"device block validation: {dev_rate:,.0f} tx/s")
    return dev_rate, sw_rate


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--metric", choices=("verify", "block"),
                    default="verify")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local testing)")
    args = ap.parse_args()

    if args.cpu:
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.metric == "block":
        dev_rate, sw_rate = measure_block(min(args.batch, 1000), args.reps)
        print(json.dumps({
            "metric": "validated_tx_per_sec_1k_block_2of3",
            "value": round(dev_rate, 1),
            "unit": "tx/s",
            "vs_baseline": round(dev_rate / sw_rate, 3),
        }))
        return 0

    items, expect = make_items(args.batch)
    sw_rate = measure_sw(items, expect)
    log(f"sw baseline: {sw_rate:,.0f} verifies/s")
    dev_rate = measure_device(items, expect, args.reps)
    log(f"device: {dev_rate:,.0f} verifies/s "
        f"({dev_rate / sw_rate:.2f}x sw)")

    print(json.dumps({
        "metric": "ecdsa_p256_verifies_per_sec",
        "value": round(dev_rate, 1),
        "unit": "verifies/s",
        "vs_baseline": round(dev_rate / sw_rate, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
