#!/usr/bin/env python
"""Benchmark driver: batched ECDSA-P256 verification throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline metric per BASELINE.md: ECDSA-P256 verifies/sec/chip on the
device batch verifier vs the software CSP (`bccsp.sw`, backed by
OpenSSL via the `cryptography` package — the analog of the reference's
bccsp/sw, bccsp/sw/ecdsa.go:41-57).  The measured path is end-to-end
through TpuVerifier.verify_many: host DER decode + range checks +
limb marshalling + one jitted device program per bucket — the same
path the block validator uses, so the number is honest about host
overheads, not a kernel-only figure.

Baseline is measured in-process each run (same machine, same OpenSSL)
rather than hard-coded.  Diagnostics go to stderr; stdout carries
exactly the one JSON line the driver parses.
"""
import argparse
import hashlib
import json
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_items(n: int, n_keys: int = 64):
    """n real signatures (~0.4% deliberately invalid) as VerifyItems,
    from the shared fixture generator (sw-provider signing, so low-S
    normalized like production)."""
    from fabric_mod_tpu.utils.fixtures import make_verify_items

    return make_verify_items(n, n_keys=n_keys, invalid_every=256,
                             seed=b"bench")


def measure_sw(items, expect) -> float:
    from fabric_mod_tpu.bccsp.sw import SwCSP

    csp = SwCSP()
    sub = items[:256]
    t0 = time.perf_counter()
    got = csp.verify_batch(sub)
    dt = time.perf_counter() - t0
    if got != expect[:256]:
        raise AssertionError("sw baseline verdicts wrong")
    return len(sub) / dt


def measure_device(items, expect, reps: int) -> float:
    import jax

    from fabric_mod_tpu.bccsp.tpu import TpuVerifier

    log(f"jax platform: {jax.devices()[0].platform}, "
        f"{len(jax.devices())} device(s)")
    v = TpuVerifier()
    t0 = time.perf_counter()
    got = v.verify_many(items)          # includes compile on cold cache
    log(f"warm-up (incl. compile): {time.perf_counter() - t0:.1f}s")
    if list(got) != expect:
        bad = [i for i, (g, e) in enumerate(zip(got, expect)) if g != e]
        raise AssertionError(f"device verdicts wrong at {bad[:10]}")
    t0 = time.perf_counter()
    for _ in range(reps):
        v.verify_many(items)
    dt = time.perf_counter() - t0
    return len(items) * reps / dt


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local testing)")
    args = ap.parse_args()

    if args.cpu:
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")

    items, expect = make_items(args.batch)
    sw_rate = measure_sw(items, expect)
    log(f"sw baseline: {sw_rate:,.0f} verifies/s")
    dev_rate = measure_device(items, expect, args.reps)
    log(f"device: {dev_rate:,.0f} verifies/s "
        f"({dev_rate / sw_rate:.2f}x sw)")

    print(json.dumps({
        "metric": "ecdsa_p256_verifies_per_sec",
        "value": round(dev_rate, 1),
        "unit": "verifies/s",
        "vs_baseline": round(dev_rate / sw_rate, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
