#!/usr/bin/env python
"""Benchmark driver: batched ECDSA-P256 verification throughput on device.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The headline metric matches BASELINE.json: ECDSA-P256 verifies/sec/chip on
the device batch verifier vs. the software CSP (the `sw` provider, backed by
OpenSSL via the `cryptography` package — the analog of the reference's
bccsp/sw, bccsp/sw/ecdsa.go:41).
"""
import json
import sys
import time


def main() -> None:
    # Placeholder until the kernels land: measure the sw provider only and
    # report 1.0x. Replaced by the real device-vs-host comparison in task 9.
    value = 0.0
    vs = 0.0
    print(json.dumps({
        "metric": "ecdsa_p256_verifies_per_sec",
        "value": value,
        "unit": "verifies/s",
        "vs_baseline": vs,
    }))


if __name__ == "__main__":
    sys.exit(main())
