#!/usr/bin/env python
"""Benchmark driver: batched ECDSA-P256 verification throughput on device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Headline metric per BASELINE.md: ECDSA-P256 verifies/sec/chip on the
device batch verifier vs the software CSP (`bccsp.sw`, backed by
OpenSSL via the `cryptography` package — the analog of the reference's
bccsp/sw, bccsp/sw/ecdsa.go:41-57).  The measured path is end-to-end
through TpuVerifier.verify_many: host DER decode + range checks +
limb marshalling + one jitted device program per bucket — the same
path the block validator uses, so the number is honest about host
overheads, not a kernel-only figure.

Robustness (BENCH_r02 post-mortem): the TPU backend behind the axon
tunnel can FAIL (UNAVAILABLE) or HANG INDEFINITELY at jax.devices().
All jax work therefore runs in a supervised child process with a hard
timeout and bounded retries; if the TPU never comes up the supervisor
re-runs the same measurement on the CPU backend and reports it with
"platform": "cpu" plus a diagnosis — a real number with an honest
label instead of rc=1.

Baseline is measured in-process each run (same machine, same OpenSSL)
rather than hard-coded.  Diagnostics go to stderr; stdout carries
exactly the one JSON line the driver parses.
"""
import argparse
import json
import os
import subprocess
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Measurement (runs inside the worker child)
# ---------------------------------------------------------------------------

def make_items(n: int, n_keys: int = 64):
    """n real signatures (~0.4% deliberately invalid) as VerifyItems,
    from the shared fixture generator (sw-provider signing, so low-S
    normalized like production)."""
    from fabric_mod_tpu.utils.fixtures import make_verify_items

    return make_verify_items(n, n_keys=n_keys, invalid_every=256,
                             seed=b"bench")


def measure_marshal(n: int, reps: int) -> tuple:
    """Host marshalling microbench: the vectorized batch path
    (bccsp/tpu.marshal_items) vs the pre-overhaul per-item python loop
    (reproduced verbatim below), same items, outputs asserted
    identical.  Pure host work — no device, no jit."""
    import numpy as np

    from fabric_mod_tpu.bccsp import sw as _sw
    from fabric_mod_tpu.bccsp.tpu import _LOW_S_MAX, marshal_items

    items, _ = make_items(n)
    size = n

    def per_item_loop():
        # The old TpuVerifier.verify_many_async marshalling loop,
        # kept as the A/B baseline.
        d = np.zeros((size, 32), np.uint8)
        r = np.zeros((size, 32), np.uint8)
        s = np.zeros((size, 32), np.uint8)
        qx = np.zeros((size, 32), np.uint8)
        qy = np.zeros((size, 32), np.uint8)
        pre_ok = np.zeros(size, bool)
        for i, it in enumerate(items):
            try:
                ri, si = _sw.decode_dss_signature(it.signature)
                if not (len(it.digest) == 32 and len(it.public_xy) == 64):
                    continue
                if si > _LOW_S_MAX:
                    continue
                r[i] = np.frombuffer(ri.to_bytes(32, "big"), np.uint8)
                s[i] = np.frombuffer(si.to_bytes(32, "big"), np.uint8)
                d[i] = np.frombuffer(it.digest, np.uint8)
                qx[i] = np.frombuffer(it.public_xy[:32], np.uint8)
                qy[i] = np.frombuffer(it.public_xy[32:], np.uint8)
                pre_ok[i] = True
            except Exception:
                continue
        return d, r, s, qx, qy, pre_ok

    loop_out = per_item_loop()                   # warm-up + reference
    vec_out = marshal_items(items, size)
    if not np.array_equal(vec_out[5], loop_out[5]):
        raise AssertionError("vectorized marshal diverges on pre_ok")
    # value planes compared on pre_ok rows only: the old loop zeroes
    # rejected rows, the batch path leaves decoded-but-masked bytes
    # (both are discarded — pre_ok gates the verdict)
    okrows = vec_out[5]
    for a, b, name in zip(vec_out, loop_out, ("d", "r", "s", "qx", "qy")):
        if not np.array_equal(a[okrows], b[okrows]):
            raise AssertionError(f"vectorized marshal diverges on {name}")

    # INTERLEAVED min-of-k timing: the two paths alternate windows so
    # noisy-neighbor slowdowns hit both alike, and the fastest window
    # of each stands in for the uncontended cost — the ratio is then
    # a property of the code, not of the machine's mood.
    loop_best = vec_best = float("inf")
    for _ in range(max(reps, 7)):
        t0 = time.perf_counter()
        per_item_loop()
        loop_best = min(loop_best, time.perf_counter() - t0)
        t0 = time.perf_counter()
        marshal_items(items, size)
        vec_best = min(vec_best, time.perf_counter() - t0)
    loop_rate = n / loop_best
    vec_rate = n / vec_best
    backend = "openssl" if _sw.HAVE_CRYPTOGRAPHY else "pure-python-scalar"
    log(f"per-item loop ({backend} DER): {loop_rate:,.0f} items/s; "
        f"vectorized: {vec_rate:,.0f} items/s "
        f"({vec_rate / loop_rate:.1f}x)")
    return vec_rate, loop_rate


def measure_diffverify(n: int) -> tuple:
    """Differential acceptance check: every enabled ladder core must
    produce IDENTICAL verdicts to the projective XLA core on n
    randomized signatures including invalid and edge-case lanes, and
    the fused raw-message path must match host-side hashing.  Chunked
    through one static bucket so each core compiles once.

    Also times each core on the same chunks (interleaved min-of-k) —
    the on-chip mixed-vs-projective A/B the ROADMAP's "measure before
    defaulting on" question needs; the ratio lands in the JSON line.

    Returns (n, mismatches, extras): mismatches totals across every
    core pair INCLUDING the fused-hash differential.
    """
    import numpy as np

    from fabric_mod_tpu.bccsp.tpu import marshal_items
    from fabric_mod_tpu.ops import p256

    items, expect = make_items(n, n_keys=32)
    # the one tested marshalling path; copies because the edge-case
    # lanes below mutate the planes (fast-path outputs are read-only)
    d, r, s, qx, qy, _pre_ok, _msg = (
        a.copy() if isinstance(a, np.ndarray) else a
        for a in marshal_items(items, n))
    # adversarial/edge lanes sprinkled across the batch (mirrors
    # tests/test_p256.py's negatives): tampered digest, wrong key,
    # zero/overrange scalars, off-curve key, (0,0) key, high-s mirror
    N_ORDER = p256.N
    for base in range(0, n - 8, 97):
        d[base][0] ^= 1
        qx[base + 1], qy[base + 1] = qx[base + 2], qy[base + 2]
        s[base + 3][:] = 0
        r[base + 4][:] = np.frombuffer(
            N_ORDER.to_bytes(32, "big"), np.uint8)
        qy[base + 5][31] ^= 1
        qx[base + 6][:] = 0
        qy[base + 6][:] = 0
        s_int = int.from_bytes(bytes(s[base + 7]), "big")
        if 0 < s_int < N_ORDER:
            s[base + 7] = np.frombuffer(
                (N_ORDER - s_int).to_bytes(32, "big"), np.uint8)

    # pad to a whole number of fixed-size chunks so each core compiles
    # ONCE (a remainder chunk would mint a second multi-minute program
    # shape); zero rows fail range_ok identically in every core.
    # Small runs (the CPU smoke target) use one right-sized chunk.
    chunk = 2048 if n >= 2048 else max(8, n + (-n) % 8)
    pad = (-n) % chunk
    if pad:
        z = np.zeros((pad, 32), np.uint8)
        d, r, s = (np.concatenate([a, z]) for a in (d, r, s))
        qx, qy = (np.concatenate([a, z]) for a in (qx, qy))

    # every core the env knobs can select, all compared against the
    # projective XLA reference (PALLAS x MIXED_ADD composition matrix)
    cores = {"projective": p256.verify_core,
             "mixed": p256.verify_core_mixed}
    if p256._use_pallas():
        tile = next((t for t in (128, 64, 32, 16, 8)
                     if chunk % t == 0), None)
        if tile is not None:
            cores["pallas_projective"] = p256._pallas_core(tile)
            cores["pallas_mixed"] = p256._pallas_core(tile, mixed=True)

    # warm-up: compile every core on the first chunk OUTSIDE the
    # timing (a cold first call is a multi-minute XLA compile, which
    # would otherwise dominate `best` whenever the batch is one chunk
    # — i.e. exactly the A/B numbers the JSON line reports)
    warm_args, _ = p256.marshal_inputs(
        d[:chunk], r[:chunk], s[:chunk], qx[:chunk], qy[:chunk])
    for name, core in cores.items():
        t1 = time.perf_counter()
        np.asarray(core(*warm_args))
        log(f"{name}: warm-up (incl. compile) "
            f"{time.perf_counter() - t1:.1f}s")

    mismatches = 0
    best = {name: float("inf") for name in cores}
    t0 = time.perf_counter()
    for lo in range(0, n + pad, chunk):
        hi = lo + chunk
        core_args, range_ok = p256.marshal_inputs(
            d[lo:hi], r[lo:hi], s[lo:hi], qx[lo:hi], qy[lo:hi])
        got = {}
        for name, core in cores.items():        # interleaved timing:
            t1 = time.perf_counter()            # noisy neighbors hit
            out = core(*core_args)              # all cores alike
            verdicts = np.asarray(out) & range_ok
            best[name] = min(best[name], time.perf_counter() - t1)
            got[name] = verdicts
        for name, verdicts in got.items():
            if name != "projective":
                mismatches += int((verdicts != got["projective"]).sum())
    log(f"diffverify: {n} signatures x {len(cores)} cores in "
        f"{time.perf_counter() - t0:.1f}s, {mismatches} verdict "
        f"mismatches")
    rates = {name: round(chunk / b, 1) for name, b in best.items()}
    log(f"per-core best-chunk rates (verifies/s): {rates}")

    fused_mm = _fused_hash_differential(min(n, 256))
    mismatches += fused_mm
    extras = {
        "core_rates_verifies_per_sec": rates,
        "mixed_vs_projective_speedup": round(
            best["projective"] / best["mixed"], 3),
        "fused_hash_mismatches": fused_mm,
    }
    if "pallas_mixed" in best:
        extras["pallas_mixed_vs_projective_speedup"] = round(
            best["projective"] / best["pallas_mixed"], 3)
    return n, mismatches, extras


def _fused_hash_differential(k: int) -> int:
    """Raw-message items vs pre-digested items over the SAME payloads
    and signatures (incl. tampered lanes) through TpuVerifier: the
    fused on-device hash must change no verdict.  Returns mismatches."""
    import hashlib

    import numpy as np

    from fabric_mod_tpu.bccsp.api import VerifyItem
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.bccsp.tpu import TpuVerifier

    k = max(8, k + (-k) % 8)
    csp = SwCSP()
    keys = [csp.key_gen() for _ in range(4)]
    raw, dig = [], []
    for i in range(k):
        m = b"fused-%d|" % i + b"x" * (i % 77)
        kp = keys[i % len(keys)]
        sig = csp.sign(kp, hashlib.sha256(m).digest())
        if i % 9 == 5:
            m += b"!"                      # tampered message lane
        raw.append(VerifyItem(b"", sig, kp.public_xy(), message=m))
        dig.append(VerifyItem(hashlib.sha256(m).digest(), sig,
                              kp.public_xy()))
    v = TpuVerifier(cache_size=0)
    got_raw = np.asarray(v.verify_many(raw))
    got_dig = np.asarray(v.verify_many(dig))
    mm = int((got_raw != got_dig).sum())
    log(f"fused-hash differential: {k} items, {mm} mismatches")
    return mm


def measure_hashverify(n: int, reps: int) -> tuple:
    """Fused on-device hash->verify vs host-hash-then-device-verify,
    same payloads/signatures through the same TpuVerifier front door.

    The baseline pays the per-message host hashlib loop the fused path
    deletes (the reference's hash-then-verify shape,
    msp/identities.go:169); both paths' verdicts are asserted
    identical, so the number can't come from a wrong-answer shortcut.
    Messages are ~200-byte envelope-payload-sized."""
    import hashlib

    import numpy as np

    from fabric_mod_tpu.bccsp.api import VerifyItem
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.bccsp.tpu import TpuVerifier

    csp = SwCSP()
    keys = [csp.key_gen() for _ in range(64)]
    msgs, sigs, pubs, expect = [], [], [], []
    log(f"hashverify: signing {n} messages ...")
    for i in range(n):
        m = (b"hashverify-%d|" % i) + b"p" * (150 + i % 100)
        kp = keys[i % len(keys)]
        sig = csp.sign(kp, hashlib.sha256(m).digest())
        bad = i % 256 == 255
        if bad:
            m += b"!"                      # tampered message lane
        msgs.append(m)
        sigs.append(sig)
        pubs.append(kp.public_xy())
        expect.append(not bad)

    raw_items = [VerifyItem(b"", sg, pb, message=m)
                 for m, sg, pb in zip(msgs, sigs, pubs)]

    def host_hash_pass():
        return [VerifyItem(hashlib.sha256(m).digest(), sg, pb)
                for m, sg, pb in zip(msgs, sigs, pubs)]

    v = TpuVerifier(cache_size=0)
    t0 = time.perf_counter()
    got_dig = v.verify_many(host_hash_pass())
    log(f"baseline warm-up (incl. compile): "
        f"{time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    got_raw = v.verify_many(raw_items)
    log(f"fused warm-up (incl. compile): {time.perf_counter() - t0:.1f}s")
    if list(got_raw) != list(got_dig) or list(got_raw) != expect:
        bad = [i for i, (a, b) in enumerate(zip(got_raw, got_dig))
               if a != b]
        raise AssertionError(
            f"fused verdicts diverge from host hashing at {bad[:10]}")

    # interleaved min-of-k (same reasoning as measure_marshal): the
    # baseline re-hashes on the host every rep — that loop is exactly
    # the cost under test
    base_best = fused_best = float("inf")
    for _ in range(max(reps, 3)):
        t0 = time.perf_counter()
        v.verify_many(host_hash_pass())
        base_best = min(base_best, time.perf_counter() - t0)
        t0 = time.perf_counter()
        v.verify_many(raw_items)
        fused_best = min(fused_best, time.perf_counter() - t0)
    fused_rate = n / fused_best
    base_rate = n / base_best
    log(f"host-hash path: {base_rate:,.0f} verifies/s; fused: "
        f"{fused_rate:,.0f} verifies/s ({fused_rate / base_rate:.2f}x)")
    return fused_rate, base_rate


def measure_sw(items, expect) -> float:
    from fabric_mod_tpu.bccsp.sw import SwCSP

    csp = SwCSP()
    sub = items[:1024]
    t0 = time.perf_counter()
    got = csp.verify_batch(sub)
    dt = time.perf_counter() - t0
    if got != expect[:len(sub)]:
        raise AssertionError("sw baseline verdicts wrong")
    return len(sub) / dt


def measure_device(items, expect, reps: int) -> float:
    import jax

    from fabric_mod_tpu.bccsp.tpu import TpuVerifier

    t0 = time.perf_counter()
    devs = jax.devices()
    log(f"jax platform: {devs[0].platform}, {len(devs)} device(s), "
        f"backend init {time.perf_counter() - t0:.1f}s")
    # memo-cache OFF: reps re-verify identical items, and a cache hit
    # would measure the LRU, not the device (the gossip metric is the
    # cache's honest showcase — its redelivery shape is real)
    v = TpuVerifier(cache_size=0)
    t0 = time.perf_counter()
    got = v.verify_many(items)          # includes compile on cold cache
    log(f"warm-up (incl. compile): {time.perf_counter() - t0:.1f}s")
    if list(got) != expect:
        bad = [i for i, (g, e) in enumerate(zip(got, expect)) if g != e]
        raise AssertionError(f"device verdicts wrong at {bad[:10]}")
    t0 = time.perf_counter()
    for _ in range(reps):
        v.verify_many(items)
    dt = time.perf_counter() - t0
    return len(items) * reps / dt


def _block_world(n_txs: int, under_endorse_every: int = 0):
    """A 1000-tx-style block world: 3 orgs, 2-of-3 endorsement
    (BASELINE config #2; reference: txvalidator/v20/validator.go:182).
    `under_endorse_every` > 0 endorses every k-th tx by one org only —
    ENDORSEMENT_POLICY_FAILURE lanes for differentials that must not
    pass vacuously on an all-VALID block."""
    from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder
    from fabric_mod_tpu.msp import ca as calib
    from fabric_mod_tpu.msp.identities import SigningIdentity
    from fabric_mod_tpu.peer import TxValidator, ValidationInfoProvider
    from fabric_mod_tpu.policy import ApplicationPolicyEvaluator
    from fabric_mod_tpu.protos import protoutil

    csp, cas, mgr, signers, policy = _three_org_world()
    ccert, ckey = cas["Org1"].issue("client@org1", "Org1",
                                    ous=["client"])
    signers["client"] = SigningIdentity(
        "Org1", ccert, calib.key_pem(ckey), csp)

    envs = []
    for i in range(n_txs):
        b = RWSetBuilder()
        b.add_write("mycc", f"key{i}", b"val%d" % i)
        endorsers = [signers["Org1"], signers["Org2"]]
        if under_endorse_every and i % under_endorse_every == \
                under_endorse_every - 1:
            endorsers = [signers["Org1"]]      # 1-of-3 < 2: must fail
        envs.append(protoutil.create_signed_tx(
            "bench", "mycc", b.build().encode(), signers["client"],
            endorsers))
    block = protoutil.new_block(0, b"", envs)

    def make_validator(verifier):
        return TxValidator("bench", mgr,
                           ApplicationPolicyEvaluator(mgr), verifier,
                           ValidationInfoProvider(policy))
    return block, make_validator


def _three_org_world():
    """The shared bench world: 3 orgs, one peer signer each, the
    2-of-3 endorsement policy (BASELINE config #2).  Returns
    (csp, cas, mgr, signers, policy_bytes); _block_world and
    _commitpipe_world both build on it."""
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.msp import ca as calib
    from fabric_mod_tpu.msp.identities import SigningIdentity
    from fabric_mod_tpu.msp.mspimpl import Msp, MspManager
    from fabric_mod_tpu.policy import from_string
    from fabric_mod_tpu.protos import messages as m

    csp = SwCSP()
    cas, msps, signers = {}, [], {}
    for org in ("Org1", "Org2", "Org3"):
        ca = calib.CA(f"ca.{org.lower()}", org)
        cas[org] = ca
        msps.append(Msp(org, csp, [ca.cert]))
        cert, key = ca.issue(f"peer0.{org.lower()}", org, ous=["peer"])
        signers[org] = SigningIdentity(org, cert, calib.key_pem(key), csp)
    policy = m.ApplicationPolicy(signature_policy=from_string(
        "OutOf(2, 'Org1.peer', 'Org2.peer', 'Org3.peer')")).encode()
    # the production channel shape: second-chance caches around the
    # manager (peer/channel._install_bundle wraps its bundle manager
    # the same way), so the bench measures the deployed hot path
    from fabric_mod_tpu.msp.cache import CachedMsp
    return csp, cas, CachedMsp(MspManager(msps)), signers, policy


def _commitpipe_world(n_blocks: int, txs_per_block: int):
    """An in-order block stream with MIXED barrier and non-barrier
    blocks: every 6th block carries a VALIDATION_PARAMETER metadata
    write pinning key "pinned" to an alternating single org (a
    `needs_barrier` block), and the NEXT block writes "pinned" under
    endorsements that only sometimes satisfy the pin — so the final
    txflags genuinely depend on barrier-correct ordering, and the
    pipelined/sync differential can't pass by accident.

    Returns (encoded_blocks, make_committer, barrier_count) where
    make_committer builds a fresh (ledger, validator) pair wired for
    key-level policies (state_metadata) against a fresh directory."""
    from fabric_mod_tpu.ledger import KvLedger
    from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder
    from fabric_mod_tpu.peer import TxValidator, ValidationInfoProvider
    from fabric_mod_tpu.peer.txvalidator import VALIDATION_PARAMETER
    from fabric_mod_tpu.policy import ApplicationPolicyEvaluator, from_string
    from fabric_mod_tpu.protos import messages as m
    from fabric_mod_tpu.protos import protoutil

    _csp, _cas, mgr, signers, cc_policy = _three_org_world()

    def org_policy(org):
        return m.ApplicationPolicy(
            signature_policy=from_string(f"'{org}.peer'")).encode()

    def tx(rwset_bytes, endorsers):
        return protoutil.create_signed_tx(
            "bench", "mycc", rwset_bytes, signers["Org1"],
            [signers[o] for o in endorsers])

    log(f"commitpipe: signing {n_blocks} blocks x {txs_per_block} txs ...")
    blocks, prev, barriers = [], b"", 0
    for n in range(n_blocks):
        envs = []
        for j in range(txs_per_block):
            b = RWSetBuilder()
            if n == 0 and j == 0:
                # seed "pinned" so the first VP pin has a key to bind
                # to (statedb drops metadata writes on absent keys —
                # an unseeded pin would be a silent no-op and the
                # first barrier would carry no verdict signal)
                b.add_write("mycc", "pinned", b"v0")
                envs.append(tx(b.build().encode(), ("Org1", "Org2")))
                continue
            if j == 0 and n % 6 == 5:
                # barrier block: re-pin "pinned" to the next org in
                # the alternation.  Metadata-only write (any other
                # key would drag in the cc-wide policy), endorsed by
                # whichever org the STANDING pin requires — changing
                # a pinned key's VP must itself satisfy the current
                # pin, so a 2-of-3 re-pin after the first would fail
                # forever and the alternating signal would be dead
                k = barriers
                pin_orgs = ("Org3", "Org1")
                b.add_metadata_write("mycc", "pinned",
                                     VALIDATION_PARAMETER,
                                     org_policy(pin_orgs[k % 2]))
                endorsers = (("Org1", "Org2") if k == 0
                             else (pin_orgs[(k - 1) % 2],))
                envs.append(tx(b.build().encode(), endorsers))
                barriers += 1
                continue
            if j == 1 and n % 6 == 0 and n > 0:
                # first block AFTER a barrier: write the pinned key.
                # Org1+Org2 endorsements satisfy the Org1 pin but not
                # the Org3 pin (the pins alternate), so the verdict
                # depends on the PREVIOUS block's committed VP — a
                # stage-ahead bug reads the stale pin (or none) and
                # flips this tx's flag
                b.add_write("mycc", "pinned", b"v%d" % n)
                envs.append(tx(b.build().encode(), ("Org1", "Org2")))
                continue
            b.add_write("mycc", f"blk{n}tx{j}", b"v")
            envs.append(tx(b.build().encode(), ("Org1", "Org2")))
        blk = protoutil.new_block(n, prev, envs)
        prev = protoutil.block_header_hash(blk.header)
        blocks.append(blk.encode())

    def make_committer(verifier, root):
        led = KvLedger(root, "bench")

        def state_vp(ns, key):
            meta = led.state.get_metadata(ns, key)
            return meta.get(VALIDATION_PARAMETER) if meta else None
        validator = TxValidator(
            "bench", mgr, ApplicationPolicyEvaluator(mgr), verifier,
            ValidationInfoProvider(cc_policy),
            tx_id_exists=led.tx_id_exists, state_metadata=state_vp)
        return led, validator
    return blocks, make_committer, barriers


def measure_commitpipe(n_blocks: int, txs_per_block: int, depth: int,
                       use_sw: bool) -> dict:
    """Whole-pipeline committed-tx/s A/B: the synchronous Committer vs
    the PipelinedCommitter over the SAME block stream into fresh
    ledgers.  Per-block txflags and the final ledger state fingerprint
    are asserted bit-identical (and depth=1 is additionally asserted
    identical to sync) BEFORE any rate is reported — the number can't
    come from a wrong-answer shortcut."""
    import tempfile

    from fabric_mod_tpu.peer import (Committer, PipelinedCommitter,
                                     ValidatorCommitTarget)
    from fabric_mod_tpu.protos import messages as m

    if use_sw:
        from fabric_mod_tpu.bccsp.sw import SwCSP
        from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
        verifier = FakeBatchVerifier(SwCSP())
    else:
        from fabric_mod_tpu.bccsp.tpu import TpuVerifier
        # memo-cache off: every block's items are distinct anyway, and
        # the A/B must measure the pipeline, not the LRU
        verifier = TpuVerifier(cache_size=0)
    blocks, make_committer, barriers = _commitpipe_world(
        n_blocks, txs_per_block)
    n_txs = n_blocks * txs_per_block

    def run_sync(root):
        led, validator = make_committer(verifier, root)
        committer = Committer(validator, led)
        flags = []
        t0 = time.perf_counter()
        for raw in blocks:
            flags.append(list(committer.store_block(m.Block.decode(raw))))
        dt = time.perf_counter() - t0
        return flags, led.state_fingerprint(), n_txs / dt

    def run_pipe(root, d):
        led, validator = make_committer(verifier, root)
        flags = []
        pipe = PipelinedCommitter(
            ValidatorCommitTarget(validator, led), depth=d,
            on_commit=lambda _b, f: flags.append(list(f)))
        t0 = time.perf_counter()
        for raw in blocks:
            pipe.submit(m.Block.decode(raw))
        pipe.flush()
        dt = time.perf_counter() - t0
        pipe.close()
        secs = {"stage": pipe.stage_secs, "await": pipe.await_secs,
                "commit": pipe.commit_secs}
        return flags, led.state_fingerprint(), n_txs / dt, secs

    from fabric_mod_tpu.observability import tracing
    with tempfile.TemporaryDirectory(prefix="fmt_commitpipe_") as tmp:
        if not use_sw:
            # warm-up: compile the verify bucket outside the timing
            led, validator = make_committer(verifier, tmp + "/warm")
            t0 = time.perf_counter()
            Committer(validator, led).store_block(m.Block.decode(blocks[0]))
            log(f"commitpipe warm-up (incl. compile): "
                f"{time.perf_counter() - t0:.1f}s")
        # baseline arms run with tracing EXPLICITLY off: under
        # --trace-out or an exported FMT_TRACE the whole worker is
        # armed, and an armed baseline would turn the traced-vs-
        # untraced identity gate below into armed-vs-armed — vacuous,
        # and the reported rates would silently include span overhead
        with tracing.active(False):
            sync_flags, sync_fp, sync_rate = run_sync(tmp + "/sync")
            log(f"sync committer: {sync_rate:,.0f} committed tx/s")
            pipe_flags, pipe_fp, pipe_rate, _secs = run_pipe(
                tmp + "/pipe", depth)
            log(f"pipelined (depth={depth}): {pipe_rate:,.0f} "
                f"committed tx/s ({pipe_rate / sync_rate:.2f}x)")
            d1_flags, d1_fp, _, _ = run_pipe(tmp + "/depth1", 1)
        # the TRACED arm: same stream through a pipelined committer
        # with FMT_TRACE armed — verdicts + state fingerprint must be
        # IDENTICAL to the tracing-off arms before any attribution
        # number is reported, and the named sub-span totals must sum
        # to (within tolerance of) the stage/await/commit buckets the
        # engine itself measured
        tracing.recorder().reset()
        with tracing.active():
            tr_flags, tr_fp, _tr_rate, tr_secs = run_pipe(
                tmp + "/traced", depth)
            totals = {k: v["secs"]
                      for k, v in tracing.substage_totals().items()}

        # tensor-policy differential arm: with FABRIC_MOD_TPU_TENSOR_
        # POLICY armed for the arms above, re-run the sync committer
        # with the knob scrubbed — per-block txflags and the state
        # fingerprint must be BIT-IDENTICAL tensor-vs-closure before
        # any rate is reported (the acceptance oracle)
        from fabric_mod_tpu.utils import knobs as _kn
        tensor_armed = _kn.get_bool("FABRIC_MOD_TPU_TENSOR_POLICY")
        closure_rate = None
        if tensor_armed:
            saved_tp = os.environ.pop("FABRIC_MOD_TPU_TENSOR_POLICY")
            try:
                with tracing.active(False):
                    cl_flags, cl_fp, cl_rate = run_sync(tmp + "/closure")
            finally:
                os.environ["FABRIC_MOD_TPU_TENSOR_POLICY"] = saved_tp
            if cl_flags != sync_flags or cl_fp != sync_fp:
                raise AssertionError(
                    "tensor-policy verdicts/state diverge from the "
                    "closure path — the tensor compiler is wrong")
            closure_rate = cl_rate
            log(f"tensor-vs-closure differential: identical "
                f"(closure sync {cl_rate:,.0f} tx/s)")

    flags_ok = pipe_flags == sync_flags
    state_ok = pipe_fp == sync_fp
    depth1_ok = d1_flags == sync_flags and d1_fp == sync_fp
    if not flags_ok:
        bad = [i for i, (a, b) in enumerate(zip(pipe_flags, sync_flags))
               if a != b]
        raise AssertionError(
            f"pipelined txflags diverge from sync at blocks {bad[:5]}")
    if not state_ok:
        raise AssertionError("pipelined state fingerprint diverges")
    if not depth1_ok:
        raise AssertionError("depth=1 does not match the sync path")
    if tr_flags != sync_flags or tr_fp != sync_fp:
        raise AssertionError(
            "FMT_TRACE-armed run diverges from the tracing-off arms "
            "— tracing must be a pure observer")
    # stage attribution: the named sub-span totals must explain the
    # engine's own stage/await/commit buckets (within 10%, floored at
    # 100 ms so tiny CPU runs don't flake on timer noise)
    attribution = {
        "buckets_secs": {k: round(v, 3) for k, v in tr_secs.items()},
        "substage_secs": {k: round(v, 3) for k, v in sorted(
            totals.items())},
    }
    bucket_parts = {
        "stage": ("unpack", "device_dispatch", "policy_gather"),
        "await": ("verdict_await",),
        "commit": ("policy_device", "policy_finish", "mvcc",
                   "ledger_write"),
    }
    for bucket, parts in bucket_parts.items():
        have = sum(totals.get(p, 0.0) for p in parts)
        want = tr_secs[bucket]
        # floor 0.3s: post-r12 the stage/commit buckets are tens of
        # ms per block, and the engine's bucket timers (but not the
        # in-thread spans) absorb GIL-scheduling stalls while the
        # OTHER pipeline thread crunches pure-python ECDSA on the
        # wheel-less arm — sub-noise buckets must not flake the gate
        # (a genuinely unattributed NEW sub-stage at that scale is
        # invisible under any floor; the r09-scale drifts this gate
        # exists for are seconds, not fractions)
        tol = max(0.10 * want, 0.3)
        attribution[f"{bucket}_covered"] = round(
            have / want, 3) if want > 1e-9 else 1.0
        if abs(want - have) > tol:
            raise AssertionError(
                f"stage attribution drifted: {bucket} bucket "
                f"{want:.3f}s vs sub-span sum {have:.3f}s "
                f"({'+'.join(parts)}) — tolerance {tol:.3f}s")
    # the headline the vectorized-policy work is judged by: how much
    # of the commit bucket is still policy evaluation
    policy_secs = sum(totals.get(p, 0.0)
                      for p in ("policy_device", "policy_finish"))
    commit_secs = max(tr_secs["commit"], 1e-9)
    attribution["commit_policy_share"] = round(
        policy_secs / commit_secs, 3)
    # the interesting flags actually flipped (the stream exercised the
    # barrier-dependent verdicts, not just all-VALID blocks) — an
    # all-VALID stream would let the differential pass vacuously
    distinct = {f for per_block in sync_flags for f in per_block}
    if distinct == {0}:
        raise AssertionError(
            "commitpipe stream produced only VALID flags — the "
            "barrier-dependent verdicts the oracle relies on are gone")
    out = {
        "pipelined_tx_per_sec": round(pipe_rate, 1),
        "sync_tx_per_sec": round(sync_rate, 1),
        "blocks": n_blocks,
        "txs_per_block": txs_per_block,
        "barrier_blocks": barriers,
        "depth": depth,
        "distinct_flags": sorted(distinct),
        "flags_identical": flags_ok,
        "state_hash_identical": state_ok,
        "depth1_identical": depth1_ok,
        "traced_identical": True,          # asserted above
        "stage_attribution": attribution,
        "verifier": "sw" if use_sw else "device",
        "tensor_policy": tensor_armed,
    }
    if closure_rate is not None:
        out["tensor_vs_closure_identical"] = True   # asserted above
        out["closure_sync_tx_per_sec"] = round(closure_rate, 1)
    return out


def _sk(i: int) -> str:
    return "sk%07d" % i


def _statescale_world(n_blocks: int, txs_per_block: int,
                      touch_space: int):
    """A signed block stream with REAL MVCC work — fresh reads against
    prefilled state, deliberate stale reads, absent-key probes, narrow
    range queries (some provably phantom-conflicted), deletes, and a
    VALIDATION_PARAMETER pin that flips later writes of the pinned key
    invalid.  Every key it touches lives in the first `touch_space`
    prefilled keys, so ONE stream is valid at EVERY sweep point and
    the txflags must be identical across state sizes as well as
    across arms.  Reads draw from the upper half of the touched
    keyspace and writes from the lower half (disjoint), so a fresh
    read stays fresh for the whole stream and every conflict is one
    the generator placed deliberately.

    Returns (encoded_blocks, make_committer); make_committer builds a
    fresh (ledger, validator) pair — non-durable by default (the sweep
    measures decode+MVCC economics, not log fsync); durable=True runs
    the same sweep on DurableStateDB, whose batched one-buffered-
    write-per-block apply_updates is what makes that arm affordable."""
    import random

    from fabric_mod_tpu.ledger import KvLedger
    from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder
    from fabric_mod_tpu.peer import TxValidator, ValidationInfoProvider
    from fabric_mod_tpu.peer.txvalidator import VALIDATION_PARAMETER
    from fabric_mod_tpu.policy import ApplicationPolicyEvaluator, from_string
    from fabric_mod_tpu.protos import messages as m
    from fabric_mod_tpu.protos import protoutil

    _csp, _cas, mgr, signers, cc_policy = _three_org_world()
    rng = random.Random(1807)
    write_pool = touch_space // 2
    pin_key = _sk(1)

    def tx(rwset_bytes, endorsers):
        return protoutil.create_signed_tx(
            "bench", "mycc", rwset_bytes, signers["Org1"],
            [signers[o] for o in endorsers])

    log(f"statescale: signing {n_blocks} blocks x {txs_per_block} "
        f"txs ...")
    blocks, prev = [], b""
    for n in range(n_blocks):
        envs = []
        for j in range(txs_per_block):
            b = RWSetBuilder()
            endorsers = ("Org1", "Org2")
            if n == 2 and j == 0:
                # pin the (prefilled, so the metadata write sticks)
                # key's VP to Org3-only: every later write of it under
                # Org1+Org2 must flip ENDORSEMENT_POLICY_FAILURE
                b.add_metadata_write("mycc", pin_key,
                                     VALIDATION_PARAMETER,
                                     m.ApplicationPolicy(
                                         signature_policy=from_string(
                                             "'Org3.peer'")).encode())
                envs.append(tx(b.build().encode(), endorsers))
                continue
            if n >= 3 and j == 1:
                b.add_write("mycc", pin_key, b"pinned%d" % n)
                envs.append(tx(b.build().encode(), endorsers))
                continue
            # 28 reads/tx: the conflict-detection work is the sweep's
            # subject — it must dominate span-timer noise, not hide
            # under it (signing cost is per-tx, so this is ~free)
            for _ in range(28):
                k = _sk(write_pool + rng.randrange(
                    touch_space - write_pool))
                if rng.random() < 0.005:
                    b.add_read("mycc", k, (9999, 0))      # stale
                else:
                    b.add_read("mycc", k, (0, 0))         # fresh
            # absent-key probes (valid: no committed version)
            for _ in range(2):
                b.add_read("mycc", "zz%05d" % rng.randrange(1000),
                           None)
            for _ in range(3):
                k = _sk(rng.randrange(write_pool))
                if rng.random() < 0.10:
                    b.add_write("mycc", k, None)          # delete
                else:
                    b.add_write("mycc", k, b"v%d.%d" % (n, j))
            r = rng.random()
            if r < 0.10:
                # prefilled rows exist in-range but none recorded:
                # PHANTOM_READ_CONFLICT in BOTH arms, deterministically
                # (the range sits in the read-only half, so no stream
                # write ever changes what the re-scan sees)
                b.add_range_query("mycc", _sk(write_pool + 50),
                                  _sk(write_pool + 52), True, [])
            elif r < 0.25:
                b.add_range_query("mycc", "zz~0", "zz~9", True, [])
            if rng.random() < 0.08:
                endorsers = ("Org2",)     # under-endorsed: 2-of-3 fails
            envs.append(tx(b.build().encode(), endorsers))
        blk = protoutil.new_block(n, prev, envs)
        prev = protoutil.block_header_hash(blk.header)
        blocks.append(blk.encode())

    def make_committer(verifier, root, durable=False):
        led = KvLedger(root, "bench", durable=durable)

        def state_vp(ns, key):
            meta = led.state.get_metadata(ns, key)
            return meta.get(VALIDATION_PARAMETER) if meta else None
        validator = TxValidator(
            "bench", mgr, ApplicationPolicyEvaluator(mgr), verifier,
            ValidationInfoProvider(cc_policy),
            tx_id_exists=led.tx_id_exists, state_metadata=state_vp)
        return led, validator
    return blocks, make_committer


def measure_statescale(sizes, n_blocks: int = 8,
                       txs_per_block: int = 32,
                       durable: bool = False) -> dict:
    """Vectorized-MVCC differential sweep at real state scale: the
    SAME signed block stream committed into ledgers prefilled at each
    `sizes` point, generic (knob scrubbed) vs FABRIC_MOD_TPU_VECTOR_
    MVCC=1 arms.  At EVERY point, per-block txflags and the state
    fingerprint are asserted bit-identical across arms (and across
    sizes — the stream only touches the common prefilled keyspace),
    the incremental fingerprint is asserted equal to the full-scan
    oracle on BOTH arms, and the body-decode fallback counter must not
    move on this well-formed stream — all BEFORE any rate is reported.
    Both arms run FMT_TRACE-armed, so the reported stage+mvcc bucket
    seconds are like-for-like (and at >=100k keys the vectorized
    bucket must actually be smaller)."""
    import tempfile

    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
    from fabric_mod_tpu.ledger.statedb import UpdateBatch
    from fabric_mod_tpu.observability import tracing
    from fabric_mod_tpu.peer import Committer
    from fabric_mod_tpu.peer.txvalidator import _stage_metrics
    from fabric_mod_tpu.protos import messages as m

    sizes = sorted(sizes)
    if len(sizes) < 3:
        raise ValueError("statescale needs >= 3 state sizes")
    verifier = FakeBatchVerifier(SwCSP())
    blocks, make_committer = _statescale_world(
        n_blocks, txs_per_block, min(sizes))
    n_txs = n_blocks * txs_per_block

    def run_arm(root, n_keys):
        led, validator = make_committer(verifier, root, durable)
        t0 = time.perf_counter()
        for lo in range(0, n_keys, 200_000):
            batch = UpdateBatch()
            for i in range(lo, min(lo + 200_000, n_keys)):
                batch.put("mycc", _sk(i), b"seed-%07d" % i, (0, 0))
            led.state.apply_updates(batch, 0)
        prefill_secs = time.perf_counter() - t0
        t0 = time.perf_counter()
        led.state_fingerprint()        # seed the incremental fold
        seed_secs = time.perf_counter() - t0
        committer = Committer(validator, led)
        fb0 = _stage_metrics()[3].value
        flags = []
        tracing.recorder().reset()
        with tracing.active():
            t0 = time.perf_counter()
            for raw in blocks:
                flags.append(list(
                    committer.store_block(m.Block.decode(raw))))
            dt = time.perf_counter() - t0
            totals = {k: v["secs"]
                      for k, v in tracing.substage_totals().items()}
        fallbacks = _stage_metrics()[3].value - fb0
        t0 = time.perf_counter()
        fp = led.state_fingerprint()
        incr_secs = time.perf_counter() - t0
        t0 = time.perf_counter()
        full = led.state_fingerprint_full()
        full_secs = time.perf_counter() - t0
        return {
            "flags": flags, "fp": fp, "fp_full": full,
            "tx_per_sec": n_txs / dt,
            "fallbacks": fallbacks,
            # "unpack" contains the stage-side batch body decode,
            # "mvcc" the commit-side rwset materialization + version
            # compares — together the cost the columnar pipeline
            # attacks (verify/dispatch buckets are off-path here)
            "stage_mvcc_secs": totals.get("unpack", 0.0)
                               + totals.get("mvcc", 0.0),
            "buckets_secs": {k: round(totals.get(k, 0.0), 4)
                             for k in ("unpack", "body_decode",
                                       "mvcc", "mvcc_vector")},
            "prefill_secs": prefill_secs, "seed_secs": seed_secs,
            "incr_secs": incr_secs, "full_secs": full_secs,
        }

    points, flags0 = [], None
    saved = os.environ.pop("FABRIC_MOD_TPU_VECTOR_MVCC", None)
    try:
        with tempfile.TemporaryDirectory(prefix="fmt_statescale_") \
                as tmp:
            for n_keys in sizes:
                gen = run_arm(f"{tmp}/g{n_keys}", n_keys)
                os.environ["FABRIC_MOD_TPU_VECTOR_MVCC"] = "1"
                try:
                    vec = run_arm(f"{tmp}/v{n_keys}", n_keys)
                finally:
                    os.environ.pop("FABRIC_MOD_TPU_VECTOR_MVCC", None)
                # -- gates: every one BEFORE any rate is reported ----
                if vec["flags"] != gen["flags"]:
                    bad = [i for i, (a, b) in enumerate(
                        zip(vec["flags"], gen["flags"])) if a != b]
                    raise AssertionError(
                        f"statescale@{n_keys}: vectorized txflags "
                        f"diverge from generic at blocks {bad[:5]}")
                if vec["fp"] != gen["fp"]:
                    raise AssertionError(
                        f"statescale@{n_keys}: state fingerprint "
                        "diverges across arms")
                for arm_name, arm in (("generic", gen),
                                      ("vector", vec)):
                    if arm["fp"] != arm["fp_full"]:
                        raise AssertionError(
                            f"statescale@{n_keys}/{arm_name}: "
                            "incremental fingerprint != full-scan "
                            "oracle")
                    if arm["fallbacks"]:
                        raise AssertionError(
                            f"statescale@{n_keys}/{arm_name}: "
                            f"{arm['fallbacks']} body-decode "
                            "fallbacks on the well-formed stream")
                if flags0 is None:
                    flags0 = gen["flags"]
                    distinct = {f for per in flags0 for f in per}
                    if distinct == {0}:
                        raise AssertionError(
                            "statescale stream produced only VALID "
                            "flags — the conflict/policy verdicts "
                            "the oracle relies on are gone")
                elif gen["flags"] != flags0:
                    raise AssertionError(
                        f"statescale@{n_keys}: txflags changed with "
                        "state size — the stream must only touch the "
                        "common prefilled keyspace")
                if n_keys >= 100_000 and vec["stage_mvcc_secs"] >= \
                        gen["stage_mvcc_secs"]:
                    raise AssertionError(
                        f"statescale@{n_keys}: stage+mvcc "
                        f"{vec['stage_mvcc_secs']:.3f}s vectorized "
                        f"vs {gen['stage_mvcc_secs']:.3f}s generic — "
                        "the vectorized path must not be slower at "
                        "scale")
                point = {"state_keys": n_keys}
                for arm_name, arm in (("generic", gen),
                                      ("vector", vec)):
                    point[arm_name] = {
                        "tx_per_sec": round(arm["tx_per_sec"], 1),
                        "stage_mvcc_secs": round(
                            arm["stage_mvcc_secs"], 4),
                        "buckets_secs": arm["buckets_secs"],
                        "fingerprint_secs": {
                            "seed_scan": round(arm["seed_secs"], 4),
                            "incremental": round(arm["incr_secs"], 6),
                            "full_scan": round(arm["full_secs"], 4)},
                        "prefill_secs": round(arm["prefill_secs"], 3),
                    }
                point["flags_identical"] = True
                point["fingerprint_identical"] = True
                point["body_decode_fallbacks"] = 0
                point["stage_mvcc_speedup"] = round(
                    gen["stage_mvcc_secs"]
                    / max(vec["stage_mvcc_secs"], 1e-9), 3)
                log(f"statescale@{n_keys}: generic "
                    f"{gen['tx_per_sec']:,.0f} tx/s (stage+mvcc "
                    f"{gen['stage_mvcc_secs']:.3f}s), vector "
                    f"{vec['tx_per_sec']:,.0f} tx/s (stage+mvcc "
                    f"{vec['stage_mvcc_secs']:.3f}s)")
                points.append(point)
    finally:
        if saved is not None:
            os.environ["FABRIC_MOD_TPU_VECTOR_MVCC"] = saved
        else:
            os.environ.pop("FABRIC_MOD_TPU_VECTOR_MVCC", None)
    return {
        "points": points,
        "top": {
            "state_keys": sizes[-1],
            "generic_tx_per_sec":
                points[-1]["generic"]["tx_per_sec"],
            "vector_tx_per_sec":
                points[-1]["vector"]["tx_per_sec"],
        },
        "blocks": n_blocks, "txs_per_block": txs_per_block,
        "distinct_flags": sorted({f for per in flags0 for f in per}),
        "verifier": "sw", "durable": durable, "traced_arms": True,
    }


def measure_policyeval(n_txs: int, reps: int, use_sw: bool) -> dict:
    """Tensor-vs-closure policy evaluation A/B over one 2-of-3 block
    (with deliberate under-endorsed lanes so the verdicts carry
    signal): the SAME block validated by a closure-path validator and
    a tensor-path validator, txflags asserted bit-identical BEFORE any
    rate is reported.  The timed unit is TxValidator.validate — the
    full stage+finish round including the (shared) verify cost, so the
    ratio is the honest end-to-end effect, and the substage split
    shows where the policy milliseconds went."""
    from fabric_mod_tpu.observability import tracing

    if use_sw:
        from fabric_mod_tpu.bccsp.sw import SwCSP
        from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
        verifier = FakeBatchVerifier(SwCSP())
    else:
        from fabric_mod_tpu.bccsp.tpu import TpuVerifier
        verifier = TpuVerifier(cache_size=0)
    block, make_validator = _block_world(n_txs, under_endorse_every=16)

    def arm_env(armed: bool):
        if armed:
            os.environ["FABRIC_MOD_TPU_TENSOR_POLICY"] = "1"
        else:
            os.environ.pop("FABRIC_MOD_TPU_TENSOR_POLICY", None)

    def run_once(validator, armed: bool, traced=False):
        arm_env(armed)
        if traced:
            tracing.recorder().reset()
            with tracing.active():
                flags = validator.validate(block)
                totals = {k: round(v["secs"], 4)
                          for k, v in tracing.substage_totals().items()}
            return flags, 0.0, totals
        t0 = time.perf_counter()
        flags = validator.validate(block)
        return flags, time.perf_counter() - t0, None

    saved = os.environ.pop("FABRIC_MOD_TPU_TENSOR_POLICY", None)
    try:
        v_closure = make_validator(verifier)
        v_tensor = make_validator(verifier)
        closure_flags, _, _ = run_once(v_closure, False)  # warm
        tensor_flags, _, _ = run_once(v_tensor, True)     # warm
        # INTERLEAVED min-of-k (the measure_marshal stance): the two
        # arms alternate so noisy-neighbor slowdowns in the shared
        # pure-python verify hit both alike — end-to-end tx/s is
        # verify-bound by design, the ratio must not be machine mood
        closure_best = tensor_best = float("inf")
        for _ in range(max(reps, 2)):
            got, dt, _ = run_once(v_closure, False)
            closure_best = min(closure_best, dt)
            if got != closure_flags:
                raise AssertionError(
                    "policyeval closure verdicts unstable across reps")
            got, dt, _ = run_once(v_tensor, True)
            tensor_best = min(tensor_best, dt)
            if got != tensor_flags:
                raise AssertionError(
                    "policyeval tensor verdicts unstable across reps")
        # substage split of one traced validate per arm: the POLICY
        # seconds are the A/B's real subject
        _, _, closure_tot = run_once(v_closure, False,
                                     traced=True)
        _, _, tensor_tot = run_once(v_tensor, True, traced=True)
        # the session's instance/fallback census from one armed staging
        arm_env(True)
        staged = v_tensor.stage(block)
        v_tensor.finish(staged)
        session = staged.session
        instances = len(session) if session is not None else 0
        fallbacks = session.fallbacks if session is not None else 0
    finally:
        if saved is None:
            os.environ.pop("FABRIC_MOD_TPU_TENSOR_POLICY", None)
        else:
            os.environ["FABRIC_MOD_TPU_TENSOR_POLICY"] = saved

    closure_rate = n_txs / closure_best
    tensor_rate = n_txs / tensor_best
    POLICY_SPANS = ("policy_gather", "policy_device", "policy_finish")
    closure_policy_s = sum(closure_tot.get(p, 0.0)
                           for p in POLICY_SPANS)
    tensor_policy_s = sum(tensor_tot.get(p, 0.0) for p in POLICY_SPANS)
    log(f"closure policy eval: {closure_rate:,.0f} validated tx/s, "
        f"policy {closure_policy_s * 1000:.1f} ms/block")
    log(f"tensor policy eval: {tensor_rate:,.0f} validated tx/s "
        f"({tensor_rate / closure_rate:.2f}x), policy "
        f"{tensor_policy_s * 1000:.1f} ms/block "
        f"({closure_policy_s / max(tensor_policy_s, 1e-9):.1f}x)")

    # -- the verdict gate (before ANY rate is reported) ------------------
    if tensor_flags != closure_flags:
        bad = [i for i, (a, b) in enumerate(zip(tensor_flags,
                                                closure_flags)) if a != b]
        raise AssertionError(
            f"tensor policy verdicts diverge from closures at {bad[:10]}")
    distinct = sorted(set(closure_flags))
    if distinct == [0]:
        raise AssertionError(
            "policyeval block produced only VALID flags — the "
            "under-endorsed lanes the oracle relies on are gone")

    return {
        "tensor_tx_per_sec": round(tensor_rate, 1),
        "closure_tx_per_sec": round(closure_rate, 1),
        "policy_secs_closure": round(closure_policy_s, 4),
        "policy_secs_tensor": round(tensor_policy_s, 4),
        "policy_speedup": round(
            closure_policy_s / max(tensor_policy_s, 1e-9), 2),
        "txs": n_txs,
        "distinct_flags": distinct,
        "flags_identical": True,            # asserted above
        "tensor_instances": instances,
        "tensor_fallbacks": fallbacks,
        "substage_secs_tensor": dict(sorted(tensor_tot.items())),
        "substage_secs_closure": dict(sorted(closure_tot.items())),
        "verifier": "sw" if use_sw else "device",
    }


def measure_block(n_txs: int, reps: int) -> tuple:
    """Validated tx/s, device batch verifier vs sw provider.
    validate() mutates only the txflags metadata, so reps re-validate
    the same block object — no copying inside the timed loop."""
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier, TpuVerifier

    block, make_validator = _block_world(n_txs)
    V = 0  # TxValidationCode.VALID

    def run(validator, reps):
        t0 = time.perf_counter()
        for _ in range(reps):
            flags = validator.validate(block)
            if any(f != V for f in flags):
                raise AssertionError("bench block failed validation")
        return n_txs * reps / (time.perf_counter() - t0)

    sw_validator = make_validator(FakeBatchVerifier(SwCSP()))
    sw_rate = run(sw_validator, 1)
    log(f"sw block validation: {sw_rate:,.0f} tx/s")
    # cache off for the same reason as measure_device: reps replay one
    # block, production validates distinct blocks
    dev_validator = make_validator(TpuVerifier(cache_size=0))
    t0 = time.perf_counter()
    run(dev_validator, 1)                   # warm-up/compile
    log(f"block warm-up (incl. compile): {time.perf_counter() - t0:.1f}s")
    dev_rate = run(dev_validator, reps)
    log(f"device block validation: {dev_rate:,.0f} tx/s")
    return dev_rate, sw_rate


def measure_e2e(n_txs: int) -> tuple:
    """End-to-end validated tx/s: endorsed txs -> solo orderer cuts
    blocks -> peer verifies (device batch) + MVCC + commits
    (BASELINE config #3 shape, in-process network).  Returns the
    pipeline stage split too, so the record shows whether throughput
    is bounded by ordering or by crypto (BASELINE's e2e criterion)."""
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier, TpuVerifier
    from fabric_mod_tpu.e2e import run_pipeline
    from fabric_mod_tpu.observability import tracing

    # both timed arms run with tracing armed (the warm-up doesn't):
    # the sub-span totals give the stage-attribution split, and arming
    # BOTH arms keeps the vs_baseline ratio apples-to-apples
    with tracing.active():
        sw_rate = run_pipeline(min(n_txs, 2000),
                               FakeBatchVerifier(SwCSP()))
    log(f"sw e2e: {sw_rate:,.0f} tx/s")
    verifier = TpuVerifier()
    run_pipeline(min(n_txs, 2000), verifier)      # warm-up/compile
    stats = {}
    with tracing.active():
        dev_rate = run_pipeline(n_txs, verifier, stats=stats)
    log(f"device e2e: {dev_rate:,.0f} tx/s  split: {stats}")
    return dev_rate, sw_rate, stats


def measure_idemix(n: int, reps: int) -> tuple:
    """Anonymous-presentation verifies/s, device batched pairing vs the
    host pairing path (BASELINE config #4; reference:
    idemix/signature.go:243 Ver, integration/idemix/idemix_test.go:25).

    Both paths run the SAME batch_verify surface (pairing equation +
    Schnorr/Fiat-Shamir recheck); the delta is where the two pairings
    per presentation execute — batched on device vs sequential host
    Fp12.  One presentation is tampered so the bench proves the
    verdict path, not a constant-True short circuit."""
    from fabric_mod_tpu.idemix import credential as idx

    ik = idx.IssuerKey(["ou", "role"])
    sk = idx._rand_zr()
    cred = idx.issue(ik, sk, [5, 7])
    log(f"idemix: signing {n} presentations ...")
    items = []
    for i in range(n):
        sig = idx.sign(ik, cred, sk, b"msg%d" % i, {0: 5})
        items.append((sig, b"msg%d" % i, {0: 5}))
    # tamper one pairing input: A_bar off by the generator
    from fabric_mod_tpu.idemix.fp256bn import G1, g1_add
    bad = n // 2
    items[bad][0].A_bar = g1_add(items[bad][0].A_bar, G1.generator())
    expect = [i != bad for i in range(n)]

    host_n = min(n, 16)
    t0 = time.perf_counter()
    got = idx.batch_verify(ik, items[:host_n], use_device=False)
    sw_rate = host_n / (time.perf_counter() - t0)
    if got != expect[:host_n]:
        raise AssertionError("idemix host verdicts wrong")
    log(f"sw idemix: {sw_rate:,.1f} presentations/s")

    t0 = time.perf_counter()
    got = idx.batch_verify(ik, items, use_device=True)  # incl. compile
    compile_secs = time.perf_counter() - t0
    log(f"idemix warm-up (incl. compile): {compile_secs:.1f}s — the "
        f"pairing program sits on the persistent XLA cache "
        f"(ops/compilecache.py), so a cached run shows ~steady-state "
        f"time here")
    if got != expect:
        bad_idx = [i for i, (g, e) in enumerate(zip(got, expect)) if g != e]
        raise AssertionError(f"idemix device verdicts wrong at {bad_idx}")
    t0 = time.perf_counter()
    for _ in range(reps):
        idx.batch_verify(ik, items, use_device=True)
    dev_rate = n * reps / (time.perf_counter() - t0)
    steady = n / dev_rate
    log(f"device idemix: {dev_rate:,.1f} presentations/s")
    # compile cost ≈ warm-up minus one steady-state batch; recorded so
    # the artifact shows whether the persistent cache held (VERDICT #8:
    # a second run must show ~0)
    return dev_rate, sw_rate, max(0.0, compile_secs - steady)


def measure_gossip(n_peers: int, reps: int) -> tuple:
    """Aggregate block verifies/s across a simulated gossip storm:
    `n_peers` peers concurrently verify the same orderer-signed block
    stream through MessageCryptoService (data-hash recompute +
    cert-chain deserialization + BlockValidation policy) — BASELINE
    config #5 (reference: internal/peer/gossip/mcs.go:124,
    gossip/identity/identity.go:176, gossip/comm/comm_impl.go:411).

    The device path routes every peer's signature checks through ONE
    BatchingVerifyService so concurrent small verifies coalesce into
    device batches — the TPU answer to per-connection goroutines."""
    import tempfile
    import threading

    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.bccsp.tpu import (BatchingVerifyService,
                                          FakeBatchVerifier, TpuVerifier)
    from fabric_mod_tpu.channelconfig import Bundle
    from fabric_mod_tpu.channelconfig.configtx import config_from_block
    from fabric_mod_tpu.e2e import Network
    from fabric_mod_tpu.peer.mcs import MessageCryptoService

    tmp = tempfile.mkdtemp(prefix="fmt_gossip_bench_")
    net = Network(tmp, batch_timeout="50ms", max_message_count=32)
    try:
        for i in range(96):
            net.invoke([b"put", b"k%d" % i, b"v%d" % i])
        net.pump_committed(96)
        store = net.support.store
        blocks = [store.get_block_by_number(i)
                  for i in range(1, store.height)]
        log(f"gossip: {len(blocks)} orderer-signed blocks, "
            f"{n_peers} peers x {reps} reps")
        _, config = config_from_block(net.genesis_block)
        bundle = Bundle(net.channel_id, config, net.csp)

        def storm(verify_many) -> float:
            svcs = [MessageCryptoService(lambda: bundle,
                                         _VerifierShim(verify_many))
                    for _ in range(n_peers)]
            start = threading.Barrier(n_peers + 1)
            errs = []

            def peer_main(svc):
                start.wait()
                try:
                    for _ in range(reps):
                        for blk in blocks:
                            svc.verify_block(net.channel_id, blk)
                except Exception as e:       # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=peer_main, args=(s,),
                                        daemon=True) for s in svcs]
            for t in threads:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if errs:
                raise errs[0]
            return n_peers * reps * len(blocks) / dt

        sw_rate = storm(FakeBatchVerifier(SwCSP()).verify_many)
        log(f"sw gossip storm: {sw_rate:,.1f} block-verifies/s")
        dev = BatchingVerifyService(TpuVerifier())
        # BOUNDED wait sized to the worker's own kill budget (the old
        # `timeout=None` workaround outlived its cause: the verify
        # bucket programs sit on the persistent compile cache, and the
        # supervisor's process-group timeout is the real backstop —
        # an unbounded Future wait could only turn a wedged device
        # into a silent hang).  The default matches verify_smoke.sh's
        # export: a COLD CPU compile of the verify cores runs multiple
        # minutes, and the first storm call carries it whole
        budget = float(os.environ.get("FABRIC_MOD_TPU_BENCH_TIMEOUT",
                                      "2400"))
        dev_verify = lambda items: dev.verify_many(items, timeout=budget)
        try:
            storm(dev_verify)                 # warm-up/compile
            dev_rate = storm(dev_verify)
        finally:
            dev.close()
        log(f"device gossip storm: {dev_rate:,.1f} block-verifies/s")
        return dev_rate, sw_rate
    finally:
        net.close()


class _VerifierShim:
    """Adapts a bare verify_many callable to the MCS verifier seam."""

    def __init__(self, verify_many):
        self.verify_many = verify_many


# ---------------------------------------------------------------------------
# broadcast storm: admission control under a many-client overload burst
# ---------------------------------------------------------------------------

def _storm_material(n_clients: int, max_message_count: int,
                    batch_timeout: str) -> dict:
    """Shared crypto + genesis for every storm arm: one org, one solo
    orderer, `n_clients` distinct client identities (one token bucket
    each).  Both arms open fresh channels from the SAME genesis so the
    pre-signed envelopes satisfy both arms' Writers policy.  No peers
    — the storm invariant is about broadcast→order→deliver, and the
    orderer's own store is the deliver source of truth."""
    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.channelconfig import genesis
    from fabric_mod_tpu.msp import ca as calib
    from fabric_mod_tpu.msp.identities import SigningIdentity

    csp = SwCSP()
    org_ca = calib.CA("ca.org1", "Org1")
    ord_ca = calib.CA("ca.orderer", "OrdererOrg")
    ocert, okey = ord_ca.issue("orderer0", "OrdererOrg", ous=["orderer"])
    orderer_signer = SigningIdentity("OrdererOrg", ocert,
                                     calib.key_pem(okey), csp)
    clients = []
    for i in range(n_clients):
        cert, key = org_ca.issue(f"client{i}@org1", "Org1",
                                 ous=["client"])
        clients.append(SigningIdentity("Org1", cert,
                                       calib.key_pem(key), csp))
    gblock = genesis.standard_network(
        "storm", {"Org1": [calib.cert_pem(org_ca.cert)]},
        {"OrdererOrg": [calib.cert_pem(ord_ca.cert)]},
        max_message_count=max_message_count,
        batch_timeout=batch_timeout)
    return {"csp": csp, "clients": clients, "genesis": gblock,
            "orderer_signer": orderer_signer}


def _storm_channel(root: str, mat: dict, verify_many=None):
    from fabric_mod_tpu.orderer import Registrar
    registrar = Registrar(root, mat["orderer_signer"], mat["csp"],
                          verify_many=verify_many)
    support = registrar.create_channel(mat["genesis"])
    return registrar, support


def _storm_device_verifier(staged_batch: int):
    """Build the device batch verifier for the --storm-verifier=device
    arms: verdict memo-cache OFF (the same pre-signed envelopes replay
    in every arm — a cache hit would fake the batch economics), and
    the 1-item and `staged_batch`-item padding buckets warmed with
    garbage items OUTSIDE any measured window, so arms time dispatch,
    not XLA compiles.  Returns (verify_many, close)."""
    from fabric_mod_tpu.bccsp.api import VerifyItem
    from fabric_mod_tpu.bccsp.tpu import TpuVerifier

    verifier = TpuVerifier(cache_size=0)

    def junk(n):
        # distinct digests: identical items would dedup to one device
        # lane and warm the wrong bucket
        return [VerifyItem((b"storm-warm-%08d" % i).ljust(32, b"\0"),
                           b"\x00" * 8, b"\x00" * 64)
                for i in range(n)]

    log("storm: warming device verify buckets (1 and "
        f"{staged_batch}-item) ...")
    t0 = time.perf_counter()
    verifier.verify_many(junk(1))
    verifier.verify_many(junk(max(2, staged_batch)))
    log(f"storm: device buckets warm in {time.perf_counter() - t0:.1f}s")
    return verifier.verify_many, verifier.close


def _storm_envelopes(clients, per_client: int):
    """Pre-signed envelopes (setup, untimed): one Writers signature
    each, distinct tx ids so commits are countable per envelope."""
    from fabric_mod_tpu.protos import messages as m
    from fabric_mod_tpu.protos import protoutil

    envs = []                              # [(client_idx, tx_id, env)]
    for ci, signer in enumerate(clients):
        creator = signer.serialize()
        for j in range(per_client):
            tx_id = f"storm-c{ci}-{j}"
            ch = protoutil.make_channel_header(
                m.HeaderType.ENDORSER_TRANSACTION, "storm", tx_id=tx_id)
            sh = protoutil.make_signature_header(creator,
                                                 protoutil.new_nonce())
            payload = protoutil.make_payload(ch, sh,
                                             b"storm-%d-%d" % (ci, j))
            envs.append((ci, tx_id, protoutil.sign_envelope(payload,
                                                            signer)))
    return envs


def _storm_committed_tx_ids(store) -> list:
    from fabric_mod_tpu.protos import protoutil
    tx_ids = []
    for n in range(1, store.height):
        block = store.get_block_by_number(n)
        for env in protoutil.get_envelopes(block):
            ch = protoutil.envelope_channel_header(env)
            tx_ids.append(ch.tx_id)
    return tx_ids


def _storm_arm(root: str, envs_by_client, mat: dict, gated: bool,
               drain_delay_s: float, queue_cap: int,
               staged: int = 0, verify_many=None) -> dict:
    """One storm run: every client thread pushes its envelopes as fast
    as the ingress admits them; a sleep shim on write_block caps the
    drain rate (the controlled overload; `drain_delay_s` <= 0 leaves
    the backend unthrottled, so INGRESS is the binding resource).
    `staged` > 0 arms the staged ingress engine at that coalescing
    depth; `verify_many` overrides the Writers batch verifier (the
    device arms).  Returns stats AFTER asserting the invariant: every
    admitted envelope committed exactly once, every shed answered
    typed."""
    import tempfile
    import threading

    from fabric_mod_tpu.orderer import (Broadcast,
                                        ResourceExhaustedError)

    knobs = {"FABRIC_MOD_TPU_SUBMIT_QUEUE": str(queue_cap)} if gated \
        else {}
    if staged > 0:
        knobs["FABRIC_MOD_TPU_STAGED_BROADCAST"] = str(staged)
    saved = {k: os.environ.pop(k, None)
             for k in ("FABRIC_MOD_TPU_SUBMIT_QUEUE",
                       "FABRIC_MOD_TPU_INGRESS_RATE",
                       "FABRIC_MOD_TPU_SHED_LAT_S",
                       "FABRIC_MOD_TPU_STAGED_BROADCAST")}
    os.environ.update(knobs)
    try:
        with tempfile.TemporaryDirectory(dir=root) as tmp:
            registrar, support = _storm_channel(tmp, mat, verify_many)
            if drain_delay_s > 0:
                # drain throttle: a bounded-rate ordering backend
                orig_write = support.writer.write_block

                def slow_write(block, _orig=orig_write):
                    time.sleep(drain_delay_s)
                    return _orig(block)
                support.writer.write_block = slow_write
            bcast = Broadcast(registrar)

            admitted, shed, errors = [], [], []
            latencies = []
            rec_lock = threading.Lock()
            stop_mon = threading.Event()
            max_depth = [0]

            def monitor():
                while not stop_mon.is_set():
                    q, _cap = support.chain.submit_queue_depth()
                    if q > max_depth[0]:
                        max_depth[0] = q
                    time.sleep(0.002)

            def client_main(my_envs):
                acc, sh, lat, errs = [], [], [], []
                for tx_id, env in my_envs:
                    t0 = time.perf_counter()
                    try:
                        bcast.submit(env)
                        lat.append(time.perf_counter() - t0)
                        acc.append(tx_id)
                    except ResourceExhaustedError as e:
                        sh.append((tx_id, e.reason))
                    except Exception as e:  # noqa: BLE001 — gate fails
                        errs.append((tx_id, repr(e)))
                with rec_lock:
                    admitted.extend(acc)
                    shed.extend(sh)
                    latencies.extend(lat)
                    errors.extend(errs)

            threads = [threading.Thread(target=client_main, args=(ce,),
                                        daemon=True)
                       for ce in envs_by_client]
            mon = threading.Thread(target=monitor, daemon=True)
            mon.start()
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            burst_wall = time.perf_counter() - t0

            # drain: EXACTLY the admitted count must land (the threads
            # have joined, so the target is known); the deadline turns
            # a lost tx into a loud invariant failure below instead of
            # a hang
            want = len(admitted)
            deadline = time.time() + max(
                120.0, 2 * want * drain_delay_s + 30.0)
            store = support.store
            while time.time() < deadline:
                landed = sum(
                    len(store.get_block_by_number(i).data.data)
                    for i in range(1, store.height))
                if landed >= want:
                    break
                time.sleep(0.02)
            drain_wall = time.perf_counter() - t0 - burst_wall
            stop_mon.set()
            mon.join(timeout=2)
            committed = _storm_committed_tx_ids(support.store)
            bcast.close()          # stop any staging lanes
            registrar.close()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # -- the consistency gate (before ANY rate is reported) --------------
    if errors:
        raise AssertionError(
            f"storm: {len(errors)} untyped failures, e.g. {errors[:3]}")
    from collections import Counter
    commit_counts = Counter(committed)
    dupes = {t: c for t, c in commit_counts.items() if c > 1}
    if dupes:
        raise AssertionError(f"storm: double-committed {dupes}")
    lost = set(admitted) - set(committed)
    if lost:
        raise AssertionError(
            f"storm: {len(lost)} admitted-then-LOST txs, "
            f"e.g. {sorted(lost)[:5]}")
    ghost = set(committed) - set(admitted)
    if ghost:
        raise AssertionError(
            f"storm: {len(ghost)} committed-but-shed txs {sorted(ghost)[:5]}")
    total = len(admitted) + len(shed)
    lat_sorted = sorted(latencies)
    p99 = lat_sorted[int(0.99 * (len(lat_sorted) - 1))] if lat_sorted \
        else 0.0
    shed_reasons = {}
    for _t, reason in shed:
        shed_reasons[reason] = shed_reasons.get(reason, 0) + 1
    wall = burst_wall + max(0.0, drain_wall)
    return {
        "accepted": len(admitted),
        "shed": len(shed),
        "shed_fraction": round(len(shed) / total, 4) if total else 0.0,
        "shed_reasons": shed_reasons,
        "accepted_tx_per_sec": round(len(admitted) / burst_wall, 1),
        # submit-to-committed: the honest throughput once the drain
        # tail (the buffered backlog) is paid
        "sustained_tx_per_sec": round(len(admitted) / wall, 1),
        "p99_admission_ms": round(p99 * 1000, 2),
        "max_queue_depth": max_depth[0],
        "burst_wall_s": round(burst_wall, 2),
        "drain_wall_s": round(max(0.0, drain_wall), 2),
    }


def _multichannel_world(n_channels: int, n_blocks: int,
                        txs_per_block: int):
    """N per-channel block streams over ONE shared 3-org world: every
    4th tx under-endorsed (1-of-3 < 2 -> ENDORSEMENT_POLICY_FAILURE)
    so the differential's flags carry signal, per-channel key content
    so fingerprints differ across channels.  Returns (streams,
    make_target): streams[cid] -> encoded blocks; make_target builds
    a fresh (validator, ledger) commit target for `cid` against
    `verifier` under `root`."""
    from fabric_mod_tpu.ledger import KvLedger
    from fabric_mod_tpu.peer import (TxValidator,
                                     ValidationInfoProvider,
                                     ValidatorCommitTarget)
    from fabric_mod_tpu.policy import ApplicationPolicyEvaluator
    from fabric_mod_tpu.utils.fixtures import make_channel_stream

    _csp, _cas, mgr, signers, cc_policy = _three_org_world()
    log(f"multichannel: signing {n_channels} channels x {n_blocks} "
        f"blocks x {txs_per_block} txs ...")
    # the shared oracle stream generator (utils/fixtures.py): bench
    # and tests/test_sharding.py gate against the SAME streams
    streams = {f"mc{c}": make_channel_stream(
        signers, f"mc{c}", n_blocks, txs_per_block)
        for c in range(n_channels)}

    def make_target(cid, verifier, root):
        led = KvLedger(root, cid)
        validator = TxValidator(
            cid, mgr, ApplicationPolicyEvaluator(mgr), verifier,
            ValidationInfoProvider(cc_policy),
            tx_id_exists=led.tx_id_exists)
        return ValidatorCommitTarget(validator, led)
    return streams, make_target


def _axis3(lo, mid, hi):
    """>=3 distinct monotone points per axis (collapses gracefully
    when the caller passes a tiny maximum)."""
    return sorted({lo, mid, hi})


def measure_multichannel(n_slices: int, n_channels: int, n_peers: int,
                         n_blocks: int, txs_per_block: int,
                         use_sw: bool) -> dict:
    """The channel-sharded scale curve: N channels placed on mesh
    slices by a ChannelShardRouter, blocks driven round-robin through
    the per-channel slice-pinned commit pipes while `peers` gossip-
    storm-style riders push small verifies through the SHARED
    cross-channel service (small channels riding big channels' flush
    windows — the whole point of sharing the front door).

    Per point, BEFORE any rate is reported, every channel's per-block
    txflags and final state fingerprint are asserted BIT-IDENTICAL to
    an independent unsharded synchronous run of the same stream — the
    sharded path may only move work, never change a verdict.

    The sweep holds a base point and varies each axis (slices,
    channels, peers) through >=3 values; the JSON carries the full
    point list: aggregate committed tx/s per (slices x channels x
    peers) — the scale curve MULTICHIP_r*.json records."""
    import tempfile
    import threading

    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.protos import messages as m
    from fabric_mod_tpu.protos import protoutil
    from fabric_mod_tpu.sharding import ChannelShardRouter
    from fabric_mod_tpu.utils.fixtures import make_verify_items

    streams, make_target = _multichannel_world(
        n_channels, n_blocks, txs_per_block)
    cids = list(streams)
    csp = SwCSP()

    if use_sw:
        from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
        make_verifier = lambda mesh: FakeBatchVerifier(csp)
        meshes_for = lambda s: None
    else:
        import jax

        from fabric_mod_tpu.bccsp.tpu import TpuVerifier
        from fabric_mod_tpu.parallel import slice_meshes
        n_dev = len(jax.devices())
        # cache off: points replay identical streams, and the curve
        # must measure placement, not the memo LRU
        make_verifier = lambda mesh: TpuVerifier(mesh=mesh,
                                                 cache_size=0)

        def meshes_for(s):
            # a slice count the device set cannot split evenly runs
            # UNMESHED slices (distinct programs, whole device set
            # visible to each) — recorded per point as meshed=False
            return slice_meshes(s) if s <= n_dev and n_dev % s == 0 \
                else None

    # -- the independent-unsharded oracle (and serial baseline rate) -----
    from fabric_mod_tpu.utils.fixtures import independent_baseline
    with tempfile.TemporaryDirectory(prefix="fmt_mc_base_") as tmp:
        if not use_sw:
            # device arm: an untimed warm baseline pass first, so the
            # cold whole-mesh compile never lands in serial_secs — the
            # sweep points each get a warm pass below, and a compile-
            # inflated denominator would bias vs_baseline sharded-ward
            independent_baseline(
                streams,
                lambda cid: make_target(cid, make_verifier(None),
                                        f"{tmp}/warm-{cid}"))
        baseline = independent_baseline(
            streams,
            lambda cid: make_target(cid, make_verifier(None),
                                    f"{tmp}/{cid}"))
    serial_secs = {cid: b[2] for cid, b in baseline.items()}
    distinct = {f for flags, _fp, _dt in baseline.values()
                for blk in flags for f in blk}
    if distinct == {0}:
        raise AssertionError(
            "multichannel streams produced only VALID flags — the "
            "under-endorsed lanes the oracle relies on are gone")

    rider_items, rider_expect = make_verify_items(8, invalid_every=3,
                                                  seed=b"mc-rider")

    def run_point(s, c, p, root) -> dict:
        point_cids = cids[:c]
        c = len(point_cids)                # the committed truth: the
        #                                    axis value may exceed the
        #                                    generated channel set
        router = ChannelShardRouter(
            n_slices=s, meshes=meshes_for(s), depth=2,
            verifier_factory=lambda i, mesh: make_verifier(mesh))
        stop = threading.Event()
        riders = []
        try:
            targets = {}
            for cid in point_cids:
                handle = router.add_channel(cid)
                targets[cid] = make_target(cid, handle,
                                           f"{root}/{cid}")
                router.bind_target(cid, targets[cid])
            rider_counts = [0] * p
            rider_errs = []

            def rider(k):
                i = k
                while not stop.is_set():
                    cid = point_cids[i % len(point_cids)]
                    try:
                        # timeout well under the finally's join budget
                        # so a wedged rider is observed dead, never
                        # left racing router.close()
                        got = router.service.verify_many_for(
                            cid, rider_items, timeout=30)
                    except Exception as e:  # noqa: BLE001 — gate fails
                        # a dying rider must FAIL the point, not
                        # silently deflate its rider rate: the curve
                        # claims the shared front door carried this
                        # traffic
                        rider_errs.append(f"rider {k} died: {e!r}")
                        return
                    if got != rider_expect:
                        rider_errs.append(
                            f"rider {k} verdicts wrong")
                        return
                    rider_counts[k] += 1
                    i += 1
                    # gossip-cadence pacing: riders model redelivery
                    # traffic, not a busy-spin that starves the GIL
                    stop.wait(0.02)

            riders = [threading.Thread(target=rider, args=(k,),
                                       daemon=True) for k in range(p)]
            for t in riders:
                t.start()
            t0 = time.perf_counter()
            for n in range(n_blocks):
                for cid in point_cids:
                    router.submit_block(
                        cid, m.Block.decode(streams[cid][n]))
            if not router.flush(timeout_s=3600):
                raise AssertionError("multichannel flush timed out")
            dt = time.perf_counter() - t0
            if rider_errs:
                raise AssertionError(rider_errs[0])
            # the per-point acceptance gate, BEFORE any rate
            for cid in point_cids:
                led = targets[cid].ledger
                got = [list(protoutil.block_txflags(
                    led.get_block_by_number(nb)))
                    for nb in range(led.height)]
                if got != baseline[cid][0]:
                    raise AssertionError(
                        f"sharded txflags diverge from the "
                        f"independent run on {cid}")
                if led.state_fingerprint() != baseline[cid][1]:
                    raise AssertionError(
                        f"sharded state fingerprint diverges on {cid}")
            txs = c * n_blocks * txs_per_block
            return {
                "slices": s, "channels": c, "peers": p,
                "tx_per_sec": round(txs / dt, 1),
                "rider_verifies_per_sec": round(
                    sum(rider_counts) * len(rider_items) / dt, 1),
                "meshed": meshes_for(s) is not None,
            }
        finally:
            # riders stop BEFORE the router teardown on every exit
            # path — the join budget exceeds the riders' 30 s verify
            # deadline, so even a wedged rider fails typed and exits
            # before the service it rides is closed under it
            stop.set()
            for t in riders:
                t.join(timeout=90)
            router.close()

    # every axis clamped to the user-requested cap (and the channel
    # axis additionally to the GENERATED channel set): a sweep must
    # never run a point the caller asked to exclude — on the device
    # arm an unrequested slice count would also pay an extra
    # per-slice-shape compile.  Small caps collapse below 3 values;
    # the recorded-curve acceptance runs the defaults, which don't.
    s_axis = sorted({min(v, n_slices) for v in
                     (1, max(1, n_slices // 2), max(1, n_slices))})
    c_axis = sorted({min(v, len(cids)) for v in
                     (1, max(2, n_channels // 2), max(1, n_channels))})
    p_axis = sorted({min(v, n_peers) for v in
                     (0, n_peers // 4, n_peers)})
    s_mid, c_mid, p_mid = s_axis[len(s_axis) // 2], \
        c_axis[len(c_axis) // 2], p_axis[len(p_axis) // 2]
    sweep = []
    for s in s_axis:
        sweep.append((s, c_mid, p_mid))
    for c in c_axis:
        sweep.append((s_mid, c, p_mid))
    for p in p_axis:
        sweep.append((s_mid, c_mid, p))
    sweep = sorted(set(sweep))

    points = []
    with tempfile.TemporaryDirectory(prefix="fmt_mc_") as tmp:
        for k, (s, c, p) in enumerate(sweep):
            if not use_sw:
                # device arm: one untimed pass per point absorbs the
                # per-slice-shape XLA compiles, then the timed pass
                run_point(s, c, p, f"{tmp}/warm{k}")
            pt = run_point(s, c, p, f"{tmp}/pt{k}")
            log(f"multichannel point {pt}")
            points.append(pt)

    best = max(points, key=lambda pt: pt["tx_per_sec"])
    # serial-independent rate over the SAME channel set as the best
    # point: the honest scaling denominator (what N separate
    # unsharded processes did, one after another, on this host)
    best_cids = cids[:best["channels"]]
    serial_rate = (best["channels"] * n_blocks * txs_per_block
                   / max(sum(serial_secs[cid] for cid in best_cids),
                         1e-9))
    return {
        "points": points,
        "best": best,
        "agg_tx_per_sec": best["tx_per_sec"],
        "serial_independent_tx_per_sec": round(serial_rate, 1),
        "axes": {"slices": s_axis, "channels": c_axis,
                 "peers": p_axis},
        "blocks_per_channel": n_blocks,
        "txs_per_block": txs_per_block,
        "distinct_flags": sorted(distinct),
        "sharded_vs_independent_identical": True,   # gated per point
        "verifier": "sw" if use_sw else "device",
    }


def measure_soak(seed, n_events, kinds=None) -> dict:
    """Sustained soak-under-churn (host-only): the full SoakHarness
    run — mixed x509+idemix traffic across channels while the seeded
    ChurnPlan joins peers, revokes ACLs, reshapes batches, changes the
    consenter set, kills leaders, hard-crashes + rejoins peers on
    their durable dirs, restarts orderers from their WALs, and
    installs/heals network partitions, with the background fault plan
    permanently armed.  Every invariant (fingerprint convergence
    within the recovery window, admitted => committed exactly once,
    no thread leaks, throughput recovery) gates BEFORE any rate is
    reported; the JSON carries per-event-kind recovery times and the
    replayable seed + schedule.  `kinds` (--soak-kinds, comma list)
    restricts the plan's event catalog."""
    from fabric_mod_tpu.observability import tracing
    from fabric_mod_tpu.soak import SoakConfig, SoakHarness
    kind_tuple = None
    if kinds:
        from fabric_mod_tpu.soak import EVENT_KINDS
        kind_tuple = tuple(k.strip() for k in kinds.split(",")
                           if k.strip())
        bad = [k for k in kind_tuple if k not in EVENT_KINDS]
        if bad:
            raise SystemExit(f"--soak-kinds: unknown kind(s) {bad}; "
                             f"catalog: {', '.join(EVENT_KINDS)}")
    cfg = SoakConfig(seed=seed, n_events=n_events, kinds=kind_tuple)
    log(f"soak: seed {cfg.seed}, {cfg.n_events} events, "
        f"{cfg.n_channels} channels, {cfg.n_peers} peers")
    harness = SoakHarness(cfg)
    log(f"soak schedule: {harness.plan.to_json()}")
    # armed: the report carries the run-wide stage attribution, and a
    # SoakError carries the flight-recorder tail next to its replay
    # seed + schedule
    with tracing.active():
        rep = harness.run()
    log(f"soak: PASS — {rep['x509_txs']} x509 + {rep['idemix_txs']} "
        f"idemix txs over {rep['wall_secs']}s, "
        f"{rep['fault_fires']} background faults fired")
    return rep


def _fanout_chain(channel_id: str, n_blocks: int, config_at: int):
    """Deterministic committed chain for the fan-out A/B: endorser txs
    with chaincode events (the filtered projection has real work), a
    multi-action tx per block (exercising the batch scanner's
    fallback), and one mid-chain CONFIG block (exercising the forced
    session re-check)."""
    from fabric_mod_tpu.protos import messages as m
    from fabric_mod_tpu.protos import protoutil

    def tx_bytes(txid, nactions=1):
        actions = []
        for _ in range(nactions):
            ev = m.ChaincodeEvent(chaincode_id="cc", tx_id=txid,
                                  event_name="moved",
                                  payload=b"p" * 64).encode()
            cca = m.ChaincodeAction(results=b"rw" * 32, events=ev)
            prp = m.ProposalResponsePayload(proposal_hash=b"h" * 32,
                                            extension=cca.encode())
            cap = m.ChaincodeActionPayload(
                chaincode_proposal_payload=b"cpp",
                action=m.ChaincodeEndorsedAction(
                    proposal_response_payload=prp.encode(),
                    endorsements=[m.Endorsement(endorser=b"e" * 64,
                                                signature=b"s" * 70)]))
        actions.append(m.TransactionAction(header=b"sh",
                                           payload=cap.encode()))
        return m.Transaction(actions=actions).encode()

    def env(txid, htype=None, data=b""):
        htype = (m.HeaderType.ENDORSER_TRANSACTION
                 if htype is None else htype)
        ch = protoutil.make_channel_header(htype, channel_id, tx_id=txid)
        sh = protoutil.make_signature_header(b"creator", b"\x00" * 24)
        payload = protoutil.make_payload(ch, sh, data)
        return m.Envelope(payload=payload.encode(), signature=b"sig")

    blocks = []
    for b in range(n_blocks):
        if b == config_at:
            envs = [env(f"cfg-{b}", htype=m.HeaderType.CONFIG,
                        data=b"new-config")]
        else:
            envs = [env(f"t{b}-{i}", data=tx_bytes(f"t{b}-{i}"))
                    for i in range(3)]
            envs.append(env(f"t{b}-multi",
                            data=tx_bytes(f"t{b}-multi", nactions=2)))
        blk = protoutil.new_block(b, b"\x00" * 32, envs)
        protoutil.set_block_txflags(
            blk, bytes([m.TxValidationCode.VALID] * len(envs)))
        blocks.append(blk)
    return blocks


class _RevealLedger:
    """ledger-shaped replay source: the pre-built chain revealed block
    by block (the sustained commit traffic), identically for both
    arms — the determinism the byte-identity gate needs."""

    def __init__(self, blocks):
        import threading
        self._blocks = blocks
        self._revealed = 0
        self.height_changed = threading.Condition()

    @property
    def height(self):
        return self._revealed

    def get_block_by_number(self, num):
        if 0 <= num < self._revealed:
            return self._blocks[num]
        return None

    def reveal(self):
        self._revealed += 1
        with self.height_changed:
            self.height_changed.notify_all()


def measure_deliverfanout(n_subscribers: int) -> dict:
    """Shared fan-out vs per-stream materialization (host-only A/B).

    Per swept subscriber count: the SAME revealed-block-by-block chain
    drives (a) the shared FanoutEngine with N mixed full/filtered
    subscribers consuming ring frames over a small worker pool, and
    (b) the historical per-stream arm (every stream re-projects +
    re-encodes every block, batch=False) on a bounded sample of
    streams (the arm's blocks*subs/s is size-invariant — each frame
    costs a full materialization regardless of N).

    Gates, per point, BEFORE any rate is reported:
      * byte-identity — every subscriber's frame-sequence digest equals
        the per-stream arm's digest for its form;
      * one materialization + one encode per (block, form), zero
        ring fallbacks;
      * the batched session ACL fired exactly once per (group, key).
    """
    import hashlib
    import threading as th
    import time as _t

    from fabric_mod_tpu.peer.fanout import FanoutEngine, encode_frame
    from fabric_mod_tpu.protos.protoutil import SignedData

    channel_id = "bench-fanout"
    n_groups = 4

    class _SeqAcl:
        def __init__(self):
            self.seq = 0
            self.checks = 0

        def config_sequence(self):
            return self.seq

        def check_acl(self, resource, sds):
            self.checks += 1

    points = sorted({max(8, n_subscribers // 100),
                     max(32, n_subscribers // 10), n_subscribers})
    results = []
    for n_subs in points:
        n_blocks = max(6, min(24, 200_000 // max(1, n_subs)))
        config_at = n_blocks // 2
        if n_subs >= 100_000:
            # the 100k top point replays a chain that arrived over the
            # DISSEMINATION RELAY (read back from a non-leader peer's
            # ledger) — the fan-out engine's input provably composes
            # with the tree path, not only a leader's own pull.  Real
            # committed blocks carry no mid-chain config tx; the
            # pacer's sequence advance still exercises the standing
            # session re-check (gate 3's lower bound).
            cid, blocks = _relayed_chain(n_blocks)
        else:
            cid, blocks = channel_id, _fanout_chain(
                channel_id, n_blocks, config_at)

        # reference digests: the per-stream sender's exact output
        refs = {}
        for form in ("full", "filtered"):
            h = hashlib.sha256()
            for blk in blocks:
                h.update(encode_frame(cid, form, blk,
                                      batch=False))
            refs[form] = h.hexdigest()

        # -- shared arm ------------------------------------------------
        led = _RevealLedger(blocks)
        acl = _SeqAcl()
        eng = FanoutEngine(cid, led, acl,
                           ring_size=max(128, n_blocks))
        forms = ["full" if i % 2 else "filtered"
                 for i in range(n_subs)]
        sessions = [eng.acl_groups.join(
            "event/Block" if forms[i] == "full"
            else "event/FilteredBlock",
            SignedData(data=b"d", identity=b"id%d" % (i % n_groups),
                       signature=b"s"),
            acl.seq) for i in range(n_subs)]
        for f in forms:
            eng.attach(f)
        digests = [hashlib.sha256() for _ in range(n_subs)]
        nexts = [0] * n_subs
        n_workers = min(8, n_subs)
        slices = [list(range(w, n_subs, n_workers))
                  for w in range(n_workers)]
        errors = []

        def run_slice(idx):
            try:
                waiter = eng.notifier.waiter()
                pending = set(slices[idx])
                while pending:
                    progress = False
                    for s in list(pending):
                        while nexts[s] < n_blocks:
                            fr = eng.get_frame(forms[s], nexts[s])
                            if fr is None:
                                break
                            if fr.is_config:
                                sessions[s].recheck(
                                    force=True, config_mark=fr.num)
                            else:
                                sessions[s].recheck()
                            digests[s].update(fr.payload)
                            nexts[s] += 1
                            progress = True
                        if nexts[s] >= n_blocks:
                            pending.discard(s)
                    if pending and not progress:
                        low = min(nexts[s] for s in pending)
                        if eng.notifier.wait_above(
                                low, waiter, timeout_s=30.0) == "timeout":
                            raise RuntimeError("fanout stall")
                eng.notifier.release(waiter)
            except Exception as e:  # worker failure must fail the gate
                errors.append(e)

        def pace():
            for b in range(n_blocks):
                if b == config_at:
                    acl.seq += 1      # the config commit advances it
                led.reveal()
                _t.sleep(0.001)       # sustained traffic, not a batch

        workers = [th.Thread(target=run_slice, args=(w,), daemon=True)
                   for w in range(n_workers)]
        t0 = _t.perf_counter()
        pacer = th.Thread(target=pace, daemon=True)
        pacer.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join(timeout=600)
        shared_s = _t.perf_counter() - t0
        pacer.join(timeout=60)
        for f in forms:
            eng.detach(f)
        eng.close()
        if errors:
            raise AssertionError(f"fanout worker failed: {errors[0]}")

        # gate 1: every stream's frame sequence bit-identical to the
        # per-stream arm's output
        for i in range(n_subs):
            assert digests[i].hexdigest() == refs[forms[i]], \
                f"stream {i} ({forms[i]}) diverged from the " \
                f"per-stream materialization at {n_subs} subscribers"
        # gate 2: one materialization + one encode per (block, form),
        # no slow-path fallbacks
        for form in ("full", "filtered"):
            st = eng.stats[form]
            assert st["materialized"] == n_blocks, st
            assert st["encoded"] == n_blocks, st
            assert st["fallbacks"] == 0, st
        # gate 3: the batched session re-check fired once per (group,
        # key) — at most two keys exist per config commit (the
        # standing sequence-advance recheck and the forced config-mark
        # recheck; which members hit first is timing), so N streams
        # produce at most 2 evaluations per group, never one per
        # stream
        n_group_objs = len(eng.acl_groups._groups)
        assert n_group_objs <= acl.checks <= 2 * n_group_objs, \
            (acl.checks, n_group_objs)

        # -- per-stream arm (bounded sample; rate is size-invariant) --
        sample = min(n_subs, 128)
        t0 = _t.perf_counter()
        h_check = [hashlib.sha256() for _ in range(sample)]
        for i in range(sample):
            form = forms[i]
            for blk in blocks:
                h_check[i].update(encode_frame(cid, form, blk,
                                               batch=False))
        per_stream_s = _t.perf_counter() - t0
        for i in range(sample):
            assert h_check[i].hexdigest() == refs[forms[i]]

        shared_rate = n_blocks * n_subs / shared_s
        per_rate = n_blocks * sample / per_stream_s
        log(f"deliverfanout: {n_subs} subs x {n_blocks} blocks — "
            f"shared {shared_rate:,.0f} vs per-stream "
            f"{per_rate:,.0f} blocks*subs/s "
            f"({shared_rate / per_rate:.1f}x, sample {sample})")
        results.append({
            "subscribers": n_subs, "blocks": n_blocks,
            "shared_blocks_subs_per_sec": round(shared_rate, 1),
            "per_stream_blocks_subs_per_sec": round(per_rate, 1),
            "per_stream_sample": sample,
            "identical": True,
            "acl_group_checks": acl.checks,
        })
    top = results[-1]
    ratio = (top["shared_blocks_subs_per_sec"]
             / top["per_stream_blocks_subs_per_sec"])
    assert ratio > 1.0, \
        f"shared fan-out did not beat per-stream at the top point " \
        f"({ratio:.2f}x)"
    return {"points": results, "top": top, "ratio": ratio}


def _build_relay_world(net, fabric, root_dir, n_peers):
    """`n_peers` relay-mode gossip peers over `net`'s channel, wired
    for the dissemination A/B: per-peer ledger + channel + GossipNode
    + RelayService + GossipService, leadership pinned statically to
    the min-(PKI-ID, endpoint) peer — the SAME peer the dynamic
    election and RelayService._elected_leader both derive, so the
    static pin changes nothing about who roots the tree.

    Membership and the tree PARENT's identity are seeded directly
    into discovery/the identity mapper instead of running alive
    broadcast rounds: at 128 peers the N^2 signed heartbeats plus
    N^2 cert validations are minutes of pure-python ECDSA on the
    fallback CSP — warm-up cost, not the dissemination under test.
    The relay path itself stays fully signed and fully verified.

    Returns (peers, leader_i, stream_calls): each peer is a dict
    (node/relay/svc/tap/mgr/channel), `stream_calls` counts deliver-
    source creations — the orderer-stream-economy gate reads its
    length."""
    from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
    from fabric_mod_tpu.channelconfig import Bundle
    from fabric_mod_tpu.channelconfig.configtx import config_from_block
    from fabric_mod_tpu.dissemination import RelayService
    from fabric_mod_tpu.gossip import GossipNode, GossipService
    from fabric_mod_tpu.ledger.kvledger import LedgerManager
    from fabric_mod_tpu.msp import ca as calib
    from fabric_mod_tpu.msp.identities import SigningIdentity
    from fabric_mod_tpu.orderer import DeliverService
    from fabric_mod_tpu.peer.channel import Channel
    from fabric_mod_tpu.protos import messages as m

    _, config = config_from_block(net.genesis_block)
    orgs = ("Org1", "Org2", "Org3")
    peers = []
    for i in range(n_peers):
        org = orgs[i % len(orgs)]
        csp = net.csp
        mgr = LedgerManager(os.path.join(root_dir, f"relay{i}"))
        ledger = mgr.create_or_open(net.channel_id)
        channel = Channel(net.channel_id, ledger,
                          FakeBatchVerifier(csp),
                          Bundle(net.channel_id, config, csp), csp)
        if ledger.height == 0:
            channel.init_from_genesis(net.genesis_block)
        cert, key = net.cas[org].issue(f"dis{i}.{org.lower()}", org,
                                       ous=["peer"])
        signer = SigningIdentity(org, cert, calib.key_pem(key), csp)
        node = GossipNode(f"dis{i}:7051", signer, channel, fabric)
        relay = RelayService(node)
        tap = []
        relay.relay.on_deliver = \
            lambda num, frame, acc=tap: acc.append((num, frame))
        peers.append({"node": node, "relay": relay, "tap": tap,
                      "mgr": mgr, "channel": channel})
    for p in peers:
        node = p["node"]
        for other in peers:
            onode = other["node"]
            if onode is node:
                continue
            node.discovery.handle_alive(onode.pki_id, m.AliveMessage(
                membership=m.GossipMember(endpoint=onode.endpoint,
                                          pki_id=onode.pki_id),
                timestamp=m.PeerTime(inc_num=1, seq_num=1)))
    leader_i = min(range(n_peers),
                   key=lambda i: (peers[i]["node"].pki_id,
                                  peers[i]["node"].endpoint))
    by_ep = {p["node"].endpoint: p["node"] for p in peers}
    tree = peers[leader_i]["relay"].tree()
    for p in peers:
        parent_ep = tree.parent(p["node"].endpoint)
        if parent_ep is not None:
            # the only inbound envelope signer this peer must verify
            p["node"].mapper.put(by_ep[parent_ep]._identity)
    stream_calls = []

    def factory():
        stream_calls.append(1)
        return DeliverService(net.support)

    for i, p in enumerate(peers):
        p["svc"] = GossipService(p["node"], factory,
                                 static_leader=(i == leader_i),
                                 relay=p["relay"])
        # long anti-entropy cadence, pinned BEFORE svc.start()'s
        # idempotent re-start: the quiescent-channel pull hellos are
        # sqrt-N signed messages per peer per tick — at 128 peers
        # that storm measures the fallback CSP, not the relay.  The
        # relay's explicit request_gap prod stays live for repairs.
        p["node"].state.start(interval_s=120.0)
    return peers, leader_i, stream_calls


def _stop_relay_world(peers, leader_i):
    # root first, so no push races the children's teardown
    peers[leader_i]["svc"].stop()
    for i, p in enumerate(peers):
        if i != leader_i:
            p["svc"].stop()
    for p in peers:
        p["node"].stop()
        p["mgr"].close()


def _relayed_chain(n_blocks: int) -> tuple:
    """(channel_id, blocks) for the fan-out sweep's TOP point, read
    back from a relayed NON-leader peer's ledger: a 4-peer
    dissemination tree carries ONE orderer deliver stream to every
    peer, so the chain the 100k-subscriber fan-out replays provably
    arrived over the relay path, not a per-peer pull."""
    import tempfile
    import time as _t

    from fabric_mod_tpu.e2e import Network
    from fabric_mod_tpu.gossip import InProcNetwork

    tmp = tempfile.mkdtemp(prefix="fmt_dissem_chain_")
    net = Network(tmp, batch_timeout="50ms", max_message_count=4)
    try:
        for i in range(4 * n_blocks):
            net.invoke([b"put", b"fk%d" % i, b"fv%d" % i])
        net.pump_committed(4 * n_blocks)
        target_h = net.support.store.height
        assert target_h - 1 >= n_blocks, target_h
        fabric = InProcNetwork()
        peers, leader_i, streams = _build_relay_world(net, fabric,
                                                      tmp, 4)
        try:
            for i, p in enumerate(peers):
                if i != leader_i:
                    p["svc"].start()
            peers[leader_i]["svc"].start()
            deadline = _t.perf_counter() + 120.0
            while _t.perf_counter() < deadline:
                if all(p["channel"].ledger.height >= target_h
                       for p in peers):
                    break
                _t.sleep(0.005)
            src = peers[next(i for i in range(len(peers))
                             if i != leader_i)]
            assert src["channel"].ledger.height >= target_h, \
                [p["channel"].ledger.height for p in peers]
            assert len(streams) == 1, len(streams)
            got = {num for num, _ in src["tap"]}
            assert got == set(range(1, target_h)), sorted(got)
            blocks = [src["channel"].ledger.get_block_by_number(num)
                      for num in range(1, 1 + n_blocks)]
        finally:
            _stop_relay_world(peers, leader_i)
        return net.channel_id, blocks
    finally:
        net.close()


def measure_dissemination(n_peers: int) -> dict:
    """Tree relay vs per-peer orderer pull (host-only A/B).

    Per swept peer count, the SAME pre-committed orderer chain drives
    (a) relay mode — ONE gossip leader pulls the deliver stream and
    the degree-d dissemination tree carries each frame to every other
    peer over the signed gossip comm layer — and (b) all-pull mode —
    every peer dials its own DeliverClient (the pre-forest cost
    model).

    Gates, per point, BEFORE any rate is reported:
      * byte-identity — every relayed frame equals the frame a DIRECT
        orderer pull produces on a peer (the all-pull arm's committed
        ledger is the reference encoder — peer commit sets the
        tx-flags metadata, so the orderer's raw store is NOT the
        right oracle), and every non-leader received the WHOLE chain
        through the tree;
      * convergence — one state fingerprint across all relay-mode
        peers, equal to the all-pull arm's;
      * stream economy — the orderer served exactly ONE deliver
        stream for the whole relay arm (== the number of leaders,
        the forest's headline contract) while the all-pull arm paid
        one stream per peer.
    """
    import tempfile
    import threading as th
    import time as _t

    from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
    from fabric_mod_tpu.channelconfig import Bundle
    from fabric_mod_tpu.channelconfig.configtx import config_from_block
    from fabric_mod_tpu.e2e import Network
    from fabric_mod_tpu.gossip import InProcNetwork
    from fabric_mod_tpu.ledger.kvledger import LedgerManager
    from fabric_mod_tpu.orderer import DeliverService
    from fabric_mod_tpu.peer.channel import Channel
    from fabric_mod_tpu.peer.deliverclient import DeliverClient
    from fabric_mod_tpu.peer.fanout import encode_frame

    points = sorted({8, max(8, n_peers // 4), n_peers})
    results = []
    for n in points:
        tmp = tempfile.mkdtemp(prefix="fmt_dissem_bench_")
        net = Network(tmp, batch_timeout="50ms", max_message_count=12)
        try:
            # ~1 block per tx: each pure-python-signed invoke outlasts
            # the batch timeout, and the per-(block, peer) MCS verify
            # + commit (~60ms on the fallback CSP) is what the sweep
            # scales by — 6 blocks keeps the 128-peer point inside the
            # worker budget while still measuring a sustained stream
            n_txs = 6
            for i in range(n_txs):
                net.invoke([b"put", b"dk%d" % i, b"dv%d" % i])
            net.pump_committed(n_txs)
            target_h = net.support.store.height
            n_blocks = target_h - 1
            _, config = config_from_block(net.genesis_block)

            # -- all-pull arm FIRST: its committed ledgers are the
            # byte-identity gate's reference encoders ----------------
            pull_streams = []

            def pull_source():
                pull_streams.append(1)
                return DeliverService(net.support)

            pulls = []
            for i in range(n):
                mgr = LedgerManager(os.path.join(tmp, f"pull{i}"))
                ledger = mgr.create_or_open(net.channel_id)
                channel = Channel(net.channel_id, ledger,
                                  FakeBatchVerifier(net.csp),
                                  Bundle(net.channel_id, config,
                                         net.csp), net.csp)
                if ledger.height == 0:
                    channel.init_from_genesis(net.genesis_block)
                pulls.append({"mgr": mgr, "channel": channel,
                              "client": DeliverClient(channel,
                                                      pull_source())})

            def pull_main(c):
                try:
                    c.run(idle_timeout_s=30.0)
                except Exception:
                    pass    # stopped post-convergence; heights gate

            threads = [th.Thread(target=pull_main,
                                 args=(p["client"],), daemon=True)
                       for p in pulls]
            t0 = _t.perf_counter()
            for t in threads:
                t.start()
            deadline = t0 + 180.0 + 0.5 * n
            while _t.perf_counter() < deadline:
                if all(p["channel"].ledger.height >= target_h
                       for p in pulls):
                    break
                _t.sleep(0.002)
            pull_s = _t.perf_counter() - t0
            heights = [p["channel"].ledger.height for p in pulls]
            assert all(h >= target_h for h in heights), heights
            for p in pulls:
                p["client"].stop()
            for t in threads:
                t.join(timeout=30)
            assert len(pull_streams) == n, len(pull_streams)
            ref_ledger = pulls[0]["channel"].ledger
            refs = {num: encode_frame(net.channel_id, "full",
                                      ref_ledger.get_block_by_number(
                                          num))
                    for num in range(1, target_h)}
            pull_fps = {p["channel"].ledger.state_fingerprint()
                        for p in pulls}
            assert len(pull_fps) == 1, pull_fps

            # -- relay arm -------------------------------------------
            fabric = InProcNetwork()
            peers, leader_i, relay_streams = _build_relay_world(
                net, fabric, tmp, n)
            t0 = _t.perf_counter()
            for i, p in enumerate(peers):    # children accept BEFORE
                if i != leader_i:            # the root starts pushing
                    p["svc"].start()
            peers[leader_i]["svc"].start()
            deadline = t0 + 180.0 + 0.5 * n
            while _t.perf_counter() < deadline:
                if all(p["channel"].ledger.height >= target_h
                       for p in peers):
                    break
                _t.sleep(0.002)
            relay_s = _t.perf_counter() - t0
            heights = [p["channel"].ledger.height for p in peers]
            assert all(h >= target_h for h in heights), heights

            # gate: ONE orderer deliver stream served n peers
            assert len(relay_streams) == 1, len(relay_streams)
            # gate: every non-leader got the WHOLE chain through the
            # tree, every frame byte-identical to the direct pull
            for i, p in enumerate(peers):
                if i == leader_i:
                    assert not p["tap"]      # the root receives nothing
                    continue
                got = dict(p["tap"])
                assert set(got) == set(range(1, target_h)), \
                    (i, sorted(got))
                for num, frame in got.items():
                    assert frame == refs[num], \
                        f"peer {i} frame {num} diverged from the " \
                        f"direct-pull encoding"
            # gate: convergence, and equal to the all-pull arm's state
            relay_fps = {p["channel"].ledger.state_fingerprint()
                         for p in peers}
            assert relay_fps == pull_fps, (relay_fps, pull_fps)
            rstats = {k: sum(p["relay"].stats.get(k, 0) for p in peers)
                      for k in ("pushed", "forwarded", "received",
                                "dropped", "send_failures",
                                "repair_prods", "duplicates")}
            assert rstats["received"] > 0, rstats
            _stop_relay_world(peers, leader_i)
            for p in pulls:
                p["mgr"].close()

            relay_rate = n_blocks * n / relay_s
            pull_rate = n_blocks * n / pull_s
            log(f"dissemination: {n} peers x {n_blocks} blocks — "
                f"relay {relay_rate:,.0f} vs all-pull "
                f"{pull_rate:,.0f} blocks*peers/s "
                f"(streams 1 vs {n})")
            results.append({
                "peers": n, "blocks": n_blocks,
                "relay_blocks_peers_per_sec": round(relay_rate, 1),
                "pull_blocks_peers_per_sec": round(pull_rate, 1),
                "orderer_streams_relay": len(relay_streams),
                "orderer_streams_pull": len(pull_streams),
                "relay_stats": rstats,
                "identical": True,
            })
        finally:
            net.close()
    top = results[-1]
    return {"points": results, "top": top,
            "ratio": (top["relay_blocks_peers_per_sec"]
                      / top["pull_blocks_peers_per_sec"])}


def measure_broadcaststorm(n_txs: int, n_clients: int = 8,
                           staged_batch: int = 64,
                           storm_verifier: str = "sw") -> dict:
    """A/B overload burst through the REAL ingress (Broadcast ->
    SoloChain -> block store): gated arm (bounded queue + overload
    gate) vs the un-gated PR 6 baseline (blocking puts), same
    pre-signed envelopes, a write_block sleep shim pinning the drain
    rate to ~1/4 of the measured submit capacity (a 4x-overload
    burst).

    With `staged_batch` > 0, a SECOND pair runs the staged-vs-unstaged
    A/B with the drain UNTHROTTLED: the throttled pair is about what
    admission does when the backend is the cap, the staged pair about
    what coalescing does when INGRESS is the cap (the tentpole's
    claim) — a throttled staged arm would just re-measure the
    throttle.  `storm_verifier` picks the Writers batch verifier both
    staged arms AND the throttled pair dispatch through: "sw" (host
    ECDSA: per-item cost is flat, so staging shows its queueing win
    only) or "device" (ops/p256 batch verify: real batch economics —
    one padded dispatch per drain vs one per submission; buckets are
    pre-warmed so no arm times an XLA compile).  Every arm must pass
    the consistency gate — every admitted envelope commits exactly
    once, every shed is typed — before any rate is reported."""
    import tempfile

    requested_txs = n_txs
    n_txs = max(n_clients * 4, n_txs)
    if n_txs != requested_txs:
        log(f"storm: raising txs {requested_txs} -> {n_txs} "
            f"(floor: 4 per client x {n_clients} clients)")
    per_client = n_txs // n_clients
    max_message_count = 16

    # scrub ambient admission knobs for the WHOLE measurement,
    # calibration included — a user-set FABRIC_MOD_TPU_INGRESS_RATE
    # would shed calibration submits (crashing the metric) or skew
    # per_submit_s; each arm re-arms exactly what it measures
    scrubbed = {k: os.environ.pop(k, None)
                for k in ("FABRIC_MOD_TPU_SUBMIT_QUEUE",
                          "FABRIC_MOD_TPU_INGRESS_RATE",
                          "FABRIC_MOD_TPU_INGRESS_BURST",
                          "FABRIC_MOD_TPU_SHED_LAT_S",
                          "FABRIC_MOD_TPU_STAGED_BROADCAST")}
    vm_close = None
    try:
        with tempfile.TemporaryDirectory(prefix="fmt_storm_") as root:
            mat = _storm_material(n_clients, max_message_count, "100ms")
            clients = mat["clients"]
            vm = None
            if storm_verifier == "device":
                vm, vm_close = _storm_device_verifier(staged_batch)
            # calibration: the per-submit cost (Writers verify
            # dominates) sets the drain throttle for a ~4x overload
            from fabric_mod_tpu.orderer import Broadcast
            cal_registrar, _sup = _storm_channel(root + "/cal", mat, vm)
            cal_envs = _storm_envelopes(clients[:1], 16)
            cal_bcast = Broadcast(cal_registrar)
            t0 = time.perf_counter()
            for _ci, _tx, env in cal_envs:
                cal_bcast.submit(env)
            per_submit_s = max(
                1e-5, (time.perf_counter() - t0) / len(cal_envs))
            cal_bcast.close()
            cal_registrar.close()
            drain_delay_s = 4.0 * per_submit_s * max_message_count
            offered_rate = 1.0 / per_submit_s
            drain_rate = max_message_count / drain_delay_s
            log(f"storm calibration: {per_submit_s * 1000:.2f} "
                f"ms/submit -> offered ~{offered_rate:,.0f} tx/s, "
                f"drain capped at ~{drain_rate:,.0f} tx/s "
                f"({offered_rate / drain_rate:.1f}x overload)")

            log(f"storm: signing {n_clients} clients x {per_client} "
                f"envelopes ...")
            all_envs = _storm_envelopes(clients, per_client)
            by_client = [[(tx, env) for ci, tx, env in all_envs
                          if ci == i] for i in range(n_clients)]
            # cap well under the burst so the watermarks actually
            # engage at smoke scale too (>= one full block, <= burst/4)
            queue_cap = max(max_message_count,
                            min(4 * max_message_count,
                                len(all_envs) // 4))

            gated = _storm_arm(root, by_client, mat, True,
                               drain_delay_s, queue_cap, verify_many=vm)
            log(f"gated arm: {gated}")
            ungated = _storm_arm(root, by_client, mat, False,
                                 drain_delay_s, queue_cap, verify_many=vm)
            log(f"ungated arm: {ungated}")
            staged = unstaged = None
            if staged_batch > 0:
                # the staged A/B: same gated config, drain UNTHROTTLED
                # (ingress-limited — the resource staging changes)
                unstaged = _storm_arm(root, by_client, mat, True,
                                      0.0, queue_cap, verify_many=vm)
                log(f"unstaged ingress-limited arm: {unstaged}")
                staged = _storm_arm(root, by_client, mat, True,
                                    0.0, queue_cap,
                                    staged=staged_batch, verify_many=vm)
                log(f"staged arm (depth {staged_batch}): {staged}")
    finally:
        if vm_close is not None:
            vm_close()
        for k, v in scrubbed.items():
            if v is not None:
                os.environ[k] = v

    if gated["max_queue_depth"] > queue_cap:
        raise AssertionError(
            f"gated queue depth {gated['max_queue_depth']} exceeded "
            f"the {queue_cap} cap")
    if not gated["shed"]:
        raise AssertionError(
            "gated arm shed nothing under a 4x overload — the "
            "admission knobs did not engage")
    if ungated["shed"]:
        raise AssertionError("ungated arm shed — knob leakage")
    out = {
        "gated": gated,
        "ungated_baseline": ungated,
        "overload_x": round(offered_rate / drain_rate, 2),
        "queue_cap": queue_cap,
        "clients": n_clients,
        "txs": n_clients * per_client,
        "requested_txs": requested_txs,
        "storm_verifier": storm_verifier,
        "consistency": "admitted==committed exactly once, all arms",
    }
    if staged is not None:
        out["staged"] = staged
        out["unstaged_baseline"] = unstaged
        out["staged_batch"] = staged_batch
        out["staged_vs_unstaged"] = round(
            staged["sustained_tx_per_sec"]
            / max(unstaged["sustained_tx_per_sec"], 1e-9), 3)
    return out


def run_worker(args) -> int:
    """The actual measurement; prints the final JSON line on stdout.
    With --trace-out, the whole run executes FMT_TRACE-armed and the
    span ring is exported as Chrome trace-event JSON (Perfetto-
    loadable; device dispatches as async slices) on the way out."""
    # Under the axon sitecustomize the JAX_PLATFORMS env var alone does
    # NOT disable the TPU plugin (a half-disabled axon hangs); the
    # config update is the reliable switch, and it must happen before
    # any jax use in this process.
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    trace_mod = None
    if getattr(args, "trace_out", None):
        from fabric_mod_tpu.observability import tracing as trace_mod
        trace_mod.enable(True)
        trace_mod.install_compile_counter()
    try:
        return _worker_metric(args)
    finally:
        if trace_mod is not None:
            # best-effort: a bad --trace-out path must not mask the
            # metric's real result (or failure) from this finally
            try:
                d = os.path.dirname(os.path.abspath(args.trace_out))
                os.makedirs(d, exist_ok=True)
                n = trace_mod.export_chrome_trace(args.trace_out)
                log(f"[trace] {n} chrome trace events -> "
                    f"{args.trace_out} (xla compiles observed: "
                    f"{trace_mod.compile_count()})")
            except OSError as e:
                log(f"[trace] export to {args.trace_out} failed: {e}")


def _worker_metric(args) -> int:
    # A/B knobs for the pipelined front-end (all runtime-read env vars,
    # set before any fabric_mod_tpu construction):
    #   --mixed-add    -> affine-table mixed-addition ladder
    #   --memo-cache   -> verdict memo-cache size (0 disables)
    #   --inflight     -> in-flight dispatch window depth
    #   --precision    -> limb matmul precision (BENCH-SCOPED; the env
    #                     var is only honored through this entrypoint)
    if args.tensor_policy is not None:
        if args.tensor_policy:
            os.environ["FABRIC_MOD_TPU_TENSOR_POLICY"] = "1"
        else:
            os.environ.pop("FABRIC_MOD_TPU_TENSOR_POLICY", None)
    if args.mixed_add is not None:
        os.environ["FABRIC_MOD_TPU_MIXED_ADD"] = str(args.mixed_add)
    if args.memo_cache is not None:
        os.environ["FABRIC_MOD_TPU_VERDICT_CACHE"] = str(args.memo_cache)
    if args.inflight is not None:
        os.environ["FABRIC_MOD_TPU_INFLIGHT"] = str(args.inflight)
    precision = (args.precision
                 or os.environ.get("FABRIC_MOD_TPU_PRECISION", "highest"))
    if precision.lower() == "high":
        from fabric_mod_tpu.ops import limbs9
        limbs9.set_precision_mode("high")

    if args.metric == "marshal":
        from fabric_mod_tpu.bccsp.sw import HAVE_CRYPTOGRAPHY
        vec_rate, loop_rate = measure_marshal(args.batch,
                                              max(3, args.reps))
        out = {
            "metric": f"marshal_items_per_sec_{args.batch}_bucket",
            "value": round(vec_rate, 1),
            "unit": "items/s",
            "vs_baseline": round(vec_rate / loop_rate, 3),
            # the per-item loop decodes DER through whichever scalar
            # parser the platform has — label it so ratios are only
            # compared like-for-like across rounds
            "baseline_der": "openssl" if HAVE_CRYPTOGRAPHY
                            else "pure-python-scalar",
        }
        # host-only metric: no device banner needed
        print(json.dumps(out))
        return 0
    if args.metric == "diffverify":
        n, mismatches, extras = measure_diffverify(args.batch)
        out = {
            "metric": "mixed_ladder_verdict_differential",
            "value": float(n),
            "unit": "signatures",
            "vs_baseline": 1.0 if mismatches == 0 else 0.0,
            "mismatches": mismatches,
            **extras,
        }
        import jax
        out["platform"] = jax.devices()[0].platform
        print(json.dumps(out))
        return 0 if mismatches == 0 else 1
    if args.metric == "hashverify":
        fused_rate, base_rate = measure_hashverify(
            args.batch, max(1, args.reps))
        out = {
            "metric": "fused_hashverify_verifies_per_sec",
            "value": round(fused_rate, 1),
            "unit": "verifies/s",
            "vs_baseline": round(fused_rate / base_rate, 3),
        }
        import jax
        out["platform"] = jax.devices()[0].platform
        print(json.dumps(out))
        return 0
    if args.metric == "soak":
        # host-only (no device): the churn-soak integration run; the
        # invariants gate inside the harness — reaching here means
        # every convergence/exactly-once/leak/recovery check passed
        rep = measure_soak(args.soak_seed, args.soak_events,
                           kinds=args.soak_kinds)
        out = {
            "metric": "soak_churn_sustained_mixed_tx_per_sec",
            "value": rep["mixed_tx_per_sec"],
            "unit": "tx/s",
            # first soak record: no prior baseline config to compare
            # against — the gate is the invariants, not a ratio
            "vs_baseline": None,
            "x509_tx_per_sec": rep["x509_tx_per_sec"],
            "idemix_tx_per_sec": rep["idemix_tx_per_sec"],
            **{k: rep[k] for k in (
                "seed", "wall_secs", "x509_txs", "idemix_txs",
                "idemix_tamper_rejects", "audited_txs", "fault_fires",
                "submit_errors", "peers_final", "channels")},
            "recovery_s_by_kind": rep["recovery_s_by_kind"],
            "schedule": rep["schedule"],
        }
        if "stage_attribution" in rep:
            out["stage_attribution"] = rep["stage_attribution"]
        print(json.dumps(out))
        return 0
    if args.metric == "deliverfanout":
        # host-only (no device): the shared fan-out A/B; every rate is
        # gated by the byte-identity + once-per-(block, form) +
        # once-per-(group, key) assertions inside the measure
        extras = measure_deliverfanout(args.subscribers)
        out = {
            "metric": "deliverfanout_blocks_subscribers_per_sec",
            "value": extras["top"]["shared_blocks_subs_per_sec"],
            "unit": "blocks*subs/s",
            "vs_baseline": round(extras["ratio"], 3),
            "subscribers": extras["top"]["subscribers"],
            "points": extras["points"],
        }
        print(json.dumps(out))
        return 0
    if args.metric == "dissemination":
        # host-only (no device): the relay-vs-all-pull A/B; every rate
        # is gated by the frame byte-identity, all-peer fingerprint
        # convergence, and one-deliver-stream-per-leader assertions
        # inside the measure
        extras = measure_dissemination(
            max(8, args.peers if args.peers is not None else 128))
        out = {
            "metric": "dissemination_blocks_peers_per_sec",
            "value": extras["top"]["relay_blocks_peers_per_sec"],
            "unit": "blocks*peers/s",
            # relay vs the all-pull arm at the top point: on the CPU
            # fallback CSP the relay ALSO pays one pure-python
            # envelope verify per hop, so the honest headline here is
            # stream economy (1 orderer stream vs n), not the ratio
            "vs_baseline": round(extras["ratio"], 3),
            "peers": extras["top"]["peers"],
            "orderer_streams_relay":
                extras["top"]["orderer_streams_relay"],
            "orderer_streams_pull":
                extras["top"]["orderer_streams_pull"],
            "points": extras["points"],
        }
        print(json.dumps(out))
        return 0
    if args.metric == "statescale":
        # host-only (no device): the vectorized-MVCC state-scale
        # sweep; every rate is gated by the arm/size flag+fingerprint
        # identity, the incremental-vs-full fingerprint oracle, and
        # the zero-fallback assertion inside the measure
        sizes = sorted({int(s) for s in args.state_keys.split(",")
                        if s})
        extras = measure_statescale(sizes, durable=args.state_durable)
        top = extras["top"]
        out = {
            "metric": "statescale_committed_tx_per_sec_vector",
            "value": top["vector_tx_per_sec"],
            "unit": "tx/s",
            "vs_baseline": round(
                top["vector_tx_per_sec"]
                / max(top["generic_tx_per_sec"], 1e-9), 3),
            **extras,
        }
        print(json.dumps(out))
        return 0
    if args.metric == "broadcaststorm":
        # host-only (no device): the admission A/B under a 4x-overload
        # burst plus the staged-vs-unstaged ingress A/B.  The batch is
        # honored as requested up to a LOUD drain-tail wall-time cap
        # (the old silent min(batch, 512) hid that the requested scale
        # never ran); any cap is logged and recorded in the extras
        storm_cap = 4096
        n_storm = min(args.batch, storm_cap)
        if n_storm < args.batch:
            log(f"broadcaststorm: capping txs {args.batch} -> "
                f"{n_storm} (un-gated drain tail must fit the worker "
                f"budget)")
        n_clients = max(2, args.clients) if args.clients is not None \
            else 8
        staged_batch = args.staged_batch if args.staged_batch \
            is not None else 64
        extras = measure_broadcaststorm(n_storm, n_clients,
                                        staged_batch,
                                        args.storm_verifier)
        if n_storm < args.batch:
            extras["batch_capped"] = {"requested": args.batch,
                                      "ran": n_storm,
                                      "cap": storm_cap}
        g = extras["gated"]
        u = extras["ungated_baseline"]
        out = {
            # the client count rides the metric name (like gossip's
            # peer count): rates only ever compare like-for-like
            "metric": f"broadcaststorm_sustained_tx_per_sec_"
                      f"{n_clients}client",
            "value": g["sustained_tx_per_sec"],
            "unit": "tx/s",
            # ~1.0 = shedding lost no committed throughput while the
            # gated arm kept queue depth and p99 bounded (the extras)
            "vs_baseline": round(
                g["sustained_tx_per_sec"]
                / max(u["sustained_tx_per_sec"], 1e-9), 3),
            **extras,
        }
        print(json.dumps(out))
        return 0
    if args.metric == "policyeval":
        extras = measure_policyeval(
            max(32, min(args.batch, 1000)), max(1, args.reps),
            use_sw=args.policyeval_verifier == "sw")
        rate = extras.pop("tensor_tx_per_sec")
        out = {
            "metric": "policyeval_validated_tx_per_sec_2of3",
            "value": rate,
            "unit": "tx/s",
            "vs_baseline": round(
                rate / extras["closure_tx_per_sec"], 3),
            **extras,
        }
        if args.policyeval_verifier == "sw":
            # host-only A/B: no device banner needed
            print(json.dumps(out))
            return 0
        import jax
        out["platform"] = jax.devices()[0].platform
        print(json.dumps(out))
        return 0
    if args.metric == "multichannel":
        # blocks-per-channel scale with --batch at 4 txs/block,
        # floor 4 / cap 32 (the sweep multiplies by channels x points)
        n_blocks = max(4, min(32, args.batch // 16))
        extras = measure_multichannel(
            max(1, args.slices), max(1, args.channels),
            max(0, args.peers if args.peers is not None else 16),
            n_blocks, 4, use_sw=args.multichannel_verifier == "sw")
        rate = extras.pop("agg_tx_per_sec")
        out = {
            "metric": "multichannel_agg_committed_tx_per_sec",
            "value": rate,
            "unit": "tx/s",
            # scaling efficiency vs N independent unsharded runs done
            # serially on this host (the pre-sharding reality)
            "vs_baseline": round(
                rate / max(extras["serial_independent_tx_per_sec"],
                           1e-9), 3),
            **extras,
        }
        if args.multichannel_verifier == "sw":
            # host-only A/B: no device banner needed
            print(json.dumps(out))
            return 0
        import jax
        out["platform"] = jax.devices()[0].platform
        out["n_devices"] = len(jax.devices())
        print(json.dumps(out))
        return 0
    if args.metric == "commitpipe":
        # blocks scale with --batch at 8 txs/block, floor 32 blocks
        # (the acceptance stream); barrier cadence is fixed inside
        n_blocks = max(32, args.batch // 8)
        extras = measure_commitpipe(
            n_blocks, 8, max(1, args.pipeline_depth),
            use_sw=args.commitpipe_verifier == "sw")
        pipe_rate = extras.pop("pipelined_tx_per_sec")
        out = {
            "metric": "commitpipe_committed_tx_per_sec",
            "value": pipe_rate,
            "unit": "tx/s",
            "vs_baseline": round(pipe_rate / extras["sync_tx_per_sec"], 3),
            **extras,
        }
        if args.commitpipe_verifier == "sw":
            # host-only A/B: no device banner needed
            print(json.dumps(out))
            return 0
        import jax
        out["platform"] = jax.devices()[0].platform
        print(json.dumps(out))
        return 0
    if args.metric == "block":
        dev_rate, sw_rate = measure_block(min(args.batch, 1000), args.reps)
        out = {
            "metric": "validated_tx_per_sec_1k_block_2of3",
            "value": round(dev_rate, 1),
            "unit": "tx/s",
            "vs_baseline": round(dev_rate / sw_rate, 3),
        }
    elif args.metric == "idemix":
        # n presentations bounded: host signing dominates setup
        dev_rate, sw_rate, compile_secs = measure_idemix(
            min(args.batch, 64), max(1, min(args.reps, 2)))
        out = {
            "metric": "idemix_presentations_per_sec",
            "value": round(dev_rate, 1),
            "unit": "presentations/s",
            "vs_baseline": round(dev_rate / sw_rate, 3),
            # ~0 on a warm persistent cache (VERDICT #8's "done" bar)
            "compile_secs": round(compile_secs, 1),
        }
    elif args.metric == "gossip":
        # --peers grows the storm (50-peer default preserved; the
        # roadmap's "toward 500" runs land via the watcher matrix);
        # the metric name carries the count so rates are only ever
        # compared like-for-like
        n_peers = max(1, args.peers if args.peers is not None else 50)
        dev_rate, sw_rate = measure_gossip(n_peers, max(1, args.reps))
        out = {
            "metric": f"gossip_storm_block_verifies_per_sec_"
                      f"{n_peers}peer",
            "value": round(dev_rate, 1),
            "unit": "block-verifies/s",
            "vs_baseline": round(dev_rate / sw_rate, 3),
        }
    elif args.metric == "e2e":
        # the batch IS the tx count (the supervisor's CPU-fallback
        # bound must be respected; the consenter's batch timeout cuts
        # partial blocks, so small counts still flow)
        dev_rate, sw_rate, stats = measure_e2e(args.batch)
        out = {
            "metric": "e2e_validated_tx_per_sec",
            "value": round(dev_rate, 1),
            "unit": "tx/s",
            "vs_baseline": round(dev_rate / sw_rate, 3),
            "pipeline_split": stats,
        }
    else:
        from fabric_mod_tpu.bccsp.sw import HAVE_CRYPTOGRAPHY
        items, expect = make_items(args.batch)
        sw_rate = measure_sw(items, expect)
        log(f"sw baseline: {sw_rate:,.0f} verifies/s")
        dev_rate = measure_device(items, expect, args.reps)
        log(f"device: {dev_rate:,.0f} verifies/s "
            f"({dev_rate / sw_rate:.2f}x sw)")
        out = {
            "metric": "ecdsa_p256_verifies_per_sec",
            "value": round(dev_rate, 1),
            "unit": "verifies/s",
            "vs_baseline": round(dev_rate / sw_rate, 3),
            # the ratio is only comparable across rounds when the sw
            # baseline ran the same backend — label it
            "sw_backend": "openssl" if HAVE_CRYPTOGRAPHY
                          else "pure-python-fallback",
        }
    import jax
    out["platform"] = jax.devices()[0].platform
    print(json.dumps(out))
    return 0


# ---------------------------------------------------------------------------
# Supervisor (parent): hard timeouts, retries, CPU fallback
# ---------------------------------------------------------------------------

def _run_bounded(cmd, env, timeout_s: float, stderr):
    """subprocess.run with a timeout that actually BOUNDS: the child
    gets its own process group and on expiry the WHOLE group is
    SIGKILLed.  BENCH_r05 post-mortem: `subprocess.run(timeout=...)`
    kills only the direct child, then blocks in communicate() until
    every grandchild holding the stdout pipe exits — the TPU plugin's
    tunnel helpers do exactly that, so the 180s probe "timeout" hung
    far past 180s.  Returns (rc | None, stdout_bytes, note)."""
    import signal

    t0 = time.perf_counter()
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=stderr, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return proc.returncode, out, f"in {time.perf_counter() - t0:.0f}s"
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            # bounded drain: the group is dead, the pipe must close;
            # the belt-and-braces timeout guards a half-killed group
            out, _ = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out = b""
        return None, out, f"hung >{timeout_s:.0f}s (process group killed)"


def _preflight_probe(env, timeout_s: float):
    """Cheap TPU liveness probe in a throwaway child: just jax.devices().

    A hung axon tunnel used to cost the whole measurement budget
    (BENCH_r03 post-mortem: one 600s attempt, tunnel hung, round
    recorded the CPU fallback).  The probe bounds that discovery to
    `timeout_s` — enforced by process-group kill (`_run_bounded`), not
    subprocess.run's advisory timeout, which BENCH_r05 showed blowing
    through 180s while tunnel grandchildren held the stdout pipe.  The
    failure reason lands in the final JSON line ("preflight").
    """
    code = ("import jax, sys; d = jax.devices(); "
            "sys.stdout.write(d[0].platform)")
    rc, out, note = _run_bounded([sys.executable, "-c", code], env,
                                 timeout_s, subprocess.DEVNULL)
    if rc is None:
        return None, f"probe {note}"
    if rc != 0:
        return None, f"probe rc={rc}"
    platform = out.decode().strip() or "unknown"
    return platform, f"probe ok: platform={platform}"


def _spawn_worker(argv, env, timeout_s: float):
    """Run this script with --_worker; return (json_dict | None, note).
    Same process-group-bounded supervision as the probe."""
    cmd = [sys.executable, os.path.abspath(__file__), "--_worker"] + argv
    rc, out, note = _run_bounded(cmd, env, timeout_s, sys.stderr)
    if rc is None:
        return None, f"worker {note}"
    if rc != 0:
        return None, f"worker rc={rc} {note}"
    for line in reversed(out.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), f"ok {note}"
            except json.JSONDecodeError:
                pass
    return None, "worker produced no JSON"


def supervise(args, argv) -> int:
    # Two observed axon failure modes (r2/r3 post-mortems): the tunnel
    # hangs indefinitely at backend init, or comes up slowly but then
    # works for the whole session.  So: (1) a cheap bounded pre-flight
    # probe discovers a dead tunnel in minutes, not the whole budget;
    # (2) if the probe passes, TWO measurement attempts by default —
    # the persistent XLA compile cache (bccsp/tpu._enable_compile_cache,
    # shared via FABRIC_MOD_TPU_JIT_CACHE) makes the second attempt
    # skip the cold compile, so it is cheap.
    timeout_s = float(os.environ.get("FABRIC_MOD_TPU_BENCH_TIMEOUT", "600"))
    attempts = int(os.environ.get("FABRIC_MOD_TPU_BENCH_ATTEMPTS", "2"))
    probe_s = float(os.environ.get("FABRIC_MOD_TPU_BENCH_PROBE_TIMEOUT",
                                   "180"))
    base_env = dict(os.environ)
    # one shared persistent compile cache across probe/attempts
    base_env.setdefault("FABRIC_MOD_TPU_JIT_CACHE",
                        os.path.expanduser("~/.cache/fabric_mod_tpu/jit"))

    note = "no TPU attempts configured"
    pnote = None
    if not args.cpu:
        platform, pnote = _preflight_probe(base_env, probe_s)
        log(f"[bench] pre-flight: {pnote}")
        if platform is None:
            attempts = 0
            note = pnote
        for attempt in range(1, attempts + 1):
            log(f"[bench] device attempt {attempt}/{attempts} "
                f"(timeout {timeout_s:.0f}s)")
            result, note = _spawn_worker(argv, base_env, timeout_s)
            log(f"[bench] device attempt {attempt}: {note}")
            if result is not None:
                result["preflight"] = pnote
                print(json.dumps(result))
                return 0
            if attempt < attempts:
                backoff = 15 * attempt
                log(f"[bench] backing off {backoff}s before retry")
                time.sleep(backoff)
        diagnosis = ("TPU backend init failed or hung "
                     f"(pre-flight: {pnote}; attempts: {attempts}); "
                     "falling back to CPU backend. Last failure: " + note)
        log(f"[bench] {diagnosis}")
    else:
        diagnosis = "forced --cpu"

    cpu_env = dict(base_env)
    cpu_env["JAX_PLATFORMS"] = "cpu"
    if args.cpu:
        # explicit --cpu: honor the user's batch/reps exactly
        cpu_argv = argv
    else:
        # emergency fallback after TPU attempts burned the budget:
        # bound the work (smaller batch, single rep) — the
        # vs_baseline ratio stays honest, the wall-clock stays small
        cpu_argv = ["--batch", str(min(args.batch, 512)), "--reps", "1",
                    "--metric", args.metric]
        if getattr(args, "trace_out", None):
            cpu_argv += ["--trace-out", args.trace_out]
        if args.tensor_policy is not None:
            cpu_argv += ["--tensor-policy", str(args.tensor_policy)]
        if args.metric == "commitpipe":
            # keep the pipeline shape; drop to the sw backend so the
            # fallback doesn't pay a multi-minute CPU XLA compile
            cpu_argv += ["--pipeline-depth", str(args.pipeline_depth),
                         "--commitpipe-verifier", "sw"]
        if args.metric == "policyeval":
            cpu_argv += ["--policyeval-verifier", "sw"]
        if args.metric == "multichannel":
            # keep the sweep shape; sw slices so the fallback doesn't
            # pay per-slice multi-minute CPU XLA compiles
            cpu_argv += ["--slices", str(args.slices),
                         "--channels", str(args.channels),
                         "--multichannel-verifier", "sw"]
            if args.peers is not None:
                cpu_argv += ["--peers", str(args.peers)]
        if args.metric in ("gossip", "dissemination") \
                and args.peers is not None:
            cpu_argv += ["--peers", str(args.peers)]
        if args.metric == "broadcaststorm":
            if args.clients is not None:
                cpu_argv += ["--clients", str(args.clients)]
            if args.staged_batch is not None:
                cpu_argv += ["--staged-batch", str(args.staged_batch)]
            # sw on the emergency fallback: the device arms would pay
            # multi-minute CPU XLA compiles out of a burned budget
            cpu_argv += ["--storm-verifier", "sw"]
        if args.metric == "soak":
            # replayability: the fallback must run the SAME schedule
            if args.soak_seed is not None:
                cpu_argv += ["--soak-seed", str(args.soak_seed)]
            if args.soak_events is not None:
                cpu_argv += ["--soak-events", str(args.soak_events)]
            if args.soak_kinds is not None:
                cpu_argv += ["--soak-kinds", args.soak_kinds]
        if args.metric == "deliverfanout":
            cpu_argv += ["--subscribers", str(args.subscribers)]
        if args.metric == "statescale":
            cpu_argv += ["--state-keys", args.state_keys]
            if args.state_durable:
                cpu_argv += ["--state-durable"]
    result, note = _spawn_worker(cpu_argv, cpu_env, timeout_s)
    log(f"[bench] cpu fallback: {note}")
    if result is not None:
        result["platform"] = "cpu"
        if pnote is not None:
            result["preflight"] = pnote
        if not args.cpu:
            result["note"] = diagnosis
        print(json.dumps(result))
        return 0
    # Even the CPU run failed — emit a parseable failure record.
    print(json.dumps({
        "metric": args.metric, "value": 0.0, "unit": "FAILED",
        "vs_baseline": 0.0, "preflight": pnote,
        "error": f"{diagnosis}; cpu fallback: {note}",
    }))
    return 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--metric", action="append",
                    choices=("verify", "block", "e2e", "idemix", "gossip",
                             "marshal", "diffverify", "hashverify",
                             "commitpipe", "broadcaststorm", "soak",
                             "policyeval", "multichannel",
                             "deliverfanout", "statescale",
                             "dissemination"),
                    default=None,
                    help="repeatable: each metric runs in sequence and "
                         "prints its own JSON line (the smoke target "
                         "passes --metric diffverify --metric hashverify)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    # pipelined-front-end A/B knobs (see run_worker)
    ap.add_argument("--mixed-add", type=int, choices=(0, 1), default=None,
                    help="1: affine-table mixed-addition ladder "
                         "(FABRIC_MOD_TPU_MIXED_ADD)")
    ap.add_argument("--memo-cache", type=int, default=None,
                    help="verdict memo-cache capacity, 0 disables "
                         "(FABRIC_MOD_TPU_VERDICT_CACHE)")
    ap.add_argument("--inflight", type=int, default=None,
                    help="in-flight dispatch window depth "
                         "(FABRIC_MOD_TPU_INFLIGHT)")
    ap.add_argument("--precision", choices=("highest", "high"),
                    default=None,
                    help="limb matmul precision — bench-scoped A/B only")
    ap.add_argument("--pipeline-depth", type=int, default=4,
                    help="commitpipe: staged-but-uncommitted block "
                         "bound (1 = the synchronous path)")
    ap.add_argument("--commitpipe-verifier", choices=("device", "sw"),
                    default="device",
                    help="commitpipe: signature backend for BOTH arms "
                         "(sw = no XLA compile; the CPU smoke target)")
    ap.add_argument("--policyeval-verifier", choices=("device", "sw"),
                    default="device",
                    help="policyeval: signature backend for BOTH arms "
                         "(sw = no XLA compile; the CPU smoke target)")
    ap.add_argument("--tensor-policy", type=int, choices=(0, 1),
                    default=None,
                    help="1: arm FABRIC_MOD_TPU_TENSOR_POLICY for the "
                         "worker (commitpipe then adds the tensor-vs-"
                         "closure differential arm); 0: force the "
                         "closure path")
    ap.add_argument("--peers", type=int, default=None,
                    help="gossip: storm peer count (default 50; the "
                         "metric name carries it); multichannel: the "
                         "top of the rider-peer axis (default 16)")
    ap.add_argument("--clients", type=int, default=None,
                    help="broadcaststorm: client thread count "
                         "(default 8; the metric name carries it)")
    ap.add_argument("--staged-batch", type=int, default=None,
                    help="broadcaststorm: staged-arm coalescing depth "
                         "(FABRIC_MOD_TPU_STAGED_BROADCAST; default "
                         "64, 0 skips the staged arm)")
    ap.add_argument("--storm-verifier", choices=("sw", "device"),
                    default="sw",
                    help="broadcaststorm: Writers batch verifier the "
                         "arms dispatch through — sw (host ECDSA, "
                         "flat per-item cost) or device (ops/p256 "
                         "batch verify: real batch economics, buckets "
                         "pre-warmed outside the timed windows)")
    ap.add_argument("--slices", type=int, default=4,
                    help="multichannel: top of the mesh-slice axis "
                         "(the sweep runs 1, slices/2, slices)")
    ap.add_argument("--channels", type=int, default=4,
                    help="multichannel: top of the channel axis")
    ap.add_argument("--multichannel-verifier", choices=("device", "sw"),
                    default="device",
                    help="multichannel: signature backend (sw = no "
                         "XLA compile; the CPU smoke target)")
    ap.add_argument("--soak-seed", type=int, default=None,
                    help="soak: churn schedule seed (default "
                         "FMT_SOAK_SEED or 8) — a failed run prints "
                         "the seed to replay it here")
    ap.add_argument("--soak-events", type=int, default=None,
                    help="soak: churn events per run (default "
                         "FMT_SOAK_EVENTS or 6)")
    ap.add_argument("--soak-kinds", default=None,
                    help="soak: comma list restricting the churn-kind "
                         "pool (e.g. peer_crash_rejoin,orderer_restart)"
                         " — default is the full 9-kind catalog")
    ap.add_argument("--subscribers", type=int, default=10000,
                    help="deliverfanout: top of the subscriber-count "
                         "sweep (>=3 points up to this)")
    ap.add_argument("--state-keys", default="10000,100000,1000000",
                    help="statescale: comma list of prefilled statedb "
                         "sizes to sweep (>=3; the stream only "
                         "touches the smallest, so flags are "
                         "comparable across points)")
    ap.add_argument("--state-durable", action="store_true",
                    help="statescale: run the sweep on DurableStateDB "
                         "(batched one-buffered-write-per-block log) "
                         "instead of the in-memory statedb")
    ap.add_argument("--trace-out", default=None,
                    help="run FMT_TRACE-armed and export the span "
                         "ring as Chrome trace-event JSON "
                         "(Perfetto-loadable) to this path")
    ap.add_argument("--_worker", action="store_true",
                    help=argparse.SUPPRESS)
    args, _ = ap.parse_known_args()
    metrics = args.metric or ["verify"]

    if args._worker:
        args.metric = metrics[0]       # one metric per worker child
        return run_worker(args)

    rc = 0
    for metric in metrics:
        args.metric = metric
        argv = ["--batch", str(args.batch), "--reps", str(args.reps),
                "--metric", metric]
        if args.mixed_add is not None:
            argv += ["--mixed-add", str(args.mixed_add)]
        if args.memo_cache is not None:
            argv += ["--memo-cache", str(args.memo_cache)]
        if args.inflight is not None:
            argv += ["--inflight", str(args.inflight)]
        if args.precision is not None:
            argv += ["--precision", args.precision]
        if args.trace_out is not None:
            argv += ["--trace-out", args.trace_out]
        if args.tensor_policy is not None:
            argv += ["--tensor-policy", str(args.tensor_policy)]
        if metric == "commitpipe":
            argv += ["--pipeline-depth", str(args.pipeline_depth),
                     "--commitpipe-verifier", args.commitpipe_verifier]
        if metric == "policyeval":
            argv += ["--policyeval-verifier", args.policyeval_verifier]
        if args.peers is not None:
            argv += ["--peers", str(args.peers)]
        if metric == "broadcaststorm":
            if args.clients is not None:
                argv += ["--clients", str(args.clients)]
            if args.staged_batch is not None:
                argv += ["--staged-batch", str(args.staged_batch)]
            argv += ["--storm-verifier", args.storm_verifier]
        if metric == "multichannel":
            argv += ["--slices", str(args.slices),
                     "--channels", str(args.channels),
                     "--multichannel-verifier",
                     args.multichannel_verifier]
        if metric == "soak":
            if args.soak_seed is not None:
                argv += ["--soak-seed", str(args.soak_seed)]
            if args.soak_events is not None:
                argv += ["--soak-events", str(args.soak_events)]
            if args.soak_kinds is not None:
                argv += ["--soak-kinds", args.soak_kinds]
        if metric == "deliverfanout":
            argv += ["--subscribers", str(args.subscribers)]
        if metric == "statescale":
            argv += ["--state-keys", args.state_keys]
            if args.state_durable:
                argv += ["--state-durable"]
        rc |= supervise(args, argv)
    return rc


if __name__ == "__main__":
    sys.exit(main())
