"""Batched SHA-256 on device.

The TPU-native replacement for the reference's per-message hashing
(reference: bccsp/sw hash path, bccsp/bccsp.go Hash/GetHash and its
use in msp/identities.go:169-196 where every signature verify first
hashes the message): the batch axis carries the parallelism, one jitted
program hashes every message of a block at once.

Mixed lengths are handled without host-side bucketing: all messages
are padded to the batch's max block count and the compression state
simply freezes (via `where`) once a message's own blocks run out —
compute on the dead lanes is wasted, but the program stays shape-static
and branch-free, which is what XLA wants.  The jittable core
(`sha256_blocks`) is exposed separately so later pipelines can fuse
hash -> ECDSA-verify entirely on device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_H0 = np.array([
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19], np.uint32)

_K = np.array([
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
    0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
    0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
    0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
    0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
    0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
    0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
    0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
    0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
    0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
    0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2], np.uint32)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-256 compression: state (..., 8) x block (..., 16) uint32."""
    # Message schedule: rolling 16-word window scanned 48 times.
    w0 = jnp.moveaxis(block, -1, 0)                     # (16, ...)

    def sched(win, _):
        s0 = _rotr(win[1], 7) ^ _rotr(win[1], 18) ^ (win[1] >> np.uint32(3))
        s1 = _rotr(win[14], 17) ^ _rotr(win[14], 19) ^ (win[14] >> np.uint32(10))
        nxt = win[0] + s0 + win[9] + s1
        return jnp.concatenate([win[1:], nxt[None]], axis=0), win[0]

    win, w_head = jax.lax.scan(sched, w0, None, length=48)
    w_all = jnp.concatenate([w_head, win], axis=0)      # (64, ...)

    def round_(acc, xs):
        a, b, c, d, e, f, g, h = acc
        k, w = xs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k + w
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    init = tuple(state[..., i] for i in range(8))
    out, _ = jax.lax.scan(round_, init, (jnp.asarray(_K), w_all))
    return state + jnp.stack(out, axis=-1)


@jax.jit
def sha256_blocks(words: jnp.ndarray, nblocks: jnp.ndarray) -> jnp.ndarray:
    """Hash pre-padded messages.

    Args:
      words: (batch, max_blocks, 16) uint32 big-endian message words,
        padded per FIPS 180-4 within each message's own block count.
      nblocks: (batch,) int32 — number of real blocks per message.
    Returns:
      (batch, 8) uint32 digest words.
    """
    state0 = jnp.broadcast_to(jnp.asarray(_H0), words.shape[:-2] + (8,))
    blocks = jnp.moveaxis(words, -2, 0)                 # (max_blocks, batch, 16)

    def body(state, xs):
        i, block = xs
        new = _compress(state, block)
        live = (i < nblocks)[..., None]
        return jnp.where(live, new, state), None

    idx = jnp.arange(blocks.shape[0], dtype=jnp.int32)
    state, _ = jax.lax.scan(body, state0, (idx, blocks))
    return state


# --- Host-side padding / marshalling ---------------------------------------

def pad_messages(msgs) -> tuple[np.ndarray, np.ndarray]:
    """Pad a list of byte strings -> (words (N, B, 16) uint32, nblocks)."""
    nb = np.array([(len(m) + 8) // 64 + 1 for m in msgs], np.int32)
    maxb = int(nb.max()) if len(msgs) else 1
    buf = np.zeros((len(msgs), maxb * 64), np.uint8)
    for i, m in enumerate(msgs):
        L = len(m)
        buf[i, :L] = np.frombuffer(m, np.uint8)
        buf[i, L] = 0x80
        buf[i, nb[i] * 64 - 8:nb[i] * 64] = np.frombuffer(
            (L * 8).to_bytes(8, "big"), np.uint8)
    words = buf.reshape(len(msgs), maxb, 16, 4)
    words = (words[..., 0].astype(np.uint32) << 24
             | words[..., 1].astype(np.uint32) << 16
             | words[..., 2].astype(np.uint32) << 8
             | words[..., 3].astype(np.uint32))
    return words, nb


def digest_to_bytes(digest_words: np.ndarray) -> np.ndarray:
    """(..., 8) uint32 -> (..., 32) uint8 big-endian."""
    d = np.asarray(digest_words)
    out = np.empty(d.shape[:-1] + (32,), np.uint8)
    for i in range(4):
        out[..., i::4] = (d >> (24 - 8 * i)).astype(np.uint8)
    return out


def sha256_many(msgs) -> np.ndarray:
    """Hash a list of byte strings on device -> (N, 32) uint8 digests."""
    if not msgs:
        return np.zeros((0, 32), np.uint8)
    words, nb = pad_messages(msgs)
    # fmtlint: allow[jax-hot-path] -- sha256_many is the host-facing one-shot API; the fused commit path uses sha256_blocks directly inside verify_core_fused
    return digest_to_bytes(np.asarray(
        sha256_blocks(jnp.asarray(words), jnp.asarray(nb))))
