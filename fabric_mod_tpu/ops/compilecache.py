"""Persistent XLA compilation cache, shared by every jitted program.

The ECDSA ladder and the idemix pairing program each cost tens of
seconds (minutes, on CPU) to compile; pointing jax at a persistent
on-disk cache makes compiles survive process restarts.  bccsp/tpu.py
has always enabled this for the verify programs at import; the
pairing path (ops/fp256bn_dev.py) now does the same at ITS import —
"service start" for an idemix-verifying peer — so the second
`bench.py --metric idemix` run (and every production restart) reuses
the cached executable instead of re-paying the compile
(VERDICT r5 #8).

FABRIC_MOD_TPU_JIT_CACHE overrides the cache directory.
"""
from __future__ import annotations

import os

_enabled = False


def enable_compile_cache() -> None:
    """Idempotent; safe before or after jax initialization, and a
    silent no-op when jax is unavailable/misconfigured (the caller
    may be a wheel-less host-only deployment)."""
    global _enabled
    if _enabled:
        return
    try:
        import jax
        from fabric_mod_tpu.utils import knobs
        cache_dir = os.path.expanduser(
            knobs.get_str("FABRIC_MOD_TPU_JIT_CACHE"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _enabled = True
    except Exception:  # fmtlint: allow[swallowed-exceptions] -- wheel-less or read-only host: the persistent compile cache is best-effort by design
        pass
