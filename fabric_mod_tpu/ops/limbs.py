"""Vectorized 256-bit modular arithmetic for TPU.

Represents field elements as K=25 signed int32 limbs in radix B=2**11,
little-endian, with *lazy carries*: between operations limbs satisfy
|limb| < 2**12, so every schoolbook product column (up to K terms of
|a_i*b_j| < 2**24) stays below 2**29 — comfortably inside int32 — and
carry propagation is two fully-parallel local passes (no sequential scan
on the hot path). Signed limbs make subtraction a plain limb-wise
subtract with no borrow handling.

Modular multiplication is Montgomery in *separated* form with R = 2**275:

    T = a*b                       (schoolbook, 2K-1 columns)
    m = (T mod R) * N' mod R      (low-K schoolbook; N' = -p^-1 mod R)
    out = (T + m*p) / R           (exact; low K limbs telescope to zero)

Value-bound analysis (used throughout, do not change K/B casually):
inputs |v| < 2**262 give |T|/R < 2**249 and |m*p|/R < 2**257.3, so
outputs are < 2**258 — the chain is self-stabilizing. The only
sequential pieces are the exact carry over the low K limbs of T + m*p
(K steps) and final canonicalization.

All functions treat the last axis as limbs and broadcast over leading
batch axes, so no vmap is required; lax.scan bodies stay batched.

This layer is the TPU-native answer to the reference's software crypto
in bccsp/sw (reference: bccsp/sw/ecdsa.go:41-57 verify path) — there the
per-signature math is Go stdlib crypto/elliptic; here the batch axis is
the parallelism (SURVEY.md §2.9).
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

K = 25          # number of limbs
B = 11          # bits per limb
MASK = (1 << B) - 1
RBITS = K * B   # 275


# ---------------------------------------------------------------------------
# Host-side converters (numpy; vectorized over batch)
# ---------------------------------------------------------------------------

def int_to_limbs(x: int) -> np.ndarray:
    """Convert a non-negative python int (< 2**RBITS) to K limbs."""
    assert 0 <= x < (1 << RBITS)
    out = np.zeros(K, np.int32)
    for i in range(K):
        out[i] = x & MASK
        x >>= B
    return out


def limbs_to_int(a) -> int:
    """Exact value of a (possibly lazy, signed) limb array -> python int."""
    a = np.asarray(a)
    assert a.ndim == 1
    return sum(int(v) << (B * i) for i, v in enumerate(a.tolist()))


def be_bytes_to_limbs(buf: np.ndarray) -> np.ndarray:
    """(..., 32) uint8 big-endian byte strings -> (..., K) int32 limbs.

    Vectorized over the batch; used to marshal digests/coordinates/scalars
    onto the device.
    """
    buf = np.asarray(buf, np.uint8)
    assert buf.shape[-1] == 32
    # little-endian bit order over the whole 256-bit integer
    bits = np.unpackbits(buf[..., ::-1], axis=-1, bitorder="little")  # (...,256)
    pad = np.zeros(bits.shape[:-1] + (RBITS - 256,), np.uint8)
    bits = np.concatenate([bits, pad], axis=-1)
    bits = bits.reshape(bits.shape[:-1] + (K, B))
    weights = (1 << np.arange(B)).astype(np.int32)
    return (bits.astype(np.int32) * weights).sum(-1).astype(np.int32)


def limbs_to_be_bytes(a: np.ndarray) -> np.ndarray:
    """Canonical non-negative (..., K) limbs -> (..., 32) big-endian bytes."""
    a = np.asarray(a, np.int64)
    bits = ((a[..., :, None] >> np.arange(B)) & 1).astype(np.uint8)
    bits = bits.reshape(a.shape[:-1] + (RBITS,))[..., :256]
    by = np.packbits(bits, axis=-1, bitorder="little")  # (..., 32) LE
    return by[..., ::-1].copy()


# ---------------------------------------------------------------------------
# Field specification (per modulus): Montgomery constants
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FieldSpec:
    """Montgomery constants for one odd modulus.

    Stored as *numpy* arrays on purpose: the spec is lru_cached and may
    be first materialized inside a jit trace — caching jnp values there
    would cache tracers (leak).  numpy constants are trace-neutral and
    XLA lifts them into the compiled program at each use site.
    """
    name: str
    modulus: int                 # python int, for host-side math/tests
    p: np.ndarray                # (K,) canonical limbs of modulus
    nprime: np.ndarray           # (K,) canonical limbs of -p^-1 mod R
    r2: np.ndarray               # (K,) R^2 mod p   (to_mont multiplier)
    one: np.ndarray              # (K,) limbs of 1
    one_mont: np.ndarray         # (K,) R mod p     (Montgomery one)
    kp: np.ndarray               # (9, K) canonical limbs of [128p,64p,...,p, 0]
    mp128: np.ndarray            # (K,) canonical limbs of 128p (sign lift)
    p_mat: np.ndarray            # (K, 2K-1) banded matrix: x @ p_mat = full
    #                              schoolbook columns of x*p (constant operand)
    np_mat: np.ndarray           # (K, K) banded matrix: x @ np_mat = low K
    #                              columns of x*nprime (mod R)

    @staticmethod
    def _band_full(c: np.ndarray) -> np.ndarray:
        m = np.zeros((K, 2 * K - 1), np.int32)
        for i in range(K):
            m[i, i:i + K] = c
        return m

    @staticmethod
    def _band_low(c: np.ndarray) -> np.ndarray:
        m = np.zeros((K, K), np.int32)
        for i in range(K):
            m[i, i:K] = c[:K - i]
        return m

    @staticmethod
    @functools.lru_cache(maxsize=None)
    def make(name: str, modulus: int) -> "FieldSpec":
        R = 1 << RBITS
        nprime = (-pow(modulus, -1, R)) % R
        r2 = (R * R) % modulus
        kps = [int_to_limbs((128 >> i) * modulus) for i in range(8)]
        kps.append(np.zeros(K, np.int32))
        p_limbs = int_to_limbs(modulus)
        np_limbs = int_to_limbs(nprime)
        return FieldSpec(
            name=name,
            modulus=modulus,
            p=p_limbs,
            nprime=np_limbs,
            r2=int_to_limbs(r2),
            one=int_to_limbs(1),
            one_mont=int_to_limbs(R % modulus),
            kp=np.stack(kps),
            mp128=int_to_limbs(128 * modulus),
            p_mat=FieldSpec._band_full(p_limbs),
            np_mat=FieldSpec._band_low(np_limbs),
        )


# ---------------------------------------------------------------------------
# Core limb ops (device; batched over leading axes)
# ---------------------------------------------------------------------------

def carry2(x: jnp.ndarray) -> jnp.ndarray:
    """Two local carry passes; output limbs satisfy |limb| < 2**12.

    Valid for column values |v| < 2**30. The top limb is left unmasked so
    no carry is ever dropped (dropping a negative top carry would add R to
    the value); for |value| < 2**262 the masked passes keep |top limb|
    within a few units, preserving the lazy bound.
    """
    for _ in range(2):
        lo = jnp.bitwise_and(x, MASK)
        lo = lo.at[..., -1].set(x[..., -1])
        hi = jnp.right_shift(x, B)
        x = lo + jnp.pad(hi[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    return x


def _pad_last(x: jnp.ndarray, left: int, right: int) -> jnp.ndarray:
    cfg = [(0, 0, 0)] * (x.ndim - 1) + [(left, right, 0)]
    return jax.lax.pad(x, jnp.int32(0), cfg)


# Constant anti-diagonal gather: flattened outer-product index (i*K+j)
# -> column i+j.  One (K^2, 2K-1) int32 matmul replaces K shifted pads;
# XLA compiles it ~8x faster than the pad-and-sum form and it is a
# single fusable op on the TPU.
_COLSUM = np.zeros((K * K, 2 * K - 1), np.int32)
for _i in range(K):
    for _j in range(K):
        _COLSUM[_i * K + _j, _i + _j] = 1


def sb_mul_full(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product columns: (..., K) x (..., K) -> (..., 2K-1).

    Outer product + one constant matmul folding the anti-diagonals.
    Column bound: up to K terms of |a_i*b_j| < 2**24 stays < 2**29.
    """
    outer = a[..., :, None] * b[..., None, :]
    return jnp.matmul(outer.reshape(outer.shape[:-2] + (K * K,)),
                      _COLSUM)


def sb_sqr_full(a: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook square columns: (..., K) -> (..., 2K-1).

    Exploits symmetry (a_i*a_j == a_j*a_i): ~K(K+1)/2 multiplies instead
    of K^2, which nearly halves the cost of every squaring on the VPU.
    Column bound: diagonal |a_i^2| < 2**24 plus <=12 cross terms
    |2*a_i*a_j| < 2**25 keeps columns < 2**29 — inside carry2's domain.
    """
    shape = a.shape[:-1]
    # diagonal a_i^2 lands at column 2i: interleave with zeros
    sq = a * a
    diag = jnp.stack([sq, jnp.zeros_like(sq)], axis=-1)
    diag = diag.reshape(shape + (2 * K,))[..., :2 * K - 1]
    rows = [diag]
    for i in range(K - 1):
        cross = 2 * a[..., i:i + 1] * a[..., i + 1:]   # cols 2i+1..i+K-1
        rows.append(_pad_last(cross, 2 * i + 1, K - 1 - i))
    return jnp.sum(jnp.stack(rows, axis=0), axis=0)


def carry_mod_r(x: jnp.ndarray) -> jnp.ndarray:
    """carry2 over exactly K limbs, dropping carries past limb K-1 (mod R)."""
    for _ in range(2):
        lo = jnp.bitwise_and(x, MASK)
        hi = jnp.right_shift(x, B)
        x = lo + jnp.pad(hi[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    return x


def _exact_low_carry(s: jnp.ndarray) -> jnp.ndarray:
    """Exact carry out of the low K limbs of s (which are ≡ 0 mod R).

    fori_loop, not an unrolled python loop: the body compiles once,
    which matters in mont-mul-dense graphs (the pairing kernel)."""
    def body(i, c):
        return jnp.right_shift(
            jax.lax.dynamic_index_in_dim(s, i, axis=-1, keepdims=False)
            + c, B)
    return jax.lax.fori_loop(0, K, body,
                             jnp.zeros(s.shape[:-1], jnp.int32))


def _mont_reduce(t: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery reduction of carried product columns t -> t*R^-1 mod p.

    The two products with the CONSTANT operands nprime and p are plain
    banded matmuls (spec.np_mat / spec.p_mat) — linear in the constant,
    no outer product needed.  Bounds: t's low limbs are lazy
    (|limb| < 2**12) and the constants canonical (< 2**11), so columns
    stay < 25 * 2**23 < 2**28."""
    m = carry_mod_r(jnp.matmul(t[..., :K], spec.np_mat))
    s = t + jnp.matmul(m, spec.p_mat)                  # low K limbs ≡ 0 mod R
    c = _exact_low_carry(s)
    hi = s[..., K:]                                    # (..., K-1)
    hi = jnp.concatenate(
        [ (hi[..., :1] + c[..., None]),
          hi[..., 1:],
          jnp.zeros(hi.shape[:-1] + (1,), jnp.int32) ], axis=-1)  # (..., K)
    return carry2(hi)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod p (lazy signed limbs in, out)."""
    return _mont_reduce(carry2(sb_mul_full(a, b)), spec)


def mont_sqr(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery square via the symmetric schoolbook (~half the MACs)."""
    return _mont_reduce(carry2(sb_sqr_full(a)), spec)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry2(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry2(a - b)


def to_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, spec.r2, spec)


def from_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, spec.one, spec)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small non-negative python int (k < 2**16)."""
    return carry2(a * jnp.int32(k))


def _full_carry_nonneg(x: jnp.ndarray) -> jnp.ndarray:
    """Full sequential carry; input value must be non-negative and < R."""
    c = jnp.zeros(x.shape[:-1], jnp.int32)
    outs = []
    for i in range(K):
        t = x[..., i] + c
        outs.append(jnp.bitwise_and(t, MASK))
        c = jnp.right_shift(t, B)
    return jnp.stack(outs, axis=-1)


def _geq_sub(v: jnp.ndarray, kp: jnp.ndarray) -> jnp.ndarray:
    """If canonical v >= canonical kp: v - kp (canonical), else v."""
    d = v - kp
    borrow = jnp.zeros(d.shape[:-1], jnp.int32)
    outs = []
    for i in range(K):
        t = d[..., i] + borrow
        outs.append(jnp.bitwise_and(t, MASK))
        borrow = jnp.right_shift(t, B)   # 0 or -1
    sub_ok = (borrow >= 0)[..., None]
    return jnp.where(sub_ok, jnp.stack(outs, axis=-1), v)


def canonical(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Reduce lazy signed limbs (|value| < 2**262) to canonical [0, p).

    Adds 128p to lift the value into [0, 2**264+), full-carries, then
    binary conditional subtraction of 128p..p.
    """
    v = _full_carry_nonneg(a + spec.mp128)
    for i in range(8):
        v = _geq_sub(v, spec.kp[i])
    return v


def eq_zero(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Is lazy signed value ≡ 0 (mod p)?  (..., K) -> (...) bool."""
    c = canonical(a, spec)
    return jnp.all(c == 0, axis=-1)


def eq_canonical(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Equality of two canonical limb arrays."""
    return jnp.all(a == b, axis=-1)


def pow_static(a_mont: jnp.ndarray, exponent: int, spec: FieldSpec) -> jnp.ndarray:
    """a^exponent in the Montgomery domain, static python-int exponent.

    Left-to-right square-and-multiply as a lax.scan over the (static) bit
    string, so the traced graph is one squaring + one multiply.
    """
    nbits = max(exponent.bit_length(), 1)
    bits = jnp.asarray(
        np.array([(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)],
                 np.bool_))
    acc0 = jnp.broadcast_to(spec.one_mont, a_mont.shape).astype(jnp.int32)

    def body(acc, bit):
        acc = mont_sqr(acc, spec)
        withmul = mont_mul(acc, a_mont, spec)
        acc = jnp.where(bit, withmul, acc)
        return acc, None

    acc, _ = jax.lax.scan(body, acc0, bits)
    return acc


def inv_mont(a_mont: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular inverse in Montgomery domain via Fermat (p must be prime)."""
    return pow_static(a_mont, spec.modulus - 2, spec)


def bits_le(canon: jnp.ndarray, nbits: int = 256) -> jnp.ndarray:
    """Canonical limbs -> (..., nbits) int32 bit array, LSB first."""
    limb_idx = np.arange(nbits) // B
    bit_idx = np.arange(nbits) % B
    limbs = canon[..., limb_idx]
    return jnp.right_shift(limbs, jnp.asarray(bit_idx, jnp.int32)) & 1
