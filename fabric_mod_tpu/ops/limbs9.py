"""f32 radix-2^9 modular arithmetic for TPU — the MXU limb layer.

Second-generation limb layer (first: ops/limbs.py, int32 radix-2^11).
Two structural changes move the hot work from the VPU's weakest paths
onto the MXU and fully-occupied vector lanes:

1. **f32 limbs, radix B=2^9, K=30.**  All products and column sums stay
   exact in the 24-bit f32 mantissa (bounds below), so the schoolbook
   column fold and both Montgomery constant-operand products become
   *float matmuls* — which XLA puts on the MXU systolic array.  The
   int32 matmuls of the previous layer had no MXU lowering and ran as
   vector-unit emulation.
2. **Limb axis FIRST.**  Arrays are (K, ...batch): the minor-most axis
   is the batch, so every element-wise op (carries, adds) fills all 128
   vector lanes.  The previous (batch, K=25) layout wasted 80% of every
   vreg on lane padding, and limb shifts were lane-relayouts; here a
   limb shift is a whole-register sublane move.

Value-bound analysis (do not change K/B casually):

* ``carried`` uses *rounded* carries: hi = floor(x/B + 1/2), so limbs
  land in [-B/2, B/2] = [-256, 256]; the second pass adds a carry-in
  of at most ~17, giving the working invariant |limb| <= 273.
* products |a_i*b_j| <= 273^2 < 2^16.2; a column sums <= K such terms
  plus the slightly larger top-limb terms: < 2^21.3 — exact in f32.
* Montgomery with R = 2^270 (K*B = 270): for inputs |v| < 2^260,
  |T|/R < 2^251 and |m*p|/R < 2^256.2, so outputs are < 2^256.3 —
  the chain is self-stabilizing with ~10 bits of headroom for the
  add/sub chains between multiplies (point formulas sum at most a few
  terms, staying far below 2^260).
* canonicalization lifts by 32p (> any |v| above) and still fits the
  30-limb capacity 2^270 — the extra headroom relative to the old
  R = 2^275 design is why K is 30 and not 29.

Matmul exactness: operands are integer-valued f32 well inside the
mantissa, and accumulation happens in f32 on values bounded < 2^22, so
a full-precision float32 dot is exact.  ``PRECISION`` pins
jax.lax.Precision.HIGHEST (6-pass bf16 emulation on TPU — exact for
f32 operands); see test_limbs9.py for the differential that guards it.

Replaces the software per-signature math of the reference
(bccsp/sw/ecdsa.go:41-57) with a batch axis (SURVEY.md §2.9): the
batch is the trailing axes, no vmap needed anywhere.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

K = 30            # number of limbs
B = 9             # bits per limb
BASE = 1 << B     # 512
MASK = BASE - 1
RBITS = K * B     # 270
HALF = BASE // 2  # rounding offset

# Exact f32 dot emulation on TPU (6-pass bf16). The operands here are
# integers < 2^17 and sums < 2^22, so HIGHEST is bit-exact.
#
# The cheaper 3-pass emulation (Precision.HIGH) is exact ONLY for the
# 0/1 fold matrices — it exists for an on-chip A/B, and it can make
# verify verdicts silently WRONG if it leaks into production.  The
# knob is therefore scoped to the bench entrypoint: bench.py calls
# `set_precision_mode("high")` in its measurement worker; nothing else
# may.  (ADVICE r5: the old FABRIC_MOD_TPU_PRECISION env var switched
# every deployment that inherited it, with no runtime guard.)
import sys as _sys

PRECISION = jax.lax.Precision.HIGHEST


def set_precision_mode(mode: str) -> str:
    """Select the limb matmul precision ("highest" | "high").

    BENCH-ONLY.  Returns the previous mode.  Must be called before the
    first verify/pairing trace in the process — jitted programs bake
    the precision at trace time and are NOT retraced.  Selecting
    "high" emits a prominent warning: verdicts are only trustworthy
    after the differential suite passes at that precision.
    """
    global PRECISION
    prev = "high" if PRECISION == jax.lax.Precision.HIGH else "highest"
    mode = (mode or "highest").lower()
    if mode not in ("high", "highest"):
        raise ValueError(f"unknown precision mode {mode!r}")
    PRECISION = (jax.lax.Precision.HIGH if mode == "high"
                 else jax.lax.Precision.HIGHEST)
    if mode == "high":
        print("=" * 70 + "\nWARNING: fabric_mod_tpu limb matmuls set to "
              "Precision.HIGH (3-pass bf16\nemulation).  This is exact "
              "ONLY for the 0/1 fold matrices; signature and\npairing "
              "verdicts are NOT guaranteed until the differential suite "
              "passes\nat this precision.  Bench A/B use only — never "
              "production.\n" + "=" * 70, file=_sys.stderr, flush=True)
    return prev


from fabric_mod_tpu.utils import knobs as _knobs

if _knobs.get_str("FABRIC_MOD_TPU_PRECISION").lower() == "high":
    # The env var is no longer honored here (it used to silently change
    # verify semantics in any process that inherited it).  The bench
    # worker translates it via set_precision_mode; everyone else gets
    # default precision and this notice.
    print("fabric_mod_tpu: ignoring FABRIC_MOD_TPU_PRECISION=high outside "
          "the bench entrypoint (see ops/limbs9.set_precision_mode)",
          file=_sys.stderr, flush=True)

_F = jnp.float32


# ---------------------------------------------------------------------------
# Host-side converters (numpy; trailing limb axis for numpy-friendliness —
# device code moves limbs to axis 0 via `to_device` below)
# ---------------------------------------------------------------------------

def int_to_limbs(x: int) -> np.ndarray:
    """Non-negative python int (< 2**RBITS) -> (K,) float32 limbs."""
    assert 0 <= x < (1 << RBITS)
    out = np.zeros(K, np.float32)
    for i in range(K):
        out[i] = x & MASK
        x >>= B
    return out


def limbs_to_int(a) -> int:
    """Exact value of a (possibly lazy, signed) limb vector -> int.

    Accepts the device's (K,) arrays (f32 or int32)."""
    a = np.asarray(a)
    assert a.ndim == 1 and a.shape[0] == K
    return sum(int(v) << (B * i) for i, v in enumerate(a.tolist()))


def be_bytes_to_limbs(buf: np.ndarray) -> np.ndarray:
    """(..., 32) uint8 big-endian -> (..., K) int32 limbs (host-side)."""
    buf = np.asarray(buf, np.uint8)
    assert buf.shape[-1] == 32
    bits = np.unpackbits(buf[..., ::-1], axis=-1, bitorder="little")
    pad = np.zeros(bits.shape[:-1] + (RBITS - 256,), np.uint8)
    bits = np.concatenate([bits, pad], axis=-1)
    bits = bits.reshape(bits.shape[:-1] + (K, B))
    weights = (1 << np.arange(B)).astype(np.int32)
    return (bits.astype(np.int32) * weights).sum(-1).astype(np.int32)


def to_device(host_limbs: np.ndarray) -> jnp.ndarray:
    """(..., K) host limbs -> (K, ...) f32 device layout."""
    return jnp.asarray(np.moveaxis(np.asarray(host_limbs), -1, 0), _F)


# ---------------------------------------------------------------------------
# Field specification (per modulus)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FieldSpec:
    """Montgomery constants for one odd modulus (R = 2^270).

    numpy on purpose: the spec may first materialize inside a jit trace
    and numpy constants are trace-neutral (jnp values there would cache
    tracers)."""
    name: str
    modulus: int
    p: np.ndarray          # (K,) f32 canonical limbs of p
    one: np.ndarray        # (K,) f32 limbs of 1
    one_mont: np.ndarray   # (K,) f32 R mod p
    r2: np.ndarray         # (K,) f32 R^2 mod p
    np_mat: np.ndarray     # (K, K) f32: m = np_mat @ t_low  (x*N' mod R)
    p_mat: np.ndarray      # (2K-1, K) f32: full columns of m*p
    kp32: np.ndarray       # (6, K) int32 canonical limbs of 32p..p
    lift32: np.ndarray     # (K,) int32 canonical limbs of 32p

    @staticmethod
    @functools.lru_cache(maxsize=None)
    def make(name: str, modulus: int) -> "FieldSpec":
        R = 1 << RBITS
        nprime = (-pow(modulus, -1, R)) % R
        p_l = int_to_limbs(modulus)
        np_l = int_to_limbs(nprime)
        np_mat = np.zeros((K, K), np.float32)      # m_c = sum_j np_{c-j} t_j
        p_mat = np.zeros((2 * K - 1, K), np.float32)  # out_c = sum_j p_{c-j} m_j
        for c in range(K):
            for j in range(c + 1):
                np_mat[c, j] = np_l[c - j]
        for c in range(2 * K - 1):
            for j in range(K):
                if 0 <= c - j < K:
                    p_mat[c, j] = p_l[c - j]
        kps = [int_to_limbs((32 >> i) * modulus).astype(np.int32)
               for i in range(6)]
        return FieldSpec(
            name=name, modulus=modulus, p=p_l,
            one=int_to_limbs(1),
            one_mont=int_to_limbs(R % modulus),
            r2=int_to_limbs((R * R) % modulus),
            np_mat=np_mat, p_mat=p_mat,
            kp32=np.stack(kps), lift32=kps[0],
        )


# ---------------------------------------------------------------------------
# Carries (f32 arithmetic; no bitwise ops exist for floats)
# ---------------------------------------------------------------------------

def _split(x: jnp.ndarray):
    """Rounded carry split: x = hi*BASE + lo with lo in [-HALF, HALF]."""
    hi = jnp.floor(x * (1.0 / BASE) + 0.5)
    return hi, x - hi * BASE


def _shift_up(hi: jnp.ndarray) -> jnp.ndarray:
    """Move carry rows up one limb along axis 0 (drop the top row)."""
    pad = [(1, 0, 0)] + [(0, 0, 0)] * (hi.ndim - 1)
    return jax.lax.pad(hi[:-1], jnp.float32(0), pad)


def carried(x: jnp.ndarray) -> jnp.ndarray:
    """Two rounded carry passes preserving the exact value.

    The TOP limb is never split (splitting would drop value); for the
    operation-driven value bounds in the module docstring it stays
    small.  Output invariant: |limb| <= 273 for all but the top limb,
    top limb <= value/2^(B*(L-1)) + 273."""
    for _ in range(2):
        hi, lo = _split(x)
        hi = hi.at[-1].set(0.0)
        lo = lo.at[-1].set(x[-1])
        x = lo + _shift_up(hi)
    return x


def carry_mod_r(x: jnp.ndarray) -> jnp.ndarray:
    """Two rounded passes over exactly K limbs, dropping overflow (mod R)."""
    for _ in range(2):
        hi, lo = _split(x)
        x = lo + _shift_up(hi)
    return x


# ---------------------------------------------------------------------------
# Schoolbook + Montgomery (the MXU path)
# ---------------------------------------------------------------------------

# Trace-time constant source override: Pallas kernels may not capture
# array constants, so while a kernel body is being traced this hook
# maps the module's numpy constant singletons (by IDENTITY) to values
# read from kernel input refs.  THREAD-LOCAL: a concurrent trace of
# the ordinary XLA path on another thread must never observe a Pallas
# kernel's in-flight hook (leaked tracers otherwise).
import threading as _threading

_TRACE_TLS = _threading.local()


def set_const_lookup(fn) -> None:
    """Install/clear (None) this thread's constant-source hook."""
    _TRACE_TLS.const_lookup = fn


def get_const_lookup():
    return getattr(_TRACE_TLS, "const_lookup", None)


def const_jnp(arr: np.ndarray) -> jnp.ndarray:
    hook = get_const_lookup()
    if hook is not None:
        got = hook(arr)
        if got is not None:
            return got
    return jnp.asarray(arr)


def const_dot(mat: np.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """(rows, cols) constant  @  (cols, ...batch) -> (rows, ...batch).

    ALWAYS use this (never a bare jnp.matmul/tensordot) for any product
    involving limb values: it pins PRECISION so the TPU does not round
    f32 operands to bf16 (integers > 256 are not bf16-exact)."""
    return jnp.tensordot(const_jnp(mat), x, axes=(1, 0),
                         precision=PRECISION)


# Anti-diagonal fold: flattened outer index (i*K+j) -> column i+j.
_COLSUM = np.zeros((2 * K - 1, K * K), np.float32)
for _i in range(K):
    for _j in range(K):
        _COLSUM[_i + _j, _i * K + _j] = 1.0

# Symmetric fold for squaring: upper-triangle products (i <= j), laid
# out as K concatenated slices [a_i*a_i, a_i*a_{i+1}, ..., a_i*a_{K-1}];
# cross terms carry weight 2.  K(K+1)/2 = 465 multiplies instead of 900.
_COLSUM_SQR = np.zeros((2 * K - 1, K * (K + 1) // 2), np.float32)
_idx = 0
for _i in range(K):
    for _j in range(_i, K):
        _COLSUM_SQR[_i + _j, _idx] = 1.0 if _i == _j else 2.0
        _idx += 1


def sb_mul_cols(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product columns: (K, ...) x (K, ...) -> (2K-1, ...).

    The outer product is element-wise VPU work (broadcast along leading
    axes — no lane shuffles); the anti-diagonal fold is ONE constant
    (2K-1, K^2) matmul on the MXU.  Column sums < 2^21.3 (docstring
    bounds) — exact in f32."""
    outer = a[:, None] * b[None, :]                      # (K, K, ...)
    return const_dot(_COLSUM, outer.reshape((K * K,) + outer.shape[2:]))


# When True (per-thread), the sequential low-carry unrolls to
# straight-line code with STATIC row indices — required inside Pallas
# kernels (Mosaic's dynamic sublane indexing is the risk) and a
# compile-time/runtime trade elsewhere.
def set_unroll_low_carry(flag: bool) -> None:
    _TRACE_TLS.unroll_low_carry = flag


# env default lets bench variants A/B this without code changes
_UNROLL_DEFAULT = _knobs.get_bool("FABRIC_MOD_TPU_UNROLL_LOW_CARRY")


def get_unroll_low_carry() -> bool:
    return getattr(_TRACE_TLS, "unroll_low_carry", _UNROLL_DEFAULT)


def _exact_low_carry(s: jnp.ndarray) -> jnp.ndarray:
    """Exact carry out of the low K limbs of s (value ≡ 0 mod R).

    Sequential by nature; fori_loop so the body compiles once (or
    unrolled under set_unroll_low_carry, see above)."""
    if get_unroll_low_carry():
        c = jnp.zeros(s.shape[1:], _F)
        for i in range(K):
            c = jnp.floor((s[i] + c) * (1.0 / BASE))
        return c

    def body(i, c):
        row = jax.lax.dynamic_index_in_dim(s, i, axis=0, keepdims=False)
        return jnp.floor((row + c) * (1.0 / BASE))
    return jax.lax.fori_loop(0, K, body,
                             jnp.zeros(s.shape[1:], _F))


def _mont_reduce(t: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery reduction of carried columns t -> t*R^-1 mod p.

    Both constant-operand products (x*N' mod R, m*p) are MXU matmuls."""
    m = carry_mod_r(const_dot(spec.np_mat, t[:K]))
    s = t + const_dot(spec.p_mat, m)             # low K limbs ≡ 0 mod R
    c = _exact_low_carry(s)
    hi = s[K:]                              # (K-1, ...)
    hi = jnp.concatenate(
        [hi[:1] + c[None], hi[1:],
         jnp.zeros((1,) + hi.shape[1:], _F)], axis=0)   # (K, ...)
    return carried(hi)


def _align2(a: jnp.ndarray, b: jnp.ndarray):
    """Rank-align two leading-limb-axis operands: a bare (K,) constant
    against a (K, batch...) value reshapes to (K, 1, ...) — numpy's
    trailing-axis broadcasting would otherwise reject (or worse,
    misalign) the pair.  No-op when ranks agree."""
    an = getattr(a, "ndim", 0)
    bn = getattr(b, "ndim", 0)
    if an < bn:
        a = jnp.reshape(a, a.shape + (1,) * (bn - an))
    elif bn < an:
        b = jnp.reshape(b, b.shape + (1,) * (an - bn))
    return a, b


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery product a*b*R^-1 mod p (lazy limbs in and out)."""
    a, b = _align2(a, b)
    return _mont_reduce(carried(sb_mul_cols(a, b)), spec)


def sb_sqr_cols(a: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook square columns via the upper triangle: (K, ...) ->
    (2K-1, ...).  465 multiplies instead of 900 (a_i*a_j == a_j*a_i);
    the doubling of cross terms lives in the constant fold matrix, so
    column bounds only double for cross terms: < 2*K*273^2 < 2^22.2 —
    still exact in f32."""
    tri = jnp.concatenate([a[i:i + 1] * a[i:] for i in range(K)], axis=0)
    return const_dot(_COLSUM_SQR, tri)


def mont_sqr(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Montgomery square via the symmetric schoolbook (~half the MACs)."""
    return _mont_reduce(carried(sb_sqr_cols(a)), spec)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a, b = _align2(a, b)
    return carried(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a, b = _align2(a, b)
    return carried(a - b)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small non-negative python int (k < 2**6)."""
    return carried(a * jnp.float32(k))


def const_like(c: np.ndarray, a: jnp.ndarray) -> jnp.ndarray:
    """(K,) constant -> (K, 1, ..., 1) matching a's rank.

    With the limb axis FIRST, numpy-style trailing-axis broadcasting
    would mis-align a bare (K,) against (K, batch...) — every constant
    must be lifted explicitly."""
    return const_jnp(c).reshape((K,) + (1,) * (a.ndim - 1))


def to_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, const_like(spec.r2, a), spec)


def from_mont(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    return mont_mul(a, const_like(spec.one, a), spec)


# ---------------------------------------------------------------------------
# Canonicalization & comparisons (int32 tail — low volume, exact bit ops)
# ---------------------------------------------------------------------------

def _full_carry_nonneg_i32(x: jnp.ndarray) -> jnp.ndarray:
    """Full sequential masked carry; value must be in [0, R)."""
    c = jnp.zeros(x.shape[1:], jnp.int32)
    outs = []
    for i in range(K):
        t = x[i] + c
        outs.append(jnp.bitwise_and(t, MASK))
        c = jnp.right_shift(t, B)
    return jnp.stack(outs, axis=0)


def _geq_sub_i32(v: jnp.ndarray, kp: jnp.ndarray) -> jnp.ndarray:
    """If canonical v >= canonical kp: v - kp, else v."""
    d = v - kp.reshape((K,) + (1,) * (v.ndim - 1))
    borrow = jnp.zeros(d.shape[1:], jnp.int32)
    outs = []
    for i in range(K):
        t = d[i] + borrow
        outs.append(jnp.bitwise_and(t, MASK))
        borrow = jnp.right_shift(t, B)      # 0 or -1
    ok = (borrow >= 0)[None]
    return jnp.where(ok, jnp.stack(outs, axis=0), v)


def canonical(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Lazy f32 limbs (|value| < 2^260) -> canonical int32 limbs in [0, p).

    Lifts by 32p (sign removal), carries sequentially in int32 (limbs
    are small ints — the cast is exact), then six conditional
    subtractions of 32p..p."""
    x = a.astype(jnp.int32) + jnp.asarray(spec.lift32).reshape(
        (K,) + (1,) * (a.ndim - 1))
    v = _full_carry_nonneg_i32(x)
    for i in range(6):
        v = _geq_sub_i32(v, jnp.asarray(spec.kp32[i]))
    return v


def eq_zero(a: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Is lazy value ≡ 0 (mod p)?  (K, ...) -> (...) bool."""
    return jnp.all(canonical(a, spec) == 0, axis=0)


def eq_canonical(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=0)


def bits_le(canon_i32: jnp.ndarray, nbits: int = 256) -> jnp.ndarray:
    """Canonical int32 limbs (K, ...) -> (nbits, ...) bits, LSB first."""
    limb_idx = np.arange(nbits) // B
    bit_idx = np.arange(nbits) % B
    rows = canon_i32[limb_idx]                       # (nbits, ...)
    shifts = jnp.asarray(bit_idx, jnp.int32).reshape(
        (nbits,) + (1,) * (canon_i32.ndim - 1))
    return jnp.right_shift(rows, shifts) & 1


# ---------------------------------------------------------------------------
# Exponentiation
# ---------------------------------------------------------------------------

def pow_static(a_mont: jnp.ndarray, exponent: int, spec: FieldSpec) -> jnp.ndarray:
    """a^exponent in the Montgomery domain, static python-int exponent."""
    nbits = max(exponent.bit_length(), 1)
    bits = jnp.asarray(
        np.array([(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)],
                 np.bool_))
    acc0 = jnp.broadcast_to(
        jnp.asarray(spec.one_mont).reshape((K,) + (1,) * (a_mont.ndim - 1)),
        a_mont.shape).astype(_F)

    def body(acc, bit):
        acc = mont_sqr(acc, spec)
        withmul = mont_mul(acc, a_mont, spec)
        return jnp.where(bit, withmul, acc), None

    acc, _ = jax.lax.scan(body, acc0, bits)
    return acc


def inv_mont(a_mont: jnp.ndarray, spec: FieldSpec) -> jnp.ndarray:
    """Modular inverse in the Montgomery domain (Fermat; p prime)."""
    return pow_static(a_mont, spec.modulus - 2, spec)


def inv_mont_many(vals, spec: FieldSpec, inv=None) -> list:
    """Montgomery's simultaneous-inversion trick: invert m Montgomery-
    domain values with ONE Fermat inversion plus 3(m-1) multiplies.

    `vals` is a python list of (K, ...batch) arrays (a static table,
    e.g. the per-lane Q window table's Z coordinates); returns their
    inverses in order.  All products/inverses are element-wise along
    the batch axes, so lanes never mix.  A zero value poisons every
    inverse OF ITS LANE (0^(p-2) = 0 propagates through the prefix
    products) — callers rely on such lanes being masked out anyway
    (an on-curve point of a prime-order curve never has Z = 0 in the
    window table; only invalid keys do, and key_ok masks those).

    `inv` overrides the single Fermat inversion (default `inv_mont`,
    the generic square-and-multiply scan).  Pallas kernels pass a
    scan-free addition chain (ops/p256.inv_mont_p_chain): a lax.scan
    over a captured (256,) constant bit array is exactly the kind of
    trace Mosaic rejects.
    """
    inv = inv or inv_mont
    m = len(vals)
    if m == 0:
        return []
    if m == 1:
        return [inv(vals[0], spec)]
    prefix = [vals[0]]
    for v in vals[1:]:
        prefix.append(mont_mul(prefix[-1], v, spec))
    running = inv(prefix[-1], spec)          # (v_0 * ... * v_{m-1})^-1
    out = [None] * m
    for i in range(m - 1, 0, -1):
        out[i] = mont_mul(running, prefix[i - 1], spec)
        running = mont_mul(running, vals[i], spec)
    out[0] = running
    return out
