"""Batched ECDSA-P256 verification on TPU.

The TPU-native replacement for the reference's per-signature software
verify (reference: bccsp/sw/ecdsa.go:41-57 ``verifyECDSA`` and the
dispatch in bccsp/sw/impl.go:247): instead of one goroutine per
signature behind a semaphore (core/committer/txvalidator/v20/
validator.go:194-239), the whole block's (digest, r, s, pubkey) tuples
become device arrays and one jitted program verifies them all.

Field arithmetic is the f32/MXU limb layer (ops/limbs9.py): radix-2^9
limbs with the limb axis FIRST — (K, batch) arrays — so element-wise
work fills all vector lanes and the schoolbook/Montgomery folds run as
constant matmuls on the MXU.

Point arithmetic uses the Renes-Costello-Batina *complete* projective
addition formulas for a=-3 short Weierstrass curves (eprint 2015/1060,
algorithms 4 and 6).  Complete formulas are the TPU-idiomatic choice:
they are branch-free — identity, doubling, and inverse cases all fall
out of the same straight-line code — so a batch never diverges and XLA
sees one fused SIMD program.

Scalar multiplication u1*G + u2*Q is one interleaved windowed (Shamir)
ladder: 64 steps of 4 doublings + two table-adds, where the 16-entry
G table is a host-precomputed constant (selected by one-hot matmul on
the MXU) and the 16-entry Q table is built on device per lane.  The
final comparison avoids an inversion: accept iff X == (r + k*n)*Z
(mod p) for k in {0, 1} (with r + k*n < p), Z != 0.

Two ladder variants share that schedule: the original all-projective
`shamir_ladder` (complete addition, alg. 4) and the affine-table
`shamir_ladder_mixed` (complete MIXED addition, alg. 5, with the Q
table normalized by one Montgomery simultaneous inversion) —
selectable via FABRIC_MOD_TPU_MIXED_ADD, differentially tested to
produce identical verdicts.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from fabric_mod_tpu.ops import limbs9 as limbs
from fabric_mod_tpu.ops.limbs9 import (
    FieldSpec, K, add, sub, mont_mul, mont_sqr, to_mont, eq_zero,
    mul_small, canonical, bits_le, inv_mont, inv_mont_many,
    be_bytes_to_limbs, const_like, const_dot,
)

WINDOW = 4                     # Shamir ladder window width (bits)
N_WINDOWS = 256 // WINDOW
TABLE = 1 << WINDOW

# --- Curve constants (NIST P-256 / secp256r1) ------------------------------
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5


def _affine_add(p1, p2):
    """Host-side python-int affine addition (build-time table precompute
    only — never on the hot path)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1 - 3) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


@functools.lru_cache(maxsize=None)
def _consts():
    """Field specs and Montgomery-domain curve params.

    numpy (not jnp) on purpose — may be first called under a jit trace,
    and caching jnp values there would cache tracers.
    """
    fp = FieldSpec.make("p256.p", P)
    fn = FieldSpec.make("p256.n", N)
    R = 1 << limbs.RBITS
    b_m = limbs.int_to_limbs((B * R) % P)
    gx_m = limbs.int_to_limbs((GX * R) % P)
    gy_m = limbs.int_to_limbs((GY * R) % P)
    return fp, fn, b_m, gx_m, gy_m


@functools.lru_cache(maxsize=None)
def _g_table():
    """(3, TABLE, K) numpy constants: projective Montgomery-domain
    multiples [inf, G, 2G, ..., 15G] of the fixed base point, shared by
    every batch lane of the windowed ladder (the base point is a curve
    constant — unlike the per-signature Q table built on device)."""
    R = 1 << limbs.RBITS
    one_m = limbs.int_to_limbs(R % P)
    zero = np.zeros(K, np.float32)
    xs, ys, zs = [zero], [one_m.copy()], [np.zeros(K, np.float32)]
    acc = None
    for _ in range(1, TABLE):
        acc = _affine_add(acc, (GX, GY))
        xs.append(limbs.int_to_limbs(acc[0] * R % P))
        ys.append(limbs.int_to_limbs(acc[1] * R % P))
        zs.append(one_m.copy())
    return np.stack([np.stack(xs), np.stack(ys), np.stack(zs)])


# --- Complete projective point addition (RCB alg. 4/6, a = -3) -------------

def point_add(p1, p2, fp: FieldSpec, b_m: jnp.ndarray):
    """Complete addition of projective points (X:Y:Z), Montgomery domain.

    Valid for ALL inputs on the (prime-order) curve, including P == Q,
    P == -Q, and either operand at infinity (0:1:0).  Arrays are
    (K, ...batch); `b_m` must already be rank-matched (const_like).
    12 muls + 2 muls-by-b; every add/sub re-normalises limbs so lazy
    value bounds stay far inside limbs9's 2**260 domain.
    """
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    t0 = mont_mul(X1, X2, fp)
    t1 = mont_mul(Y1, Y2, fp)
    t2 = mont_mul(Z1, Z2, fp)
    t3 = add(X1, Y1)
    t4 = add(X2, Y2)
    t3 = mont_mul(t3, t4, fp)
    t4 = add(t0, t1)
    t3 = sub(t3, t4)
    t4 = add(Y1, Z1)
    X3 = add(Y2, Z2)
    t4 = mont_mul(t4, X3, fp)
    X3 = add(t1, t2)
    t4 = sub(t4, X3)
    X3 = add(X1, Z1)
    Y3 = add(X2, Z2)
    X3 = mont_mul(X3, Y3, fp)
    Y3 = add(t0, t2)
    Y3 = sub(X3, Y3)
    Z3 = mont_mul(b_m, t2, fp)
    X3 = sub(Y3, Z3)
    Z3 = add(X3, X3)
    X3 = add(X3, Z3)
    Z3 = sub(t1, X3)
    X3 = add(t1, X3)
    Y3 = mont_mul(b_m, Y3, fp)
    t1 = add(t2, t2)
    t2 = add(t1, t2)
    Y3 = sub(Y3, t2)
    Y3 = sub(Y3, t0)
    t1 = add(Y3, Y3)
    Y3 = add(t1, Y3)
    t1 = add(t0, t0)
    t0 = add(t1, t0)
    t0 = sub(t0, t2)
    t1 = mont_mul(t4, Y3, fp)
    t2 = mont_mul(t0, Y3, fp)
    Y3 = mont_mul(X3, Z3, fp)
    Y3 = add(Y3, t2)
    X3 = mont_mul(t3, X3, fp)
    X3 = sub(X3, t1)
    Z3 = mont_mul(t4, Z3, fp)
    t1 = mont_mul(t3, t0, fp)
    Z3 = add(Z3, t1)
    return (X3, Y3, Z3)


def point_add_mixed(p1, p2, fp: FieldSpec, b_m: jnp.ndarray):
    """Complete MIXED addition (RCB alg. 5, a = -3): p1 projective,
    p2 AFFINE (Z2 = 1 implicit), Montgomery domain.

    Algorithm 4 with Z2 = 1 substituted: t2 degenerates to Z1 and the
    three rank-1 cross products collapse (t4 = Y2*Z1 + Y1, the X-plane
    twin = X2*Z1 + X1), dropping the Z1*Z2 multiply — 11 muls + 2
    muls-by-b vs the full add's 12 + 2, and table entries need no Z
    plane at all (2/3 of the one-hot select bandwidth).  Complete for
    every projective p1 ON THE CURVE including infinity and p1 == ±p2;
    p2 cannot encode infinity — callers select around zero windows
    (see shamir_ladder_mixed).
    """
    X1, Y1, Z1 = p1
    X2, Y2 = p2
    t0 = mont_mul(X1, X2, fp)
    t1 = mont_mul(Y1, Y2, fp)
    t3 = add(X2, Y2)
    t4 = add(X1, Y1)
    t3 = mont_mul(t3, t4, fp)
    t4 = add(t0, t1)
    t3 = sub(t3, t4)
    t4 = mont_mul(Y2, Z1, fp)
    t4 = add(t4, Y1)
    Y3 = mont_mul(X2, Z1, fp)
    Y3 = add(Y3, X1)
    Z3 = mont_mul(b_m, Z1, fp)
    X3 = sub(Y3, Z3)
    Z3 = add(X3, X3)
    X3 = add(X3, Z3)
    Z3 = sub(t1, X3)
    X3 = add(t1, X3)
    Y3 = mont_mul(b_m, Y3, fp)
    t1 = add(Z1, Z1)
    t2 = add(t1, Z1)
    Y3 = sub(Y3, t2)
    Y3 = sub(Y3, t0)
    t1 = add(Y3, Y3)
    Y3 = add(t1, Y3)
    t1 = add(t0, t0)
    t0 = add(t1, t0)
    t0 = sub(t0, t2)
    t1 = mont_mul(t4, Y3, fp)
    t2 = mont_mul(t0, Y3, fp)
    Y3 = mont_mul(X3, Z3, fp)
    Y3 = add(Y3, t2)
    X3 = mont_mul(t3, X3, fp)
    X3 = sub(X3, t1)
    Z3 = mont_mul(t4, Z3, fp)
    t1 = mont_mul(t3, t0, fp)
    Z3 = add(Z3, t1)
    return (X3, Y3, Z3)


def point_double(p, fp: FieldSpec, b_m: jnp.ndarray):
    """Complete projective doubling (RCB alg. 6, a = -3), Montgomery
    domain.  Valid for ALL curve points including infinity.  3 squarings
    + 8 muls + 2 muls-by-b — ~20% cheaper than doubling through the
    generic complete addition."""
    X, Y, Z = p
    t0 = mont_sqr(X, fp)
    t1 = mont_sqr(Y, fp)
    t2 = mont_sqr(Z, fp)
    t3 = mont_mul(X, Y, fp)
    t3 = add(t3, t3)
    Z3 = mont_mul(X, Z, fp)
    Z3 = add(Z3, Z3)
    Y3 = mont_mul(b_m, t2, fp)
    Y3 = sub(Y3, Z3)
    X3 = add(Y3, Y3)
    Y3 = add(X3, Y3)
    X3 = sub(t1, Y3)
    Y3 = add(t1, Y3)
    Y3 = mont_mul(X3, Y3, fp)
    X3 = mont_mul(X3, t3, fp)
    t3 = add(t2, t2)
    t2 = add(t2, t3)
    Z3 = mont_mul(b_m, Z3, fp)
    Z3 = sub(Z3, t2)
    Z3 = sub(Z3, t0)
    t3 = add(Z3, Z3)
    Z3 = add(Z3, t3)
    t3 = add(t0, t0)
    t0 = add(t3, t0)
    t0 = sub(t0, t2)
    t0 = mont_mul(t0, Z3, fp)
    Y3 = add(Y3, t0)
    t0 = mont_mul(Y, Z, fp)
    t0 = add(t0, t0)
    Z3 = mont_mul(t0, Z3, fp)
    X3 = sub(X3, Z3)
    Z3 = mont_mul(t0, t1, fp)
    Z3 = add(Z3, Z3)
    Z3 = add(Z3, Z3)
    return (X3, Y3, Z3)


def infinity(shape_suffix) -> tuple:
    """The projective identity (0 : 1 : 0), (K, *shape_suffix) arrays."""
    fp, _, _, _, _ = _consts()
    zero = jnp.zeros((K,) + tuple(shape_suffix), jnp.float32)
    one = jnp.broadcast_to(
        jnp.asarray(fp.one_mont).reshape((K,) + (1,) * len(shape_suffix)),
        (K,) + tuple(shape_suffix)).astype(jnp.float32)
    return (zero, one, zero)


def on_curve(xm: jnp.ndarray, ym: jnp.ndarray) -> jnp.ndarray:
    """y^2 == x^3 - 3x + b (mod p) for Montgomery-domain affine coords."""
    fp, _, b_m, _, _ = _consts()
    y2 = mont_sqr(ym, fp)
    x2 = mont_sqr(xm, fp)
    x3 = mont_mul(x2, xm, fp)
    rhs = add(sub(x3, mul_small(xm, 3)), const_like(b_m, xm))
    return eq_zero(sub(y2, rhs), fp)


# --- The jitted verify core ------------------------------------------------

def build_q_table(q1, inf_pt, fp: FieldSpec, b_m):
    """[inf, Q, 2Q, ..., 15Q] as a list of projective points — the
    per-lane window table schedule (7 doublings + 7 additions),
    shared by the XLA ladder and the Pallas kernel so the two can
    never diverge."""
    qtab = [inf_pt, q1]
    for i in range(2, TABLE):
        if i % 2 == 0:
            qtab.append(point_double(qtab[i // 2], fp, b_m))
        else:
            qtab.append(point_add(qtab[i - 1], q1, fp, b_m))
    return qtab


def shamir_ladder(u1_w: jnp.ndarray, u2_w: jnp.ndarray,
                  qx_m: jnp.ndarray, qy_m: jnp.ndarray):
    """The windowed Shamir ladder: u1*G + u2*Q from MSB-first window
    values (N_WINDOWS, batch) and the Montgomery-domain affine key.
    Returns the projective (X, Y, Z).  This is the dominant cost of a
    verify; ops/p256_pallas.py provides a VMEM-fused drop-in."""
    fp, _fn, b_m_np, _, _ = _consts()
    batch = qx_m.shape[1:]
    b_m = const_like(b_m_np, qx_m)

    qtab = build_q_table((qx_m, qy_m, infinity(batch)[1]),
                         infinity(batch), fp, b_m)
    q_table = tuple(
        jnp.stack([pt[c] for pt in qtab], axis=0)    # (TABLE, K, batch)
        for c in range(3))
    g_tab_np = _g_table()                            # (3, TABLE, K)

    # MSB -> LSB: per step WINDOW doublings, one add from each table
    # (complete addition absorbs the zero-window infinity entries
    # branch-free).
    sel_seq = jnp.stack([u1_w, u2_w], axis=1)        # (NW, 2, batch)

    def step(acc, w2):
        # WINDOW doublings as a fori_loop: the traced scan body holds
        # ONE doubling instead of WINDOW unrolled copies — measurably
        # faster XLA compiles with identical math.
        acc = jax.lax.fori_loop(
            0, WINDOW, lambda _i, a: point_double(a, fp, b_m), acc)
        # Q-table select: one-hot reduce over the per-lane tables (VPU).
        oh_q = jax.nn.one_hot(w2[1], TABLE, dtype=jnp.float32, axis=0)
        acc = point_add(acc, tuple(
            jnp.sum(oh_q[:, None] * q_table[c], axis=0)
            for c in range(3)), fp, b_m)
        # G-table select: constant table -> one-hot matmul (MXU).
        # const_dot, NOT a bare tensordot: table limbs reach 511 and
        # would be rounded by the TPU's default bf16 matmul precision.
        oh_g = jax.nn.one_hot(w2[0], TABLE, dtype=jnp.float32, axis=0)
        acc = point_add(acc, tuple(
            const_dot(g_tab_np[c].T, oh_g)
            for c in range(3)), fp, b_m)
        return acc, None

    acc, _ = jax.lax.scan(step, infinity(batch), sel_seq)
    return acc


@functools.lru_cache(maxsize=None)
def _g_table_affine():
    """(2, TABLE-1, K) numpy constants: AFFINE Montgomery-domain
    multiples [G, 2G, ..., 15G] — no Z plane, no infinity entry (the
    zero window is handled by the mixed ladder's keep-select)."""
    R = 1 << limbs.RBITS
    xs, ys = [], []
    acc = None
    for _ in range(1, TABLE):
        acc = _affine_add(acc, (GX, GY))
        xs.append(limbs.int_to_limbs(acc[0] * R % P))
        ys.append(limbs.int_to_limbs(acc[1] * R % P))
    return np.stack([np.stack(xs), np.stack(ys)])


def build_q_table_affine(qx_m, qy_m, fp: FieldSpec, b_m):
    """[Q, 2Q, ..., 15Q] as AFFINE Montgomery-domain (x, y) pairs.

    Built through the shared projective schedule (build_q_table) and
    normalized with ONE batched Montgomery simultaneous inversion
    (limbs9.inv_mont_many) — 1 Fermat inversion + 3(TABLE-2) muls for
    the whole table instead of one inversion per entry.  All 128
    table-adds of the ladder then take the cheaper mixed formula and
    the one-hot selects move two planes instead of three.

    Lanes whose key is invalid (off-curve / (0,0)) can hit Z = 0 in
    the schedule; the simultaneous inversion then zeroes that LANE's
    whole table — harmless, those lanes are masked by key_ok.
    """
    batch = qx_m.shape[1:]
    inf_pt = infinity(batch)
    qtab = build_q_table((qx_m, qy_m, inf_pt[1]), inf_pt, fp, b_m)[1:]
    zinv = inv_mont_many([pt[2] for pt in qtab], fp)
    ax = [mont_mul(pt[0], zi, fp) for pt, zi in zip(qtab, zinv)]
    ay = [mont_mul(pt[1], zi, fp) for pt, zi in zip(qtab, zinv)]
    return ax, ay


def shamir_ladder_mixed(u1_w: jnp.ndarray, u2_w: jnp.ndarray,
                        qx_m: jnp.ndarray, qy_m: jnp.ndarray):
    """The windowed Shamir ladder over AFFINE tables + complete mixed
    additions — same contract as `shamir_ladder` (identical verdicts;
    the projective representative differs by a Z scale).

    Both window tables are affine (G: host constant; Q: device-built
    then normalized by one simultaneous inversion), so every table-add
    is RCB algorithm 5 and the one-hot selects move x/y only.  Affine
    tables cannot encode the infinity entry a zero window used to
    select; instead the add runs unconditionally against whatever the
    all-zero one-hot produces and a keep-select drops it — branch-free
    (the same reason the complete formulas are used at all).

    Selected by FABRIC_MOD_TPU_MIXED_ADD=1 (bccsp buckets route
    through `verify_core_mixed`); dark by default until on-chip
    measurement confirms it, like the Pallas ladder before it.
    """
    fp, _fn, b_m_np, _, _ = _consts()
    batch = qx_m.shape[1:]
    b_m = const_like(b_m_np, qx_m)

    ax, ay = build_q_table_affine(qx_m, qy_m, fp, b_m)
    q_tab = (jnp.stack(ax, axis=0), jnp.stack(ay, axis=0))
    g_aff = _g_table_affine()                        # (2, TABLE-1, K)
    sel_seq = jnp.stack([u1_w, u2_w], axis=1)        # (NW, 2, batch)

    def add_selected(acc, w, p2):
        """Mixed-add the selected affine point; keep acc on w == 0
        (the affine table has no infinity row — the one-hot is all
        zero there and the formula output is discarded)."""
        added = point_add_mixed(acc, p2, fp, b_m)
        keep = (w == 0)[None]
        return tuple(jnp.where(keep, a, n) for a, n in zip(acc, added))

    def step(acc, w2):
        acc = jax.lax.fori_loop(
            0, WINDOW, lambda _i, a: point_double(a, fp, b_m), acc)
        # Q-table select: one-hot reduce over the per-lane AFFINE
        # table (w-1 indexed; w == 0 yields a zero one-hot).
        oh_q = jax.nn.one_hot(w2[1] - 1, TABLE - 1, dtype=jnp.float32,
                              axis=0)
        acc = add_selected(acc, w2[1], tuple(
            jnp.sum(oh_q[:, None] * q_tab[c], axis=0) for c in range(2)))
        # G-table select: constant table -> one-hot matmul (MXU,
        # precision-pinned — table limbs reach 511).
        oh_g = jax.nn.one_hot(w2[0] - 1, TABLE - 1, dtype=jnp.float32,
                              axis=0)
        acc = add_selected(acc, w2[0], tuple(
            const_dot(g_aff[c].T, oh_g) for c in range(2)))
        return acc, None

    acc, _ = jax.lax.scan(step, infinity(batch), sel_seq)
    return acc


def inv_mont_p_chain(a_mont: jnp.ndarray, spec=None) -> jnp.ndarray:
    """Fermat inversion mod p via a fixed addition chain — 255
    squarings (in fori_loop runs) + 13 multiplies, no data-dependent
    control flow and, unlike the generic `limbs9.inv_mont`, no
    lax.scan over a captured (256,) exponent-bit constant — which is
    what makes it usable INSIDE a Pallas kernel (Mosaic cannot
    materialize captured array constants; kernel window-0 table
    normalization runs this).

    The chain is specific to P-256's p (the exponent p-2 decomposes
    into 2^32-1 word runs plus a (2^30-1)·4+1 tail); `spec`, if given,
    must be the p field.  Verified against `inv_mont` in
    tests/test_p256_mixed.py.
    """
    fp = _consts()[0]
    if spec is not None and spec.modulus != P:
        raise ValueError("inv_mont_p_chain is specific to the P-256 p field")

    def sqr_n(x, n):
        return jax.lax.fori_loop(
            0, n, lambda _i, v: mont_sqr(v, fp), x)

    a = a_mont
    x2 = mont_mul(mont_sqr(a, fp), a, fp)            # a^(2^2 - 1)
    x4 = mont_mul(sqr_n(x2, 2), x2, fp)              # a^(2^4 - 1)
    x8 = mont_mul(sqr_n(x4, 4), x4, fp)              # a^(2^8 - 1)
    x16 = mont_mul(sqr_n(x8, 8), x8, fp)             # a^(2^16 - 1)
    x24 = mont_mul(sqr_n(x16, 8), x8, fp)            # a^(2^24 - 1)
    x28 = mont_mul(sqr_n(x24, 4), x4, fp)            # a^(2^28 - 1)
    x30 = mont_mul(sqr_n(x28, 2), x2, fp)            # a^(2^30 - 1)
    x32 = mont_mul(sqr_n(x30, 2), x2, fp)            # a^(2^32 - 1)
    # p - 2 as big-endian 32-bit words: FFFFFFFF 00000001 00000000
    # 00000000 00000000 FFFFFFFF FFFFFFFF FFFFFFFD
    acc = mont_mul(sqr_n(x32, 32), a, fp)            # FFFFFFFF 00000001
    acc = sqr_n(acc, 96)                             # three zero words
    acc = mont_mul(sqr_n(acc, 32), x32, fp)          # FFFFFFFF
    acc = mont_mul(sqr_n(acc, 32), x32, fp)          # FFFFFFFF
    acc = mont_mul(sqr_n(acc, 30), x30, fp)          # FFFFFFFD ...
    acc = mont_mul(sqr_n(acc, 2), a, fp)             # ... = (2^30-1)*4+1
    return acc


def digest_words_to_limbs(dw: jnp.ndarray) -> jnp.ndarray:
    """(..., 8) uint32 big-endian SHA-256 digest words -> (K, ...) f32
    limbs of the digest-as-256-bit-integer — the DEVICE-side half of
    the fused hash->verify path (host twin: `limbs9.be_bytes_to_limbs`
    over `sha256.digest_to_bytes`; differentially tested equal).
    Pure shifts/masks + one tiny constant fold, shape-static."""
    w = jnp.moveaxis(dw.astype(jnp.uint32), -1, 0)   # (8, ...batch)
    j = np.arange(256)
    # global bit j (LSB-first) lives in word 7 - j//32, bit j%32
    rows = w[7 - j // 32]                            # (256, ...batch)
    shifts = jnp.asarray(j % 32, jnp.uint32).reshape(
        (256,) + (1,) * (w.ndim - 1))
    bits = ((rows >> shifts) & jnp.uint32(1)).astype(jnp.float32)
    pad = jnp.zeros((limbs.RBITS - 256,) + bits.shape[1:], jnp.float32)
    bits = jnp.concatenate([bits, pad], axis=0)
    bits = bits.reshape((K, limbs.B) + bits.shape[1:])
    wts = jnp.asarray((1 << np.arange(limbs.B)).astype(np.float32))
    # precision-pinned like every limb fold: weights are powers of two
    # (bf16-exact), but the pin keeps this path out of the "bare
    # matmul rounds limbs" bug class limbs9.const_dot exists to stop
    return jnp.tensordot(wts, bits, axes=(0, 1),
                         precision=limbs.PRECISION)  # (K, ...batch)


def _verify_core_impl(e, r, s, qx, qy, rn_lt_p,
                      ladder=shamir_ladder) -> jnp.ndarray:
    """Batched ECDSA-P256 verify on raw limb arrays.

    Args:
      e, r, s: (K, batch) f32 canonical limbs — digest (as 256-bit int),
        and signature scalars already range-checked to [1, n-1] on host.
      qx, qy: (K, batch) f32 canonical limbs of the affine public key,
        host-checked to be < p.
      rn_lt_p: (batch,) bool — whether r + n < p (host-precomputed).
    Returns:
      (batch,) bool — signature valid AND key on curve.
    """
    fp, fn, _b_m_np, _, _ = _consts()
    batch = e.shape[1:]

    # Key checks: on curve, not the identity encoding (0, 0).
    qx_m = to_mont(qx, fp)
    qy_m = to_mont(qy, fp)
    key_ok = on_curve(qx_m, qy_m)
    key_ok &= ~(eq_zero(qx, fp) & eq_zero(qy, fp))

    # Scalars mod n: w = s^-1, u1 = e*w, u2 = r*w.  mont_mul of a *plain*
    # value by a Montgomery-domain one yields a plain product directly.
    s_mn = to_mont(s, fn)
    w_mn = inv_mont(s_mn, fn)
    u1 = canonical(mont_mul(e, w_mn, fn), fn)       # (K, batch) int32
    u2 = canonical(mont_mul(r, w_mn, fn), fn)

    # WINDOW-bit window values, MSB-window first: (N_WINDOWS, batch).
    wexp = jnp.asarray(1 << np.arange(WINDOW), jnp.int32)

    def windows_msb_first(u):
        bits = bits_le(u)                            # (256, batch)
        w = jnp.tensordot(
            wexp, bits.reshape((N_WINDOWS, WINDOW) + batch), axes=(0, 1))
        return w[::-1]                               # (N_WINDOWS, batch)

    u1_w = windows_msb_first(u1)
    u2_w = windows_msb_first(u2)

    acc = ladder(u1_w, u2_w, qx_m, qy_m)
    X, Z = acc[0], acc[2]

    # Accept iff Z != 0 and X == r'*Z for r' in {r, r+n} (r' < p).
    not_inf = ~eq_zero(Z, fp)
    r_m = to_mont(r, fp)
    ok_r = eq_zero(sub(X, mont_mul(r_m, Z, fp)), fp)
    rn = add(r, const_like(fn.p, r))
    rn_m = to_mont(rn, fp)
    ok_rn = eq_zero(sub(X, mont_mul(rn_m, Z, fp)), fp) & rn_lt_p
    return key_ok & not_inf & (ok_r | ok_rn)


verify_core = jax.jit(_verify_core_impl)
verify_core_mixed = jax.jit(
    functools.partial(_verify_core_impl, ladder=shamir_ladder_mixed))


def _verify_core_fused_impl(words, nblocks, has_msg, e, r, s, qx, qy,
                            rn_lt_p, ladder=shamir_ladder) -> jnp.ndarray:
    """The fused hash->verify core: e = SHA-256(m) computed ON DEVICE
    in the same program as the ECDSA verify — one dispatch, no host
    digest loop (the host half of the old path hashed per message in
    msp/identities.digest_for).

    Args (beyond _verify_core_impl's):
      words: (batch, max_blocks, 16) uint32 — FIPS 180-4 pre-padded
        message words (bccsp/der.pack_messages).
      nblocks: (batch,) int32 — real block count per lane; 0 for
        pre-digested lanes (the compression state freezes at H0 and
        the lane's digest comes from `e` instead).
      has_msg: (batch,) bool — which lanes carry a raw message.  Mixed
        batches are first-class: a bucket can hold raw-message items
        and pre-digested items and still be ONE device program.
      e: (K, batch) f32 — host-side digest limbs for the pre-digested
        lanes (ignored where has_msg).
    """
    from fabric_mod_tpu.ops import sha256
    dw = sha256.sha256_blocks(words, nblocks)        # (batch, 8) u32
    e_dev = digest_words_to_limbs(dw)                # (K, batch) f32
    e = jnp.where(has_msg[None], e_dev, e)
    return _verify_core_impl(e, r, s, qx, qy, rn_lt_p, ladder=ladder)


verify_core_fused = jax.jit(_verify_core_fused_impl)
verify_core_fused_mixed = jax.jit(
    functools.partial(_verify_core_fused_impl, ladder=shamir_ladder_mixed))


# --- Host wrapper ----------------------------------------------------------

_N_BYTES = N.to_bytes(32, "big")
_P_BYTES = P.to_bytes(32, "big")
_P_MINUS_N_BYTES = (P - N).to_bytes(32, "big")


def _lt_bytes(a: np.ndarray, b_: bytes) -> np.ndarray:
    """Lexicographic a < b over (..., 32) big-endian byte arrays."""
    bb = np.frombuffer(b_, np.uint8)
    diff = a.astype(np.int16) - bb.astype(np.int16)
    nz = diff != 0
    first = np.argmax(nz, axis=-1)
    any_nz = nz.any(axis=-1)
    firstval = np.take_along_axis(diff, first[..., None], axis=-1)[..., 0]
    return np.where(any_nz, firstval < 0, False)


def _host_limbs(b: np.ndarray) -> np.ndarray:
    """(batch, 32) bytes -> (K, batch) f32 host array (device layout)."""
    return np.moveaxis(be_bytes_to_limbs(b), -1, 0).astype(np.float32)


def marshal_inputs(digests: np.ndarray, r_bytes: np.ndarray,
                   s_bytes: np.ndarray, qx_bytes: np.ndarray,
                   qy_bytes: np.ndarray):
    """Host prologue shared by batch_verify and the driver entry
    points: range checks + byte->limb marshalling.

    Returns (core_args, range_ok): `core_args` is the positional tuple
    for verify_core ((K, batch) f32 limb arrays + rn_lt_p flags),
    `range_ok` the host-side scalar-range verdict to AND into the
    device mask.
    """
    digests = np.asarray(digests, np.uint8)
    r_bytes = np.asarray(r_bytes, np.uint8)
    s_bytes = np.asarray(s_bytes, np.uint8)
    qx_bytes = np.asarray(qx_bytes, np.uint8)
    qy_bytes = np.asarray(qy_bytes, np.uint8)

    nonzero_r = r_bytes.any(axis=-1)
    nonzero_s = s_bytes.any(axis=-1)
    range_ok = (nonzero_r & nonzero_s
                & _lt_bytes(r_bytes, _N_BYTES) & _lt_bytes(s_bytes, _N_BYTES)
                & _lt_bytes(qx_bytes, _P_BYTES)
                & _lt_bytes(qy_bytes, _P_BYTES))
    rn_lt_p = _lt_bytes(r_bytes, _P_MINUS_N_BYTES)
    core_args = (_host_limbs(digests), _host_limbs(r_bytes),
                 _host_limbs(s_bytes), _host_limbs(qx_bytes),
                 _host_limbs(qy_bytes), rn_lt_p)
    return core_args, range_ok


def batch_verify(digests: np.ndarray, r_bytes: np.ndarray,
                 s_bytes: np.ndarray, qx_bytes: np.ndarray,
                 qy_bytes: np.ndarray, mesh=None, lazy: bool = False):
    """Verify a batch of ECDSA-P256 signatures over 32-byte digests.

    All args are (batch, 32) uint8 big-endian.  Returns (batch,) bool —
    or, with `lazy=True`, a zero-arg resolver: the device program has
    been DISPATCHED (jax dispatch is asynchronous) but not awaited, so
    the caller can overlap host work for the next batch against this
    one's device execution and call the resolver when the verdicts are
    needed (the commit pipeline's double buffer, SURVEY §2.9 row 2).

    `mesh` (optional jax.sharding.Mesh, see parallel/mesh.py) shards
    the trailing batch axis of the limb arrays across the `dp` axis, so
    GSPMD partitions the same jitted program across chips — multi-chip
    is a data-placement decision, not a different code path.  The batch
    must then divide the mesh size (every bucket in bccsp/tpu.py does).
    """
    core_args, range_ok = marshal_inputs(
        digests, r_bytes, s_bytes, qx_bytes, qy_bytes)

    shardings = (None,) * 6
    if mesh is not None:
        from fabric_mod_tpu.parallel import verify_shardings
        limb_s, flag_s = verify_shardings(mesh)
        shardings = (limb_s,) * 5 + (flag_s,)

    def _dev(x, s):
        arr = jnp.asarray(x)
        if s is not None:
            arr = jax.device_put(arr, s)
        return arr

    core = _select_core(digests.shape[0], mesh)
    ok = core(*(_dev(a, s) for a, s in zip(core_args, shardings)))
    if lazy:
        return lambda: np.asarray(ok) & range_ok
    return np.asarray(ok) & range_ok


def batch_verify_raw(words: np.ndarray, nblocks: np.ndarray,
                     has_msg: np.ndarray, digests: np.ndarray,
                     r_bytes: np.ndarray, s_bytes: np.ndarray,
                     qx_bytes: np.ndarray, qy_bytes: np.ndarray,
                     mesh=None, lazy: bool = False):
    """`batch_verify` with the digest computed ON DEVICE for raw-
    message lanes: one jitted program runs SHA-256 over the pre-padded
    message words AND the ECDSA verify (verify_core_fused) — the last
    host round-trip of the commit path (the per-message hashlib loop)
    gone.  Lanes with has_msg=False fall back to the `digests` plane,
    so mixed buckets stay one program.

    `words` is (batch, max_blocks, 16) uint32 from
    bccsp/der.pack_messages; the other args match `batch_verify`.
    Honors the same FABRIC_MOD_TPU_MIXED_ADD / FABRIC_MOD_TPU_PALLAS
    composition, and the same mesh sharding (message words shard on
    their LEADING batch axis — parallel.fused_verify_shardings).
    """
    core_args, range_ok = marshal_inputs(
        digests, r_bytes, s_bytes, qx_bytes, qy_bytes)

    limb_s = flag_s = words_s = None
    if mesh is not None:
        from fabric_mod_tpu.parallel import (fused_verify_shardings,
                                             verify_shardings)
        limb_s, flag_s = verify_shardings(mesh)
        words_s, _ = fused_verify_shardings(mesh)

    def _dev(x, s):
        arr = jnp.asarray(x)
        if s is not None:
            arr = jax.device_put(arr, s)
        return arr

    core = _select_core(digests.shape[0], mesh, fused=True)
    ok = core(_dev(np.asarray(words, np.uint32), words_s),
              _dev(np.asarray(nblocks, np.int32), flag_s),
              _dev(np.asarray(has_msg, bool), flag_s),
              *(_dev(a, s) for a, s in zip(
                  core_args, (limb_s,) * 5 + (flag_s,))))
    if lazy:
        return lambda: np.asarray(ok) & range_ok
    return np.asarray(ok) & range_ok


def _select_core(batch: int, mesh, fused: bool = False):
    """The env-knob composition matrix (PALLAS x MIXED_ADD x fused
    hash), one place: Pallas when enabled and tileable (single-device
    only — GSPMD cannot partition a pallas_call, so the mesh path
    stays on the XLA core), mixed ladder when enabled — the Pallas
    kernel now IMPLEMENTS the mixed schedule rather than being routed
    around it (the PR-1 follow-up ROADMAP.md named)."""
    mixed = _use_mixed()
    if _use_pallas() and mesh is None and batch % 8 == 0:
        # odd direct-caller batches (not divisible by 8 — bccsp
        # buckets always are) stay on the XLA core above: a lane
        # width under 8 would make the grid pathological
        tile = next(t for t in (128, 64, 32, 16, 8) if batch % t == 0)
        return _pallas_core(tile, mixed, fused)
    if fused:
        return verify_core_fused_mixed if mixed else verify_core_fused
    return verify_core_mixed if mixed else verify_core


def _use_mixed() -> bool:
    """FABRIC_MOD_TPU_MIXED_ADD=1 swaps the affine-table mixed-
    addition ladder into the verify pipeline (shamir_ladder_mixed) —
    dark-launched pending on-chip measurement, selectable per-run by
    bench.py --mixed-add.  COMPOSES with FABRIC_MOD_TPU_PALLAS: with
    both set, the VMEM-fused Pallas kernel runs the mixed-addition
    schedule (ops/p256_pallas.pallas_ladder_mixed) — no longer routed
    around it."""
    from fabric_mod_tpu.utils import knobs
    return knobs.get_bool("FABRIC_MOD_TPU_MIXED_ADD")


def _use_pallas() -> bool:
    """FABRIC_MOD_TPU_PALLAS=1 swaps the VMEM-fused Pallas ladder into
    the verify pipeline (ops/p256_pallas.py) — dark-launched until
    on-chip measurement confirms it over the XLA ladder.  No-op on the
    CPU backend (compiled pallas_call is TPU-only; the interpreter is
    for tests)."""
    from fabric_mod_tpu.utils import knobs
    if not knobs.get_bool("FABRIC_MOD_TPU_PALLAS"):
        return False
    return jax.default_backend() != "cpu"


@functools.lru_cache(maxsize=None)
def _pallas_core(tile: int, mixed: bool = False, fused: bool = False):
    """Jitted Pallas verify core for one (tile, ladder-variant,
    hash-fusion) combination — lru-cached so each compiles once."""
    from fabric_mod_tpu.ops import p256_pallas
    ladder = functools.partial(
        p256_pallas.pallas_ladder_mixed if mixed
        else p256_pallas.pallas_ladder, tile=tile)
    impl = _verify_core_fused_impl if fused else _verify_core_impl
    return jax.jit(functools.partial(impl, ladder=ladder))
