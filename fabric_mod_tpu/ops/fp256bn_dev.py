"""Batched FP256BN optimal-ate pairing on device (JAX).

(reference: the fabric-amcl FP256BN pairing behind idemix —
idemix/util.go:13-21, consumed by Signature.Ver at
idemix/signature.go:243.  Semantics are pinned by the host
implementation in idemix/fp256bn.py; this module reproduces them
batched, per idemix/KERNEL_PLAN.md.)

Design (KERNEL_PLAN.md §2-3):
* The G2 arguments of idemix's pairing checks are SHARED across a
  batch (the issuer's W and the fixed g2), so all G2 arithmetic — the
  Miller loop's point doublings/additions and line slopes — is
  precomputed ONCE per issuer on host as a static schedule of sparse
  line coefficients.  The device work is only the per-signature line
  evaluation l(P_i) and the Fp12 square/multiply chain, batched over
  signatures on the f32/MXU limb layer of ops/limbs9.py (batch axis =
  lanes).
* Sparse lines: with the M-type twist untwist psi(x',y') =
  (x' v^2/xi, y' v w/xi), the line through T with slope lam' evaluated
  at an Fp point (xP, yP) is
      l = yP·1  +  A·(v·w)  +  (B·xP)·(v^2·w),
  A = (lam'·xT − yT)/xi,  B = −lam'/xi  — three nonzero Fp2 slots,
  so the accumulator multiply is a 42-mont sparse mul, not 54.
* Final exponentiation: easy part (conj/inv + Frobenius), then the
  Devegili–Scott–Dominguez u-chain for the hard part — 3 static
  |u|-exponentiations in the cyclotomic subgroup (63-step lax.scan)
  plus ~13 Fp12 muls; NOT the naive 766-bit exponent.
* Equality checks e(A,W) == e(Abar,g2) run as
  e(A,W)·e(−Abar,g2) == 1: two Miller loops, one shared final exp.

Field elements are (K, batch) f32 lazy limbs in the Montgomery domain
of the MXU limb layer (ops/limbs9.py — limb axis FIRST, schoolbook
fold + Montgomery constant products as precision-pinned matmuls);
Fp2/Fp6/Fp12 are nested tuples (pytrees).  Per-step line constants
stay bare (K,) vectors — the limb ops rank-align them against batched
operands.
"""
from __future__ import annotations

import functools
from typing import List, Tuple

import numpy as np

from fabric_mod_tpu.idemix import fp256bn as host
from fabric_mod_tpu.ops import limbs9 as limbs
from fabric_mod_tpu.ops.compilecache import enable_compile_cache

# the pairing program goes on the SAME persistent XLA cache as the
# ECDSA ladder: importing this module is "service start" for an
# idemix-verifying peer, and the second process reuses the compiled
# executable instead of re-paying the multi-minute compile
enable_compile_cache()

SPEC = limbs.FieldSpec.make("fp256bn.p", host.P)
_R = 1 << limbs.RBITS


def _mont_np(x: int) -> np.ndarray:
    """Host int -> canonical limbs of x*R mod p (Montgomery form)."""
    return limbs.int_to_limbs((x % host.P) * _R % host.P)


def _mont_fp2_np(x: "host.Fp2") -> np.ndarray:
    """(2, K) Montgomery limbs of an Fp2 constant."""
    return np.stack([_mont_np(x.a), _mont_np(x.b)])


# ---------------------------------------------------------------------------
# Device tower arithmetic.  Fp = (..., K); Fp2 = (a, b); Fp6 = (c0,c1,c2);
# Fp12 = (c0, c1).  All ops stay in the Montgomery domain.
# ---------------------------------------------------------------------------

def f2_add(x, y):
    return (limbs.add(x[0], y[0]), limbs.add(x[1], y[1]))


def f2_sub(x, y):
    return (limbs.sub(x[0], y[0]), limbs.sub(x[1], y[1]))


def f2_neg(x):
    return (limbs.carried(-x[0]), limbs.carried(-x[1]))


def f2_conj(x):
    return (x[0], limbs.carried(-x[1]))


def f2_mul(x, y):
    """Karatsuba: 3 Montgomery muls."""
    t0 = limbs.mont_mul(x[0], y[0], SPEC)
    t1 = limbs.mont_mul(x[1], y[1], SPEC)
    t2 = limbs.mont_mul(limbs.add(x[0], x[1]), limbs.add(y[0], y[1]), SPEC)
    return (limbs.sub(t0, t1), limbs.sub(t2, limbs.add(t0, t1)))


def f2_sqr(x):
    """(a+b)(a-b), 2ab: 2 Montgomery muls."""
    a, b = x
    return (limbs.mont_mul(limbs.add(a, b), limbs.sub(a, b), SPEC),
            limbs.mul_small(limbs.mont_mul(a, b, SPEC), 2))


def f2_mul_fp(x, s):
    """Fp2 scaled by an Fp element: 2 muls."""
    return (limbs.mont_mul(x[0], s, SPEC), limbs.mont_mul(x[1], s, SPEC))


def f2_mul_xi(x):
    """xi = 1 + i: (a - b, a + b), adds only."""
    return (limbs.sub(x[0], x[1]), limbs.add(x[0], x[1]))


def f2_inv(x):
    d = limbs.inv_mont(
        limbs.add(limbs.mont_sqr(x[0], SPEC), limbs.mont_sqr(x[1], SPEC)),
        SPEC)
    return (limbs.mont_mul(x[0], d, SPEC),
            limbs.carried(-limbs.mont_mul(x[1], d, SPEC)))


def f6_add(x, y):
    return tuple(f2_add(a, b) for a, b in zip(x, y))


def f6_sub(x, y):
    return tuple(f2_sub(a, b) for a, b in zip(x, y))


def f6_neg(x):
    return tuple(f2_neg(a) for a in x)


def f6_mul(x, y):
    """Toom-style 6-mul Fp6 product (18 Montgomery muls)."""
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0, t1, t2 = f2_mul(a0, b0), f2_mul(a1, b1), f2_mul(a2, b2)
    c0 = f2_add(f2_mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)),
                                 f2_add(t1, t2))), t0)
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)),
                       f2_add(t0, t1)), f2_mul_xi(t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)),
                       f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_mul_sparse12(x, b1, b2):
    """x * Fp6(0, b1, b2): 15 Montgomery muls."""
    a0, a1, a2 = x
    t1, t2 = f2_mul(a1, b1), f2_mul(a2, b2)
    c0 = f2_mul_xi(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)),
                          f2_add(t1, t2)))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), b1), t1), f2_mul_xi(t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), b2), t2), t1)
    return (c0, c1, c2)


def f6_mul_fp(x, s):
    return tuple(f2_mul_fp(a, s) for a in x)


def f6_mul_v(x):
    return (f2_mul_xi(x[2]), x[0], x[1])


def f6_inv(x):
    a0, a1, a2 = x
    t0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    t1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    t2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    d = f2_add(f2_mul(a0, t0),
               f2_add(f2_mul_xi(f2_mul(a2, t1)), f2_mul_xi(f2_mul(a1, t2))))
    di = f2_inv(d)
    return (f2_mul(t0, di), f2_mul(t1, di), f2_mul(t2, di))


def f12_mul(x, y):
    a0, a1 = x
    b0, b1 = y
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    return (f6_add(t0, f6_mul_v(t1)),
            f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1)))


def f12_sqr(x):
    a0, a1 = x
    t0 = f6_mul(a0, a1)
    c0 = f6_sub(f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_v(a1))),
                f6_add(t0, f6_mul_v(t0)))
    return (c0, f6_add(t0, t0))


def f12_conj(x):
    return (x[0], f6_neg(x[1]))


def f12_inv(x):
    t = f6_inv(f6_sub(f6_mul(x[0], x[0]), f6_mul_v(f6_mul(x[1], x[1]))))
    return (f6_mul(x[0], t), f6_neg(f6_mul(x[1], t)))


def f12_mul_line(f, yp, A, Bxp):
    """f * l where l = yp·1 + A·(v·w) + Bxp·(v^2·w)  — the sparse line
    (l.c0 = (yp, 0, 0); l.c1 = (0, A, Bxp)): 12 + 30 = 42 muls."""
    a0, a1 = f
    l1_mul = functools.partial(f6_mul_sparse12, b1=A, b2=Bxp)
    t0 = f6_mul_fp(a0, yp)              # a0 * l0
    t1 = l1_mul(a1)                     # a1 * l1
    c1 = f6_add(l1_mul(a0), f6_mul_fp(a1, yp))
    return (f6_add(t0, f6_mul_v(t1)), c1)


# Frobenius constants (Montgomery, numpy) — x -> x^p on Fp12
_F61 = _mont_fp2_np(host._FROB6_1)
_F62 = _mont_fp2_np(host._FROB6_2)
_F12 = _mont_fp2_np(host._FROB12)
_F12_61 = _mont_fp2_np(host._FROB12 * host._FROB6_1)
_F12_62 = _mont_fp2_np(host._FROB12 * host._FROB6_2)


def f12_frobenius(x):
    c0, c1 = x
    f0 = (f2_conj(c0[0]),
          f2_mul(f2_conj(c0[1]), tuple(_F61)),
          f2_mul(f2_conj(c0[2]), tuple(_F62)))
    f1 = (f2_mul(f2_conj(c1[0]), tuple(_F12)),
          f2_mul(f2_conj(c1[1]), tuple(_F12_61)),
          f2_mul(f2_conj(c1[2]), tuple(_F12_62)))
    return (f0, f1)


def f12_one(shape_like):
    """Montgomery one broadcast to the batch shape of `shape_like`
    ((K, batch) leading-limb layout)."""
    import jax.numpy as jnp
    one = jnp.broadcast_to(
        limbs.const_like(SPEC.one_mont, shape_like),
        shape_like.shape).astype(jnp.float32)
    zero = jnp.zeros_like(one)
    z2 = (zero, zero)
    return (((one, zero), z2, z2), (z2, z2, z2))


def f12_is_one(x):
    """(batch,) bool: is x == 1 (all coefficients canonical-checked)."""
    import jax.numpy as jnp
    (c00, c01, c02), (c10, c11, c12) = x
    ok = limbs.eq_zero(limbs.sub(c00[0], SPEC.one_mont), SPEC)
    for f2 in (c01, c02, c10, c11, c12):
        ok &= limbs.eq_zero(f2[0], SPEC) & limbs.eq_zero(f2[1], SPEC)
    ok &= limbs.eq_zero(c00[1], SPEC)
    return ok


# ---------------------------------------------------------------------------
# Host: static line schedule per G2 point (shared across the batch)
# ---------------------------------------------------------------------------

class LineSchedule:
    """Stacked per-step line coefficients for one G2 point.

    Arrays (all numpy, Montgomery limbs):
      is_add: (N,) bool — add-step (no squaring before the multiply)
      A, B:   (N, 2, K) — the Fp2 line constants per step
      corr_A, corr_B: (2, 2, K) — the two Frobenius correction lines
    """

    def __init__(self, is_add, A, B, corr_A, corr_B):
        self.is_add = is_add
        self.A = A
        self.B = B
        self.corr_A = corr_A
        self.corr_B = corr_B


@functools.lru_cache(maxsize=32)
def _schedule_cached(qx_a: int, qx_b: int, qy_a: int, qy_b: int
                     ) -> LineSchedule:
    q = host.G2(host.Fp2(qx_a, qx_b), host.Fp2(qy_a, qy_b))
    return _build_schedule(q)


def line_schedule(q: "host.G2") -> LineSchedule:
    return _schedule_cached(q.x.a, q.x.b, q.y.a, q.y.b)


def _build_schedule(q: "host.G2") -> LineSchedule:
    """Replicates host.miller_loop's control flow on G2 only, recording
    A = (lam·xT − yT)/xi and B = −lam/xi per line (host math; runs once
    per issuer and is cached)."""
    xi_inv = host.XI.inv()
    state = {"t": q}
    steps: List[Tuple[bool, "host.Fp2", "host.Fp2"]] = []

    def rec(q2, is_add: bool) -> None:
        q1 = state["t"]
        assert not (q1.x == q2.x and (q1.y + q2.y).is_zero()), \
            "degenerate (vertical) line in pairing schedule"
        if q1 == q2:
            lam = (q1.x.sqr() * 3) * (q1.y * 2).inv()
        else:
            lam = (q2.y - q1.y) * (q2.x - q1.x).inv()
        A = (lam * q1.x - q1.y) * xi_inv
        Bc = -lam * xi_inv
        x3 = lam.sqr() - q1.x - q2.x
        state["t"] = host.G2(x3, lam * (q1.x - x3) - q1.y)
        steps.append((is_add, A, Bc))

    e = abs(6 * host.U + 2)
    for bit in bin(e)[3:]:
        rec(state["t"], False)
        if bit == "1":
            rec(q, True)
    # 6u+2 < 0 for this curve: conjugate f (device side) and negate T
    assert 6 * host.U + 2 < 0
    state["t"] = state["t"].neg()
    n_main = len(steps)
    q1f = host.g2_frobenius(q)
    q2f = host.g2_frobenius(q1f).neg()
    rec(q1f, True)
    rec(q2f, True)
    main, corr = steps[:n_main], steps[n_main:]
    return LineSchedule(
        is_add=np.array([s[0] for s in main], np.bool_),
        A=np.stack([_mont_fp2_np(s[1]) for s in main]),
        B=np.stack([_mont_fp2_np(s[2]) for s in main]),
        corr_A=np.stack([_mont_fp2_np(s[1]) for s in corr]),
        corr_B=np.stack([_mont_fp2_np(s[2]) for s in corr]),
    )


# ---------------------------------------------------------------------------
# Device: Miller loop + final exponentiation
# ---------------------------------------------------------------------------

def miller_batch(xp_m, yp_m, sched: LineSchedule):
    """Batched Miller loop: (K, batch) Montgomery G1 coords against one
    precomputed schedule.  One lax.scan step = Fp12 sqr (skipped via
    select on add-steps) + sparse line mul."""
    import jax
    import jax.numpy as jnp

    f = f12_one(xp_m)

    def body(f, step):
        is_add, A, B = step
        fsq = f12_sqr(f)
        f = jax.tree_util.tree_map(
            lambda a, b: jnp.where(is_add, a, b), f, fsq)
        Bxp = f2_mul_fp((B[0], B[1]), xp_m)
        f = f12_mul_line(f, yp_m, (A[0], A[1]), Bxp)
        return f, None

    f, _ = jax.lax.scan(
        body, f,
        (jnp.asarray(sched.is_add), jnp.asarray(sched.A),
         jnp.asarray(sched.B)))
    f = f12_conj(f)                      # 6u+2 < 0
    for i in range(2):                   # Frobenius correction lines
        A = tuple(jnp.asarray(sched.corr_A[i]))
        B = tuple(jnp.asarray(sched.corr_B[i]))
        f = f12_mul_line(f, yp_m, A, f2_mul_fp(B, xp_m))
    return f


def _pow_abs_u(f):
    """f^|u| via a static-bit square-and-multiply lax.scan (f must be
    in the cyclotomic subgroup; 63 uniform steps)."""
    import jax
    import jax.numpy as jnp
    e = abs(host.U)
    nbits = e.bit_length()
    bits = np.array([(e >> (nbits - 1 - i)) & 1 for i in range(nbits)],
                    np.bool_)
    acc = f12_one(f[0][0][0])

    def body(acc, bit):
        acc = f12_sqr(acc)
        withmul = f12_mul(acc, f)
        acc = jax.tree_util.tree_map(
            lambda w, a: jnp.where(bit, w, a), withmul, acc)
        return acc, None

    acc, _ = jax.lax.scan(body, acc, jnp.asarray(bits))
    return acc


def _pow_u(f):
    """f^u (u < 0): conj of f^|u| — cyclotomic inverse is conjugation."""
    assert host.U < 0
    return f12_conj(_pow_abs_u(f))


def final_exp_batch(f):
    """f^((p^12-1)/r): easy part, then the DSD u-chain hard part
    (KERNEL_PLAN.md §3 — NOT the naive 766-bit exponent)."""
    # easy: f^(p^6-1) then ^(p^2+1)
    f = f12_mul(f12_conj(f), f12_inv(f))
    f = f12_mul(f12_frobenius(f12_frobenius(f)), f)
    # hard part (Devegili–Scott–Dominguez)
    fu = _pow_u(f)
    fu2 = _pow_u(fu)
    fu3 = _pow_u(fu2)
    fp = f12_frobenius(f)
    fp2 = f12_frobenius(fp)
    fp3 = f12_frobenius(fp2)
    y0 = f12_mul(f12_mul(fp, fp2), fp3)
    y1 = f12_conj(f)
    y2 = f12_frobenius(f12_frobenius(fu2))
    y3 = f12_conj(f12_frobenius(fu))
    y4 = f12_conj(f12_mul(fu, f12_frobenius(fu2)))
    y5 = f12_conj(fu2)
    y6 = f12_conj(f12_mul(fu3, f12_frobenius(fu3)))
    t0 = f12_mul(f12_mul(f12_sqr(y6), y4), y5)
    t1 = f12_mul(f12_mul(y3, y5), t0)
    t0 = f12_mul(t0, y2)
    t1 = f12_sqr(f12_mul(f12_sqr(t1), t0))
    t0 = f12_mul(t1, y1)
    t1 = f12_mul(t1, y0)
    t0 = f12_sqr(t0)
    return f12_mul(t0, t1)


# ---------------------------------------------------------------------------
# The verify surface
# ---------------------------------------------------------------------------

def _g1_batch_to_mont_np(points) -> Tuple[np.ndarray, np.ndarray]:
    """[host.G1] -> two (K, batch) canonical Montgomery limb arrays
    (the device layout: limb axis first)."""
    xs = np.stack([_mont_np(p.x) for p in points], axis=-1)
    ys = np.stack([_mont_np(p.y) for p in points], axis=-1)
    return np.ascontiguousarray(xs), np.ascontiguousarray(ys)


@functools.lru_cache(maxsize=8)
def _check_fn():
    import jax

    def run(ax, ay, bx, by, s1_is_add, s1_A, s1_B, s1_cA, s1_cB,
            s2_is_add, s2_A, s2_B, s2_cA, s2_cB):
        s1 = LineSchedule(s1_is_add, s1_A, s1_B, s1_cA, s1_cB)
        s2 = LineSchedule(s2_is_add, s2_A, s2_B, s2_cA, s2_cB)
        ml = f12_mul(miller_batch(ax, ay, s1), miller_batch(bx, by, s2))
        return f12_is_one(final_exp_batch(ml))

    return jax.jit(run)


@functools.lru_cache(maxsize=8)
def _miller_pair_fn():
    import jax

    def run(ax, ay, bx, by, s1_is_add, s1_A, s1_B, s1_cA, s1_cB,
            s2_is_add, s2_A, s2_B, s2_cA, s2_cB):
        s1 = LineSchedule(s1_is_add, s1_A, s1_B, s1_cA, s1_cB)
        s2 = LineSchedule(s2_is_add, s2_A, s2_B, s2_cA, s2_cB)
        return f12_mul(miller_batch(ax, ay, s1), miller_batch(bx, by, s2))

    return jax.jit(run)


def _use_split_finalexp() -> bool:
    """Whether to run the final exponentiation EAGERLY on the jitted
    Miller output instead of one fused jitted program.

    Jitting final_exp_batch costs >9 min of XLA compile on the CPU
    backend (eager dispatch ~3 min; test_fp256bn_dev.py's in-suite
    differential runs exactly this split), so the split is the default
    off-chip.  On TPU the fused program is the performance path;
    FABRIC_MOD_TPU_SPLIT_FINALEXP=0/1 overrides either way for A/B."""
    from fabric_mod_tpu.utils import knobs
    env = knobs.get_str("FABRIC_MOD_TPU_SPLIT_FINALEXP")
    if env in ("0", "1"):
        return env == "1"
    import jax
    return jax.default_backend() == "cpu"


def pairing_check_batch(a_points, q1: "host.G2",
                        b_points, q2: "host.G2") -> np.ndarray:
    """(batch,) bool: e(A_i, Q1) * e(B_i, Q2) == 1 for each i.

    For idemix Ver's `e(A', W) == e(Abar, g2)` pass B_i = −Abar_i
    (negation is host-side).  Q1/Q2 schedules are cached per point —
    the per-issuer precompute amortizes across every batch."""
    assert len(a_points) == len(b_points)
    s1, s2 = line_schedule(q1), line_schedule(q2)
    ax, ay = _g1_batch_to_mont_np(a_points)
    bx, by = _g1_batch_to_mont_np(b_points)
    sched_args = (s1.is_add, s1.A, s1.B, s1.corr_A, s1.corr_B,
                  s2.is_add, s2.A, s2.B, s2.corr_A, s2.corr_B)
    if _use_split_finalexp():
        ml = _miller_pair_fn()(ax, ay, bx, by, *sched_args)
        out = f12_is_one(final_exp_batch(ml))      # eager by design
    else:
        out = _check_fn()(ax, ay, bx, by, *sched_args)
    return np.asarray(out)


def pairing_batch(p_points, q: "host.G2"):
    """Batched full pairings e(P_i, Q) as device Fp12 values — used by
    the differential tests against the host implementation."""
    import jax
    sched = line_schedule(q)
    xs, ys = _g1_batch_to_mont_np(p_points)

    @jax.jit
    def run(xp, yp):
        return final_exp_batch(miller_batch(xp, yp, sched))

    return run(xs, ys)


def f12_to_host(dev_f12, index: int = 0) -> "host.Fp12":
    """One batch element of a device Fp12 -> host Fp12 (for tests)."""
    def fp_of(x):
        canon = limbs.canonical(np.asarray(x)[:, index], SPEC)
        v = limbs.limbs_to_int(np.asarray(canon))
        return v * pow(_R, -1, host.P) % host.P

    (c00, c01, c02), (c10, c11, c12) = dev_f12
    def fp2_of(t):
        return host.Fp2(fp_of(t[0]), fp_of(t[1]))
    return host.Fp12(
        host.Fp6(fp2_of(c00), fp2_of(c01), fp2_of(c02)),
        host.Fp6(fp2_of(c10), fp2_of(c11), fp2_of(c12)))
