"""Pallas-fused Shamir ladder: the whole scalar-mult loop in VMEM.

The XLA version of the ladder (ops/p256.shamir_ladder) materializes
every intermediate limb array to HBM between fusions — measured to be
the throughput ceiling at the XLA level (ROUND3_NOTES "kernel perf
findings": long element-wise chains run at ~0.07 Tops/s because they
are HBM-materialization-bound).  This kernel keeps the accumulator,
the per-lane Q window table, and every Montgomery intermediate in
VMEM for the full 64-window ladder.

Structure (designed around the Mosaic failure modes catalogued in
round 3 — no giant concats, no scratch-slice accumulation, no
unrolled vreg lists, no dynamic sublane indexing):

* grid = (batch_tiles, N_WINDOWS); TPU grids execute sequentially
  with the LAST axis minor, so for one batch tile the 64 window steps
  run in order sharing VMEM scratch (the standard accumulator
  pattern).
* window selections arrive pre-tiled via BlockSpec index maps — the
  kernel never indexes by a loop variable;
* the Q window table (16 points, built once per tile at window 0)
  lives in three (TABLE*K, T) f32 scratch buffers; selects are
  one-hot multiply-reduces;
* the G table is a host constant folded in with a precision-pinned
  dot;
* all field math is ops/limbs9 — inside the kernel the sequential
  low-carry unrolls to static row indices
  (limbs9.set_unroll_low_carry, thread-local).

TWO ladder schedules share the kernel skeleton, selected by the same
env knobs as the XLA cores (the PALLAS x MIXED_ADD composition
matrix, ops/p256._select_core):

* `pallas_ladder` — the original all-projective schedule, numerically
  IDENTICAL to p256.shamir_ladder (same formulas, same order).
* `pallas_ladder_mixed` — the affine-table mixed-addition schedule
  (p256.shamir_ladder_mixed ported into VMEM, the PR-1 follow-up
  ROADMAP.md named): at window 0 the per-lane Q table is built through
  the shared projective schedule and normalized AFFINE by one
  Montgomery simultaneous inversion (limbs9.inv_mont_many with the
  scan-free p256.inv_mont_p_chain — Mosaic cannot materialize the
  generic inversion's captured exponent-bit constant), dropping the
  Z plane: VMEM scratch shrinks from three (TABLE*K, tile) table
  buffers to two ((TABLE-1)*K, tile), every window select moves one
  fewer plane, and all 128 table-adds take the cheaper complete MIXED
  formula (RCB alg. 5, 11+2 muls vs 12+2).  Zero windows keep-select
  around the add exactly like the XLA mixed ladder.

Both are differentially tested in interpret mode; flip on in
production with FABRIC_MOD_TPU_PALLAS=1 (+ FABRIC_MOD_TPU_MIXED_ADD=1
for the mixed schedule) once on-chip measurement confirms the win —
`bench.py --metric diffverify` reports the on-chip mixed-vs-projective
A/B alongside the verdict differential.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from fabric_mod_tpu.ops import limbs9 as limbs
from fabric_mod_tpu.ops import p256
from fabric_mod_tpu.ops.limbs9 import K
from fabric_mod_tpu.ops.p256 import (
    N_WINDOWS, TABLE, _consts, _g_table, point_add, point_double)

_F = jnp.float32


def _one_hot(sel: jnp.ndarray, t: int, rows: int = TABLE) -> jnp.ndarray:
    """(T,) int32 -> (rows, T) f32 one-hot via 2D iota (Mosaic needs
    >= 2D iotas; jax.nn.one_hot can emit 1D).  Out-of-range selects
    (e.g. the mixed ladder's sel-1 == -1 for zero windows) yield an
    all-zero column — exactly the keep-select contract."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (rows, t), 0)
    return (iota == sel[None, :]).astype(_F)


def _ladder_kernel(sel1_ref, sel2_ref, qx_ref, qy_ref,
                   colsum_ref, colsum_sqr_ref, npmat_ref, pmat_ref,
                   onemont_ref, bm_ref, gtab_ref,
                   xo_ref, yo_ref, zo_ref,
                   qtx_ref, qty_ref, qtz_ref,
                   accx_ref, accy_ref, accz_ref):
    import jax.experimental.pallas as pl

    fp, _fn, _b_m_np, _gx, _gy = _consts()
    t = qx_ref.shape[1]
    nw = pl.program_id(1)

    # Pallas kernels may not capture array constants; the limb layer's
    # fold matrices arrive as inputs and are routed into limbs9's
    # mont ops via the identity-keyed, THREAD-LOCAL constant hook
    # (limbs9.set_const_lookup, trace-time).
    const_map = {
        id(limbs._COLSUM): colsum_ref[...],
        id(limbs._COLSUM_SQR): colsum_sqr_ref[...],
        id(fp.np_mat): npmat_ref[...],
        id(fp.p_mat): pmat_ref[...],
    }
    old_hook = limbs.get_const_lookup()
    limbs.set_const_lookup(lambda arr: const_map.get(id(arr)))
    try:
        b_m = bm_ref[...]                            # (K, 1)
        one_m = jnp.broadcast_to(onemont_ref[...], (K, t))
        zero = jnp.zeros((K, t), _F)

        @pl.when(nw == 0)
        def _init():
            # per-lane window table, shared schedule with the XLA
            # ladder (p256.build_q_table)
            q1 = (qx_ref[...], qy_ref[...], one_m)
            qtab = p256.build_q_table(q1, (zero, one_m, zero), fp,
                                      b_m)
            qtx_ref[...] = jnp.concatenate([pt[0] for pt in qtab],
                                           axis=0)
            qty_ref[...] = jnp.concatenate([pt[1] for pt in qtab],
                                           axis=0)
            qtz_ref[...] = jnp.concatenate([pt[2] for pt in qtab],
                                           axis=0)
            accx_ref[...] = zero
            accy_ref[...] = one_m
            accz_ref[...] = zero

        acc = (accx_ref[...], accy_ref[...], accz_ref[...])
        # WINDOW doublings (unrolled: 4 copies trace once per kernel,
        # not per window — the window loop is the grid)
        for _ in range(p256.WINDOW):
            acc = point_double(acc, fp, b_m)
        # Q-table select: one-hot reduce over the VMEM-resident table
        oh_q = _one_hot(sel2_ref[0], t)[:, None]     # (TABLE, 1, T)
        qsel = tuple(
            jnp.sum(oh_q * ref[...].reshape(TABLE, K, t), axis=0)
            for ref in (qtx_ref, qty_ref, qtz_ref))
        acc = point_add(acc, qsel, fp, b_m)
        # G-table select (precision-pinned: limbs reach 511)
        oh_g = _one_hot(sel1_ref[0], t)
        gt = gtab_ref[...]                           # (3*K, TABLE)
        gsel = tuple(
            jax.lax.dot_general(gt[c * K:(c + 1) * K], oh_g,
                                (((1,), (0,)), ((), ())),
                                precision=limbs.PRECISION)
            for c in range(3))
        acc = point_add(acc, gsel, fp, b_m)

        accx_ref[...], accy_ref[...], accz_ref[...] = acc

        @pl.when(nw == N_WINDOWS - 1)
        def _finish():
            xo_ref[...] = accx_ref[...]
            yo_ref[...] = accy_ref[...]
            zo_ref[...] = accz_ref[...]
    finally:
        limbs.set_const_lookup(old_hook)


def _ladder_kernel_mixed(sel1_ref, sel2_ref, qx_ref, qy_ref,
                         colsum_ref, colsum_sqr_ref, npmat_ref, pmat_ref,
                         onemont_ref, bm_ref, gtab_ref,
                         xo_ref, yo_ref, zo_ref,
                         qtx_ref, qty_ref,
                         accx_ref, accy_ref, accz_ref):
    """The affine-table mixed-addition schedule in VMEM: the Q table
    is normalized affine at window 0 (one simultaneous inversion) and
    held in TWO ((TABLE-1)*K, tile) scratch planes — no Z plane, no
    infinity row; zero windows keep-select around the add, exactly
    like p256.shamir_ladder_mixed (identical formulas, same order)."""
    import jax.experimental.pallas as pl

    fp, _fn, _b_m_np, _gx, _gy = _consts()
    t = qx_ref.shape[1]
    nw = pl.program_id(1)

    const_map = {
        id(limbs._COLSUM): colsum_ref[...],
        id(limbs._COLSUM_SQR): colsum_sqr_ref[...],
        id(fp.np_mat): npmat_ref[...],
        id(fp.p_mat): pmat_ref[...],
    }
    old_hook = limbs.get_const_lookup()
    limbs.set_const_lookup(lambda arr: const_map.get(id(arr)))
    try:
        b_m = bm_ref[...]                            # (K, 1)
        one_m = jnp.broadcast_to(onemont_ref[...], (K, t))
        zero = jnp.zeros((K, t), _F)

        @pl.when(nw == 0)
        def _init():
            # shared projective schedule (p256.build_q_table), then
            # ONE Montgomery simultaneous inversion drops the Z plane
            # (the scan-free chain: Mosaic cannot materialize the
            # generic inversion's captured bit-array constant)
            q1 = (qx_ref[...], qy_ref[...], one_m)
            qtab = p256.build_q_table(q1, (zero, one_m, zero), fp,
                                      b_m)[1:]
            zinv = limbs.inv_mont_many([pt[2] for pt in qtab], fp,
                                       inv=p256.inv_mont_p_chain)
            qtx_ref[...] = jnp.concatenate(
                [limbs.mont_mul(pt[0], zi, fp)
                 for pt, zi in zip(qtab, zinv)], axis=0)
            qty_ref[...] = jnp.concatenate(
                [limbs.mont_mul(pt[1], zi, fp)
                 for pt, zi in zip(qtab, zinv)], axis=0)
            accx_ref[...] = zero
            accy_ref[...] = one_m
            accz_ref[...] = zero

        def add_selected(acc, sel, p2):
            """Complete mixed add of the selected affine point; keep
            acc on sel == 0 (the affine table has no infinity row —
            the one-hot was all zero there)."""
            added = p256.point_add_mixed(acc, p2, fp, b_m)
            keep = (sel == 0)[None]
            return tuple(jnp.where(keep, a, n)
                         for a, n in zip(acc, added))

        acc = (accx_ref[...], accy_ref[...], accz_ref[...])
        for _ in range(p256.WINDOW):
            acc = point_double(acc, fp, b_m)
        # Q-table select: one-hot reduce over TWO VMEM planes (w-1
        # indexed; w == 0 yields an all-zero one-hot column)
        sel2 = sel2_ref[0]
        oh_q = _one_hot(sel2 - 1, t, rows=TABLE - 1)[:, None]
        acc = add_selected(acc, sel2, tuple(
            jnp.sum(oh_q * ref[...].reshape(TABLE - 1, K, t), axis=0)
            for ref in (qtx_ref, qty_ref)))
        # G-table select: affine constant table, precision-pinned MXU
        # one-hot matmul (limbs reach 511)
        sel1 = sel1_ref[0]
        oh_g = _one_hot(sel1 - 1, t, rows=TABLE - 1)
        gt = gtab_ref[...]                           # (2K, TABLE-1)
        acc = add_selected(acc, sel1, tuple(
            jax.lax.dot_general(gt[c * K:(c + 1) * K], oh_g,
                                (((1,), (0,)), ((), ())),
                                precision=limbs.PRECISION)
            for c in range(2)))

        accx_ref[...], accy_ref[...], accz_ref[...] = acc

        @pl.when(nw == N_WINDOWS - 1)
        def _finish():
            xo_ref[...] = accx_ref[...]
            yo_ref[...] = accy_ref[...]
            zo_ref[...] = accz_ref[...]
    finally:
        limbs.set_const_lookup(old_hook)


@functools.partial(jax.jit,
                   static_argnames=("tile", "interpret", "mixed"))
def _ladder_call(u1_w, u2_w, qx_m, qy_m, tile: int = 128,
                 interpret: bool = False, mixed: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch = qx_m.shape[1]
    if batch % tile != 0:
        # explicit raise, not assert: under python -O a stripped
        # assert would silently drop the remainder lanes and return
        # uninitialized output rows for them
        raise ValueError(f"batch {batch} not divisible by tile {tile}")
    grid = (batch // tile, N_WINDOWS)
    sel_spec = pl.BlockSpec((1, tile), lambda i, nw: (nw, i))
    limb_spec = pl.BlockSpec((K, tile), lambda i, nw: (0, i))

    def full(shape):
        return pl.BlockSpec(shape, lambda i, nw: (0, 0))

    fp, _fn, b_m_np, _gx, _gy = _consts()
    if mixed:
        g_aff = p256._g_table_affine()               # (2, TABLE-1, K)
        g_flat = np.concatenate([g_aff[c].T for c in range(2)],
                                axis=0).astype(np.float32)
        kernel = _ladder_kernel_mixed
        scratch = [
            pltpu.VMEM(((TABLE - 1) * K, tile), _F),  # q table x (affine)
            pltpu.VMEM(((TABLE - 1) * K, tile), _F),  # q table y (affine)
        ]
    else:
        g_tab = _g_table()                           # (3, TABLE, K)
        g_flat = np.concatenate([g_tab[c].T for c in range(3)],
                                axis=0).astype(np.float32)  # (3K, TABLE)
        kernel = _ladder_kernel
        scratch = [
            pltpu.VMEM((TABLE * K, tile), _F),       # q table x
            pltpu.VMEM((TABLE * K, tile), _F),       # q table y
            pltpu.VMEM((TABLE * K, tile), _F),       # q table z
        ]
    consts = (
        limbs._COLSUM, limbs._COLSUM_SQR,
        fp.np_mat, fp.p_mat,
        fp.one_mont.reshape(K, 1).astype(np.float32),
        np.asarray(b_m_np, np.float32).reshape(K, 1),
        g_flat,
    )

    old = limbs.get_unroll_low_carry()
    limbs.set_unroll_low_carry(True)       # static indices in-kernel
    try:
        out_shape = [jax.ShapeDtypeStruct((K, batch), _F)] * 3
        x, y, z = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[sel_spec, sel_spec, limb_spec, limb_spec]
                     + [full(c.shape) for c in consts],
            out_specs=[limb_spec] * 3,
            out_shape=out_shape,
            scratch_shapes=scratch + [
                pltpu.VMEM((K, tile), _F),           # acc x
                pltpu.VMEM((K, tile), _F),           # acc y
                pltpu.VMEM((K, tile), _F),           # acc z
            ],
            interpret=interpret,
        )(u1_w.astype(jnp.int32), u2_w.astype(jnp.int32), qx_m, qy_m,
          *(jnp.asarray(c) for c in consts))
    finally:
        limbs.set_unroll_low_carry(old)
    return x, y, z


def pallas_ladder(u1_w, u2_w, qx_m, qy_m, tile: int = 128,
                  interpret: bool = False):
    """Drop-in for p256.shamir_ladder (same signature + semantics)."""
    return _ladder_call(u1_w, u2_w, qx_m, qy_m, tile=tile,
                        interpret=interpret)


def pallas_ladder_mixed(u1_w, u2_w, qx_m, qy_m, tile: int = 128,
                        interpret: bool = False):
    """Drop-in for p256.shamir_ladder_mixed: identical formulas in the
    same order, so canonical outputs match the XLA mixed ladder bit
    for bit (and verdicts match the projective ladder — the
    representatives differ by a Z scale)."""
    return _ladder_call(u1_w, u2_w, qx_m, qy_m, tile=tile,
                        interpret=interpret, mixed=True)


def verify_core_pallas(e, r, s, qx, qy, rn_lt_p, tile: int = 128,
                       interpret: bool = False, mixed: bool = False):
    """p256._verify_core_impl with the VMEM-fused ladder (jit this
    per deployment; ops/p256._select_core wires it under
    FABRIC_MOD_TPU_PALLAS, with `mixed` from FABRIC_MOD_TPU_MIXED_ADD)."""
    ladder = functools.partial(
        pallas_ladder_mixed if mixed else pallas_ladder,
        tile=tile, interpret=interpret)
    return p256._verify_core_impl(e, r, s, qx, qy, rn_lt_p,
                                  ladder=ladder)
