"""The fault-point registry: every injection point, declared here.

``faults.point("name")`` seams are stringly-typed: a typo'd name in an
``FMT_FAULTS`` plan used to arm a rule that silently never fired — the
chaos run passed while injecting nothing.  Declaring every point in
this module (imported before the env-spec plan is armed) makes
``FaultPlan.validate()`` a complete check at arm time, and the fmtlint
``fault-points`` rule closes the other direction: a ``faults.point``
literal that is not declared here, or a declared point no production
seam references, fails the lint gate.

Tests arming synthetic points for framework units register them
scoped via :func:`declared_point` (a context manager) or pass
``validate=False`` where the point's absence is the subject under
test.
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Set

# One line per production seam; keep sorted.  The lint rule
# cross-checks both directions against the tree.
DECLARED_POINTS: Set[str] = {
    "bccsp.device.dispatch",
    "bccsp.device.probe",
    "bccsp.device.resolve",
    "commitpipe.commit",
    "commitpipe.stage",
    "deliver.failover.stream",
    "deliver.fanout",
    "deliver.stream",
    "dissemination.push",
    "dissemination.repair",
    "gossip.comm.drop",
    "gossip.comm.send",
    "orderer.admission.overload",
    "orderer.broadcast.stage",
    "orderer.raft.replicate",
    "orderer.raft.submit",
    "orderer.wal.crash",
    "orderer.wal.sync",
    "peer.ledger.crash",
    "peer.mvcc.vector",
    "sharding.dispatch",
}


def is_declared(name: str) -> bool:
    return name in DECLARED_POINTS


@contextlib.contextmanager
def declared_point(name: str) -> Iterator[str]:
    """Scoped synthetic declaration for framework unit tests."""
    added = name not in DECLARED_POINTS
    if added:
        DECLARED_POINTS.add(name)
    try:
        yield name
    finally:
        if added:
            DECLARED_POINTS.discard(name)
