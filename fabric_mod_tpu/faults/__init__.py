"""Fault-injection framework: named seams, deterministic triggers.

Armed by ``FMT_FAULTS`` (env) or programmatically; near-zero cost
unarmed — the seams live in production code permanently, the way the
FMT_RACECHECK guards do.  See faults/core.py for the grammar and the
trigger catalog (fire-on-Nth-call / seeded-probability / one-shot).
"""
from fabric_mod_tpu.faults.core import (FaultPlan, FaultRule,
                                        InjectedFault, active, arm,
                                        arm_spec, armed, current_plan,
                                        disarm, point)
from fabric_mod_tpu.faults.points import (DECLARED_POINTS,
                                          declared_point)

__all__ = [
    "InjectedFault", "FaultRule", "FaultPlan",
    "point", "arm", "arm_spec", "disarm", "active", "armed",
    "current_plan", "DECLARED_POINTS", "declared_point",
]
