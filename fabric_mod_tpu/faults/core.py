"""Deterministic, seedable fault injection: the tolerance-proof harness.

(reference evaluation model: Raft's leader-crash validation — Ongaro &
Ousterhout, ATC '14 §9.2 — crashes are INJECTED at chosen points and
recovery is asserted, rather than waited for; Fabric's own chaos
coverage lives in integration tests that kill orderers/peers
mid-stream.  PR 4 built the *detection* half of robustness
(FMT_RACECHECK); this package is the *tolerance* half's proof harness:
every retry/failover/degradation mechanism in the framework lands with
the injected fault that kills the old code and the test that shows the
new code survives it.)

Usage — production code declares **named injection points** at its
fault seams::

    from fabric_mod_tpu import faults
    ...
    faults.point("gossip.comm.send")       # raises InjectedFault when
                                           # an armed rule triggers

    if faults.point("gossip.comm.drop"):   # drop-mode rules return
        return False                       # True instead of raising

Unarmed (the default), ``point()`` is one module-attribute read and a
``None`` check — the FMT_RACECHECK cost model, so the seams stay in
production code permanently.

Plans are armed programmatically (tests)::

    plan = faults.FaultPlan().add("deliver.stream", nth=3)
    with faults.active(plan):
        ...                                # 3rd pass through the point
                                           # raises InjectedFault

or by environment for whole-process chaos runs::

    FMT_FAULTS="deliver.stream:error@n=3;gossip.comm.send:drop@p=0.2,seed=7"

Triggers are **deterministic**: fire-on-Nth-call (``n=K``, 1-based —
fires from the Kth pass on, so ``times`` caps apply), one-shot
(``once`` ≡ ``n=1``), or seeded probability (``p=F,seed=S`` — a
per-rule ``random.Random(S)``, so a given seed yields the same fire
pattern on every run).  ``times=T`` caps total fires (default 1 for
``n``/``once``, unlimited for ``p``).  ``kind=K`` labels the raised
fault's failure class — ``kind=device`` is what the bccsp circuit
breaker classifies as a device/XLA error.
"""
from __future__ import annotations

import contextlib
import functools
import random
import threading
from typing import Dict, List, Optional

from fabric_mod_tpu.faults import points as _points
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.utils import knobs as _knobs
from fabric_mod_tpu.concurrency.locks import RegisteredLock

_FIRED_OPTS = MetricOpts(
    "fabric", "faults", "injected_total",
    help="Faults fired by the injection framework, per point (nonzero "
         "outside chaos runs means FMT_FAULTS leaked into production).",
    label_names=("point",))


@functools.lru_cache(maxsize=None)
def _fired_counter():
    return default_provider().counter(_FIRED_OPTS)


class InjectedFault(Exception):
    """Raised at an armed injection point.

    `kind` labels the simulated failure class so classifiers route it
    like the real thing ("device" → the bccsp breaker's device-error
    classifier; "io" → transport retry paths; default "fault").
    """

    def __init__(self, point: str, kind: str = "fault"):
        super().__init__(f"injected fault at {point!r} (kind={kind})")
        self.point = point
        self.kind = kind


class FaultRule:
    """One armed rule: trigger (nth/probability) + action (error/drop)."""

    __slots__ = ("point", "mode", "kind", "nth", "p", "times", "exc",
                 "_rng", "calls", "fires")

    def __init__(self, point: str, mode: str = "error",
                 kind: str = "fault", nth: Optional[int] = None,
                 p: Optional[float] = None, seed: int = 0,
                 times: Optional[int] = None, exc=None):
        if mode not in ("error", "drop"):
            raise ValueError(f"unknown fault mode {mode!r}")
        if (nth is None) == (p is None):
            raise ValueError("exactly one trigger: nth=K or p=F")
        if nth is not None and nth < 1:
            raise ValueError("nth is 1-based")
        if p is not None and not (0.0 <= p <= 1.0):
            raise ValueError("p must be in [0, 1]")
        self.point = point
        self.mode = mode
        self.kind = kind
        self.nth = nth
        self.p = p
        # nth-triggers default to one-shot; probability rules keep
        # firing (their determinism lives in the seeded rng stream)
        self.times = times if times is not None else \
            (1 if nth is not None else None)
        self.exc = exc                     # optional custom factory
        self._rng = random.Random(seed)
        self.calls = 0                     # passes through the point
        self.fires = 0                     # times this rule triggered

    def evaluate(self) -> bool:
        """One pass through the point: did this rule trigger?  Caller
        holds the plan lock (counters + rng stream are shared state)."""
        self.calls += 1
        if self.times is not None and self.fires >= self.times:
            return False
        if self.nth is not None:
            # fires FROM the Nth pass on, capped by `times` (default
            # 1, i.e. exactly the Nth call) — equality would make
            # `n=K,times=T>1` silently under-inject T-1 faults
            hit = self.calls >= self.nth
        else:
            hit = self._rng.random() < self.p
        if hit:
            self.fires += 1
        return hit

    def make_exception(self) -> Exception:
        if self.exc is not None:
            return self.exc() if callable(self.exc) else self.exc
        return InjectedFault(self.point, self.kind)


class FaultPlan:
    """A set of rules keyed by injection point; armable as a unit."""

    def __init__(self):
        self._rules: Dict[str, List[FaultRule]] = {}
        self._lock = RegisteredLock("faults.core._lock")

    def add(self, point: str, mode: str = "error", kind: str = "fault",
            nth: Optional[int] = None, p: Optional[float] = None,
            seed: int = 0, times: Optional[int] = None,
            exc=None) -> "FaultPlan":
        """Add one rule; returns self for chaining.  Default trigger
        (neither nth nor p given) is ``nth=1`` — one-shot on first
        pass, the most common test shape."""
        if nth is None and p is None:
            nth = 1
        rule = FaultRule(point, mode=mode, kind=kind, nth=nth, p=p,
                         seed=seed, times=times, exc=exc)
        with self._lock:
            self._rules.setdefault(point, []).append(rule)
        return self

    def evaluate(self, point: str) -> Optional[FaultRule]:
        """The armed-path hit test: first triggering rule, or None."""
        with self._lock:
            rules = self._rules.get(point)
            if not rules:
                return None
            for rule in rules:
                if rule.evaluate():
                    return rule
        return None

    def fires(self, point: Optional[str] = None) -> int:
        """Total fires (per point, or across the plan) — tests assert
        the fault actually fired, so a renamed/removed seam fails the
        scenario instead of silently passing it."""
        with self._lock:
            rules = (self._rules.get(point, []) if point is not None
                     else [r for rs in self._rules.values() for r in rs])
            return sum(r.fires for r in rules)

    def calls(self, point: str) -> int:
        with self._lock:
            return sum(r.calls for r in self._rules.get(point, []))

    def validate(self) -> "FaultPlan":
        """Check every rule's point against the fault-point registry
        (faults/points.py); an unknown name raises immediately instead
        of arming a rule that silently never fires.  Returns self so
        the env-arming path chains it."""
        with self._lock:
            unknown = sorted(p for p in self._rules
                             if not _points.is_declared(p))
        if unknown:
            raise ValueError(
                f"fault plan names unknown injection point(s) "
                f"{unknown}: declared points live in "
                f"fabric_mod_tpu/faults/points.py "
                f"(known: {sorted(_points.DECLARED_POINTS)})")
        return self

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the FMT_FAULTS grammar:
        ``point:mode@trigger[,opt...][;rule...]`` where trigger is
        ``n=K`` | ``once`` | ``p=F`` and opts are ``seed=S``,
        ``times=T``, ``kind=K``.  Malformed rules raise — a chaos run
        with a typo'd plan must fail loudly, not run clean."""
        plan = cls()
        for raw in spec.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            try:
                head, _, trig = raw.partition("@")
                point, _, mode = head.partition(":")
                kw: dict = {"mode": mode or "error"}
                for part in (trig or "once").split(","):
                    part = part.strip()
                    if part == "once":
                        kw["nth"] = 1
                    elif part.startswith("n="):
                        kw["nth"] = int(part[2:])
                    elif part.startswith("p="):
                        kw["p"] = float(part[2:])
                    elif part.startswith("seed="):
                        kw["seed"] = int(part[5:])
                    elif part.startswith("times="):
                        kw["times"] = int(part[6:])
                    elif part.startswith("kind="):
                        kw["kind"] = part[5:]
                    else:
                        raise ValueError(f"unknown option {part!r}")
                plan.add(point.strip(), **kw)
            except Exception as e:
                raise ValueError(
                    f"bad FMT_FAULTS rule {raw!r}: {e}") from e
        return plan


# -- the module-level arming gate (mirrors concurrency.core) ---------------

_plan: Optional[FaultPlan] = None


def armed() -> bool:
    return _plan is not None


def current_plan() -> Optional[FaultPlan]:
    return _plan


def arm(plan: FaultPlan) -> None:
    """Arm a plan process-wide (production chaos uses FMT_FAULTS)."""
    global _plan
    _plan = plan


def disarm() -> None:
    global _plan
    _plan = None


@contextlib.contextmanager
def active(plan: FaultPlan):
    """Scoped arming — the fault-scenario tests' toggle."""
    global _plan
    prev = _plan
    _plan = plan
    try:
        yield plan
    finally:
        _plan = prev


def point(name: str) -> bool:
    """The injection seam.  Unarmed: one None-check, returns False.
    Armed: if a rule for `name` triggers, raise its exception
    (mode="error") or return True (mode="drop" — the caller drops the
    unit of work it was about to process)."""
    plan = _plan
    if plan is None:
        return False
    rule = plan.evaluate(name)
    if rule is None:
        return False
    _fired_counter().with_labels(name).add(1)
    # flight-recorder breadcrumb + (rate-limited) auto-dump: a chaos
    # run's failure report shows WHAT the system was doing around each
    # injected fault, not just that one fired (FMT_TRACE armed only)
    from fabric_mod_tpu.observability import tracing
    tracing.note_event("fault", f"{name} (kind={rule.kind})")
    tracing.auto_dump(f"fault[{name}]")
    if rule.mode == "error":
        raise rule.make_exception()
    return True


def arm_spec(spec: str) -> FaultPlan:
    """Parse + VALIDATE + arm an FMT_FAULTS-grammar plan: the
    production chaos path.  A typo'd point name raises here, at arm
    time, instead of running a chaos plan that injects nothing."""
    plan = FaultPlan.from_spec(spec).validate()
    arm(plan)
    return plan


_env_spec = _knobs.get_str("FMT_FAULTS")
if _env_spec:
    arm_spec(_env_spec)
