"""Signed gossip message helpers.

(reference: gossip/protoext/signing.go:209 — every gossip message
travels as an envelope whose payload is signed by the sender and
verified against the sender's identity via the MCS.)
"""
from __future__ import annotations

from typing import Callable, Optional

from fabric_mod_tpu.protos import messages as m


def sign_message(msg: m.GossipMessage, signer) -> m.GossipEnvelope:
    payload = msg.encode()
    return m.GossipEnvelope(payload=payload,
                            signature=signer.sign_message(payload))


def verify_envelope(env: m.GossipEnvelope,
                    verify: Callable[[bytes, bytes], bool]
                    ) -> Optional[m.GossipMessage]:
    """-> decoded message if `verify(payload, signature)` holds, else
    None (fail-closed)."""
    if not env.payload or not env.signature:
        return None
    if not verify(env.payload, env.signature):
        return None
    try:
        return m.GossipMessage.decode(env.payload)
    except Exception:
        return None
