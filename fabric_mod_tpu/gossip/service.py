"""Gossip service binding: election-driven deliver ownership.

(reference: gossip/service/gossip_service.go:556 — InitializeChannel
hands the deliver client to the leader-election service so exactly ONE
peer per org pulls from the ordering service while the others receive
blocks via gossip state transfer; leadership changes start/stop the
client.)

Composition per channel:

  LeaderElectionService (over discovery's alive view)
        │ on_change(is_leader)
        ▼
  DeliverClient(channel, deliver_source)   — started when elected
        │ on_commit(block)
        ▼
  GossipNode.gossip_block                  — epidemic fan-out to the
                                             non-leaders' state buffers

A demoted leader stops its client; a promoted peer starts one from the
channel's current height.  Non-leaders commit through the gossip state
provider (in-order payload buffer + anti-entropy), so a leader crash
costs one election interval, not a stalled channel.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from fabric_mod_tpu.gossip.election import LeaderElectionService
from fabric_mod_tpu.observability import get_logger
from fabric_mod_tpu.peer.deliverclient import DeliverClient
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.concurrency.locks import RegisteredLock

log = get_logger("gossip.service")


class GossipService:
    """One channel's gossip + election + deliver composition."""

    def __init__(self, node, deliver_source_factory: Callable[[], object],
                 static_leader: Optional[bool] = None,
                 election_interval_s: float = 0.5,
                 relay=None):
        """`node`: a started GossipNode.  `deliver_source_factory`:
        () -> a deliver source (FailoverDeliverSource in production,
        the in-process DeliverService in tests); called fresh on every
        promotion so a returning leader re-dials.  `static_leader`
        pins leadership (reference: the static org-leader mode).
        `relay`: a dissemination.RelayService replacing the epidemic
        push with tree-structured frame relay; auto-built when the
        FABRIC_MOD_TPU_RELAY knob is set."""
        self._node = node
        self._factory = deliver_source_factory
        self._interval = election_interval_s
        self._client: Optional[DeliverClient] = None
        self._client_thread: Optional[threading.Thread] = None
        self._client_halt: Optional[threading.Event] = None
        self._lock = RegisteredLock("gossip.service._lock")
        if relay is None:
            from fabric_mod_tpu.utils import knobs
            if knobs.get_bool("FABRIC_MOD_TPU_RELAY"):
                from fabric_mod_tpu.dissemination import RelayService
                relay = RelayService(node)
        self._relay = relay
        self.election = LeaderElectionService(
            node.pki_id,
            lambda: [mb.pki_id for mb in node.discovery.alive_members()],
            on_change=self._on_leadership,
            static=static_leader)

    @property
    def relay(self):
        """The dissemination RelayService, if composed (else None)."""
        return self._relay

    @property
    def is_leader(self) -> bool:
        return self.election.is_leader

    def start(self) -> None:
        # the state provider's drain/anti-entropy loop is what turns a
        # NON-leader's gossip receipts into commits — the service owns
        # it so every composed peer commits regardless of leadership
        self._node.state.start()
        if self._relay is not None:
            # hooks node.on_relay + spawns the push thread BEFORE any
            # leadership verdict: an interior peer must already accept
            # relayed frames when the root starts pushing
            self._relay.start()
        # immediate first verdict BEFORE the loop spawns: once the
        # election loop runs, it owns ticking (concurrency.ThreadOwnership
        # — an external tick racing the loop can deliver on_change
        # transitions out of order, so the old start-then-tick order
        # was a real, now machine-checked, race)
        self.election.tick()
        self.election.start(self._interval)
        # the static-leader path never fires on_change (leadership is
        # fixed from construction) — start the client directly
        if self.election.is_leader:
            if self._relay is not None:
                self._relay.on_leadership(True)
            self._start_client()

    def stop(self) -> None:
        self.election.stop()
        self._stop_client()
        if self._relay is not None:
            self._relay.stop()
        self._node.state.stop()

    # -- leadership transitions -------------------------------------------
    def _on_leadership(self, is_leader: bool) -> None:
        if is_leader:
            log.info("%s: elected deliver leader", self._node.endpoint)
            if self._relay is not None:
                # promote BEFORE the client starts: the first commit's
                # on_leader_commit must find the relay rooted, or the
                # leading edge of the stream never enters the tree
                self._relay.on_leadership(True)
            self._start_client()
        else:
            log.info("%s: demoted from deliver leadership",
                     self._node.endpoint)
            self._stop_client()
            if self._relay is not None:
                self._relay.on_leadership(False)

    def _start_client(self) -> None:
        with self._lock:
            if self._client is not None:
                return
            channel = self._node._channel
            # with a relay composed, the leader's committed blocks feed
            # the dissemination tree (encoded once off the fanout ring)
            # instead of the sqrt-N epidemic push
            on_commit = (self._relay.on_leader_commit
                         if self._relay is not None
                         else self._node.gossip_block)
            client = DeliverClient(
                channel, self._factory(),
                on_commit=on_commit)
            self._client = client
            halt = threading.Event()
            self._client_halt = halt

            def run():
                # the reference's DeliverBlocks retry loop
                # (blocksprovider.go:141): while this peer HOLDS
                # deliver leadership, a died client is restarted from
                # the committed height with backoff — the client is
                # reusable by contract (each run() builds fresh pipe
                # workers).  Without the retry, one commit race or
                # injected stream fault killed the org's ONLY orderer
                # puller and every peer stalled at the tip forever
                # (found by the soak harness's churn runs).
                backoff = 0.2
                while not halt.is_set():
                    try:
                        client.run(idle_timeout_s=3600.0)
                        # clean end: either stop() landed (halt is
                        # set — the loop exits above) or the source
                        # went IDLE.  While this peer still leads,
                        # re-run from the committed height: a quiet
                        # stretch must not permanently orphan the
                        # org's only orderer puller
                        backoff = 0.2
                        halt.wait(0.05)
                    except Exception as e:
                        if halt.is_set():
                            return
                        log.warning(
                            "%s: deliver client died: %s — restarting "
                            "from committed height",
                            self._node.endpoint, e)
                        # flight-recorder breadcrumb: a restart storm
                        # shows up next to the block timelines it
                        # interleaved with
                        from fabric_mod_tpu.observability import tracing
                        tracing.note_event(
                            "deliver_restart",
                            f"{self._node.endpoint}: {e!r}")
                        halt.wait(backoff)
                        backoff = min(2.0, backoff * 2)

            t = RegisteredThread(target=run,
                                 name="gossip-deliver-restart",
                                 structure="gossip.service")
            self._client_thread = t
            t.start()

    def _stop_client(self) -> None:
        with self._lock:
            client, self._client = self._client, None
            thread, self._client_thread = self._client_thread, None
            halt, self._client_halt = self._client_halt, None
        if halt is not None:
            # BEFORE client.stop(): the restart loop must see the halt
            # when run() returns, or it would re-arm a stopped client
            halt.set()
        if thread is not None:
            # re-issue stop() until the thread exits: a restart
            # attempt that had already entered client.run() CLEARS the
            # client's stop flag (the reusable-client contract), so a
            # single stop() landing in that window would be erased and
            # a demoted peer would keep pulling forever — each re-stop
            # sticks until the next restart, and halt prevents any
            # further restart
            deadline = time.monotonic() + 10.0
            while thread.is_alive() and time.monotonic() < deadline:
                if client is not None:
                    client.stop()
                thread.join(timeout=0.5)
        elif client is not None:
            client.stop()
