"""The epidemic dissemination layer (reference: gossip/): membership
discovery, push fan-out, pull anti-entropy, identity mapping, and
in-order state transfer into the commit pipeline."""
from fabric_mod_tpu.gossip.comm import GossipComm, InProcNetwork  # noqa: F401
from fabric_mod_tpu.gossip.discovery import Discovery             # noqa: F401
from fabric_mod_tpu.gossip.identity import IdentityMapper         # noqa: F401
from fabric_mod_tpu.gossip.election import (                      # noqa: F401
    LeaderElectionService)
from fabric_mod_tpu.gossip.node import GossipNode                 # noqa: F401
from fabric_mod_tpu.gossip.service import GossipService           # noqa: F401
from fabric_mod_tpu.gossip.state import (                         # noqa: F401
    GossipStateProvider, PayloadsBuffer)
