"""Membership discovery: alive heartbeats + dead-peer expiry.

(reference: gossip/discovery/discovery_impl.go — periodicalSendAlive
at :759, periodicalCheckAlive at :697, expireDeadMembers at :710,
handleAliveMessage's incarnation/seq freshness logic at :497.)

Deterministic core + optional background thread: `tick_send_alive` /
`tick_check_alive(now)` drive the logic directly in tests (the
reference manipulates clocks for the same reason); `start()` wraps
them in a daemon thread for live nodes.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class MemberInfo:
    __slots__ = ("member", "peertime", "last_seen")

    def __init__(self, member: m.GossipMember, peertime: m.PeerTime,
                 last_seen: float):
        self.member = member
        self.peertime = peertime
        self.last_seen = last_seen


def _fresher(a: m.PeerTime, b: m.PeerTime) -> bool:
    """Is a strictly fresher than b (reference: the incarnation
    then-sequence comparison)."""
    if a.inc_num != b.inc_num:
        return a.inc_num > b.inc_num
    return a.seq_num > b.seq_num


class Discovery:
    def __init__(self, self_member: m.GossipMember, identity: bytes,
                 comm, expiry_s: float = 5.0,
                 on_expire: Optional[Callable[[bytes], None]] = None,
                 clock: Optional[Callable[[], float]] = None):
        self._self = self_member
        self._self_pki = self_member.pki_id
        self._identity = identity
        self._comm = comm
        self.expiry_s = expiry_s
        self._on_expire = on_expire
        # injectable liveness clock (tests drive expiry via `now=` or
        # a fake clock; the default is wall time)
        self._clock = clock if clock is not None else time.time
        self._inc = int(self._clock() * 1000)
        self._seq = 0
        self._lock = RegisteredLock("gossip.discovery._lock")
        self._members: Dict[bytes, MemberInfo] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- views -----------------------------------------------------------
    def alive_members(self) -> List[m.GossipMember]:
        with self._lock:
            return [info.member for info in self._members.values()]

    def alive_endpoints(self) -> List[str]:
        return [mb.endpoint for mb in self.alive_members()]

    # -- the two periodic duties ----------------------------------------
    def make_alive(self) -> m.GossipMessage:
        self._seq += 1
        return m.GossipMessage(alive_msg=m.AliveMessage(
            membership=self._self,
            timestamp=m.PeerTime(inc_num=self._inc, seq_num=self._seq),
            identity=self._identity))

    def tick_send_alive(self) -> None:
        """(reference: periodicalSendAlive :759)"""
        msg = self.make_alive()
        self._comm.broadcast(self.alive_endpoints(), msg)

    def tick_check_alive(self, now: Optional[float] = None) -> List[bytes]:
        """Expire members not heard from within expiry_s
        (reference: periodicalCheckAlive :697 + expireDeadMembers
        :710).  Returns expired PKI-IDs."""
        now = now if now is not None else self._clock()
        expired = []
        with self._lock:
            for pid, info in list(self._members.items()):
                if now - info.last_seen > self.expiry_s:
                    del self._members[pid]
                    expired.append(pid)
        for pid in expired:
            if self._on_expire is not None:
                self._on_expire(pid)
        return expired

    # -- inbound ---------------------------------------------------------
    def handle_alive(self, pki_id: bytes, alive: m.AliveMessage,
                     now: Optional[float] = None) -> bool:
        """(reference: handleAliveMessage :497 — only strictly fresher
        (incarnation, seq) pairs update liveness).  Returns whether
        the message advanced our view (fresh => worth forwarding)."""
        if alive.membership is None or alive.timestamp is None:
            return False
        if pki_id == self._self_pki:
            return False               # our own forwarded heartbeat
        now = now if now is not None else self._clock()
        with self._lock:
            cur = self._members.get(pki_id)
            if cur is not None and not _fresher(alive.timestamp,
                                                cur.peertime):
                return False
            self._members[pki_id] = MemberInfo(
                alive.membership, alive.timestamp, now)
        return True

    # -- background mode --------------------------------------------------
    def start(self, interval_s: float = 1.0) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                self.tick_send_alive()
                self.tick_check_alive()
        self._thread = RegisteredThread(target=loop,
                                        name="discovery-loop",
                                        structure="gossip.discovery")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
