"""The gossip node: push dissemination + pull anti-entropy + routing.

(reference: gossip/gossip/gossip_impl.go — handleMessage routing,
sqrt-N push fan-out, the message store dedup, and algo/pull.go's
hello/digest/request/update engine.)

One node per (peer, channel).  Blocks are MCS-verified (orderer
signature policy over the batch verifier) BEFORE entering the state
buffer — the same gate the deliver client applies
(internal/peer/gossip/mcs.go:124).
"""
from __future__ import annotations

import math
import random
import threading
from typing import Callable, Dict, List, Optional

from fabric_mod_tpu.gossip.comm import GossipComm, InProcNetwork
from fabric_mod_tpu.gossip.discovery import Discovery
from fabric_mod_tpu.gossip.identity import IdentityMapper, pki_id_of
from fabric_mod_tpu.gossip.protoext import sign_message, verify_envelope
from fabric_mod_tpu.gossip.state import GossipStateProvider
from fabric_mod_tpu.observability import MetricOpts, default_provider
from fabric_mod_tpu.peer.mcs import BlockVerificationError

# Reconciliation backlog (reference: gossip/privdata metrics) — how
# many committed-without-plaintext digests are still waiting for a
# peer to supply the data.
_MISSING_GAUGE = default_provider().new_gauge(MetricOpts(
    "gossip", "privdata", "reconciliation_backlog",
    "Missing private-data digests awaiting reconciliation",
    ("channel",)))
from fabric_mod_tpu.protos import messages as m


class GossipNode:
    def __init__(self, endpoint: str, signer, channel,
                 network: InProcNetwork, fanout: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        self.endpoint = endpoint
        self._signer = signer
        self._channel = channel          # peer.Channel (MCS + commit)
        self._network = network
        self._fanout = fanout
        self._rng = rng or random.Random()
        self._identity = signer.serialize()
        self.pki_id = pki_id_of(self._identity)
        self.mapper = IdentityMapper(channel.bundle().msp_manager,
                                     channel.verifier)
        self.mapper.put(self._identity)
        self.comm = GossipComm(endpoint, self.pki_id, network,
                               signer)
        self._members_by_pki: Dict[bytes, str] = {}
        self.discovery = Discovery(
            m.GossipMember(endpoint=endpoint, pki_id=self.pki_id),
            self._identity, self.comm)
        self.state = GossipStateProvider(
            channel, request_missing=self._pull_range,
            on_tick=self.pull_tick)
        # TTL'd duplicate suppression (reference: gossip msgstore) —
        # an entry is suppressed for exactly the TTL regardless of
        # arrival rate; a 200k-message burst cannot evict entries
        # seen moments earlier the way the old FIFO cap could
        from fabric_mod_tpu.gossip.msgstore import TTLMessageStore
        self._seen = TTLMessageStore(ttl_s=120.0)
        # the dissemination layer's receive hook (RelayService wires
        # BlockRelay.on_relay here); relay frames are dropped until a
        # relay is composed — a relay-less peer still converges via
        # the push epidemic + anti-entropy
        self.on_relay: Optional[Callable[[m.GossipMessage], None]] = None
        network.register(endpoint, self.on_message)

    # -- outbound ---------------------------------------------------------
    def _pick_peers(self, k: Optional[int] = None) -> List[str]:
        peers = [p for p in self.discovery.alive_endpoints()
                 if p != self.endpoint]
        if not peers:
            return []
        if k is None:
            # sqrt-N fan-out with the reference's small-net floor
            # (gossip defaults PropagatePeerNum=3)
            k = self._fanout or max(2, int(math.isqrt(len(peers))))
        self._rng.shuffle(peers)
        return peers[:k]

    def gossip_block(self, block: m.Block) -> None:
        """Push a block to ~sqrt(N) peers (reference: the emit/fan-out
        path of gossip_impl.go)."""
        nonce = self._rng.getrandbits(63)
        msg = m.GossipMessage(
            nonce=nonce, channel=self._channel.channel_id.encode(),
            data_msg=m.DataMessage(payload=m.GossipPayload(
                seq_num=block.header.number, data=block.encode())))
        self._remember_nonce(nonce)
        self.comm.broadcast(self._pick_peers(), msg)

    def _remember_nonce(self, nonce: int) -> bool:
        """Record a nonce; False when already seen within the TTL."""
        return self._seen.check_and_add(nonce)

    def join(self, bootstrap_endpoints: List[str]) -> None:
        """Announce ourselves to bootstrap peers."""
        msg = self.discovery.make_alive()
        self.comm.broadcast(
            [e for e in bootstrap_endpoints if e != self.endpoint], msg)

    # -- inbound routing (reference: gossip_impl.go handleMessage) -------
    def on_message(self, src_pki_id: bytes, env_bytes: bytes) -> None:
        try:
            env = m.GossipEnvelope.decode(env_bytes)
        except Exception:
            return
        msg = verify_envelope(
            env, lambda payload, sig:
            self.mapper.verify(src_pki_id, payload, sig)
            or self._verify_with_carried_identity(env, payload, sig))
        if msg is None:
            return
        if msg.alive_msg is not None:
            self._handle_alive(src_pki_id, msg.alive_msg)
        elif msg.data_msg is not None:
            self._handle_data(msg)
        elif msg.hello is not None:
            self._handle_hello(src_pki_id, msg)
        elif msg.data_dig is not None:
            self._handle_digest(src_pki_id, msg)
        elif msg.data_req is not None:
            self._handle_request(src_pki_id, msg)
        elif msg.data_update is not None:
            self._handle_update(msg)
        elif msg.private_data is not None:
            self._handle_private(msg)
        elif msg.pvt_req is not None:
            self._handle_pvt_request(src_pki_id, msg)
        elif msg.pvt_resp is not None:
            self._handle_pvt_response(msg)
        elif msg.relay_msg is not None:
            handler = self.on_relay
            if handler is not None:
                handler(msg)

    def _verify_with_carried_identity(self, env, payload, sig) -> bool:
        """Bootstrap: an alive message carries its own identity —
        admit it if the MSP validates it and the signature checks
        (reference: the identity learning on first contact)."""
        try:
            msg = m.GossipMessage.decode(env.payload)
        except Exception:
            return False
        if msg.alive_msg is None or not msg.alive_msg.identity:
            return False
        try:
            pid = self.mapper.put(msg.alive_msg.identity)
        except Exception:
            return False
        return self.mapper.verify(pid, payload, sig)

    def _handle_alive(self, src: bytes, alive: m.AliveMessage) -> None:
        pid = (pki_id_of(alive.identity) if alive.identity
               else (alive.membership.pki_id if alive.membership else b""))
        if not pid or pid == self.pki_id:
            return
        if alive.membership is not None:
            self._members_by_pki[pid] = alive.membership.endpoint
        if self.discovery.handle_alive(pid, alive):
            # fresh news travels (push membership epidemically)
            fwd = m.GossipMessage(alive_msg=alive)
            self.comm.broadcast(
                [e for e in self._pick_peers()
                 if e != (alive.membership.endpoint
                          if alive.membership else "")], fwd)

    def _handle_data(self, msg: m.GossipMessage) -> None:
        if not self._remember_nonce(msg.nonce):
            return                          # dedup (message store)
        payload = msg.data_msg.payload
        if payload is None:
            return
        try:
            block = m.Block.decode(payload.data)
            self._channel.mcs.verify_block(
                self._channel.channel_id, block)
        except (BlockVerificationError, Exception):
            return                          # unverifiable: drop, no relay
        if self.state.add_block(block):
            # forward fresh blocks (push epidemic)
            self.comm.broadcast(self._pick_peers(), msg)

    # -- private data distribution (reference: gossip/privdata/
    # -- distributor.go:458 — plaintext to ELIGIBLE peers only) ----------
    def distribute_pvt(self, txid: str, pvt_rwset,
                       eligible: Callable[[bytes], bool]) -> int:
        """Send a private write-set to ELIGIBLE alive peers only — the
        filter is mandatory (fail-closed: the reference's distributor
        always applies the collection AccessFilter; an optional filter
        would fail-open the confidentiality property this exists
        for).  Returns peers reached."""
        msg = m.GossipMessage(
            nonce=self._rng.getrandbits(63),
            channel=self._channel.channel_id.encode(),
            private_data=m.PvtDataElement(
                txid=txid, payload=pvt_rwset.encode()))
        sent = 0
        for member in self.discovery.alive_members():
            if member.endpoint == self.endpoint:
                continue
            ident = self.mapper.get(member.pki_id)
            if ident is None or not eligible(ident):
                continue
            if self.comm.send(member.endpoint, msg):
                sent += 1
        return sent

    def _handle_private(self, msg: m.GossipMessage) -> None:
        """Received plaintext goes to the transient store; the commit
        path hash-verifies it against the block before applying
        (reference: the coordinator's transient persist on
        dissemination).  Channel-checked; the store itself bounds
        growth against flooding."""
        pd = msg.private_data
        if not pd.txid or not pd.payload:
            return
        if msg.channel != self._channel.channel_id.encode():
            return                          # cross-channel leak guard
        try:
            pvt = m.TxPvtReadWriteSet.decode(pd.payload)
        except Exception:
            return
        self._channel.transient_store.persist(
            pd.txid, self._channel.ledger.height, pvt)

    def eligibility_by_policy(self, member_orgs_policy):
        """eligible(identity_bytes) closure for a collection's
        member_orgs_policy (SignaturePolicyEnvelope): org-principal
        check over the peer's identity — sufficient for membership
        (no signature to check at dissemination time; the reference's
        AccessFilter does the same principal-only evaluation)."""
        from fabric_mod_tpu.policy.manager import compile_policy_bytes
        bundle = self._channel.bundle()
        msp_mgr = bundle.msp_manager
        pol = compile_policy_bytes(member_orgs_policy.encode(), msp_mgr,
                                   bundle.sequence)

        def eligible(identity_bytes: bytes) -> bool:
            try:
                ident = msp_mgr.deserialize_identity(identity_bytes)
                # full validation (chain, expiry, CRLs) — a revoked
                # peer must stop receiving plaintext even though its
                # identity was admitted to the mapper earlier
                msp_mgr.validate(ident)
            except Exception:
                return False
            return pol.satisfied_by_principals([ident])
        return eligible

    # -- private data reconciliation (reference: gossip/privdata/
    # -- reconcile.go:339 + pull.go:727) ---------------------------------
    def reconcile_tick(self) -> int:
        """Ask a few random alive peers for private write-sets this
        peer committed hashes for but never received the plaintext of.
        Returns the number of digests requested."""
        ledger = self._channel.ledger
        if not hasattr(ledger, "missing_pvt"):
            return 0
        missing = ledger.missing_pvt()
        # backlog visibility: a long outage reconciles at most
        # `limit` digests per tick — operators need to see the queue
        # draining
        if hasattr(ledger, "missing_pvt_count"):
            _MISSING_GAUGE.with_labels(
                self._channel.channel_id).set(ledger.missing_pvt_count())
        if not missing:
            return 0
        digests = [m.PvtDataDigest(block_num=bn, tx_num=tn,
                                   namespace=ns, collection=coll)
                   for bn, tn, ns, coll in missing]
        req = m.GossipMessage(
            nonce=self._rng.getrandbits(63),
            channel=self._channel.channel_id.encode(),
            pvt_req=m.PvtDataRequest(nonce=self._rng.getrandbits(63),
                                     digests=digests))
        peers = self._pick_peers(3)
        if not peers:
            return 0
        self.comm.broadcast(peers, req)
        return len(digests)

    def _handle_pvt_request(self, src: bytes, msg: m.GossipMessage) -> None:
        """Serve missing-data requests — but ONLY to requesters whose
        identity satisfies the collection's member_orgs_policy (same
        fail-closed gate as dissemination; an ineligible peer learns
        nothing, not even emptiness vs refusal)."""
        if msg.channel != self._channel.channel_id.encode():
            return
        src_ep = self._members_by_pki.get(src)
        ident = self.mapper.get(src)
        if src_ep is None or ident is None:
            return
        ledger = self._channel.ledger
        if not hasattr(ledger, "get_pvt"):
            return
        eligible_cache: Dict = {}
        elements = []
        for dig in msg.pvt_req.digests:
            key = (dig.namespace, dig.collection)
            if key not in eligible_cache:
                pol = self._channel.collection_policy(*key)
                if pol is None:
                    eligible_cache[key] = lambda _b: False
                else:
                    eligible_cache[key] = self.eligibility_by_policy(pol)
            if not eligible_cache[key](ident):
                continue
            for ns, coll, kv in ledger.get_pvt(dig.block_num, dig.tx_num):
                if ns == dig.namespace and coll == dig.collection:
                    elements.append(m.PvtDataResponseElement(
                        digest=dig, rwset=kv.encode()))
        if not elements:
            return
        self.comm.send(src_ep, m.GossipMessage(
            nonce=self._rng.getrandbits(63),
            channel=self._channel.channel_id.encode(),
            pvt_resp=m.PvtDataResponse(nonce=msg.pvt_req.nonce,
                                       elements=elements)))

    def _handle_pvt_response(self, msg: m.GossipMessage) -> None:
        """Backfill returned write-sets; the ledger re-verifies each
        against the committed block's hashes, so a forged response is
        rejected there, not trusted here."""
        if msg.channel != self._channel.channel_id.encode():
            return
        ledger = self._channel.ledger
        if not hasattr(ledger, "reconcile_pvt"):
            return
        for el in msg.pvt_resp.elements:
            if el.digest is None or not el.rwset:
                continue
            try:
                kv = m.KVRWSet.decode(el.rwset)
            except Exception:
                continue
            ledger.reconcile_pvt(el.digest.block_num, el.digest.tx_num,
                                 el.digest.namespace,
                                 el.digest.collection, kv)

    # -- pull engine (reference: algo/pull.go) ----------------------------
    def pull_tick(self) -> None:
        """Send a hello to one random peer asking what blocks it has."""
        peers = self._pick_peers(1)
        if not peers:
            return
        nonce = self._rng.getrandbits(63)
        self.comm.send(peers[0], m.GossipMessage(
            nonce=nonce, hello=m.GossipHello(nonce=nonce)))

    def _pull_range(self, gap: range) -> None:
        peers = self._pick_peers(1)
        if not peers:
            return
        digests = [str(n).encode() for n in gap]
        self.comm.send(peers[0], m.GossipMessage(
            data_req=m.DataRequest(nonce=self._rng.getrandbits(63),
                                   digests=digests)))

    # hello answers carry at most this many trailing block digests:
    # the standing pull cadence must stay O(window), not O(height) —
    # a deeply-behind puller still converges (each update raises its
    # height, so successive pulls reveal successive windows), and the
    # anti-entropy gap path handles bulk catch-up once pushes arrive
    PULL_DIGEST_WINDOW = 64

    def _handle_hello(self, src: bytes, msg: m.GossipMessage) -> None:
        src_ep = self._members_by_pki.get(src)
        if src_ep is None:
            return
        height = self._channel.ledger.height
        lo = max(0, height - self.PULL_DIGEST_WINDOW)
        digests = [str(n).encode() for n in range(lo, height)]
        self.comm.send(src_ep, m.GossipMessage(
            data_dig=m.DataDigest(nonce=msg.hello.nonce,
                                  digests=digests)))

    def _handle_digest(self, src: bytes, msg: m.GossipMessage) -> None:
        src_ep = self._members_by_pki.get(src)
        if src_ep is None:
            return
        have = self._channel.ledger.height
        wanted = []
        for d in msg.data_dig.digests:      # peer-supplied: parse safely
            try:
                if int(d.decode()) >= have:
                    wanted.append(d)
            except (ValueError, UnicodeDecodeError):
                continue
        if not wanted:
            return
        self.comm.send(src_ep, m.GossipMessage(
            data_req=m.DataRequest(nonce=msg.data_dig.nonce,
                                   digests=wanted)))

    def _handle_request(self, src: bytes, msg: m.GossipMessage) -> None:
        src_ep = self._members_by_pki.get(src)
        if src_ep is None:
            return
        out = []
        for d in msg.data_req.digests:
            try:
                num = int(d.decode())
            except ValueError:
                continue
            block = self._channel.ledger.get_block_by_number(num)
            if block is None:
                continue
            inner = m.GossipMessage(
                nonce=self._rng.getrandbits(63),
                data_msg=m.DataMessage(payload=m.GossipPayload(
                    seq_num=num, data=block.encode())))
            out.append(sign_message(inner, self._signer))
        if out:
            self.comm.send(src_ep, m.GossipMessage(
                data_update=m.DataUpdate(nonce=msg.data_req.nonce,
                                         data=out)))

    def _handle_update(self, msg: m.GossipMessage) -> None:
        for env in msg.data_update.data:
            inner = verify_envelope(
                env, lambda payload, sig: True)  # block sigs checked next
            if inner is None or inner.data_msg is None:
                continue
            payload = inner.data_msg.payload
            try:
                block = m.Block.decode(payload.data)
                self._channel.mcs.verify_block(
                    self._channel.channel_id, block)
            except Exception:
                continue
            self.state.add_block(block)

    def stop(self) -> None:
        self._network.unregister(self.endpoint)
        self.discovery.stop()
        self.state.stop()
