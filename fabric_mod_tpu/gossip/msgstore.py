"""TTL'd seen-message store for duplicate suppression.

(reference: gossip/gossip/msgstore/msgs.go — messages expire by TTL,
not by count.  The previous FIFO cap meant a burst of 100k+ nonces
evicted entries seen moments earlier and re-admitted their duplicates;
with TTL semantics an entry is suppressed for exactly `ttl_s`
regardless of arrival rate.)

Implementation: time-bucketed sets.  Insertion lands in the current
bucket; membership scans the live buckets (a handful of set lookups);
whole expired buckets are dropped in O(1) — no per-entry timers.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Optional, Tuple
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class TTLMessageStore:
    """`max_entries` keeps the flood bound the old FIFO cap provided:
    past it, the OLDEST buckets are evicted early (best-effort under a
    deliberate flood — normal traffic never gets near it).  Time is
    monotonic by default so an NTP step can neither flush the store
    nor stall eviction."""

    def __init__(self, ttl_s: float = 120.0, n_buckets: int = 16,
                 max_entries: int = 1_000_000):
        if n_buckets < 2:
            raise ValueError("need at least 2 buckets")
        self._width = ttl_s / n_buckets
        self._n = n_buckets
        self._max = max_entries
        self._lock = RegisteredLock("gossip.msgstore._lock")
        self._count = 0
        self._buckets: Deque[Tuple[int, set]] = deque()

    def check_and_add(self, key, now: Optional[float] = None) -> bool:
        """True if `key` is NEW (and remember it); False if it was
        seen within the TTL."""
        now = time.monotonic() if now is None else now
        idx = int(now / self._width)
        with self._lock:
            # drop whole expired buckets from the left
            while self._buckets and self._buckets[0][0] <= idx - self._n:
                self._count -= len(self._buckets.popleft()[1])
            for _, entries in self._buckets:
                if key in entries:
                    return False
            while self._count >= self._max and len(self._buckets) > 1:
                self._count -= len(self._buckets.popleft()[1])
            if self._count >= self._max:
                # a single-bucket burst (everything arrived within one
                # bucket width) has nothing older to evict: refuse the
                # insert so the flood bound actually holds.  "Seen"
                # (False) is the safe answer — the store exists to
                # suppress re-forwarding, and a flooding burst is
                # exactly when re-forwarding must stop.
                return False
            if self._buckets and self._buckets[-1][0] == idx:
                self._buckets[-1][1].add(key)
            else:
                self._buckets.append((idx, {key}))
            self._count += 1
            return True

    def __len__(self) -> int:
        with self._lock:
            return self._count
