"""Gossip state transfer: in-order payload buffer feeding the commit
pipeline, with anti-entropy catch-up.

(reference: gossip/state/state.go — the payloads buffer + the
deliverPayloads loop at :583 popping blocks in sequence and
committing at :817; anti-entropy requests for missing ranges at
:583-838.)
"""
from __future__ import annotations

import heapq
import threading
from typing import Callable, Dict, List, Optional

from fabric_mod_tpu.protos import messages as m


class PayloadsBuffer:
    """Min-heap of blocks keyed by number; pop only when the next
    expected sequence is present (reference: the payloads buffer)."""

    def __init__(self, next_seq: int):
        self._heap: List = []
        self._have: set = set()
        self.next_seq = next_seq
        self._lock = threading.Lock()
        self.ready = threading.Condition(self._lock)

    def push(self, block: m.Block) -> bool:
        num = block.header.number
        with self._lock:
            if num < self.next_seq or num in self._have:
                return False               # stale/duplicate
            heapq.heappush(self._heap, (num, block.encode()))
            self._have.add(num)
            if num == self.next_seq:
                self.ready.notify_all()
            return True

    def pop_in_order(self) -> Optional[m.Block]:
        with self._lock:
            if self._heap and self._heap[0][0] == self.next_seq:
                num, raw = heapq.heappop(self._heap)
                self._have.discard(num)
                self.next_seq += 1
                return m.Block.decode(raw)
            return None

    def missing_range(self) -> Optional[range]:
        """The gap blocking progress, if any (for anti-entropy)."""
        with self._lock:
            if not self._heap:
                return None
            head = self._heap[0][0]
            if head == self.next_seq:
                return None
            return range(self.next_seq, head)


class GossipStateProvider:
    """Binds the buffer to a committer; the deliver loop commits
    blocks strictly in order (reference: state.go:583)."""

    def __init__(self, channel, request_missing: Optional[Callable] = None):
        self._channel = channel
        self.buffer = PayloadsBuffer(channel.ledger.height)
        self._request_missing = request_missing
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_block(self, block: m.Block) -> bool:
        """Verified block in (MCS check happens in the gossip node
        before this, reference: mcs.go VerifyBlock upstream)."""
        return self.buffer.push(block)

    def drain(self, max_blocks: int = 1000) -> int:
        """Commit everything poppable now; returns count."""
        n = 0
        while n < max_blocks:
            block = self.buffer.pop_in_order()
            if block is None:
                break
            self._channel.store_block(block)
            n += 1
        return n

    def anti_entropy_tick(self) -> Optional[range]:
        """If a gap blocks progress, ask for it
        (reference: the anti-entropy goroutine)."""
        gap = self.buffer.missing_range()
        if gap is not None and self._request_missing is not None:
            self._request_missing(gap)
        return gap

    # -- background mode --------------------------------------------------
    def start(self, interval_s: float = 0.05) -> None:
        """Idempotent: a second start() (e.g. two services composed
        over one node) does not spawn a second drain loop."""
        if self._thread is not None and self._thread.is_alive():
            return
        def loop():
            while not self._stop.wait(interval_s):
                self.drain()
                self.anti_entropy_tick()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.drain()
