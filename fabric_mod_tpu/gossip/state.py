"""Gossip state transfer: in-order payload buffer feeding the commit
pipeline, with anti-entropy catch-up.

(reference: gossip/state/state.go — the payloads buffer + the
deliverPayloads loop at :583 popping blocks in sequence and
committing at :817; anti-entropy requests for missing ranges at
:583-838.)

The background drain loop is EVENT-DRIVEN: `add_block` signals the
buffer's condition variable whenever the next in-order block becomes
poppable, so commit latency is wakeup latency, not a poll interval
(the old loop slept 50 ms between drains — an idle-latency floor per
block and idle CPU burn).  The anti-entropy tick keeps its own
interval, as in the reference's separate goroutine.

With FABRIC_MOD_TPU_COMMIT_PIPELINE set, drained blocks feed the
channel's shared PipelinedCommitter (peer/commitpipe.py) instead of
the synchronous store_block — stage(N+1) overlaps finish+commit(N).
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Dict, List, Optional

from fabric_mod_tpu.concurrency import (RegisteredLock,
                                        RegisteredThread, assert_joined)
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.protos import messages as m


class PayloadsBuffer:
    """Min-heap of blocks keyed by number; pop only when the next
    expected sequence is present (reference: the payloads buffer)."""

    def __init__(self, next_seq: int):
        self._heap: List = []
        self._have: set = set()
        self.next_seq = next_seq
        self._known_to = next_seq          # 1 past the highest num seen
        # registry-fed: the buffer lock nests inside the provider's
        # drain lock and around the commit pipe's locks — any future
        # inversion across those is a detected cycle, not a deadlock
        self._lock = RegisteredLock("gossip-payloads")
        self.ready = threading.Condition(self._lock)

    def push(self, block: m.Block) -> bool:
        num = block.header.number
        with self._lock:
            if num >= self._known_to:
                self._known_to = num + 1
            if num < self.next_seq or num in self._have:
                return False               # stale/duplicate
            heapq.heappush(self._heap, (num, block.encode()))
            self._have.add(num)
            if num == self.next_seq:
                self.ready.notify_all()
            return True

    def pop_in_order(self) -> Optional[m.Block]:
        with self._lock:
            if self._heap and self._heap[0][0] == self.next_seq:
                num, raw = heapq.heappop(self._heap)
                self._have.discard(num)
                self.next_seq += 1
                return m.Block.decode(raw)
            return None

    def wait_ready(self, timeout_s: Optional[float]) -> bool:
        """Block until the next in-order block is poppable (True) or
        the timeout lapses (False).  `wake()` also returns the waiter
        (spurious wakeups are fine — the drain loop re-checks)."""
        with self._lock:
            if self._heap and self._heap[0][0] == self.next_seq:
                return True
            return self.ready.wait(timeout=timeout_s)

    def wake(self) -> None:
        """Wake any wait_ready waiter (shutdown, external prod)."""
        with self._lock:
            self.ready.notify_all()

    def resync(self, next_seq: int) -> None:
        """Rewind the expected sequence (lowering only): a popped
        block that never actually committed (its committer failed) is
        gone from the heap, so without the rewind every redelivery
        would be rejected as stale and the gap would be invisible to
        anti-entropy — the channel would stall permanently.  Buffered
        future blocks stay valid."""
        with self._lock:
            if next_seq < self.next_seq:
                self.next_seq = next_seq

    def missing_range(self) -> Optional[range]:
        """The gap blocking progress, if any (for anti-entropy).  An
        empty heap still reports a gap when a block we KNOW exists
        (it was pushed — e.g. popped into a committer that failed,
        then resync()'d) is missing: without the `_known_to` bound
        that block would be invisible here and, if gossip never
        redelivers it, the channel would stall at the rewound
        height."""
        with self._lock:
            head = self._heap[0][0] if self._heap else self._known_to
            if head <= self.next_seq:
                return None
            return range(self.next_seq, head)


class GossipStateProvider:
    """Binds the buffer to a committer; the deliver loop commits
    blocks strictly in order (reference: state.go:583)."""

    def __init__(self, channel, request_missing: Optional[Callable] = None,
                 on_tick: Optional[Callable] = None):
        """`on_tick` runs on the anti-entropy cadence alongside the
        gap check (the node wires its pull engine here): a block lost
        at the chain TAIL leaves the payload buffer gapless — only a
        periodic hello/digest pull can discover it, so without this
        hook a dropped final push stalls an idle peer forever (found
        by the soak harness's background-drop chaos plan)."""
        self._channel = channel
        self.buffer = PayloadsBuffer(channel.ledger.height)
        self._request_missing = request_missing
        self._on_tick = on_tick
        self._tick_seq = -1                # buffer progress marker
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes pop->commit sequences: two concurrent drain()
        # callers interleaving pops would submit blocks out of order
        self._drain_lock = RegisteredLock("gossip-state-drain")
        self._active_pipe = None           # the pipe drain last fed

    def add_block(self, block: m.Block) -> bool:
        """Verified block in (MCS check happens in the gossip node
        before this, reference: mcs.go VerifyBlock upstream).  Pushing
        the next in-order block wakes the background drain loop."""
        return self.buffer.push(block)

    def _commit_pipeline(self):
        """The channel's shared PipelinedCommitter, when enabled (only
        peer.Channel exposes one; bare committer stubs in tests
        don't)."""
        getter = getattr(self._channel, "commit_pipeline", None)
        return getter() if getter is not None else None

    def _refresh_pipe(self):
        """Fetch the channel pipe; on a NEW pipe (first use, or the
        channel rebuilt a failed one) rewind the buffer to the
        committed height — blocks handed to a previous pipe but never
        committed are not coming back, and without the rewind both
        gossip redelivery and anti-entropy would treat the lost range
        as already handled.  Caller holds _drain_lock."""
        pipe = self._commit_pipeline()
        if pipe is not self._active_pipe:
            self.buffer.resync(self._channel.ledger.height)
            self._active_pipe = pipe
        return pipe

    def drain(self, max_blocks: int = 1000) -> int:
        """Commit everything poppable now; returns count.  With the
        commit pipeline enabled the blocks are SUBMITTED in order and
        commit asynchronously — `flush()` (or `stop()`) waits them
        out."""
        n = 0
        with self._drain_lock:
            pipe = self._refresh_pipe()
            # the drain is the gossip->commit seam: its span parents
            # the engine-side block timelines submitted under it, so a
            # gossip-fed commit traces back to the drain that fed it
            with tracing.span("gossip.drain") as drain_span:
                while n < max_blocks:
                    block = self.buffer.pop_in_order()
                    if block is None:
                        break
                    try:
                        if pipe is not None:
                            pipe.submit(block)
                        else:
                            self._channel.store_block(block)
                    except Exception:
                        # the popped block never committed: rewind so
                        # it stays requestable instead of stalling the
                        # channel on a permanent invisible gap
                        self.buffer.resync(self._channel.ledger.height)
                        raise
                    n += 1
                drain_span.set(blocks=n)
        return n

    def flush(self, timeout_s: Optional[float] = None) -> bool:
        """Wait until every drained block is actually committed (a
        no-op on the synchronous path)."""
        pipe = self._commit_pipeline()
        if pipe is None:
            return True
        return pipe.flush(timeout_s)

    def request_gap(self) -> Optional[range]:
        """Immediately request the gap blocking progress, if any.
        The relay's repair prod: a child that just SAW a frame beyond
        its next needed block knows the gap exists NOW — waiting out
        the anti-entropy cadence would add a full interval to every
        relay drop's repair latency.  The periodic tick below remains
        the backstop for gaps nobody observed."""
        gap = self.buffer.missing_range()
        if gap is not None and self._request_missing is not None:
            self._request_missing(gap)
        return gap

    def anti_entropy_tick(self) -> Optional[range]:
        """If a gap blocks progress, ask for it
        (reference: the anti-entropy goroutine).  Also detects an
        ASYNC pipeline failure on a quiescent channel: without this
        check the rebuild+resync would wait for the next drain —
        which only fires on a new block — leaving a lost tail
        invisible to the gap request below forever."""
        with self._drain_lock:
            self._refresh_pipe()
        gap = self.buffer.missing_range()
        if gap is not None and self._request_missing is not None:
            self._request_missing(gap)
        # the pull hook fires only on a QUIESCENT channel (no buffer
        # progress since the previous tick): while blocks are flowing
        # the push path is clearly alive and a pull is pure overhead;
        # when nothing moved, either we are fully caught up or the
        # tail was lost — exactly the two cases only a pull can tell
        # apart
        seq = self.buffer.next_seq
        if self._on_tick is not None and seq == self._tick_seq:
            self._on_tick()
        self._tick_seq = seq
        return gap

    # -- background mode --------------------------------------------------
    def start(self, interval_s: float = 0.5) -> None:
        """Idempotent: a second start() (e.g. two services composed
        over one node) does not spawn a second drain loop.
        `interval_s` is the ANTI-ENTROPY cadence only — commits are
        event-driven off `add_block`."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            from fabric_mod_tpu.observability import get_logger
            log = get_logger("gossip.state")
            next_tick = time.monotonic() + interval_s
            while not self._stop.is_set():
                timeout = max(0.0, next_tick - time.monotonic())
                got = self.buffer.wait_ready(timeout)
                if self._stop.is_set():
                    return
                if got:
                    try:
                        self.drain()
                    except Exception as e:
                        # the loop must survive a failed commit: drain
                        # already resynced the buffer, and this same
                        # thread runs the anti-entropy that re-requests
                        # the gap — dying here would stall the channel
                        log.warning("background drain failed: %s "
                                    "(resynced; redelivery/anti-"
                                    "entropy will retry)", e)
                if time.monotonic() >= next_tick:
                    try:
                        self.anti_entropy_tick()
                    except Exception as e:
                        # same survival contract as drain: the tick
                        # runs a user callback and a pipe health
                        # check — neither may kill the loop
                        log.warning("anti-entropy tick failed: %s", e)
                    next_tick = time.monotonic() + interval_s
        self._thread = RegisteredThread(target=loop,
                                        name="gossip-state-drain",
                                        structure="GossipStateProvider")
        self._thread.start()

    def stop(self) -> None:
        """Best-effort teardown: drain + wait out pending commits,
        logging (never raising) on failure — any commit error was
        already surfaced to the drain caller that hit it, and the
        resync in drain() keeps uncommitted blocks requestable."""
        self._stop.set()
        self.buffer.wake()
        if self._thread is not None:
            assert_joined((self._thread,),
                          owner="GossipStateProvider", timeout=5)
        from fabric_mod_tpu.observability import get_logger
        try:
            self.drain()
            # generous: the tail blocks may still be compiling/
            # committing (a cold XLA verify compile runs minutes)
            if not self.flush(timeout_s=600.0):
                get_logger("gossip.state").warning(
                    "stop(): commit pipeline did not drain within "
                    "600s — tail blocks remain uncommitted "
                    "(redeliverable)")
        except Exception as e:
            get_logger("gossip.state").warning(
                "stop(): final drain failed: %s — uncommitted blocks "
                "remain requestable after resync", e)
