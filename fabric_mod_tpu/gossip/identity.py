"""Gossip identity mapper: PKI-ID <-> certificate store.

(reference: gossip/identity/identity.go — Mapper with Put/Get/Sign/
Verify at :176 and expiry-based purging SuspectPeers at :190.)

The PKI-ID is the SHA-256 of the serialized identity (like the
reference's digest of cert bytes); verification routes through the
MSP so revoked/expired identities drop out on re-validation.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict, Optional

from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.concurrency.locks import RegisteredLock


def pki_id_of(serialized_identity: bytes) -> bytes:
    return hashlib.sha256(serialized_identity).digest()


class IdentityMapper:
    def __init__(self, msp_mgr, verifier=None):
        self._msp = msp_mgr
        self._verifier = verifier
        self._lock = RegisteredLock("gossip.identity._lock")
        self._store: Dict[bytes, bytes] = {}    # pki_id -> serialized

    def put(self, serialized_identity: bytes) -> bytes:
        """Validate + store; returns the PKI-ID.  Raises on identities
        the MSP rejects (reference: identity.go Put)."""
        ident = self._msp.deserialize_identity(serialized_identity)
        self._msp.validate(ident)
        pid = pki_id_of(serialized_identity)
        with self._lock:
            self._store[pid] = serialized_identity
        return pid

    def get(self, pki_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._store.get(pki_id)

    def verify(self, pki_id: bytes, msg: bytes, sig: bytes) -> bool:
        """(reference: identity.go:176 Verify)"""
        raw = self.get(pki_id)
        if raw is None:
            return False
        try:
            ident = self._msp.deserialize_identity(raw)
        except Exception:
            return False
        if self._verifier is not None:
            item = ident.verify_item(msg, sig)
            if item is not None:
                return bool(self._verifier.verify_many([item])[0])
        return ident.verify(msg, sig)

    def suspect_peers(self, is_suspected: Callable[[bytes], bool]) -> None:
        """Re-validate suspected identities, dropping the ones the MSP
        no longer accepts (reference: identity.go:190 SuspectPeers)."""
        with self._lock:
            items = list(self._store.items())
        for pid, raw in items:
            if not is_suspected(raw):
                continue
            try:
                ident = self._msp.deserialize_identity(raw)
                self._msp.validate(ident)
            except Exception:
                with self._lock:
                    self._store.pop(pid, None)
