"""Per-channel leader election: who runs the deliver client.

(reference: gossip/election/election.go — LeaderElectionService at
:92, the proposal/declaration rounds of leaderElectionSvcImpl at
:189-242, and the static-leader mode of the gossip service config.)

Deterministic-minimum election over the converged membership view:
every peer computes leader = min(PKI-ID) over {self} ∪ alive peers.
Given the same membership view all peers agree without extra message
rounds (the reference's proposal rounds exist to stabilize exactly
this computation under churn; here churn resolves through the
discovery heartbeats that feed the same view).  `static=True` pins
leadership to the configured flag instead (reference: the
org-leader static mode).
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from fabric_mod_tpu import concurrency as _cc
from fabric_mod_tpu.concurrency import (RegisteredLock,
                                        RegisteredThread, ThreadOwnership,
                                        assert_joined)


class LeaderElectionService:
    def __init__(self, pki_id: bytes, alive_pki_ids_fn,
                 on_change: Optional[Callable[[bool], None]] = None,
                 static: Optional[bool] = None):
        self._pki = pki_id
        self._alive = alive_pki_ids_fn     # () -> iterable of pki ids
        self._on_change = on_change
        self._static = static
        self._is_leader = bool(static) if static is not None else False
        self._lock = RegisteredLock("election")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # once start()'s loop runs, IT owns ticking: an external
        # tick() racing the loop can fire on_change transitions out
        # of order (the verdict flips back and forth but callbacks
        # land swapped).  Manual tick() on an un-started service
        # (tests, static mode) stays legal; after stop() the dead
        # loop thread releases ownership.
        self._ticker = ThreadOwnership("election-ticker",
                                       live_only=True)

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self._is_leader

    def tick(self) -> bool:
        """Recompute leadership; fires on_change on transitions.
        Returns the current verdict."""
        if _cc.enabled():
            self._ticker.guard()
        if self._static is not None:
            return self._is_leader
        candidates = [self._pki] + list(self._alive())
        new = min(candidates) == self._pki
        fire = False
        with self._lock:
            if new != self._is_leader:
                self._is_leader = new
                fire = True
        if fire and self._on_change is not None:
            self._on_change(new)
        return new

    def start(self, interval_s: float = 1.0) -> None:
        def loop():
            self._ticker.claim()           # the loop owns ticking now
            while not self._stop.wait(interval_s):
                self.tick()
        self._thread = RegisteredThread(target=loop,
                                        name="election-loop",
                                        structure="LeaderElectionService")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            assert_joined((self._thread,),
                          owner="LeaderElectionService", timeout=5)
