"""Gossip transport: authenticated peer-to-peer message passing.

(reference: gossip/comm/comm_impl.go — gRPC duplex streams whose
connections are bound to an MSP identity by the authenticated
handshake at :411; every delivered message is attributed to the
authenticated sender.)

The transport here is pluggable: `InProcNetwork` delivers between
in-process nodes (the test fabric, like the reference's inproc comm
mocks); the gRPC duplex transport slots behind the same `send`
surface when multi-process lands.  Attribution is by sender PKI-ID,
exactly what the reference's handshake establishes.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from fabric_mod_tpu import faults
from fabric_mod_tpu.concurrency import (GuardedQueue, RegisteredLock,
                                        RegisteredThread, assert_joined)
from fabric_mod_tpu.observability.logging import get_logger
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils import knobs
from fabric_mod_tpu.utils.retry import Retrier

log = get_logger("gossip.comm")

Handler = Callable[[bytes, bytes], None]     # (src_pki_id, envelope bytes)


class GossipAuth:
    """Connection-authentication hooks for the gRPC gossip transport
    (reference: comm_impl.go:411 authenticateRemotePeer — the signed
    TLS-binding handshake that ties a connection to an MSP identity).

    `identity`: this node's serialized MSP identity;
    `sign(data)`: signature by that identity's key;
    `validate(identity_bytes) -> pki_id`: MSP-validate a remote
    identity (raise on invalid) — wire to IdentityMapper.put;
    `verify(pki_id, data, sig) -> bool` — wire to IdentityMapper.verify.
    """

    def __init__(self, identity: bytes, sign, validate, verify):
        self.identity = identity
        self.sign = sign
        self.validate = validate
        self.verify = verify


_HSK_CTX = b"gossip-handshake-v1\x00"


def _pem_cert_der_hash(pem: bytes) -> bytes:
    """Stable digest of a TLS certificate: hash the DER, not the PEM
    (PEM wrapping differs between the client's file and the server's
    re-encoded auth_context view)."""
    import hashlib as _hl
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives.serialization import Encoding
        der_enc = Encoding.DER
    except ImportError:       # wheel-less: bccsp/_x509fallback.py
        from fabric_mod_tpu.bccsp import _x509fallback as x509
        der_enc = "DER"
    cert = x509.load_pem_x509_certificate(pem)
    return _hl.sha256(cert.public_bytes(der_enc)).digest()


class InProcNetwork:
    """Endpoint registry + direct delivery (the wire stand-in)."""

    def __init__(self):
        self._lock = RegisteredLock("gossip.comm._lock")
        self._handlers: Dict[str, Handler] = {}
        self.partitioned: set = set()        # endpoints cut off (tests)

    def register(self, endpoint: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[endpoint] = handler

    def unregister(self, endpoint: str) -> None:
        with self._lock:
            self._handlers.pop(endpoint, None)

    def send(self, src_endpoint: str, src_pki_id: bytes,
             dst_endpoint: str, env_bytes: bytes) -> bool:
        # chaos seam (the faults-module docstring's canonical drop
        # example): an armed drop-mode rule loses this message on the
        # wire — gossip redelivery / anti-entropy must repair it, which
        # is exactly what the soak's background plan asserts at scale
        if faults.point("gossip.comm.drop"):
            return False
        with self._lock:
            if (src_endpoint in self.partitioned or
                    dst_endpoint in self.partitioned):
                return False
            handler = self._handlers.get(dst_endpoint)
        if handler is None:
            return False
        try:
            handler(src_pki_id, env_bytes)
            return True
        except Exception:
            return False


class GRPCGossipNetwork:
    """The same register/send surface over real gRPC — one node's
    gossip endpoint IS its host:port (reference: gossip/comm's
    GossipStream service, collapsed to a `Gossip/Message` RPC; with
    mTLS configured, transport-level peer auth complements the
    per-envelope MSP signature every message already carries —
    attribution remains signature-based, as in protoext).

    Remote sends are ASYNC: per-destination bounded queues drained by
    sender threads (the GRPCRaftTransport pattern) — a dead peer
    drops its own traffic, never blocking the caller (which may be an
    inbound RPC worker); gossip tolerates the loss."""

    SERVICE = ("Gossip", "Message")
    QUEUE_CAP = 256

    SERVICE_CONNECT = ("Gossip", "Connect")
    NONCE_TTL_S = 30.0
    SESSION_TTL_S = 3600.0
    SESSION_CAP = 4096

    def __init__(self, listen_address: str = "127.0.0.1:0",
                 server_cert: Optional[bytes] = None,
                 server_key: Optional[bytes] = None,
                 client_ca: Optional[bytes] = None,
                 client_cert: Optional[bytes] = None,
                 client_key: Optional[bytes] = None,
                 send_timeout_s: float = 1.5,
                 auth: Optional[GossipAuth] = None,
                 send_retries: Optional[int] = None,
                 retrier: Optional[Retrier] = None):
        """With `auth`, every connection must complete the signed
        handshake before Message RPCs are accepted: the remote signs
        (context ‖ server nonce ‖ its TLS client-cert digest), the
        server checks the digest against the cert actually presented
        on THIS connection and MSP-validates the identity.  Messages
        are then attributed to the HANDSHAKE identity — a claimed
        sender that differs from the authenticated one is dropped."""
        import base64
        import json
        from fabric_mod_tpu.comm.grpc_comm import (
            GRPCClient, GRPCServer, MethodKind)
        self._b64 = base64.b64encode
        self._unb64 = base64.b64decode
        self._json = json
        self._GRPCClient = GRPCClient
        self._client_tls = (client_ca, client_cert, client_key)
        self._timeout = send_timeout_s
        self._auth = auth
        # per-message send retries: a TRANSIENT peer failure (restart,
        # one dropped RPC) should cost a short retry, not the message
        # (gossip tolerates loss, but every loss is convergence delay
        # anti-entropy must repair later).  A peer that stays dead
        # still drops its own traffic after the budget — never
        # blocking other destinations (per-destination queues).
        # FABRIC_MOD_TPU_GOSSIP_SEND_RETRIES, default 2; 0 restores
        # the old drop-on-first-failure behavior.
        if send_retries is None:
            send_retries = knobs.get_int(
                "FABRIC_MOD_TPU_GOSSIP_SEND_RETRIES")
        self._send_retries = max(0, send_retries)
        self._retrier = retrier if retrier is not None else Retrier(
            base_s=0.05, max_s=min(1.0, send_timeout_s),
            max_attempts=self._send_retries + 1,
            giveup=lambda: self._stopped.is_set(),
            name="gossip.send")
        # retry budget callers can reason about (stop() join budget)
        self._retry_sleep_budget = self._retrier.worst_case_delay(
            self._send_retries)
        self._my_tls_hash = (_pem_cert_der_hash(client_cert)
                             if client_cert is not None else b"")
        # registry-fed mutex: the comm lock nests inside callers'
        # locks (gossip node, discovery) — an inversion is a real
        # deadlock and the registry reports the first one observed
        self._lock = RegisteredLock("gossip.comm")
        self._senders: List[RegisteredThread] = []
        self._stopped = threading.Event()
        self._handlers: Dict[str, Handler] = {}
        self._clients: Dict[str, object] = {}
        self._queues: Dict[str, object] = {}
        self._tokens: Dict[str, str] = {}        # dst endpoint -> token
        self._nonces: Dict[str, float] = {}      # minted nonce -> expiry
        self._sessions: Dict[str, tuple] = {}    # token -> (pki, tlshash)
        self.partitioned: set = set()      # honored like InProcNetwork
        self.server = GRPCServer(listen_address,
                                 server_cert_pem=server_cert,
                                 server_key_pem=server_key,
                                 client_root_pem=client_ca)
        host = listen_address.rsplit(":", 1)[0]
        self.listen_endpoint = f"{host}:{self.server.port}"
        self.server.register(*self.SERVICE, MethodKind.UNARY,
                             self._on_message)
        self.server.register(*self.SERVICE_CONNECT, MethodKind.UNARY,
                             self._on_connect)

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            queues = list(self._queues.values())
            senders, self._senders = self._senders, []
        for q in queues:
            try:
                q.put_nowait(None)
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- wake sentinel on a full queue: senders poll _stopped too
                pass
        for c in clients:
            c.close()
        self.server.stop()
        # leak check: every per-destination sender must terminate.
        # An IDLE sender wakes within its 0.5 s poll slice, but one
        # mid-send against an unresponsive peer can legitimately chain
        # handshake hello + auth + send + NACK token-drop + re-
        # handshake + resend (up to ~6 unary calls, each bounded by
        # send_timeout_s) per ATTEMPT, and the retrier may take
        # send_retries further attempts with backoff sleeps between
        # (giveup cuts retries once _stopped is set, but a sleep/
        # attempt already underway completes) — derive the budget
        # from the knobs so clean teardown never raises a false leak
        # at any configured timeout
        worst = (6 * self._timeout * (self._send_retries + 1)
                 + self._retry_sleep_budget)
        assert_joined(senders, owner="gossip.comm",
                      timeout=max(15.0, worst + 1.0))

    # -- the network surface ---------------------------------------------
    def register(self, endpoint: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[endpoint] = handler

    def unregister(self, endpoint: str) -> None:
        with self._lock:
            self._handlers.pop(endpoint, None)

    def send(self, src_endpoint: str, src_pki_id: bytes,
             dst_endpoint: str, env_bytes: bytes) -> bool:
        if self._stopped.is_set():
            return False
        if src_endpoint in self.partitioned or \
                dst_endpoint in self.partitioned:
            return False
        with self._lock:
            local = self._handlers.get(dst_endpoint)
        if local is not None:              # same-process shortcut
            try:
                local(src_pki_id, env_bytes)
                return True
            except Exception:
                return False
        payload = self._json.dumps(
            {"dst": dst_endpoint,
             "pki": self._b64(src_pki_id).decode(),
             "env": self._b64(env_bytes).decode()}).encode()
        q = self._queue_for(dst_endpoint)
        try:
            q.put_nowait(payload)
            return True                    # best-effort enqueue
        except Exception:
            return False                   # full: drop (gossip re-sends)

    # -- internals --------------------------------------------------------
    def _queue_for(self, endpoint: str):
        with self._lock:
            q = self._queues.get(endpoint)
            if q is None:
                # consumer side pinned to the sender thread: any other
                # thread draining a destination's queue would reorder
                # or steal its traffic — a race, caught at the get
                q = GuardedQueue(self.QUEUE_CAP,
                                 name=f"gossip-send[{endpoint}]")
                self._queues[endpoint] = q
                t = RegisteredThread(target=self._sender,
                                     name=f"gossip-send[{endpoint}]",
                                     structure="gossip.comm",
                                     args=(endpoint, q))
                self._senders.append(t)
                t.start()
            return q

    def _sender(self, endpoint: str, q) -> None:
        while not self._stopped.is_set():
            try:
                payload = q.get(timeout=0.5)
            except Exception:
                continue
            if payload is None or self._stopped.is_set():
                return
            try:
                # bounded jittered-backoff retries (utils/retry.py):
                # a transient failure costs a short retry instead of
                # the message; _attempt_send resets the dead client
                # between attempts so each retry redials
                self._retrier.call(self._attempt_send, endpoint,
                                   payload)
            except Exception as e:
                # budget exhausted: drop (gossip re-sends)
                log.debug("gossip send to %s dropped after "
                          "retries: %r", endpoint, e)

    def _attempt_send(self, endpoint: str, payload: bytes) -> bytes:
        """One send attempt, NACK re-handshake included; on failure
        the cached client/token are dropped so the NEXT attempt (or
        message) dials fresh instead of reusing a dead connection."""
        try:
            faults.point("gossip.comm.send")
            resp = self._send_one(endpoint, payload)
            if resp == b"NACK" and self._auth is not None:
                # receiver restarted and lost our session: drop the
                # cached token, re-handshake, retry once
                with self._lock:
                    self._tokens.pop(endpoint, None)
                resp = self._send_one(endpoint, payload)
            return resp
        except Exception:
            with self._lock:
                client = self._clients.pop(endpoint, None)
                self._tokens.pop(endpoint, None)
            if client is not None:
                client.close()
            raise

    def _send_one(self, endpoint: str, payload: bytes) -> bytes:
        if self._auth is not None:
            token = self._token_for(endpoint)
            d = self._json.loads(payload)
            d["token"] = token
            payload = self._json.dumps(d).encode()
        return self._client_for(endpoint).unary(
            *self.SERVICE, payload, timeout=self._timeout)

    # -- client side of the handshake -------------------------------------
    def _token_for(self, endpoint: str) -> str:
        with self._lock:
            token = self._tokens.get(endpoint)
        if token is not None:
            return token
        client = self._client_for(endpoint)
        hello = self._json.loads(client.unary(
            *self.SERVICE_CONNECT,
            self._json.dumps({"phase": "hello"}).encode(),
            timeout=self._timeout))
        nonce = self._unb64(hello["nonce"])
        sig = self._auth.sign(_HSK_CTX + nonce + self._my_tls_hash)
        resp = self._json.loads(client.unary(
            *self.SERVICE_CONNECT,
            self._json.dumps({
                "phase": "auth",
                "nonce": hello["nonce"],
                "identity": self._b64(self._auth.identity).decode(),
                "tls": self._b64(self._my_tls_hash).decode(),
                "sig": self._b64(sig).decode()}).encode(),
            timeout=self._timeout))
        token = resp["token"]
        with self._lock:
            self._tokens[endpoint] = token
        return token

    # -- server side of the handshake --------------------------------------
    _CERT_HASH_CACHE_MAX = 256

    def _peer_cert_hash(self, context) -> bytes:
        """DER digest of the TLS client certificate actually presented
        on this connection ('' without mTLS).  Cached by PEM bytes —
        this runs on the per-message hot path and the ASN.1 parse is
        constant per peer."""
        try:
            auth = context.auth_context()
            pems = auth.get("x509_pem_cert") or []
            if pems:
                pem = pems[0]
                cache = getattr(self, "_cert_hash_cache", None)
                if cache is None:
                    cache = self._cert_hash_cache = {}
                h = cache.get(pem)
                if h is None:
                    if len(cache) >= self._CERT_HASH_CACHE_MAX:
                        cache.clear()
                    h = cache[pem] = _pem_cert_der_hash(pem)
                return h
        except Exception as e:
            log.debug("peer cert hash failed (auth downgraded "
                      "to empty): %r", e)
        return b""

    def _on_connect(self, request: bytes, context) -> bytes:
        import os as _os
        import time as _time
        if self._auth is None:
            return self._json.dumps({"error": "auth not enabled"}).encode()
        try:
            d = self._json.loads(request)
            if d.get("phase") == "hello":
                nonce = _os.urandom(16)
                with self._lock:
                    now = _time.time()
                    self._nonces = {n: exp for n, exp in
                                    self._nonces.items() if exp > now}
                    self._nonces[self._b64(nonce).decode()] = \
                        now + self.NONCE_TTL_S
                return self._json.dumps(
                    {"nonce": self._b64(nonce).decode()}).encode()
            # phase: auth
            nonce_b64 = d["nonce"]
            with self._lock:
                exp = self._nonces.pop(nonce_b64, None)
            if exp is None or exp < _time.time():
                return self._json.dumps(
                    {"error": "unknown or expired nonce"}).encode()
            identity = self._unb64(d["identity"])
            claimed_tls = self._unb64(d["tls"])
            sig = self._unb64(d["sig"])
            actual_tls = self._peer_cert_hash(context)
            if not actual_tls:
                # no mTLS client cert on this connection: both hashes
                # would be b"" and the "binding" check below would pass
                # vacuously, turning session tokens into unbound bearer
                # credentials.  Auth-enabled gossip requires mTLS —
                # fail the handshake instead of degrading silently.
                return self._json.dumps(
                    {"error": "auth requires an mTLS client "
                     "certificate to bind the session to"}).encode()
            if claimed_tls != actual_tls:
                # the signed TLS binding does not match the cert on
                # THIS connection: a replayed/stolen handshake
                return self._json.dumps(
                    {"error": "tls binding mismatch"}).encode()
            pki = self._auth.validate(identity)   # raises on invalid
            nonce = self._unb64(nonce_b64)
            if not self._auth.verify(pki, _HSK_CTX + nonce +
                                     claimed_tls, sig):
                return self._json.dumps(
                    {"error": "bad handshake signature"}).encode()
            token = self._b64(_os.urandom(16)).decode()
            now = _time.time()
            with self._lock:
                # sessions are TTL'd and capped: every valid MSP
                # member can mint them, so unbounded growth would be
                # a slow memory DoS
                self._sessions = {
                    t: s for t, s in self._sessions.items()
                    if s[2] > now}
                while len(self._sessions) >= self.SESSION_CAP:
                    oldest = min(self._sessions,
                                 key=lambda t: self._sessions[t][2])
                    del self._sessions[oldest]
                self._sessions[token] = (pki, actual_tls,
                                         now + self.SESSION_TTL_S)
            return self._json.dumps({"token": token}).encode()
        except Exception as e:
            return self._json.dumps({"error": str(e)}).encode()

    def _client_for(self, endpoint: str):
        with self._lock:
            if self._stopped.is_set():
                raise RuntimeError("network stopped")
            client = self._clients.get(endpoint)
            if client is None:
                ca, cert, key = self._client_tls
                client = self._GRPCClient(endpoint, server_root_pem=ca,
                                          client_cert_pem=cert,
                                          client_key_pem=key)
                self._clients[endpoint] = client
            return client

    def _on_message(self, request: bytes, context) -> bytes:
        try:
            d = self._json.loads(request)
            claimed_pki = self._unb64(d["pki"])
            if self._auth is not None:
                import time as _time
                now = _time.time()
                with self._lock:
                    session = self._sessions.get(d.get("token", ""))
                if session is None or session[2] < now:
                    # unknown/expired token (e.g. we restarted and
                    # lost the session): NACK so the sender
                    # re-handshakes instead of blackholing forever
                    return b"NACK"
                auth_pki, bound_tls, _exp = session
                # the token is bound to the TLS client cert it was
                # minted under — a stolen token dies with its session
                if bound_tls != self._peer_cert_hash(context):
                    return b""
                # a claimed sender that is not the authenticated
                # connection identity is exactly the org-A-TLS/
                # org-B-signature confusion the handshake exists to
                # stop (reference: comm_impl.go:411)
                if claimed_pki != auth_pki:
                    return b""
            with self._lock:
                handler = self._handlers.get(d["dst"])
            if handler is not None:
                handler(claimed_pki, self._unb64(d["env"]))
        except Exception as e:
            log.debug("inbound gossip dispatch failed: %r", e)
        return b""


class GossipComm:
    """One node's sending surface (reference: comm_impl.go Send)."""

    def __init__(self, endpoint: str, pki_id: bytes,
                 network: InProcNetwork, signer):
        self.endpoint = endpoint
        self.pki_id = pki_id
        self._network = network
        self._signer = signer

    def send(self, dst_endpoint: str, msg: m.GossipMessage) -> bool:
        from fabric_mod_tpu.gossip.protoext import sign_message
        env = sign_message(msg, self._signer)
        return self._network.send(self.endpoint, self.pki_id,
                                  dst_endpoint, env.encode())

    def sign_once(self, msg: m.GossipMessage) -> bytes:
        """Pre-sign a message into its envelope bytes.  The relay's
        push signs each frame ONE time and ships the identical
        envelope to every tree child — degree sends must not mean
        degree signatures (the frame was likewise encoded once)."""
        from fabric_mod_tpu.gossip.protoext import sign_message
        return sign_message(msg, self._signer).encode()

    def send_signed(self, dst_endpoint: str, env_bytes: bytes) -> bool:
        """Ship pre-signed envelope bytes (from sign_once)."""
        return self._network.send(self.endpoint, self.pki_id,
                                  dst_endpoint, env_bytes)

    def broadcast(self, dst_endpoints, msg: m.GossipMessage) -> int:
        got = 0
        for dst in dst_endpoints:
            if self.send(dst, msg):
                got += 1
        return got
