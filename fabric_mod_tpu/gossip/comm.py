"""Gossip transport: authenticated peer-to-peer message passing.

(reference: gossip/comm/comm_impl.go — gRPC duplex streams whose
connections are bound to an MSP identity by the authenticated
handshake at :411; every delivered message is attributed to the
authenticated sender.)

The transport here is pluggable: `InProcNetwork` delivers between
in-process nodes (the test fabric, like the reference's inproc comm
mocks); the gRPC duplex transport slots behind the same `send`
surface when multi-process lands.  Attribution is by sender PKI-ID,
exactly what the reference's handshake establishes.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from fabric_mod_tpu.protos import messages as m

Handler = Callable[[bytes, bytes], None]     # (src_pki_id, envelope bytes)


class InProcNetwork:
    """Endpoint registry + direct delivery (the wire stand-in)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handlers: Dict[str, Handler] = {}
        self.partitioned: set = set()        # endpoints cut off (tests)

    def register(self, endpoint: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[endpoint] = handler

    def unregister(self, endpoint: str) -> None:
        with self._lock:
            self._handlers.pop(endpoint, None)

    def send(self, src_endpoint: str, src_pki_id: bytes,
             dst_endpoint: str, env_bytes: bytes) -> bool:
        with self._lock:
            if (src_endpoint in self.partitioned or
                    dst_endpoint in self.partitioned):
                return False
            handler = self._handlers.get(dst_endpoint)
        if handler is None:
            return False
        try:
            handler(src_pki_id, env_bytes)
            return True
        except Exception:
            return False


class GossipComm:
    """One node's sending surface (reference: comm_impl.go Send)."""

    def __init__(self, endpoint: str, pki_id: bytes,
                 network: InProcNetwork, signer):
        self.endpoint = endpoint
        self.pki_id = pki_id
        self._network = network
        self._signer = signer

    def send(self, dst_endpoint: str, msg: m.GossipMessage) -> bool:
        from fabric_mod_tpu.gossip.protoext import sign_message
        env = sign_message(msg, self._signer)
        return self._network.send(self.endpoint, self.pki_id,
                                  dst_endpoint, env.encode())

    def broadcast(self, dst_endpoints, msg: m.GossipMessage) -> int:
        got = 0
        for dst in dst_endpoints:
            if self.send(dst, msg):
                got += 1
        return got
