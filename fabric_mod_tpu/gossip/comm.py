"""Gossip transport: authenticated peer-to-peer message passing.

(reference: gossip/comm/comm_impl.go — gRPC duplex streams whose
connections are bound to an MSP identity by the authenticated
handshake at :411; every delivered message is attributed to the
authenticated sender.)

The transport here is pluggable: `InProcNetwork` delivers between
in-process nodes (the test fabric, like the reference's inproc comm
mocks); the gRPC duplex transport slots behind the same `send`
surface when multi-process lands.  Attribution is by sender PKI-ID,
exactly what the reference's handshake establishes.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from fabric_mod_tpu.protos import messages as m

Handler = Callable[[bytes, bytes], None]     # (src_pki_id, envelope bytes)


class InProcNetwork:
    """Endpoint registry + direct delivery (the wire stand-in)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._handlers: Dict[str, Handler] = {}
        self.partitioned: set = set()        # endpoints cut off (tests)

    def register(self, endpoint: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[endpoint] = handler

    def unregister(self, endpoint: str) -> None:
        with self._lock:
            self._handlers.pop(endpoint, None)

    def send(self, src_endpoint: str, src_pki_id: bytes,
             dst_endpoint: str, env_bytes: bytes) -> bool:
        with self._lock:
            if (src_endpoint in self.partitioned or
                    dst_endpoint in self.partitioned):
                return False
            handler = self._handlers.get(dst_endpoint)
        if handler is None:
            return False
        try:
            handler(src_pki_id, env_bytes)
            return True
        except Exception:
            return False


class GRPCGossipNetwork:
    """The same register/send surface over real gRPC — one node's
    gossip endpoint IS its host:port (reference: gossip/comm's
    GossipStream service, collapsed to a `Gossip/Message` RPC; with
    mTLS configured, transport-level peer auth complements the
    per-envelope MSP signature every message already carries —
    attribution remains signature-based, as in protoext).

    Remote sends are ASYNC: per-destination bounded queues drained by
    sender threads (the GRPCRaftTransport pattern) — a dead peer
    drops its own traffic, never blocking the caller (which may be an
    inbound RPC worker); gossip tolerates the loss."""

    SERVICE = ("Gossip", "Message")
    QUEUE_CAP = 256

    def __init__(self, listen_address: str = "127.0.0.1:0",
                 server_cert: Optional[bytes] = None,
                 server_key: Optional[bytes] = None,
                 client_ca: Optional[bytes] = None,
                 client_cert: Optional[bytes] = None,
                 client_key: Optional[bytes] = None,
                 send_timeout_s: float = 1.5):
        import base64
        import json
        import queue
        from fabric_mod_tpu.comm.grpc_comm import (
            GRPCClient, GRPCServer, MethodKind)
        self._b64 = base64.b64encode
        self._unb64 = base64.b64decode
        self._json = json
        self._queue_mod = queue
        self._GRPCClient = GRPCClient
        self._client_tls = (client_ca, client_cert, client_key)
        self._timeout = send_timeout_s
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._handlers: Dict[str, Handler] = {}
        self._clients: Dict[str, object] = {}
        self._queues: Dict[str, object] = {}
        self.partitioned: set = set()      # honored like InProcNetwork
        self.server = GRPCServer(listen_address,
                                 server_cert_pem=server_cert,
                                 server_key_pem=server_key,
                                 client_root_pem=client_ca)
        host = listen_address.rsplit(":", 1)[0]
        self.listen_endpoint = f"{host}:{self.server.port}"
        self.server.register(*self.SERVICE, MethodKind.UNARY,
                             self._on_message)

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
            queues = list(self._queues.values())
        for q in queues:
            try:
                q.put_nowait(None)
            except Exception:
                pass                       # senders poll _stopped too
        for c in clients:
            c.close()
        self.server.stop()

    # -- the network surface ---------------------------------------------
    def register(self, endpoint: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[endpoint] = handler

    def unregister(self, endpoint: str) -> None:
        with self._lock:
            self._handlers.pop(endpoint, None)

    def send(self, src_endpoint: str, src_pki_id: bytes,
             dst_endpoint: str, env_bytes: bytes) -> bool:
        if self._stopped.is_set():
            return False
        if src_endpoint in self.partitioned or \
                dst_endpoint in self.partitioned:
            return False
        with self._lock:
            local = self._handlers.get(dst_endpoint)
        if local is not None:              # same-process shortcut
            try:
                local(src_pki_id, env_bytes)
                return True
            except Exception:
                return False
        payload = self._json.dumps(
            {"dst": dst_endpoint,
             "pki": self._b64(src_pki_id).decode(),
             "env": self._b64(env_bytes).decode()}).encode()
        q = self._queue_for(dst_endpoint)
        try:
            q.put_nowait(payload)
            return True                    # best-effort enqueue
        except Exception:
            return False                   # full: drop (gossip re-sends)

    # -- internals --------------------------------------------------------
    def _queue_for(self, endpoint: str):
        with self._lock:
            q = self._queues.get(endpoint)
            if q is None:
                q = self._queue_mod.Queue(self.QUEUE_CAP)
                self._queues[endpoint] = q
                threading.Thread(target=self._sender,
                                 args=(endpoint, q),
                                 daemon=True).start()
            return q

    def _sender(self, endpoint: str, q) -> None:
        while not self._stopped.is_set():
            try:
                payload = q.get(timeout=0.5)
            except Exception:
                continue
            if payload is None or self._stopped.is_set():
                return
            try:
                self._client_for(endpoint).unary(
                    *self.SERVICE, payload, timeout=self._timeout)
            except Exception:
                with self._lock:
                    client = self._clients.pop(endpoint, None)
                if client is not None:
                    client.close()

    def _client_for(self, endpoint: str):
        with self._lock:
            if self._stopped.is_set():
                raise RuntimeError("network stopped")
            client = self._clients.get(endpoint)
            if client is None:
                ca, cert, key = self._client_tls
                client = self._GRPCClient(endpoint, server_root_pem=ca,
                                          client_cert_pem=cert,
                                          client_key_pem=key)
                self._clients[endpoint] = client
            return client

    def _on_message(self, request: bytes, context) -> bytes:
        try:
            d = self._json.loads(request)
            with self._lock:
                handler = self._handlers.get(d["dst"])
            if handler is not None:
                handler(self._unb64(d["pki"]), self._unb64(d["env"]))
        except Exception:
            pass
        return b""


class GossipComm:
    """One node's sending surface (reference: comm_impl.go Send)."""

    def __init__(self, endpoint: str, pki_id: bytes,
                 network: InProcNetwork, signer):
        self.endpoint = endpoint
        self.pki_id = pki_id
        self._network = network
        self._signer = signer

    def send(self, dst_endpoint: str, msg: m.GossipMessage) -> bool:
        from fabric_mod_tpu.gossip.protoext import sign_message
        env = sign_message(msg, self._signer)
        return self._network.send(self.endpoint, self.pki_id,
                                  dst_endpoint, env.encode())

    def broadcast(self, dst_endpoints, msg: m.GossipMessage) -> int:
        got = 0
        for dst in dst_endpoints:
            if self.send(dst, msg):
                got += 1
        return got
