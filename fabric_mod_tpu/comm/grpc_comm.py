"""gRPC server/client wrappers over mutual TLS.

(reference: internal/pkg/comm — GRPCServer at server.go:268 with
client-cert verification, GRPCClient at client.go:211, keepalive and
message-size options in config.go.)

The framework's wire messages are the deterministic hand-rolled codec
(protos/wire.py), so services register **generic byte handlers**
(identity serializers) instead of protoc stubs — the method path
carries the service contract, the payload is our encoding.  This is
the L4 control plane; device batches never cross these sockets
(SURVEY §5.8: gRPC for control, XLA for data).
"""
from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Callable, Dict, Optional, Tuple

import grpc
from fabric_mod_tpu.concurrency.locks import RegisteredLock

_IDENT = (lambda b: b, lambda b: b)

_OPTIONS = [
    ("grpc.max_send_message_length", 100 * 1024 * 1024),
    ("grpc.max_receive_message_length", 100 * 1024 * 1024),
    ("grpc.keepalive_time_ms", 60_000),
    ("grpc.keepalive_timeout_ms", 20_000),
]


class MethodKind:
    UNARY = "unary"
    SERVER_STREAM = "server_stream"
    STREAM_STREAM = "stream_stream"


# -- RPC observability (reference: common/grpcmetrics/interceptor.go +
# -- common/grpclogging/server.go — every server handler is wrapped
# -- with request counters, a duration histogram, and debug logs) -----------

_rpc_metrics_lock = RegisteredLock("comm.grpc_comm._rpc_metrics_lock")
_rpc_metrics = None


def _get_rpc_metrics():
    global _rpc_metrics
    if _rpc_metrics is not None:           # hot path: no lock
        return _rpc_metrics
    with _rpc_metrics_lock:
        if _rpc_metrics is None:
            from fabric_mod_tpu.observability.metrics import (
                MetricOpts, default_provider)
            prov = default_provider()
            _rpc_metrics = (
                prov.new_counter(MetricOpts(
                    "grpc", "server", "requests_completed",
                    "RPCs completed", ("service", "method", "code"))),
                prov.new_histogram(MetricOpts(
                    "grpc", "server", "request_duration_seconds",
                    "RPC handling time", ("service", "method"))),
            )
        return _rpc_metrics


def _observe(service: str, method: str, kind: str, fn):
    """Wrap a handler with counters + duration (streams time the full
    stream life, like the reference's stream interceptor)."""
    from fabric_mod_tpu.observability.logging import get_logger
    log = get_logger("comm.grpc")

    def wrapped(request, context):
        counter, hist = _get_rpc_metrics()
        t0 = time.perf_counter()
        code = "OK"
        try:
            result = fn(request, context)
            if kind != MethodKind.UNARY:
                # drain-through generator so the duration covers the
                # whole stream, not just handler setup; a mid-stream
                # raise must count as ERROR, not OK
                def stream():
                    scode = "OK"
                    try:
                        yield from result
                    except BaseException:
                        scode = "ERROR"
                        raise
                    finally:
                        hist.with_labels(service, method).observe(
                            time.perf_counter() - t0)
                        counter.with_labels(service, method,
                                            scode).add(1)
                return stream()
            return result
        except Exception:
            code = "ERROR"
            raise
        finally:
            if kind == MethodKind.UNARY:
                hist.with_labels(service, method).observe(
                    time.perf_counter() - t0)
                counter.with_labels(service, method, code).add(1)
                log.debug("%s/%s -> %s", service, method, code)
            elif code == "ERROR":
                counter.with_labels(service, method, "ERROR").add(1)
    return wrapped


class GRPCServer:
    """mTLS gRPC server with generic byte-level method registration."""

    def __init__(self, address: str = "127.0.0.1:0",
                 server_cert_pem: Optional[bytes] = None,
                 server_key_pem: Optional[bytes] = None,
                 client_root_pem: Optional[bytes] = None,
                 max_workers: int = 16):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_OPTIONS)
        self._services: Dict[str, Dict[str, Tuple[str, Callable]]] = {}
        if server_cert_pem is not None:
            creds = grpc.ssl_server_credentials(
                [(server_key_pem, server_cert_pem)],
                root_certificates=client_root_pem,
                require_client_auth=client_root_pem is not None)
            self.port = self._server.add_secure_port(address, creds)
        else:
            self.port = self._server.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"could not bind {address}")

    def register(self, service: str, method: str, kind: str,
                 handler: Callable) -> None:
        self._services.setdefault(service, {})[method] = (kind, handler)

    def start(self) -> None:
        for service, methods in self._services.items():
            rpcs = {}
            for name, (kind, fn) in methods.items():
                fn = _observe(service, name, kind, fn)
                if kind == MethodKind.UNARY:
                    rpcs[name] = grpc.unary_unary_rpc_method_handler(
                        fn, *_IDENT)
                elif kind == MethodKind.SERVER_STREAM:
                    rpcs[name] = grpc.unary_stream_rpc_method_handler(
                        fn, *_IDENT)
                else:
                    rpcs[name] = grpc.stream_stream_rpc_method_handler(
                        fn, *_IDENT)
            self._server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(service, rpcs),))
        self._server.start()

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)


class GRPCClient:
    """mTLS channel factory + method helpers."""

    def __init__(self, target: str,
                 server_root_pem: Optional[bytes] = None,
                 client_cert_pem: Optional[bytes] = None,
                 client_key_pem: Optional[bytes] = None,
                 override_authority: Optional[str] = None):
        opts = list(_OPTIONS)
        if override_authority:
            opts.append(("grpc.ssl_target_name_override",
                         override_authority))
        if server_root_pem is not None:
            creds = grpc.ssl_channel_credentials(
                root_certificates=server_root_pem,
                private_key=client_key_pem,
                certificate_chain=client_cert_pem)
            self._channel = grpc.secure_channel(target, creds,
                                                options=opts)
        else:
            self._channel = grpc.insecure_channel(target, options=opts)

    # `metadata` on each helper: optional [(key, value)] pairs (the
    # trace-context carrier observability/tracing.inject builds); None
    # is gRPC's no-metadata, so un-traced callers are byte-identical
    # to the pre-metadata wire.

    def unary(self, service: str, method: str, request: bytes,
              timeout: Optional[float] = 30.0, metadata=None) -> bytes:
        fn = self._channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=_IDENT[0],
            response_deserializer=_IDENT[1])
        return fn(request, timeout=timeout, metadata=metadata)

    def server_stream(self, service: str, method: str, request: bytes,
                      timeout: Optional[float] = None, metadata=None):
        fn = self._channel.unary_stream(
            f"/{service}/{method}",
            request_serializer=_IDENT[0],
            response_deserializer=_IDENT[1])
        return fn(request, timeout=timeout, metadata=metadata)

    def stream_stream(self, service: str, method: str, requests,
                      timeout: Optional[float] = None, metadata=None):
        fn = self._channel.stream_stream(
            f"/{service}/{method}",
            request_serializer=_IDENT[0],
            response_deserializer=_IDENT[1])
        return fn(requests, timeout=timeout, metadata=metadata)

    def close(self) -> None:
        self._channel.close()
