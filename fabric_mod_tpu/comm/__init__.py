"""L4 communication: gRPC over mutual TLS (reference:
internal/pkg/comm) + TLS material utilities (common/crypto)."""
from fabric_mod_tpu.comm.grpc_comm import (   # noqa: F401
    GRPCClient, GRPCServer, MethodKind)
from fabric_mod_tpu.comm.tls import TlsCA, track_expiration  # noqa: F401
