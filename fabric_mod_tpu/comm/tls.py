"""TLS material generation + certificate expiration tracking.

(reference: common/crypto — tlsgen's on-the-fly TLS CAs for tests and
TrackExpiration's warn-before-expiry scanning at
common/crypto/expiration.go.)

Reuses the MSP CA library for issuance; TLS certs get
serverAuth/clientAuth EKUs and SAN entries, which the MSP CA's
identity certs don't carry.
"""
from __future__ import annotations

import datetime
import ipaddress
import os
from typing import Callable, List, Optional, Sequence, Tuple

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
except ImportError:
    # Wheel-less container: minimal DER x509 fallback (see
    # bccsp/_x509fallback.py; bccsp/sw.py logged the downgrade).
    from fabric_mod_tpu.bccsp import _x509fallback as x509
    from fabric_mod_tpu.bccsp._ecfallback import (ec, hashes,
                                                  serialization)

from fabric_mod_tpu.msp import ca as calib


class TlsCA:
    """A TLS-only CA (reference: common/crypto/tlsgen/ca.go)."""

    def __init__(self, name: str = "tlsca", org: str = "tls"):
        self._ca = calib.CA(name, org)

    @property
    def cert_pem(self) -> bytes:
        return self._ca.cert_pem()

    def issue(self, cn: str, sans: Sequence[str] = ("localhost",),
              server: bool = True, client: bool = True,
              valid_days: int = 365) -> Tuple[bytes, bytes]:
        """-> (cert PEM, key PEM) with proper EKUs + SANs."""
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        san_entries: List[x509.GeneralName] = []
        for s in sans:
            try:
                san_entries.append(
                    x509.IPAddress(ipaddress.ip_address(s)))
            except ValueError:
                san_entries.append(x509.DNSName(s))
        ekus = []
        if server:
            ekus.append(x509.oid.ExtendedKeyUsageOID.SERVER_AUTH)
        if client:
            ekus.append(x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH)
        cert = (
            x509.CertificateBuilder()
            .subject_name(x509.Name([x509.NameAttribute(
                x509.oid.NameOID.COMMON_NAME, cn)]))
            .issuer_name(self._ca.cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=valid_days))
            .add_extension(x509.BasicConstraints(ca=False,
                                                 path_length=None),
                           critical=True)
            .add_extension(x509.SubjectAlternativeName(san_entries),
                           critical=False)
            .add_extension(x509.ExtendedKeyUsage(ekus), critical=False)
            .sign(self._ca.key, hashes.SHA256()))
        return (cert.public_bytes(serialization.Encoding.PEM),
                calib.key_pem(key))


def write_pems(dir_path: str, **pems: bytes) -> dict:
    """Write named PEMs to files; returns {name: path} (gRPC creds
    APIs want in-memory bytes, but ssl contexts want files)."""
    os.makedirs(dir_path, exist_ok=True)
    out = {}
    for name, data in pems.items():
        path = os.path.join(dir_path, f"{name}.pem")
        with open(path, "wb") as f:
            f.write(data)
        out[name] = path
    return out


def track_expiration(cert_pems: Sequence[bytes],
                     warn: Callable[[str], None],
                     now: Optional[datetime.datetime] = None,
                     warn_within_days: int = 7) -> List[str]:
    """Warn for certs expiring soon/already (reference:
    common/crypto/expiration.go TrackExpiration).  Returns the warned
    subjects."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    flagged = []
    for pem in cert_pems:
        cert = x509.load_pem_x509_certificate(pem)
        subject = cert.subject.rfc4514_string()
        left = cert.not_valid_after_utc - now
        if left.total_seconds() <= 0:
            warn(f"certificate {subject} has expired")
            flagged.append(subject)
        elif left <= datetime.timedelta(days=warn_within_days):
            warn(f"certificate {subject} expires in {left}")
            flagged.append(subject)
    return flagged
