"""Generic Msg <-> JSON translation (the configtxlator surface).

(reference: internal/configtxlator — protolator's proto<->JSON
round-trip used by `configtxlator proto_encode/proto_decode`.  Our
wire layer's FIELDS metadata plays protolator's reflection role.)

Bytes fields are base64 strings; sub-messages are nested objects;
repeated fields are arrays.  Fields at their default are omitted on
encode and defaulted on decode, so the round-trip is stable.
"""
from __future__ import annotations

import base64
from typing import Any, Dict, Type

from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos.wire import Msg, _REGISTRY


class JsonPbError(Exception):
    pass


def _resolve(kind) -> Type[Msg]:
    name = kind[1]
    if name not in _REGISTRY:
        raise JsonPbError(f"unknown message type {name!r}")
    return _REGISTRY[name]


def to_json(msg: Msg) -> Dict[str, Any]:
    """Msg -> plain JSON-serializable dict."""
    out: Dict[str, Any] = {}
    for _num, attr, kind in msg.FIELDS:
        val = getattr(msg, attr)
        if isinstance(kind, list):
            if not val:
                continue
            inner = kind[0]
            if isinstance(inner, tuple):
                out[attr] = [to_json(v) for v in val]
            elif inner == "b":
                out[attr] = [base64.b64encode(v).decode() for v in val]
            else:
                out[attr] = list(val)
        elif isinstance(kind, tuple):
            if val is not None:
                out[attr] = to_json(val)
        elif kind == "b":
            if val:
                out[attr] = base64.b64encode(val).decode()
        elif kind == "s":
            if val:
                out[attr] = val
        else:                              # "u" / "i"
            if val:
                out[attr] = val
    return out


def from_json(cls_or_name, data: Dict[str, Any]) -> Msg:
    """JSON dict -> Msg instance of `cls_or_name`."""
    cls = (_REGISTRY[cls_or_name] if isinstance(cls_or_name, str)
           else cls_or_name)
    kwargs: Dict[str, Any] = {}
    known = {attr for _n, attr, _k in cls.FIELDS}
    for key in data:
        if key not in known:
            raise JsonPbError(
                f"{cls.__name__} has no field {key!r}")
    for _num, attr, kind in cls.FIELDS:
        if attr not in data:
            continue
        val = data[attr]
        if isinstance(kind, list):
            inner = kind[0]
            if isinstance(inner, tuple):
                kwargs[attr] = [from_json(_resolve(inner), v)
                                for v in val]
            elif inner == "b":
                kwargs[attr] = [base64.b64decode(v) for v in val]
            else:
                kwargs[attr] = list(val)
        elif isinstance(kind, tuple):
            kwargs[attr] = from_json(_resolve(kind), val)
        elif kind == "b":
            kwargs[attr] = base64.b64decode(val)
        else:
            kwargs[attr] = val
    return cls(**kwargs)


def proto_decode(type_name: str, raw: bytes) -> Dict[str, Any]:
    """Wire bytes -> JSON (configtxlator proto_decode)."""
    if type_name not in _REGISTRY:
        raise JsonPbError(f"unknown message type {type_name!r}")
    return to_json(_REGISTRY[type_name].decode(raw))


def proto_encode(type_name: str, data: Dict[str, Any]) -> bytes:
    """JSON -> wire bytes (configtxlator proto_encode)."""
    return from_json(type_name, data).encode()
