"""Deterministic protobuf-wire-format message layer.

The L0 of the framework (reference: protoutil/ and the external
fabric-protos-go module): every envelope, block, proposal, and rwset
is a `Msg` dataclass with numbered fields, serialized in the protobuf
wire format (varint / length-delimited).  Hand-rolled rather than
protoc-generated for two reasons that matter here:

* **Determinism is a consensus requirement** — commit results must be
  bit-identical across peers (SURVEY.md §7 hard part #7).  This
  encoder always writes fields in ascending field-number order and
  repeated fields in list order, so `encode(decode(x)) == x` holds
  and hashes over encodings are stable.
* The host marshal path feeds device batches; owning the encoder lets
  later rounds move hot unmarshal loops into the C++ host bridge
  without fighting a generated API.

Field kinds: "u" varint uint64, "i" zigzag-free int32/enum (encoded as
varint, two's-complement 64-bit for negatives like protobuf), "b"
bytes, "s" str, ("m", cls) submessage, and list-wrapped variants for
repeated fields.
"""
from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Iterable, Type


def write_varint(out: bytearray, v: int) -> None:
    if v < 0:
        v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _write_tag(out: bytearray, num: int, wt: int) -> None:
    write_varint(out, (num << 3) | wt)


def _write_len_delim(out: bytearray, num: int, data: bytes) -> None:
    _write_tag(out, num, 2)
    write_varint(out, len(data))
    out.extend(data)


class Msg:
    """Base for wire messages.  Subclasses are dataclasses that set
    FIELDS = ((num, attr, kind), ...) with num ascending."""

    FIELDS: ClassVar[tuple] = ()

    def encode(self) -> bytes:
        out = bytearray()
        for num, attr, kind in self.FIELDS:
            val = getattr(self, attr)
            rep = isinstance(kind, list)
            k = kind[0] if rep else kind
            items: Iterable[Any] = val if rep else (
                () if _is_default(val, k) else (val,))
            for item in items:
                if k == "u" or k == "i":
                    _write_tag(out, num, 0)
                    write_varint(out, int(item))
                elif k == "b":
                    _write_len_delim(out, num, bytes(item))
                elif k == "s":
                    _write_len_delim(out, num, item.encode())
                elif isinstance(k, tuple) and k[0] == "m":
                    _write_len_delim(out, num, item.encode())
                else:
                    raise TypeError(f"bad field kind {k!r}")
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Msg":
        by_num = {num: (attr, kind) for num, attr, kind in cls.FIELDS}
        kwargs: dict[str, Any] = {}
        pos = 0
        while pos < len(buf):
            tag, pos = read_varint(buf, pos)
            num, wt = tag >> 3, tag & 7
            if wt == 0:
                val, pos = read_varint(buf, pos)
                payload: Any = val
            elif wt == 2:
                ln, pos = read_varint(buf, pos)
                if pos + ln > len(buf):
                    raise ValueError("truncated length-delimited field")
                payload = buf[pos:pos + ln]
                pos += ln
            elif wt == 5:
                if pos + 4 > len(buf):
                    raise ValueError("truncated fixed32 field")
                payload = buf[pos:pos + 4]
                pos += 4
            elif wt == 1:
                if pos + 8 > len(buf):
                    raise ValueError("truncated fixed64 field")
                payload = buf[pos:pos + 8]
                pos += 8
            else:
                raise ValueError(f"unsupported wire type {wt}")
            if num not in by_num:
                continue                      # unknown fields tolerated
            attr, kind = by_num[num]
            rep = isinstance(kind, list)
            k = kind[0] if rep else kind
            # Wire type must match the declared kind: a varint arriving on
            # a bytes field (or vice versa) is a malformed message, not a
            # value to coerce — this runs on untrusted envelope bytes.
            expect_wt = 0 if k in ("u", "i") else 2
            if wt != expect_wt:
                raise ValueError(
                    f"field {num}: wire type {wt}, expected {expect_wt}")
            if k == "u" or k == "i":
                item: Any = int(payload)
                if k == "i" and item >= 1 << 63:
                    item -= 1 << 64
            elif k == "b":
                item = bytes(payload)
            elif k == "s":
                item = bytes(payload).decode()
            elif isinstance(k, tuple) and k[0] == "m":
                item = _resolve(k[1]).decode(bytes(payload))
            else:
                raise TypeError(f"bad field kind {k!r}")
            if rep:
                kwargs.setdefault(attr, []).append(item)
            else:
                kwargs[attr] = item
        return cls(**kwargs)


def _is_default(val: Any, k: Any) -> bool:
    if val is None:
        return True
    if k in ("u", "i"):
        return val == 0
    if k == "b":
        return len(val) == 0
    if k == "s":
        return val == ""
    return False


_REGISTRY: dict[str, Type[Msg]] = {}


def _resolve(name_or_cls) -> Type[Msg]:
    if isinstance(name_or_cls, str):
        return _REGISTRY[name_or_cls]
    return name_or_cls


def message(cls):
    """Decorator: dataclass + registry entry for by-name submessages."""
    cls = dataclasses.dataclass(cls)
    _REGISTRY[cls.__name__] = cls
    return cls
