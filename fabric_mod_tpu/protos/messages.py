"""Wire message definitions.

Field numbers mirror the reference's proto schema (fabric-protos:
common/common.proto, common/policies.proto, msp/identities.proto,
peer/proposal.proto, peer/transaction.proto, peer/chaincode.proto,
ledger/rwset/*.proto) so the structure is recognizable and a future
interop shim is mechanical; the implementation is the deterministic
encoder in wire.py.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from fabric_mod_tpu.protos.wire import Msg, message

_f = dataclasses.field


# --- common/common.proto ---------------------------------------------------

class HeaderType:
    MESSAGE = 0
    CONFIG = 1
    CONFIG_UPDATE = 2
    ENDORSER_TRANSACTION = 3
    ORDERER_TRANSACTION = 4
    DELIVER_SEEK_INFO = 5
    CHAINCODE_PACKAGE = 6


class TxValidationCode:
    VALID = 0
    NIL_ENVELOPE = 1
    BAD_PAYLOAD = 2
    BAD_COMMON_HEADER = 3
    BAD_CREATOR_SIGNATURE = 4
    INVALID_ENDORSER_TRANSACTION = 5
    INVALID_CONFIG_TRANSACTION = 6
    UNSUPPORTED_TX_PAYLOAD = 7
    BAD_PROPOSAL_TXID = 8
    DUPLICATE_TXID = 9
    ENDORSEMENT_POLICY_FAILURE = 10
    MVCC_READ_CONFLICT = 11
    PHANTOM_READ_CONFLICT = 12
    UNKNOWN_TX_TYPE = 13
    TARGET_CHAIN_NOT_FOUND = 14
    MARSHAL_TX_ERROR = 15
    NIL_TXACTION = 16
    EXPIRED_CHAINCODE = 17
    CHAINCODE_VERSION_CONFLICT = 18
    BAD_HEADER_EXTENSION = 19
    BAD_CHANNEL_HEADER = 20
    BAD_RESPONSE_PAYLOAD = 21
    BAD_RWSET = 22
    ILLEGAL_WRITESET = 23
    INVALID_WRITESET = 24
    INVALID_CHAINCODE = 25
    NOT_VALIDATED = 254
    INVALID_OTHER_REASON = 255


@message
class ChannelHeader(Msg):
    FIELDS = ((1, "type", "i"), (2, "version", "i"), (3, "timestamp", "u"),
              (4, "channel_id", "s"), (5, "tx_id", "s"), (6, "epoch", "u"),
              (7, "extension", "b"), (8, "tls_cert_hash", "b"))
    type: int = 0
    version: int = 0
    timestamp: int = 0          # unix nanos (proto uses Timestamp msg)
    channel_id: str = ""
    tx_id: str = ""
    epoch: int = 0
    extension: bytes = b""
    tls_cert_hash: bytes = b""


@message
class SignatureHeader(Msg):
    FIELDS = ((1, "creator", "b"), (2, "nonce", "b"))
    creator: bytes = b""
    nonce: bytes = b""


@message
class Header(Msg):
    FIELDS = ((1, "channel_header", "b"), (2, "signature_header", "b"))
    channel_header: bytes = b""
    signature_header: bytes = b""


@message
class Payload(Msg):
    FIELDS = ((1, "header", ("m", "Header")), (2, "data", "b"))
    header: Optional[Header] = None
    data: bytes = b""


@message
class Envelope(Msg):
    FIELDS = ((1, "payload", "b"), (2, "signature", "b"))
    payload: bytes = b""
    signature: bytes = b""


@message
class BlockHeader(Msg):
    FIELDS = ((1, "number", "u"), (2, "previous_hash", "b"),
              (3, "data_hash", "b"))
    number: int = 0
    previous_hash: bytes = b""
    data_hash: bytes = b""


@message
class BlockData(Msg):
    FIELDS = ((1, "data", ["b"]),)
    data: List[bytes] = _f(default_factory=list)


@message
class MetadataSignature(Msg):
    FIELDS = ((1, "signature_header", "b"), (2, "signature", "b"))
    signature_header: bytes = b""
    signature: bytes = b""


@message
class Metadata(Msg):
    FIELDS = ((1, "value", "b"),
              (2, "signatures", [("m", "MetadataSignature")]))
    value: bytes = b""
    signatures: List[MetadataSignature] = _f(default_factory=list)


class BlockMetadataIndex:
    SIGNATURES = 0
    LAST_CONFIG = 1           # deprecated in ref; kept for layout parity
    TRANSACTIONS_FILTER = 2
    COMMIT_HASH = 4


@message
class BlockMetadata(Msg):
    FIELDS = ((1, "metadata", ["b"]),)
    metadata: List[bytes] = _f(default_factory=list)


@message
class Block(Msg):
    FIELDS = ((1, "header", ("m", "BlockHeader")),
              (2, "data", ("m", "BlockData")),
              (3, "metadata", ("m", "BlockMetadata")))
    header: Optional[BlockHeader] = None
    data: Optional[BlockData] = None
    metadata: Optional[BlockMetadata] = None


@message
class LastConfig(Msg):
    FIELDS = ((1, "index", "u"),)
    index: int = 0


# --- msp/identities.proto --------------------------------------------------

@message
class SerializedIdentity(Msg):
    FIELDS = ((1, "mspid", "s"), (2, "id_bytes", "b"))
    mspid: str = ""
    id_bytes: bytes = b""       # PEM cert


# --- common/policies.proto -------------------------------------------------

@message
class NOutOf(Msg):
    FIELDS = ((1, "n", "i"), (2, "rules", [("m", "SignaturePolicy")]))
    n: int = 0
    rules: List["SignaturePolicy"] = _f(default_factory=list)


@message
class SignaturePolicy(Msg):
    # proto oneof: a leaf is signed_by (an identities index, 0 is
    # meaningful so the usual zero-suppression cannot apply), an inner
    # node is n_out_of.  Custom encode keeps the invariant explicit.
    FIELDS = ((1, "signed_by", "i"), (2, "n_out_of", ("m", "NOutOf")))
    signed_by: int = -1
    n_out_of: Optional[NOutOf] = None

    def encode(self) -> bytes:
        from fabric_mod_tpu.protos import wire
        out = bytearray()
        if self.n_out_of is None:
            wire._write_tag(out, 1, 0)
            wire.write_varint(out, self.signed_by)
        else:
            wire._write_len_delim(out, 2, self.n_out_of.encode())
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "SignaturePolicy":
        m = super().decode(buf)
        # wire default for an inner node: mark leaf side unset
        if m.n_out_of is not None:
            m.signed_by = -1
        return m


class MSPRoleType:
    MEMBER = 0
    ADMIN = 1
    CLIENT = 2
    PEER = 3
    ORDERER = 4


@message
class MSPRole(Msg):
    FIELDS = ((1, "msp_identifier", "s"), (2, "role", "i"))
    msp_identifier: str = ""
    role: int = 0


class PrincipalClassification:
    ROLE = 0
    ORGANIZATION_UNIT = 1
    IDENTITY = 2


@message
class OrganizationUnit(Msg):
    FIELDS = ((1, "msp_identifier", "s"),
              (2, "organizational_unit_identifier", "s"),
              (3, "certifiers_identifier", "b"))
    msp_identifier: str = ""
    organizational_unit_identifier: str = ""
    certifiers_identifier: bytes = b""


@message
class MSPPrincipal(Msg):
    FIELDS = ((1, "principal_classification", "i"), (2, "principal", "b"))
    principal_classification: int = 0
    principal: bytes = b""


@message
class SignaturePolicyEnvelope(Msg):
    FIELDS = ((1, "version", "i"), (2, "rule", ("m", "SignaturePolicy")),
              (3, "identities", [("m", "MSPPrincipal")]))
    version: int = 0
    rule: Optional[SignaturePolicy] = None
    identities: List[MSPPrincipal] = _f(default_factory=list)


class PolicyType:
    # common/policies.proto Policy.PolicyType
    UNKNOWN = 0
    SIGNATURE = 1
    MSP = 2
    IMPLICIT_META = 3


@message
class Policy(Msg):
    FIELDS = ((1, "type", "i"), (2, "value", "b"))
    type: int = 0
    value: bytes = b""


class ImplicitMetaRule:
    ANY = 0
    ALL = 1
    MAJORITY = 2


@message
class ImplicitMetaPolicy(Msg):
    FIELDS = ((1, "sub_policy", "s"), (2, "rule", "i"))
    sub_policy: str = ""
    rule: int = 0


@message
class ApplicationPolicy(Msg):
    # oneof: signature_policy or channel_config_policy_reference
    FIELDS = ((1, "signature_policy", ("m", "SignaturePolicyEnvelope")),
              (2, "channel_config_policy_reference", "s"))
    signature_policy: Optional[SignaturePolicyEnvelope] = None
    channel_config_policy_reference: str = ""


# --- peer/chaincode.proto --------------------------------------------------

@message
class ChaincodeID(Msg):
    FIELDS = ((1, "path", "s"), (2, "name", "s"), (3, "version", "s"))
    path: str = ""
    name: str = ""
    version: str = ""


@message
class ChaincodeInput(Msg):
    FIELDS = ((1, "args", ["b"]), (3, "is_init", "u"))
    args: List[bytes] = _f(default_factory=list)
    is_init: int = 0


@message
class ChaincodeSpec(Msg):
    FIELDS = ((1, "type", "i"), (2, "chaincode_id", ("m", "ChaincodeID")),
              (3, "input", ("m", "ChaincodeInput")), (4, "timeout", "i"))
    type: int = 0
    chaincode_id: Optional[ChaincodeID] = None
    input: Optional[ChaincodeInput] = None
    timeout: int = 0


@message
class ChaincodeInvocationSpec(Msg):
    FIELDS = ((1, "chaincode_spec", ("m", "ChaincodeSpec")),)
    chaincode_spec: Optional[ChaincodeSpec] = None


@message
class ChaincodeHeaderExtension(Msg):
    FIELDS = ((2, "chaincode_id", ("m", "ChaincodeID")),)
    chaincode_id: Optional[ChaincodeID] = None


# --- peer/proposal.proto ---------------------------------------------------

@message
class Proposal(Msg):
    FIELDS = ((1, "header", "b"), (2, "payload", "b"), (3, "extension", "b"))
    header: bytes = b""
    payload: bytes = b""
    extension: bytes = b""


@message
class SignedProposal(Msg):
    FIELDS = ((1, "proposal_bytes", "b"), (2, "signature", "b"))
    proposal_bytes: bytes = b""
    signature: bytes = b""


@message
class TransientMapEntry(Msg):
    FIELDS = ((1, "key", "s"), (2, "value", "b"))
    key: str = ""
    value: bytes = b""


@message
class ChaincodeProposalPayload(Msg):
    # TransientMap (field 2) carries side-channel inputs (private
    # data); it is STRIPPED when the payload embeds into a tx
    FIELDS = ((1, "input", "b"),
              (2, "transient_map", [("m", "TransientMapEntry")]))
    input: bytes = b""          # ChaincodeInvocationSpec bytes
    transient_map: List["TransientMapEntry"] = _f(default_factory=list)


@message
class Response(Msg):
    FIELDS = ((1, "status", "i"), (2, "message", "s"), (3, "payload", "b"))
    status: int = 0
    message: str = ""
    payload: bytes = b""


@message
class Endorsement(Msg):
    FIELDS = ((1, "endorser", "b"), (2, "signature", "b"))
    endorser: bytes = b""       # SerializedIdentity bytes
    signature: bytes = b""


@message
class ProposalResponse(Msg):
    FIELDS = ((1, "version", "i"), (2, "timestamp", "u"),
              (4, "response", ("m", "Response")), (5, "payload", "b"),
              (6, "endorsement", ("m", "Endorsement")))
    version: int = 0
    timestamp: int = 0
    response: Optional[Response] = None
    payload: bytes = b""        # ProposalResponsePayload bytes
    endorsement: Optional[Endorsement] = None


@message
class ChaincodeAction(Msg):
    FIELDS = ((1, "results", "b"), (2, "events", "b"),
              (3, "response", ("m", "Response")),
              (4, "chaincode_id", ("m", "ChaincodeID")))
    results: bytes = b""        # TxReadWriteSet bytes
    events: bytes = b""
    response: Optional[Response] = None
    chaincode_id: Optional[ChaincodeID] = None


@message
class ProposalResponsePayload(Msg):
    FIELDS = ((1, "proposal_hash", "b"), (2, "extension", "b"))
    proposal_hash: bytes = b""
    extension: bytes = b""      # ChaincodeAction bytes


# --- peer/transaction.proto ------------------------------------------------

@message
class ChaincodeEndorsedAction(Msg):
    FIELDS = ((1, "proposal_response_payload", "b"),
              (2, "endorsements", [("m", "Endorsement")]))
    proposal_response_payload: bytes = b""
    endorsements: List[Endorsement] = _f(default_factory=list)


@message
class ChaincodeActionPayload(Msg):
    FIELDS = ((1, "chaincode_proposal_payload", "b"),
              (2, "action", ("m", "ChaincodeEndorsedAction")))
    chaincode_proposal_payload: bytes = b""
    action: Optional[ChaincodeEndorsedAction] = None


@message
class TransactionAction(Msg):
    FIELDS = ((1, "header", "b"), (2, "payload", "b"))
    header: bytes = b""         # SignatureHeader bytes
    payload: bytes = b""        # ChaincodeActionPayload bytes


@message
class Transaction(Msg):
    FIELDS = ((1, "actions", [("m", "TransactionAction")]),)
    actions: List[TransactionAction] = _f(default_factory=list)


@message
class ProcessedTransaction(Msg):
    FIELDS = ((1, "transaction_envelope", ("m", "Envelope")),
              (2, "validation_code", "i"))
    transaction_envelope: Optional[Envelope] = None
    validation_code: int = 0


# --- ledger/rwset ----------------------------------------------------------

@message
class Version(Msg):
    FIELDS = ((1, "block_num", "u"), (2, "tx_num", "u"))
    block_num: int = 0
    tx_num: int = 0


@message
class KVRead(Msg):
    FIELDS = ((1, "key", "s"), (2, "version", ("m", "Version")))
    key: str = ""
    version: Optional[Version] = None


@message
class KVWrite(Msg):
    FIELDS = ((1, "key", "s"), (2, "is_delete", "u"), (3, "value", "b"))
    key: str = ""
    is_delete: int = 0
    value: bytes = b""


@message
class RangeQueryInfo(Msg):
    FIELDS = ((1, "start_key", "s"), (2, "end_key", "s"),
              (3, "itr_exhausted", "u"), (4, "reads_merkle_hash", "b"))
    start_key: str = ""
    end_key: str = ""
    itr_exhausted: int = 0
    reads_merkle_hash: bytes = b""


@message
class KVRWSet(Msg):
    FIELDS = ((1, "reads", [("m", "KVRead")]),
              (2, "range_queries_info", [("m", "RangeQueryInfo")]),
              (3, "writes", [("m", "KVWrite")]),
              (4, "metadata_writes", [("m", "KVMetadataWrite")]))
    reads: List[KVRead] = _f(default_factory=list)
    range_queries_info: List[RangeQueryInfo] = _f(default_factory=list)
    writes: List[KVWrite] = _f(default_factory=list)
    metadata_writes: List["KVMetadataWrite"] = _f(default_factory=list)


@message
class NsReadWriteSet(Msg):
    FIELDS = ((1, "namespace", "s"), (2, "rwset", "b"),
              (3, "collection_hashed_rwset",
               [("m", "CollectionHashedReadWriteSet")]))
    namespace: str = ""
    rwset: bytes = b""          # KVRWSet bytes
    collection_hashed_rwset: List["CollectionHashedReadWriteSet"] = \
        _f(default_factory=list)


@message
class TxReadWriteSet(Msg):
    FIELDS = ((1, "data_model", "i"),
              (2, "ns_rwset", [("m", "NsReadWriteSet")]))
    data_model: int = 0
    ns_rwset: List[NsReadWriteSet] = _f(default_factory=list)


# --- common/configtx.proto -------------------------------------------------
# Proto maps are repeated {key, value} entry messages on the wire; the
# channelconfig layer converts to/from dicts and keeps entries sorted by
# key so encodings stay deterministic (wire.py's consensus requirement).

@message
class ConfigSignature(Msg):
    FIELDS = ((1, "signature_header", "b"), (2, "signature", "b"))
    signature_header: bytes = b""
    signature: bytes = b""


@message
class ConfigUpdateEnvelope(Msg):
    FIELDS = ((1, "config_update", "b"),
              (2, "signatures", [("m", "ConfigSignature")]))
    config_update: bytes = b""  # ConfigUpdate bytes
    signatures: List[ConfigSignature] = _f(default_factory=list)


@message
class ConfigGroupEntry(Msg):
    FIELDS = ((1, "key", "s"), (2, "value", ("m", "ConfigGroup")))
    key: str = ""
    value: Optional["ConfigGroup"] = None


@message
class ConfigValueEntry(Msg):
    FIELDS = ((1, "key", "s"), (2, "value", ("m", "ConfigValue")))
    key: str = ""
    value: Optional["ConfigValue"] = None


@message
class ConfigPolicyEntry(Msg):
    FIELDS = ((1, "key", "s"), (2, "value", ("m", "ConfigPolicy")))
    key: str = ""
    value: Optional["ConfigPolicy"] = None


@message
class ConfigGroup(Msg):
    FIELDS = ((1, "version", "u"),
              (2, "groups", [("m", "ConfigGroupEntry")]),
              (3, "values", [("m", "ConfigValueEntry")]),
              (4, "policies", [("m", "ConfigPolicyEntry")]),
              (5, "mod_policy", "s"))
    version: int = 0
    groups: List[ConfigGroupEntry] = _f(default_factory=list)
    values: List[ConfigValueEntry] = _f(default_factory=list)
    policies: List[ConfigPolicyEntry] = _f(default_factory=list)
    mod_policy: str = ""


@message
class ConfigValue(Msg):
    FIELDS = ((1, "version", "u"), (2, "value", "b"), (3, "mod_policy", "s"))
    version: int = 0
    value: bytes = b""
    mod_policy: str = ""


@message
class ConfigPolicy(Msg):
    FIELDS = ((1, "version", "u"), (2, "policy", ("m", "Policy")),
              (3, "mod_policy", "s"))
    version: int = 0
    policy: Optional[Policy] = None
    mod_policy: str = ""


@message
class Config(Msg):
    FIELDS = ((1, "sequence", "u"), (2, "channel_group", ("m", "ConfigGroup")))
    sequence: int = 0
    channel_group: Optional[ConfigGroup] = None


@message
class ConfigEnvelope(Msg):
    FIELDS = ((1, "config", ("m", "Config")), (2, "last_update", ("m", "Envelope")))
    config: Optional[Config] = None
    last_update: Optional[Envelope] = None


@message
class ConfigUpdate(Msg):
    FIELDS = ((1, "channel_id", "s"), (2, "read_set", ("m", "ConfigGroup")),
              (3, "write_set", ("m", "ConfigGroup")))
    channel_id: str = ""
    read_set: Optional[ConfigGroup] = None
    write_set: Optional[ConfigGroup] = None


# --- common/configuration.proto + orderer/configuration.proto values -------

@message
class HashingAlgorithm(Msg):
    FIELDS = ((1, "name", "s"),)
    name: str = ""


@message
class BlockDataHashingStructure(Msg):
    FIELDS = ((1, "width", "u"),)
    width: int = 0


@message
class OrdererAddresses(Msg):
    FIELDS = ((1, "addresses", ["s"]),)
    addresses: List[str] = _f(default_factory=list)


@message
class Capability(Msg):
    FIELDS = ()


@message
class CapabilityEntry(Msg):
    FIELDS = ((1, "key", "s"), (2, "value", ("m", "Capability")))
    key: str = ""
    value: Optional[Capability] = None


@message
class Capabilities(Msg):
    FIELDS = ((1, "capabilities", [("m", "CapabilityEntry")]),)
    capabilities: List[CapabilityEntry] = _f(default_factory=list)


@message
class BatchSize(Msg):
    FIELDS = ((1, "max_message_count", "u"), (2, "absolute_max_bytes", "u"),
              (3, "preferred_max_bytes", "u"))
    max_message_count: int = 0
    absolute_max_bytes: int = 0
    preferred_max_bytes: int = 0


@message
class BatchTimeout(Msg):
    FIELDS = ((1, "timeout", "s"),)   # duration string, e.g. "2s"
    timeout: str = ""


@message
class ConsensusType(Msg):
    FIELDS = ((1, "type", "s"), (2, "metadata", "b"), (3, "state", "i"))
    type: str = ""
    metadata: bytes = b""
    state: int = 0


@message
class RaftMetadata(Msg):
    """Consenter set carried in ConsensusType.metadata (reference:
    etcdraft.ConfigMetadata — ours lists transport node ids; consenter
    TLS identity is pinned at the cluster-comm layer)."""
    FIELDS = ((1, "consenters", ["s"]),)
    consenters: List[str] = _f(default_factory=list)


# --- msp/msp_config.proto --------------------------------------------------

@message
class FabricOUIdentifier(Msg):
    FIELDS = ((1, "certificate", "b"),
              (2, "organizational_unit_identifier", "s"))
    certificate: bytes = b""
    organizational_unit_identifier: str = ""


@message
class FabricNodeOUs(Msg):
    FIELDS = ((1, "enable", "u"),
              (2, "client_ou_identifier", ("m", "FabricOUIdentifier")),
              (3, "peer_ou_identifier", ("m", "FabricOUIdentifier")),
              (4, "admin_ou_identifier", ("m", "FabricOUIdentifier")),
              (5, "orderer_ou_identifier", ("m", "FabricOUIdentifier")))
    enable: int = 0
    client_ou_identifier: Optional[FabricOUIdentifier] = None
    peer_ou_identifier: Optional[FabricOUIdentifier] = None
    admin_ou_identifier: Optional[FabricOUIdentifier] = None
    orderer_ou_identifier: Optional[FabricOUIdentifier] = None


@message
class FabricMSPConfig(Msg):
    FIELDS = ((1, "name", "s"), (2, "root_certs", ["b"]),
              (3, "intermediate_certs", ["b"]), (4, "admins", ["b"]),
              (5, "revocation_list", ["b"]),
              (11, "fabric_node_ous", ("m", "FabricNodeOUs")))
    name: str = ""
    root_certs: List[bytes] = _f(default_factory=list)      # PEM
    intermediate_certs: List[bytes] = _f(default_factory=list)
    admins: List[bytes] = _f(default_factory=list)
    revocation_list: List[bytes] = _f(default_factory=list)  # DER CRLs
    fabric_node_ous: Optional[FabricNodeOUs] = None


@message
class MSPConfig(Msg):
    FIELDS = ((1, "type", "i"), (2, "config", "b"))
    type: int = 0               # 0 = FABRIC (X.509)
    config: bytes = b""         # FabricMSPConfig bytes


# --- key-level validation metadata (ledger/rwset kvrwset.proto) ------------

@message
class KVMetadataEntry(Msg):
    FIELDS = ((1, "name", "s"), (2, "value", "b"))
    name: str = ""
    value: bytes = b""


@message
class KVMetadataWrite(Msg):
    FIELDS = ((1, "key", "s"), (2, "entries", [("m", "KVMetadataEntry")]))
    key: str = ""
    entries: List[KVMetadataEntry] = _f(default_factory=list)


# --- chaincode lifecycle definition (the committed state record the
# --- validation-info provider resolves; reference: core/chaincode/
# --- lifecycle's namespaces/fields state keys, collapsed to one record) ----

@message
class ChaincodeDefinition(Msg):
    FIELDS = ((1, "sequence", "u"), (2, "version", "s"),
              (3, "endorsement_policy", "b"),
              (4, "validation_plugin", "s"), (5, "init_required", "u"),
              (6, "collections", "b"))
    sequence: int = 0
    version: str = ""
    endorsement_policy: bytes = b""     # ApplicationPolicy bytes
    validation_plugin: str = ""
    init_required: int = 0
    collections: bytes = b""            # CollectionConfigPackage bytes


# --- orderer/ab.proto (broadcast/deliver service messages) -----------------

class Status:
    # common/common.proto Status (the HTTP-ish codes the reference uses)
    UNKNOWN = 0
    SUCCESS = 200
    BAD_REQUEST = 400
    FORBIDDEN = 403
    NOT_FOUND = 404
    REQUEST_ENTITY_TOO_LARGE = 413
    # admission shed (orderer/admission.py): retryable, with a
    # retry-after hint serialized in BroadcastResponse.info (the gRPC
    # RESOURCE_EXHAUSTED analog on the reference's HTTP-ish scale)
    RESOURCE_EXHAUSTED = 429
    INTERNAL_SERVER_ERROR = 500
    NOT_IMPLEMENTED = 501
    SERVICE_UNAVAILABLE = 503


@message
class BroadcastResponse(Msg):
    FIELDS = ((1, "status", "i"), (2, "info", "s"))
    status: int = 0
    info: str = ""


@message
class SeekNewest(Msg):
    FIELDS = ()


@message
class SeekOldest(Msg):
    FIELDS = ()


@message
class SeekSpecified(Msg):
    FIELDS = ((1, "number", "u"),)
    number: int = 0


@message
class SeekPosition(Msg):
    # oneof: newest / oldest / specified
    FIELDS = ((1, "newest", ("m", "SeekNewest")),
              (2, "oldest", ("m", "SeekOldest")),
              (3, "specified", ("m", "SeekSpecified")))
    newest: Optional[SeekNewest] = None
    oldest: Optional[SeekOldest] = None
    specified: Optional[SeekSpecified] = None


class SeekBehavior:
    BLOCK_UNTIL_READY = 0
    FAIL_IF_NOT_READY = 1


@message
class SeekInfo(Msg):
    FIELDS = ((1, "start", ("m", "SeekPosition")),
              (2, "stop", ("m", "SeekPosition")),
              (3, "behavior", "i"))
    start: Optional[SeekPosition] = None
    stop: Optional[SeekPosition] = None
    behavior: int = 0


@message
class DeliverResponse(Msg):
    # oneof: status / block / filtered_block (the filtered arm is the
    # peer event service's response, peer/events.proto DeliverResponse)
    FIELDS = ((1, "status", "i"), (2, "block", ("m", "Block")),
              (3, "filtered_block", ("m", "FilteredBlock")))
    status: int = 0
    block: Optional[Block] = None
    filtered_block: Optional["FilteredBlock"] = None


# --- peer/events.proto (client-facing event deliver service) ---------------
# (reference: core/peer/deliverevents.go:240-310 — the filtered-block
# shape SDKs consume to learn a tx's validation code)

@message
class ChaincodeEvent(Msg):
    # peer/chaincode_event.proto
    FIELDS = ((1, "chaincode_id", "s"), (2, "tx_id", "s"),
              (3, "event_name", "s"), (4, "payload", "b"))
    chaincode_id: str = ""
    tx_id: str = ""
    event_name: str = ""
    payload: bytes = b""


@message
class FilteredChaincodeAction(Msg):
    FIELDS = ((1, "chaincode_event", ("m", "ChaincodeEvent")),)
    chaincode_event: Optional[ChaincodeEvent] = None


@message
class FilteredTransactionActions(Msg):
    FIELDS = ((1, "chaincode_actions",
               [("m", "FilteredChaincodeAction")]),)
    chaincode_actions: List[FilteredChaincodeAction] = _f(
        default_factory=list)


@message
class FilteredTransaction(Msg):
    FIELDS = ((1, "txid", "s"), (2, "type", "i"),
              (3, "tx_validation_code", "i"),
              (4, "transaction_actions",
               ("m", "FilteredTransactionActions")))
    txid: str = ""
    type: int = 0               # HeaderType
    tx_validation_code: int = 0
    transaction_actions: Optional[FilteredTransactionActions] = None


@message
class FilteredBlock(Msg):
    # field 3 is skipped in peer/events.proto: filtered_transactions
    # is 4 (SDK wire parity)
    FIELDS = ((1, "channel_id", "s"), (2, "number", "u"),
              (4, "filtered_transactions", [("m", "FilteredTransaction")]))
    channel_id: str = ""
    number: int = 0
    filtered_transactions: List[FilteredTransaction] = _f(
        default_factory=list)


# --- gossip/message.proto (the epidemic layer's wire messages) -------------

@message
class GossipMember(Msg):
    FIELDS = ((1, "endpoint", "s"), (2, "metadata", "b"),
              (3, "pki_id", "b"))
    endpoint: str = ""
    metadata: bytes = b""
    pki_id: bytes = b""


@message
class PeerTime(Msg):
    FIELDS = ((1, "inc_num", "u"), (2, "seq_num", "u"))
    inc_num: int = 0            # process incarnation (boot time)
    seq_num: int = 0            # monotonic within incarnation


@message
class AliveMessage(Msg):
    FIELDS = ((1, "membership", ("m", "GossipMember")),
              (2, "timestamp", ("m", "PeerTime")),
              (4, "identity", "b"))
    membership: Optional[GossipMember] = None
    timestamp: Optional[PeerTime] = None
    identity: bytes = b""       # SerializedIdentity


@message
class GossipPayload(Msg):
    FIELDS = ((1, "seq_num", "u"), (2, "data", "b"))
    seq_num: int = 0            # block number
    data: bytes = b""           # Block bytes


@message
class DataMessage(Msg):
    FIELDS = ((1, "payload", ("m", "GossipPayload")),)
    payload: Optional[GossipPayload] = None


@message
class GossipHello(Msg):
    FIELDS = ((1, "nonce", "u"), (2, "metadata", "b"), (3, "msg_type", "i"))
    nonce: int = 0
    metadata: bytes = b""
    msg_type: int = 0


@message
class DataDigest(Msg):
    FIELDS = ((1, "nonce", "u"), (2, "digests", ["b"]), (3, "msg_type", "i"))
    nonce: int = 0
    digests: List[bytes] = _f(default_factory=list)
    msg_type: int = 0


@message
class DataRequest(Msg):
    FIELDS = ((1, "nonce", "u"), (2, "digests", ["b"]), (3, "msg_type", "i"))
    nonce: int = 0
    digests: List[bytes] = _f(default_factory=list)
    msg_type: int = 0


@message
class DataUpdate(Msg):
    FIELDS = ((1, "nonce", "u"), (2, "data", [("m", "GossipEnvelope")]),
              (3, "msg_type", "i"))
    nonce: int = 0
    data: List["GossipEnvelope"] = _f(default_factory=list)
    msg_type: int = 0


@message
class PvtDataElement(Msg):
    FIELDS = ((1, "txid", "s"), (2, "payload", "b"))
    txid: str = ""
    payload: bytes = b""        # TxPvtReadWriteSet bytes


@message
class PvtDataDigest(Msg):
    """Identifies one missing private write-set (reference:
    gossip/protoext + the reconciler's PvtDataDigest)."""
    FIELDS = ((1, "block_num", "u"), (2, "tx_num", "u"),
              (3, "namespace", "s"), (4, "collection", "s"))
    block_num: int = 0
    tx_num: int = 0
    namespace: str = ""
    collection: str = ""


@message
class PvtDataRequest(Msg):
    FIELDS = ((1, "nonce", "u"), (2, "digests", [("m", "PvtDataDigest")]))
    nonce: int = 0
    digests: List["PvtDataDigest"] = _f(default_factory=list)


@message
class PvtDataResponseElement(Msg):
    FIELDS = ((1, "digest", ("m", "PvtDataDigest")), (2, "rwset", "b"))
    digest: Optional[PvtDataDigest] = None
    rwset: bytes = b""          # KVRWSet bytes (plaintext writes)


@message
class PvtDataResponse(Msg):
    FIELDS = ((1, "nonce", "u"),
              (2, "elements", [("m", "PvtDataResponseElement")]))
    nonce: int = 0
    elements: List[PvtDataResponseElement] = _f(default_factory=list)


@message
class RelayMessage(Msg):
    """One relayed deliver frame: the leader's once-encoded
    DeliverResponse bytes pushed down the dissemination tree verbatim
    (dissemination/relay.py) — a receiving peer forwards the SAME
    bytes to its children, so every hop ships what a direct orderer
    pull would have returned."""
    FIELDS = ((1, "seq_num", "u"), (2, "frame", "b"), (3, "config", "u"),
              (4, "epoch", "u"))
    seq_num: int = 0            # block number
    frame: bytes = b""          # DeliverResponse wire bytes
    config: int = 0             # carries a channel config tx
    epoch: int = 0              # sender's tree epoch


@message
class GossipMessage(Msg):
    # oneof payload: alive/data/hello/digest/request/update/private
    FIELDS = ((1, "nonce", "u"), (2, "channel", "b"), (3, "tag", "i"),
              (5, "alive_msg", ("m", "AliveMessage")),
              (6, "data_msg", ("m", "DataMessage")),
              (7, "hello", ("m", "GossipHello")),
              (8, "data_dig", ("m", "DataDigest")),
              (9, "data_req", ("m", "DataRequest")),
              (10, "data_update", ("m", "DataUpdate")),
              (11, "private_data", ("m", "PvtDataElement")),
              (12, "pvt_req", ("m", "PvtDataRequest")),
              (13, "pvt_resp", ("m", "PvtDataResponse")),
              (14, "relay_msg", ("m", "RelayMessage")))
    nonce: int = 0
    channel: bytes = b""
    tag: int = 0
    alive_msg: Optional[AliveMessage] = None
    data_msg: Optional[DataMessage] = None
    hello: Optional[GossipHello] = None
    data_dig: Optional[DataDigest] = None
    data_req: Optional[DataRequest] = None
    data_update: Optional[DataUpdate] = None
    private_data: Optional["PvtDataElement"] = None
    pvt_req: Optional[PvtDataRequest] = None
    pvt_resp: Optional[PvtDataResponse] = None
    relay_msg: Optional[RelayMessage] = None


@message
class GossipEnvelope(Msg):
    FIELDS = ((1, "payload", "b"), (2, "signature", "b"))
    payload: bytes = b""        # GossipMessage bytes
    signature: bytes = b""


# --- private data: collections + hashed rwsets -----------------------------
# (reference: peer/collection.proto + ledger/rwset/kvrwset.proto's
# hashed read/write sets and rwset.proto's TxPvtReadWriteSet)

@message
class StaticCollectionConfig(Msg):
    FIELDS = ((1, "name", "s"),
              (2, "member_orgs_policy", ("m", "SignaturePolicyEnvelope")),
              (3, "required_peer_count", "i"),
              (4, "maximum_peer_count", "i"),
              (5, "block_to_live", "u"),
              (6, "member_only_read", "u"),
              (7, "member_only_write", "u"))
    name: str = ""
    member_orgs_policy: Optional[SignaturePolicyEnvelope] = None
    required_peer_count: int = 0
    maximum_peer_count: int = 0
    block_to_live: int = 0      # 0 = never expires
    member_only_read: int = 0
    member_only_write: int = 0


@message
class CollectionConfig(Msg):
    FIELDS = ((1, "static_collection_config",
               ("m", "StaticCollectionConfig")),)
    static_collection_config: Optional[StaticCollectionConfig] = None


@message
class CollectionConfigPackage(Msg):
    FIELDS = ((1, "config", [("m", "CollectionConfig")]),)
    config: List[CollectionConfig] = _f(default_factory=list)


@message
class KVWriteHash(Msg):
    FIELDS = ((1, "key_hash", "b"), (2, "is_delete", "u"),
              (3, "value_hash", "b"))
    key_hash: bytes = b""
    is_delete: int = 0
    value_hash: bytes = b""


@message
class KVReadHash(Msg):
    FIELDS = ((1, "key_hash", "b"), (2, "version", ("m", "Version")))
    key_hash: bytes = b""
    version: Optional[Version] = None


@message
class HashedRWSet(Msg):
    FIELDS = ((1, "hashed_reads", [("m", "KVReadHash")]),
              (2, "hashed_writes", [("m", "KVWriteHash")]))
    hashed_reads: List[KVReadHash] = _f(default_factory=list)
    hashed_writes: List[KVWriteHash] = _f(default_factory=list)


@message
class CollectionHashedReadWriteSet(Msg):
    FIELDS = ((1, "collection_name", "s"), (2, "hashed_rwset", "b"))
    collection_name: str = ""
    hashed_rwset: bytes = b""   # HashedRWSet bytes


@message
class CollectionPvtReadWriteSet(Msg):
    FIELDS = ((1, "collection_name", "s"), (2, "rwset", "b"))
    collection_name: str = ""
    rwset: bytes = b""          # KVRWSet bytes (plaintext)


@message
class NsPvtReadWriteSet(Msg):
    FIELDS = ((1, "namespace", "s"),
              (2, "collection_pvt_rwset",
               [("m", "CollectionPvtReadWriteSet")]))
    namespace: str = ""
    collection_pvt_rwset: List[CollectionPvtReadWriteSet] = \
        _f(default_factory=list)


@message
class TxPvtReadWriteSet(Msg):
    FIELDS = ((1, "data_model", "i"),
              (2, "ns_pvt_rwset", [("m", "NsPvtReadWriteSet")]))
    data_model: int = 0
    ns_pvt_rwset: List[NsPvtReadWriteSet] = _f(default_factory=list)
