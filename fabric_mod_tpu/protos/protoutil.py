"""Envelope/block/proposal construction and extraction helpers.

The equivalent of the reference's protoutil package (reference:
protoutil/commonutils.go, protoutil/proputils.go,
protoutil/blockutils.go, protoutil/signeddata.go, protoutil/txutils.go)
— every layer above builds and unpacks wire messages through here.

Hashing conventions (deterministic, but intentionally *not* byte-
compatible with the reference — this is a new framework, not a fork):
* tx_id = hex(sha256(nonce ‖ creator)) — same recipe as the ref.
* block data hash = sha256 over the concatenation of the block's tx
  envelope encodings.
* block header hash = sha256 of the header's wire encoding (the ref
  uses ASN.1 here; ours is the same deterministic proto encoding used
  everywhere else).
"""
from __future__ import annotations

import hashlib
import os
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

from fabric_mod_tpu.protos import messages as m


@dataclass(frozen=True)
class SignedData:
    """The universal (data, identity, signature) triple every policy
    check consumes (reference: protoutil/signeddata.go)."""
    data: bytes
    identity: bytes             # SerializedIdentity bytes
    signature: bytes


def compute_tx_id(nonce: bytes, creator: bytes) -> str:
    return hashlib.sha256(nonce + creator).hexdigest()


def new_nonce() -> bytes:
    return os.urandom(24)


def now_ns() -> int:
    return time.time_ns()


def make_channel_header(htype: int, channel_id: str, tx_id: str = "",
                        epoch: int = 0, extension: bytes = b"",
                        timestamp: Optional[int] = None) -> m.ChannelHeader:
    return m.ChannelHeader(type=htype, version=0,
                           timestamp=now_ns() if timestamp is None else timestamp,
                           channel_id=channel_id, tx_id=tx_id, epoch=epoch,
                           extension=extension)


def make_signature_header(creator: bytes, nonce: bytes) -> m.SignatureHeader:
    return m.SignatureHeader(creator=creator, nonce=nonce)


def make_payload(ch: m.ChannelHeader, sh: m.SignatureHeader,
                 data: bytes) -> m.Payload:
    return m.Payload(
        header=m.Header(channel_header=ch.encode(),
                        signature_header=sh.encode()),
        data=data)


def sign_envelope(payload: m.Payload, signer) -> m.Envelope:
    """signer: object with .sign_message(msg: bytes) -> bytes."""
    pb = payload.encode()
    return m.Envelope(payload=pb, signature=signer.sign_message(pb))


def unmarshal_envelope_payload(env: m.Envelope) -> m.Payload:
    return m.Payload.decode(env.payload)


def envelope_channel_header(env: m.Envelope) -> m.ChannelHeader:
    pl = m.Payload.decode(env.payload)
    return m.ChannelHeader.decode(pl.header.channel_header)


def envelope_as_signed_data(env: m.Envelope) -> List[SignedData]:
    """(reference: protoutil/signeddata.go EnvelopeAsSignedData)."""
    pl = m.Payload.decode(env.payload)
    sh = m.SignatureHeader.decode(pl.header.signature_header)
    return [SignedData(data=env.payload, identity=sh.creator,
                       signature=env.signature)]


# --- blocks ---------------------------------------------------------------

def create_signed_tx(channel_id: str, chaincode_ns: str,
                     results: bytes, creator, endorsers: Sequence,
                     response_payload: bytes = b"",
                     events: bytes = b"") -> m.Envelope:
    """Assemble a fully-signed endorser transaction
    (reference: protoutil/txutils.go CreateSignedTx).

    `creator` and each endorser are SigningIdentity-shaped (serialize()
    + sign_message()).  Each endorsement signs
    proposal-response-payload ‖ endorser-identity — exactly the
    signature-set data the validator reconstructs
    (statebased/validator_keylevel.go:245-258).
    """
    nonce = new_nonce()
    creator_bytes = creator.serialize()
    tx_id = compute_tx_id(nonce, creator_bytes)
    cca = m.ChaincodeAction(
        results=results, events=events,
        response=m.Response(status=200, payload=response_payload),
        chaincode_id=m.ChaincodeID(name=chaincode_ns))
    prp = m.ProposalResponsePayload(
        proposal_hash=hashlib.sha256(tx_id.encode()).digest(),
        extension=cca.encode())
    prp_bytes = prp.encode()
    endorsements = [
        m.Endorsement(endorser=e.serialize(),
                      signature=e.sign_message(prp_bytes + e.serialize()))
        for e in endorsers]
    cap = m.ChaincodeActionPayload(action=m.ChaincodeEndorsedAction(
        proposal_response_payload=prp_bytes, endorsements=endorsements))
    tx = m.Transaction(actions=[m.TransactionAction(payload=cap.encode())])
    ch = make_channel_header(m.HeaderType.ENDORSER_TRANSACTION,
                             channel_id, tx_id=tx_id)
    sh = make_signature_header(creator_bytes, nonce)
    payload = make_payload(ch, sh, tx.encode())
    return sign_envelope(payload, creator)


def block_data_hash(data: m.BlockData) -> bytes:
    h = hashlib.sha256()
    for d in data.data:
        h.update(d)
    return h.digest()


def block_header_hash(header: m.BlockHeader) -> bytes:
    return hashlib.sha256(header.encode()).digest()


def new_block(number: int, previous_hash: bytes,
              envelopes: Sequence[m.Envelope]) -> m.Block:
    data = m.BlockData(data=[e.encode() for e in envelopes])
    header = m.BlockHeader(number=number, previous_hash=previous_hash,
                           data_hash=block_data_hash(data))
    ntx = len(data.data)
    flags = bytes([m.TxValidationCode.NOT_VALIDATED] * ntx)
    meta = m.BlockMetadata(metadata=[b"", b"", flags, b"", b""])
    return m.Block(header=header, data=data, metadata=meta)


def block_txflags(block: m.Block) -> bytearray:
    """The per-tx validation-code bitmap stored in block metadata
    (reference: internal/pkg/txflags)."""
    md = block.metadata.metadata
    idx = m.BlockMetadataIndex.TRANSACTIONS_FILTER
    ntx = len(block.data.data)
    if len(md) > idx and len(md[idx]) == ntx:
        return bytearray(md[idx])
    return bytearray([m.TxValidationCode.NOT_VALIDATED] * ntx)


def set_block_txflags(block: m.Block, flags: bytes) -> None:
    md = block.metadata.metadata
    idx = m.BlockMetadataIndex.TRANSACTIONS_FILTER
    while len(md) <= idx:
        md.append(b"")
    md[idx] = bytes(flags)


def get_envelopes(block: m.Block) -> List[m.Envelope]:
    return [m.Envelope.decode(d) for d in block.data.data]


# --- transactions ----------------------------------------------------------

def extract_endorser_tx(payload: m.Payload) -> m.Transaction:
    return m.Transaction.decode(payload.data)


def tx_rwset_and_endorsements(action: m.TransactionAction):
    """Unpack one action -> (ChaincodeAction, prp_bytes, endorsements).

    prp_bytes is the exact ProposalResponsePayload encoding the
    endorsers signed over (together with the endorser identity) — the
    signature-set data for endorsement-policy checks (reference:
    core/common/validation/statebased/validator_keylevel.go:245-258).
    """
    cap = m.ChaincodeActionPayload.decode(action.payload)
    prp_bytes = cap.action.proposal_response_payload
    prp = m.ProposalResponsePayload.decode(prp_bytes)
    cca = m.ChaincodeAction.decode(prp.extension)
    return cca, prp_bytes, cap.action.endorsements


# --- proposals (the endorsement flow) --------------------------------------

def create_chaincode_proposal(channel_id: str, chaincode_ns: str,
                              args: Sequence[bytes], creator,
                              transient: "Optional[dict]" = None
                              ) -> "tuple[m.SignedProposal, m.Proposal, str]":
    """Client-side proposal construction + signature
    (reference: protoutil/proputils.go CreateChaincodeProposal +
    GetSignedProposal).  Returns (signed_proposal, proposal, tx_id).
    `transient` carries side-channel inputs (private data plaintext)
    that never reach the ordered transaction."""
    nonce = new_nonce()
    creator_bytes = creator.serialize()
    tx_id = compute_tx_id(nonce, creator_bytes)
    cis = m.ChaincodeInvocationSpec(chaincode_spec=m.ChaincodeSpec(
        chaincode_id=m.ChaincodeID(name=chaincode_ns),
        input=m.ChaincodeInput(args=list(args))))
    ext = m.ChaincodeHeaderExtension(
        chaincode_id=m.ChaincodeID(name=chaincode_ns))
    ch = make_channel_header(m.HeaderType.ENDORSER_TRANSACTION, channel_id,
                             tx_id=tx_id)
    ch.extension = ext.encode()
    sh = make_signature_header(creator_bytes, nonce)
    header = m.Header(channel_header=ch.encode(),
                      signature_header=sh.encode())
    ccpp = m.ChaincodeProposalPayload(
        input=cis.encode(),
        transient_map=[m.TransientMapEntry(key=k, value=v)
                       for k, v in sorted((transient or {}).items())])
    prop = m.Proposal(header=header.encode(), payload=ccpp.encode())
    prop_bytes = prop.encode()
    sp = m.SignedProposal(proposal_bytes=prop_bytes,
                          signature=creator.sign_message(prop_bytes))
    return sp, prop, tx_id


def create_tx_from_responses(prop: m.Proposal,
                             responses: "Sequence[m.ProposalResponse]",
                             creator) -> m.Envelope:
    """Assemble the transaction envelope from a proposal and the
    endorsers' responses (reference: protoutil/txutils.go
    CreateSignedTx — requires all response payloads identical)."""
    if not responses:
        raise ValueError("no proposal responses")
    prp_bytes = responses[0].payload
    for r in responses[1:]:
        if r.payload != prp_bytes:
            raise ValueError("proposal response payloads differ")
    for r in responses:
        if r.response is None or r.response.status != 200:
            raise ValueError("endorsement failed: "
                             f"{r.response.message if r.response else '?'}")
    header = m.Header.decode(prop.header)
    # strip the transient map: side-channel inputs (private data)
    # must never enter the ordered transaction (reference:
    # txutils.go's proposal-payload visibility handling)
    ccpp = m.ChaincodeProposalPayload.decode(prop.payload)
    clean_ccpp = m.ChaincodeProposalPayload(input=ccpp.input)
    cap = m.ChaincodeActionPayload(
        chaincode_proposal_payload=clean_ccpp.encode(),
        action=m.ChaincodeEndorsedAction(
            proposal_response_payload=prp_bytes,
            endorsements=[r.endorsement for r in responses]))
    tx = m.Transaction(actions=[m.TransactionAction(
        header=header.signature_header, payload=cap.encode())])
    payload = m.Payload(header=header, data=tx.encode())
    return sign_envelope(payload, creator)


def block_last_config_index(block: m.Block) -> "Optional[int]":
    """The last-config pointer from a committed block's SIGNATURES
    metadata, or None (reference: protoutil/blockutils.go
    GetLastConfigIndexFromBlock)."""
    md = block.metadata.metadata if block.metadata else []
    idx = m.BlockMetadataIndex.SIGNATURES
    if len(md) <= idx or not md[idx]:
        return None
    try:
        meta = m.Metadata.decode(md[idx])
        return m.LastConfig.decode(meta.value).index
    except Exception:
        return None


def seek_number(pos, height: int, newest_tip: bool):
    """Decode one SeekPosition against a chain height — the shared
    convention of every deliver surface (orderer AtomicBroadcast and
    the peer event service; reference: common/deliver/deliver.go:199).

    start positions (`newest_tip=True`): newest pins the current tip
    block, absent/unknown defaults to oldest.  stop positions: newest
    (or absent) means "no stop — stream forever"."""
    if pos is None:
        return None
    if pos.specified is not None:
        return pos.specified.number
    if pos.oldest is not None:
        return 0
    if pos.newest is not None:
        return max(0, height - 1) if newest_tip else None
    return None if not newest_tip else 0
