"""Vectorized block-spine decode: one varint/field scan for all txs.

PR 9's trace attribution put the stage bucket at 98% ``unpack`` — the
per-tx host loop that runs the generic ``Msg.decode`` four layers deep
(Envelope -> Payload -> Header -> ChannelHeader/SignatureHeader) for
every transaction of a block, rebuilding field tables and dataclass
kwargs tx by tx.  This module extends the PR 1 vectorized-DER
precedent (bccsp/der.py) one layer up: the protobuf wire grammar of
the fixed envelope spine evaluated as numpy array arithmetic over the
whole block at once — tag varints, length varints, and bounds checks
are batched gathers/masks, and only the final (tiny) per-row object
construction stays in python.

Correctness stance (same as der.py): the scanner's ACCEPTANCE must be
sound, not complete.  A row the scanner accepts produces values
identical to the generic decoder (differential-tested, including
zero-suppressed defaults, unknown-field skipping and wire-type
enforcement); any row it cannot prove clean — truncated varints,
>9-byte varints, unknown wire types, known fields on the wrong wire
type, DUPLICATED known fields (the generic decoder parses every
occurrence of a submessage/string field, so last-wins acceptance is
only sound for a single one), trailing bytes, malformed UTF-8 — comes back
as ``None`` and the caller re-runs the generic per-tx decoder, which
owns the verdict for malformed inputs.  The scanner therefore can
never *change* a validation outcome, only skip redundant host work.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from fabric_mod_tpu.protos import messages as m

# the spine never carries more fields per message than this; rows with
# more fall back to the generic decoder
_MAX_FIELDS = 12
# varints longer than 9 bytes (shift > 56) fall back: vectorizing the
# 10-byte two's-complement tail is not worth it for fields that are
# timestamps and enums in practice
_MAX_VARINT = 9


class SpineRow:
    """One tx's batch-decoded spine: the exact objects the per-tx
    staging loop would have decoded itself."""

    __slots__ = ("env", "payload", "ch", "sh")

    def __init__(self, env: m.Envelope, payload: m.Payload,
                 ch: m.ChannelHeader, sh: m.SignatureHeader):
        self.env = env
        self.payload = payload
        self.ch = ch
        self.sh = sh


def _read_varints(flat: np.ndarray, pos: np.ndarray, active: np.ndarray,
                  width: int = _MAX_VARINT):
    """Vectorized varint decode at per-row byte offsets.

    Returns (value uint64, nbytes int64, ok bool) — rows with no
    terminator within `width` bytes come back ok=False (the caller
    falls back to the generic decoder for them; `width` is sized per
    call site: tags are 1-2 bytes, lengths < 2^28, only field VALUES
    need the full 9).  Reads are clipped to the flat buffer; the
    caller's bounds checks reject any row whose varint would have
    crossed its span, so clipped/neighbor bytes never influence an
    accepted row's value.
    """
    k = min(width, _MAX_VARINT) + 1
    idx = pos[:, None] + np.arange(k, dtype=np.int64)
    b = flat[np.minimum(idx, flat.size - 1)].astype(np.uint64)
    stop = (b & np.uint64(0x80)) == 0
    first_stop = np.argmax(stop, axis=1)
    nbytes = first_stop.astype(np.int64) + 1
    ok = active & stop.any(axis=1) & (nbytes <= k - 1)
    take = np.arange(k)[None, :] < nbytes[:, None]
    shifts = (np.uint64(7) * np.arange(k, dtype=np.uint64))[None, :]
    val = np.where(take, (b & np.uint64(0x7F)) << shifts,
                   np.uint64(0)).sum(axis=1, dtype=np.uint64)
    return val, nbytes, ok


def scan_message(flat: np.ndarray, starts: np.ndarray, ends: np.ndarray,
                 spec: dict, max_fields: int = _MAX_FIELDS):
    """Scan one message layer for every row at once.

    `spec` maps field number -> kind ("u"/"i" varint, "r" a REPEATED
    length-delimited field — wire-type enforced but not captured and
    not dup-rejected, for declared repeated fields the caller does not
    read, e.g. endorsements — anything else a single length-delimited
    span).  Returns (results, ok): results[num] is a
    dict of (val, off, ln, present) arrays (absent -> default; a
    DUPLICATED known field rejects its row — see the module
    docstring); ok marks rows
    whose ENTIRE span parsed cleanly under the wire rules the generic
    decoder enforces.  Rows entering with start == end are trivially
    ok (an empty message decodes to all defaults).
    """
    n = starts.size
    pos = starts.astype(np.int64).copy()
    ends = ends.astype(np.int64)
    ok = np.ones(n, bool)
    res = {num: {"val": np.zeros(n, np.uint64),
                 "off": np.zeros(n, np.int64),
                 "ln": np.zeros(n, np.int64),
                 "present": np.zeros(n, bool)}
           for num, kind in spec.items() if kind != "r"}
    zero = np.int64(0)
    for _ in range(max_fields):
        active = ok & (pos < ends)
        if not active.any():
            break
        # spine tags are single-byte (field <= 15); a 2-byte budget
        # still accepts any field the specs name, and higher unknown
        # fields just fall back
        tagv, tagn, tok = _read_varints(flat, pos, active, width=2)
        ok &= np.where(active, tok, True)
        active &= tok
        pos2 = pos + np.where(active, tagn, zero)
        wt = (tagv & np.uint64(7)).astype(np.int64)
        num = (tagv >> np.uint64(3)).astype(np.int64)

        is0 = active & (wt == 0)
        if is0.any():
            v0, n0, ok0 = _read_varints(flat, pos2, is0)
            ok &= np.where(is0, ok0 & (pos2 + n0 <= ends), True)
        else:                         # no varint fields this round
            v0 = np.zeros(n, np.uint64)
            n0 = np.zeros(n, np.int64)

        is2 = active & (wt == 2)
        l2, n2, ok2 = _read_varints(flat, pos2, is2, width=4)
        l2i = l2.astype(np.int64)
        body = pos2 + n2
        ok &= np.where(is2, ok2 & (l2 < np.uint64(1 << 31))
                       & (body + l2i <= ends), True)

        is5 = active & (wt == 5)
        is1 = active & (wt == 1)
        ok &= np.where(is5, pos2 + 4 <= ends, True)
        ok &= np.where(is1, pos2 + 8 <= ends, True)
        ok &= ~(active & ~(is0 | is2 | is5 | is1))

        hitrow = active & ok
        for fnum, kind in spec.items():
            hit = hitrow & (num == fnum)
            if kind == "r":
                # declared repeated field the caller skips: every
                # occurrence must still be length-delimited (the
                # generic decoder raises otherwise), nothing captured
                ok &= ~(hit & (wt != 2))
                continue
            want0 = kind in ("u", "i")
            # the generic decoder raises on a known field arriving on
            # the wrong wire type — reject the row so the fallback
            # reproduces that outcome
            ok &= ~(hit & (wt != (0 if want0 else 2)))
            # DUPLICATED known fields also fall back: the generic
            # decoder parses EVERY occurrence of a submessage/string
            # field (and raises on a malformed non-last one) while
            # this scanner would only validate the last — last-wins
            # acceptance is only sound when there is exactly one
            ok &= ~(hit & res[fnum]["present"])
            hit &= ok
            slot = res[fnum]
            if want0:
                slot["val"] = np.where(hit, v0, slot["val"])
            else:
                slot["off"] = np.where(hit, body, slot["off"])
                slot["ln"] = np.where(hit, l2i, slot["ln"])
            slot["present"] |= hit

        adv = np.where(is0, n0, zero)
        adv = np.where(is2, n2 + l2i, adv)
        adv = np.where(is5, np.int64(4), adv)
        adv = np.where(is1, np.int64(8), adv)
        pos = np.where(active & ok, pos2 + adv, pos)
    # anything still unconsumed (more fields than the scan budget, or
    # a parse that stalled) is a fallback row, not a verdict
    ok &= pos >= ends
    return res, ok


_ENV_SPEC = {1: "b", 2: "b"}
_PAYLOAD_SPEC = {1: "b", 2: "b"}
_HEADER_SPEC = {1: "b", 2: "b"}
_SH_SPEC = {1: "b", 2: "b"}
_CH_SPEC = {1: "i", 2: "i", 3: "u", 4: "s", 5: "s", 6: "u",
            7: "b", 8: "b"}


def _span(res: dict, num: int):
    return res[num]["off"], res[num]["ln"]


def decode_block_spine(datas: Sequence[bytes]
                       ) -> List[Optional[SpineRow]]:
    """Batch-decode the Envelope/Payload/Header spine of a whole block.

    Returns one entry per tx: a SpineRow whose decoded objects are
    value-identical to the generic per-tx decode, or None for any row
    the scanner could not prove clean (the caller falls back to the
    generic decoder for exactly those rows).  Rows with an empty or
    absent payload, or an absent payload.header, are also None: their
    flag outcome (NIL_ENVELOPE / BAD_PAYLOAD) belongs to the per-tx
    path's own error handling.
    """
    n = len(datas)
    out: List[Optional[SpineRow]] = [None] * n
    if n < 4:
        return out                    # numpy setup beats tiny blocks
    try:
        lens = np.fromiter(map(len, datas), np.int64, n)
        joined = b"".join(datas)
    except TypeError:
        return out
    if not joined:
        return out
    flat = np.frombuffer(joined, np.uint8)
    starts = np.zeros(n, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    ends = starts + lens

    # L1: Envelope(payload, signature)
    env_res, ok = scan_message(flat, starts, ends, _ENV_SPEC)
    pay_off, pay_ln = _span(env_res, 1)
    ok &= env_res[1]["present"] & (pay_ln > 0)

    def gated(off, ln):
        """Empty spans for already-rejected rows: the layer scan is a
        no-op there (ok stays whatever it was)."""
        return np.where(ok, off, 0), np.where(ok, off + ln, 0)

    # L2: Payload(header, data)
    s2, e2 = gated(pay_off, pay_ln)
    pl_res, ok2 = scan_message(flat, s2, e2, _PAYLOAD_SPEC)
    ok &= ok2 & pl_res[1]["present"]
    hdr_off, hdr_ln = _span(pl_res, 1)
    data_off, data_ln = _span(pl_res, 2)

    # L3: Header(channel_header, signature_header)
    s3, e3 = gated(hdr_off, hdr_ln)
    h_res, ok3 = scan_message(flat, s3, e3, _HEADER_SPEC)
    ok &= ok3
    ch_off, ch_ln = _span(h_res, 1)
    sh_off, sh_ln = _span(h_res, 2)

    # L4: ChannelHeader (all eight fields) + SignatureHeader
    s4, e4 = gated(ch_off, ch_ln)
    ch_res, ok4 = scan_message(flat, s4, e4, _CH_SPEC)
    ok &= ok4
    s5, e5 = gated(sh_off, sh_ln)
    sh_res, ok5 = scan_message(flat, s5, e5, _SH_SPEC)
    ok &= ok5

    sig_off, sig_ln = _span(env_res, 2)
    cre_off, cre_ln = _span(sh_res, 1)
    non_off, non_ln = _span(sh_res, 2)
    ext_off, ext_ln = _span(ch_res, 7)
    tls_off, tls_ln = _span(ch_res, 8)
    cid_off, cid_ln = _span(ch_res, 4)
    tid_off, tid_ln = _span(ch_res, 5)

    # python-native lists for the construction loop: indexing numpy
    # scalars row by row costs more than the whole scan
    (pay_o, pay_l, sig_o, sig_l, data_o, data_l, ch_o, ch_l, sh_o,
     sh_l, cre_o, cre_l, non_o, non_l, ext_o, ext_l, tls_o, tls_l,
     cid_o, cid_l, tid_o, tid_l) = (
        a.tolist() for a in (
            pay_off, pay_ln, sig_off, sig_ln, data_off, data_ln,
            ch_off, ch_ln, sh_off, sh_ln, cre_off, cre_ln, non_off,
            non_ln, ext_off, ext_ln, tls_off, tls_ln, cid_off,
            cid_ln, tid_off, tid_ln))
    ch_type = ch_res[1]["val"].tolist()
    ch_ver = ch_res[2]["val"].tolist()
    ch_ts = ch_res[3]["val"].tolist()
    ch_epoch = ch_res[6]["val"].tolist()

    for i in np.nonzero(ok)[0].tolist():
        try:
            channel_id = joined[cid_o[i]:cid_o[i] + cid_l[i]].decode()
            tx_id = joined[tid_o[i]:tid_o[i] + tid_l[i]].decode()
        except UnicodeDecodeError:
            continue                  # generic decode raises: fallback
        env = m.Envelope(
            payload=joined[pay_o[i]:pay_o[i] + pay_l[i]],
            signature=joined[sig_o[i]:sig_o[i] + sig_l[i]])
        payload = m.Payload(
            header=m.Header(
                channel_header=joined[ch_o[i]:ch_o[i] + ch_l[i]],
                signature_header=joined[sh_o[i]:sh_o[i] + sh_l[i]]),
            data=joined[data_o[i]:data_o[i] + data_l[i]])
        ch = m.ChannelHeader(
            type=ch_type[i], version=ch_ver[i],
            timestamp=ch_ts[i], channel_id=channel_id,
            tx_id=tx_id, epoch=ch_epoch[i],
            extension=joined[ext_o[i]:ext_o[i] + ext_l[i]],
            tls_cert_hash=joined[tls_o[i]:tls_o[i] + tls_l[i]])
        sh = m.SignatureHeader(
            creator=joined[cre_o[i]:cre_o[i] + cre_l[i]],
            nonce=joined[non_o[i]:non_o[i] + non_l[i]])
        out[i] = SpineRow(env, payload, ch, sh)
    return out


# ---------------------------------------------------------------------------
# Tx-body layers (ISSUE 17): the deliver fan-out's shared filtered
# projection walks Transaction -> TransactionAction ->
# ChaincodeActionPayload -> ChaincodeEndorsedAction ->
# ProposalResponsePayload -> ChaincodeAction -> ChaincodeEvent — the
# "residual per-tx staging python" tail — in the same one-scan-per-
# layer style as the spine.  Every DECLARED field of each message is
# in its spec so a wrong-wire-type occurrence rejects the row exactly
# where the generic decoder would raise; `actions` is spec'd single
# (a multi-action tx dup-rejects into the sound per-tx fallback) and
# `endorsements` is spec'd "r" (repeated, skipped, wire-enforced).
# ---------------------------------------------------------------------------

_TX_SPEC = {1: "b"}                    # Transaction.actions (1 action)
_TXA_SPEC = {1: "b", 2: "b"}           # TransactionAction
_CAP_SPEC = {1: "b", 2: "b"}           # ChaincodeActionPayload
_CEA_SPEC = {1: "b", 2: "r"}           # ChaincodeEndorsedAction
_PRP_SPEC = {1: "b", 2: "b"}           # ProposalResponsePayload
_CCA_SPEC = {1: "b", 2: "b", 3: "b", 4: "b"}   # ChaincodeAction
_CEV_SPEC = {1: "s", 2: "s", 3: "s", 4: "b"}   # ChaincodeEvent


def decode_filtered_actions(tx_datas: Sequence[Optional[bytes]]
                            ) -> List[Optional[
                                m.FilteredTransactionActions]]:
    """Batch-build FilteredTransactionActions for a block's endorser
    txs (payload.data per tx; None rows are skipped).

    Same contract as :func:`decode_block_spine`: an entry is either
    value-identical to the per-tx generic path
    (``deliverevents._filtered_actions`` — chaincode event payloads
    STRIPPED) or ``None``, and the caller re-runs the generic decoder
    for exactly the ``None`` rows, which keeps ownership of every
    malformed-input outcome.
    """
    n = len(tx_datas)
    out: List[Optional[m.FilteredTransactionActions]] = [None] * n
    live = [i for i, d in enumerate(tx_datas) if d is not None]
    nl = len(live)
    if nl < 4:
        return out                    # numpy setup beats tiny batches
    try:
        lens = np.fromiter((len(tx_datas[i]) for i in live), np.int64, nl)
        joined = b"".join(tx_datas[i] for i in live)
    except TypeError:
        return out
    if not joined:
        return out
    flat = np.frombuffer(joined, np.uint8)
    starts = np.zeros(nl, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    ends = starts + lens

    def gated(off, ln, mask):
        """Empty spans for rows outside `mask`: their layer scan is a
        trivially-ok no-op (absent parents stay absent)."""
        return np.where(mask, off, 0), np.where(mask, off + ln, 0)

    # L1: Transaction(actions) — dup field 1 (a multi-action tx)
    # rejects into the fallback, so accepted rows have 0 or 1 action
    tx_res, ok = scan_message(flat, starts, ends, _TX_SPEC)
    act_off, act_ln = _span(tx_res, 1)
    act_present = tx_res[1]["present"]

    # L2: TransactionAction(header, payload)
    s, e = gated(act_off, act_ln, ok & act_present)
    ta_res, ok2 = scan_message(flat, s, e, _TXA_SPEC)
    ok &= ok2
    pay_off, pay_ln = _span(ta_res, 2)

    # L3: ChaincodeActionPayload(ccpp, action)
    s, e = gated(pay_off, pay_ln, ok & act_present)
    cap_res, ok3 = scan_message(flat, s, e, _CAP_SPEC)
    ok &= ok3
    ea_off, ea_ln = _span(cap_res, 2)
    # absent action => the generic loop `continue`s (empty actions
    # list); PRESENT-but-empty still decodes the cascade of defaults
    ea_present = cap_res[2]["present"]

    # L4: ChaincodeEndorsedAction(prp, endorsements*)
    deep = ok & act_present & ea_present
    s, e = gated(ea_off, ea_ln, deep)
    cea_res, ok4 = scan_message(flat, s, e, _CEA_SPEC)
    ok &= ok4
    prp_off, prp_ln = _span(cea_res, 1)

    # L5: ProposalResponsePayload(hash, extension)
    s, e = gated(prp_off, prp_ln, deep)
    prp_res, ok5 = scan_message(flat, s, e, _PRP_SPEC)
    ok &= ok5
    ext_off, ext_ln = _span(prp_res, 2)

    # L6: ChaincodeAction(results, events, response, chaincode_id)
    s, e = gated(ext_off, ext_ln, deep)
    cca_res, ok6 = scan_message(flat, s, e, _CCA_SPEC)
    ok &= ok6
    ev_off, ev_ln = _span(cca_res, 2)

    # L7: ChaincodeEvent — only for non-empty `events` (the generic
    # path's `if cca.events:` truthiness gate)
    has_ev = deep & (ev_ln > 0)
    s, e = gated(ev_off, ev_ln, ok & has_ev)
    cev_res, ok7 = scan_message(flat, s, e, _CEV_SPEC)
    ok &= ok7

    ccid_o, ccid_l = (a.tolist() for a in _span(cev_res, 1))
    txid_o, txid_l = (a.tolist() for a in _span(cev_res, 2))
    name_o, name_l = (a.tolist() for a in _span(cev_res, 3))
    act_p = act_present.tolist()
    ea_p = ea_present.tolist()
    has_e = has_ev.tolist()

    for j in np.nonzero(ok)[0].tolist():
        i = live[j]
        if not (act_p[j] and ea_p[j]):
            out[i] = m.FilteredTransactionActions(chaincode_actions=[])
            continue
        event = None
        if has_e[j]:
            try:
                event = m.ChaincodeEvent(
                    chaincode_id=joined[ccid_o[j]:ccid_o[j]
                                        + ccid_l[j]].decode(),
                    tx_id=joined[txid_o[j]:txid_o[j]
                                 + txid_l[j]].decode(),
                    event_name=joined[name_o[j]:name_o[j]
                                      + name_l[j]].decode())
            except UnicodeDecodeError:
                continue              # generic decode raises: fallback
        out[i] = m.FilteredTransactionActions(
            chaincode_actions=[m.FilteredChaincodeAction(
                chaincode_event=event)])
    return out
