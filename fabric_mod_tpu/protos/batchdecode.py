"""Vectorized block-spine decode: one varint/field scan for all txs.

PR 9's trace attribution put the stage bucket at 98% ``unpack`` — the
per-tx host loop that runs the generic ``Msg.decode`` four layers deep
(Envelope -> Payload -> Header -> ChannelHeader/SignatureHeader) for
every transaction of a block, rebuilding field tables and dataclass
kwargs tx by tx.  This module extends the PR 1 vectorized-DER
precedent (bccsp/der.py) one layer up: the protobuf wire grammar of
the fixed envelope spine evaluated as numpy array arithmetic over the
whole block at once — tag varints, length varints, and bounds checks
are batched gathers/masks, and only the final (tiny) per-row object
construction stays in python.

Correctness stance (same as der.py): the scanner's ACCEPTANCE must be
sound, not complete.  A row the scanner accepts produces values
identical to the generic decoder (differential-tested, including
zero-suppressed defaults, unknown-field skipping and wire-type
enforcement); any row it cannot prove clean — truncated varints,
>9-byte varints, unknown wire types, known fields on the wrong wire
type, DUPLICATED known fields (the generic decoder parses every
occurrence of a submessage/string field, so last-wins acceptance is
only sound for a single one), trailing bytes, malformed UTF-8 — comes back
as ``None`` and the caller re-runs the generic per-tx decoder, which
owns the verdict for malformed inputs.  The scanner therefore can
never *change* a validation outcome, only skip redundant host work.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from fabric_mod_tpu.protos import messages as m

# the spine never carries more fields per message than this; rows with
# more fall back to the generic decoder
_MAX_FIELDS = 12
# varints longer than 9 bytes (shift > 56) fall back: vectorizing the
# 10-byte two's-complement tail is not worth it for fields that are
# timestamps and enums in practice
_MAX_VARINT = 9


class SpineRow:
    """One tx's batch-decoded spine: the exact objects the per-tx
    staging loop would have decoded itself."""

    __slots__ = ("env", "payload", "ch", "sh")

    def __init__(self, env: m.Envelope, payload: m.Payload,
                 ch: m.ChannelHeader, sh: m.SignatureHeader):
        self.env = env
        self.payload = payload
        self.ch = ch
        self.sh = sh


def _read_varints(flat: np.ndarray, pos: np.ndarray, active: np.ndarray,
                  width: int = _MAX_VARINT):
    """Vectorized varint decode at per-row byte offsets.

    Returns (value uint64, nbytes int64, ok bool) — rows with no
    terminator within `width` bytes come back ok=False (the caller
    falls back to the generic decoder for them; `width` is sized per
    call site: tags are 1-2 bytes, lengths < 2^28, only field VALUES
    need the full 9).  Reads are clipped to the flat buffer; the
    caller's bounds checks reject any row whose varint would have
    crossed its span, so clipped/neighbor bytes never influence an
    accepted row's value.
    """
    k = min(width, _MAX_VARINT) + 1
    idx = pos[:, None] + np.arange(k, dtype=np.int64)
    b = flat[np.minimum(idx, flat.size - 1)].astype(np.uint64)
    stop = (b & np.uint64(0x80)) == 0
    first_stop = np.argmax(stop, axis=1)
    nbytes = first_stop.astype(np.int64) + 1
    ok = active & stop.any(axis=1) & (nbytes <= k - 1)
    take = np.arange(k)[None, :] < nbytes[:, None]
    shifts = (np.uint64(7) * np.arange(k, dtype=np.uint64))[None, :]
    val = np.where(take, (b & np.uint64(0x7F)) << shifts,
                   np.uint64(0)).sum(axis=1, dtype=np.uint64)
    return val, nbytes, ok


def scan_message(flat: np.ndarray, starts: np.ndarray, ends: np.ndarray,
                 spec: dict, max_fields: int = _MAX_FIELDS):
    """Scan one message layer for every row at once.

    `spec` maps field number -> kind ("u"/"i" varint, "r" a REPEATED
    length-delimited field — wire-type enforced but not captured and
    not dup-rejected, for declared repeated fields the caller does not
    read, e.g. endorsements — anything else a single length-delimited
    span).  Returns (results, ok): results[num] is a
    dict of (val, off, ln, present) arrays (absent -> default; a
    DUPLICATED known field rejects its row — see the module
    docstring); ok marks rows
    whose ENTIRE span parsed cleanly under the wire rules the generic
    decoder enforces.  Rows entering with start == end are trivially
    ok (an empty message decodes to all defaults).
    """
    n = starts.size
    pos = starts.astype(np.int64).copy()
    ends = ends.astype(np.int64)
    ok = np.ones(n, bool)
    res = {num: {"val": np.zeros(n, np.uint64),
                 "off": np.zeros(n, np.int64),
                 "ln": np.zeros(n, np.int64),
                 "present": np.zeros(n, bool)}
           for num, kind in spec.items() if kind != "r"}
    zero = np.int64(0)
    for _ in range(max_fields):
        active = ok & (pos < ends)
        if not active.any():
            break
        # spine tags are single-byte (field <= 15); a 2-byte budget
        # still accepts any field the specs name, and higher unknown
        # fields just fall back
        tagv, tagn, tok = _read_varints(flat, pos, active, width=2)
        ok &= np.where(active, tok, True)
        active &= tok
        pos2 = pos + np.where(active, tagn, zero)
        wt = (tagv & np.uint64(7)).astype(np.int64)
        num = (tagv >> np.uint64(3)).astype(np.int64)

        is0 = active & (wt == 0)
        if is0.any():
            v0, n0, ok0 = _read_varints(flat, pos2, is0)
            ok &= np.where(is0, ok0 & (pos2 + n0 <= ends), True)
        else:                         # no varint fields this round
            v0 = np.zeros(n, np.uint64)
            n0 = np.zeros(n, np.int64)

        is2 = active & (wt == 2)
        l2, n2, ok2 = _read_varints(flat, pos2, is2, width=4)
        l2i = l2.astype(np.int64)
        body = pos2 + n2
        ok &= np.where(is2, ok2 & (l2 < np.uint64(1 << 31))
                       & (body + l2i <= ends), True)

        is5 = active & (wt == 5)
        is1 = active & (wt == 1)
        ok &= np.where(is5, pos2 + 4 <= ends, True)
        ok &= np.where(is1, pos2 + 8 <= ends, True)
        ok &= ~(active & ~(is0 | is2 | is5 | is1))

        hitrow = active & ok
        for fnum, kind in spec.items():
            hit = hitrow & (num == fnum)
            if kind == "r":
                # declared repeated field the caller skips: every
                # occurrence must still be length-delimited (the
                # generic decoder raises otherwise), nothing captured
                ok &= ~(hit & (wt != 2))
                continue
            want0 = kind in ("u", "i")
            # the generic decoder raises on a known field arriving on
            # the wrong wire type — reject the row so the fallback
            # reproduces that outcome
            ok &= ~(hit & (wt != (0 if want0 else 2)))
            # DUPLICATED known fields also fall back: the generic
            # decoder parses EVERY occurrence of a submessage/string
            # field (and raises on a malformed non-last one) while
            # this scanner would only validate the last — last-wins
            # acceptance is only sound when there is exactly one
            ok &= ~(hit & res[fnum]["present"])
            hit &= ok
            slot = res[fnum]
            if want0:
                slot["val"] = np.where(hit, v0, slot["val"])
            else:
                slot["off"] = np.where(hit, body, slot["off"])
                slot["ln"] = np.where(hit, l2i, slot["ln"])
            slot["present"] |= hit

        adv = np.where(is0, n0, zero)
        adv = np.where(is2, n2 + l2i, adv)
        adv = np.where(is5, np.int64(4), adv)
        adv = np.where(is1, np.int64(8), adv)
        pos = np.where(active & ok, pos2 + adv, pos)
    # anything still unconsumed (more fields than the scan budget, or
    # a parse that stalled) is a fallback row, not a verdict
    ok &= pos >= ends
    return res, ok


_ENV_SPEC = {1: "b", 2: "b"}
_PAYLOAD_SPEC = {1: "b", 2: "b"}
_HEADER_SPEC = {1: "b", 2: "b"}
_SH_SPEC = {1: "b", 2: "b"}
_CH_SPEC = {1: "i", 2: "i", 3: "u", 4: "s", 5: "s", 6: "u",
            7: "b", 8: "b"}


def _span(res: dict, num: int):
    return res[num]["off"], res[num]["ln"]


def decode_block_spine(datas: Sequence[bytes]
                       ) -> List[Optional[SpineRow]]:
    """Batch-decode the Envelope/Payload/Header spine of a whole block.

    Returns one entry per tx: a SpineRow whose decoded objects are
    value-identical to the generic per-tx decode, or None for any row
    the scanner could not prove clean (the caller falls back to the
    generic decoder for exactly those rows).  Rows with an empty or
    absent payload, or an absent payload.header, are also None: their
    flag outcome (NIL_ENVELOPE / BAD_PAYLOAD) belongs to the per-tx
    path's own error handling.
    """
    n = len(datas)
    out: List[Optional[SpineRow]] = [None] * n
    if n < 4:
        return out                    # numpy setup beats tiny blocks
    try:
        lens = np.fromiter(map(len, datas), np.int64, n)
        joined = b"".join(datas)
    except TypeError:
        return out
    if not joined:
        return out
    flat = np.frombuffer(joined, np.uint8)
    starts = np.zeros(n, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    ends = starts + lens

    # L1: Envelope(payload, signature)
    env_res, ok = scan_message(flat, starts, ends, _ENV_SPEC)
    pay_off, pay_ln = _span(env_res, 1)
    ok &= env_res[1]["present"] & (pay_ln > 0)

    def gated(off, ln):
        """Empty spans for already-rejected rows: the layer scan is a
        no-op there (ok stays whatever it was)."""
        return np.where(ok, off, 0), np.where(ok, off + ln, 0)

    # L2: Payload(header, data)
    s2, e2 = gated(pay_off, pay_ln)
    pl_res, ok2 = scan_message(flat, s2, e2, _PAYLOAD_SPEC)
    ok &= ok2 & pl_res[1]["present"]
    hdr_off, hdr_ln = _span(pl_res, 1)
    data_off, data_ln = _span(pl_res, 2)

    # L3: Header(channel_header, signature_header)
    s3, e3 = gated(hdr_off, hdr_ln)
    h_res, ok3 = scan_message(flat, s3, e3, _HEADER_SPEC)
    ok &= ok3
    ch_off, ch_ln = _span(h_res, 1)
    sh_off, sh_ln = _span(h_res, 2)

    # L4: ChannelHeader (all eight fields) + SignatureHeader
    s4, e4 = gated(ch_off, ch_ln)
    ch_res, ok4 = scan_message(flat, s4, e4, _CH_SPEC)
    ok &= ok4
    s5, e5 = gated(sh_off, sh_ln)
    sh_res, ok5 = scan_message(flat, s5, e5, _SH_SPEC)
    ok &= ok5

    sig_off, sig_ln = _span(env_res, 2)
    cre_off, cre_ln = _span(sh_res, 1)
    non_off, non_ln = _span(sh_res, 2)
    ext_off, ext_ln = _span(ch_res, 7)
    tls_off, tls_ln = _span(ch_res, 8)
    cid_off, cid_ln = _span(ch_res, 4)
    tid_off, tid_ln = _span(ch_res, 5)

    # python-native lists for the construction loop: indexing numpy
    # scalars row by row costs more than the whole scan
    (pay_o, pay_l, sig_o, sig_l, data_o, data_l, ch_o, ch_l, sh_o,
     sh_l, cre_o, cre_l, non_o, non_l, ext_o, ext_l, tls_o, tls_l,
     cid_o, cid_l, tid_o, tid_l) = (
        a.tolist() for a in (
            pay_off, pay_ln, sig_off, sig_ln, data_off, data_ln,
            ch_off, ch_ln, sh_off, sh_ln, cre_off, cre_ln, non_off,
            non_ln, ext_off, ext_ln, tls_off, tls_ln, cid_off,
            cid_ln, tid_off, tid_ln))
    ch_type = ch_res[1]["val"].tolist()
    ch_ver = ch_res[2]["val"].tolist()
    ch_ts = ch_res[3]["val"].tolist()
    ch_epoch = ch_res[6]["val"].tolist()

    for i in np.nonzero(ok)[0].tolist():
        try:
            channel_id = joined[cid_o[i]:cid_o[i] + cid_l[i]].decode()
            tx_id = joined[tid_o[i]:tid_o[i] + tid_l[i]].decode()
        except UnicodeDecodeError:
            continue                  # generic decode raises: fallback
        env = m.Envelope(
            payload=joined[pay_o[i]:pay_o[i] + pay_l[i]],
            signature=joined[sig_o[i]:sig_o[i] + sig_l[i]])
        payload = m.Payload(
            header=m.Header(
                channel_header=joined[ch_o[i]:ch_o[i] + ch_l[i]],
                signature_header=joined[sh_o[i]:sh_o[i] + sh_l[i]]),
            data=joined[data_o[i]:data_o[i] + data_l[i]])
        ch = m.ChannelHeader(
            type=ch_type[i], version=ch_ver[i],
            timestamp=ch_ts[i], channel_id=channel_id,
            tx_id=tx_id, epoch=ch_epoch[i],
            extension=joined[ext_o[i]:ext_o[i] + ext_l[i]],
            tls_cert_hash=joined[tls_o[i]:tls_o[i] + tls_l[i]])
        sh = m.SignatureHeader(
            creator=joined[cre_o[i]:cre_o[i] + cre_l[i]],
            nonce=joined[non_o[i]:non_o[i] + non_l[i]])
        out[i] = SpineRow(env, payload, ch, sh)
    return out


# ---------------------------------------------------------------------------
# Tx-body layers (ISSUE 17): the deliver fan-out's shared filtered
# projection walks Transaction -> TransactionAction ->
# ChaincodeActionPayload -> ChaincodeEndorsedAction ->
# ProposalResponsePayload -> ChaincodeAction -> ChaincodeEvent — the
# "residual per-tx staging python" tail — in the same one-scan-per-
# layer style as the spine.  Every DECLARED field of each message is
# in its spec so a wrong-wire-type occurrence rejects the row exactly
# where the generic decoder would raise; `actions` is spec'd single
# (a multi-action tx dup-rejects into the sound per-tx fallback) and
# `endorsements` is spec'd "r" (repeated, skipped, wire-enforced).
# ---------------------------------------------------------------------------

_TX_SPEC = {1: "b"}                    # Transaction.actions (1 action)
_TXA_SPEC = {1: "b", 2: "b"}           # TransactionAction
_CAP_SPEC = {1: "b", 2: "b"}           # ChaincodeActionPayload
_CEA_SPEC = {1: "b", 2: "r"}           # ChaincodeEndorsedAction
_PRP_SPEC = {1: "b", 2: "b"}           # ProposalResponsePayload
_CCA_SPEC = {1: "b", 2: "b", 3: "b", 4: "b"}   # ChaincodeAction
_CEV_SPEC = {1: "s", 2: "s", 3: "s", 4: "b"}   # ChaincodeEvent


def decode_filtered_actions(tx_datas: Sequence[Optional[bytes]]
                            ) -> List[Optional[
                                m.FilteredTransactionActions]]:
    """Batch-build FilteredTransactionActions for a block's endorser
    txs (payload.data per tx; None rows are skipped).

    Same contract as :func:`decode_block_spine`: an entry is either
    value-identical to the per-tx generic path
    (``deliverevents._filtered_actions`` — chaincode event payloads
    STRIPPED) or ``None``, and the caller re-runs the generic decoder
    for exactly the ``None`` rows, which keeps ownership of every
    malformed-input outcome.
    """
    n = len(tx_datas)
    out: List[Optional[m.FilteredTransactionActions]] = [None] * n
    live = [i for i, d in enumerate(tx_datas) if d is not None]
    nl = len(live)
    if nl < 4:
        return out                    # numpy setup beats tiny batches
    try:
        lens = np.fromiter((len(tx_datas[i]) for i in live), np.int64, nl)
        joined = b"".join(tx_datas[i] for i in live)
    except TypeError:
        return out
    if not joined:
        return out
    flat = np.frombuffer(joined, np.uint8)
    starts = np.zeros(nl, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    ends = starts + lens

    def gated(off, ln, mask):
        """Empty spans for rows outside `mask`: their layer scan is a
        trivially-ok no-op (absent parents stay absent)."""
        return np.where(mask, off, 0), np.where(mask, off + ln, 0)

    # L1: Transaction(actions) — dup field 1 (a multi-action tx)
    # rejects into the fallback, so accepted rows have 0 or 1 action
    tx_res, ok = scan_message(flat, starts, ends, _TX_SPEC)
    act_off, act_ln = _span(tx_res, 1)
    act_present = tx_res[1]["present"]

    # L2: TransactionAction(header, payload)
    s, e = gated(act_off, act_ln, ok & act_present)
    ta_res, ok2 = scan_message(flat, s, e, _TXA_SPEC)
    ok &= ok2
    pay_off, pay_ln = _span(ta_res, 2)

    # L3: ChaincodeActionPayload(ccpp, action)
    s, e = gated(pay_off, pay_ln, ok & act_present)
    cap_res, ok3 = scan_message(flat, s, e, _CAP_SPEC)
    ok &= ok3
    ea_off, ea_ln = _span(cap_res, 2)
    # absent action => the generic loop `continue`s (empty actions
    # list); PRESENT-but-empty still decodes the cascade of defaults
    ea_present = cap_res[2]["present"]

    # L4: ChaincodeEndorsedAction(prp, endorsements*)
    deep = ok & act_present & ea_present
    s, e = gated(ea_off, ea_ln, deep)
    cea_res, ok4 = scan_message(flat, s, e, _CEA_SPEC)
    ok &= ok4
    prp_off, prp_ln = _span(cea_res, 1)

    # L5: ProposalResponsePayload(hash, extension)
    s, e = gated(prp_off, prp_ln, deep)
    prp_res, ok5 = scan_message(flat, s, e, _PRP_SPEC)
    ok &= ok5
    ext_off, ext_ln = _span(prp_res, 2)

    # L6: ChaincodeAction(results, events, response, chaincode_id)
    s, e = gated(ext_off, ext_ln, deep)
    cca_res, ok6 = scan_message(flat, s, e, _CCA_SPEC)
    ok &= ok6
    ev_off, ev_ln = _span(cca_res, 2)

    # L7: ChaincodeEvent — only for non-empty `events` (the generic
    # path's `if cca.events:` truthiness gate)
    has_ev = deep & (ev_ln > 0)
    s, e = gated(ev_off, ev_ln, ok & has_ev)
    cev_res, ok7 = scan_message(flat, s, e, _CEV_SPEC)
    ok &= ok7

    ccid_o, ccid_l = (a.tolist() for a in _span(cev_res, 1))
    txid_o, txid_l = (a.tolist() for a in _span(cev_res, 2))
    name_o, name_l = (a.tolist() for a in _span(cev_res, 3))
    act_p = act_present.tolist()
    ea_p = ea_present.tolist()
    has_e = has_ev.tolist()

    for j in np.nonzero(ok)[0].tolist():
        i = live[j]
        if not (act_p[j] and ea_p[j]):
            out[i] = m.FilteredTransactionActions(chaincode_actions=[])
            continue
        event = None
        if has_e[j]:
            try:
                event = m.ChaincodeEvent(
                    chaincode_id=joined[ccid_o[j]:ccid_o[j]
                                        + ccid_l[j]].decode(),
                    tx_id=joined[txid_o[j]:txid_o[j]
                                 + txid_l[j]].decode(),
                    event_name=joined[name_o[j]:name_o[j]
                                      + name_l[j]].decode())
            except UnicodeDecodeError:
                continue              # generic decode raises: fallback
        out[i] = m.FilteredTransactionActions(
            chaincode_actions=[m.FilteredChaincodeAction(
                chaincode_event=event)])
    return out


# ---------------------------------------------------------------------------
# Rwset columnar planes (ISSUE 18): extend the scan downward through
# the endorser-tx body — Transaction -> TransactionAction ->
# ChaincodeActionPayload -> ChaincodeEndorsedAction (endorsements
# COLLECTED this time, not skipped) -> ProposalResponsePayload ->
# ChaincodeAction (Response/ChaincodeID validated) -> TxReadWriteSet
# -> NsReadWriteSet -> KVRWSet -> KVRead/KVWrite/RangeQueryInfo/
# KVMetadataWrite — into flat per-block planes the MVCC stage can
# hash-join and compare with numpy.  Same soundness contract: any row
# (or any row whose ANY descendant) the scanner can't prove identical
# to the generic decoder falls back, counted, and the generic path
# owns the verdict.
# ---------------------------------------------------------------------------

_CEA_RW_SPEC = {1: "b", 2: "*"}        # ChaincodeEndorsedAction (collect)
_END_SPEC = {1: "b", 2: "b"}           # Endorsement
_RESP_SPEC = {1: "i", 2: "s", 3: "b"}  # Response
_CCID_SPEC = {1: "s", 2: "s", 3: "s"}  # ChaincodeID
_TXRW_SPEC = {1: "i", 2: "*"}          # TxReadWriteSet(ns_rwset*)
_NSRW_SPEC = {1: "s", 2: "b", 3: "*"}  # NsReadWriteSet(colls*)
_COLL_SPEC = {1: "s", 2: "b"}          # CollectionHashedReadWriteSet
_KVRW_SPEC = {1: "*", 2: "*", 3: "*", 4: "*"}  # KVRWSet (all collected)
_KVR_SPEC = {1: "s", 2: "b"}           # KVRead(key, version)
_VER_SPEC = {1: "u", 2: "u"}           # Version
_KVW_SPEC = {1: "s", 2: "u", 3: "b"}   # KVWrite
_RQI_SPEC = {1: "s", 2: "s", 3: "u", 4: "b"}   # RangeQueryInfo
_KVMW_SPEC = {1: "s", 2: "*"}          # KVMetadataWrite(entries*)
_KVME_SPEC = {1: "s", 2: "b"}          # KVMetadataEntry

# occurrence-collecting scans must outlast scan_message's 12-field
# budget: a KVRWSet row carries one field occurrence per read/write
_MAX_OCCURRENCES = 4096


def scan_collect(flat: np.ndarray, starts: np.ndarray, ends: np.ndarray,
                 spec: dict, max_iters: int = _MAX_OCCURRENCES):
    """scan_message variant whose "*" fields are REPEATED
    length-delimited fields with every occurrence COLLECTED.

    Returns (results, ok, reps): results/ok as scan_message (for the
    non-"*" fields; a "*" occurrence on the wrong wire type rejects
    its row), and reps[num] = (rows, offs, lns) int64 arrays — one
    entry per occurrence, grouped by row in stable document order,
    occurrences of rows that later failed filtered out.  The loop
    runs until no row is active (pos strictly advances for every
    active row each iteration, so it terminates); rows needing more
    than `max_iters` iterations fall back via the unconsumed check.
    """
    n = starts.size
    pos = starts.astype(np.int64).copy()
    ends = ends.astype(np.int64)
    ok = np.ones(n, bool)
    res = {num: {"val": np.zeros(n, np.uint64),
                 "off": np.zeros(n, np.int64),
                 "ln": np.zeros(n, np.int64),
                 "present": np.zeros(n, bool)}
           for num, kind in spec.items() if kind not in ("r", "*")}
    rep: dict = {num: [] for num, kind in spec.items() if kind == "*"}
    zero = np.int64(0)
    for _ in range(max_iters):
        active = ok & (pos < ends)
        if not active.any():
            break
        tagv, tagn, tok = _read_varints(flat, pos, active, width=2)
        ok &= np.where(active, tok, True)
        active &= tok
        pos2 = pos + np.where(active, tagn, zero)
        wt = (tagv & np.uint64(7)).astype(np.int64)
        num = (tagv >> np.uint64(3)).astype(np.int64)

        is0 = active & (wt == 0)
        if is0.any():
            v0, n0, ok0 = _read_varints(flat, pos2, is0)
            ok &= np.where(is0, ok0 & (pos2 + n0 <= ends), True)
        else:
            v0 = np.zeros(n, np.uint64)
            n0 = np.zeros(n, np.int64)

        is2 = active & (wt == 2)
        l2, n2, ok2 = _read_varints(flat, pos2, is2, width=4)
        l2i = l2.astype(np.int64)
        body = pos2 + n2
        ok &= np.where(is2, ok2 & (l2 < np.uint64(1 << 31))
                       & (body + l2i <= ends), True)

        is5 = active & (wt == 5)
        is1 = active & (wt == 1)
        ok &= np.where(is5, pos2 + 4 <= ends, True)
        ok &= np.where(is1, pos2 + 8 <= ends, True)
        ok &= ~(active & ~(is0 | is2 | is5 | is1))

        hitrow = active & ok
        for fnum, kind in spec.items():
            hit = hitrow & (num == fnum)
            if kind in ("r", "*"):
                ok &= ~(hit & (wt != 2))
                if kind == "*":
                    hit &= ok
                    if hit.any():
                        rep[fnum].append((np.nonzero(hit)[0],
                                          body[hit], l2i[hit]))
                continue
            want0 = kind in ("u", "i")
            ok &= ~(hit & (wt != (0 if want0 else 2)))
            ok &= ~(hit & res[fnum]["present"])
            hit &= ok
            slot = res[fnum]
            if want0:
                slot["val"] = np.where(hit, v0, slot["val"])
            else:
                slot["off"] = np.where(hit, body, slot["off"])
                slot["ln"] = np.where(hit, l2i, slot["ln"])
            slot["present"] |= hit

        adv = np.where(is0, n0, zero)
        adv = np.where(is2, n2 + l2i, adv)
        adv = np.where(is5, np.int64(4), adv)
        adv = np.where(is1, np.int64(8), adv)
        pos = np.where(active & ok, pos2 + adv, pos)
    ok &= pos >= ends
    empty = np.zeros(0, np.int64)
    reps = {}
    for fnum, chunks in rep.items():
        if not chunks:
            reps[fnum] = (empty, empty, empty)
            continue
        rows = np.concatenate([c[0] for c in chunks])
        offs = np.concatenate([c[1] for c in chunks])
        lns = np.concatenate([c[2] for c in chunks])
        keep = ok[rows]               # drop occurrences of failed rows
        rows, offs, lns = rows[keep], offs[keep], lns[keep]
        order = np.argsort(rows, kind="stable")
        reps[fnum] = (rows[order], offs[order], lns[order])
    return res, ok, reps


class TxBody:
    """One accepted tx's staged body view — the exact values the
    generic ``_stage_tx``/``_stage_key_policies`` pair would have
    decoded itself (shared by VP resolution, key-level policy staging,
    and the vectorized MVCC planes)."""

    __slots__ = ("ns", "prp", "endorsements", "no_action", "has_pvt",
                 "groups")

    def __init__(self, ns, prp, endorsements, no_action, has_pvt,
                 groups):
        self.ns = ns                  # ChaincodeAction.chaincode_id.name
        self.prp = prp                # exact prp bytes endorsers signed
        self.endorsements = endorsements   # [(endorser, signature)]
        self.no_action = no_action    # tx.actions empty => NIL_TXACTION
        self.has_pvt = has_pvt        # any collection_hashed_rwset
        # ordered per-ns-OCCURRENCE written view, mirroring
        # parse_tx_rwset: [(ns, [(wkey,...)], [(mkey, entries)])]
        self.groups = groups

    def lifecycle_write_keys(self, ns: str):
        """Write keys (writes only, not metadata — the generic
        _resolve_vinfo decodes exactly kv.writes) under `ns`, in
        document order across duplicate ns occurrences."""
        return [k for g_ns, wkeys, _metas in self.groups
                if g_ns == ns for k in wkeys]


class BlockRWSets:
    """Columnar per-block rwset planes + per-tx staged bodies.

    ``bodies[i]`` is a TxBody for every tx the scanner accepted (None
    = fall back to the generic per-tx decoder, counted in
    ``fallbacks``).  The flat planes carry one row per read / write /
    range-query / metadata-write across every ACCEPTED tx, sorted by
    tx then document order, with ``*_bounds`` searchsorted slice
    boundaries per tx; ``read_nsi``/``range_nsi`` carry a global
    ns-occurrence ordinal so MVCC can replay the generic per-ns
    check order (reads then ranges, occurrence by occurrence).
    """

    __slots__ = (
        "n", "bodies", "fallbacks", "txids", "types",
        "read_tx", "read_nsi", "read_ns", "read_key",
        "read_has_ver", "read_vb", "read_vt", "read_bounds",
        "write_tx", "write_ns", "write_key", "write_del", "write_val",
        "write_bounds",
        "range_tx", "range_nsi", "range_ns", "range_rqi",
        "range_bounds",
        "meta_tx", "meta_ns", "meta_key", "meta_entries", "meta_bounds",
    )

    def __init__(self, n: int):
        self.n = n
        self.bodies: List[Optional[TxBody]] = [None] * n
        self.fallbacks = 0
        # filled by the stage() spine pre-pass: value-identical to the
        # generic envelope_channel_header decode for spine-accepted
        # rows, None where commit must re-decode generically
        self.txids: List[Optional[str]] = [None] * n
        self.types: List[Optional[int]] = [None] * n
        self.read_tx = []
        self.read_nsi = []
        self.read_ns = []
        self.read_key = []
        self.read_has_ver = []
        self.read_vb = []
        self.read_vt = []
        self.write_tx = []
        self.write_ns = []
        self.write_key = []
        self.write_del = []
        self.write_val = []
        self.range_tx = []
        self.range_nsi = []
        self.range_ns = []
        self.range_rqi = []
        self.meta_tx = []
        self.meta_ns = []
        self.meta_key = []
        self.meta_entries = []

    def finalize(self):
        grid = np.arange(self.n + 1)
        self.read_tx = np.asarray(self.read_tx, np.int64)
        self.read_nsi = np.asarray(self.read_nsi, np.int64)
        self.read_has_ver = np.asarray(self.read_has_ver, bool)
        self.read_vb = np.asarray(self.read_vb, np.int64)
        self.read_vt = np.asarray(self.read_vt, np.int64)
        self.read_bounds = np.searchsorted(self.read_tx, grid)
        self.write_tx = np.asarray(self.write_tx, np.int64)
        self.write_bounds = np.searchsorted(self.write_tx, grid)
        self.range_tx = np.asarray(self.range_tx, np.int64)
        self.range_nsi = np.asarray(self.range_nsi, np.int64)
        self.range_bounds = np.searchsorted(self.range_tx, grid)
        self.meta_tx = np.asarray(self.meta_tx, np.int64)
        self.meta_bounds = np.searchsorted(self.meta_tx, grid)
        return self


def decode_block_rwsets(tx_datas: Sequence[Optional[bytes]]
                        ) -> Optional[BlockRWSets]:
    """Batch-decode a block's endorser-tx bodies into columnar rwset
    planes (payload.data per tx; None rows — non-endorser txs, rows
    the spine already rejected — are skipped).

    Returns None for tiny blocks (the numpy setup beats them), else a
    BlockRWSets whose accepted bodies/planes are value-identical to
    the generic Transaction -> ... -> KVRWSet decode and whose
    fallback rows (bodies[i] None with a non-None input) are counted.
    """
    n = len(tx_datas)
    live = [i for i, d in enumerate(tx_datas) if d is not None]
    nl = len(live)
    if nl < 4:
        return None                   # numpy setup beats tiny batches
    try:
        lens = np.fromiter((len(tx_datas[i]) for i in live), np.int64, nl)
        joined = b"".join(tx_datas[i] for i in live)
    except TypeError:
        return None
    if not joined:
        return None
    flat = np.frombuffer(joined, np.uint8)
    starts = np.zeros(nl, np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    ends = starts + lens
    arange1 = np.arange(nl + 1)

    def spans(res, num):
        off, ln = _span(res, num)
        return off, off + ln

    def fail_parents(tx_rows, child_ok):
        """A failed descendant row makes its whole tx a fallback."""
        bad = tx_rows[~child_ok]
        if bad.size:
            ok[np.unique(bad)] = False

    # L1: Transaction(actions) — dup field 1 (multi-action) rejects
    tx_res, ok = scan_message(flat, starts, ends, _TX_SPEC)
    act_present = tx_res[1]["present"]
    # L2: TransactionAction(header, payload) — absent action rows scan
    # the (0,0) span, trivially ok (their body is NIL_TXACTION's)
    ta_res, ok2 = scan_message(flat, *spans(tx_res, 1), _TXA_SPEC)
    ok &= ok2
    # L3: ChaincodeActionPayload(ccpp, action)
    cap_res, ok3 = scan_message(flat, *spans(ta_res, 2), _CAP_SPEC)
    ok &= ok3
    # an action-bearing tx whose endorsed action is ABSENT falls back:
    # the generic path's `cap.action.proposal_response_payload` owns
    # that (AttributeError -> INVALID_ENDORSER_TRANSACTION) verdict
    ok &= ~(act_present & ~cap_res[2]["present"])
    # L4: ChaincodeEndorsedAction(prp, endorsements COLLECTED)
    cea_res, ok4, cea_rep = scan_collect(flat, *spans(cap_res, 2),
                                         _CEA_RW_SPEC)
    ok &= ok4
    e_rows, e_off, e_ln = cea_rep[2]
    # L4b: every Endorsement occurrence, flattened across the block
    end_res, ok_e = scan_message(flat, e_off, e_off + e_ln, _END_SPEC)
    fail_parents(e_rows, ok_e)
    # L5: ProposalResponsePayload(hash, extension)
    prp_res, ok5 = scan_message(flat, *spans(cea_res, 1), _PRP_SPEC)
    ok &= ok5
    # L6: ChaincodeAction(results, events, response, chaincode_id) —
    # response and chaincode_id are submessages the generic staging
    # path DECODES, so both get validating sub-scans (absent ones scan
    # the (0,0) span, trivially ok)
    cca_res, ok6 = scan_message(flat, *spans(prp_res, 2), _CCA_SPEC)
    ok &= ok6
    resp_res, ok6a = scan_message(flat, *spans(cca_res, 3), _RESP_SPEC)
    ok &= ok6a
    ccid_res, ok6b = scan_message(flat, *spans(cca_res, 4), _CCID_SPEC)
    ok &= ok6b
    # L7: TxReadWriteSet(data_model, ns_rwset COLLECTED) over results
    txrw_res, ok7, txrw_rep = scan_collect(flat, *spans(cca_res, 1),
                                           _TXRW_SPEC)
    ok &= ok7
    ns_tx, ns_off, ns_ln = txrw_rep[2]     # ns row -> live row
    # L8: NsReadWriteSet(namespace, rwset, colls COLLECTED)
    nsrw_res, ok8, nsrw_rep = scan_collect(flat, ns_off, ns_off + ns_ln,
                                           _NSRW_SPEC)
    fail_parents(ns_tx, ok8)
    c_rows, c_off, c_ln = nsrw_rep[3]      # coll row -> ns row
    # L8b: CollectionHashedReadWriteSet — validated (generic decodes
    # it), its presence marks the tx pvt-bearing
    coll_res, ok_c = scan_message(flat, c_off, c_off + c_ln, _COLL_SPEC)
    fail_parents(ns_tx[c_rows], ok_c)
    # L9: KVRWSet with all four repeated fields collected
    kv_res, ok9, kv_rep = scan_collect(flat, *spans(nsrw_res, 2),
                                       _KVRW_SPEC)
    fail_parents(ns_tx, ok9)
    r_rows, r_off, r_ln = kv_rep[1]        # read row -> ns row
    q_rows, q_off, q_ln = kv_rep[2]        # range row -> ns row
    w_rows, w_off, w_ln = kv_rep[3]        # write row -> ns row
    m_rows, m_off, m_ln = kv_rep[4]        # meta row -> ns row
    # L10: KVRead(key, version) + Version sub-scan
    kvr_res, ok_r = scan_message(flat, r_off, r_off + r_ln, _KVR_SPEC)
    fail_parents(ns_tx[r_rows], ok_r)
    ver_res, ok_v = scan_message(flat, *spans(kvr_res, 2), _VER_SPEC)
    fail_parents(ns_tx[r_rows], ok_v)
    # L10b: KVWrite / RangeQueryInfo / KVMetadataWrite(+entries)
    kvw_res, ok_w = scan_message(flat, w_off, w_off + w_ln, _KVW_SPEC)
    fail_parents(ns_tx[w_rows], ok_w)
    rqi_res, ok_q = scan_message(flat, q_off, q_off + q_ln, _RQI_SPEC)
    fail_parents(ns_tx[q_rows], ok_q)
    kvm_res, ok_m, kvm_rep = scan_collect(flat, m_off, m_off + m_ln,
                                          _KVMW_SPEC)
    fail_parents(ns_tx[m_rows], ok_m)
    me_rows, me_off, me_ln = kvm_rep[2]    # entry row -> meta row
    kvme_res, ok_me = scan_message(flat, me_off, me_off + me_ln,
                                   _KVME_SPEC)
    fail_parents(ns_tx[m_rows[me_rows]], ok_me)

    # slice boundaries: ns rows per live row, child rows per ns row,
    # entry rows per meta row — every level is row-sorted, so a tx's
    # descendants are contiguous ranges at each level
    ns_b = np.searchsorted(ns_tx, arange1)
    n_ns = ns_tx.size
    grid_ns = np.arange(n_ns + 1)
    rd_b = np.searchsorted(r_rows, grid_ns)
    wr_b = np.searchsorted(w_rows, grid_ns)
    rq_b = np.searchsorted(q_rows, grid_ns)
    mt_b = np.searchsorted(m_rows, grid_ns)
    cl_b = np.searchsorted(c_rows, grid_ns)
    en_b = np.searchsorted(me_rows, np.arange(m_rows.size + 1))
    e_b = np.searchsorted(e_rows, arange1)

    # python-native lists for the construction loop
    def lst(res, num):
        return res[num]["off"].tolist(), res[num]["ln"].tolist()

    prp_o, prp_l = lst(cea_res, 1)
    eo_o, eo_l = lst(end_res, 1)
    es_o, es_l = lst(end_res, 2)
    rm_o, rm_l = lst(resp_res, 2)          # Response.message (utf-8)
    cp_o, cp_l = lst(ccid_res, 1)          # ChaincodeID.path
    cn_o, cn_l = lst(ccid_res, 2)          # ChaincodeID.name
    cv_o, cv_l = lst(ccid_res, 3)          # ChaincodeID.version
    ccid_present = cca_res[4]["present"].tolist()
    nsn_o, nsn_l = lst(nsrw_res, 1)
    cno_o, cno_l = lst(coll_res, 1)
    rk_o, rk_l = lst(kvr_res, 1)
    ver_present = kvr_res[2]["present"].tolist()
    ver_b = ver_res[1]["val"].tolist()
    ver_t = ver_res[2]["val"].tolist()
    wk_o, wk_l = lst(kvw_res, 1)
    wd_v = kvw_res[2]["val"].tolist()
    wv_o, wv_l = lst(kvw_res, 3)
    qs_o, qs_l = lst(rqi_res, 1)
    qe_o, qe_l = lst(rqi_res, 2)
    qx_v = rqi_res[3]["val"].tolist()
    qh_o, qh_l = lst(rqi_res, 4)
    mk_o, mk_l = lst(kvm_res, 1)
    men_o, men_l = lst(kvme_res, 1)
    mev_o, mev_l = lst(kvme_res, 2)
    act_p = act_present.tolist()

    out = BlockRWSets(n)
    for j in np.nonzero(ok)[0].tolist():
        i = live[j]
        if not act_p[j]:
            out.bodies[i] = TxBody("", b"", [], True, False, [])
            continue
        try:
            # strings the generic decode would utf-8-decode (and raise
            # on): validate them all, used or not
            joined[rm_o[j]:rm_o[j] + rm_l[j]].decode()
            ns_name = ""
            if ccid_present[j]:
                joined[cp_o[j]:cp_o[j] + cp_l[j]].decode()
                joined[cv_o[j]:cv_o[j] + cv_l[j]].decode()
                ns_name = joined[cn_o[j]:cn_o[j] + cn_l[j]].decode()
            endors = [
                (joined[eo_o[k]:eo_o[k] + eo_l[k]],
                 joined[es_o[k]:es_o[k] + es_l[k]])
                for k in range(e_b[j], e_b[j + 1])]
            prp = joined[prp_o[j]:prp_o[j] + prp_l[j]]
            has_pvt = False
            groups = []
            t_reads, t_writes, t_ranges, t_metas = [], [], [], []
            for u in range(ns_b[j], ns_b[j + 1]):
                ns = joined[nsn_o[u]:nsn_o[u] + nsn_l[u]].decode()
                for c in range(cl_b[u], cl_b[u + 1]):
                    has_pvt = True
                    joined[cno_o[c]:cno_o[c] + cno_l[c]].decode()
                for r in range(rd_b[u], rd_b[u + 1]):
                    t_reads.append((
                        u, ns,
                        joined[rk_o[r]:rk_o[r] + rk_l[r]].decode(),
                        ver_present[r], ver_b[r], ver_t[r]))
                for q in range(rq_b[u], rq_b[u + 1]):
                    t_ranges.append((u, ns, m.RangeQueryInfo(
                        start_key=joined[qs_o[q]:qs_o[q]
                                         + qs_l[q]].decode(),
                        end_key=joined[qe_o[q]:qe_o[q]
                                       + qe_l[q]].decode(),
                        itr_exhausted=qx_v[q],
                        reads_merkle_hash=joined[qh_o[q]:qh_o[q]
                                                 + qh_l[q]])))
                wkeys = []
                for w in range(wr_b[u], wr_b[u + 1]):
                    key = joined[wk_o[w]:wk_o[w] + wk_l[w]].decode()
                    wkeys.append(key)
                    t_writes.append((
                        ns, key, bool(wd_v[w]),
                        joined[wv_o[w]:wv_o[w] + wv_l[w]]))
                metas = []
                for t in range(mt_b[u], mt_b[u + 1]):
                    key = joined[mk_o[t]:mk_o[t] + mk_l[t]].decode()
                    entries = [
                        (joined[men_o[x]:men_o[x]
                                + men_l[x]].decode(),
                         joined[mev_o[x]:mev_o[x] + mev_l[x]])
                        for x in range(en_b[t], en_b[t + 1])]
                    metas.append((key, entries))
                    t_metas.append((ns, key, entries))
                groups.append((ns, wkeys, metas))
        except UnicodeDecodeError:
            continue                  # generic decode raises: fallback
        out.bodies[i] = TxBody(ns_name, prp, endors, False, has_pvt,
                               groups)
        for nsi, ns, key, hv, vb, vt in t_reads:
            out.read_tx.append(i)
            out.read_nsi.append(nsi)
            out.read_ns.append(ns)
            out.read_key.append(key)
            out.read_has_ver.append(hv)
            out.read_vb.append(vb)
            out.read_vt.append(vt)
        for ns, key, is_del, val in t_writes:
            out.write_tx.append(i)
            out.write_ns.append(ns)
            out.write_key.append(key)
            out.write_del.append(is_del)
            out.write_val.append(val)
        for nsi, ns, rqi in t_ranges:
            out.range_tx.append(i)
            out.range_nsi.append(nsi)
            out.range_ns.append(ns)
            out.range_rqi.append(rqi)
        for ns, key, entries in t_metas:
            out.meta_tx.append(i)
            out.meta_ns.append(ns)
            out.meta_key.append(key)
            out.meta_entries.append(entries)
    out.fallbacks = nl - sum(
        1 for i in live if out.bodies[i] is not None)
    return out.finalize()
