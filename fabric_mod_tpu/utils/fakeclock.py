"""Deterministic time source for timer-driven components.

(reference test model: etcd/raft drives its FSM with explicit Tick()
calls instead of wall-clock timers, which is why its election tests
are deterministic; scripts/run-unit-tests.sh runs them under load
without flaking.  ManualClock gives RaftNode the same property: tests
advance time explicitly, so CPU starvation cannot fire spurious
elections or miss heartbeats.)

Components accept a `clock` with `monotonic()`; if the clock also has
`subscribe(cb)`, the component registers a wakeup callback and
`advance()` invokes every callback after moving time — that nudges
queue-blocked FSM threads to re-evaluate their (fake) deadlines.
"""
from __future__ import annotations

import threading
from typing import Callable, List
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class ManualClock:
    def __init__(self, start: float = 0.0):
        self._t = start
        self._lock = RegisteredLock("utils.fakeclock._lock")
        self._subs: List[Callable[[], None]] = []

    def monotonic(self) -> float:
        with self._lock:
            return self._t

    def subscribe(self, cb: Callable[[], None]) -> None:
        with self._lock:
            self._subs.append(cb)

    def advance(self, dt: float) -> None:
        """Move time forward and wake every subscriber."""
        assert dt >= 0
        with self._lock:
            self._t += dt
            subs = list(self._subs)
        for cb in subs:
            cb()
