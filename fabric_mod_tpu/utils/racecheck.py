"""Race-detection primitives — compatibility shim.

The detectors grew into the full concurrency-correctness subsystem at
``fabric_mod_tpu/concurrency/`` (guarded queues, field-level
ownership, registered threads, and the process-wide lock-order
registry with cycle detection, all armed suite-wide by
``FMT_RACECHECK=1``).  This module keeps the original import surface
for the ledger/raft call sites and external users; new code should
import from ``fabric_mod_tpu.concurrency`` directly.
"""
from fabric_mod_tpu.concurrency import (OrderedLock, RaceError,
                                        ThreadOwnership)

__all__ = ["OrderedLock", "RaceError", "ThreadOwnership"]
