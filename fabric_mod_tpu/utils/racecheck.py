"""Race-detection analog: lock-order + thread-ownership checking.

(reference: scripts/run-unit-tests.sh:142-161 runs the whole unit
suite under the Go race detector.  Python has no -race; what bites
in this codebase's threaded core are (a) lock-order inversions
(deadlocks) and (b) structures owned by one thread being mutated from
another.  This module makes both crash loudly instead of corrupting
silently: OrderedLock enforces a global lock hierarchy per thread,
ThreadOwnership pins a structure to its owning thread.  Both are
cheap enough to stay ON in production paths; the seeded interleaving
stress tier (tests/test_racecheck.py) drives them hard and proves via
injected-race canaries that they actually bite.)
"""
from __future__ import annotations

import threading
from typing import Optional


class RaceError(AssertionError):
    """A detected race/ordering violation (AssertionError so test
    frameworks treat it as a hard failure, never a skip)."""


_tls = threading.local()


def _held():
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class OrderedLock:
    """An RLock with a rank in a global hierarchy: a thread may only
    acquire ranks STRICTLY ABOVE the highest it already holds (re-
    entry on the same lock is fine).  Any inversion — the classic
    AB/BA deadlock shape — raises RaceError at acquire time, on the
    first interleaving that exhibits it, instead of deadlocking one
    run in a thousand."""

    def __init__(self, rank: int, name: str = ""):
        self.rank = rank
        self.name = name or f"lock@{rank}"
        self._lock = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = _held()
        # Re-entry of ANY already-held lock is always safe (RLock) and
        # exempt from the rank rule — scan the whole held stack, not
        # just its top: ledger(10) -> pvtstore(30) -> ledger(10) again
        # cannot deadlock, and the checker runs live on production
        # commit paths where a false positive would abort commits.
        # Fresh locks still check against the HIGHEST held rank (not
        # the stack top — after a re-entry the top can be a low rank
        # that would mask a real inversion against a lock in between).
        if held and not any(h[1] is self for h in held):
            top_rank, top_lock = max(held, key=lambda h: h[0])
            if top_rank >= self.rank:
                raise RaceError(
                    f"lock-order violation: acquiring {self.name} "
                    f"(rank {self.rank}) while holding "
                    f"{top_lock.name} (rank {top_rank}) — the "
                    f"hierarchy requires strictly increasing ranks")
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            held.append((self.rank, self))
        return ok

    def release(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                del held[i]
                break
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()


class ThreadOwnership:
    """Pins a structure to one owning thread.  `claim()` binds the
    current thread (the FSM/worker thread at startup); `guard()`
    raises when any OTHER thread enters a guarded section.  The
    raft FSM's whole design contract — all state transitions on the
    FSM thread (chain.go:533's single-threaded run loop) — becomes
    machine-checked instead of a docstring."""

    def __init__(self, name: str = "structure"):
        self.name = name
        self._owner: Optional[int] = None

    def claim(self) -> None:
        self._owner = threading.get_ident()

    def guard(self) -> None:
        if self._owner is None:
            return                        # not yet claimed (startup)
        me = threading.get_ident()
        if me != self._owner:
            raise RaceError(
                f"thread-ownership violation: {self.name} touched "
                f"from thread {me}, owned by {self._owner}")
