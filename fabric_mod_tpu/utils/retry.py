"""Shared retry policy: jittered exponential backoff with deadlines.

(reference: the backoff loops Fabric scatters per-subsystem —
blocksprovider.go:141's deliver retry, comm/connection.go dial retry,
etcdraft submit re-forwarding — folded into ONE policy object so every
transport path retries the same way and tests can make the schedule
deterministic.)

Determinism contract: `clock`, `sleep`, and `rng` are injectable.  A
test passes a seeded ``random.Random`` for a reproducible jitter
sequence and a ``sleep`` that advances a utils/fakeclock.ManualClock —
retry waits then DRIVE fake time (e.g. a raft election completing
while broadcast backs off) instead of stalling the suite on real
sleeps.
"""
from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type

from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.utils import knobs

_RETRIES_OPTS = MetricOpts(
    "fabric", "retry", "attempts_total",
    help="Retry attempts taken (first tries excluded), per policy name.",
    label_names=("name",))
_GIVEUPS_OPTS = MetricOpts(
    "fabric", "retry", "giveups_total",
    help="Operations abandoned after exhausting retries/deadline, per "
         "policy name.",
    label_names=("name",))


@functools.lru_cache(maxsize=None)
def _metrics():
    prov = default_provider()
    return prov.counter(_RETRIES_OPTS), prov.counter(_GIVEUPS_OPTS)


class RetryBudgetExceeded(Exception):
    """Retries/deadline exhausted; `last` carries the final attempt's
    exception (also chained as __cause__)."""

    def __init__(self, msg: str, last: Optional[BaseException] = None):
        super().__init__(msg)
        self.last = last


class Retrier:
    """Jittered-exponential-backoff retry with an overall deadline.

    delay(attempt) = min(max_s, base_s * multiplier**attempt) scaled by
    a jitter factor uniform in [1-jitter, 1+jitter]; attempt 0 is the
    first RETRY (i.e. the second try).  `deadline_s` bounds the whole
    call() from first attempt to last raise; `max_attempts` bounds
    total tries.  Defaults come from FABRIC_MOD_TPU_RETRY_BASE_S /
    FABRIC_MOD_TPU_RETRY_MAX_S so operators tune one pair of knobs for
    every transport path.
    """

    def __init__(self, base_s: Optional[float] = None,
                 max_s: Optional[float] = None,
                 multiplier: float = 2.0, jitter: float = 0.1,
                 deadline_s: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 clock=None, sleep: Optional[Callable[[float], None]] = None,
                 rng: Optional[random.Random] = None,
                 giveup: Optional[Callable[[], bool]] = None,
                 on_retry: Optional[Callable[[BaseException, int], None]]
                 = None, name: str = "retry"):
        self.base_s = (base_s if base_s is not None else
                       knobs.get_float("FABRIC_MOD_TPU_RETRY_BASE_S"))
        self.max_s = (max_s if max_s is not None else
                      knobs.get_float("FABRIC_MOD_TPU_RETRY_MAX_S"))
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.max_attempts = max_attempts
        self.retry_on = retry_on
        self.name = name
        self._clock = clock or time
        self._sleep = sleep if sleep is not None else time.sleep
        self._rng = rng or random.Random()
        self._giveup = giveup
        self._on_retry = on_retry
        self._m_retries, self._m_giveups = _metrics()

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry #`attempt` (0-based), jitter applied.
        The exponent is clamped so a multi-hour outage cannot overflow
        the float (the blocksprovider lesson)."""
        exp = min(60, max(0, attempt))
        raw = min(self.max_s, self.base_s * (self.multiplier ** exp))
        if self.jitter:
            raw *= 1.0 + self.jitter * (self._rng.random() * 2.0 - 1.0)
        return max(0.0, raw)

    def worst_case_delay(self, attempts: Optional[int] = None) -> float:
        """Upper bound on total sleep across `attempts` retries — join
        budgets are derived from this instead of hand-summed magic."""
        n = attempts if attempts is not None else (self.max_attempts or 1)
        total = 0.0
        for i in range(max(0, n)):
            exp = min(60, i)
            total += min(self.max_s,
                         self.base_s * (self.multiplier ** exp))
        return total * (1.0 + self.jitter)

    def call(self, fn: Callable, *args, **kwargs):
        """Run `fn` until it returns, an un-retryable exception raises,
        or the budget (deadline/max_attempts/giveup) is exhausted —
        then the LAST exception re-raises (typed errors like
        NotLeaderError stay catchable; RetryBudgetExceeded would mask
        them)."""
        start = self._clock.monotonic()
        attempt = 0                        # retries taken so far
        while True:
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                out_of_attempts = (self.max_attempts is not None
                                   and attempt + 1 >= self.max_attempts)
                gave_up = self._giveup is not None and self._giveup()
                if out_of_attempts or gave_up:
                    self._m_giveups.with_labels(self.name).add(1)
                    raise
                delay = self.delay_for(attempt)
                if self.deadline_s is not None:
                    elapsed = self._clock.monotonic() - start
                    # a retry that cannot START before the deadline is
                    # not taken: the deadline bounds the whole call
                    if elapsed + delay >= self.deadline_s:
                        self._m_giveups.with_labels(self.name).add(1)
                        raise
                if self._on_retry is not None:
                    self._on_retry(e, attempt)
                if delay > 0:
                    self._sleep(delay)
                attempt += 1
                self._m_retries.with_labels(self.name).add(1)
