"""Counting semaphore for service admission control.

(reference: common/semaphore/semaphore.go — the channel-based
semaphore capping the validator pool — and internal/peer/node/
grpc_limiters.go, the per-service concurrency limiters on unary and
stream RPCs.)
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Iterator, Optional


class AcquireTimeout(Exception):
    pass


class Semaphore:
    """Bounded concurrency with an acquire timeout — the admission
    answer is "wait briefly, then shed load", never unbounded queuing
    (the reference's TryAcquire-on-context semantics)."""

    def __init__(self, permits: int):
        if permits < 1:
            raise ValueError("permits must be >= 1")
        self.permits = permits
        self._sem = threading.Semaphore(permits)

    @contextmanager
    def acquire(self, timeout_s: Optional[float] = None) -> Iterator[None]:
        if not self._sem.acquire(timeout=timeout_s):
            raise AcquireTimeout(
                f"no permit within {timeout_s}s ({self.permits} in use)")
        try:
            yield
        finally:
            self._sem.release()

    def try_acquire(self) -> bool:
        return self._sem.acquire(blocking=False)

    def release(self) -> None:
        self._sem.release()


class ServiceLimiter:
    """Named per-service semaphores (reference: grpc_limiters.go's
    map of service -> semaphore wrapped around handlers)."""

    def __init__(self, limits: dict, timeout_s: float = 5.0):
        self._sems = {name: Semaphore(n)
                      for name, n in limits.items() if n > 0}
        self._timeout = timeout_s

    @contextmanager
    def limit(self, service: str) -> Iterator[None]:
        sem = self._sems.get(service)
        if sem is None:
            yield
            return
        with sem.acquire(timeout_s=self._timeout):
            yield
