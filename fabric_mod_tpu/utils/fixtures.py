"""Signature fixture generation — the single source for every harness.

bench.py, the driver entry points (__graft_entry__), and tests all
need "n real ECDSA-P256 signatures, some deliberately bad, plus the
expected verdict mask".  Keeping one generator prevents the fixtures
from drifting apart (e.g. one harness forgetting the low-S
normalization the providers enforce).  This is the role the
reference's generated test crypto plays (internal/cryptogen/ca/ca.go,
common/crypto/tlsgen).
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

import numpy as np

from fabric_mod_tpu.bccsp.api import VerifyItem
from fabric_mod_tpu.bccsp.sw import SwCSP


def make_verify_items(
        n: int, n_keys: int = 8, invalid_every: Optional[int] = None,
        seed: bytes = b"fixture") -> Tuple[List[VerifyItem], List[bool]]:
    """n signed VerifyItems over `n_keys` keys; every `invalid_every`-th
    item (1-based: i % invalid_every == invalid_every - 1) gets a
    tampered digest.  Signatures come from the sw provider, so they are
    low-S normalized exactly like production signing."""
    csp = SwCSP()
    keys = [csp.key_gen() for _ in range(min(n_keys, max(n, 1)))]
    items, expect = [], []
    for i in range(n):
        k = keys[i % len(keys)]
        digest = hashlib.sha256(seed + b"-%d" % i).digest()
        sig = csp.sign(k, digest)
        bad = invalid_every is not None and i % invalid_every == invalid_every - 1
        if bad:
            digest = hashlib.sha256(seed + b"-tampered-%d" % i).digest()
        items.append(VerifyItem(digest, sig, k.public_xy()))
        expect.append(not bad)
    return items, expect


def make_channel_stream(signers, cid: str, n_blocks: int,
                        txs_per_block: int,
                        under_endorse_every: int = 4,
                        namespace: str = "mycc") -> List[bytes]:
    """One channel's encoded block stream for the sharding
    differentials — the SINGLE oracle stream generator shared by
    bench.py --metric multichannel and tests/test_sharding.py, so the
    two can never gate against drifted streams: every
    `under_endorse_every`-th tx is endorsed 1-of-3 (fails a 2-of-3
    policy -> the flags carry signal), keys are per-channel
    (`{cid}-b{n}t{j}` holding `cid`) so fingerprints differ across
    channels.  `signers` maps org -> SigningIdentity for Org1/Org2
    (Org1 is the creator)."""
    from fabric_mod_tpu.ledger.rwsetutil import RWSetBuilder
    from fabric_mod_tpu.protos import protoutil

    blocks, prev = [], b""
    for n in range(n_blocks):
        envs = []
        for j in range(txs_per_block):
            b = RWSetBuilder()
            b.add_write(namespace, f"{cid}-b{n}t{j}", cid.encode())
            endorsers = (
                ("Org1",)
                if (n * txs_per_block + j) % under_endorse_every
                == under_endorse_every - 1
                else ("Org1", "Org2"))
            envs.append(protoutil.create_signed_tx(
                cid, namespace, b.build().encode(), signers["Org1"],
                [signers[o] for o in endorsers]))
        blk = protoutil.new_block(n, prev, envs)
        prev = protoutil.block_header_hash(blk.header)
        blocks.append(blk.encode())
    return blocks


def independent_baseline(streams, make_target) -> dict:
    """The sharding differentials' oracle: per channel, an INDEPENDENT
    unsharded synchronous run of its stream into a fresh ledger —
    returns {cid: (per_block_flags, state_fingerprint, wall_secs)}.
    `make_target(cid)` builds a fresh ValidatorCommitTarget-shaped
    (validator, ledger) pair with its own unsharded verifier."""
    import time

    from fabric_mod_tpu.peer.txvalidator import Committer
    from fabric_mod_tpu.protos import messages as m

    out = {}
    for cid, raws in streams.items():
        t = make_target(cid)
        committer = Committer(t.validator, t.ledger)
        t0 = time.perf_counter()
        flags = [list(committer.store_block(m.Block.decode(raw)))
                 for raw in raws]
        out[cid] = (flags, t.ledger.state_fingerprint(),
                    time.perf_counter() - t0)
    return out


def signature_arrays(
        n: int, tamper_last: bool = True,
        seed: bytes = b"fixture") -> Tuple[np.ndarray, ...]:
    """The same fixtures as raw (n, 32) uint8 arrays (digest, r, s,
    qx, qy) + expected mask — the shape ops/p256.marshal_inputs takes."""
    from fabric_mod_tpu.bccsp.sw import decode_dss_signature

    items, _ = make_verify_items(n, n_keys=1, seed=seed)
    d = np.zeros((n, 32), np.uint8)
    r = np.zeros((n, 32), np.uint8)
    s = np.zeros((n, 32), np.uint8)
    qx = np.zeros((n, 32), np.uint8)
    qy = np.zeros((n, 32), np.uint8)
    expect = np.ones(n, bool)
    for i, it in enumerate(items):
        ri, si = decode_dss_signature(it.signature)
        d[i] = np.frombuffer(it.digest, np.uint8)
        r[i] = np.frombuffer(ri.to_bytes(32, "big"), np.uint8)
        s[i] = np.frombuffer(si.to_bytes(32, "big"), np.uint8)
        qx[i] = np.frombuffer(it.public_xy[:32], np.uint8)
        qy[i] = np.frombuffer(it.public_xy[32:], np.uint8)
    if tamper_last and n:
        d[n - 1, 0] ^= 0xFF
        expect[n - 1] = False
    return d, r, s, qx, qy, expect
