"""Shared env-knob parsing: one malformed-value policy everywhere.

Every FABRIC_MOD_TPU_* tuning knob parses through these two helpers,
so the edge behavior (unset or garbage → the documented default,
never a crash at import) cannot drift between subsystems.
"""
from __future__ import annotations

import os


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default
