"""The typed knob registry: every env tunable, declared once.

(reference: the role Viper + config structs play in the Go stack —
every tunable has a declared name, type, default, and doc, so a typo'd
override fails visibly instead of silently running defaults.  Our
knobs were stringly-typed `os.environ` reads scattered across 15+
modules; the fmtlint `knobs` rule now requires every
``FABRIC_MOD_TPU_*`` / ``FMT_*`` access to go through this registry,
and the README knob table is cross-checked against it so the docs
cannot drift.)

Reading an UNDECLARED knob raises ``KeyError`` at call time — the
static mirror is the fmtlint rule that flags undeclared knob literals
at lint time.  Parsing is built on :mod:`fabric_mod_tpu.utils.env`
(malformed values fall back to the default, never crash at import).

Usage::

    from fabric_mod_tpu.utils import knobs
    depth = knobs.get_int("FABRIC_MOD_TPU_INFLIGHT")      # registry default
    k     = knobs.get_int("FABRIC_MOD_TPU_BREAKER_K", 3)  # caller override
    if knobs.get_bool("FABRIC_MOD_TPU_FUSED_HASH"):
        ...

Boolean semantics are uniform: set-and-not-("", "0") is true.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Union

from fabric_mod_tpu.utils.env import env_float, env_int

Default = Union[int, float, str, bool, None]


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared tunable: the registry row the README table and the
    fmtlint cross-checks are generated from."""
    name: str
    type: str                  # "int" | "float" | "str" | "bool"
    default: Default           # documented default (None = unset/off)
    doc: str


_REGISTRY: Dict[str, Knob] = {}


def declare(name: str, type: str, default: Default, doc: str) -> Knob:
    if type not in ("int", "float", "str", "bool"):
        raise ValueError(f"knob {name}: unknown type {type!r}")
    if name in _REGISTRY:
        raise ValueError(f"knob {name} declared twice")
    knob = Knob(name, type, default, doc)
    _REGISTRY[name] = knob
    return knob


def declared() -> Dict[str, Knob]:
    """Name -> Knob view of the registry (for the lint cross-checks
    and the generated README table)."""
    return dict(_REGISTRY)


def is_declared(name: str) -> bool:
    return name in _REGISTRY


def _lookup(name: str, want: str) -> Knob:
    knob = _REGISTRY.get(name)
    if knob is None:
        raise KeyError(
            f"undeclared knob {name!r}: declare it in "
            f"fabric_mod_tpu/utils/knobs.py (fmtlint rule 'knobs')")
    if knob.type != want:
        raise TypeError(
            f"knob {name} is declared {knob.type}, read as {want}")
    return knob


def get_int(name: str, default: Optional[int] = None) -> int:
    """Parse an int knob; `default` overrides the registry default for
    call sites whose fallback is computed at runtime."""
    knob = _lookup(name, "int")
    fallback = default if default is not None else knob.default
    return env_int(name, int(fallback if fallback is not None else 0))


def get_float(name: str, default: Optional[float] = None) -> float:
    knob = _lookup(name, "float")
    fallback = default if default is not None else knob.default
    return env_float(name, float(fallback if fallback is not None else 0.0))


def get_str(name: str, default: Optional[str] = None) -> str:
    knob = _lookup(name, "str")
    fallback = default if default is not None else (knob.default or "")
    return os.environ.get(name, str(fallback))


def get_bool(name: str) -> bool:
    """Uniform arming semantics: set and not in ("", "0")."""
    _lookup(name, "bool")
    return os.environ.get(name, "") not in ("", "0")


def knob_table() -> List[Knob]:
    """Rows for the generated README table, sorted by name."""
    return sorted(_REGISTRY.values(), key=lambda k: k.name)


# ---------------------------------------------------------------------------
# The registry.  One row per tunable; the README "Knob registry" table
# is GENERATED from these rows (`python -m fabric_mod_tpu.analysis
# --knob-table`) and the drift test fails when they diverge.
# ---------------------------------------------------------------------------

# -- framework arming gates (the FMT_* discipline layer) --------------------
declare("FMT_RACECHECK", "bool", None,
        "1 arms every concurrency guard process-wide (race tier); "
        "unset, each guard is one module-flag read")
declare("FMT_FAULTS", "str", None,
        "arm a fault plan process-wide, e.g. "
        "\"deliver.stream:error@n=3\"; unknown point names and "
        "malformed rules fail loudly at arm time")
declare("FMT_TRACE", "bool", None,
        "1 arms spans + timelines + flight recorder process-wide; "
        "unset is byte-identical behavior with zero span allocations")
declare("FMT_TRACE_RING", "int", 256,
        "flight-recorder ring: block timelines retained")
declare("FMT_TRACE_SPANS", "int", 2048,
        "span ring: finished spans retained for /trace + export")
declare("FMT_TRACE_JAX_PROFILE", "str", None,
        "directory for the one-shot jax.profiler capture around a "
        "device batch dispatch (needs FMT_TRACE=1)")
declare("FMT_SLOW_TESTS", "bool", None,
        "1 enables the multi-minute eager-pairing differentials in "
        "the test suite (excluded from tier-1)")
declare("FMT_NO_COMPILE_CACHE", "bool", None,
        "1 disables the persistent XLA compilation cache the test "
        "harness keeps under .cache/jax (use to time cold compiles); "
        "unset, repeat suite runs skip every unchanged kernel compile")

# -- soak harness -----------------------------------------------------------
declare("FMT_SOAK_SEED", "int", 8,
        "churn schedule + rng seed (the replay handle)")
declare("FMT_SOAK_EVENTS", "int", 6, "churn events per run")
declare("FMT_SOAK_CHANNELS", "int", 2, "soak channels")
declare("FMT_SOAK_PEERS", "int", 2,
        "peers at start (join events add more)")
declare("FMT_SOAK_GAP_TXS", "str", "4:9",
        "\"lo:hi\" seeded range of txs between churn events")
declare("FMT_SOAK_WINDOW_S", "float", 45.0,
        "per-event recovery window (convergence deadline)")
declare("FMT_SOAK_RECOVERY_FRAC", "float", 0.05,
        "post/pre-event throughput floor")
declare("FMT_SOAK_X509_GAP_S", "float", 0.12,
        "x509 lane inter-tx gap (s)")
declare("FMT_SOAK_IDEMIX_GAP_S", "float", 1.0,
        "idemix lane inter-tx gap (s)")
declare("FMT_SOAK_FAULT_P", "float", 0.05,
        "background fault probability per injection-point pass")
declare("FMT_SOAK_SHARDED", "bool", None,
        "1 routes every soak peer's channels through a per-peer "
        "ChannelShardRouter (host-mode slices + the shared "
        "cross-channel verify service) so churn rides the sharding "
        "subsystem")
declare("FMT_SOAK_RELAY", "bool", None,
        "1 runs every soak peer's channels in relay mode "
        "(dissemination/ trees instead of epidemic push): churn "
        "exercises reparenting + anti-entropy repair, and leader_kill "
        "additionally flaps the relay root (recovery recorded as "
        "kind=relay_reparent)")
declare("FMT_SOAK_NO_CRASH", "bool", None,
        "1 drops the crash-shaped churn kinds (peer_crash_rejoin, "
        "orderer_restart, network_partition) from the default plan "
        "(they are in the pool by default since PR 20)")
declare("FMT_SOAK_PARTITION_S", "float", 2.0,
        "network_partition hold time (s): traffic keeps flowing on "
        "the majority side before the scheduled heal")
declare("FMT_SOAK_CRASH_HOLD_S", "float", 1.0,
        "peer_crash_rejoin / orderer_restart down window (s): traffic "
        "continues while the victim is gone, so its rejoin has a real "
        "tail to recover")

# -- device / kernel routing ------------------------------------------------
declare("FABRIC_MOD_TPU_MIXED_ADD", "bool", None,
        "1 routes bucket verifies through the affine-table "
        "mixed-addition ladder (RCB alg. 5); dark pending on-chip "
        "measurement")
declare("FABRIC_MOD_TPU_PALLAS", "bool", None,
        "1 selects the VMEM-fused Pallas ladder; composes with "
        "MIXED_ADD")
declare("FABRIC_MOD_TPU_FUSED_HASH", "bool", None,
        "1 makes msp identities emit raw-message verify items: "
        "SHA-256 on device in the same jitted program as the verify")
declare("FABRIC_MOD_TPU_PRECISION", "str", None,
        "bench-scoped ONLY: \"high\" selects the 3-pass limb-matmul "
        "emulation via set_precision_mode; ignored (with a notice) "
        "everywhere else")
declare("FABRIC_MOD_TPU_UNROLL_LOW_CARRY", "bool", None,
        "1 defaults the unrolled low-carry lane on (bench A/B seam; "
        "set_unroll_low_carry overrides per thread)")
declare("FABRIC_MOD_TPU_SPLIT_FINALEXP", "str", None,
        "0/1 forces the split/fused idemix final-exponentiation "
        "program; unset = split on the CPU backend, fused on TPU")
declare("FABRIC_MOD_TPU_JIT_CACHE", "str", "~/.cache/fabric_mod_tpu/jit",
        "persistent XLA compile-cache directory")

# -- verify front-end -------------------------------------------------------
declare("FABRIC_MOD_TPU_VERDICT_CACHE", "int", 8192,
        "verdict memo-cache capacity, LRU over (digest, signature, "
        "pubkey); 0 disables")
declare("FABRIC_MOD_TPU_INFLIGHT", "int", 2,
        "in-flight dispatch window depth of BatchingVerifyService")
declare("FABRIC_MOD_TPU_VERIFY_DEADLINE", "float", 30.0,
        "whole-call deadline (s) of BatchingVerifyService.verify/"
        "verify_many; 0 = wait forever")
declare("FABRIC_MOD_TPU_BREAKER_K", "int", 3,
        "consecutive device failures that open the verify circuit; "
        "0 = never open (per-batch fallback only)")
declare("FABRIC_MOD_TPU_BREAKER_PROBE_S", "float", 5.0,
        "background probe period while the circuit is open; 0 "
        "disables the prober thread")

# -- commit path ------------------------------------------------------------
declare("FABRIC_MOD_TPU_COMMIT_PIPELINE", "int", 0,
        "pipeline depth for the gossip drain loop and "
        "Channel.store_block; 0/unset = synchronous")
declare("FABRIC_MOD_TPU_TENSOR_POLICY", "bool", None,
        "1 evaluates a whole block's policy verdicts as dense "
        "mask/threshold tensors in one program fused downstream of "
        "the batch verify (non-tensorizable trees fall back per "
        "policy); unset = the closure path")
declare("FABRIC_MOD_TPU_VECTOR_MVCC", "bool", None,
        "1 runs MVCC over the columnar rwset planes batch-decoded at "
        "stage time: ONE get_versions_many statedb call per block "
        "(hash-join) + numpy version compares; rows the scanner "
        "can't prove fall back per-tx, counted; unset = the serial "
        "per-key path")

# -- channel sharding -------------------------------------------------------
declare("FABRIC_MOD_TPU_SHARDS", "int", 0,
        "mesh slices the channel-shard router carves (sharding/); "
        "0/unset = sharding disabled (single-slice behavior)")
declare("FABRIC_MOD_TPU_SHARD_DEPTH", "int", 0,
        "per-channel commit-pipeline depth under the shard router; "
        "0 = fall back to FABRIC_MOD_TPU_COMMIT_PIPELINE, defaulting "
        "to depth 2 when that is unset too (floor 1 — router-bound "
        "channels always pipeline)")
declare("FABRIC_MOD_TPU_SHARD_HOSTS", "int", 1,
        "expected jax.distributed process count of the multi-host "
        "spec (sharding/multihost.py); >1 is specified but stubbed — "
        "initialize_multihost raises until the bring-up lands")

# -- ordering / ingress -----------------------------------------------------
declare("FABRIC_MOD_TPU_BROADCAST_RETRY_S", "float", 5.0,
        "how long Broadcast.submit retries NotLeaderError before "
        "surfacing it; 0 = no retry")
declare("FABRIC_MOD_TPU_SUBMIT_QUEUE", "int", 0,
        "consenter submit-queue bound + non-blocking puts; 0/unset = "
        "blocking 10k queue (pre-admission behavior)")
declare("FABRIC_MOD_TPU_INGRESS_RATE", "float", 0.0,
        "per-client sustained tokens/s; 0/unset disables the limiter")
declare("FABRIC_MOD_TPU_INGRESS_BURST", "float", None,
        "token-bucket capacity (burst size); default 2x rate, min 1")
declare("FABRIC_MOD_TPU_SHED_HIGH", "float", 0.9,
        "submit-queue occupancy fraction that opens the overload gate")
declare("FABRIC_MOD_TPU_SHED_LOW", "float", 0.6,
        "occupancy fraction that closes the gate (hysteresis band)")
declare("FABRIC_MOD_TPU_SHED_LAT_S", "float", 0.0,
        "admission-latency EWMA (s) that opens the gate even below "
        "the occupancy watermark; 0 = off")
declare("FABRIC_MOD_TPU_RAFT_QUEUE", "int", 8192,
        "raft FSM ingress queue bound; overflowed peer messages drop "
        "counted; 0 = unbounded")
declare("FABRIC_MOD_TPU_STAGED_BROADCAST", "int", 0,
        "staged broadcast ingress: max envelopes a per-channel "
        "drainer coalesces into ONE batched Writers-policy verify; "
        "0/unset = per-submission processing (pre-staging behavior)")
declare("FABRIC_MOD_TPU_RAFT_PIPELINE", "int", 0,
        "in-flight AppendEntries windows per follower (optimistic "
        "pipelining; replies repair the window on mismatch); "
        "0/unset = one outstanding round per follower")
declare("FABRIC_MOD_TPU_WAL_GROUP_COMMIT", "bool", None,
        "1 defers the raft WAL fsync to the group-commit barrier "
        "(one fsync covers every entry appended since the last "
        "barrier, still BEFORE any ack/commit); unset = fsync per "
        "append")

# -- peer deliver fan-out ---------------------------------------------------
declare("FABRIC_MOD_TPU_DELIVER_STREAMS", "int", 40,
        "peer event-deliver admission cap (streams per channel "
        "service); past it new streams get SERVICE_UNAVAILABLE")
declare("FABRIC_MOD_TPU_FANOUT_RING", "int", 128,
        "per-(channel, form) deliver fan-out ring depth: blocks kept "
        "as ready-to-send frames; subscribers lagging past the tail "
        "fall back to a counted per-stream ledger re-read")

# -- cross-peer dissemination ----------------------------------------------
declare("FABRIC_MOD_TPU_RELAY", "bool", None,
        "1 builds a RelayService into every GossipService: the "
        "elected leader keeps the sole orderer pull and pushes "
        "once-encoded frames down the deterministic relay tree; "
        "unset = the epidemic gossip_block push")
declare("FABRIC_MOD_TPU_RELAY_DEGREE", "int", 4,
        "relay-tree fan-out degree: children each member pushes to")
declare("FABRIC_MOD_TPU_RELAY_QUEUE", "int", 64,
        "per-child relay queue bound; a slow child sheds its own "
        "OLDEST frames, counted (anti-entropy repairs the gap)")

# -- retries / gossip -------------------------------------------------------
declare("FABRIC_MOD_TPU_RETRY_BASE_S", "float", 0.05,
        "default base of every Retrier backoff schedule")
declare("FABRIC_MOD_TPU_RETRY_MAX_S", "float", 5.0,
        "default cap of every Retrier backoff schedule")
declare("FABRIC_MOD_TPU_GOSSIP_SEND_RETRIES", "int", 2,
        "bounded per-message gossip send retries (fresh dial per "
        "attempt); 0 = drop on first failure")

# -- bench ------------------------------------------------------------------
declare("FABRIC_MOD_TPU_BENCH_TIMEOUT", "float", 1200.0,
        "bench worker wall-clock budget (s) per metric")
