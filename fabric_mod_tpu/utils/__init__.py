"""Cross-cutting utilities: fixtures, logging, metrics, config."""
