"""Idemix anonymous credentials over FP256BN pairings (reference:
idemix/ + bccsp/idemix).  Host-side reference implementation this
round; kernel decomposition in KERNEL_PLAN.md."""
from fabric_mod_tpu.idemix.credential import (   # noqa: F401
    Credential, IssuerKey, credential_valid, issue, sign, verify)
