"""Idemix anonymous credentials: issuance + presentation + Ver.

(reference: idemix/ — issuerkey.go IssuerKey, credential.go
NewCredential/Ver, signature.go:50 NewSignature and :243
Signature.Ver — the BBS+-style scheme over FP256BN pairings.)

The scheme (multiplicative notation, G1/G2/GT from fp256bn):

  Issuer key:  isk = x;  ipk = (W = g2^x, HSk, HRand, HAttrs[0..L-1],
               all in G1, plus a Schnorr PoK of x)
  Credential:  on user secret sk and attributes a[0..L-1]:
               e, s random;  B = g1 * HSk^sk * HRand^s * prod Hi^ai
               A = B^(1/(e+x));   cred = (A, B, e, s)
               valid iff  e(A, W * g2^e) == e(B, g2)
  Presentation ("signature"): prove possession of a credential with
  the hidden attributes undisclosed and bind the proof to a message:
               r1, r2, r3=1/r1:  A' = A^r1 (never identity),
               Abar = A'^-e * B^r1,  B' = B^r1 * HRand^-r2,
               s' = s - r2*r3
               two Schnorr relations under Fiat-Shamir challenge c:
                 (1) Abar/B' = A'^-e * HRand^r2
                 (2) g1 * prod_{i in D} Hi^ai
                       = B'^r3 * HRand^-s' * HSk^-sk
                         * prod_{i not in D} Hi^-ai
  Ver (signature.go:243): ONE pairing equation
               e(A', W) == e(Abar, g2)
  plus the recomputed-challenge check of both Schnorr relations.

Keys/credentials here are self-consistent (sign/verify round-trips)
but not wire-compatible with amcl-issued material: the G2 generator is
our deterministic one (fp256bn.g2_generator), not the amcl ROM
constant, and the hash-to-group is SHA-256-based.
"""
from __future__ import annotations

import hashlib
import secrets
from typing import Dict, List, Optional, Sequence, Tuple

from fabric_mod_tpu.idemix import fp256bn as bn
from fabric_mod_tpu.idemix.fp256bn import (
    G1, G2, Fp12, g1_add, g1_mul, g2_add, g2_mul, pairing)

R = bn.R


class IdemixError(Exception):
    pass


def _rand_zr() -> int:
    return secrets.randbelow(R - 1) + 1


def _hash_to_zr(*parts: bytes) -> int:
    h = hashlib.sha256()
    for p in parts:
        h.update(len(p).to_bytes(4, "big"))
        h.update(p)
    return int.from_bytes(h.digest(), "big") % R


def _g1_bytes(p: Optional[G1]) -> bytes:
    if p is None:
        return b"\x00" * 64
    return p.x.to_bytes(32, "big") + p.y.to_bytes(32, "big")


def _g2_bytes(q: Optional[G2]) -> bytes:
    if q is None:
        return b"\x00" * 128
    return b"".join(v.to_bytes(32, "big")
                    for v in (q.x.a, q.x.b, q.y.a, q.y.b))


def hash_to_g1(label: bytes) -> G1:
    """Deterministic try-and-increment hash to the curve (cofactor 1
    on G1 for BN curves, so any curve point is in the r-group)."""
    ctr = 0
    while True:
        x = int.from_bytes(hashlib.sha256(
            b"fmt-idemix-h2c" + label + ctr.to_bytes(4, "big")
        ).digest(), "big") % bn.P
        rhs = (x * x * x + bn.B) % bn.P
        y = pow(rhs, (bn.P + 1) // 4, bn.P)
        if y * y % bn.P == rhs:
            return G1(x, y)
        ctr += 1


# --- Issuer key -------------------------------------------------------------

class IssuerKey:
    """(reference: idemix/issuerkey.go NewIssuerKey)"""

    def __init__(self, attr_names: Sequence[str]):
        self.attr_names = list(attr_names)
        self.x = _rand_zr()
        self.g2 = bn.g2_generator()
        self.W = g2_mul(self.x, self.g2)
        self.HSk = hash_to_g1(b"HSk")
        self.HRand = hash_to_g1(b"HRand")
        self.HAttrs = [hash_to_g1(b"HAttr" + n.encode())
                       for n in self.attr_names]
        # PoK of x: t = g2^r, c = H(g2, W, t), z = r + c*x
        r = _rand_zr()
        t = g2_mul(r, self.g2)
        self.pok_c = _hash_to_zr(_g2_bytes(self.g2), _g2_bytes(self.W),
                                 _g2_bytes(t))
        self.pok_z = (r + self.pok_c * self.x) % R

    def check_pok(self) -> bool:
        """Verify the issuer's proof of knowledge of x
        (reference: ipk.Check)."""
        t = g2_add(g2_mul(self.pok_z, self.g2),
                   g2_mul(-self.pok_c, self.W))
        return self.pok_c == _hash_to_zr(
            _g2_bytes(self.g2), _g2_bytes(self.W), _g2_bytes(t))

    # -- serialization (reference: the idemixgen artifact files) ---------
    def public_dict(self) -> dict:
        return {"attr_names": list(self.attr_names),
                "W": _g2_bytes(self.W).hex(),
                "pok_c": str(self.pok_c), "pok_z": str(self.pok_z)}

    def to_dict(self) -> dict:
        d = self.public_dict()
        d["x"] = str(self.x)               # the issuer SECRET key
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "IssuerKey":
        ik = cls.__new__(cls)
        ik.attr_names = list(d["attr_names"])
        # public-only artifacts have no secret: keep it None so
        # issuing with a public key fails LOUDLY, not with unverifiable
        # credentials
        ik.x = int(d["x"]) if "x" in d else None
        ik.g2 = bn.g2_generator()
        ik.W = _g2_from_hex(d["W"])
        ik.HSk = hash_to_g1(b"HSk")
        ik.HRand = hash_to_g1(b"HRand")
        ik.HAttrs = [hash_to_g1(b"HAttr" + n.encode())
                     for n in ik.attr_names]
        ik.pok_c = int(d["pok_c"])
        ik.pok_z = int(d["pok_z"])
        if not ik.check_pok():
            raise IdemixError("issuer key PoK invalid")
        return ik


# --- Credential -------------------------------------------------------------

def _g2_from_hex(hexs: str) -> Optional[G2]:
    raw = bytes.fromhex(hexs)
    if raw == b"\x00" * 128:
        return None
    vals = [int.from_bytes(raw[i:i + 32], "big") for i in range(0, 128, 32)]
    from fabric_mod_tpu.idemix.fp256bn import Fp2
    q = G2(Fp2(vals[0], vals[1]), Fp2(vals[2], vals[3]))
    if not q.is_on_curve():
        raise IdemixError("G2 point not on the twist")
    return q


def _g1_from_hex(hexs: str) -> Optional[G1]:
    raw = bytes.fromhex(hexs)
    if raw == b"\x00" * 64:
        return None
    p = G1(int.from_bytes(raw[:32], "big"),
           int.from_bytes(raw[32:], "big"))
    if not p.is_on_curve():
        raise IdemixError("G1 point not on the curve")
    return p


class Credential:
    def __init__(self, A: G1, B: G1, e: int, s: int,
                 attrs: List[int]):
        self.A, self.B, self.e, self.s = A, B, e, s
        self.attrs = list(attrs)

    def to_dict(self) -> dict:
        return {"A": _g1_bytes(self.A).hex(), "B": _g1_bytes(self.B).hex(),
                "e": str(self.e), "s": str(self.s),
                "attrs": [str(a) for a in self.attrs]}

    @classmethod
    def from_dict(cls, d: dict) -> "Credential":
        return cls(_g1_from_hex(d["A"]), _g1_from_hex(d["B"]),
                   int(d["e"]), int(d["s"]),
                   [int(a) for a in d["attrs"]])


def issue(ik: IssuerKey, sk: int, attrs: Sequence[int]) -> Credential:
    """(reference: idemix/credential.go NewCredential — collapsed
    issuance: the blinded-request round trip is protocol plumbing)"""
    if len(attrs) != len(ik.HAttrs):
        raise IdemixError("attribute count mismatch")
    if ik.x is None:
        raise IdemixError("issuer key is public-only; issuing needs "
                          "the secret key")
    e, s = _rand_zr(), _rand_zr()
    B = g1_add(G1.generator(), g1_mul(sk, ik.HSk))
    B = g1_add(B, g1_mul(s, ik.HRand))
    for ai, Hi in zip(attrs, ik.HAttrs):
        B = g1_add(B, g1_mul(ai, Hi))
    inv = pow((e + ik.x) % R, -1, R)
    A = g1_mul(inv, B)
    return Credential(A, B, e, s, list(attrs))


def credential_valid(ik: IssuerKey, cred: Credential) -> bool:
    """e(A, W * g2^e) == e(B, g2) (reference: credential.go Ver)"""
    lhs = pairing(cred.A, g2_add(ik.W, g2_mul(cred.e, ik.g2)))
    rhs = pairing(cred.B, ik.g2)
    return lhs == rhs


# --- Presentation signature -------------------------------------------------

class Signature:
    __slots__ = ("A_prime", "A_bar", "B_prime", "c", "z_e", "z_r2",
                 "z_r3", "z_s", "z_sk", "z_attrs", "nonce")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


def sign(ik: IssuerKey, cred: Credential, sk: int, msg: bytes,
         disclosed: Dict[int, int]) -> Signature:
    """Create a presentation proof over `msg` disclosing only the
    attribute indices in `disclosed` (reference: signature.go:50
    NewSignature)."""
    for i, v in disclosed.items():
        if cred.attrs[i] != v:
            raise IdemixError("disclosed value mismatch")
    r1 = _rand_zr()
    r2 = _rand_zr()
    r3 = pow(r1, -1, R)
    A_prime = g1_mul(r1, cred.A)
    A_bar = g1_add(g1_mul((-cred.e) % R, A_prime),
                   g1_mul(r1, cred.B))
    B_prime = g1_add(g1_mul(r1, cred.B), g1_mul((-r2) % R, ik.HRand))
    s_prime = (cred.s - r2 * r3) % R
    hidden = [i for i in range(len(cred.attrs)) if i not in disclosed]

    # commitments
    re_, rr2 = _rand_zr(), _rand_zr()
    rr3, rs = _rand_zr(), _rand_zr()
    rsk = _rand_zr()
    rattrs = {i: _rand_zr() for i in hidden}
    t1 = g1_add(g1_mul(re_, A_prime), g1_mul(rr2, ik.HRand))
    t2 = g1_add(g1_mul(rr3, B_prime), g1_mul((-rs) % R, ik.HRand))
    t2 = g1_add(t2, g1_mul((-rsk) % R, ik.HSk))
    for i in hidden:
        t2 = g1_add(t2, g1_mul((-rattrs[i]) % R, ik.HAttrs[i]))

    nonce = secrets.token_bytes(32)
    c = _challenge(ik, A_prime, A_bar, B_prime, t1, t2, disclosed,
                   msg, nonce)
    return Signature(
        A_prime=A_prime, A_bar=A_bar, B_prime=B_prime, c=c,
        z_e=(re_ + c * ((-cred.e) % R)) % R,
        z_r2=(rr2 + c * r2) % R,
        z_r3=(rr3 + c * r3) % R,
        z_s=(rs + c * s_prime) % R,
        z_sk=(rsk + c * sk) % R,
        z_attrs={i: (rattrs[i] + c * cred.attrs[i]) % R for i in hidden},
        nonce=nonce)


def _challenge(ik, A_prime, A_bar, B_prime, t1, t2, disclosed, msg,
               nonce) -> int:
    parts = [_g1_bytes(A_prime), _g1_bytes(A_bar), _g1_bytes(B_prime),
             _g1_bytes(t1), _g1_bytes(t2), _g2_bytes(ik.W), msg, nonce]
    for i in sorted(disclosed):
        parts.append(i.to_bytes(4, "big"))
        parts.append(disclosed[i].to_bytes(32, "big"))
    return _hash_to_zr(*parts)


def verify(ik: IssuerKey, sig: Signature, msg: bytes,
           disclosed: Dict[int, int]) -> bool:
    """(reference: idemix/signature.go:243 Signature.Ver — the
    pairing check + recomputed Fiat-Shamir challenge)"""
    if sig.A_prime is None:
        return False                   # A' must not be the identity
    # THE pairing equation: e(A', W) == e(Abar, g2)
    if pairing(sig.A_prime, ik.W) != pairing(sig.A_bar, ik.g2):
        return False
    return _verify_schnorr(ik, sig, msg, disclosed)


def batch_verify(ik: IssuerKey, items, use_device: bool = True):
    """Verify many presentations at once: the pairing equations — the
    ~85% cost of Ver — run as ONE batched device dispatch
    (ops/fp256bn_dev.pairing_check_batch, per idemix/KERNEL_PLAN.md
    R4.4); the cheap Schnorr/Fiat-Shamir algebra stays host-side.

    `items`: [(sig, msg, disclosed)];  -> [bool] per item.
    (reference behavior anchor: idemix/signature.go:243 Ver, applied
    per block of presentations — BASELINE config #4)."""
    results = [False] * len(items)
    todo = []                          # (index, sig)
    for idx, (sig, _msg, _d) in enumerate(items):
        if sig.A_prime is not None and sig.A_bar is not None:
            todo.append(idx)
    if todo:
        if use_device:
            from fabric_mod_tpu.ops.fp256bn_dev import pairing_check_batch
            a_pts = [items[i][0].A_prime for i in todo]
            b_pts = [items[i][0].A_bar.neg() for i in todo]
            ok = pairing_check_batch(a_pts, ik.W, b_pts, ik.g2)
            pair_ok = {i: bool(o) for i, o in zip(todo, ok)}
        else:
            pair_ok = {i: pairing(items[i][0].A_prime, ik.W) ==
                       pairing(items[i][0].A_bar, ik.g2) for i in todo}
        for i in todo:
            if pair_ok[i]:
                sig, msg, disclosed = items[i]
                results[i] = _verify_schnorr(ik, sig, msg, disclosed)
    return results


def _verify_schnorr(ik: IssuerKey, sig: Signature, msg: bytes,
                    disclosed: Dict[int, int]) -> bool:
    """The non-pairing remainder of Ver: recompute the Fiat-Shamir
    commitments from the responses and check the challenge."""
    c = sig.c
    # t1' = A'^z_e * HRand^z_r2 * (Abar/B')^-c
    t1 = g1_add(g1_mul(sig.z_e, sig.A_prime),
                g1_mul(sig.z_r2, ik.HRand))
    abar_over_bp = g1_add(sig.A_bar, sig.B_prime.neg()
                          if sig.B_prime else None)
    t1 = g1_add(t1, g1_mul((-c) % R, abar_over_bp))
    # t2' = B'^z_r3 * HRand^-z_s * HSk^-z_sk * prod_hidden Hi^-z_ai
    #       * (g1 * prod_disclosed Hi^ai)^-c
    t2 = g1_add(g1_mul(sig.z_r3, sig.B_prime),
                g1_mul((-sig.z_s) % R, ik.HRand))
    t2 = g1_add(t2, g1_mul((-sig.z_sk) % R, ik.HSk))
    for i, z in sig.z_attrs.items():
        if i in disclosed:
            return False               # hidden/disclosed sets must agree
        t2 = g1_add(t2, g1_mul((-z) % R, ik.HAttrs[i]))
    base = G1.generator()
    for i in sorted(disclosed):
        base = g1_add(base, g1_mul(disclosed[i], ik.HAttrs[i]))
    t2 = g1_add(t2, g1_mul((-c) % R, base))
    if set(sig.z_attrs) | set(disclosed) != set(range(len(ik.HAttrs))):
        return False
    return c == _challenge(ik, sig.A_prime, sig.A_bar, sig.B_prime,
                           t1, t2, disclosed, msg, sig.nonce)
