"""Epoch-based credential revocation for idemix.

(reference: idemix/revocation_authority.go — the Revocation Authority
signs per-epoch Credential Revocation Information (CRI) with an ECDSA
key; Signature.Ver (signature.go:243) checks the non-revocation
evidence against the CRI before accepting a presentation.)

Design (and its honest delta from the reference): the reference ships
ALG_NO_REVOCATION — the signed CRI exists but never names a revoked
credential, so nothing is enforceable.  Here the CRI carries the
DIGESTS of revoked revocation handles, and enforcement is real: a
presentation made under a CRI-enforcing verifier must DISCLOSE its
revocation-handle attribute; the verifier checks the proof binds the
handle into the credential (the ordinary disclosed-attribute Schnorr
relation) and that its digest is not in the CRI.  The privacy cost —
presentations by one credential become linkable to the verifier via
the disclosed handle — is the zero-egress trade for the reference's
(unshipped) accumulator math, and is documented at the MSP layer.

Epoch freshness: verifiers pin the epoch they expect; a CRI for an
older epoch (a replayed, pre-revocation list) is rejected.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import List, Optional, Sequence

try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import Prehashed
    from cryptography.exceptions import InvalidSignature
except ImportError:
    # Wheel-less container: pure-python P-256 fallback (see
    # bccsp/_ecfallback.py; bccsp/sw.py logged the downgrade).
    from fabric_mod_tpu.bccsp._ecfallback import (InvalidSignature,
                                                  Prehashed, ec, hashes,
                                                  serialization)


def rh_digest(rh: int) -> str:
    """Digest under which a revocation handle appears in the CRI."""
    return hashlib.sha256(
        rh.to_bytes(32, "big", signed=False)).hexdigest()


@dataclasses.dataclass
class CRI:
    """Credential Revocation Information: one epoch's signed list
    (reference: the CRI proto of revocation_authority.go)."""
    epoch: int
    revoked_digests: List[str]
    signature_hex: str = ""

    def __post_init__(self):
        self._revoked_set = set(self.revoked_digests)

    def signed_payload(self) -> bytes:
        return json.dumps({"epoch": self.epoch,
                           "revoked": sorted(self.revoked_digests)},
                          sort_keys=True).encode()

    def to_dict(self) -> dict:
        return {"epoch": self.epoch,
                "revoked": sorted(self.revoked_digests),
                "sig": self.signature_hex}

    @classmethod
    def from_dict(cls, d: dict) -> "CRI":
        return cls(epoch=int(d["epoch"]),
                   revoked_digests=list(d["revoked"]),
                   signature_hex=str(d["sig"]))

    def is_revoked(self, rh: int) -> bool:
        return rh_digest(rh) in self._revoked_set


class RevocationAuthority:
    """Holds the RA key, tracks revoked handles, signs CRIs
    (reference: revocation_authority.go NewRevocationAuthority +
    Sign)."""

    def __init__(self):
        self._key = ec.generate_private_key(ec.SECP256R1())
        self._revoked: set = set()
        self.epoch = 0

    @property
    def public_pem(self) -> bytes:
        return self._key.public_key().public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    def revoke(self, rh: int) -> None:
        """Revoking advances the epoch: every verifier pinned to the
        new epoch immediately refuses the old list."""
        self._revoked.add(rh_digest(rh))
        self.epoch += 1

    def cri(self) -> CRI:
        # always the RA's CURRENT epoch: a caller-chosen epoch would
        # be a signing oracle for future-epoch lists carrying a
        # pre-revocation view
        out = CRI(epoch=self.epoch,
                  revoked_digests=sorted(self._revoked))
        digest = hashlib.sha256(out.signed_payload()).digest()
        sig = self._key.sign(digest,
                             ec.ECDSA(Prehashed(hashes.SHA256())))
        out.signature_hex = sig.hex()
        return out


def verify_cri(cri: CRI, ra_public_pem: bytes,
               expected_epoch: Optional[int] = None) -> bool:
    """RA signature + epoch pin (reference: the CRI checks inside
    signature.go Ver)."""
    if expected_epoch is not None and cri.epoch != expected_epoch:
        return False
    try:
        pub = serialization.load_pem_public_key(ra_public_pem)
        digest = hashlib.sha256(cri.signed_payload()).digest()
        pub.verify(bytes.fromhex(cri.signature_hex), digest,
                   ec.ECDSA(Prehashed(hashes.SHA256())))
        return True
    except (InvalidSignature, ValueError, TypeError):
        return False
