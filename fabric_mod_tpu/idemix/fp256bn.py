"""FP256BN pairing arithmetic — host-side reference implementation.

(reference: the fabric-amcl FP256BN library behind idemix/ —
idemix/util.go:13-21 — re-derived from the public curve definition,
not ported: FP256BN is the ISO/IEC 15946-5 / CFRG "BN256" curve with
BN parameter u = -0x6882F5C030B0A801, p = 36u⁴+36u³+24u²+6u+1,
r = 36u⁴+36u³+18u²+6u+1, E: y² = x³ + 3 over Fp, G1 = (1, 2), and a
sextic D-type twist E': y² = x³ + 3/ξ over Fp2 with ξ = 1 + i.
Both p and r verified prime and consistent with the BN polynomials
(see tests).

This is the round-3 feasibility spike (SURVEY §7 hard part #2): a
correct, slow, pure-Python optimal-ate pairing that pins down the
semantics the TPU kernels must reproduce.  The kernel decomposition
plan lives in idemix/KERNEL_PLAN.md; the batch axis is "many pairing
checks per block" (BASELINE config #4).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

# --- BN parameters ----------------------------------------------------------
U = -0x6882F5C030B0A801
P = 36 * U**4 + 36 * U**3 + 24 * U**2 + 6 * U + 1
R = 36 * U**4 + 36 * U**3 + 18 * U**2 + 6 * U + 1
T = 6 * U**2 + 1                     # trace of Frobenius
B = 3                                # E: y^2 = x^3 + 3

assert P % 4 == 3                    # i^2 = -1 is a non-residue


def _inv(a: int, m: int = P) -> int:
    return pow(a, -1, m)


# --- Fp2 = Fp[i]/(i^2+1) ----------------------------------------------------

class Fp2:
    __slots__ = ("a", "b")           # a + b*i

    def __init__(self, a: int, b: int = 0):
        self.a = a % P
        self.b = b % P

    def __add__(self, o):  return Fp2(self.a + o.a, self.b + o.b)
    def __sub__(self, o):  return Fp2(self.a - o.a, self.b - o.b)
    def __neg__(self):     return Fp2(-self.a, -self.b)

    def __mul__(self, o):
        if isinstance(o, int):
            return Fp2(self.a * o, self.b * o)
        # Karatsuba
        t0 = self.a * o.a
        t1 = self.b * o.b
        t2 = (self.a + self.b) * (o.a + o.b)
        return Fp2(t0 - t1, t2 - t0 - t1)

    __rmul__ = __mul__

    def sqr(self):
        # (a+bi)^2 = (a+b)(a-b) + 2ab i
        return Fp2((self.a + self.b) * (self.a - self.b),
                   2 * self.a * self.b)

    def inv(self):
        d = _inv((self.a * self.a + self.b * self.b) % P)
        return Fp2(self.a * d, -self.b * d)

    def conj(self):
        return Fp2(self.a, -self.b)

    def mul_xi(self):
        """Multiply by xi = 1 + i (the twist constant)."""
        return Fp2(self.a - self.b, self.a + self.b)

    def __eq__(self, o):
        return self.a == o.a and self.b == o.b

    def is_zero(self):
        return self.a == 0 and self.b == 0

    def __repr__(self):
        return f"Fp2({hex(self.a)},{hex(self.b)})"

    @staticmethod
    def zero():
        return Fp2(0, 0)

    @staticmethod
    def one():
        return Fp2(1, 0)


XI = Fp2(1, 1)
# The sextic twist carrying the r-torsion for this (p, xi) is the
# M-type: y^2 = x^3 + 3*xi (verified empirically in tests: cofactor
# (2p - r) clearing yields r-torsion on 3*xi, not on 3/xi).
B_TWIST = XI * B


# --- Fp6 = Fp2[v]/(v^3 - xi);  Fp12 = Fp6[w]/(w^2 - v) ----------------------

class Fp6:
    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fp2, c1: Fp2, c2: Fp2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    @staticmethod
    def zero():
        return Fp6(Fp2.zero(), Fp2.zero(), Fp2.zero())

    @staticmethod
    def one():
        return Fp6(Fp2.one(), Fp2.zero(), Fp2.zero())

    def __add__(self, o):
        return Fp6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o):
        return Fp6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self):
        return Fp6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o):
        if isinstance(o, (int, Fp2)):
            return Fp6(self.c0 * o, self.c1 * o, self.c2 * o)
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_xi() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_xi()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fp6(c0, c1, c2)

    def sqr(self):
        return self * self

    def mul_v(self):
        """Multiply by v (the Fp6 indeterminate)."""
        return Fp6(self.c2.mul_xi(), self.c0, self.c1)

    def inv(self):
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.sqr() - (a1 * a2).mul_xi()
        t1 = a2.sqr().mul_xi() - a0 * a1
        t2 = a1.sqr() - a0 * a2
        d = (a0 * t0 + (a2 * t1).mul_xi() + (a1 * t2).mul_xi())
        di = Fp2(d.a, d.b).inv() if d.b else Fp2(_inv(d.a), 0)
        return Fp6(t0 * di, t1 * di, t2 * di)

    def __eq__(self, o):
        return self.c0 == o.c0 and self.c1 == o.c1 and self.c2 == o.c2

    def is_zero(self):
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()


class Fp12:
    __slots__ = ("c0", "c1")         # c0 + c1*w

    def __init__(self, c0: Fp6, c1: Fp6):
        self.c0, self.c1 = c0, c1

    @staticmethod
    def one():
        return Fp12(Fp6.one(), Fp6.zero())

    def __add__(self, o):
        return Fp12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fp12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fp12(-self.c0, -self.c1)

    def __mul__(self, o):
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        return Fp12(t0 + t1.mul_v(),
                    (a0 + a1) * (b0 + b1) - t0 - t1)

    def sqr(self):
        a0, a1 = self.c0, self.c1
        t0 = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_v()) - t0 - t0.mul_v()
        return Fp12(c0, t0 + t0)

    def conj(self):
        """Conjugate over Fp6 (the p^6 Frobenius): unary inverse for
        elements in the cyclotomic subgroup."""
        return Fp12(self.c0, -self.c1)

    def inv(self):
        t = (self.c0 * self.c0 - (self.c1 * self.c1).mul_v()).inv()
        return Fp12(self.c0 * t, -(self.c1 * t))

    def pow(self, e: int):
        if e < 0:
            return self.pow(-e).inv()
        acc = Fp12.one()
        base = self
        while e:
            if e & 1:
                acc = acc * base
            base = base.sqr()
            e >>= 1
        return acc

    def frobenius(self):
        """x -> x^p."""
        c0, c1 = self.c0, self.c1
        f0 = Fp6(c0.c0.conj(), c0.c1.conj() * _FROB6_1,
                 c0.c2.conj() * _FROB6_2)
        f1 = Fp6(c1.c0.conj() * _FROB12, c1.c1.conj() * _FROB12 * _FROB6_1,
                 c1.c2.conj() * _FROB12 * _FROB6_2)
        return Fp12(f0, f1)

    def __eq__(self, o):
        return self.c0 == o.c0 and self.c1 == o.c1


def _fp2_pow(x: Fp2, e: int) -> Fp2:
    acc = Fp2.one()
    while e:
        if e & 1:
            acc = acc * x
        x = x.sqr()
        e >>= 1
    return acc


# Frobenius constants: gamma = xi^((p-1)/6); v^p = gamma^2 v-ish.
# v^p = v^(p-1) * v = xi^((p-1)/3) * v ; w^p = xi^((p-1)/6) * w.
_FROB6_1 = _fp2_pow(XI, (P - 1) // 3)     # multiplies c1 of Fp6
_FROB6_2 = _fp2_pow(XI, 2 * (P - 1) // 3)  # multiplies c2 of Fp6
_FROB12 = _fp2_pow(XI, (P - 1) // 6)       # multiplies the w part


# --- Curve points -----------------------------------------------------------

class G1:
    """Affine point on E/Fp: y^2 = x^3 + 3 (None = infinity)."""

    __slots__ = ("x", "y")

    def __init__(self, x: int, y: int):
        self.x, self.y = x % P, y % P

    @staticmethod
    def generator():
        return G1(1, 2)

    def is_on_curve(self) -> bool:
        return (self.y * self.y - self.x**3 - B) % P == 0

    def __eq__(self, o):
        if o is None:
            return False
        return self.x == o.x and self.y == o.y

    def neg(self):
        return G1(self.x, -self.y)


def g1_add(p: Optional[G1], q: Optional[G1]) -> Optional[G1]:
    if p is None:
        return q
    if q is None:
        return p
    if p.x == q.x and (p.y + q.y) % P == 0:
        return None
    if p.x == q.x:
        lam = (3 * p.x * p.x) * _inv(2 * p.y) % P
    else:
        lam = (q.y - p.y) * _inv(q.x - p.x) % P
    x3 = (lam * lam - p.x - q.x) % P
    return G1(x3, lam * (p.x - x3) - p.y)


def g1_mul(k: int, p: Optional[G1]) -> Optional[G1]:
    if k < 0:
        return g1_mul(-k, p.neg() if p else None)
    acc = None
    while k:
        if k & 1:
            acc = g1_add(acc, p)
        p = g1_add(p, p)
        k >>= 1
    return acc


class G2:
    """Affine point on the twist E'/Fp2: y^2 = x^3 + 3/xi."""

    __slots__ = ("x", "y")

    def __init__(self, x: Fp2, y: Fp2):
        self.x, self.y = x, y

    def is_on_curve(self) -> bool:
        return self.y.sqr() == self.x.sqr() * self.x + B_TWIST

    def __eq__(self, o):
        if o is None:
            return False
        return self.x == o.x and self.y == o.y

    def neg(self):
        return G2(self.x, -self.y)


def g2_add(p: Optional[G2], q: Optional[G2]) -> Optional[G2]:
    if p is None:
        return q
    if q is None:
        return p
    if p.x == q.x and (p.y + q.y).is_zero():
        return None
    if p.x == q.x:
        lam = (p.x.sqr() * 3) * (p.y * 2).inv()
    else:
        lam = (q.y - p.y) * (q.x - p.x).inv()
    x3 = lam.sqr() - p.x - q.x
    return G2(x3, lam * (p.x - x3) - p.y)


def g2_mul(k: int, p: Optional[G2]) -> Optional[G2]:
    if k < 0:
        return g2_mul(-k, p.neg() if p else None)
    acc = None
    while k:
        if k & 1:
            acc = g2_add(acc, p)
        p = g2_add(p, p)
        k >>= 1
    return acc


def _g2_cofactor() -> int:
    # #E'(Fp2) = p^2 - 1 + t^2  hmm — standard: n2 = p + t - 1 reduced…
    # For BN curves the twist order is h2 * r with h2 = p - 1 + t.
    return P - 1 + T


def g2_generator() -> G2:
    """A fixed generator of the r-torsion on the twist: hash-free
    deterministic construction — smallest valid x, cofactor-cleared.

    NOTE: this is OUR generator, not fabric-amcl's ROM constant; all
    keys/credentials here are self-consistent but not wire-compatible
    with amcl-issued ones until the ROM generator is transcribed."""
    x = Fp2(0, 1)
    while True:
        rhs = x.sqr() * x + B_TWIST
        y = _fp2_sqrt(rhs)
        if y is not None:
            cand = G2(x, y)
            gen = g2_mul(_g2_cofactor(), cand)
            if gen is not None:
                assert g2_mul(R, gen) is None, "twist generator not r-torsion"
                return gen
        x = x + Fp2.one()


def _fp2_sqrt(a: Fp2) -> Optional[Fp2]:
    """Square root in Fp2 (p = 3 mod 4), via the norm trick."""
    if a.is_zero():
        return Fp2.zero()
    # norm = a.a^2 + a.b^2 must be a QR in Fp
    n = (a.a * a.a + a.b * a.b) % P
    s = pow(n, (P + 1) // 4, P)
    if s * s % P != n:
        return None
    # x = sqrt((a.a + s)/2) (try both signs of s)
    for sv in (s, P - s):
        half = (a.a + sv) * _inv(2) % P
        x = pow(half, (P + 1) // 4, P)
        if x * x % P != half:
            continue
        if x == 0:
            continue
        y = a.b * _inv(2 * x) % P
        cand = Fp2(x, y)
        if cand.sqr() == a:
            return cand
    return None


# --- Untwist: E'(Fp2) -> E(Fp12) -------------------------------------------
# M-type twist iso with u = w^-1 (u^6 = 1/xi):
#   psi(x', y') = (x' * v^2 / xi,  y' * v*w / xi)
# (v^3 = xi, w^2 = v; verified on-curve + group-iso in tests).

def untwist(q: Optional[G2]):
    """Twist point -> (X, Y) in full Fp12 coordinates on y^2=x^3+3."""
    if q is None:
        return None
    xi_inv = XI.inv()
    x = Fp12(Fp6(Fp2.zero(), Fp2.zero(), q.x * xi_inv), Fp6.zero())
    y = Fp12(Fp6.zero(), Fp6(Fp2.zero(), q.y * xi_inv, Fp2.zero()))
    return (x, y)


def _twist_down(X: Fp12, Y: Fp12) -> G2:
    """Inverse of `untwist` for sparse images (used by the Frobenius
    endomorphism on G2)."""
    return G2(X.c0.c2 * XI, Y.c1.c1 * XI)


def g2_frobenius(q: G2) -> G2:
    """The p-power Frobenius endomorphism on G2 (untwist-Frobenius-
    twist): psi^-1 . pi_p . psi — sparse shapes are preserved, so this
    is just conjugation + two Fp2 constants."""
    X, Y = untwist(q)
    return _twist_down(X.frobenius(), Y.frobenius())


# --- Optimal ate pairing ----------------------------------------------------

def _fp12_of(n: int) -> Fp12:
    return Fp12(Fp6(Fp2(n), Fp2.zero(), Fp2.zero()), Fp6.zero())


def _line(q1: G2, q2: G2, p: G1) -> Tuple[Fp12, Optional[G2]]:
    """Line through q1, q2 (tangent when equal) evaluated at the G1
    point p, computed in full Fp12 via the untwist (generic, not
    sparse-packed: this is the correctness spike; the kernel plan
    sparsifies).  Returns (l(P), q1+q2)."""
    X1, Y1 = untwist(q1)
    xP, yP = _fp12_of(p.x), _fp12_of(p.y)
    if q1.x == q2.x and (q1.y + q2.y).is_zero():
        return xP - X1, None
    if q1 == q2:
        lam2 = (q1.x.sqr() * 3) * (q1.y * 2).inv()
    else:
        lam2 = (q2.y - q1.y) * (q2.x - q1.x).inv()
    x3 = lam2.sqr() - q1.x - q2.x
    q3 = G2(x3, lam2 * (q1.x - x3) - q1.y)
    # lambda in Fp12 via the untwist scaling: lam12 = lam' * u with
    # u = w^-1... easier: recompute from untwisted endpoints
    X2, Y2 = untwist(q2)
    if q1 == q2:
        lam12 = (X1 * X1 * _fp12_of(3)) * (Y1 + Y1).inv()
    else:
        lam12 = (Y2 - Y1) * (X2 - X1).inv()
    l = yP - Y1 - lam12 * (xP - X1)
    return l, q3


def miller_loop(p: G1, q: G2) -> Fp12:
    """Miller loop for the optimal ate pairing: f_{6u+2,Q}(P) times the
    two Frobenius line corrections (6u+2 < 0 here, so the loop result
    is conjugated and T negated, Aranha et al.'s standard trick)."""
    e = 6 * U + 2
    neg = e < 0
    e = abs(e)
    bits = bin(e)[3:]                 # skip leading 1
    f = Fp12.one()
    t = q
    for bit in bits:
        l, t = _line(t, t, p)
        f = f.sqr() * l
        if bit == "1":
            l, t = _line(t, q, p)
            f = f * l
    if neg:
        f = f.conj()                 # f_{-n} = 1/f_n after final exp
        t = t.neg()
    # Frobenius corrections: Q1 = pi_p(Q), Q2 = -pi_p^2(Q)
    q1 = g2_frobenius(q)
    q2 = g2_frobenius(q1).neg()
    l, t = _line(t, q1, p)
    f = f * l
    l, _ = _line(t, q2, p)
    f = f * l
    return f


def final_exponentiation(f: Fp12) -> Fp12:
    """f^((p^12-1)/r): easy part then (slow, correct) hard part."""
    # easy: f^(p^6-1) = conj(f)/f ; then ^(p^2+1)
    f = f.conj() * f.inv()
    f = f.frobenius().frobenius() * f
    # hard part (p^4 - p^2 + 1)/r — naive square-and-multiply (spike)
    e = (P**4 - P**2 + 1) // R
    return f.pow(e)


def pairing(p: Optional[G1], q: Optional[G2]) -> Fp12:
    if p is None or q is None:
        return Fp12.one()
    return final_exponentiation(miller_loop(p, q))
