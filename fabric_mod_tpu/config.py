"""Node configuration: YAML files + environment overrides.

(reference: the viper config system — core/peer/config.go reading
core.yaml with CORE_* env overrides, orderer/common/localconfig/
config.go:505 reading orderer.yaml with ORDERER_* — collapsed to one
typed loader.)

Lookup order (highest wins): environment variable
`<PREFIX>_SECTION_SUBKEY`, the YAML file, the dataclass default —
the same precedence viper gives the reference.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

import yaml


def _env_override(prefix: str, path: str) -> Optional[str]:
    """peer.ledger.snapshotEvery -> PREFIX_LEDGER_SNAPSHOTEVERY."""
    key = prefix + "_" + "_".join(
        p.upper() for p in path.split(".")[1:])
    return os.environ.get(key)


def _dig(data: Dict[str, Any], path: str) -> Optional[Any]:
    cur: Any = data
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        # tolerate case differences like viper
        lowered = {k.lower(): v for k, v in cur.items()}
        cur = lowered.get(part.lower())
    return cur


@dataclasses.dataclass
class PeerConfig:
    """(reference: core/peer/config.go Config — the subset in play)"""
    ledger_dir: str = "data/ledgers"
    validator_pool_size: int = 0        # 0 = device-batched (no pool)
    ops_listen_address: str = "127.0.0.1:0"
    ops_tls_cert: str = ""              # operations TLS (reference:
    ops_tls_key: str = ""               # core.yaml operations.tls.*)
    ops_tls_client_ca: str = ""
    log_spec: str = "info"
    deliver_queue_size: int = 8
    bccsp: str = "TPU"                  # TPU | SW

    FIELDS = {
        "ledger_dir": "peer.fileSystemPath",
        "validator_pool_size": "peer.validatorPoolSize",
        "ops_listen_address": "operations.listenAddress",
        "ops_tls_cert": "operations.tls.cert.file",
        "ops_tls_key": "operations.tls.key.file",
        "ops_tls_client_ca": "operations.tls.clientRootCAs.file",
        "log_spec": "logging.spec",
        "deliver_queue_size": "peer.deliverclient.queueSize",
        "bccsp": "peer.BCCSP.Default",
    }
    ENV_PREFIX = "CORE"


@dataclasses.dataclass
class OrdererConfig:
    """(reference: orderer/common/localconfig/config.go)"""
    ledger_dir: str = "data/orderer"
    consensus_type: str = "solo"
    ops_listen_address: str = "127.0.0.1:0"
    log_spec: str = "info"

    FIELDS = {
        "ledger_dir": "general.fileSystemPath",
        "consensus_type": "general.consensusType",
        "ops_listen_address": "operations.listenAddress",
        "log_spec": "logging.spec",
    }
    ENV_PREFIX = "ORDERER"


def load_config(cls, path: Optional[str] = None):
    """Materialize a typed config: defaults <- YAML <- env."""
    data: Dict[str, Any] = {}
    if path and os.path.exists(path):
        with open(path) as f:
            data = yaml.safe_load(f) or {}
    out = cls()
    for attr, yaml_path in cls.FIELDS.items():
        val = _dig(data, yaml_path)
        env = _env_override(cls.ENV_PREFIX, yaml_path)
        if env is not None:
            val = env
        if val is None:
            continue
        default = getattr(out, attr)
        if isinstance(default, bool):
            val = str(val).lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            val = int(val)
        else:
            val = str(val)
        setattr(out, attr, val)
    return out
