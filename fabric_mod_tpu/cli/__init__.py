"""CLI tools (reference: cmd/ + internal/{cryptogen,configtxgen,peer})."""
