"""discover: query channel config + endorsement layouts.

(reference: cmd/discover + discovery/cmd — the client CLI for the
discovery service; peers/config/endorsers subcommands.  This tool
builds the discovery view from a genesis/config block plus a
membership JSON ({org: [endpoint, ...]}), i.e. the same inputs the
in-process service reads from gossip.)
"""
from __future__ import annotations

import argparse
import json
import sys

from fabric_mod_tpu.channelconfig import Bundle
from fabric_mod_tpu.channelconfig.configtx import config_from_block
from fabric_mod_tpu.protos import messages as m


def _load_bundle(genesis_path: str):
    from fabric_mod_tpu.bccsp.sw import SwCSP
    block = m.Block.decode(open(genesis_path, "rb").read())
    cid, config = config_from_block(block)
    return cid, Bundle(cid, config, SwCSP())


def _membership_fn(path):
    members = {}
    if path:
        raw = json.load(open(path))
        for org, eps in raw.items():
            members[org] = [m.GossipMember(endpoint=e) for e in eps]
    return lambda: members


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabric-mod-tpu discover")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("peers", "config", "endorsers"):
        p = sub.add_parser(name)
        p.add_argument("--genesis", required=True,
                       help="channel genesis/config block file")
        p.add_argument("--membership",
                       help="JSON file: {org: [endpoint, ...]}")
        if name == "endorsers":
            p.add_argument("--chaincode", required=True)
    args = ap.parse_args(argv)

    from fabric_mod_tpu.discovery.service import DiscoveryService
    cid, bundle = _load_bundle(args.genesis)

    class _StaticVinfo:
        def validation_info(self, ns):
            return "builtin", m.ApplicationPolicy(
                channel_config_policy_reference=
                "/Channel/Application/Endorsement").encode()

    svc = DiscoveryService(lambda: bundle, _StaticVinfo(),
                           _membership_fn(args.membership))
    if args.cmd == "peers":
        out = {org: [mem.endpoint for mem in members]
               for org, members in svc.peers().items()}
        json.dump({"channel": cid, "peers": out}, sys.stdout, indent=2)
    elif args.cmd == "config":
        cfg = svc.config()
        out = {"msps": {k: [c.decode() for c in v]
                        for k, v in cfg["msps"].items()},
               "orderers": cfg["orderers"]}
        json.dump({"channel": cid, "config": out}, sys.stdout, indent=2)
    else:
        desc = svc.peers_for_endorsement(args.chaincode)
        json.dump({"channel": cid, "chaincode": args.chaincode,
                   "layouts": [dict(l.quantities_by_org)
                               for l in desc.layouts],
                   "peers_by_org": {
                       org: [mem.endpoint for mem in members]
                       for org, members in desc.peers_by_org.items()}},
                  sys.stdout, indent=2)
    print()
    return 0
