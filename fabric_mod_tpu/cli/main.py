"""The umbrella CLI: fabric-mod-tpu <tool> ...

(reference: the cmd/{peer,orderer,configtxgen,cryptogen} binaries and
internal/peer's cobra tree, collapsed to subcommands of one entry.)
"""
from __future__ import annotations

import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print("usage: fabric-mod-tpu {cryptogen|configtxgen|"
              "configtxlator|idemixgen|discover|node|ledger|"
              "chaincode} ...",
              file=sys.stderr)
        return 2
    tool, rest = argv[0], argv[1:]
    if tool == "cryptogen":
        from fabric_mod_tpu.cli.cryptogen import main as run
    elif tool == "configtxgen":
        from fabric_mod_tpu.cli.configtxgen import main as run
    elif tool == "configtxlator":
        from fabric_mod_tpu.cli.configtxlator import main as run
    elif tool == "idemixgen":
        from fabric_mod_tpu.cli.idemixgen import main as run
    elif tool == "discover":
        from fabric_mod_tpu.cli.discover import main as run
    elif tool == "node":
        from fabric_mod_tpu.cli.node import main as run
    elif tool == "ledger":
        from fabric_mod_tpu.cli.ledgerutil import main as run
    elif tool == "chaincode":
        from fabric_mod_tpu.cli.chaincode import main as run
    else:
        print(f"unknown tool {tool!r}", file=sys.stderr)
        return 2
    return run(rest)


if __name__ == "__main__":
    sys.exit(main())
