"""configtxlator: proto<->JSON translation + config update computation.

(reference: internal/configtxlator — the proto_encode/proto_decode/
compute_update commands (update/update.go); the REST router collapses
to this CLI since the translation logic is library-first here.)
"""
from __future__ import annotations

import argparse
import json
import sys

from fabric_mod_tpu.protos import jsonpb
from fabric_mod_tpu.protos import messages as m


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabric-mod-tpu configtxlator")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("proto_decode",
                       help="wire bytes -> JSON on stdout")
    p.add_argument("--type", required=True,
                   help="message type name, e.g. Config, Block")
    p.add_argument("--input", required=True)

    p = sub.add_parser("proto_encode",
                       help="JSON -> wire bytes")
    p.add_argument("--type", required=True)
    p.add_argument("--input", required=True)
    p.add_argument("--output", required=True)

    p = sub.add_parser("compute_update",
                       help="delta between two Config protos")
    p.add_argument("--channel_id", required=True)
    p.add_argument("--original", required=True)
    p.add_argument("--updated", required=True)
    p.add_argument("--output", required=True)

    args = ap.parse_args(argv)
    if args.cmd == "proto_decode":
        raw = open(args.input, "rb").read()
        json.dump(jsonpb.proto_decode(args.type, raw), sys.stdout,
                  indent=2, sort_keys=True)
        print()
        return 0
    if args.cmd == "proto_encode":
        data = json.load(open(args.input))
        raw = jsonpb.proto_encode(args.type, data)
        with open(args.output, "wb") as f:
            f.write(raw)
        return 0
    if args.cmd == "compute_update":
        from fabric_mod_tpu.channelconfig import compute_update
        original = m.Config.decode(open(args.original, "rb").read())
        updated = m.Config.decode(open(args.updated, "rb").read())
        update = compute_update(args.channel_id, original, updated)
        with open(args.output, "wb") as f:
            f.write(update.encode())
        return 0
    return 2
