"""node: run an orderer + committing peer in one process.

(reference: internal/peer/node/start.go:205 `serve` + orderer/common/
server/main.go:71 `Main` — the bring-up wiring: config, crypto,
registrar, channels, ops server — shrunk to the in-process topology
until the gRPC comm layer lands.)

    fabric-mod-tpu node --genesis genesis.block --crypto crypto-config \
        --orderer-org OrdererOrg --peer-config core.yaml

Starts the solo ordering service + a peer committing via the deliver
client, exposes /metrics /healthz /logspec on the ops address, and
runs until interrupted.
"""
from __future__ import annotations

import os
import signal
import threading

from fabric_mod_tpu.bccsp.sw import SwCSP
from fabric_mod_tpu.channelconfig import Bundle
from fabric_mod_tpu.channelconfig.configtx import config_from_block
from fabric_mod_tpu.config import PeerConfig, load_config
from fabric_mod_tpu.ledger.kvledger import LedgerManager
from fabric_mod_tpu.msp import ca as calib
from fabric_mod_tpu.msp.identities import SigningIdentity
from fabric_mod_tpu.observability import (
    HealthRegistry, OperationsServer, default_provider, get_logger,
    init_logging)
from fabric_mod_tpu.orderer import Broadcast, DeliverService, Registrar
from fabric_mod_tpu.peer.channel import Channel
from fabric_mod_tpu.peer.deliverclient import DeliverClient
from fabric_mod_tpu.protos import messages as m

log = get_logger("node")


def _load_signer(crypto_dir: str, org: str, kind: str, csp):
    from cryptography import x509
    base = os.path.join(crypto_dir, org)
    cert_path = os.path.join(base, f"{kind}s", f"{kind}0.pem")
    key_path = os.path.join(base, f"{kind}s", f"{kind}0.key")
    with open(cert_path, "rb") as f:
        cert = x509.load_pem_x509_certificate(f.read())
    with open(key_path, "rb") as f:
        key_pem = f.read()
    return SigningIdentity(org, cert, key_pem, csp)


def run_node(genesis_path: str, crypto_dir: str, orderer_org: str,
             data_dir: str, peer_cfg: PeerConfig,
             stop_event=None) -> None:
    init_logging(default_provider(), peer_cfg.log_spec)
    csp = SwCSP()
    with open(genesis_path, "rb") as f:
        genesis_block = m.Block.decode(f.read())
    cid, config = config_from_block(genesis_block)

    ingress = None
    if peer_cfg.bccsp.upper() == "TPU":
        import functools
        from fabric_mod_tpu.bccsp.tpu import (
            BatchingVerifyService, TpuVerifier)
        verifier = TpuVerifier()
        # warm EVERY bucket's device program BEFORE serving: cold XLA
        # compiles run minutes, ingress futures must never wait on
        # them, and a flush can select any bucket size
        from fabric_mod_tpu.bccsp.tpu import BUCKETS
        from fabric_mod_tpu.utils.fixtures import make_verify_items
        items, _ = make_verify_items(BUCKETS[-1], n_keys=4,
                                     seed=b"warmup")
        for bucket in BUCKETS:
            log.info("warming device verify program (bucket %d)...",
                     bucket)
            verifier.verify_many(items[:bucket])
        log.info("device warm")
        # ingress coalescing only pays when the device is real; the
        # whole-call timeout still allows a surprise recompile
        ingress = BatchingVerifyService(verifier)
        ingress_verify = functools.partial(ingress.verify_many,
                                           timeout=600)
    else:
        from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
        verifier = FakeBatchVerifier(csp)
        ingress_verify = None

    orderer_signer = _load_signer(crypto_dir, orderer_org, "orderer", csp)
    registrar = Registrar(os.path.join(data_dir, "orderer"),
                          orderer_signer, csp,
                          verify_many=ingress_verify)
    if registrar.get_chain(cid) is None:
        support = registrar.create_channel(genesis_block)
    else:
        support = registrar.get_chain(cid)
    broadcast = Broadcast(registrar)

    ledger_mgr = LedgerManager(os.path.join(data_dir, peer_cfg.ledger_dir))
    ledger = ledger_mgr.create_or_open(cid)
    bundle = Bundle(cid, config, csp)
    channel = Channel(cid, ledger, verifier, bundle, csp)
    if ledger.height == 0:
        channel.init_from_genesis(genesis_block)

    health = HealthRegistry()
    health.register("ledger", lambda: None if ledger.height > 0 else
                    (_ for _ in ()).throw(RuntimeError("empty ledger")))
    host, _, port = peer_cfg.ops_listen_address.partition(":")
    # operations TLS (reference: core.yaml operations.tls.*); with a
    # client CA, clients must present certs
    ops_tls = None
    if peer_cfg.ops_tls_cert and peer_cfg.ops_tls_key:
        ops_tls = {"cert": peer_cfg.ops_tls_cert,
                   "key": peer_cfg.ops_tls_key,
                   "client_ca": peer_cfg.ops_tls_client_ca or None}
    # the participation API can destroy channel storage: mount it only
    # on loopback, or off-loopback strictly behind client-
    # authenticated TLS (reference: the admin server's
    # clientAuthRequired stance)
    participation = None
    loopback = (host or "127.0.0.1") in ("127.0.0.1", "localhost",
                                         "::1")
    if loopback or (ops_tls and ops_tls["client_ca"]):
        from fabric_mod_tpu.orderer.participation import (
            ChannelParticipation)
        participation = ChannelParticipation(registrar)
    else:
        log.warning(
            "ops listener on %s is not loopback and has no client-"
            "authenticated TLS (operations.tls.cert/key + "
            "clientRootCAs): channel participation API disabled",
            host)
    ops = OperationsServer(host or "127.0.0.1", int(port or 0),
                           default_provider(), health,
                           participation=participation, tls=ops_tls)
    ops.start()
    log.info("ops server on %s; channel %s at height %d",
             ops.addr, cid, ledger.height)

    client = DeliverClient(channel, DeliverService(support),
                           queue_size=peer_cfg.deliver_queue_size)
    runner = threading.Thread(
        target=lambda: client.run(idle_timeout_s=3600.0), daemon=True)
    runner.start()

    stop = stop_event or threading.Event()
    try:
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        from fabric_mod_tpu.observability.diag import install_signal_dump
        install_signal_dump()              # SIGUSR1 -> thread stacks
    except ValueError:
        pass                               # not the main thread (tests)
    stop.wait()
    client.stop()
    ops.stop()
    registrar.close()
    ledger_mgr.close()
    if ingress is not None:
        ingress.close()
    return broadcast


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="node")
    ap.add_argument("--genesis", required=True)
    ap.add_argument("--crypto", default="crypto-config")
    ap.add_argument("--orderer-org", default="OrdererOrg")
    ap.add_argument("--data", default="data")
    ap.add_argument("--config", default=None, help="core.yaml path")
    args = ap.parse_args(argv)
    peer_cfg = load_config(PeerConfig, args.config)
    run_node(args.genesis, args.crypto, args.orderer_org, args.data,
             peer_cfg)
    return 0
