"""node: the process entry points — standalone orderer, standalone
peer, or the combined single-process topology.

(reference: orderer/common/server/main.go:71 `Main` for
`--role orderer`; internal/peer/node/start.go:205 `serve` for
`--role peer`; the combined role keeps the original in-process
topology for development.)

    # a raft ordering node (gRPC Broadcast/Deliver + cluster Step):
    fabric-mod-tpu node --role orderer --id o0 \
        --genesis genesis.block --crypto crypto-config \
        --listen 127.0.0.1:7050 --cluster-listen 127.0.0.1:7055 \
        --cluster-peers o0=127.0.0.1:7055,o1=...,o2=...

    # a committing peer pulling from the ordering service with
    # failover across endpoints:
    fabric-mod-tpu node --role peer --org Org1 \
        --genesis genesis.block --crypto crypto-config \
        --orderers 127.0.0.1:7050,127.0.0.1:7150

Each role exposes /metrics /healthz /logspec (and, on orderers, the
channel-participation API) on its ops address and runs until
interrupted.  The process-network test tier
(tests/test_procnet.py) spawns these as real OS processes, kills the
raft leader, and watches commit resume — the nwo model
(reference: integration/nwo/network.go:44-60).
"""
from __future__ import annotations

import itertools
import os
import signal
import threading

from fabric_mod_tpu.bccsp.sw import SwCSP
from fabric_mod_tpu.channelconfig import Bundle
from fabric_mod_tpu.channelconfig.configtx import config_from_block
from fabric_mod_tpu.config import PeerConfig, load_config
from fabric_mod_tpu.ledger.kvledger import LedgerManager
from fabric_mod_tpu.msp import ca as calib
from fabric_mod_tpu.msp.identities import SigningIdentity
from fabric_mod_tpu.observability import (
    OperationsServer, default_health, default_provider,
    get_logger, init_logging)
from fabric_mod_tpu.orderer import Broadcast, DeliverService, Registrar
from fabric_mod_tpu.peer.channel import Channel
from fabric_mod_tpu.peer.deliverclient import DeliverClient
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.concurrency.threads import RegisteredThread

log = get_logger("node")


_role_seq = itertools.count()


def _register_role_health(health, name, checker):
    """Per-instance key (name#seq): two roles hosted in one process
    (embedding, in-process tests) share the process-default registry,
    and a fixed key would let the second registration mask the
    first's failing checker — the same masking the commitpipe/breaker
    registrants key around."""
    key = f"{name}#{next(_role_seq)}"
    health.register(key, checker)
    return key


def _load_signer(crypto_dir: str, org: str, kind: str, csp):
    try:
        from cryptography import x509
    except ImportError:       # wheel-less: bccsp/_x509fallback.py
        from fabric_mod_tpu.bccsp import _x509fallback as x509
    base = os.path.join(crypto_dir, org)
    cert_path = os.path.join(base, f"{kind}s", f"{kind}0.pem")
    key_path = os.path.join(base, f"{kind}s", f"{kind}0.key")
    with open(cert_path, "rb") as f:
        cert = x509.load_pem_x509_certificate(f.read())
    with open(key_path, "rb") as f:
        key_pem = f.read()
    return SigningIdentity(org, cert, key_pem, csp)


def run_node(genesis_path: str, crypto_dir: str, orderer_org: str,
             data_dir: str, peer_cfg: PeerConfig,
             stop_event=None) -> None:
    init_logging(default_provider(), peer_cfg.log_spec)
    csp = SwCSP()
    with open(genesis_path, "rb") as f:
        genesis_block = m.Block.decode(f.read())
    cid, config = config_from_block(genesis_block)

    ingress = None
    if peer_cfg.bccsp.upper() == "TPU":
        import functools
        from fabric_mod_tpu.bccsp.tpu import (
            BatchingVerifyService, TpuVerifier)
        verifier = TpuVerifier()
        # warm EVERY bucket's device program BEFORE serving: cold XLA
        # compiles run minutes, ingress futures must never wait on
        # them, and a flush can select any bucket size
        from fabric_mod_tpu.bccsp.tpu import BUCKETS
        from fabric_mod_tpu.utils.fixtures import make_verify_items
        items, _ = make_verify_items(BUCKETS[-1], n_keys=4,
                                     seed=b"warmup")
        for bucket in BUCKETS:
            log.info("warming device verify program (bucket %d)...",
                     bucket)
            verifier.verify_many(items[:bucket])
        log.info("device warm")
        # ingress coalescing only pays when the device is real; the
        # whole-call timeout still allows a surprise recompile
        ingress = BatchingVerifyService(verifier)
        ingress_verify = functools.partial(ingress.verify_many,
                                           timeout=600)
    else:
        from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
        verifier = FakeBatchVerifier(csp)
        ingress_verify = None

    orderer_signer = _load_signer(crypto_dir, orderer_org, "orderer", csp)
    registrar = Registrar(os.path.join(data_dir, "orderer"),
                          orderer_signer, csp,
                          verify_many=ingress_verify)
    if registrar.get_chain(cid) is None:
        support = registrar.create_channel(genesis_block)
    else:
        support = registrar.get_chain(cid)
    broadcast = Broadcast(registrar)

    ledger_mgr = LedgerManager(os.path.join(data_dir, peer_cfg.ledger_dir))
    ledger = ledger_mgr.create_or_open(cid)
    bundle = Bundle(cid, config, csp)
    channel = Channel(cid, ledger, verifier, bundle, csp)
    if ledger.height == 0:
        channel.init_from_genesis(genesis_block)

    health = default_health()
    _register_role_health(
        health, "ledger", lambda: None if ledger.height > 0 else
        (_ for _ in ()).throw(RuntimeError("empty ledger")))
    host, _, port = peer_cfg.ops_listen_address.partition(":")
    # operations TLS (reference: core.yaml operations.tls.*); with a
    # client CA, clients must present certs
    ops_tls = None
    if peer_cfg.ops_tls_cert and peer_cfg.ops_tls_key:
        ops_tls = {"cert": peer_cfg.ops_tls_cert,
                   "key": peer_cfg.ops_tls_key,
                   "client_ca": peer_cfg.ops_tls_client_ca or None}
    # the participation API can destroy channel storage: mount it only
    # on loopback, or off-loopback strictly behind client-
    # authenticated TLS (reference: the admin server's
    # clientAuthRequired stance)
    participation = None
    loopback = (host or "127.0.0.1") in ("127.0.0.1", "localhost",
                                         "::1")
    if loopback or (ops_tls and ops_tls["client_ca"]):
        from fabric_mod_tpu.orderer.participation import (
            ChannelParticipation)
        participation = ChannelParticipation(registrar)
    else:
        log.warning(
            "ops listener on %s is not loopback and has no client-"
            "authenticated TLS (operations.tls.cert/key + "
            "clientRootCAs): channel participation API disabled",
            host)
    ops = OperationsServer(host or "127.0.0.1", int(port or 0),
                           default_provider(), health,
                           participation=participation, tls=ops_tls)
    ops.start()
    log.info("ops server on %s; channel %s at height %d",
             ops.addr, cid, ledger.height)

    client = DeliverClient(channel, DeliverService(support),
                           queue_size=peer_cfg.deliver_queue_size)
    runner = RegisteredThread(
        target=lambda: client.run(idle_timeout_s=3600.0),
        name="node-deliver", structure="cli.node")
    runner.start()

    stop = stop_event or threading.Event()
    try:
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        from fabric_mod_tpu.observability.diag import install_signal_dump
        install_signal_dump()              # SIGUSR1 -> thread stacks
    except ValueError:
        pass                               # not the main thread (tests)
    stop.wait()
    client.stop()
    ops.stop()
    registrar.close()
    ledger_mgr.close()
    if ingress is not None:
        ingress.close()
    return broadcast


def _read_tls_dir(tls_dir):
    """Optional TLS material directory: ca.crt server.crt server.key
    [client.crt client.key].  Returns a dict of PEM bytes or None."""
    if not tls_dir:
        return None
    out = {}
    for name in ("ca.crt", "server.crt", "server.key",
                 "client.crt", "client.key"):
        path = os.path.join(tls_dir, name)
        if os.path.exists(path):
            with open(path, "rb") as f:
                out[name] = f.read()
    return out or None


def _start_ops(peer_cfg: PeerConfig, health, participation=None):
    host, _, port = peer_cfg.ops_listen_address.partition(":")
    ops_tls = None
    if peer_cfg.ops_tls_cert and peer_cfg.ops_tls_key:
        ops_tls = {"cert": peer_cfg.ops_tls_cert,
                   "key": peer_cfg.ops_tls_key,
                   "client_ca": peer_cfg.ops_tls_client_ca or None}
    loopback = (host or "127.0.0.1") in ("127.0.0.1", "localhost", "::1")
    if participation is not None and not (
            loopback or (ops_tls and ops_tls["client_ca"])):
        log.warning("ops listener on %s is not loopback and has no "
                    "client-authenticated TLS: participation API "
                    "disabled", host)
        participation = None
    ops = OperationsServer(host or "127.0.0.1", int(port or 0),
                           default_provider(), health,
                           participation=participation, tls=ops_tls)
    ops.start()
    return ops


def _install_stop_signals(stop):
    try:
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        from fabric_mod_tpu.observability.diag import install_signal_dump
        install_signal_dump()              # SIGUSR1 -> thread stacks
    except ValueError:
        pass                               # not the main thread (tests)


def run_orderer(node_id: str, genesis_path: str, crypto_dir: str,
                orderer_org: str, data_dir: str, listen: str,
                cluster_listen: str, cluster_peers: dict,
                peer_cfg: PeerConfig, tls=None, stop_event=None) -> None:
    """A standalone ordering node (reference: orderer/common/server/
    main.go:71): registrar + consenter-by-ConsensusType + gRPC
    AtomicBroadcast server + cluster Step transport + participation
    API on the ops listener."""
    init_logging(default_provider(), peer_cfg.log_spec)
    csp = SwCSP()
    with open(genesis_path, "rb") as f:
        genesis_block = m.Block.decode(f.read())
    cid, _config = config_from_block(genesis_block)
    signer = _load_signer(crypto_dir, orderer_org, "orderer", csp)

    tls = tls or {}
    transport = None
    consenters = {}
    if cluster_peers:
        from fabric_mod_tpu.orderer.cluster import GRPCRaftTransport
        from fabric_mod_tpu.orderer.raftchain import RaftChain
        transport = GRPCRaftTransport(
            node_id, dict(cluster_peers), listen_address=cluster_listen,
            server_cert=tls.get("server.crt"),
            server_key=tls.get("server.key"),
            client_ca=tls.get("ca.crt"),
            client_cert=tls.get("client.crt"),
            client_key=tls.get("client.key"))
        transport.start()
        wal_dir = os.path.join(data_dir, "raft")
        os.makedirs(wal_dir, exist_ok=True)

        def raft_factory(support, _t=transport):
            return RaftChain(
                node_id, sorted(cluster_peers), _t,
                os.path.join(wal_dir, f"{support.channel_id}.wal"),
                support)
        consenters["etcdraft"] = raft_factory

    registrar = Registrar(os.path.join(data_dir, "orderer"), signer,
                          csp, consenters=consenters)
    if registrar.get_chain(cid) is None:
        registrar.create_channel(genesis_block)

    from fabric_mod_tpu.orderer.server import OrdererServer
    server = OrdererServer(registrar, listen,
                           server_cert_pem=tls.get("server.crt"),
                           server_key_pem=tls.get("server.key"))
    server.start()

    health = default_health()
    _register_role_health(health, "registrar", lambda: None)
    from fabric_mod_tpu.orderer.participation import ChannelParticipation
    ops = _start_ops(peer_cfg, health,
                     participation=ChannelParticipation(registrar))
    log.info("orderer %s: channel %s, broadcast/deliver on port %d, "
             "ops on %s", node_id, cid, server.port, ops.addr)

    stop = stop_event or threading.Event()
    _install_stop_signals(stop)
    stop.wait()
    server.stop()
    ops.stop()
    registrar.close()
    if transport is not None:
        transport.stop()


def run_peer(org: str, genesis_path: str, crypto_dir: str,
             data_dir: str, orderer_addresses: list,
             peer_cfg: PeerConfig, tls=None, stop_event=None,
             peer_listen: str = "127.0.0.1:0") -> None:
    """A standalone committing peer (reference: internal/peer/node/
    start.go:205): ledger + channel + MCS-verified pipelined deliver
    client with endpoint failover + the gRPC endorsement service on
    `peer_listen`."""
    init_logging(default_provider(), peer_cfg.log_spec)
    csp = SwCSP()
    with open(genesis_path, "rb") as f:
        genesis_block = m.Block.decode(f.read())
    cid, config = config_from_block(genesis_block)

    if peer_cfg.bccsp.upper() == "TPU":
        from fabric_mod_tpu.bccsp.tpu import TpuVerifier
        verifier = TpuVerifier()
    else:
        from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
        verifier = FakeBatchVerifier(csp)

    ledger_mgr = LedgerManager(os.path.join(data_dir,
                                            peer_cfg.ledger_dir))
    ledger = ledger_mgr.create_or_open(cid)
    bundle = Bundle(cid, config, csp)
    channel = Channel(cid, ledger, verifier, bundle, csp)
    if ledger.height == 0:
        channel.init_from_genesis(genesis_block)

    from fabric_mod_tpu.peer.blocksprovider import (
        Endpoint, FailoverDeliverSource)
    tls = tls or {}
    endpoints = [Endpoint(addr, server_root_pem=tls.get("ca.crt"))
                 for addr in orderer_addresses]
    source = FailoverDeliverSource(endpoints, cid)
    client = DeliverClient(channel, source,
                           queue_size=peer_cfg.deliver_queue_size)
    runner = RegisteredThread(
        target=lambda: client.run(idle_timeout_s=3600.0),
        name="peer-deliver", structure="cli.node")
    runner.start()

    # the endorsement surface (reference: core/endorser's
    # ProcessProposal service registered at node start): user
    # contract + system chaincodes + the lifecycle ceremony
    from fabric_mod_tpu.comm.grpc_comm import GRPCServer
    from fabric_mod_tpu.peer.aclmgmt import ACLProvider
    from fabric_mod_tpu.peer.deliverevents import EventDeliverServer
    from fabric_mod_tpu.peer.endorser import Endorser
    from fabric_mod_tpu.peer.endorserserver import EndorserServer
    from fabric_mod_tpu.peer.scc import build_default_registry
    peer_signer = _load_signer(crypto_dir, org, "peer", csp)
    endorser = Endorser(channel, build_default_registry(channel, ledger),
                        peer_signer)
    # one listener for every peer-facing service (endorsement + client
    # events), like the reference's single peer gRPC server
    # worker headroom: event streams park threads at the chain tip
    # (EventDeliverServer caps them at FABRIC_MOD_TPU_DELIVER_STREAMS,
    # default 40), endorsement must always find a free worker beyond
    # that cap
    pserver = GRPCServer(peer_listen,
                         server_cert_pem=tls.get("server.crt"),
                         server_key_pem=tls.get("server.key"),
                         max_workers=64)
    eserver = EndorserServer(endorser, grpc=pserver)
    acl = ACLProvider(channel.bundle, verify_many=verifier.verify_many)
    events = EventDeliverServer(cid, ledger, acl, grpc=pserver)
    pserver.start()

    health = default_health()
    _register_role_health(
        health, "ledger", lambda: None if ledger.height > 0 else
        (_ for _ in ()).throw(RuntimeError("empty ledger")))
    ops = _start_ops(peer_cfg, health)
    log.info("peer (%s): channel %s at height %d, endorser+events on "
             "port %d, orderers %s, ops on %s", org, cid, ledger.height,
             eserver.port, orderer_addresses, ops.addr)

    stop = stop_event or threading.Event()
    _install_stop_signals(stop)
    stop.wait()
    client.stop()
    # join the puller/committer before closing stores: a commit in
    # flight must not race the ledger's file handles going away
    runner.join(timeout=10)
    events.stop()           # wakes tip-parked deliver handlers first
    pserver.stop(1.0)
    ops.stop()
    ledger_mgr.close()


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="node")
    ap.add_argument("--role", choices=("combined", "orderer", "peer"),
                    default="combined")
    ap.add_argument("--genesis", required=True)
    ap.add_argument("--crypto", default="crypto-config")
    ap.add_argument("--orderer-org", default="OrdererOrg")
    ap.add_argument("--org", default="Org1", help="peer role: MSP org")
    ap.add_argument("--data", default="data")
    ap.add_argument("--config", default=None, help="core.yaml path")
    ap.add_argument("--id", default="o0", help="orderer node id")
    ap.add_argument("--listen", default="127.0.0.1:0",
                    help="orderer broadcast/deliver address")
    ap.add_argument("--cluster-listen", default="127.0.0.1:0",
                    help="orderer raft Step address")
    ap.add_argument("--cluster-peers", default="",
                    help="id=host:port,... raft cluster map")
    ap.add_argument("--orderers", default="",
                    help="peer role: comma-separated deliver endpoints")
    ap.add_argument("--peer-listen", default="127.0.0.1:0",
                    help="peer role: endorsement service address")
    ap.add_argument("--tls-dir", default="",
                    help="dir with ca.crt server.crt server.key "
                         "[client.crt client.key]")
    args = ap.parse_args(argv)
    peer_cfg = load_config(PeerConfig, args.config)
    tls = _read_tls_dir(args.tls_dir)
    if args.role == "orderer":
        peers = {}
        for part in filter(None, args.cluster_peers.split(",")):
            pid, _, addr = part.partition("=")
            peers[pid] = addr
        run_orderer(args.id, args.genesis, args.crypto,
                    args.orderer_org, args.data, args.listen,
                    args.cluster_listen, peers, peer_cfg, tls=tls)
    elif args.role == "peer":
        addrs = [a for a in args.orderers.split(",") if a]
        run_peer(args.org, args.genesis, args.crypto, args.data,
                 addrs, peer_cfg, tls=tls,
                 peer_listen=args.peer_listen)
    else:
        run_node(args.genesis, args.crypto, args.orderer_org,
                 args.data, peer_cfg)
    return 0
