"""configtxgen: render a genesis block from a profile + crypto tree.

(reference: internal/configtxgen — encoder.go building the channel
group from configtx.yaml profiles, emitting the genesis block the
orderer bootstraps from.)

Profile (YAML):

    ChannelID: mychannel
    PeerOrgs: [Org1, Org2]        # must exist in the crypto dir
    OrdererOrgs: [OrdererOrg]
    BatchSize:
      MaxMessageCount: 500
    BatchTimeout: 2s
"""
from __future__ import annotations

import os

import yaml

from fabric_mod_tpu.channelconfig import genesis
from fabric_mod_tpu.protos import messages as m


def _org_roots(crypto_dir: str, org: str) -> list:
    path = os.path.join(crypto_dir, org, "ca", "ca.pem")
    with open(path, "rb") as f:
        return [f.read()]


def make_genesis(profile_path: str, crypto_dir: str) -> "tuple[str, m.Block]":
    with open(profile_path) as f:
        prof = yaml.safe_load(f) or {}
    channel_id = prof.get("ChannelID", "testchannel")
    batch = prof.get("BatchSize", {}) or {}
    block = genesis.standard_network(
        channel_id,
        {org: _org_roots(crypto_dir, org)
         for org in prof.get("PeerOrgs", [])},
        {org: _org_roots(crypto_dir, org)
         for org in prof.get("OrdererOrgs", [])},
        max_message_count=int(batch.get("MaxMessageCount", 500)),
        absolute_max_bytes=int(batch.get("AbsoluteMaxBytes",
                                         10 * 1024 * 1024)),
        preferred_max_bytes=int(batch.get("PreferredMaxBytes",
                                          2 * 1024 * 1024)),
        batch_timeout=str(prof.get("BatchTimeout", "2s")),
        consensus_type=str(prof.get("ConsensusType", "solo")),
        consenters=tuple(prof.get("Consenters", []) or ()))
    return channel_id, block


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="configtxgen")
    ap.add_argument("--profile", required=True)
    ap.add_argument("--crypto", default="crypto-config")
    ap.add_argument("--output", default="genesis.block")
    args = ap.parse_args(argv)
    channel_id, block = make_genesis(args.profile, args.crypto)
    with open(args.output, "wb") as f:
        f.write(block.encode())
    print(f"wrote genesis block for {channel_id!r} to {args.output}")
    return 0
