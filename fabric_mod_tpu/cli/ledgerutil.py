"""ledger: operator maintenance + snapshot CLI.

(reference: the `peer node reset/rollback/rebuild-dbs` cobra commands
of internal/peer/node/*.go and the `peer snapshot` CLI.)
"""
from __future__ import annotations


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="ledger")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("reset", "rebuild-dbs"):
        p = sub.add_parser(name)
        p.add_argument("--ledger", required=True,
                       help="ledger directory (peer data/<channel>)")
    p = sub.add_parser("rollback")
    p.add_argument("--ledger", required=True)
    p.add_argument("--block", type=int, required=True)
    p = sub.add_parser("snapshot")
    p.add_argument("--ledger", required=True)
    p.add_argument("--channel", required=True)
    p.add_argument("--output", required=True)
    p = sub.add_parser("join-from-snapshot")
    p.add_argument("--snapshot", required=True)
    p.add_argument("--ledger", required=True)
    args = ap.parse_args(argv)

    from fabric_mod_tpu.ledger import admin
    if args.cmd in ("reset", "rebuild-dbs"):
        admin.rebuild_dbs(args.ledger)
        print(f"dropped derived stores under {args.ledger}; "
              f"state rebuilds from blocks on next start")
    elif args.cmd == "rollback":
        admin.rollback(args.ledger, args.block)
        print(f"rolled {args.ledger} back to block {args.block}")
    elif args.cmd == "snapshot":
        from fabric_mod_tpu.ledger.kvledger import KvLedger
        from fabric_mod_tpu.ledger.snapshot import generate_snapshot
        led = KvLedger(args.ledger, args.channel)
        meta = generate_snapshot(led, args.output)
        led.close()
        print(f"snapshot of {meta['channel']} at height "
              f"{meta['height']} -> {args.output}")
    elif args.cmd == "join-from-snapshot":
        from fabric_mod_tpu.ledger.snapshot import bootstrap_from_snapshot
        led = bootstrap_from_snapshot(args.snapshot, args.ledger)
        print(f"bootstrapped {led.ledger_id} at height {led.height} "
              f"under {args.ledger}")
        led.close()
    return 0
