"""chaincode: invoke/query against running peers + orderer.

(reference: internal/peer/chaincode — `peer chaincode invoke` collects
endorsements over the Endorser gRPC service and broadcasts the tx;
`peer chaincode query` evaluates on one peer and prints the payload.)

    fabric-mod-tpu chaincode invoke --channel ch --name mycc \\
        --args put,k,v --crypto crypto --org Org1 --user user0 \\
        --peers 127.0.0.1:7051,127.0.0.1:8051 \\
        --orderer 127.0.0.1:7050 [--tls-ca ca.crt]

    fabric-mod-tpu chaincode query --channel ch --name mycc \\
        --args get,k --crypto crypto --org Org1 --user user0 \\
        --peers 127.0.0.1:7051
"""
from __future__ import annotations

import os
import sys


def _load_identity(crypto_dir: str, org: str, kind: str, name: str):
    try:
        from cryptography import x509
    except ImportError:       # wheel-less: bccsp/_x509fallback.py
        from fabric_mod_tpu.bccsp import _x509fallback as x509

    from fabric_mod_tpu.bccsp.sw import SwCSP
    from fabric_mod_tpu.msp.identities import SigningIdentity
    base = os.path.join(crypto_dir, org, kind)
    with open(os.path.join(base, f"{name}.pem"), "rb") as f:
        cert = x509.load_pem_x509_certificate(f.read())
    with open(os.path.join(base, f"{name}.key"), "rb") as f:
        key_pem = f.read()
    return SigningIdentity(org, cert, key_pem, SwCSP())


def main(argv=None) -> int:
    import argparse

    from fabric_mod_tpu.comm.grpc_comm import GRPCClient
    from fabric_mod_tpu.peer.endorserserver import (
        RemoteEndorser, invoke_remote, query_remote)

    ap = argparse.ArgumentParser(prog="chaincode")
    ap.add_argument("verb", choices=("invoke", "query"))
    ap.add_argument("--channel", required=True)
    ap.add_argument("--name", default="mycc")
    ap.add_argument("--args", required=True,
                    help="comma-separated chaincode args")
    ap.add_argument("--crypto", default="crypto-config")
    ap.add_argument("--org", default="Org1")
    ap.add_argument("--user", default="user0")
    ap.add_argument("--peers", required=True,
                    help="comma-separated endorser endpoints")
    ap.add_argument("--orderer", default="",
                    help="broadcast endpoint (invoke)")
    ap.add_argument("--tls-ca", default="",
                    help="PEM bundle to verify TLS servers")
    ap.add_argument("--tls-authority", default="",
                    help="expected TLS server name override")
    ap.add_argument("--wait-event", action="store_true",
                    help="after broadcast, wait on the first peer's "
                    "DeliverFiltered stream for the tx's validation "
                    "code (reference: peer chaincode invoke "
                    "--waitForEvent)")
    ap.add_argument("--wait-timeout", type=float, default=30.0)
    args = ap.parse_args(argv)

    root_pem = None
    if args.tls_ca:
        with open(args.tls_ca, "rb") as f:
            root_pem = f.read()
    signer = _load_identity(args.crypto, args.org, "users", args.user)
    cc_args = [a.encode() for a in args.args.split(",")]

    clients = [GRPCClient(addr, server_root_pem=root_pem,
                          override_authority=args.tls_authority or None)
               for addr in args.peers.split(",") if addr]
    endorsers = [RemoteEndorser(c) for c in clients]
    try:
        if args.verb == "query":
            payload = query_remote(args.channel, args.name, cc_args,
                                   signer, endorsers[0])
            sys.stdout.buffer.write(payload)
            sys.stdout.write("\n")
            return 0
        if not args.orderer:
            print("invoke needs --orderer", file=sys.stderr)
            return 2
        from fabric_mod_tpu.peer.grpcdeliver import GrpcBroadcaster
        oclient = GRPCClient(args.orderer, server_root_pem=root_pem,
                             override_authority=args.tls_authority
                             or None)
        bcast = GrpcBroadcaster(oclient)
        try:
            from fabric_mod_tpu.peer.deliverevents import (
                EventDeliverClient)
            wait_start = 0
            if args.wait_event:
                # pin the subscription numerically BEFORE broadcasting:
                # the tx can only commit at a block >= the peer's
                # current height, so a stream starting there can never
                # miss it, and the peer never re-serves old history
                import json
                info = json.loads(query_remote(
                    args.channel, "qscc", [b"GetChainInfo"], signer,
                    endorsers[0]))
                wait_start = int(info["height"])
            tx_id = invoke_remote(args.channel, args.name, cc_args,
                                  signer, endorsers, bcast)
            if args.wait_event:
                waiter = EventDeliverClient(clients[0], args.channel,
                                            signer)
                code = waiter.wait_for_tx(tx_id, start=wait_start,
                                          timeout_s=args.wait_timeout)
                print(f"{tx_id} {code}")
                return 0 if code == 0 else 3   # 0 == VALID
            print(tx_id)
            return 0
        finally:
            bcast.close()
            oclient.close()
    except Exception as e:
        # one-line operator error for the expected failure classes:
        # unreachable endpoints (grpc.RpcError), missing files
        # (OSError), rejected endorsements/broadcasts (RuntimeError)
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    finally:
        for c in clients:
            c.close()


if __name__ == "__main__":
    sys.exit(main())
