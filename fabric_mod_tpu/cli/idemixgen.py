"""idemixgen: issuer key + anonymous credential generation.

(reference: common/tools/idemixgen — ca-keygen writes the issuer key
pair, signerconfig issues a credential for one signer; artifacts are
the JSON wire forms the IdemixMsp consumes.)
"""
from __future__ import annotations

import argparse
import json
import os

from fabric_mod_tpu.idemix import credential as cred


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fabric-mod-tpu idemixgen")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("ca-keygen",
                       help="generate an issuer key pair")
    p.add_argument("--output", default="idemix-config")
    p.add_argument("--attrs", default="OU,Role,EnrollmentID,RevocationHandle",
                   help="comma-separated attribute names")

    p = sub.add_parser("signerconfig",
                       help="issue a credential for one signer")
    p.add_argument("--ca-input", default="idemix-config")
    p.add_argument("--output", default="idemix-config")
    p.add_argument("--org-unit", default="")
    p.add_argument("--enrollment-id", default="")
    p.add_argument("--role", type=int, default=0)

    args = ap.parse_args(argv)
    if args.cmd == "ca-keygen":
        names = [a for a in args.attrs.split(",") if a]
        ik = cred.IssuerKey(names)
        os.makedirs(args.output, exist_ok=True)
        with open(os.path.join(args.output, "IssuerKey.json"), "w") as f:
            json.dump(ik.to_dict(), f, indent=2, sort_keys=True)
        with open(os.path.join(args.output,
                               "IssuerPublicKey.json"), "w") as f:
            json.dump(ik.public_dict(), f, indent=2, sort_keys=True)
        print(f"issuer key written to {args.output}/")
        return 0
    if args.cmd == "signerconfig":
        with open(os.path.join(args.ca_input, "IssuerKey.json")) as f:
            ik = cred.IssuerKey.from_dict(json.load(f))
        sk = cred._rand_zr()
        attrs = []
        for name in ik.attr_names:
            if name == "OU":
                attrs.append(cred._hash_to_zr(args.org_unit.encode()))
            elif name == "Role":
                attrs.append(args.role)
            elif name == "EnrollmentID":
                attrs.append(cred._hash_to_zr(
                    args.enrollment_id.encode()))
            else:
                attrs.append(0)
        c = cred.issue(ik, sk, attrs)
        user_dir = os.path.join(args.output, "user")
        os.makedirs(user_dir, exist_ok=True)
        with open(os.path.join(user_dir, "SignerConfig.json"), "w") as f:
            json.dump({"sk": hex(sk), "credential": c.to_dict(),
                       "organizational_unit": args.org_unit,
                       "enrollment_id": args.enrollment_id,
                       "role": args.role},
                      f, indent=2, sort_keys=True)
        print(f"signer config written to {user_dir}/")
        return 0
    return 2
