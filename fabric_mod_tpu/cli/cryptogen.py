"""cryptogen: generate a test-network crypto tree from a config.

(reference: internal/cryptogen — ca.go + msp.go generating per-org CA
hierarchies and MSP directory layouts from crypto-config.yaml.)

Config (YAML):

    PeerOrgs:
      - Name: Org1
        PeerCount: 2
        UserCount: 1
    OrdererOrgs:
      - Name: OrdererOrg
        OrdererCount: 1

Output layout per org under <out>/<org>/:
    ca/ca.pem ca.key
    peers/peer<N>.pem .key   (OU=peer)
    orderers/orderer<N>.pem .key (OU=orderer)
    users/user<N>.pem .key   (OU=client)
    admin/admin.pem .key     (OU=admin)
"""
from __future__ import annotations

import os
from typing import Dict

import yaml

from fabric_mod_tpu.msp import ca as calib


def _write(path: str, data: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def _gen_org(out: str, name: str, node_kind: str, node_count: int,
             user_count: int) -> calib.CA:
    ca = calib.CA(f"ca.{name.lower()}", name)
    base = os.path.join(out, name)
    _write(os.path.join(base, "ca", "ca.pem"), calib.cert_pem(ca.cert))
    _write(os.path.join(base, "ca", "ca.key"), calib.key_pem(ca.key))
    for i in range(node_count):
        cn = f"{node_kind}{i}.{name.lower()}"
        cert, key = ca.issue(cn, name, ous=[node_kind])
        _write(os.path.join(base, f"{node_kind}s", f"{node_kind}{i}.pem"),
               calib.cert_pem(cert))
        _write(os.path.join(base, f"{node_kind}s", f"{node_kind}{i}.key"),
               calib.key_pem(key))
    for i in range(user_count):
        cn = f"user{i}@{name.lower()}"
        cert, key = ca.issue(cn, name, ous=["client"])
        _write(os.path.join(base, "users", f"user{i}.pem"),
               calib.cert_pem(cert))
        _write(os.path.join(base, "users", f"user{i}.key"),
               calib.key_pem(key))
    cert, key = ca.issue(f"admin@{name.lower()}", name, ous=["admin"])
    _write(os.path.join(base, "admin", "admin.pem"), calib.cert_pem(cert))
    _write(os.path.join(base, "admin", "admin.key"), calib.key_pem(key))
    return ca


def generate(config_path: str, out_dir: str) -> Dict[str, list]:
    with open(config_path) as f:
        conf = yaml.safe_load(f) or {}
    generated = {"peer_orgs": [], "orderer_orgs": []}
    for org in conf.get("PeerOrgs", []) or []:
        _gen_org(out_dir, org["Name"], "peer",
                 int(org.get("PeerCount", 1)),
                 int(org.get("UserCount", 1)))
        generated["peer_orgs"].append(org["Name"])
    for org in conf.get("OrdererOrgs", []) or []:
        _gen_org(out_dir, org["Name"], "orderer",
                 int(org.get("OrdererCount", 1)), 0)
        generated["orderer_orgs"].append(org["Name"])
    return generated


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="cryptogen")
    ap.add_argument("--config", required=True)
    ap.add_argument("--output", default="crypto-config")
    args = ap.parse_args(argv)
    got = generate(args.config, args.output)
    print(f"generated {got['peer_orgs']} + {got['orderer_orgs']} "
          f"under {args.output}")
    return 0
