"""Device-mesh parallelism utilities (dp sharding of crypto batches).

See mesh.py for the design rationale; SURVEY.md §2.9 maps the
reference's goroutine-per-tx fan-out to the batch axis sharded here.
"""
from fabric_mod_tpu.parallel.mesh import (  # noqa: F401
    data_mesh, fused_verify_shardings, replicated, slice_meshes,
    verify_shardings)
