"""Device-mesh data parallelism for the batch crypto path.

The reference's only intra-node parallel axis on the commit path is
"one goroutine per transaction behind a semaphore" (reference:
core/committer/txvalidator/v20/validator.go:194-239 and the pool knob
at core/peer/config.go:255-258).  The TPU-native equivalent (SURVEY.md
§2.9 row 1) is the batch dimension of the verify kernel, sharded over
a 1-D `dp` device mesh: inputs are placed with a `NamedSharding` whose
leading (batch) axis is split across chips, and XLA/GSPMD partitions
the already-jitted verify program — no per-device code, no collectives
beyond the final verdict gather, because signature verification is
embarrassingly parallel across items (SURVEY.md §5.7: batch is the
only parallel axis; nothing rides ICI except the result).

Multi-host later: the same mesh spec over jax.distributed processes;
the sharding annotations do not change.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def data_mesh(n_devices: Optional[int] = None):
    """A 1-D ``("dp",)`` mesh over the first `n_devices` devices."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), ("dp",))


def batch_sharding(mesh):
    """NamedSharding splitting the leading (batch) axis across `dp`.

    Applies to every per-item array of the verify step: (batch, K)
    limb arrays and (batch,) flag vectors alike — PartitionSpec("dp")
    constrains only the leading axis, trailing axes stay replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P("dp"))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())
