"""Device-mesh data parallelism for the batch crypto path.

The reference's only intra-node parallel axis on the commit path is
"one goroutine per transaction behind a semaphore" (reference:
core/committer/txvalidator/v20/validator.go:194-239 and the pool knob
at core/peer/config.go:255-258).  The TPU-native equivalent (SURVEY.md
§2.9 row 1) is the batch dimension of the verify kernel, sharded over
a 1-D `dp` device mesh: inputs are placed with `NamedSharding`s that
split the batch axis across chips — the TRAILING axis of the (K, batch)
limb arrays, the leading (only) axis of per-item flag vectors — and
XLA/GSPMD partitions the already-jitted verify program: no per-device
code, no collectives beyond the final verdict gather, because signature
verification is embarrassingly parallel across items (SURVEY.md §5.7:
batch is the only parallel axis; nothing rides ICI except the result).

Multi-host later: the same mesh spec over jax.distributed processes;
the sharding annotations do not change (the concrete process-group
spec lives in sharding/multihost.py, stubbed behind
FABRIC_MOD_TPU_SHARDS).

A THIRD axis landed with the sharding subsystem (sharding/):
horizontal CHANNEL placement.  `data_mesh` accepts an explicit device
subset and `slice_meshes` carves the device set into disjoint
equal-size slices — one per channel shard — so K chips x N channels
run N independent verify/policy programs side by side instead of one
channel's program owning every chip.  Slices never share devices;
each slice's programs keep the exact NamedShardings above, just over
fewer devices.

A SECOND, host-side parallel axis composes with the mesh since the
commit pipeline landed (peer/commitpipe.py): with pipeline depth >= 2,
block N's verify batch is in flight on the mesh while block N+1's host
staging marshals the next batch — so the dp axis sees back-to-back
dispatches instead of host-gap bubbles.  Nothing here changes for
that: both in-flight batches carry the same NamedShardings; the
overlap is purely dispatch-order (XLA queues per-device programs
FIFO), which is why the pipeline needs no device-side coordination.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def data_mesh(n_devices: Optional[int] = None, devices=None):
    """A 1-D ``("dp",)`` mesh over the first `n_devices` devices, or —
    for SLICE meshes — over an explicit `devices` subset (any iterable
    of jax devices; order is the dp order).  The two selectors are
    mutually exclusive."""
    import jax
    from jax.sharding import Mesh

    if devices is not None:
        if n_devices is not None:
            raise ValueError("pass n_devices OR devices, not both")
        devs = list(devices)
        if not devs:
            raise ValueError("empty device subset")
        if len(set(devs)) != len(devs):
            raise ValueError("duplicate devices in subset")
        return Mesh(np.array(devs), ("dp",))
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), ("dp",))


def slice_meshes(n_slices: int, n_devices: Optional[int] = None):
    """Carve the first `n_devices` devices (default: all) into
    `n_slices` DISJOINT contiguous equal-size ``("dp",)`` meshes — the
    placement primitive of the channel-sharding subsystem
    (sharding/shardmap.py): each channel shard owns one slice, so N
    channels' verify/policy programs run side by side without sharing
    a chip.  Contiguous split on purpose: adjacent device ids sit on
    the same ICI neighborhood, so a slice's final verdict gather never
    crosses another slice's links.

    The device count must divide evenly — a ragged split would give
    slices different bucket divisibility (bccsp.tpu._bucket pads the
    batch axis to a multiple of the mesh size) and two channels'
    otherwise-identical programs would stop being shape-identical.
    """
    import jax

    if n_slices <= 0:
        raise ValueError("n_slices must be positive")
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    if n % n_slices != 0:
        raise ValueError(
            f"{n} devices do not split into {n_slices} equal slices")
    per = n // n_slices
    return [data_mesh(devices=devs[i * per:(i + 1) * per])
            for i in range(n_slices)]


def verify_shardings(mesh):
    """(limb_sharding, flag_sharding) for the verify step's arrays.

    Limb arrays are (K, batch) — the batch is the TRAILING axis
    (ops/limbs9.py layout), so the limb axis stays replicated and only
    the batch splits across `dp`; flag vectors are (batch,).  Sharding
    the limb axis instead would break carries and matmuls into
    cross-chip traffic — always place limb arrays with the first
    element of this pair.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(None, "dp")), NamedSharding(mesh, P("dp"))


def fused_verify_shardings(mesh):
    """(words_sharding, flag_sharding) for the fused hash->verify
    message operands (ops/p256.batch_verify_raw).

    Message words are (batch, max_blocks, 16) uint32 — unlike the
    limb arrays, the batch is the LEADING axis (ops/sha256.py layout:
    lax.scan walks the block axis, the compression state is
    (batch, 8)), so the dp split goes on axis 0 and the block/word
    axes stay whole.  nblocks/has_msg are (batch,) flags."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return (NamedSharding(mesh, P("dp", None, None)),
            NamedSharding(mesh, P("dp")))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())
