"""Device-mesh data parallelism for the batch crypto path.

The reference's only intra-node parallel axis on the commit path is
"one goroutine per transaction behind a semaphore" (reference:
core/committer/txvalidator/v20/validator.go:194-239 and the pool knob
at core/peer/config.go:255-258).  The TPU-native equivalent (SURVEY.md
§2.9 row 1) is the batch dimension of the verify kernel, sharded over
a 1-D `dp` device mesh: inputs are placed with `NamedSharding`s that
split the batch axis across chips — the TRAILING axis of the (K, batch)
limb arrays, the leading (only) axis of per-item flag vectors — and
XLA/GSPMD partitions the already-jitted verify program: no per-device
code, no collectives beyond the final verdict gather, because signature
verification is embarrassingly parallel across items (SURVEY.md §5.7:
batch is the only parallel axis; nothing rides ICI except the result).

Multi-host later: the same mesh spec over jax.distributed processes;
the sharding annotations do not change.

A SECOND, host-side parallel axis composes with the mesh since the
commit pipeline landed (peer/commitpipe.py): with pipeline depth >= 2,
block N's verify batch is in flight on the mesh while block N+1's host
staging marshals the next batch — so the dp axis sees back-to-back
dispatches instead of host-gap bubbles.  Nothing here changes for
that: both in-flight batches carry the same NamedShardings; the
overlap is purely dispatch-order (XLA queues per-device programs
FIFO), which is why the pipeline needs no device-side coordination.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def data_mesh(n_devices: Optional[int] = None):
    """A 1-D ``("dp",)`` mesh over the first `n_devices` devices."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), ("dp",))


def verify_shardings(mesh):
    """(limb_sharding, flag_sharding) for the verify step's arrays.

    Limb arrays are (K, batch) — the batch is the TRAILING axis
    (ops/limbs9.py layout), so the limb axis stays replicated and only
    the batch splits across `dp`; flag vectors are (batch,).  Sharding
    the limb axis instead would break carries and matmuls into
    cross-chip traffic — always place limb arrays with the first
    element of this pair.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(None, "dp")), NamedSharding(mesh, P("dp"))


def fused_verify_shardings(mesh):
    """(words_sharding, flag_sharding) for the fused hash->verify
    message operands (ops/p256.batch_verify_raw).

    Message words are (batch, max_blocks, 16) uint32 — unlike the
    limb arrays, the batch is the LEADING axis (ops/sha256.py layout:
    lax.scan walks the block axis, the compression state is
    (batch, 8)), so the dp split goes on axis 0 and the block/word
    axes stay whole.  nblocks/has_msg are (batch,) flags."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return (NamedSharding(mesh, P("dp", None, None)),
            NamedSharding(mesh, P("dp")))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())
