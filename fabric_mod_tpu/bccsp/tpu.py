"""TPU batch crypto provider — the framework's north star.

The device-offload CSP the reference only gestures at with its PKCS#11
HSM binding (reference: bccsp/pkcs11/pkcs11.go:241 Verify — the
in-repo template for "send crypto to a device"): ECDSA-P256 verifies
are staged into fixed-size buckets, verified in one jitted program on
the TPU (ops/p256.py), and results are returned as futures so the
caller-facing API stays BCCSP-shaped.

Design notes (SURVEY.md §2.9, §7):
* The batch axis replaces the reference's goroutine-per-tx fan-out
  (core/committer/txvalidator/v20/validator.go:194-239).
* Buckets are padded to a small set of static sizes so XLA compiles a
  handful of programs, ever; a persistent compilation cache makes them
  survive process restarts.
* Latency-sensitive small batches are handled by a deadline-based
  flusher (default 2 ms), the device answer for the reference's
  assumption that a verify dispatch costs ~µs.
* Signing, key management and single hashes stay host-side (private
  keys never benefit from batch; reference keeps HSM signing
  device-side only because the key lives there).
"""
from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from fabric_mod_tpu.bccsp.api import BCCSP, Key, VerifyItem
from fabric_mod_tpu.bccsp import sw as _sw

# Persistent XLA compilation cache: the ECDSA ladder costs tens of
# seconds to compile; cache it across processes.
def _enable_compile_cache() -> None:
    try:
        import jax
        cache_dir = os.environ.get(
            "FABRIC_MOD_TPU_JIT_CACHE",
            os.path.expanduser("~/.cache/fabric_mod_tpu/jit"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


_enable_compile_cache()

BUCKETS = (8, 64, 512, 2048)

# Low-S bound over the curve order defined alongside the device kernel,
# so the rule can't desynchronize from the math layer.
from fabric_mod_tpu.ops.p256 import N as _P256_N  # noqa: E402

_LOW_S_MAX = _P256_N // 2


def _bucket(n: int, min_div: int = 1) -> int:
    """Smallest static bucket holding n that `min_div` divides (the
    mesh size must divide the sharded batch axis evenly); n must be
    <= max bucket (larger batches are chunked by the caller so the
    set of compiled program shapes stays fixed)."""
    for b in BUCKETS:
        if n <= b and b % min_div == 0:
            return b
    raise ValueError(
        f"no bucket >= {n} divisible by {min_div} (max {BUCKETS[-1]})")


class TpuVerifier:
    """Marshals VerifyItems to the device batch verifier.

    Separated from the CSP so the commit pipeline (and tests, via a
    fake with the same shape) can depend on just this seam — the
    equivalent of the reference's narrow per-consumer interfaces
    (SURVEY.md §4).

    Pass a `mesh` (parallel.data_mesh) to shard each bucket's batch
    axis across chips; bucket selection then skips buckets the mesh
    size does not divide, so the partition is always even.  The mesh
    size must divide the largest bucket (i.e. be a power of two
    <= 2048) — checked at construction.
    """

    def __init__(self, mesh=None):
        self._mesh = mesh
        self._mesh_size = 1
        if mesh is not None:
            self._mesh_size = int(np.prod(mesh.devices.shape))
            if BUCKETS[-1] % self._mesh_size != 0:
                raise ValueError(
                    f"mesh size {self._mesh_size} must divide the max "
                    f"bucket {BUCKETS[-1]} (use a power-of-two mesh)")

    def verify_many(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return self.verify_many_async(items)()

    def verify_many_async(self, items: Sequence[VerifyItem]):
        """Marshal + DISPATCH the device batch, returning a zero-arg
        resolver for the verdicts.  Between dispatch and resolution the
        device executes while the caller does host work for the next
        block — the commit pipeline's double buffer (SURVEY §2.9
        row 2; reference analog: the payload buffer decoupling pull
        from commit at gossip/state/state.go:583)."""
        n = len(items)
        if n == 0:
            return lambda: np.zeros(0, bool)
        if n > BUCKETS[-1]:
            # chunk through the fixed buckets — never mint new shapes
            parts = [self.verify_many_async(items[i:i + BUCKETS[-1]])
                     for i in range(0, n, BUCKETS[-1])]
            return lambda: np.concatenate([p() for p in parts])
        size = _bucket(n, self._mesh_size)
        d = np.zeros((size, 32), np.uint8)
        r = np.zeros((size, 32), np.uint8)
        s = np.zeros((size, 32), np.uint8)
        qx = np.zeros((size, 32), np.uint8)
        qy = np.zeros((size, 32), np.uint8)
        pre_ok = np.zeros(size, bool)
        for i, it in enumerate(items):
            try:
                ri, si = _sw.decode_dss_signature(it.signature)
                if not (len(it.digest) == 32 and len(it.public_xy) == 64):
                    continue
                if si > _LOW_S_MAX:                  # low-S rule
                    continue
                r[i] = np.frombuffer(ri.to_bytes(32, "big"), np.uint8)
                s[i] = np.frombuffer(si.to_bytes(32, "big"), np.uint8)
                d[i] = np.frombuffer(it.digest, np.uint8)
                qx[i] = np.frombuffer(it.public_xy[:32], np.uint8)
                qy[i] = np.frombuffer(it.public_xy[32:], np.uint8)
                pre_ok[i] = True
            except Exception:
                continue
        from fabric_mod_tpu.ops import p256
        resolve = p256.batch_verify(d, r, s, qx, qy, mesh=self._mesh,
                                    lazy=True)
        return lambda: (resolve() & pre_ok)[:n]


class FakeBatchVerifier:
    """Deterministic CPU stand-in with the TpuVerifier seam (for tests
    and TPU-less deployments — the reference's fake-at-the-interface
    testing pattern, SURVEY.md §4)."""

    def __init__(self, csp: Optional[BCCSP] = None):
        self._csp = csp or _sw.SwCSP()

    def verify_many(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return np.asarray(self._csp.verify_batch(items), bool)

    def verify_many_async(self, items: Sequence[VerifyItem]):
        """Deferred-to-resolution stand-in for the device's async
        dispatch: the sw verify runs when the resolver is called (in
        the commit stage), preserving the pipeline's thread layout."""
        return lambda: self.verify_many(items)


class BatchingVerifyService:
    """Deadline/size-batched async verify front-end.

    Single background worker drains a queue; a flush happens when
    `max_batch` items are pending or the oldest item is `deadline_s`
    old.  Callers get Futures.  This is the latency/throughput
    trade-off knob (SURVEY.md §7 hard part #3).
    """

    def __init__(self, verifier=None, max_batch: int = 2048,
                 deadline_s: float = 0.002):
        self._verifier = verifier or TpuVerifier()
        self.max_batch = max_batch
        self.deadline_s = deadline_s
        self._q: "queue.Queue[tuple[VerifyItem, Future]]" = queue.Queue()
        self._stop = threading.Event()
        self._lifecycle = threading.Lock()   # serializes submit vs close
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, item: VerifyItem) -> Future:
        fut: Future = Future()
        # Under the lock, either close() has not started (the item lands
        # before close()'s straggler drain) or it has finished setting
        # _stop (we reject here) — no orphaned Futures either way.
        with self._lifecycle:
            if self._stop.is_set():
                fut.set_exception(RuntimeError("verify service is closed"))
                return fut
            self._q.put((item, fut))
        return fut

    def verify_many(self, items: Sequence[VerifyItem],
                    timeout: Optional[float] = 30):
        """The policy-engine seam (same shape as TpuVerifier): submit
        each item and gather verdicts.  Concurrent callers' items
        coalesce into shared device batches — this is how ingress
        paths (broadcast filters, gossip-storm verifies) ride ONE
        deadline-batched dispatch across many independent requests
        (SURVEY §2.9 'admission control feeding fixed-size batches').
        `timeout` bounds the WHOLE call, not each item."""
        futs = [self.submit(it) for it in items]
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        out = []
        for f in futs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            out.append(f.result(remaining))
        return out

    def verify(self, item: VerifyItem, timeout: Optional[float] = 30) -> bool:
        return self.submit(item).result(timeout)

    def close(self) -> None:
        """Stop the worker, draining: everything already submitted still
        gets a verdict (callers may be blocked on their Futures)."""
        with self._lifecycle:
            self._stop.set()
        self._worker.join(timeout=30)
        # A submit may have raced the worker's final drain; fail any
        # stragglers rather than leaving callers hung.
        while True:
            try:
                _, fut = self._q.get_nowait()
            except queue.Empty:
                break
            fut.set_exception(RuntimeError("verify service is closed"))

    def _flush(self, batch) -> None:
        try:
            mask = self._verifier.verify_many([b[0] for b in batch])
            for (_, fut), ok in zip(batch, mask):
                fut.set_result(bool(ok))
        except Exception as e:               # pragma: no cover
            for _, fut in batch:
                fut.set_exception(e)

    def _run(self) -> None:
        pending: list[tuple[VerifyItem, Future]] = []
        first_ts = 0.0
        while not self._stop.is_set():
            timeout = None
            if pending:
                timeout = max(0.0, first_ts + self.deadline_s - time.time())
            try:
                item = self._q.get(timeout=timeout if pending else 0.05)
                if not pending:
                    first_ts = time.time()
                pending.append(item)
            except queue.Empty:
                pass
            if pending and (len(pending) >= self.max_batch
                            or time.time() - first_ts >= self.deadline_s):
                batch, pending = pending, []
                self._flush(batch)
        # Drain on close: anything submitted before close() still gets
        # a verdict rather than leaving callers hung on their Futures.
        while True:
            try:
                pending.append(self._q.get_nowait())
            except queue.Empty:
                break
        if pending:
            self._flush(pending)


class TpuCSP(BCCSP):
    """BCCSP whose Verify path runs on the TPU.

    Key management, hashing of single messages, signing, and symmetric
    crypto delegate to the software provider; `verify`/`verify_batch`
    go to the device.  `hash_many` exposes the device SHA-256 batch
    for pipelines that hash entire blocks.
    """

    def __init__(self, keystore_path: Optional[str] = None,
                 verifier=None, service: Optional[BatchingVerifyService] = None):
        self._sw = _sw.SwCSP(keystore_path)
        self._verifier = verifier or TpuVerifier()
        self._service = service

    # -- delegated host-side ops --
    def key_gen(self, algorithm: str = "P256", ephemeral: bool = True) -> Key:
        return self._sw.key_gen(algorithm, ephemeral)

    def key_import(self, raw: bytes, kind: str) -> Key:
        return self._sw.key_import(raw, kind)

    def get_key(self, ski: bytes) -> Optional[Key]:
        return self._sw.get_key(ski)

    def hash(self, msg: bytes, algorithm: str = "SHA256") -> bytes:
        return self._sw.hash(msg, algorithm)

    def hash_many(self, msgs: Sequence[bytes]) -> np.ndarray:
        from fabric_mod_tpu.ops import sha256
        return sha256.sha256_many(list(msgs))

    def sign(self, key: Key, digest: bytes) -> bytes:
        return self._sw.sign(key, digest)

    def encrypt(self, key: Key, plaintext: bytes) -> bytes:
        return self._sw.encrypt(key, plaintext)

    def decrypt(self, key: Key, ciphertext: bytes) -> bytes:
        return self._sw.decrypt(key, ciphertext)

    # -- device verify path --
    def verify(self, key: _sw.EcdsaKey, signature: bytes, digest: bytes) -> bool:
        if key.curve != "P256":
            return self._sw.verify(key, signature, digest)
        item = VerifyItem(digest, signature, key.public_xy())
        if self._service is not None:
            return self._service.verify(item)
        return bool(self._verifier.verify_many([item])[0])

    def verify_batch(self, items: Sequence[VerifyItem]) -> "list[bool]":
        return [bool(v) for v in self._verifier.verify_many(items)]
