"""TPU batch crypto provider — the framework's north star.

The device-offload CSP the reference only gestures at with its PKCS#11
HSM binding (reference: bccsp/pkcs11/pkcs11.go:241 Verify — the
in-repo template for "send crypto to a device"): ECDSA-P256 verifies
are staged into fixed-size buckets, verified in one jitted program on
the TPU (ops/p256.py), and results are returned as futures so the
caller-facing API stays BCCSP-shaped.

Design notes (SURVEY.md §2.9, §7):
* The batch axis replaces the reference's goroutine-per-tx fan-out
  (core/committer/txvalidator/v20/validator.go:194-239).
* Buckets are padded to a small set of static sizes so XLA compiles a
  handful of programs, ever; a persistent compilation cache makes them
  survive process restarts.
* Latency-sensitive small batches are handled by a deadline-based
  flusher (default 2 ms), the device answer for the reference's
  assumption that a verify dispatch costs ~µs.
* Signing, key management and single hashes stay host-side (private
  keys never benefit from batch; reference keeps HSM signing
  device-side only because the key lives there).

The PIPELINED front-end (this layer's whole job is keeping the device
fed):

* **Vectorized marshalling** — DER decode + byte staging for a whole
  bucket is numpy array arithmetic (bccsp/der.py), not a 2048-pass
  python loop; see `marshal_items`.
* **Verdict memo-cache** — an LRU keyed by (digest, signature, public
  key) consulted BEFORE bucketing (`VerdictCache`); gossip
  redelivery, retried blocks, and the endorsement/commit
  dual-validation both repeat identical verifies, and a hit skips the
  device entirely (the role of the reference's msp cache layer,
  msp/cache).  Identical items within one call dedup to one device
  lane for the same reason.
* **In-flight dispatch window** — `BatchingVerifyService` dispatches
  buckets via `verify_many_async` into a bounded in-flight queue
  (default depth 2, FABRIC_MOD_TPU_INFLIGHT) and a resolver thread
  completes Futures in dispatch order, so bucket k+1 marshals on the
  worker thread while bucket k executes on the device.
"""
from __future__ import annotations

import collections
import operator
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeout
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fabric_mod_tpu import faults
from fabric_mod_tpu.bccsp.api import BCCSP, Key, VerifyItem
from fabric_mod_tpu.bccsp.breaker import CircuitBreaker
from fabric_mod_tpu.bccsp import der as _der
from fabric_mod_tpu.bccsp import sw as _sw
from fabric_mod_tpu.concurrency import (GuardedQueue, RegisteredLock,
                                        RegisteredThread, assert_joined)
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.utils import knobs as _knobs

# Persistent XLA compilation cache: the ECDSA ladder costs tens of
# seconds to compile; cache it across processes.  (Shared helper —
# ops/fp256bn_dev.py puts the idemix pairing program on the same
# cache at its import.)
from fabric_mod_tpu.ops.compilecache import (  # noqa: E402
    enable_compile_cache as _enable_compile_cache)

_enable_compile_cache()

BUCKETS = (8, 64, 512, 2048)

# Low-S bound over the curve order defined alongside the device kernel,
# so the rule can't desynchronize from the math layer.
from fabric_mod_tpu.ops.p256 import N as _P256_N  # noqa: E402

_LOW_S_MAX = _P256_N // 2
# s is acceptable iff s < _LOW_S_MAX + 1, as a big-endian byte bound
# for the batched lexicographic compare.
_LOW_S_BOUND = (_LOW_S_MAX + 1).to_bytes(32, "big")


def _bucket(n: int, min_div: int = 1) -> int:
    """Smallest static bucket holding n that `min_div` divides (the
    mesh size must divide the sharded batch axis evenly); n must be
    <= max bucket (larger batches are chunked by the caller so the
    set of compiled program shapes stays fixed)."""
    for b in BUCKETS:
        if n <= b and b % min_div == 0:
            return b
    raise ValueError(
        f"no bucket >= {n} divisible by {min_div} (max {BUCKETS[-1]})")


def marshal_items(items: Sequence[VerifyItem], size: Optional[int] = None
                  ) -> Tuple[np.ndarray, ...]:
    """Whole-batch host marshalling: VerifyItems -> device byte planes.

    The vectorized replacement for the old per-item python loop
    (per-item DER decode, int.to_bytes, np.frombuffer): one batched
    DER parse, one packed copy per fixed-width field, and ONE low-S /
    length range check across the whole batch.  Returns
    (d, r, s, qx, qy, pre_ok, msg) — five (size, 32) uint8 planes
    padded to the bucket `size`, the (size,) host-side validity mask
    (False rows never contribute a True verdict, whatever the device
    says), and the fused-hash MESSAGE lane: None when no item carries
    a raw message, else (words, nblocks, has_msg) from the vectorized
    padder (der.pack_messages) — raw rows get their digest computed
    ON DEVICE (p256.batch_verify_raw), pre-digested rows keep the
    digest plane, one program either way.

    Fresh output arrays each call on purpose: jax's host->device
    transfer of a dispatched-but-unresolved batch may still be reading
    the source buffers, so reusing one staging buffer under the
    in-flight window would be a use-after-write hazard.
    """
    n = len(items)
    size = n if size is None else size
    msgs = [getattr(it, "message", None) for it in items]
    any_raw = any(m is not None for m in msgs)
    d, d_ok = _der.pack_fixed(
        list(map(operator.attrgetter("digest"), items)), 32, size)
    pub, pub_ok = _der.pack_fixed(
        list(map(operator.attrgetter("public_xy"), items)), 64, size)
    r, s, der_ok = _der.decode_der_batch(
        list(map(operator.attrgetter("signature"), items)), size)
    low_s = _der.lt_bytes(s, _LOW_S_BOUND)           # the low-S rule
    msg = None
    if any_raw:
        words, nblocks, msg_ok = _der.pack_messages(
            [m if m is not None else b"" for m in msgs], size,
            round_blocks_pow2=True)
        has_msg = np.zeros(size, bool)
        has_msg[:n] = [m is not None for m in msgs]
        # raw rows validate on the message, not the (empty) digest;
        # a raw item whose message is not bytes stays invalid rather
        # than silently falling back to a digest it did not carry
        d_ok = np.where(has_msg, msg_ok, d_ok)
        nblocks = np.where(has_msg, nblocks, 0).astype(np.int32)
        msg = (words, nblocks, has_msg)
    pre_ok = d_ok & pub_ok & der_ok & low_s
    qx = np.ascontiguousarray(pub[:, :32])
    qy = np.ascontiguousarray(pub[:, 32:])
    return d, r, s, qx, qy, pre_ok, msg


# ---------------------------------------------------------------------------
# Verdict memo-cache
# ---------------------------------------------------------------------------

_CACHE_HITS_OPTS = MetricOpts(
    "fabric", "bccsp", "verdict_cache_hits",
    help="Verify verdicts served from the memo-cache (device skipped).")
_CACHE_MISSES_OPTS = MetricOpts(
    "fabric", "bccsp", "verdict_cache_misses",
    help="Verify items that had to be dispatched to the device.")
_CACHE_EVICTIONS_OPTS = MetricOpts(
    "fabric", "bccsp", "verdict_cache_evictions",
    help="LRU evictions from the verdict memo-cache.")
_CACHE_SIZE_OPTS = MetricOpts(
    "fabric", "bccsp", "verdict_cache_size",
    help="Current number of memoized verify verdicts.")


class VerdictCache:
    """Bounded LRU of (digest, signature, public key) -> bool verdict.

    A verify is a pure function of that triple, so the verdict is
    memoizable forever; the LRU bound only caps memory.  Gossip
    redelivery, retried blocks, and the endorsement-then-commit
    dual validation (peer/txvalidator.py) all re-verify identical
    items — a hit skips DER decode, bucketing, and the device program
    entirely (the role the msp cache layer plays in the reference).

    Thread-safe; instrumented through observability/metrics.py via
    get-or-create so every instance shares one exposition row set.
    """

    def __init__(self, capacity: int, provider=None):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._od: "collections.OrderedDict[tuple, bool]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()  # fmtlint: allow[locks] -- leaf lock on the per-verify memo-cache path, never nested; C-level speed matters
        prov = provider or default_provider()
        self._hits = prov.counter(_CACHE_HITS_OPTS)
        self._misses = prov.counter(_CACHE_MISSES_OPTS)
        self._evictions = prov.counter(_CACHE_EVICTIONS_OPTS)
        self._size = prov.gauge(_CACHE_SIZE_OPTS)

    @staticmethod
    def key_of(item: VerifyItem) -> Optional[tuple]:
        """Hashable memo key, or None for items with non-bytes fields
        (bytearray coerces; anything else is uncacheable and must not
        raise — one weird item may never poison a coalesced batch).
        Raw-message items key on the message too: (digest, sig, key,
        message) is the full pure-function input of the fused path."""
        key = []
        for x in (item.digest, item.signature, item.public_xy):
            if type(x) is not bytes:
                if not isinstance(x, (bytes, bytearray, memoryview)):
                    return None
                x = bytes(x)
            key.append(x)
        msg = getattr(item, "message", None)
        if msg is not None and type(msg) is not bytes:
            if not isinstance(msg, (bytes, bytearray, memoryview)):
                return None
            msg = bytes(msg)
        key.append(msg)
        return tuple(key)

    def get_many(self, keys: Sequence[Optional[tuple]]
                 ) -> List[Optional[bool]]:
        """Probe many keys under one lock pass; hits refresh recency.
        None keys (uncacheable items) always miss."""
        out: List[Optional[bool]] = []
        hits = 0
        with self._lock:
            od = self._od
            for k in keys:
                got = od.get(k) if k is not None else None
                if got is not None:
                    od.move_to_end(k)
                    hits += 1
                out.append(got)
        self._hits.add(hits)
        self._misses.add(len(keys) - hits)
        return out

    def put_many(self, keys: Sequence[Optional[tuple]], verdicts) -> None:
        evicted = 0
        with self._lock:
            od = self._od
            before = len(od)
            for k, v in zip(keys, verdicts):
                if k is None:
                    continue
                od[k] = bool(v)
                od.move_to_end(k)
            while len(od) > self.capacity:
                od.popitem(last=False)
                evicted += 1
            delta = len(od) - before
        self._evictions.add(evicted)
        # delta, not set(): the exposition row is get-or-create-shared
        # across caches, so it reports the process-wide total of
        # memoized verdicts rather than last-writer-wins of one cache
        self._size.add(delta)

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)


def _cache_from_env() -> Optional[VerdictCache]:
    cap = _knobs.get_int("FABRIC_MOD_TPU_VERDICT_CACHE")
    return VerdictCache(cap) if cap > 0 else None


# ---------------------------------------------------------------------------
# The device verifier
# ---------------------------------------------------------------------------

_DEVICE_ERRORS_OPTS = MetricOpts(
    "fabric", "bccsp", "device_errors_total",
    help="Device/XLA runtime errors on the verify path (each failed "
         "over per-batch to the sw verifier).")
_FALLBACK_OPTS = MetricOpts(
    "fabric", "bccsp", "sw_fallback_batches_total",
    help="Verify batches answered by the sw fallback instead of the "
         "device (device error, or circuit open).")


def is_device_error(e: BaseException) -> bool:
    """Is `e` a device/XLA-runtime failure (vs. a host-side bug)?
    Device failures are operational — the sw verifier computes the
    identical verdict function, so they degrade instead of failing.
    Host exceptions (marshalling bugs, bad types, and jax's own
    TRACING errors like ConcretizationTypeError — those are program
    bugs, not outages) must keep raising: masking them behind the
    fallback would hide real defects, so only the RUNTIME error
    classes the XLA client raises for device/executor failures
    qualify."""
    if isinstance(e, faults.InjectedFault):
        return e.kind == "device"
    name = type(e).__name__
    return "XlaRuntimeError" in name or "JaxRuntimeError" in name


class TpuVerifier:
    """Marshals VerifyItems to the device batch verifier.

    Separated from the CSP so the commit pipeline (and tests, via a
    fake with the same shape) can depend on just this seam — the
    equivalent of the reference's narrow per-consumer interfaces
    (SURVEY.md §4).

    Pass a `mesh` (parallel.data_mesh) to shard each bucket's batch
    axis across chips; bucket selection then skips buckets the mesh
    size does not divide, so the partition is always even.  The mesh
    size must divide the largest bucket (i.e. be a power of two
    <= 2048) — checked at construction.

    `cache_size` bounds the verdict memo-cache (default from
    FABRIC_MOD_TPU_VERDICT_CACHE, 8192; 0 disables); pass a
    `VerdictCache` to share one across verifiers.  Identical items in
    one call always dedup to a single device lane, cache or not.
    """

    def __init__(self, mesh=None, cache: Optional[VerdictCache] = None,
                 cache_size: Optional[int] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 fallback=None):
        """`breaker`: circuit breaker guarding the device (None builds
        one from the FABRIC_MOD_TPU_BREAKER_K / _BREAKER_PROBE_S
        knobs; K=0 still fails over per-batch but never opens).
        `fallback(items) -> bool mask`: the degraded verifier — default
        is the sw provider's verify_batch, which enforces the same
        low-S/encoding rules as the device marshaller, so fallback
        verdicts are bit-identical to device verdicts."""
        self._mesh = mesh
        self._mesh_size = 1
        if mesh is not None:
            self._mesh_size = int(np.prod(mesh.devices.shape))
            if BUCKETS[-1] % self._mesh_size != 0:
                raise ValueError(
                    f"mesh size {self._mesh_size} must divide the max "
                    f"bucket {BUCKETS[-1]} (use a power-of-two mesh)")
        if cache is not None:
            self._cache = cache
        elif cache_size is not None:
            self._cache = (VerdictCache(cache_size) if cache_size > 0
                           else None)
        else:
            self._cache = _cache_from_env()
        self._fallback = fallback
        self._fallback_csp: Optional[_sw.SwCSP] = None
        self.breaker = breaker if breaker is not None else \
            CircuitBreaker(probe=self._probe_device)
        prov = default_provider()
        self._m_device_errors = prov.counter(_DEVICE_ERRORS_OPTS)
        self._m_fallback = prov.counter(_FALLBACK_OPTS)

    def close(self) -> None:
        """Tear down the breaker's background prober (if the circuit
        ever opened).  Verifiers are otherwise stateless; this exists
        so owners (BatchingVerifyService, tests) can guarantee no
        probe thread outlives the device it probes."""
        self.breaker.stop()

    def verify_many(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return self.verify_many_async(items)()

    def verify_many_fused_async(self, items: Sequence[VerifyItem]):
        """The tensor-policy FUSION seam: identical pipeline to
        `verify_many_async`, but the resolver hands back the verdict
        mask in whatever form the winning path produced — a LAZY jax
        device array when every lane MISSED the memo-cache (cold or
        disabled), so a downstream jitted program
        (policy/tensorpolicy.py) consumes the mask without a
        device->host->device round trip; the cache write-back is then
        deferred to the resolver's `.writeback()` attribute, which the
        consumer calls at its own host-sync point.  Batches with cache
        hits degrade the resolver to the usual numpy mask; verdict
        VALUES are identical either way, and `np.asarray(resolver())`
        is always a correct host view."""
        return self._verify_async(items, keep_device=True)

    def verify_many_async(self, items: Sequence[VerifyItem]):
        """Memo-probe + dedup + marshal + DISPATCH, returning a
        zero-arg resolver for the verdicts.  Between dispatch and
        resolution the device executes while the caller does host work
        for the next bucket — the commit pipeline's double buffer
        (SURVEY §2.9 row 2; reference analog: the payload buffer
        decoupling pull from commit at gossip/state/state.go:583)."""
        return self._verify_async(items, keep_device=False)

    def _verify_async(self, items: Sequence[VerifyItem],
                      keep_device: bool):
        n = len(items)
        if n == 0:
            return lambda: np.zeros(0, bool)
        # Dedup FIRST, then memo-probe once per unique triple: the
        # cache hit/miss counters thereby count unique work units —
        # 2048 copies of one signature are one miss and one device
        # lane, not 2048 of either.
        slot_of: dict = {}
        uniq_items: List[VerifyItem] = []
        uniq_keys: List[tuple] = []
        lanes = np.empty(n, np.int64)
        for i, it in enumerate(items):
            k = VerdictCache.key_of(it)
            lane = slot_of.get(k) if k is not None else None
            if lane is None:
                lane = len(uniq_items)
                if k is not None:        # None: uncacheable, own lane
                    slot_of[k] = lane
                uniq_items.append(it)
                uniq_keys.append(k)
            lanes[i] = lane
        cache = self._cache
        cached = (cache.get_many(uniq_keys) if cache is not None
                  else [None] * len(uniq_keys))
        miss_lanes = [j for j, c in enumerate(cached) if c is None]
        vals = np.array([bool(c) for c in cached], bool)
        if not miss_lanes:
            out = vals[lanes]
            return lambda: out
        resolve = self._dispatch([uniq_items[j] for j in miss_lanes])
        miss_idx = np.asarray(miss_lanes)

        if keep_device and len(miss_lanes) == len(uniq_keys):
            # the fused path: EVERY lane is a miss (cache cold for this
            # batch, or disabled), so nothing needs host assembly —
            # hand the raw (possibly device-resident, still-lazy) mask
            # through; a jax fancy-gather keeps the dedup expansion on
            # device too.  Cache write-back needs a host sync, so it
            # is DEFERRED to `.writeback()`, which the consumer calls
            # at its own sync point (StagedBlock.resolve_mask) — the
            # default-cache production config keeps the device handoff
            # live instead of silently degrading to the host branch.
            identity_lanes = len(uniq_items) == n
            state: dict = {}

            def finish_fused():
                raw = state.get("raw")
                if raw is None:
                    raw = state["raw"] = resolve()
                if identity_lanes:
                    return raw
                return raw[lanes]

            def writeback() -> None:
                raw = state.get("raw")
                if cache is not None and raw is not None:
                    cache.put_many(uniq_keys, np.asarray(raw, bool))
            finish_fused.writeback = writeback
            return finish_fused

        def finish() -> np.ndarray:
            mask = np.asarray(resolve(), bool)  # fmtlint: allow[jax-hot-path] -- THE sanctioned resolve seam: verdicts sync exactly once, in the commit stage, behind the in-flight window
            if cache is not None:
                cache.put_many([uniq_keys[j] for j in miss_lanes], mask)
            vals[miss_idx] = mask
            return vals[lanes]
        return finish

    def _dispatch(self, items: Sequence[VerifyItem]):
        """Marshal + dispatch unique items (no cache/dedup layer).
        Device/XLA runtime errors — at dispatch OR at resolution —
        fail over per-batch to the sw fallback (identical verdicts)
        and feed the circuit breaker; with the circuit open the device
        is skipped outright until a probe re-closes it."""
        n = len(items)
        if n > BUCKETS[-1]:
            # chunk through the fixed buckets — never mint new shapes
            parts = [self._dispatch(items[i:i + BUCKETS[-1]])
                     for i in range(0, n, BUCKETS[-1])]
            return lambda: np.concatenate([p() for p in parts])
        breaker = self.breaker
        if not breaker.allow():
            self._m_fallback.add(1)
            return lambda: self._fallback_verify(items)
        try:
            resolve = self._device_dispatch(items)
        except Exception as e:
            return self._degrade(e, items)

        def finish() -> np.ndarray:
            try:
                mask = resolve()
            except Exception as e:
                return self._degrade(e, items)()
            breaker.record_success()
            return mask
        return finish

    def _device_dispatch(self, items: Sequence[VerifyItem]):
        """The raw device path: marshal + one program dispatch; the
        returned resolver blocks on (and surfaces errors from) the
        device execution."""
        n = len(items)
        size = _bucket(n, self._mesh_size)
        with tracing.span("der_marshal", items=n, bucket=size):
            d, r, s, qx, qy, pre_ok, msg = marshal_items(items, size)
        faults.point("bccsp.device.dispatch")
        from fabric_mod_tpu.ops import p256
        # opt-in one-shot jax.profiler window (FMT_TRACE armed +
        # FMT_TRACE_JAX_PROFILE=<dir>): dispatch AND resolve run
        # inside the capture so the profile contains real device
        # execution — this batch forfeits its overlap, once, on
        # purpose (the tpu_watcher matrix trades one batch's latency
        # for the first on-hardware device profile)
        capture = tracing.device_profile_capture()
        if msg is not None:
            # fused hash->verify: raw-message lanes hash on device in
            # the SAME program as the ladder — one dispatch, no host
            # digest loop (FABRIC_MOD_TPU_FUSED_HASH consumers)
            words, nblocks, has_msg = msg
            dispatch = lambda: p256.batch_verify_raw(
                words, nblocks, has_msg, d, r, s, qx, qy,
                mesh=self._mesh, lazy=True)
        else:
            dispatch = lambda: p256.batch_verify(d, r, s, qx, qy,
                                                 mesh=self._mesh,
                                                 lazy=True)
        if capture is not None:
            with capture:
                mask = dispatch()()
            resolve = lambda: mask
        else:
            resolve = dispatch()

        def done() -> np.ndarray:
            faults.point("bccsp.device.resolve")
            return (resolve() & pre_ok)[:n]
        return done

    def _degrade(self, e: BaseException, items: Sequence[VerifyItem]):
        """Handle a dispatch/resolve failure: device errors fall back
        to the sw verifier (and count toward opening the circuit);
        anything else re-raises — it is a host bug, not an outage."""
        if not is_device_error(e):
            raise e
        self._m_device_errors.add(1)
        self._m_fallback.add(1)
        self.breaker.record_failure()
        return lambda: self._fallback_verify(items)

    def _fallback_verify(self, items: Sequence[VerifyItem]) -> np.ndarray:
        """The degraded path: host software, identical verdicts (the
        sw provider enforces the same low-S/encoding rules the device
        marshaller bakes into pre_ok)."""
        fb = self._fallback
        if fb is not None:
            return np.asarray(fb(items), bool)  # fmtlint: allow[jax-hot-path] -- degraded sw path: verdicts are host-computed by definition
        csp = self._fallback_csp
        if csp is None:
            csp = self._fallback_csp = _sw.SwCSP()
        return np.asarray(csp.verify_batch(items), bool)  # fmtlint: allow[jax-hot-path] -- degraded sw path: verdicts are host-computed by definition

    def _probe_device(self) -> bool:
        """Breaker probe: one minimal-bucket dispatch must execute
        without a device error (its verdict is irrelevant — the probe
        item is garbage by construction)."""
        try:
            faults.point("bccsp.device.probe")
            probe_item = VerifyItem(b"\x00" * 32, b"\x00" * 8,
                                    b"\x00" * 64)
            self._device_dispatch([probe_item])()
            return True
        except Exception as e:
            return not is_device_error(e)


class FakeBatchVerifier:
    """Deterministic CPU stand-in with the TpuVerifier seam (for tests
    and TPU-less deployments — the reference's fake-at-the-interface
    testing pattern, SURVEY.md §4)."""

    def __init__(self, csp: Optional[BCCSP] = None):
        self._csp = csp or _sw.SwCSP()

    def verify_many(self, items: Sequence[VerifyItem]) -> np.ndarray:
        return np.asarray(self._csp.verify_batch(items), bool)  # fmtlint: allow[jax-hot-path] -- FakeBatchVerifier is the host stand-in; no device in the loop

    def verify_many_async(self, items: Sequence[VerifyItem]):
        """Deferred-to-resolution stand-in for the device's async
        dispatch: the sw verify runs when the resolver is called (in
        the commit stage), preserving the pipeline's thread layout."""
        return lambda: self.verify_many(items)

    def verify_many_fused_async(self, items: Sequence[VerifyItem]):
        """Host twin of TpuVerifier's fusion seam: the mask is a numpy
        array, so the tensor-policy session routes it through the
        vectorized numpy interpreter (no XLA on the sw path)."""
        return self.verify_many_async(items)


# ---------------------------------------------------------------------------
# The batching front door
# ---------------------------------------------------------------------------

_SERVICE_BATCH_OPTS = MetricOpts(
    "fabric", "bccsp", "verify_batch_items",
    help="Items per dispatched verify batch (coalescing effectiveness).")
_SERVICE_INFLIGHT_OPTS = MetricOpts(
    "fabric", "bccsp", "verify_inflight_batches",
    help="Device batches dispatched but not yet resolved.")
_SERVICE_TIMEOUTS_OPTS = MetricOpts(
    "fabric", "bccsp", "verify_deadline_timeouts_total",
    help="Verify calls that hit the FABRIC_MOD_TPU_VERIFY_DEADLINE "
         "before their verdicts resolved.")


class VerifyDeadlineExceeded(TimeoutError):
    """The verify deadline expired before the verdict resolved.

    Typed so callers can tell a DEADLINE (device overloaded / stuck —
    the caller's timeout policy fired) from a device FAILURE (the
    batch errored — the breaker/fallback layer's business).  Straggler
    futures of a timed-out verify_many fail with this same error.
    """

    def __init__(self, msg: str, deadline_s: Optional[float] = None):
        super().__init__(msg)
        self.deadline_s = deadline_s


def verify_deadline_s() -> Optional[float]:
    """FABRIC_MOD_TPU_VERIFY_DEADLINE: whole-call deadline (seconds)
    shared by BatchingVerifyService.verify/verify_many; 0 or negative
    = no deadline."""
    got = _knobs.get_float("FABRIC_MOD_TPU_VERIFY_DEADLINE")
    return got if got > 0 else None


# callers distinguish "use the knob" (default) from an explicit
# timeout=None (wait forever)
_DEADLINE_KNOB = object()


def _complete(fut: Future, value=None, exc: Optional[BaseException] = None
              ) -> None:
    """Complete a Future that a deadline may have failed first: the
    straggler path and the resolver race, and the loser must not die
    on InvalidStateError (killing the resolver thread would hang every
    later caller)."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
    except InvalidStateError:
        pass


class BatchingVerifyService:
    """Deadline/size-batched async verify front-end with a bounded
    in-flight dispatch window.

    A worker thread drains the submit queue into batches (flush on
    `max_batch` pending or the oldest item turning `deadline_s` old),
    marshals each batch, and DISPATCHES it via the verifier's
    `verify_many_async` — then immediately returns to accumulating the
    next batch while the device executes.  A separate resolver thread
    completes Futures in dispatch order.  The in-flight queue between
    them is bounded (`inflight_depth`, default 2 or
    FABRIC_MOD_TPU_INFLIGHT): when the device falls behind, the worker
    blocks on the queue — backpressure, not unbounded buffering.

    This is the latency/throughput trade-off knob (SURVEY.md §7 hard
    part #3) plus the host/device overlap the old blocking `_flush`
    forfeited: bucket k+1 marshals while bucket k executes.
    """

    _SENTINEL = None

    def __init__(self, verifier=None, max_batch: int = 2048,
                 deadline_s: float = 0.002,
                 inflight_depth: Optional[int] = None):
        # a verifier built HERE is owned here: close() must stop its
        # breaker prober (a caller-provided verifier may be shared, so
        # its lifecycle stays the caller's)
        self._owns_verifier = verifier is None
        self._verifier = verifier or TpuVerifier()
        self.max_batch = max_batch
        self.deadline_s = deadline_s
        if inflight_depth is None:
            inflight_depth = _knobs.get_int("FABRIC_MOD_TPU_INFLIGHT")
        self.inflight_depth = max(1, inflight_depth)
        # submit queue: many producers (any caller), ONE consumer (the
        # flusher worker); in-flight queue: strict SPSC worker ->
        # resolver.  Both contracts are machine-checked under
        # FMT_RACECHECK — the round-5 verdict named this flusher the
        # structure most likely to hide a real race.
        self._q: "GuardedQueue" = GuardedQueue(name="verify-submit")
        self._inflight: "GuardedQueue" = GuardedQueue(
            self.inflight_depth, name="verify-inflight",
            single_producer=True)
        self._stop = threading.Event()
        # serializes submit vs close; registry-fed for cycle detection
        self._lifecycle = RegisteredLock("verify-service-lifecycle")
        prov = default_provider()
        self._batch_hist = prov.histogram(
            _SERVICE_BATCH_OPTS, buckets=(1, 8, 64, 256, 512, 1024, 2048))
        self._inflight_gauge = prov.gauge(_SERVICE_INFLIGHT_OPTS)
        self._timeouts = prov.counter(_SERVICE_TIMEOUTS_OPTS)
        self._resolver = RegisteredThread(target=self._resolve_loop,
                                          name="verify-resolver",
                                          structure="BatchingVerifyService")
        self._resolver.start()
        self._worker = RegisteredThread(target=self._run,
                                        name="verify-flusher",
                                        structure="BatchingVerifyService")
        self._worker.start()

    def submit(self, item: VerifyItem, tag=None) -> Future:
        """`tag` rides the Future through the flusher untouched here;
        routing subclasses (sharding.CrossChannelVerifyService) read
        it in `_route_batch` to split one coalesced batch into
        per-slice dispatch groups.  It must be attached BEFORE the
        enqueue — the flusher may drain the item the instant the put
        lands."""
        fut: Future = Future()
        if tag is not None:
            fut._fmt_shard_tag = tag
        if tracing.armed():
            # the caller's trace context rides the Future through the
            # GuardedQueue handoff: the flusher/resolver threads link
            # their spans under the submitting span, so a tx's trace
            # survives the batch coalescing seam
            fut._fmt_trace_ctx = tracing.current_ctx()
        # Under the lock, either close() has not started (the item lands
        # before close()'s straggler drain) or it has finished setting
        # _stop (we reject here) — no orphaned Futures either way.
        with self._lifecycle:
            if self._stop.is_set():
                fut.set_exception(RuntimeError("verify service is closed"))
                return fut
            self._q.put((item, fut))
        return fut

    def verify_many(self, items: Sequence[VerifyItem],
                    timeout=_DEADLINE_KNOB, tag=None):
        """The policy-engine seam (same shape as TpuVerifier): submit
        each item and gather verdicts.  Concurrent callers' items
        coalesce into shared device batches — this is how ingress
        paths (broadcast filters, gossip-storm verifies) ride ONE
        deadline-batched dispatch across many independent requests
        (SURVEY §2.9 'admission control feeding fixed-size batches').
        `timeout` bounds the WHOLE call, not each item; default is the
        FABRIC_MOD_TPU_VERIFY_DEADLINE knob (explicit None waits
        forever).  On expiry every still-pending Future fails with
        VerifyDeadlineExceeded — typed, so callers can tell a deadline
        from a device failure — and the call raises it.  `tag` is the
        routing label (see `submit`)."""
        if timeout is _DEADLINE_KNOB:
            timeout = verify_deadline_s()
        futs = [self.submit(it, tag=tag) for it in items]
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        out = []
        for f in futs:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                out.append(f.result(remaining))
            except FutureTimeout:
                raise self._fail_stragglers(futs, timeout) from None
        return out

    def _fail_stragglers(self, futs: Sequence[Future],
                         timeout: Optional[float]
                         ) -> "VerifyDeadlineExceeded":
        """Deadline expiry: fail every not-yet-resolved Future with the
        typed timeout error so no caller is left parked on a verdict
        the device may never produce.  (A resolver completing a future
        concurrently wins harmlessly — both sides complete through the
        InvalidStateError-tolerant `_complete`.)"""
        pending = [f for f in futs if not f.done()]
        err = VerifyDeadlineExceeded(
            f"verify deadline ({timeout}s) expired with "
            f"{len(pending)} verdict(s) outstanding", deadline_s=timeout)
        for f in pending:
            _complete(f, exc=err)
        self._timeouts.add(1)
        return err

    def verify(self, item: VerifyItem, timeout=_DEADLINE_KNOB) -> bool:
        """Single-item verify under the shared deadline knob (see
        verify_many for the timeout semantics)."""
        if timeout is _DEADLINE_KNOB:
            timeout = verify_deadline_s()
        fut = self.submit(item)
        try:
            return fut.result(timeout)
        except FutureTimeout:
            raise self._fail_stragglers([fut], timeout) from None

    def close(self) -> None:
        """Stop both threads, draining: everything already submitted
        (including batches still in flight on the device) gets a
        verdict — callers may be blocked on their Futures."""
        with self._lifecycle:
            self._stop.set()
        try:
            # leak-checked teardown: a worker/resolver that survives
            # the join is a race report, not a silent daemon park
            assert_joined((self._worker, self._resolver),
                          owner="BatchingVerifyService", timeout=30)
        finally:
            # A submit may have raced the worker's final drain; fail
            # any stragglers rather than leaving callers hung — even
            # when the join raised (a caller parked on a raced Future
            # must not block forever behind the race report).  When
            # the join raised the worker may still be ALIVE, so the
            # consumer pin must be released explicitly or the drain
            # itself would raise a second RaceError, mask the leak
            # report, and leave the stragglers unresolved.
            self._q.release_consumer()
            while True:
                try:
                    _, fut = self._q.get_nowait()
                except queue.Empty:
                    break
                _complete(fut, exc=RuntimeError(
                    "verify service is closed"))
            if self._owns_verifier:
                close = getattr(self._verifier, "close", None)
                if close is not None:
                    close()

    # -- worker side: accumulate + dispatch -------------------------------

    def _route_batch(self, batch):
        """Split one coalesced batch into dispatch groups
        ``[(verifier, subbatch)]``.  The base service is a single
        program: everything goes to the one verifier.  The sharding
        subsystem's cross-channel service overrides this to group by
        the submit tag's mesh slice — one flusher, per-slice fused
        dispatches."""
        return [(self._verifier, batch)]

    def _flush(self, batch) -> None:
        """Marshal + dispatch one batch, then hand it to the resolver.
        Marshalling failures fail the affected GROUP's Futures here
        (a routed batch dispatches group-by-group, and one channel's
        bad marshal must not fail another channel's riders); device
        failures surface on the resolver thread."""
        self._batch_hist.observe(len(batch))
        # stitch the flush span under the FIRST traced submitter (a
        # coalesced batch has many parents; one link beats none, and
        # the span's items attr says how many riders shared it)
        parent = None
        if tracing.armed():
            parent = next(
                (getattr(f, "_fmt_trace_ctx", None) for _, f in batch
                 if getattr(f, "_fmt_trace_ctx", None) is not None),
                None)
        flush_span = tracing.span("verify.flush", parent=parent,
                                  items=len(batch))
        dispatched = []
        with flush_span:
            # the span covers routing + marshal + dispatch ONLY — the
            # backpressure puts below may block on the in-flight
            # window, and that queue-wait is resolver backlog, not
            # flush cost (the PR 9 attribution reads this span)
            try:
                groups = self._route_batch(batch)
            except Exception as e:
                for _, fut in batch:
                    _complete(fut, exc=e)
                return
            for verifier, group in groups:
                items = [b[0] for b in group]
                try:
                    async_fn = getattr(verifier,
                                       "verify_many_async", None)
                    if async_fn is not None:
                        resolve = async_fn(items)
                    else:
                        mask = verifier.verify_many(items)
                        resolve = lambda m=mask: m   # noqa: E731
                except Exception as e:
                    for _, fut in group:
                        _complete(fut, exc=e)
                    continue
                dispatched.append((group, resolve))
        for group, resolve in dispatched:
            # Bounded in-flight window: blocks when `inflight_depth`
            # batches are already executing — backpressure on the
            # worker.  Gauge BEFORE put: the dispatched batch is in
            # flight even while the put blocks, and incrementing
            # after would race the resolver's decrement below zero.
            self._inflight_gauge.add(1)
            self._inflight.put((group, resolve, flush_span.ctx))

    def _run(self) -> None:
        pending: list[tuple[VerifyItem, Future]] = []
        first_ts = 0.0
        while not self._stop.is_set():
            timeout = None
            if pending:
                timeout = max(0.0, first_ts + self.deadline_s - time.time())
            try:
                item = self._q.get(timeout=timeout if pending else 0.05)
                if not pending:
                    first_ts = time.time()
                pending.append(item)
            except queue.Empty:
                pass
            if pending and (len(pending) >= self.max_batch
                            or time.time() - first_ts >= self.deadline_s):
                batch, pending = pending, []
                self._flush(batch)
        # Drain on close: anything submitted before close() still gets
        # a verdict rather than leaving callers hung on their Futures.
        while True:
            try:
                pending.append(self._q.get_nowait())
            except queue.Empty:
                break
        if pending:
            self._flush(pending)
        self._inflight.put(self._SENTINEL)   # resolver: drain then exit

    # -- resolver side: complete futures in dispatch order -----------------

    def _resolve_loop(self) -> None:
        while True:
            got = self._inflight.get()
            if got is self._SENTINEL:
                return
            batch, resolve, flush_ctx = got
            try:
                # the resolve span continues the flush span's trace —
                # the item's journey submit -> flusher -> device ->
                # resolver is one stitched parent chain
                with tracing.span("verify.resolve", parent=flush_ctx,
                                  items=len(batch)):
                    mask = resolve()
                # _complete, not set_result: a deadline-failed
                # straggler must not kill the resolver thread
                for (_, fut), ok in zip(batch, mask):
                    _complete(fut, bool(ok))
            except Exception as e:
                for _, fut in batch:
                    _complete(fut, exc=e)
            finally:
                self._inflight_gauge.add(-1)


class TpuCSP(BCCSP):
    """BCCSP whose Verify path runs on the TPU.

    Key management, hashing of single messages, signing, and symmetric
    crypto delegate to the software provider; `verify`/`verify_batch`
    go to the device.  `hash_many` exposes the device SHA-256 batch
    for pipelines that hash entire blocks.
    """

    def __init__(self, keystore_path: Optional[str] = None,
                 verifier=None, service: Optional[BatchingVerifyService] = None):
        self._sw = _sw.SwCSP(keystore_path)
        self._verifier = verifier or TpuVerifier()
        self._service = service

    # -- delegated host-side ops --
    def key_gen(self, algorithm: str = "P256", ephemeral: bool = True) -> Key:
        return self._sw.key_gen(algorithm, ephemeral)

    def key_import(self, raw: bytes, kind: str) -> Key:
        return self._sw.key_import(raw, kind)

    def get_key(self, ski: bytes) -> Optional[Key]:
        return self._sw.get_key(ski)

    def hash(self, msg: bytes, algorithm: str = "SHA256") -> bytes:
        return self._sw.hash(msg, algorithm)

    def hash_many(self, msgs: Sequence[bytes]) -> np.ndarray:
        from fabric_mod_tpu.ops import sha256
        return sha256.sha256_many(list(msgs))

    def sign(self, key: Key, digest: bytes) -> bytes:
        return self._sw.sign(key, digest)

    def encrypt(self, key: Key, plaintext: bytes) -> bytes:
        return self._sw.encrypt(key, plaintext)

    def decrypt(self, key: Key, ciphertext: bytes) -> bytes:
        return self._sw.decrypt(key, ciphertext)

    # -- device verify path --
    def verify(self, key: _sw.EcdsaKey, signature: bytes, digest: bytes) -> bool:
        if key.curve != "P256":
            return self._sw.verify(key, signature, digest)
        item = VerifyItem(digest, signature, key.public_xy())
        if self._service is not None:
            return self._service.verify(item)
        return bool(self._verifier.verify_many([item])[0])

    def verify_batch(self, items: Sequence[VerifyItem]) -> "list[bool]":
        return [bool(v) for v in self._verifier.verify_many(items)]
