"""Device-verifier circuit breaker: degrade to software, heal by probe.

(reference stance: Fabric treats its crypto provider as infallible —
a bccsp failure fails the request.  A TPU/XLA runtime is NOT
infallible: device resets, OOMs, and runtime errors are operational
events, and the sw verifier computes the IDENTICAL verdict function,
just slower.  So the verify path degrades instead of failing: a
device error fails over per-batch to software, and after K
CONSECUTIVE device failures the breaker opens — batches skip the
device entirely — until a probe dispatch proves it healthy again.
The breaker shape is the standard one: Nygard, "Release It!", ch. 5.)

States: "closed" (device in use) -> "open" (K consecutive failures;
everything routes to the sw fallback) -> closed again when a probe
succeeds.  Probes run two ways:

* a **background prober** thread, started when the circuit opens,
  retries the probe every `probe_interval_s` (event-driven: tests call
  `probe_soon()` instead of sleeping) and exits once the circuit
  closes — traffic never pays the probe's latency;
* `probe_now()` runs one probe synchronously (deterministic tests,
  CLI health checks).

Everything is clock-injectable; the recovery-time histogram measures
open→closed on that clock.
"""
from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Callable, Optional

from fabric_mod_tpu.concurrency import RegisteredLock, RegisteredThread
from fabric_mod_tpu.observability import tracing
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.observability.opsserver import default_health
from fabric_mod_tpu.utils import knobs

_STATE_OPTS = MetricOpts(
    "fabric", "bccsp", "breaker_state",
    help="Device-verifier circuit state: 0 closed (device in use), "
         "1 open (all batches degraded to the sw verifier).",
    label_names=("name",))
_OPENS_OPTS = MetricOpts(
    "fabric", "bccsp", "breaker_opens_total",
    help="Times the device-verifier circuit opened (K consecutive "
         "device failures).",
    label_names=("name",))
_RECOVERY_OPTS = MetricOpts(
    "fabric", "bccsp", "breaker_recovery_seconds",
    help="Open->closed duration per recovery: how long verifies ran "
         "degraded on the sw fallback before a probe healed the device.")


@functools.lru_cache(maxsize=None)
def _metrics():
    prov = default_provider()
    return (prov.gauge(_STATE_OPTS), prov.counter(_OPENS_OPTS),
            prov.histogram(_RECOVERY_OPTS,
                           buckets=(0.1, 1, 5, 15, 60, 300, 1800)))


# per-instance health-registry key suffix (breaker names repeat)
_breaker_seq = itertools.count()


def breaker_k() -> int:
    """FABRIC_MOD_TPU_BREAKER_K: consecutive device failures that open
    the circuit; 0 disables the breaker (device errors keep failing
    over per-batch, but the device is always retried)."""
    return max(0, knobs.get_int("FABRIC_MOD_TPU_BREAKER_K"))


def probe_interval_s() -> float:
    """FABRIC_MOD_TPU_BREAKER_PROBE_S: background probe period while
    open; 0 disables the prober thread (probe_now() only)."""
    return max(0.0, knobs.get_float("FABRIC_MOD_TPU_BREAKER_PROBE_S"))


class CircuitBreaker:
    """K-consecutive-failure breaker with a background healing probe.

    `probe()` must return True iff the guarded resource is healthy; it
    runs OFF the request path (prober thread or explicit probe_now).
    Thread-safe; near-zero cost while closed (one lock + int check).
    """

    def __init__(self, k: Optional[int] = None,
                 probe: Optional[Callable[[], bool]] = None,
                 interval_s: Optional[float] = None,
                 clock=None, name: str = "device-verify"):
        self.k = breaker_k() if k is None else max(0, k)
        self.interval_s = (probe_interval_s() if interval_s is None
                           else max(0.0, interval_s))
        self._probe = probe
        self._clock = clock or time
        self.name = name
        self._lock = RegisteredLock(f"breaker[{name}]")
        self._failures = 0                 # consecutive, while closed
        self._open = False
        self._opened_at = 0.0
        self._stopped = threading.Event()
        self._wake = threading.Event()     # probe_soon() / stop()
        self._prober: Optional[threading.Thread] = None
        g_state, self._m_opens, self._m_recovery = _metrics()
        self._g_state = g_state.with_labels(name)
        self._g_state.set(0)
        # real health: an open circuit (every verify degraded to sw)
        # flips /healthz.  Keyed per INSTANCE (names repeat — every
        # TpuVerifier's default breaker is "device-verify", and a
        # name-shared key would let the newest registration mask an
        # open circuit elsewhere); stop() unregisters.
        self._health_key = f"breaker[{name}#{next(_breaker_seq)}]"
        default_health().register(self._health_key, self._health_check)

    def _health_check(self) -> None:
        if self._open:
            raise RuntimeError(
                f"device-verifier circuit '{self.name}' is OPEN — all "
                f"verify batches degraded to the sw fallback")

    # -- request-path surface ---------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return "open" if self._open else "closed"

    def allow(self) -> bool:
        """May the next batch try the device?  (Open ⇒ no: callers go
        straight to the fallback — no half-open traffic gambling; the
        probe owns recovery.)"""
        with self._lock:
            return not self._open

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0

    def record_failure(self) -> bool:
        """One device failure; returns True when this call OPENED the
        circuit (K consecutive reached, K>0)."""
        with self._lock:
            self._failures += 1
            if self._open or self.k == 0 or self._failures < self.k:
                return False
            self._open = True
            self._opened_at = self._clock.monotonic()
            # gauge flips INSIDE the critical section: published
            # outside, a racing probe's set(0) could be overwritten
            # and report an open circuit that is actually closed
            self._g_state.set(1)
        self._m_opens.with_labels(self.name).add(1)
        # the open IS the incident: snapshot the flight recorder so
        # the report carries the block timelines that led up to it
        tracing.note_event("breaker_open", self.name)
        tracing.auto_dump(f"breaker_open[{self.name}]")
        self._start_prober()
        return True

    # -- healing -----------------------------------------------------------
    def probe_now(self) -> bool:
        """Run one probe synchronously; closes the circuit on success.
        Returns the new `allow()` — True when healthy."""
        with self._lock:
            if not self._open:
                return True
        probe = self._probe
        healthy = True if probe is None else bool(probe())
        if healthy:
            self._close()
        return healthy

    def probe_soon(self) -> None:
        """Nudge the background prober to run immediately (tests: the
        deterministic stand-in for waiting out interval_s)."""
        self._wake.set()

    def _close(self) -> None:
        with self._lock:
            if not self._open:
                return
            self._open = False
            self._failures = 0
            took = self._clock.monotonic() - self._opened_at
            self._g_state.set(0)           # same section as the flip
        self._m_recovery.observe(max(0.0, took))

    def _start_prober(self) -> None:
        if self._probe is None or self.interval_s <= 0 \
                or self._stopped.is_set():
            return
        with self._lock:
            # registration (not liveness) gates the spawn: a healed
            # prober DEREGISTERS under this lock before returning, so
            # a circuit that re-opens while the old thread is still
            # physically exiting gets a fresh prober instead of
            # trusting a thread that already decided to die (which
            # would leave the circuit open forever with probe_soon()
            # waking nobody)
            if self._prober is not None:
                return
            self._wake.clear()
            t = RegisteredThread(target=self._probe_loop,
                                 name=f"breaker-probe[{self.name}]",
                                 structure="CircuitBreaker")
            self._prober = t
        t.start()

    def _probe_loop(self) -> None:
        while not self._stopped.is_set():
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if self._stopped.is_set():
                return
            try:
                self.probe_now()
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- a raising probe IS the failure signal: the circuit stays open and opens_total already counts it
                pass
            with self._lock:
                # exit ONLY while verifiably closed, deregistering in
                # the same critical section: record_failure's
                # _start_prober is serialized against this, so either
                # we see the re-open and keep looping, or it sees the
                # deregistration and spawns a successor
                if not self._open:
                    self._prober = None
                    return

    def stop(self) -> None:
        """Tear down the prober (owner teardown / test cleanup); the
        health checker leaves the process-default registry with it."""
        default_health().unregister(self._health_key)
        self._stopped.set()
        self._wake.set()
        with self._lock:
            t, self._prober = self._prober, None
        if t is not None:
            t.join(timeout=10)
