"""BCCSP — the pluggable crypto-service-provider boundary.

Mirrors the reference's provider abstraction (reference:
bccsp/bccsp.go:90-134 `BCCSP` interface and the opts types in
bccsp/ecdsaopts.go, bccsp/hashopts.go, bccsp/aesopts.go): every
signature/hash/encryption in the framework funnels through this
interface, which is exactly what lets the TPU batch provider slot in
underneath the policy engine and validators without any caller
changing.

Two deliberate departures from the reference, both TPU-motivated:

* `verify_batch` is first-class.  The reference amortizes repeated
  verifies with caches (msp/cache) and goroutine fan-out; here the
  hot path hands the whole batch to the device at once, so the
  provider API exposes it directly and the single-item `verify` is
  the degenerate case.
* Keys are plain frozen dataclasses, not opaque handles; SKI
  (subject key identifier) follows the reference's convention of
  SHA-256 over the uncompressed EC point.
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class VerifyItem:
    """One signature-verification work item (the batch element).

    digest: 32-byte message digest (pre-hashed, like the reference's
      Verify(k, signature, digest) contract).  Ignored (use b"") when
      `message` is set.
    signature: DER-encoded ECDSA signature.
    public_xy: 64 bytes — uncompressed P-256 point coordinates (x‖y).
    message: optional RAW message bytes.  When set, the provider
      computes e = SHA-256(message) itself — the TPU provider fuses
      that hash into the same device program as the verify
      (FABRIC_MOD_TPU_FUSED_HASH; ops/p256.batch_verify_raw), host
      providers hash in software.  Raw and pre-digested items mix
      freely in one batch.
    """
    digest: bytes
    signature: bytes
    public_xy: bytes
    message: Optional[bytes] = None


class Key(abc.ABC):
    """A cryptographic key handle (reference: bccsp/bccsp.go Key)."""

    @abc.abstractmethod
    def ski(self) -> bytes: ...

    @abc.abstractmethod
    def private(self) -> bool: ...

    @abc.abstractmethod
    def public_key(self) -> "Key": ...

    def bytes_(self) -> bytes:
        raise NotImplementedError


class BCCSP(abc.ABC):
    """Crypto provider (reference: bccsp/bccsp.go:90 BCCSP).

    Opts are plain strings ("P256", "SHA256", "AES256") rather than
    the reference's opts-struct zoo — same dispatch power, less
    ceremony.
    """

    @abc.abstractmethod
    def key_gen(self, algorithm: str = "P256", ephemeral: bool = True) -> Key: ...

    @abc.abstractmethod
    def key_import(self, raw: bytes, kind: str) -> Key: ...

    @abc.abstractmethod
    def get_key(self, ski: bytes) -> Optional[Key]: ...

    @abc.abstractmethod
    def hash(self, msg: bytes, algorithm: str = "SHA256") -> bytes: ...

    @abc.abstractmethod
    def sign(self, key: Key, digest: bytes) -> bytes: ...

    @abc.abstractmethod
    def verify(self, key: Key, signature: bytes, digest: bytes) -> bool: ...

    def verify_batch(self, items: Sequence[VerifyItem]) -> "list[bool]":
        """Verify many signatures; default loops over `verify`.

        A malformed item (bad point encoding, junk DER) yields False
        for that item only — batch-poisoning is never acceptable on
        the commit path.  Raw-message items are hashed here (host
        software) — device providers override with the fused path.
        """
        out = []
        for it in items:
            try:
                key = self.key_import(b"\x04" + it.public_xy, "P256-pub")
                digest = it.digest
                if getattr(it, "message", None) is not None:
                    digest = self.hash(it.message)
                out.append(self.verify(key, it.signature, digest))
            except Exception:
                out.append(False)
        return out

    def encrypt(self, key: Key, plaintext: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, key: Key, ciphertext: bytes) -> bytes:
        raise NotImplementedError
