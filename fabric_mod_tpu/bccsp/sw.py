"""Software crypto provider — the CPU reference implementation.

The analog of the reference's default provider (reference: bccsp/sw/
impl.go:247 dispatch, bccsp/sw/ecdsa.go:27-57 sign/verify with the
low-S rule, bccsp/sw/fileks.go keystore): pure host-side crypto via
the `cryptography` package (OpenSSL).  Every layer above is testable
against this provider with no TPU, mirroring how the reference's unit
suites run on bccsp/sw; it is also the baseline the device provider's
benchmark compares against.
"""
from __future__ import annotations

import hashlib
import os
from typing import Optional, Sequence

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed, decode_dss_signature, encode_dss_signature)
    from cryptography.hazmat.primitives.ciphers import (
        Cipher, algorithms, modes)
    from cryptography.hazmat.primitives.padding import PKCS7
    HAVE_CRYPTOGRAPHY = True
except ImportError:
    # Dependency gate: degrade to the pure-python P-256 fallback
    # (bccsp/_ecfallback.py) instead of taking down every importer.
    # P-256 keygen/sign/verify keep working (slowly); PEM, AES, and
    # P-384 raise a clear UnsupportedByFallback at first USE.  Loud on
    # purpose: the fallback's big-int math is ~1000x slower and NOT
    # constant-time, so an image silently losing the wheel must leave
    # a trace (same policy as limbs9.set_precision_mode).
    import sys as _sys
    print("fabric_mod_tpu: 'cryptography' wheel unavailable — bccsp/sw "
          "degrading to the pure-python P-256 fallback (slow, "
          "non-constant-time; PEM/AES/P-384 disabled).  Install "
          "'cryptography' for production use.",
          file=_sys.stderr, flush=True)
    from fabric_mod_tpu.bccsp import _ecfallback as _fb
    InvalidSignature = _fb.InvalidSignature
    ec = _fb.ec
    hashes = _fb.hashes
    serialization = _fb.serialization
    Cipher, algorithms, modes = _fb.Cipher, _fb.algorithms, _fb.modes
    PKCS7 = _fb.PKCS7
    Prehashed = _fb.Prehashed
    decode_dss_signature = _fb.decode_dss_signature
    encode_dss_signature = _fb.encode_dss_signature
    HAVE_CRYPTOGRAPHY = False

from fabric_mod_tpu.bccsp.api import BCCSP, Key, VerifyItem

_CURVES = {"P256": ec.SECP256R1, "P384": ec.SECP384R1}
_ORDERS = {
    "P256": 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
    "P384": int("FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFC7634D81F4372DDF"
                "581A0DB248B0A77AECEC196ACCC52973", 16),
}
_HASHES = {"SHA256": hashlib.sha256, "SHA384": hashlib.sha384,
           "SHA3_256": hashlib.sha3_256, "SHA3_384": hashlib.sha3_384}


def _curve_name(key) -> str:
    """Strict curve classification; unsupported key types are errors."""
    curve = getattr(key, "curve", None)
    if isinstance(curve, ec.SECP256R1):
        return "P256"
    if isinstance(curve, ec.SECP384R1):
        return "P384"
    raise ValueError(f"unsupported key/curve: {type(curve).__name__}")


def point_bytes(pub: ec.EllipticCurvePublicKey) -> bytes:
    """Uncompressed point encoding 0x04‖x‖y (SKI input, like the ref)."""
    return pub.public_bytes(serialization.Encoding.X962,
                            serialization.PublicFormat.UncompressedPoint)


def ski_of(pub: ec.EllipticCurvePublicKey) -> bytes:
    return hashlib.sha256(point_bytes(pub)).digest()


def normalize_low_s(der_sig: bytes, curve: str = "P256") -> bytes:
    """Rewrite s -> n - s when s > n/2 (the reference's low-S rule)."""
    n = _ORDERS[curve]
    r, s = decode_dss_signature(der_sig)
    if s > n // 2:
        s = n - s
    return encode_dss_signature(r, s)


def is_low_s(der_sig: bytes, curve: str = "P256") -> bool:
    _, s = decode_dss_signature(der_sig)
    return s <= _ORDERS[curve] // 2


class EcdsaKey(Key):
    def __init__(self, priv: Optional[ec.EllipticCurvePrivateKey],
                 pub: ec.EllipticCurvePublicKey, curve: str):
        self._priv, self._pub, self.curve = priv, pub, curve

    def ski(self) -> bytes:
        return ski_of(self._pub)

    def private(self) -> bool:
        return self._priv is not None

    def public_key(self) -> "EcdsaKey":
        return EcdsaKey(None, self._pub, self.curve)

    def bytes_(self) -> bytes:
        return point_bytes(self._pub)

    def public_xy(self) -> bytes:
        return point_bytes(self._pub)[1:]


class AesKey(Key):
    def __init__(self, raw: bytes):
        self._raw = raw

    def ski(self) -> bytes:
        return hashlib.sha256(self._raw).digest()

    def private(self) -> bool:
        return True

    def public_key(self) -> Key:
        raise ValueError("symmetric key has no public half")

    def bytes_(self) -> bytes:
        return self._raw


class FileKeyStore:
    """PEM-file keystore by hex SKI (reference: bccsp/sw/fileks.go)."""

    def __init__(self, path: Optional[str]):
        self.path = path
        if path:
            os.makedirs(path, exist_ok=True)

    def store(self, key: EcdsaKey) -> None:
        if not self.path:
            return
        if key.private():
            pem = key._priv.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption())
            name = key.ski().hex() + "_sk.pem"
        else:
            pem = key._pub.public_bytes(
                serialization.Encoding.PEM,
                serialization.PublicFormat.SubjectPublicKeyInfo)
            name = key.ski().hex() + "_pk.pem"
        with open(os.path.join(self.path, name), "wb") as f:
            f.write(pem)

    def load(self, ski: bytes) -> Optional[EcdsaKey]:
        if not self.path:
            return None
        for suffix in ("_sk.pem", "_pk.pem"):
            p = os.path.join(self.path, ski.hex() + suffix)
            if os.path.exists(p):
                with open(p, "rb") as f:
                    data = f.read()
                if suffix == "_sk.pem":
                    priv = serialization.load_pem_private_key(data, None)
                    return EcdsaKey(priv, priv.public_key(),
                                    _curve_name(priv))
                pub = serialization.load_pem_public_key(data)
                return EcdsaKey(None, pub, _curve_name(pub))
        return None


class SwCSP(BCCSP):
    """Host software provider (reference: bccsp/sw)."""

    def __init__(self, keystore_path: Optional[str] = None):
        self._ks = FileKeyStore(keystore_path)
        self._mem: dict[bytes, Key] = {}

    # -- keys --
    def key_gen(self, algorithm: str = "P256", ephemeral: bool = True) -> Key:
        if algorithm in _CURVES:
            priv = ec.generate_private_key(_CURVES[algorithm]())
            key = EcdsaKey(priv, priv.public_key(), algorithm)
        elif algorithm.startswith("AES"):
            key = AesKey(os.urandom(int(algorithm[3:]) // 8))
        else:
            raise ValueError(f"unknown algorithm {algorithm}")
        self._mem[key.ski()] = key
        if not ephemeral and isinstance(key, EcdsaKey):
            self._ks.store(key)
        return key

    def key_import(self, raw: bytes, kind: str) -> Key:
        if kind == "P256-pub":
            pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256R1(), raw)
            return EcdsaKey(None, pub, "P256")
        if kind == "P384-pub":
            pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP384R1(), raw)
            return EcdsaKey(None, pub, "P384")
        if kind == "pem-priv":
            priv = serialization.load_pem_private_key(raw, None)
            key = EcdsaKey(priv, priv.public_key(), _curve_name(priv))
            self._mem[key.ski()] = key
            return key
        if kind == "pem-pub" or kind == "x509-pub":
            pub = serialization.load_pem_public_key(raw)
            return EcdsaKey(None, pub, _curve_name(pub))
        if kind.startswith("AES"):
            key = AesKey(raw)
            self._mem[key.ski()] = key
            return key
        raise ValueError(f"unknown import kind {kind}")

    def get_key(self, ski: bytes) -> Optional[Key]:
        return self._mem.get(ski) or self._ks.load(ski)

    # -- hash --
    def hash(self, msg: bytes, algorithm: str = "SHA256") -> bytes:
        return _HASHES[algorithm](msg).digest()

    # -- sign/verify --
    def sign(self, key: EcdsaKey, digest: bytes) -> bytes:
        if not key.private():
            raise ValueError("signing needs a private key")
        halg = hashes.SHA256() if key.curve == "P256" else hashes.SHA384()
        der = key._priv.sign(digest, ec.ECDSA(Prehashed(halg)))
        return normalize_low_s(der, key.curve)

    def verify(self, key: EcdsaKey, signature: bytes, digest: bytes) -> bool:
        try:
            if not is_low_s(signature, key.curve):
                return False
            halg = hashes.SHA256() if key.curve == "P256" else hashes.SHA384()
            key._pub.verify(signature, digest, ec.ECDSA(Prehashed(halg)))
            return True
        except (InvalidSignature, ValueError):
            return False

    # -- symmetric (AES-CBC-PKCS7, reference: bccsp/sw/aes.go) --
    def encrypt(self, key: AesKey, plaintext: bytes) -> bytes:
        iv = os.urandom(16)
        padder = PKCS7(128).padder()
        padded = padder.update(plaintext) + padder.finalize()
        enc = Cipher(algorithms.AES(key.bytes_()), modes.CBC(iv)).encryptor()
        return iv + enc.update(padded) + enc.finalize()

    def decrypt(self, key: AesKey, ciphertext: bytes) -> bytes:
        iv, body = ciphertext[:16], ciphertext[16:]
        dec = Cipher(algorithms.AES(key.bytes_()), modes.CBC(iv)).decryptor()
        padded = dec.update(body) + dec.finalize()
        unpadder = PKCS7(128).unpadder()
        return unpadder.update(padded) + unpadder.finalize()
