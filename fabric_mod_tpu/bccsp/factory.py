"""Provider factory (reference: bccsp/factory/factory.go:32-64).

Selects sw vs tpu provider from config and keeps a process-global
default, mirroring `factory.GetDefault`.  The tpu provider is the
"pkcs11 slot" of this framework: same selection seam, different
device (reference: bccsp/factory/swfactory.go, sampleconfig/
core.yaml:297-310 BCCSP section).
"""
from __future__ import annotations

import threading
from typing import Optional

from fabric_mod_tpu.bccsp.api import BCCSP
from fabric_mod_tpu.bccsp.sw import SwCSP
from fabric_mod_tpu.concurrency.locks import RegisteredLock

_default: Optional[BCCSP] = None
_lock = RegisteredLock("bccsp.factory._lock")


def new_provider(config: Optional[dict] = None) -> BCCSP:
    """config = {"default": "SW"|"TPU", "keystore": path|None}.

    The tpu module is imported lazily: selecting the SW provider must
    not drag in jax or mutate device/compile-cache config.
    """
    config = config or {}
    kind = config.get("default", "SW").upper()
    ks = config.get("keystore")
    if kind == "SW":
        return SwCSP(ks)
    if kind == "TPU":
        from fabric_mod_tpu.bccsp.tpu import TpuCSP
        return TpuCSP(ks)
    raise ValueError(f"unknown BCCSP provider {kind!r}")


def get_default() -> BCCSP:
    global _default
    with _lock:
        if _default is None:
            _default = SwCSP()
        return _default


def init_factories(config: Optional[dict] = None) -> BCCSP:
    global _default
    with _lock:
        _default = new_provider(config)
        return _default
