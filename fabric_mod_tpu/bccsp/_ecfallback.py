"""Pure-python P-256 ECDSA fallback — dependency gate for `cryptography`.

The sw provider (bccsp/sw.py) fronts OpenSSL via the `cryptography`
package, but not every deployment image ships it (this container's
tier-1 environment does not).  Rather than letting a missing wheel
take down every signature fixture, the sw baseline, and half the test
suite at import time, this module provides a minimal, slow, correct
P-256 ECDSA in python ints with exactly the micro-API surface sw.py
touches — so `bccsp.sw` degrades to it transparently.

Scope is deliberately tiny: P-256 keygen / deterministic-k (RFC 6979)
sign / verify, uncompressed-point encode/decode, DER ECDSA-Sig-Value
encode/decode (decode shared with bccsp/der.py so the two parsers
cannot drift), and just enough key serialization for the self-
generated material this framework mints: SEC1/PKCS#8 private keys and
SubjectPublicKeyInfo public keys, PEM or DER (the surface
msp/ca.py-issued certificates and the x509 fallback need — see
bccsp/_x509fallback.py).  P-384 and AES raise with a clear "install
cryptography" message instead of failing mysteriously.  Performance
is ~ms per operation — fine for fixtures and baselines, never the
production verify path (that is the device's job).
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import secrets

# NIST P-256 domain parameters (public constants; duplicated from
# ops/p256.py on purpose — this module must import without jax).
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5


class InvalidSignature(Exception):
    """Verification failure (mirrors cryptography.exceptions)."""


class UnsupportedByFallback(RuntimeError):
    """Feature outside the fallback's scope — install `cryptography`."""

    def __init__(self, what: str):
        super().__init__(
            f"{what} requires the 'cryptography' package, which is not "
            f"installed; the pure-python fallback only covers P-256 "
            f"keygen/sign/verify")


# --- affine curve arithmetic (python ints; None is the identity) -----------

def point_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1 - 3) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def _jac_double(p):
    """Jacobian doubling for a = -3 (None is the identity)."""
    if p is None:
        return None
    x, y, z = p
    if y == 0:
        return None
    ysq = y * y % P
    s = 4 * x * ysq % P
    zz = z * z % P
    m = 3 * (x - zz) * (x + zz) % P
    nx = (m * m - 2 * s) % P
    ny = (m * (s - nx) - 8 * ysq * ysq) % P
    nz = 2 * y * z % P
    return (nx, ny, nz)


def _jac_add_affine(p, q):
    """Jacobian p + affine q (mixed addition; None is the identity)."""
    if q is None:
        return p
    if p is None:
        return (q[0], q[1], 1)
    x1, y1, z1 = p
    x2, y2 = q
    z1z1 = z1 * z1 % P
    u2 = x2 * z1z1 % P
    s2 = y2 * z1 * z1z1 % P
    if u2 == x1:
        if s2 == y1 % P:
            return _jac_double(p)
        return None
    h = (u2 - x1) % P
    hh = h * h % P
    i = 4 * hh % P
    j = h * i % P
    rr = 2 * (s2 - y1) % P
    v = x1 * i % P
    nx = (rr * rr - j - 2 * v) % P
    ny = (rr * (v - nx) - 2 * y1 * j) % P
    nz = 2 * z1 * h % P
    return (nx, ny, nz)


def _jac_add(p, q):
    """General Jacobian + Jacobian addition (None is the identity)."""
    if p is None:
        return q
    if q is None:
        return p
    x1, y1, z1 = p
    x2, y2, z2 = q
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return None
        return _jac_double(p)
    h = (u2 - u1) % P
    i = 4 * h * h % P
    j = h * i % P
    rr = 2 * (s2 - s1) % P
    v = u1 * i % P
    nx = (rr * rr - j - 2 * v) % P
    ny = (rr * (v - nx) - 2 * s1 * j) % P
    nz = 2 * z1 * z2 * h % P
    return (nx, ny, nz)


def _jac_to_affine(p):
    if p is None:
        return None
    zi = pow(p[2], -1, P)
    zi2 = zi * zi % P
    return (p[0] * zi2 % P, p[1] * zi2 * zi % P)


def _window_row(pt):
    """[pt, 2*pt, ..., 15*pt] in affine (the 4-bit window table)."""
    row = [pt]
    for _ in range(14):
        row.append(point_add(row[-1], pt))
    return row


# Fixed-base comb for G: 64 rows, row i holding the 1..15 multiples of
# 2^(4i)*G, so k*G is ~60 mixed additions and ZERO doublings.  Built
# lazily once per process (~1k affine ops); every sign, every keygen,
# and half of every verify rides it.
_G_COMB = None


def _g_comb():
    global _G_COMB
    if _G_COMB is None:
        rows, base = [], (GX, GY)
        for _ in range(64):
            row = _window_row(base)
            rows.append(row)
            base = point_add(row[-1], base)      # 16 * base
        _G_COMB = rows
    return _G_COMB


def _mul_g_jac(k: int):
    """k * G (Jacobian) via the fixed-base comb."""
    acc = None
    for row in _g_comb():
        nib = k & 0xF
        if nib:
            acc = _jac_add_affine(acc, row[nib - 1])
        k >>= 4
        if not k and acc is not None:
            break
    return acc


def _mul_window_jac(k: int, row):
    """k * pt (Jacobian) via a precomputed 4-bit window table for pt:
    256 doublings + ~60 mixed additions instead of ~128."""
    acc = None
    for shift in range(252, -4, -4):
        if acc is not None:
            acc = _jac_double(_jac_double(_jac_double(_jac_double(acc))))
        nib = (k >> shift) & 0xF
        if nib:
            acc = _jac_add_affine(acc, row[nib - 1])
    return acc


def point_mul(k: int, pt):
    """k * pt with ONE final inversion (the fallback's hot loop):
    fixed-base comb when pt is G, windowed Jacobian otherwise."""
    if pt is None or k % N == 0:
        return None
    k = k % N
    if pt == (GX, GY):
        return _jac_to_affine(_mul_g_jac(k))
    acc = None
    for bit in bin(k)[2:]:
        acc = _jac_double(acc)
        if bit == "1":
            acc = _jac_add_affine(acc, pt)
    return _jac_to_affine(acc)


def on_curve(x: int, y: int) -> bool:
    return (0 <= x < P and 0 <= y < P
            and (y * y - (x * x * x - 3 * x + B)) % P == 0)


# --- DER ECDSA-Sig-Value ----------------------------------------------------

def encode_dss_signature(r: int, s: int) -> bytes:
    def integer(v: int) -> bytes:
        if v < 0:
            raise ValueError("negative integer in signature")
        body = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if body[0] & 0x80:
            body = b"\x00" + body
        return b"\x02" + bytes([len(body)]) + body
    body = integer(r) + integer(s)
    if len(body) >= 0x80:
        raise ValueError("signature too large for short-form DER")
    return b"\x30" + bytes([len(body)]) + body


def decode_dss_signature(sig: bytes):
    """Strict scalar DER decode, grammar-equivalent to the batch
    decoder (bccsp/der.py) — tests/test_verify_frontend.py fuzzes the
    two against each other so they cannot drift.  A plain index parse
    on purpose: per-item callers (fallback sign/verify, the bench's
    per-item baseline loop) must not pay the batch decoder's
    per-call numpy setup."""
    ln = len(sig)
    if ln < 8 or ln > 72 or sig[0] != 0x30:
        raise ValueError("invalid ECDSA-Sig-Value DER")
    if sig[1] >= 0x80 or sig[1] + 2 != ln:
        raise ValueError("invalid ECDSA-Sig-Value DER")

    def integer(off: int):
        if off + 2 > ln or sig[off] != 0x02:
            raise ValueError("invalid ECDSA-Sig-Value DER")
        ilen = sig[off + 1]
        end = off + 2 + ilen
        if ilen < 1 or ilen > 33 or end > ln:
            raise ValueError("invalid ECDSA-Sig-Value DER")
        body = sig[off + 2:end]
        if body[0] & 0x80:
            raise ValueError("negative INTEGER")
        if body[0] == 0 and ilen > 1 and body[1] < 0x80:
            raise ValueError("non-minimal INTEGER")
        if ilen == 33 and body[0] != 0:
            raise ValueError("INTEGER too wide")
        return int.from_bytes(body, "big"), end

    r, off = integer(2)
    s, off = integer(off)
    if off != ln:
        raise ValueError("trailing garbage after ECDSA-Sig-Value")
    return r, s


# --- minimal DER primitives (shared with the x509 fallback) ----------------

def der_tlv(tag: int, body: bytes) -> bytes:
    """One DER TLV with a definite (short- or long-form) length."""
    n = len(body)
    if n < 0x80:
        return bytes([tag, n]) + body
    lb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(lb)]) + lb + body


def der_seq(*parts: bytes) -> bytes:
    return der_tlv(0x30, b"".join(parts))


def der_int(v: int) -> bytes:
    if v < 0:
        raise ValueError("negative INTEGER")
    body = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
    if body[0] & 0x80:
        body = b"\x00" + body
    return der_tlv(0x02, body)


def der_oid(dotted: str) -> bytes:
    arcs = [int(a) for a in dotted.split(".")]
    body = bytearray([arcs[0] * 40 + arcs[1]])
    for arc in arcs[2:]:
        chunk = [arc & 0x7F]
        arc >>= 7
        while arc:
            chunk.append(0x80 | (arc & 0x7F))
            arc >>= 7
        body.extend(reversed(chunk))
    return der_tlv(0x06, bytes(body))


class DerReader:
    """Strict walking reader over one DER blob (controlled shapes —
    everything this framework parses with it, it also generated)."""

    def __init__(self, buf: bytes, start: int = 0, end: int = None):
        self.buf = buf
        self.off = start
        self.end = len(buf) if end is None else end

    def done(self) -> bool:
        return self.off >= self.end

    def peek_tag(self) -> int:
        if self.done():
            raise ValueError("truncated DER")
        return self.buf[self.off]

    def read(self, expect_tag: int = None):
        """-> (tag, value_start, value_end); advances past the TLV."""
        buf, off = self.buf, self.off
        if off + 2 > self.end:
            raise ValueError("truncated DER")
        tag = buf[off]
        if expect_tag is not None and tag != expect_tag:
            raise ValueError(
                f"DER tag 0x{tag:02x}, expected 0x{expect_tag:02x}")
        ln = buf[off + 1]
        off += 2
        if ln & 0x80:
            nb = ln & 0x7F
            if nb == 0 or nb > 4 or off + nb > self.end:
                raise ValueError("bad DER length")
            ln = int.from_bytes(buf[off:off + nb], "big")
            off += nb
        if off + ln > self.end:
            raise ValueError("DER value overruns buffer")
        self.off = off + ln
        return tag, off, off + ln

    def value(self, expect_tag: int = None) -> bytes:
        _, a, b = self.read(expect_tag)
        return self.buf[a:b]

    def reader(self, expect_tag: int = None) -> "DerReader":
        _, a, b = self.read(expect_tag)
        return DerReader(self.buf, a, b)


# OIDs for the EC key/cert surface
OID_EC_PUBLIC_KEY = "1.2.840.10045.2.1"
OID_PRIME256V1 = "1.2.840.10045.3.1.7"
OID_ECDSA_SHA256 = "1.2.840.10045.4.3.2"

_EC_ALG_ID = der_seq(der_oid(OID_EC_PUBLIC_KEY), der_oid(OID_PRIME256V1))


def pem_encode(label: str, der: bytes) -> bytes:
    b64 = base64.b64encode(der)
    lines = [b64[i:i + 64] for i in range(0, len(b64), 64)]
    return (b"-----BEGIN %s-----\n" % label.encode()
            + b"\n".join(lines)
            + b"\n-----END %s-----\n" % label.encode())


def pem_decode(data: bytes) -> bytes:
    """First PEM block -> DER bytes (label-agnostic on purpose: the
    callers dispatch on content, mirroring cryptography's loaders)."""
    lines = data.replace(b"\r", b"").split(b"\n")
    body, inside = [], False
    for ln in lines:
        if ln.startswith(b"-----BEGIN"):
            inside = True
            continue
        if ln.startswith(b"-----END"):
            break
        if inside:
            body.append(ln.strip())
    if not inside or not body:
        raise ValueError("no PEM block found")
    return base64.b64decode(b"".join(body))


def spki_der(x: int, y: int) -> bytes:
    """SubjectPublicKeyInfo DER for an uncompressed P-256 point."""
    point = b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")
    return der_seq(_EC_ALG_ID, der_tlv(0x03, b"\x00" + point))


def parse_spki(der: bytes) -> "EllipticCurvePublicKey":
    outer = DerReader(der).reader(0x30)
    alg = outer.reader(0x30)
    if alg.value(0x06) != der_oid(OID_EC_PUBLIC_KEY)[2:]:
        raise UnsupportedByFallback("non-EC SubjectPublicKeyInfo")
    if alg.value(0x06) != der_oid(OID_PRIME256V1)[2:]:
        raise UnsupportedByFallback("non-P256 SubjectPublicKeyInfo")
    bits = outer.value(0x03)
    if len(bits) != 66 or bits[0] != 0 or bits[1] != 0x04:
        raise ValueError("bad EC point BIT STRING")
    return EllipticCurvePublicKey(int.from_bytes(bits[2:34], "big"),
                                  int.from_bytes(bits[34:], "big"))


def pkcs8_der(d: int) -> bytes:
    """PKCS#8 (unencrypted) DER for a P-256 private scalar, embedding
    the RFC 5915 ECPrivateKey with the public point."""
    x, y = point_mul(d, (GX, GY))
    point = b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")
    ecpriv = der_seq(
        der_int(1),
        der_tlv(0x04, d.to_bytes(32, "big")),
        der_tlv(0xA1, der_tlv(0x03, b"\x00" + point)))
    return der_seq(der_int(0), _EC_ALG_ID, der_tlv(0x04, ecpriv))


def parse_pkcs8(der: bytes) -> "EllipticCurvePrivateKey":
    outer = DerReader(der).reader(0x30)
    if outer.value(0x02) != b"\x00":
        raise ValueError("unsupported PKCS#8 version")
    alg = outer.reader(0x30)
    if alg.value(0x06) != der_oid(OID_EC_PUBLIC_KEY)[2:]:
        raise UnsupportedByFallback("non-EC private key")
    ecpriv = DerReader(outer.value(0x04)).reader(0x30)
    if ecpriv.value(0x02) != b"\x01":
        raise ValueError("unsupported ECPrivateKey version")
    d = int.from_bytes(ecpriv.value(0x04), "big")
    if not 1 <= d < N:
        raise ValueError("private scalar out of range")
    return EllipticCurvePrivateKey(d)


# --- RFC 6979 deterministic nonce ------------------------------------------

def _rfc6979_k(d: int, e: int) -> int:
    holen = 32
    x = d.to_bytes(32, "big")
    h1 = (e % N).to_bytes(32, "big")
    v = b"\x01" * holen
    k = b"\x00" * holen
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


# --- the cryptography-shaped micro-API sw.py consumes ----------------------

class SECP256R1:
    name = "secp256r1"


class SECP384R1:
    name = "secp384r1"


class ECDSA:
    """Signature-algorithm marker (digest is pre-hashed throughout)."""

    def __init__(self, algorithm=None):
        self.algorithm = algorithm


class Prehashed:
    def __init__(self, algorithm=None):
        self.algorithm = algorithm


def _digest_for_alg(data: bytes, alg) -> bytes:
    """Resolve the sign/verify input per the cryptography contract:
    ECDSA(Prehashed(...)) passes `data` through as the digest,
    ECDSA(SHA256()) (the x509 cert-signing path) hashes it.  No alg
    (legacy internal callers) means pre-hashed."""
    inner = getattr(alg, "algorithm", None)
    if inner is None or isinstance(inner, Prehashed):
        return data[:32]
    name = getattr(inner, "name", "sha256")
    if name != "sha256":
        raise UnsupportedByFallback(f"{name} message digests")
    return hashlib.sha256(data).digest()


class EllipticCurvePublicNumbers:
    def __init__(self, x: int, y: int, curve=None):
        self.x = x
        self.y = y

    def public_key(self):
        return EllipticCurvePublicKey(self.x, self.y)


class EllipticCurvePublicKey:
    curve = SECP256R1()

    def __init__(self, x: int, y: int):
        if not on_curve(x, y):
            raise ValueError("point is not on P-256")
        self._x, self._y = x, y
        self._window = None

    @classmethod
    def from_encoded_point(cls, curve, data: bytes):
        if not isinstance(curve, SECP256R1):
            raise UnsupportedByFallback("non-P256 key import")
        if len(data) != 65 or data[0] != 0x04:
            raise ValueError("only uncompressed points are supported")
        return cls(int.from_bytes(data[1:33], "big"),
                   int.from_bytes(data[33:], "big"))

    def public_numbers(self):
        return EllipticCurvePublicNumbers(self._x, self._y)

    def public_bytes(self, encoding=None, fmt=None) -> bytes:
        if encoding == "PEM":
            return pem_encode("PUBLIC KEY", spki_der(self._x, self._y))
        if encoding == "DER":
            return spki_der(self._x, self._y)
        return (b"\x04" + self._x.to_bytes(32, "big")
                + self._y.to_bytes(32, "big"))

    def verify(self, signature: bytes, data: bytes, alg=None) -> None:
        """`data` is the raw message unless alg wraps Prehashed (the
        cryptography contract: ECDSA(SHA256()) hashes, Prehashed
        passes the digest through)."""
        try:
            r, s = decode_dss_signature(signature)
        except ValueError:
            raise InvalidSignature("bad DER")
        if not (1 <= r < N and 1 <= s < N):
            raise InvalidSignature("scalar out of range")
        e = int.from_bytes(_digest_for_alg(data, alg), "big")
        w = pow(s, -1, N)
        if self._window is None:
            # identities verify many messages: one 15-entry window
            # table per key amortizes to ~nothing and halves the
            # per-verify point-op count
            self._window = _window_row((self._x, self._y))
        pt = _jac_to_affine(_jac_add(
            _mul_g_jac(e * w % N),
            _mul_window_jac(r * w % N, self._window)))
        if pt is None or pt[0] % N != r:
            raise InvalidSignature("verification failed")


class EllipticCurvePrivateKey:
    curve = SECP256R1()

    def __init__(self, d: int):
        self._d = d
        self._pub = None

    def public_key(self) -> EllipticCurvePublicKey:
        if self._pub is None:
            x, y = point_mul(self._d, (GX, GY))
            self._pub = EllipticCurvePublicKey(x, y)
        return self._pub

    def sign(self, data: bytes, alg=None) -> bytes:
        e = int.from_bytes(_digest_for_alg(data, alg), "big")
        d = self._d
        k = _rfc6979_k(d, e)
        while True:
            pt = point_mul(k, (GX, GY))
            r = pt[0] % N
            s = pow(k, -1, N) * (e + r * d) % N
            if r and s:
                return encode_dss_signature(r, s)
            k = (k + 1) % N or 1        # astronomically unlikely

    def private_bytes(self, encoding=None, fmt=None,
                      encryption=None) -> bytes:
        der = pkcs8_der(self._d)
        if encoding == "DER":
            return der
        return pem_encode("PRIVATE KEY", der)


def generate_private_key(curve) -> EllipticCurvePrivateKey:
    if not isinstance(curve, SECP256R1):
        raise UnsupportedByFallback("non-P256 key generation")
    return EllipticCurvePrivateKey(secrets.randbelow(N - 1) + 1)


# namespace shims so sw.py's call sites read identically ---------------------

class _EcNamespace:
    SECP256R1 = SECP256R1
    SECP384R1 = SECP384R1
    ECDSA = ECDSA
    EllipticCurvePublicKey = EllipticCurvePublicKey
    EllipticCurvePrivateKey = EllipticCurvePrivateKey
    EllipticCurvePublicNumbers = EllipticCurvePublicNumbers
    generate_private_key = staticmethod(generate_private_key)


class _HashAlg:
    def __init__(self, name):
        self.name = name

    def __call__(self):
        return self


class _HashesNamespace:
    SHA256 = _HashAlg("sha256")
    SHA384 = _HashAlg("sha384")


class _Raiser:
    """Attribute/call sink that defers the failure to first use."""

    def __init__(self, what):
        self._what = what

    def __getattr__(self, name):
        return _Raiser(f"{self._what}.{name}")

    def __call__(self, *a, **kw):
        raise UnsupportedByFallback(self._what)


def load_pem_private_key(data: bytes, password=None):
    if password is not None:
        raise UnsupportedByFallback("encrypted private keys")
    return parse_pkcs8(pem_decode(data))


def load_pem_public_key(data: bytes):
    return parse_spki(pem_decode(data))


def load_der_private_key(data: bytes, password=None):
    if password is not None:
        raise UnsupportedByFallback("encrypted private keys")
    return parse_pkcs8(data)


def load_der_public_key(data: bytes):
    return parse_spki(data)


class NoEncryption:
    pass


class _SerializationNamespace:
    class Encoding:
        X962 = "X962"
        PEM = "PEM"
        DER = "DER"

    class PublicFormat:
        UncompressedPoint = "UncompressedPoint"
        SubjectPublicKeyInfo = "SubjectPublicKeyInfo"

    class PrivateFormat:
        PKCS8 = "PKCS8"

    NoEncryption = NoEncryption
    load_pem_private_key = staticmethod(load_pem_private_key)
    load_pem_public_key = staticmethod(load_pem_public_key)
    load_der_private_key = staticmethod(load_der_private_key)
    load_der_public_key = staticmethod(load_der_public_key)


ec = _EcNamespace()
hashes = _HashesNamespace()
serialization = _SerializationNamespace()
Cipher = _Raiser("AES Cipher")
algorithms = _Raiser("AES algorithms")
modes = _Raiser("AES modes")
PKCS7 = _Raiser("PKCS7 padding")
