"""Pure-python P-256 ECDSA fallback — dependency gate for `cryptography`.

The sw provider (bccsp/sw.py) fronts OpenSSL via the `cryptography`
package, but not every deployment image ships it (this container's
tier-1 environment does not).  Rather than letting a missing wheel
take down every signature fixture, the sw baseline, and half the test
suite at import time, this module provides a minimal, slow, correct
P-256 ECDSA in python ints with exactly the micro-API surface sw.py
touches — so `bccsp.sw` degrades to it transparently.

Scope is deliberately tiny: P-256 keygen / deterministic-k (RFC 6979)
sign / verify, uncompressed-point encode/decode, and DER
ECDSA-Sig-Value encode/decode (decode shared with bccsp/der.py so the
two parsers cannot drift).  P-384, PEM serialization, and AES raise
with a clear "install cryptography" message instead of failing
mysteriously.  Performance is ~ms per operation — fine for fixtures
and baselines, never the production verify path (that is the device's
job).
"""
from __future__ import annotations

import hashlib
import hmac
import secrets

# NIST P-256 domain parameters (public constants; duplicated from
# ops/p256.py on purpose — this module must import without jax).
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5


class InvalidSignature(Exception):
    """Verification failure (mirrors cryptography.exceptions)."""


class UnsupportedByFallback(RuntimeError):
    """Feature outside the fallback's scope — install `cryptography`."""

    def __init__(self, what: str):
        super().__init__(
            f"{what} requires the 'cryptography' package, which is not "
            f"installed; the pure-python fallback only covers P-256 "
            f"keygen/sign/verify")


# --- affine curve arithmetic (python ints; None is the identity) -----------

def point_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1 - 3) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def _jac_double(p):
    """Jacobian doubling for a = -3 (None is the identity)."""
    if p is None:
        return None
    x, y, z = p
    if y == 0:
        return None
    ysq = y * y % P
    s = 4 * x * ysq % P
    zz = z * z % P
    m = 3 * (x - zz) * (x + zz) % P
    nx = (m * m - 2 * s) % P
    ny = (m * (s - nx) - 8 * ysq * ysq) % P
    nz = 2 * y * z % P
    return (nx, ny, nz)


def _jac_add_affine(p, q):
    """Jacobian p + affine q (mixed addition; None is the identity)."""
    if q is None:
        return p
    if p is None:
        return (q[0], q[1], 1)
    x1, y1, z1 = p
    x2, y2 = q
    z1z1 = z1 * z1 % P
    u2 = x2 * z1z1 % P
    s2 = y2 * z1 * z1z1 % P
    if u2 == x1:
        if s2 == y1 % P:
            return _jac_double(p)
        return None
    h = (u2 - x1) % P
    hh = h * h % P
    i = 4 * hh % P
    j = h * i % P
    rr = 2 * (s2 - y1) % P
    v = x1 * i % P
    nx = (rr * rr - j - 2 * v) % P
    ny = (rr * (v - nx) - 2 * y1 * j) % P
    nz = 2 * z1 * h % P
    return (nx, ny, nz)


def point_mul(k: int, pt):
    """k * pt via Jacobian double-and-add — ONE final inversion
    instead of one per point operation (the fallback's hot loop)."""
    if pt is None or k % N == 0:
        return None
    acc = None
    for bit in bin(k)[2:]:
        acc = _jac_double(acc)
        if bit == "1":
            acc = _jac_add_affine(acc, pt)
    if acc is None:
        return None
    zi = pow(acc[2], -1, P)
    zi2 = zi * zi % P
    return (acc[0] * zi2 % P, acc[1] * zi2 * zi % P)


def on_curve(x: int, y: int) -> bool:
    return (0 <= x < P and 0 <= y < P
            and (y * y - (x * x * x - 3 * x + B)) % P == 0)


# --- DER ECDSA-Sig-Value ----------------------------------------------------

def encode_dss_signature(r: int, s: int) -> bytes:
    def integer(v: int) -> bytes:
        if v < 0:
            raise ValueError("negative integer in signature")
        body = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if body[0] & 0x80:
            body = b"\x00" + body
        return b"\x02" + bytes([len(body)]) + body
    body = integer(r) + integer(s)
    if len(body) >= 0x80:
        raise ValueError("signature too large for short-form DER")
    return b"\x30" + bytes([len(body)]) + body


def decode_dss_signature(sig: bytes):
    """Strict scalar DER decode, grammar-equivalent to the batch
    decoder (bccsp/der.py) — tests/test_verify_frontend.py fuzzes the
    two against each other so they cannot drift.  A plain index parse
    on purpose: per-item callers (fallback sign/verify, the bench's
    per-item baseline loop) must not pay the batch decoder's
    per-call numpy setup."""
    ln = len(sig)
    if ln < 8 or ln > 72 or sig[0] != 0x30:
        raise ValueError("invalid ECDSA-Sig-Value DER")
    if sig[1] >= 0x80 or sig[1] + 2 != ln:
        raise ValueError("invalid ECDSA-Sig-Value DER")

    def integer(off: int):
        if off + 2 > ln or sig[off] != 0x02:
            raise ValueError("invalid ECDSA-Sig-Value DER")
        ilen = sig[off + 1]
        end = off + 2 + ilen
        if ilen < 1 or ilen > 33 or end > ln:
            raise ValueError("invalid ECDSA-Sig-Value DER")
        body = sig[off + 2:end]
        if body[0] & 0x80:
            raise ValueError("negative INTEGER")
        if body[0] == 0 and ilen > 1 and body[1] < 0x80:
            raise ValueError("non-minimal INTEGER")
        if ilen == 33 and body[0] != 0:
            raise ValueError("INTEGER too wide")
        return int.from_bytes(body, "big"), end

    r, off = integer(2)
    s, off = integer(off)
    if off != ln:
        raise ValueError("trailing garbage after ECDSA-Sig-Value")
    return r, s


# --- RFC 6979 deterministic nonce ------------------------------------------

def _rfc6979_k(d: int, e: int) -> int:
    holen = 32
    x = d.to_bytes(32, "big")
    h1 = (e % N).to_bytes(32, "big")
    v = b"\x01" * holen
    k = b"\x00" * holen
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


# --- the cryptography-shaped micro-API sw.py consumes ----------------------

class SECP256R1:
    name = "secp256r1"


class SECP384R1:
    name = "secp384r1"


class ECDSA:
    """Signature-algorithm marker (digest is pre-hashed throughout)."""

    def __init__(self, algorithm=None):
        self.algorithm = algorithm


class Prehashed:
    def __init__(self, algorithm=None):
        self.algorithm = algorithm


class EllipticCurvePublicNumbers:
    def __init__(self, x: int, y: int, curve=None):
        self.x = x
        self.y = y

    def public_key(self):
        return EllipticCurvePublicKey(self.x, self.y)


class EllipticCurvePublicKey:
    curve = SECP256R1()

    def __init__(self, x: int, y: int):
        if not on_curve(x, y):
            raise ValueError("point is not on P-256")
        self._x, self._y = x, y

    @classmethod
    def from_encoded_point(cls, curve, data: bytes):
        if not isinstance(curve, SECP256R1):
            raise UnsupportedByFallback("non-P256 key import")
        if len(data) != 65 or data[0] != 0x04:
            raise ValueError("only uncompressed points are supported")
        return cls(int.from_bytes(data[1:33], "big"),
                   int.from_bytes(data[33:], "big"))

    def public_numbers(self):
        return EllipticCurvePublicNumbers(self._x, self._y)

    def public_bytes(self, encoding=None, fmt=None) -> bytes:
        return (b"\x04" + self._x.to_bytes(32, "big")
                + self._y.to_bytes(32, "big"))

    def verify(self, signature: bytes, digest: bytes, alg=None) -> None:
        try:
            r, s = decode_dss_signature(signature)
        except ValueError:
            raise InvalidSignature("bad DER")
        if not (1 <= r < N and 1 <= s < N):
            raise InvalidSignature("scalar out of range")
        e = int.from_bytes(digest[:32], "big")
        w = pow(s, -1, N)
        pt = point_add(point_mul(e * w % N, (GX, GY)),
                       point_mul(r * w % N, (self._x, self._y)))
        if pt is None or pt[0] % N != r:
            raise InvalidSignature("verification failed")


class EllipticCurvePrivateKey:
    curve = SECP256R1()

    def __init__(self, d: int):
        self._d = d
        self._pub = None

    def public_key(self) -> EllipticCurvePublicKey:
        if self._pub is None:
            x, y = point_mul(self._d, (GX, GY))
            self._pub = EllipticCurvePublicKey(x, y)
        return self._pub

    def sign(self, digest: bytes, alg=None) -> bytes:
        e = int.from_bytes(digest[:32], "big")
        d = self._d
        k = _rfc6979_k(d, e)
        while True:
            pt = point_mul(k, (GX, GY))
            r = pt[0] % N
            s = pow(k, -1, N) * (e + r * d) % N
            if r and s:
                return encode_dss_signature(r, s)
            k = (k + 1) % N or 1        # astronomically unlikely

    def private_bytes(self, *a, **kw):
        raise UnsupportedByFallback("PEM private-key serialization")


def generate_private_key(curve) -> EllipticCurvePrivateKey:
    if not isinstance(curve, SECP256R1):
        raise UnsupportedByFallback("non-P256 key generation")
    return EllipticCurvePrivateKey(secrets.randbelow(N - 1) + 1)


# namespace shims so sw.py's call sites read identically ---------------------

class _EcNamespace:
    SECP256R1 = SECP256R1
    SECP384R1 = SECP384R1
    ECDSA = ECDSA
    EllipticCurvePublicKey = EllipticCurvePublicKey
    EllipticCurvePrivateKey = EllipticCurvePrivateKey
    EllipticCurvePublicNumbers = EllipticCurvePublicNumbers
    generate_private_key = staticmethod(generate_private_key)


class _HashAlg:
    def __init__(self, name):
        self.name = name

    def __call__(self):
        return self


class _HashesNamespace:
    SHA256 = _HashAlg("sha256")
    SHA384 = _HashAlg("sha384")


class _Raiser:
    """Attribute/call sink that defers the failure to first use."""

    def __init__(self, what):
        self._what = what

    def __getattr__(self, name):
        return _Raiser(f"{self._what}.{name}")

    def __call__(self, *a, **kw):
        raise UnsupportedByFallback(self._what)


class _SerializationNamespace:
    class Encoding:
        X962 = "X962"
        PEM = "PEM"

    class PublicFormat:
        UncompressedPoint = "UncompressedPoint"
        SubjectPublicKeyInfo = "SubjectPublicKeyInfo"

    class PrivateFormat:
        PKCS8 = "PKCS8"

    NoEncryption = _Raiser("serialization.NoEncryption")
    load_pem_private_key = _Raiser("serialization.load_pem_private_key")
    load_pem_public_key = _Raiser("serialization.load_pem_public_key")


ec = _EcNamespace()
hashes = _HashesNamespace()
serialization = _SerializationNamespace()
Cipher = _Raiser("AES Cipher")
algorithms = _Raiser("AES algorithms")
modes = _Raiser("AES modes")
PKCS7 = _Raiser("PKCS7 padding")
