"""Vectorized DER signature decoding + batch byte marshalling.

The verify front-end used to decode every signature with a per-item
python DER parse (`decode_dss_signature`) and marshal digests/keys
one `np.frombuffer` at a time — at 2048 items per bucket that python
loop serialized the host against the device (BENCH_r05: the device sat
idle while the front-end marshalled).  This module replaces the loop
with whole-batch numpy:

* `pack_fixed`  — one `b"".join` + one `np.frombuffer` for all the
  fixed-width fields (digests, public keys), with a per-row length
  mask instead of per-item try/except.
* `decode_der_batch` — the ECDSA-Sig-Value DER grammar evaluated as
  array arithmetic over an (n, MAX_SIG) byte matrix: tag/length
  checks are boolean columns, the dynamic s-offset is a
  `take_along_axis` gather, and the r/s big-endian values land
  right-aligned in (n, 32) planes via one masked gather each.

Strictness matches the `cryptography` parser the per-item path used
(and the reference's low-S pipeline expects): short-form lengths only
(a valid P-256 ECDSA-Sig-Value body is <= 70 bytes, so a long-form
length is by definition non-minimal DER), minimal positive INTEGER
encodings, and exact trailing-length accounting.  Anything else marks
the row invalid — never an exception, batch-poisoning is not
acceptable on the commit path (bccsp/api.py verify_batch contract).

Pure numpy on purpose: the bench marshalling microbench and any
host-only caller can use it without touching jax.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# A valid P-256 ECDSA-Sig-Value is at most 2 + 2·(2 + 33) = 72 bytes;
# anything longer is invalid and only needs to be length-checked, so
# the staging matrix can stay fixed-width.
MAX_SIG = 80


def pack_fixed(vals: Sequence[bytes], width: int,
               rows: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Pack same-width byte strings into one (rows, width) uint8 matrix.

    Rows whose input is not exactly `width` bytes come back zeroed with
    ok=False (the old per-item loop's length check, batched).  `rows`
    pads the matrix up to a bucket size; `ok` is always (rows,).
    """
    n = len(vals)
    rows = max(rows, n)
    out = np.zeros((rows, width), np.uint8)
    ok = np.zeros(rows, bool)
    if n == 0:
        return out, ok
    # Fast path: all entries are bytes of the right width — one C-level
    # join, no per-item python.  Anything else (wrong width, None, str)
    # falls to the defensive pass where each bad entry marks ITS row
    # invalid; it must never raise and poison the other rows of a
    # coalesced batch (the old per-item loop's try/except, batched).
    try:
        lens = np.fromiter(map(len, vals), np.int32, n)
        if (lens == width).all():
            packed = np.frombuffer(b"".join(vals),
                                   np.uint8).reshape(n, width)
            if rows == n:
                ok[:] = True
                return packed, ok         # zero-copy (read-only) view
            out[:n] = packed
            ok[:n] = True
            return out, ok
    except TypeError:
        pass
    vals = [v if isinstance(v, (bytes, bytearray)) else b""
            for v in vals]
    ok[:n] = np.fromiter((len(v) == width for v in vals), bool, n)
    buf = b"".join(v if len(v) == width else b"\x00" * width
                   for v in vals)
    out[:n] = np.frombuffer(buf, np.uint8).reshape(n, width)
    return out, ok


def lt_bytes(a: np.ndarray, bound: bytes) -> np.ndarray:
    """Lexicographic a < bound over (..., 32) big-endian byte rows
    (numpy-only twin of ops/p256._lt_bytes, kept here so the marshal
    path has no jax dependency).  Words, not bytes: 32 big-endian
    bytes view as 4 big-endian u64 words, and the lexicographic
    compare cascades over 4 word lanes instead of 32 byte lanes."""
    a8 = np.ascontiguousarray(a).view(">u8")         # (..., 4)
    b8 = np.frombuffer(bound, ">u8")                 # (4,)
    lt = a8 < b8
    eq = a8 == b8
    out = lt[..., 3]
    for i in (2, 1, 0):
        out = lt[..., i] | (eq[..., i] & out)
    return out


def pack_messages(msgs: Sequence[bytes], rows: int = 0,
                  round_blocks_pow2: bool = False
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized FIPS 180-4 SHA-256 padding for a whole batch: the
    message lane of the fused hash->verify marshal (bccsp/tpu.
    marshal_items), replacing ops/sha256.pad_messages's per-item
    python loop with one flat scatter.

    Returns (words, nblocks, ok): (rows, max_blocks, 16) uint32
    big-endian message words padded within each message's own block
    count, the (rows,) int32 real-block counts, and the validity mask
    (non-bytes entries come back as zeroed one-block rows with
    ok=False — never an exception, same contract as pack_fixed).

    `round_blocks_pow2` rounds max_blocks up to a power of two so the
    set of compiled fused-program shapes stays logarithmic in message
    size (each distinct max_blocks mints one more XLA program —
    the same reason verify buckets are fixed).  Identical output to
    sha256.pad_messages on the unpadded prefix (differential-tested).
    """
    n = len(msgs)
    rows = max(rows, n)
    try:
        lens = np.fromiter(map(len, msgs), np.int64, n)
        joined = b"".join(msgs)
        ok = np.ones(rows, bool)
        ok[n:] = False
    except TypeError:
        # memoryview included: the fast path accepts it (len/join
        # both do), so the defensive path must too — a valid row's
        # verdict may not depend on an UNRELATED malformed row
        # flipping the batch onto this path
        ok = np.zeros(rows, bool)
        ok[:n] = [isinstance(v, (bytes, bytearray, memoryview))
                  for v in msgs]
        msgs = [v if isinstance(v, (bytes, bytearray, memoryview))
                else b"" for v in msgs]
        lens = np.fromiter(map(len, msgs), np.int64, n)
        joined = b"".join(msgs)
    nb32 = np.zeros(rows, np.int32)
    if n:
        nb = (lens + 8) // 64 + 1
        nb32[:n] = nb
    maxb = int(nb32.max()) if n else 1
    maxb = max(maxb, 1)
    if round_blocks_pow2:
        maxb = 1 << (maxb - 1).bit_length()
    buf = np.zeros((rows, maxb * 64), np.uint8)
    if n:
        flat = np.frombuffer(joined, np.uint8)
        starts = np.zeros(n, np.int64)
        np.cumsum(lens[:-1], out=starts[1:])
        rows_idx = np.repeat(np.arange(n), lens)
        cols_idx = np.arange(flat.size) - np.repeat(starts, lens)
        buf[rows_idx, cols_idx] = flat
        r = np.arange(n)
        buf[r, lens] = 0x80
        bitlen = (lens * 8).astype(np.uint64)
        end = (nb * 64).astype(np.int64)
        for b in range(8):
            buf[r, end - 8 + b] = (
                (bitlen >> np.uint64(8 * (7 - b))) & np.uint64(0xFF)
            ).astype(np.uint8)
    w = buf.reshape(rows, maxb, 16, 4)
    words = (w[..., 0].astype(np.uint32) << 24
             | w[..., 1].astype(np.uint32) << 16
             | w[..., 2].astype(np.uint32) << 8
             | w[..., 3].astype(np.uint32))
    return words, nb32, ok


def decode_der_batch(sigs: Sequence[bytes], rows: int = 0
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode ECDSA-Sig-Value DER for a whole batch at once.

    Returns (r, s, ok): (rows, 32) uint8 big-endian scalar planes and
    the (rows,) validity mask.  Invalid rows (bad grammar, non-minimal
    or oversized integers, trailing garbage) are zeroed with ok=False.
    """
    n = len(sigs)
    rows = max(rows, n)
    r_out = np.zeros((rows, 32), np.uint8)
    s_out = np.zeros((rows, 32), np.uint8)
    ok_out = np.zeros(rows, bool)
    if n == 0:
        return r_out, s_out, ok_out

    # non-bytes rows become invalid, never exceptions (see pack_fixed)
    try:
        lens = np.fromiter(map(len, sigs), np.int64, n)
        joined = b"".join(sigs)
    except TypeError:
        sigs = [x if isinstance(x, (bytes, bytearray)) else b""
                for x in sigs]
        lens = np.fromiter(map(len, sigs), np.int64, n)
        joined = b"".join(sigs)

    # The grammar only ever reads ~10 scalar columns and two 32-byte
    # windows per row, so gather those straight from the concatenated
    # byte string — no (n, MAX_SIG) staging matrix.  Gathered bytes
    # can cross into a NEIGHBORING row only at positions the length
    # accounting proves out-of-row; every such read feeds either a
    # check that then fails (ok=False) or a value the check structure
    # ignores (e.g. the second content byte of a 1-byte INTEGER), so
    # verdicts and extracted values never depend on neighbor bytes.
    flat = np.frombuffer(joined, np.uint8)
    if flat.size == 0 or flat.size > (1 << 31) - 64:
        return r_out, s_out, ok_out   # all-empty (or absurd) batch
    starts = np.zeros(n, np.int32)
    np.cumsum(lens[:-1], out=starts[1:], dtype=np.int32)
    top = np.int32(flat.size - 1)

    def cols(off, k):
        """(n, k) int32 bytes at per-row offsets off..off+k-1, ONE
        bounded fancy gather (np.take(mode="clip") is several times
        slower than minimum+fancy on this path, and per-column calls
        pay numpy dispatch k times over)."""
        idx = off[:, None] + np.arange(k, dtype=np.int32)
        return flat[np.minimum(idx, top)].astype(np.int32)

    # One gather for the fixed-offset header region: SEQUENCE tag+len,
    # r INTEGER tag+len and its first two content bytes.
    hdr = cols(starts, 6)
    seq_len, rlen = hdr[:, 1], hdr[:, 3]

    # SEQUENCE header: short-form length covering exactly the rest.
    ok = (lens >= 8) & (lens <= MAX_SIG)
    ok &= (hdr[:, 0] == 0x30) & (seq_len < 0x80) & (seq_len + 2 == lens)
    # r INTEGER at fixed offset 2.
    ok &= (hdr[:, 2] == 0x02) & (rlen >= 1) & (rlen <= 33)
    rlen_c = np.clip(rlen, 1, 33)

    # s INTEGER at the dynamic offset 4 + rlen: one gather for its
    # tag, length, and first two content bytes.
    s_hdr = 4 + rlen_c
    sh = cols(starts + s_hdr, 4)
    slen = sh[:, 1]
    ok &= (sh[:, 0] == 0x02) & (slen >= 1) & (slen <= 33)
    slen_c = np.clip(slen, 1, 33)
    # exact accounting: SEQUENCE body is the two INTEGER TLVs, nothing
    # after (trailing garbage is invalid DER).
    ok &= seq_len == 4 + rlen + slen

    def int_ok(c0, c1, length):
        """Minimal positive INTEGER content: no high bit on the lead
        byte, a 0x00 pad only when required, 33 bytes only as pad+32."""
        positive = (c0 & 0x80) == 0
        minimal = ~((c0 == 0) & (length > 1) & (c1 < 0x80))
        fits = (length < 33) | (c0 == 0)
        return positive & minimal & fits

    ok &= int_ok(hdr[:, 4], hdr[:, 5], rlen) \
        & int_ok(sh[:, 2], sh[:, 3], slen)

    # Both 32-byte value windows in ONE flat gather + ONE mask: the
    # right-aligned start skips a 33-byte content's 0x00 pad; the mask
    # zero-fills short contents on the left AND zeroes invalid rows
    # (so no half-decoded values leak).  Every unmasked position
    # provably lands inside its own row's content window (see the
    # cross-row note above), so the clip never matters for kept bytes.
    col32 = np.arange(32, dtype=np.int32)
    idx = np.empty((n, 64), np.int32)
    np.add((starts + 4 + rlen_c - 32)[:, None], col32, out=idx[:, :32])
    np.add((starts + s_hdr + 2 + slen_c - 32)[:, None], col32,
           out=idx[:, 32:])
    np.clip(idx, 0, top, out=idx)
    vals = flat[idx]
    valid = np.empty((n, 64), bool)
    np.greater_equal(col32, (32 - np.minimum(rlen_c, 32))[:, None],
                     out=valid[:, :32])
    np.greater_equal(col32, (32 - np.minimum(slen_c, 32))[:, None],
                     out=valid[:, 32:])
    valid &= ok[:, None]
    vals = np.where(valid, vals, 0)
    if rows == n:
        return (np.ascontiguousarray(vals[:, :32]),
                np.ascontiguousarray(vals[:, 32:]), ok)
    r_out[:n] = vals[:, :32]
    s_out[:n] = vals[:, 32:]
    ok_out[:n] = ok
    return r_out, s_out, ok_out


def decode_der_one(sig: bytes) -> Tuple[int, int]:
    """Single-signature convenience over the batch decoder (python
    ints out, ValueError on invalid DER) — keeps one grammar for both
    shapes so they cannot drift."""
    r, s, ok = decode_der_batch([sig])
    if not ok[0]:
        raise ValueError("invalid ECDSA-Sig-Value DER")
    return (int.from_bytes(r[0].tobytes(), "big"),
            int.from_bytes(s[0].tobytes(), "big"))
