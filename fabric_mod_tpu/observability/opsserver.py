"""Operations HTTP server: /metrics /healthz /logspec /version.

(reference: core/operations/system.go:60-270 — the ops listener every
node runs: prometheus scrape endpoint, health checker registry,
dynamic log levels, build info.)

stdlib http.server on a daemon thread; handlers read the same
in-process registries the node components write.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from fabric_mod_tpu.observability import logging as flog
from fabric_mod_tpu.observability.metrics import (
    MetricsProvider, default_provider)
from fabric_mod_tpu.concurrency.threads import RegisteredThread
from fabric_mod_tpu.concurrency.locks import RegisteredLock

VERSION = "0.3.0"


class HealthRegistry:
    """(reference: the healthz checker registry, system.go:141)"""

    def __init__(self):
        self._checkers: Dict[str, Callable[[], None]] = {}
        self._lock = RegisteredLock("observability.opsserver._lock")

    def register(self, name: str, checker: Callable[[], None]) -> None:
        with self._lock:
            self._checkers[name] = checker

    def unregister(self, name: str) -> None:
        with self._lock:
            self._checkers.pop(name, None)

    def status(self):
        failures = {}
        with self._lock:
            checkers = dict(self._checkers)
        for name, check in checkers.items():
            try:
                check()
            except Exception as e:
                failures[name] = str(e)
        return ("OK" if not failures else "Service Unavailable", failures)


_default_health: Optional[HealthRegistry] = None
_default_health_lock = RegisteredLock("observability.opsserver._default_health_lock")


def default_health() -> HealthRegistry:
    """The process-default checker registry.  Long-lived components
    (circuit breakers, commit pipelines, the soak heartbeat) register
    themselves here at construction, so any OperationsServer built
    without an explicit registry serves REAL health — the reference's
    pattern where subsystems feed the healthz registry the ops
    listener was built with."""
    global _default_health
    with _default_health_lock:
        if _default_health is None:
            _default_health = HealthRegistry()
        return _default_health


class OperationsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 provider: Optional[MetricsProvider] = None,
                 health: Optional[HealthRegistry] = None,
                 participation=None, tls: Optional[dict] = None):
        """`tls`: {"cert": path, "key": path, "client_ca": path?} —
        serves HTTPS; with client_ca set, clients must present a cert
        (the reference's operations TLS + clientAuthRequired,
        system.go:60-120).  The participation API mutates/destroys
        channel storage, so expose it off-loopback ONLY behind
        client-authenticated TLS."""
        self.provider = provider or default_provider()
        self.health = health or default_health()
        # orderer-only: the channel participation API rides the ops
        # listener (reference: restapi.go mounted on the admin server)
        self.participation = participation
        ops = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):     # quiet
                pass

            def _send(self, code: int, body: bytes,
                      ctype: str = "text/plain") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200,
                               ops.provider.render_prometheus().encode())
                elif self.path == "/healthz":
                    status, failures = ops.health.status()
                    code = 200 if status == "OK" else 503
                    self._send(code, json.dumps(
                        {"status": status,
                         "failed_checks": failures}).encode(),
                        "application/json")
                elif self.path == "/logspec":
                    self._send(200, json.dumps(
                        {"spec": flog.current_spec()}).encode(),
                        "application/json")
                elif self.path == "/version":
                    self._send(200, json.dumps(
                        {"Version": VERSION}).encode(),
                        "application/json")
                elif self.path == "/debug/threads":
                    # the goroutine-dump analog (reference:
                    # common/diag + SIGUSR1 handler)
                    from fabric_mod_tpu.observability.diag import (
                        dump_threads)
                    self._send(200, dump_threads().encode())
                elif self.path.startswith(("/debug/pprof",
                                           "/debug/profile")):
                    # sampling CPU profile, collapsed-stack text
                    # (reference: the pprof endpoints of the
                    # operations server); ?seconds=N bounds the run.
                    # /debug/profile is the documented alias — a
                    # wedged soak run is profiled over HTTP, no
                    # SIGUSR1 shell access needed.
                    from urllib.parse import parse_qs, urlparse
                    from fabric_mod_tpu.observability.diag import (
                        sample_profile)
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        secs = min(30.0, float(
                            (q.get("seconds") or ["5"])[0]))
                    except ValueError:
                        self._send(400, b"bad seconds parameter")
                        return
                    self._send(200, sample_profile(secs).encode())
                elif self.path.startswith("/trace"):
                    # recent finished spans (FMT_TRACE armed), newest
                    # last; ?trace_id= filters one stitched trace,
                    # ?limit= bounds the answer
                    from urllib.parse import parse_qs, urlparse
                    from fabric_mod_tpu.observability import tracing
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = int((q.get("limit") or ["512"])[0])
                    except ValueError:
                        limit = 512
                    tid = (q.get("trace_id") or [None])[0]
                    self._send(200, json.dumps(
                        {"armed": tracing.armed(),
                         "spans": tracing.recorder().recent_spans(
                             trace_id=tid, limit=limit)}).encode(),
                        "application/json")
                elif self.path == "/flight":
                    # the flight recorder: recent block timelines +
                    # events + auto-dumps + cumulative sub-stage totals
                    from fabric_mod_tpu.observability import tracing
                    self._send(200,
                               json.dumps(tracing.flight_dump()).encode(),
                               "application/json")
                elif self.path.startswith("/participation/"):
                    self._participation("GET")
                else:
                    self._send(404, b"not found")

            def _participation(self, method: str) -> None:
                if ops.participation is None:
                    self._send(404, b"not found")
                    return
                ln = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(ln) if ln else b""
                code, payload = ops.participation.handle(
                    method, self.path, body)
                self._send(code,
                           json.dumps(payload).encode()
                           if payload is not None else b"",
                           "application/json")

            def do_POST(self):
                if self.path.startswith("/participation/"):
                    self._participation("POST")
                else:
                    self._send(404, b"not found")

            def do_DELETE(self):
                if self.path.startswith("/participation/"):
                    self._participation("DELETE")
                else:
                    self._send(404, b"not found")

            def do_PUT(self):
                if self.path == "/logspec":
                    ln = int(self.headers.get("Content-Length", "0"))
                    try:
                        body = json.loads(self.rfile.read(ln) or b"{}")
                        flog.activate_spec(body.get("spec", "info"))
                        self._send(204, b"")
                    except Exception as e:
                        self._send(400, str(e).encode())
                else:
                    self._send(404, b"not found")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        if tls:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls["cert"], tls["key"])
            if tls.get("client_ca"):
                ctx.load_verify_locations(tls["client_ca"])
                ctx.verify_mode = ssl.CERT_REQUIRED
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True)
        self.addr = self._httpd.server_address
        self._thread = RegisteredThread(
            target=self._httpd.serve_forever, name="opsserver-http",
            structure="observability.opsserver")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
