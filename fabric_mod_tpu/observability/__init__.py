"""Observability: metrics, health, logging, tracing, ops HTTP server
(reference: common/metrics, common/flogging, core/operations; the
tracing/flight-recorder layer is this repo's Dapper-style addition —
observability/tracing.py)."""
from fabric_mod_tpu.observability.metrics import (      # noqa: F401
    Counter, Gauge, Histogram, MetricOpts, MetricsProvider,
    default_provider)
from fabric_mod_tpu.observability.logging import (      # noqa: F401
    activate_spec, get_logger, init_logging)
from fabric_mod_tpu.observability.opsserver import (    # noqa: F401
    HealthRegistry, OperationsServer, default_health)
from fabric_mod_tpu.observability import tracing        # noqa: F401
