"""Observability: metrics, health, logging, ops HTTP server
(reference: common/metrics, common/flogging, core/operations)."""
from fabric_mod_tpu.observability.metrics import (      # noqa: F401
    Counter, Gauge, Histogram, MetricOpts, MetricsProvider,
    default_provider)
from fabric_mod_tpu.observability.logging import (      # noqa: F401
    activate_spec, get_logger, init_logging)
from fabric_mod_tpu.observability.opsserver import (    # noqa: F401
    HealthRegistry, OperationsServer)
