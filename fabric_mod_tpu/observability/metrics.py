"""Metrics: counter/gauge/histogram provider abstraction.

(reference: common/metrics/provider.go — the Counter/Gauge/Histogram
option types every subsystem declares statically — with the prometheus
text exposition of core/operations/system.go:162-193 served by
observability/opsserver.py.)

One in-process provider (no statsd): metrics are plain objects with
atomic-enough updates under the GIL; `render_prometheus` emits the
text format scrapers read.  Subsystems declare their metrics up-front
(module-level *Opts constants) exactly like the reference, so a
gendoc-style inventory is greppable.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple
from fabric_mod_tpu.concurrency.locks import RegisteredLock


class MetricOpts:
    def __init__(self, namespace: str, subsystem: str, name: str,
                 help: str = "", label_names: Sequence[str] = ()):
        self.namespace = namespace
        self.subsystem = subsystem
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)

    @property
    def full_name(self) -> str:
        parts = [p for p in (self.namespace, self.subsystem, self.name) if p]
        return "_".join(parts)


class _Labeled:
    """Base: per-label-values child metrics."""

    def __init__(self, opts: MetricOpts):
        self.opts = opts
        self._children: Dict[Tuple[str, ...], "_Labeled"] = {}
        self._lock = threading.Lock()  # fmtlint: allow[locks] -- leaf lock on the per-sample with_labels path, never nested; C-level speed matters

    def with_labels(self, *values: str):
        if len(values) != len(self.opts.label_names):
            raise ValueError(
                f"{self.opts.full_name}: expected labels "
                f"{self.opts.label_names}, got {values}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = type(self)(self.opts)
                self._children[values] = child
            return child

    def _samples(self):
        """[(label_values, self)] for self + children."""
        out = []
        if not self.opts.label_names:
            out.append(((), self))
        with self._lock:
            out.extend((vals, ch) for vals, ch in self._children.items())
        return out


class Counter(_Labeled):
    def __init__(self, opts: MetricOpts):
        super().__init__(opts)
        self.value = 0.0

    def add(self, delta: float = 1.0) -> None:
        self.value += delta


class Gauge(_Labeled):
    def __init__(self, opts: MetricOpts):
        super().__init__(opts)
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = value

    def add(self, delta: float = 1.0) -> None:
        self.value += delta


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


class Histogram(_Labeled):
    def __init__(self, opts: MetricOpts,
                 buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(opts)
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def time(self):
        """Context manager observing elapsed seconds."""
        hist = self

        class _Timer:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self._t0)
                return False
        return _Timer()


class MetricsProvider:
    """Registry + factory (reference: metrics.Provider)."""

    def __init__(self):
        self._metrics: List[_Labeled] = []
        self._named: Dict[Tuple[type, str], _Labeled] = {}
        self._lock = RegisteredLock("observability.metrics._lock")

    def new_counter(self, opts: MetricOpts) -> Counter:
        return self._register(Counter(opts))

    def new_gauge(self, opts: MetricOpts) -> Gauge:
        return self._register(Gauge(opts))

    def new_histogram(self, opts: MetricOpts,
                      buckets: Sequence[float] = _DEFAULT_BUCKETS
                      ) -> Histogram:
        return self._register(Histogram(opts, buckets))

    # -- get-or-create by full name ---------------------------------------
    # For metrics declared by LIBRARY code that may instantiate many
    # times (e.g. the bccsp verdict cache): every instance shares one
    # registered metric instead of emitting duplicate exposition rows.

    def counter(self, opts: MetricOpts) -> Counter:
        return self._named_register(Counter, opts)

    def gauge(self, opts: MetricOpts) -> Gauge:
        return self._named_register(Gauge, opts)

    def histogram(self, opts: MetricOpts,
                  buckets: Sequence[float] = _DEFAULT_BUCKETS) -> Histogram:
        return self._named_register(Histogram, opts, buckets)

    def _named_register(self, kind, opts: MetricOpts, *extra):
        key = (kind, opts.full_name)
        with self._lock:
            got = self._named.get(key)
            if got is None:
                got = kind(opts, *extra)
                self._named[key] = got
                self._metrics.append(got)
            return got

    def _register(self, metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    # -- prometheus text exposition --------------------------------------
    def render_prometheus(self) -> str:
        out: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for metric in metrics:
            name = metric.opts.full_name
            kind = {"Counter": "counter", "Gauge": "gauge",
                    "Histogram": "histogram"}[type(metric).__name__]
            if metric.opts.help:
                out.append(f"# HELP {name} {metric.opts.help}")
            out.append(f"# TYPE {name} {kind}")
            for vals, child in metric._samples():
                lbl = ""
                if vals:
                    pairs = ",".join(
                        f'{k}="{v}"' for k, v in
                        zip(metric.opts.label_names, vals))
                    lbl = "{" + pairs + "}"
                if isinstance(child, Histogram):
                    cum = 0
                    for b, c in zip(child.buckets, child.counts):
                        cum += c
                        lb = (lbl[:-1] + "," if lbl else "{") + \
                            f'le="{b}"' + "}"
                        out.append(f"{name}_bucket{lb} {cum}")
                    cum += child.counts[-1]
                    lb = (lbl[:-1] + "," if lbl else "{") + 'le="+Inf"}'
                    out.append(f"{name}_bucket{lb} {cum}")
                    out.append(f"{name}_sum{lbl} {child.sum}")
                    out.append(f"{name}_count{lbl} {child.count}")
                else:
                    out.append(f"{name}{lbl} {child.value}")
        return "\n".join(out) + "\n"


_default_provider: Optional[MetricsProvider] = None
_default_lock = RegisteredLock("observability.metrics._default_lock")


def default_provider() -> MetricsProvider:
    global _default_provider
    with _default_lock:
        if _default_provider is None:
            _default_provider = MetricsProvider()
        return _default_provider
