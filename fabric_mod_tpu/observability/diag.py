"""Diagnostics: on-demand thread dumps.

(reference: common/diag/goroutine.go + internal/peer/node/signals.go —
SIGUSR1 logs every goroutine's stack on a running node.)
"""
from __future__ import annotations

import faulthandler
import io
import signal
import sys
import threading
import traceback


def dump_threads(file=None) -> str:
    """All thread stacks as text (and written to `file` if given)."""
    out = io.StringIO()
    frames = sys._current_frames()
    for thread in threading.enumerate():
        frame = frames.get(thread.ident)
        out.write(f"--- thread {thread.name} "
                  f"(daemon={thread.daemon})\n")
        if frame is not None:
            traceback.print_stack(frame, file=out)
    text = out.getvalue()
    if file is not None:
        file.write(text)
        file.flush()
    return text


def install_signal_dump(sig=signal.SIGUSR1) -> None:
    """SIGUSR1 -> thread stacks on stderr (reference: signals.go)."""
    faulthandler.register(sig, file=sys.stderr, all_threads=True)


def sample_profile(seconds: float = 5.0, hz: float = 100.0) -> str:
    """Sampling CPU profile: collapsed-stack text, one line per unique
    stack with its sample count — the flamegraph/pprof interchange
    format (reference: the /debug/pprof/profile endpoint the
    operations server mounts, core/middleware + go pprof).

    Pure-stdlib wall-sampler over sys._current_frames(); it observes
    every thread, costs one stack walk per thread per tick, and needs
    no native agent.  Blocking — callers run it from a request
    handler thread."""
    import time
    from collections import Counter

    interval = 1.0 / hz
    counts: Counter = Counter()
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    n_samples = 0
    while time.monotonic() < deadline:
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames.items():
            if ident == me:
                continue                   # not the profiler itself
            stack = []
            while frame is not None:
                code = frame.f_code
                stack.append(f"{code.co_name} "
                             f"({code.co_filename.rsplit('/', 1)[-1]}"
                             f":{frame.f_lineno})")
                frame = frame.f_back
            counts[(names.get(ident, str(ident)),
                    ";".join(reversed(stack)))] += 1
        n_samples += 1
        time.sleep(interval)
    out = io.StringIO()
    out.write(f"# wall-clock samples: {n_samples} at {hz:g} Hz over "
              f"{seconds:g}s; lines are collapsed stacks "
              f"(flamegraph.pl compatible)\n")
    for (tname, stack), n in counts.most_common():
        out.write(f"{tname};{stack} {n}\n")
    return out.getvalue()
