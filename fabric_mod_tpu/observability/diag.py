"""Diagnostics: on-demand thread dumps.

(reference: common/diag/goroutine.go + internal/peer/node/signals.go —
SIGUSR1 logs every goroutine's stack on a running node.)
"""
from __future__ import annotations

import faulthandler
import io
import signal
import sys
import threading
import traceback


def dump_threads(file=None) -> str:
    """All thread stacks as text (and written to `file` if given)."""
    out = io.StringIO()
    frames = sys._current_frames()
    for thread in threading.enumerate():
        frame = frames.get(thread.ident)
        out.write(f"--- thread {thread.name} "
                  f"(daemon={thread.daemon})\n")
        if frame is not None:
            traceback.print_stack(frame, file=out)
    text = out.getvalue()
    if file is not None:
        file.write(text)
        file.flush()
    return text


def install_signal_dump(sig=signal.SIGUSR1) -> None:
    """SIGUSR1 -> thread stacks on stderr (reference: signals.go)."""
    faulthandler.register(sig, file=sys.stderr, all_threads=True)
