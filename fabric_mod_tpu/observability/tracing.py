"""In-process distributed tracing + flight recorder: attribute every
commit-path millisecond.

(reference model: Dapper (Sigelman et al., 2010) — trace_id/span_id/
parent links with explicit context propagation across async seams —
applied the way FastFabric (Gorenflo et al., 2019) profiled Fabric's
commit path before optimizing it.  The reference repo ships the
metrics half of this layer (core/operations + common/diag,
reproduced in observability/metrics.py + opsserver.py); this module
is the missing tracing half.)

Three instruments, one arming gate (``FMT_TRACE``, the FMT_RACECHECK
/ FMT_FAULTS cost model — unset, every seam is one module-flag read
and NO span objects are allocated):

* **Spans** — ``with tracing.span("unpack", block=7):`` creates a
  Span (trace_id/span_id/parent) timed on the injectable clock,
  pushed on a thread-local stack so nested spans parent naturally.
  Explicit carriers cross threads (``current_ctx()`` → pass the
  TraceContext, ``span(name, parent=ctx)``) and processes
  (``inject()``/``extract()`` — a gRPC-metadata traceparent pair, the
  broadcast client/server carrier).  Finished spans land in a bounded
  ring served at ``/trace`` and feed per-name cumulative totals (the
  bench's stage-attribution source) plus the
  ``fabric_trace_substage_seconds`` histogram.

* **Block timelines** — the commit path opens one
  ``start_timeline(consumer, block_num)`` per block; every span that
  finishes while that timeline is installed (``timeline_scope``)
  becomes one of its sub-stage entries (recv, unpack, der_marshal,
  device_dispatch, verdict_await, policy_gather, policy_device,
  policy_finish, mvcc, ledger_write,
  fingerprint).  The timeline object itself is the cross-thread
  carrier: the commitpipe stage loop starts it, StagedBlock carries
  it, the commit loop resumes it — one per-block record of where the
  milliseconds went, in a bounded **flight recorder** ring served at
  ``/flight``.

* **Auto-dumps** — SoakError, a circuit-breaker open, and fault-seam
  fires snapshot the recorder (rate-limited) so a failure report
  carries the timeline of what the system was DOING, not just which
  invariant broke.

Plus the device lens: ``export_chrome_trace()`` writes the span ring
as Chrome trace-event JSON (Perfetto-loadable; device dispatches as
async slices), ``install_compile_counter()`` counts XLA
compiles/retraces into ``fabric_tpu_compiles_total``, and
``FMT_TRACE_JAX_PROFILE=<dir>`` arms a one-shot ``jax.profiler``
capture window around a device batch dispatch.
"""
from __future__ import annotations

import collections
import contextlib
import functools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.utils import knobs
from fabric_mod_tpu.concurrency.locks import RegisteredLock

# -- the arming gate (mirrors concurrency.core / faults.core) ---------------

_enabled = knobs.get_bool("FMT_TRACE")


def armed() -> bool:
    return _enabled


def enable(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


@contextlib.contextmanager
def active(on: bool = True):
    """Scoped arming — tests and the bench's traced arms."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    try:
        yield
    finally:
        _enabled = prev


# -- clock (injectable: tests drive a ManualClock through spans) ------------

_clock = time.time


def set_clock(fn) -> None:
    """``fn() -> float`` seconds; pass ``time.time`` to restore."""
    global _clock
    _clock = fn


# -- ring bounds ------------------------------------------------------------

def _ring(env: str) -> int:
    return max(8, knobs.get_int(env))


SPAN_RING = _ring("FMT_TRACE_SPANS")
FLIGHT_RING = _ring("FMT_TRACE_RING")

_SUBSTAGE_OPTS = MetricOpts(
    "fabric", "trace", "substage_seconds",
    help="Per-span wall seconds by sub-stage name (the commit "
         "timeline's recv/unpack/der_marshal/device_dispatch/"
         "verdict_await/policy_*/mvcc/ledger_write/fingerprint "
         "split, FMT_TRACE armed only).",
    label_names=("stage",))
_COMPILES_OPTS = MetricOpts(
    "fabric", "tpu", "compiles_total",
    help="XLA compiles/retraces observed via jax.monitoring (0 until "
         "install_compile_counter() ran; a climbing value mid-steady-"
         "state means shapes are churning and dispatches re-trace).")


@functools.lru_cache(maxsize=None)
def _substage_hist():
    return default_provider().histogram(
        _SUBSTAGE_OPTS, buckets=(0.0005, 0.002, 0.01, 0.05, 0.25,
                                 1.0, 5.0, 30.0))


@functools.lru_cache(maxsize=None)
def _compiles_counter():
    return default_provider().counter(_COMPILES_OPTS)


# -- context ---------------------------------------------------------------

class TraceContext(collections.namedtuple("TraceContext",
                                          ("trace_id", "span_id"))):
    """The minimal propagated identity: what a child span needs to
    link itself under a parent across any seam."""
    __slots__ = ()


_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def new_trace_id() -> str:
    return os.urandom(8).hex()


def current_ctx() -> Optional[TraceContext]:
    """This thread's innermost live span as a carrier, or None."""
    st = getattr(_tls, "stack", None)
    if not st:
        return None
    sp = st[-1]
    return TraceContext(sp.trace_id, sp.span_id)


# gRPC metadata carrier (lowercase key per gRPC metadata rules)
TRACE_METADATA_KEY = "fmt-trace-context"


def inject(ctx: Optional[TraceContext] = None
           ) -> Optional[List[Tuple[str, str]]]:
    """Serialize a context as gRPC metadata; None when unarmed or no
    context is live (callers pass the result straight through —
    ``metadata=None`` is gRPC's no-metadata)."""
    if not _enabled:
        return None
    if ctx is None:
        ctx = current_ctx()
    if ctx is None:
        return None
    return [(TRACE_METADATA_KEY, f"{ctx.trace_id}-{ctx.span_id}")]


def extract(metadata) -> Optional[TraceContext]:
    """Parse the carrier out of gRPC invocation metadata (any iterable
    of (key, value)); malformed/absent → None, never a raise — a bad
    header must not fail the RPC it rode in on."""
    if not metadata:
        return None
    try:
        for key, value in metadata:
            if key == TRACE_METADATA_KEY:
                tid, _, sid = str(value).partition("-")
                if tid and sid:
                    return TraceContext(tid, sid)
    except Exception:
        return None
    return None


# -- spans -------------------------------------------------------------------

class Span:
    """One timed operation.  Context manager; on exit it pops the TLS
    stack, lands in the recorder ring + totals, and — when a block
    timeline is installed on this thread — becomes one of that
    timeline's sub-stage entries."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "ts",
                 "dur", "attrs", "thread")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.thread = threading.current_thread().name
        self.ts = 0.0
        self.dur = 0.0

    @property
    def ctx(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self.ts = _clock()
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.dur = max(0.0, _clock() - self.ts)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        st = getattr(_tls, "stack", None)
        if st and st[-1] is self:
            st.pop()
        tl = getattr(_tls, "timeline", None)
        if tl is not None:
            tl.add(self.name, self.ts, self.dur)
        _recorder.add_span(self)
        return False

    def to_dict(self) -> Dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "ts": self.ts, "dur": round(self.dur, 6),
                "thread": self.thread, "attrs": self.attrs}


class _NoopSpan:
    """The unarmed singleton: every method a no-op, every entry
    returns itself.  ``span()`` returns THIS object (never a fresh
    allocation) when FMT_TRACE is unset — the zero-allocation
    contract the differential test pins."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


def span(name: str, parent=None, **attrs):
    """Open a span.  `parent` may be a TraceContext, a Span, or None
    (None: the thread's current span, else a fresh trace).  Unarmed:
    returns the no-op singleton — no allocation, no clock read."""
    if not _enabled:
        return _NOOP
    if parent is None:
        parent = current_ctx()
    if parent is None:
        trace_id, parent_id = new_trace_id(), None
    else:
        trace_id, parent_id = parent.trace_id, parent.span_id
    return Span(name, trace_id, os.urandom(4).hex(), parent_id, attrs)


# -- block timelines (the flight recorder's unit) ---------------------------

class BlockTimeline:
    """One block's commit-path timeline: every sub-stage span that ran
    while this timeline was installed.  Created by the commit engine
    on the stage side, carried by StagedBlock across the stage→commit
    handoff, finished after the ledger write — the cross-thread trace
    of exactly one block."""

    __slots__ = ("consumer", "block_num", "trace_id", "ts", "dur",
                 "subs", "_done")

    def __init__(self, consumer: str, block_num: int, trace_id: str):
        self.consumer = consumer
        self.block_num = block_num
        self.trace_id = trace_id
        self.ts = _clock()
        self.dur = 0.0
        self.subs: List[Tuple[str, float, float]] = []
        self._done = False

    def add(self, name: str, ts: float, dur: float) -> None:
        self.subs.append((name, ts, dur))

    def to_dict(self) -> Dict:
        return {"consumer": self.consumer, "block": self.block_num,
                "trace_id": self.trace_id, "ts": self.ts,
                "dur": round(self.dur, 6),
                "subs": [{"name": n, "ts": t, "dur": round(d, 6)}
                         for n, t, d in self.subs]}


def start_timeline(consumer: str, block_num: int,
                   parent: Optional[TraceContext] = None
                   ) -> Optional[BlockTimeline]:
    if not _enabled:
        return None
    return BlockTimeline(
        consumer, block_num,
        parent.trace_id if parent is not None else new_trace_id())


@contextlib.contextmanager
def timeline_scope(tl: Optional[BlockTimeline]):
    """Install `tl` as this thread's active timeline (None: no-op).
    Spans finishing inside the scope become its sub-stage entries."""
    if tl is None:
        yield None
        return
    prev = getattr(_tls, "timeline", None)
    _tls.timeline = tl
    try:
        yield tl
    finally:
        _tls.timeline = prev


def finish_timeline(tl: Optional[BlockTimeline]) -> None:
    """Close the timeline and push it into the flight-recorder ring
    (idempotent — engine error paths may finish defensively)."""
    if tl is None or tl._done:
        return
    tl._done = True
    tl.dur = max(0.0, _clock() - tl.ts)
    _recorder.add_timeline(tl)


# -- the recorder ------------------------------------------------------------

class Recorder:
    """Bounded rings of recent spans / block timelines / events, the
    cumulative per-name totals (bench stage attribution), and the
    auto-dump snapshots.  One process-wide instance; every access is
    lock-serialized and cheap (deque appends)."""

    _DUMP_MIN_INTERVAL_S = 5.0

    def __init__(self):
        self._lock = RegisteredLock("observability.tracing._lock")
        self._spans: collections.deque = collections.deque(
            maxlen=SPAN_RING)
        self._timelines: collections.deque = collections.deque(
            maxlen=FLIGHT_RING)
        self._events: collections.deque = collections.deque(maxlen=256)
        self._dumps: collections.deque = collections.deque(maxlen=8)
        self._totals: Dict[str, List[float]] = {}   # name -> [secs, n]
        self._last_dump = 0.0

    def add_span(self, sp: Span) -> None:
        with self._lock:
            self._spans.append(sp.to_dict())
            tot = self._totals.get(sp.name)
            if tot is None:
                tot = self._totals[sp.name] = [0.0, 0]
            tot[0] += sp.dur
            tot[1] += 1
        _substage_hist().with_labels(sp.name).observe(sp.dur)

    def add_timeline(self, tl: BlockTimeline) -> None:
        with self._lock:
            self._timelines.append(tl.to_dict())

    def note_event(self, kind: str, detail: str) -> None:
        with self._lock:
            self._events.append(
                {"ts": _clock(), "kind": kind, "detail": detail})

    # -- read surface ------------------------------------------------------
    def recent_spans(self, trace_id: Optional[str] = None,
                     limit: int = 512) -> List[Dict]:
        with self._lock:
            out = list(self._spans)
        if trace_id is not None:
            out = [s for s in out if s["trace_id"] == trace_id]
        return out[-limit:]

    def timelines(self, limit: int = FLIGHT_RING) -> List[Dict]:
        with self._lock:
            return list(self._timelines)[-limit:]

    def events(self, limit: int = 256) -> List[Dict]:
        with self._lock:
            return list(self._events)[-limit:]

    def totals(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {name: {"secs": round(t[0], 6), "count": int(t[1])}
                    for name, t in self._totals.items()}

    def dumps(self) -> List[Dict]:
        with self._lock:
            return list(self._dumps)

    def span_count(self) -> int:
        with self._lock:
            return len(self._spans)

    def timeline_count(self) -> int:
        with self._lock:
            return len(self._timelines)

    def reset(self) -> None:
        """Clear everything (bench attribution windows, tests)."""
        with self._lock:
            self._spans.clear()
            self._timelines.clear()
            self._events.clear()
            self._dumps.clear()
            self._totals.clear()
            self._last_dump = 0.0

    # -- auto-dump ---------------------------------------------------------
    def auto_dump(self, reason: str) -> Optional[Dict]:
        """Snapshot the recorder on a failure signal (SoakError,
        breaker open, fault fire).  Rate-limited: a fault storm must
        not turn the recorder into its own hot path.  The "dump"
        event is appended only when a snapshot was actually taken —
        the tape must not claim dumps the limiter suppressed (nor let
        phantom entries evict the fault/shed breadcrumbs)."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump < self._DUMP_MIN_INTERVAL_S \
                    and self._dumps:
                return None
            self._last_dump = now
            snap = {"reason": reason, "ts": _clock(),
                    "timelines": list(self._timelines)[-32:],
                    "events": list(self._events)[-64:]}
            self._dumps.append(snap)
            self._events.append(
                {"ts": _clock(), "kind": "dump", "detail": reason})
        return snap


_recorder = Recorder()


def recorder() -> Recorder:
    return _recorder


def note_event(kind: str, detail: str) -> None:
    """Record a one-line event into the flight recorder (armed only —
    unarmed this is one flag read)."""
    if _enabled:
        _recorder.note_event(kind, detail)


def auto_dump(reason: str) -> None:
    if _enabled:
        _recorder.auto_dump(reason)


def flight_text(limit: int = 8) -> str:
    """Compact flight-recorder tail for attaching to error text
    (SoakError's replay block): the last `limit` block timelines, one
    line each, plus recent events."""
    lines = [f"flight recorder (last {limit} block timelines):"]
    for tl in _recorder.timelines()[-limit:]:
        subs = " ".join(f"{s['name']}={s['dur'] * 1000:.1f}ms"
                        for s in tl["subs"])
        lines.append(
            f"  [{tl['consumer']}] block {tl['block']} "
            f"trace {tl['trace_id']} dur {tl['dur'] * 1000:.1f}ms: "
            f"{subs or '(no sub-spans)'}")
    ev = _recorder.events()[-limit:]
    if ev:
        lines.append("recent events: " + "; ".join(
            f"{e['kind']}:{e['detail']}" for e in ev))
    return "\n".join(lines)


def flight_dump() -> Dict:
    """The /flight payload: ring + events + auto-dumps + totals."""
    return {"armed": _enabled,
            "timelines": _recorder.timelines(),
            "events": _recorder.events(),
            "dumps": _recorder.dumps(),
            "totals": _recorder.totals()}


def substage_totals() -> Dict[str, Dict[str, float]]:
    return _recorder.totals()


# -- Chrome trace-event export (Perfetto-loadable) --------------------------

def export_chrome_trace(path: str) -> int:
    """Write the span ring as Chrome trace-event JSON: one complete
    ("X") event per span (ts/dur in µs), device dispatches ALSO as
    async ("b"/"e") slices so the device lane reads as its own track
    in Perfetto.  Returns the number of events written."""
    pid = os.getpid()
    events: List[Dict] = [{
        "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
        "args": {"name": "fabric_mod_tpu"}}]
    tids: Dict[str, int] = {}
    for sp in _recorder.recent_spans(limit=SPAN_RING):
        tid = tids.setdefault(sp["thread"], len(tids) + 1)
        events.append({
            "ph": "X", "pid": pid, "tid": tid, "name": sp["name"],
            "cat": "span", "ts": round(sp["ts"] * 1e6, 1),
            "dur": round(sp["dur"] * 1e6, 1),
            "args": {"trace_id": sp["trace_id"],
                     "span_id": sp["span_id"],
                     "parent_id": sp["parent_id"], **sp["attrs"]}})
        if sp["name"] == "device_dispatch":
            ts = round(sp["ts"] * 1e6, 1)
            common = {"pid": pid, "tid": tid, "cat": "device",
                      "name": "device_batch", "id": sp["span_id"]}
            events.append({"ph": "b", "ts": ts, **common})
            events.append({
                "ph": "e", "ts": round((sp["ts"] + sp["dur"]) * 1e6, 1),
                **common})
    for name, tid in tids.items():
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": name}})
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms",
                   "otherData": {"xla_compiles": _compile_count,
                                 "substage_totals": substage_totals()}},
                  f)
    return len(events)


# -- device lens: compile counter + one-shot jax.profiler window ------------

_compile_lock = RegisteredLock("observability.tracing._compile_lock")
_compile_installed = False
_compile_count = 0


def install_compile_counter() -> bool:
    """Count XLA compiles/retraces into fabric_tpu_compiles_total via
    jax.monitoring event listeners.  Best-effort and idempotent: the
    listener API varies across jax versions, so failure to install
    just leaves the counter at 0 (never an import error on the
    commit path)."""
    global _compile_installed
    with _compile_lock:
        if _compile_installed:
            return True

        def _on_event(event: str, *a, **kw) -> None:
            global _compile_count
            if "compile" in event or "trace" in event:
                # concurrent dispatch threads compile concurrently:
                # the read-modify-write needs the lock or retraces
                # undercount — the exact shape-churn signal this
                # counter exists to surface
                with _compile_lock:
                    _compile_count += 1
                _compiles_counter().add(1)

        try:
            import jax
            jax.monitoring.register_event_listener(_on_event)
            _compile_installed = True
        except Exception:
            return False
    return True


def compile_count() -> int:
    return _compile_count


def jax_profile_dir() -> Optional[str]:
    """FMT_TRACE_JAX_PROFILE=<dir>: arm a ONE-SHOT jax.profiler
    capture window around a device batch dispatch (the tpu_watcher
    matrix sets it so the first hardware run leaves a real device
    profile behind)."""
    got = knobs.get_str("FMT_TRACE_JAX_PROFILE")
    return got or None


_profile_lock = RegisteredLock("observability.tracing._profile_lock")
_profile_taken = False


def device_profile_capture():
    """The one-shot capture window: a jax.profiler.trace context
    manager on the FIRST call after arming (FMT_TRACE set + the
    profile dir knob), else None.  Callers resolve the dispatch
    INSIDE the window so the profile actually contains device
    execution, not just the host-side enqueue."""
    global _profile_taken
    if not _enabled:
        return None
    out_dir = jax_profile_dir()
    if out_dir is None:
        return None
    with _profile_lock:
        if _profile_taken:
            return None
        _profile_taken = True
    try:
        import jax
        os.makedirs(out_dir, exist_ok=True)
        note_event("jax_profile", out_dir)
        return jax.profiler.trace(out_dir)
    except Exception:
        return None
