"""Hierarchical logging with runtime level specs.

(reference: common/flogging — zap-wrapper with per-logger level specs
(`loggerlevels.go:174` ActivateSpec parsing "gossip=debug:info"), the
observer hook feeding log-count metrics, and the /logspec HTTP admin
endpoint served by opsserver.py.)

Built over stdlib logging: `get_logger("peer.validator")` returns a
namespaced logger under the "fabric_mod_tpu" root; `activate_spec`
applies "name=level[:name2=level2]:default" at runtime.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from fabric_mod_tpu.observability.metrics import (
    MetricOpts, MetricsProvider)
from fabric_mod_tpu.concurrency.locks import RegisteredLock

ROOT = "fabric_mod_tpu"

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warn": logging.WARNING, "warning": logging.WARNING,
           "error": logging.ERROR, "fatal": logging.CRITICAL,
           "panic": logging.CRITICAL}

_spec_lock = RegisteredLock("observability.logging._spec_lock")
_current_spec = "info"


class _CountingHandler(logging.Handler):
    """The flogging observer: counts emitted records per level."""

    def __init__(self, provider: MetricsProvider):
        super().__init__(level=logging.DEBUG)
        self._counter = provider.new_counter(MetricOpts(
            "logging", "", "entries_total",
            "Number of log entries emitted", ("level",)))

    def emit(self, record: logging.LogRecord) -> None:
        self._counter.with_labels(record.levelname.lower()).add()


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"{ROOT}.{name}" if name else ROOT)


def init_logging(provider: Optional[MetricsProvider] = None,
                 spec: str = "info") -> None:
    root = logging.getLogger(ROOT)
    if not any(isinstance(h, logging.StreamHandler) for h in root.handlers):
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).4s [%(name)s] %(message)s"))
        root.addHandler(h)
    if provider is not None and not any(
            isinstance(h, _CountingHandler) for h in root.handlers):
        root.addHandler(_CountingHandler(provider))
    activate_spec(spec)


def activate_spec(spec: str) -> None:
    """Apply a level spec: "debug", "peer=debug:info",
    "gossip=warn:ledger=debug:info" (reference: ActivateSpec)."""
    global _current_spec
    default = logging.INFO
    overrides: Dict[str, int] = {}
    for part in spec.split(":"):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, lvl = part.partition("=")
            if lvl.lower() not in _LEVELS:
                raise ValueError(f"unknown level {lvl!r}")
            overrides[name.strip()] = _LEVELS[lvl.lower()]
        else:
            if part.lower() not in _LEVELS:
                raise ValueError(f"unknown level {part!r}")
            default = _LEVELS[part.lower()]
    with _spec_lock:
        logging.getLogger(ROOT).setLevel(default)
        # reset previously-overridden loggers to inherit
        for name, logger in list(logging.Logger.manager.loggerDict.items()):
            if isinstance(logger, logging.Logger) and \
                    name.startswith(ROOT + "."):
                logger.setLevel(logging.NOTSET)
        for name, lvl in overrides.items():
            logging.getLogger(f"{ROOT}.{name}").setLevel(lvl)
        _current_spec = spec


def current_spec() -> str:
    with _spec_lock:
        return _current_spec
