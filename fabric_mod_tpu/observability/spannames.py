"""The span-name registry: every tracing span, declared here.

Span names are the join key of the whole observability layer — the
per-block timelines, the ``fabric_trace_substage_seconds{stage}``
metric, the bench sub-span attribution that must explain the engine's
stage/await/commit buckets, and the Perfetto export all select spans
BY NAME.  A typo'd name in a new ``tracing.span("...")`` call would
silently fall out of every one of those views; the fmtlint
``span-names`` rule requires each literal to be declared here (and
each declaration to be used by a production seam), so the set of
stages is a reviewed, documented surface instead of an accident of
string literals.
"""
from __future__ import annotations

from typing import Set

# Keep sorted; the lint rule cross-checks both directions.
DECLARED_SPANS: Set[str] = {
    "body_decode",
    "broadcast.handle",
    "broadcast.stage",
    "broadcast.submit",
    "der_marshal",
    "device_dispatch",
    "fanout.materialize",
    "fingerprint",
    "gossip.drain",
    "ledger_write",
    "mvcc",
    "mvcc_vector",
    "policy_device",
    "policy_finish",
    "policy_gather",
    "raft.replicate",
    "recv",
    "relay.push",
    "relay.repair",
    "shard.dispatch",
    "unpack",
    "verdict_await",
    "verify.flush",
    "verify.resolve",
    "wal.sync",
}


def is_declared(name: str) -> bool:
    return name in DECLARED_SPANS
