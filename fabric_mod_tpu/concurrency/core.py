"""The arming gate + shared per-thread lock bookkeeping.

``FMT_RACECHECK`` (any value but ""/"0") arms every guard in the
package at import time; ``enable()``/``armed()`` flip it at runtime
(the canary tests prove each guard raises when armed and is silent
when off).  The held-lock stack is shared between ``OrderedLock`` and
``RegisteredLock`` so ordering edges are observed across BOTH kinds —
an inversion between a ranked ledger lock and a rank-less gossip lock
is still a cycle.
"""
from __future__ import annotations

import contextlib
import threading

from fabric_mod_tpu.utils import knobs


class RaceError(AssertionError):
    """A detected race/ordering violation (AssertionError so test
    frameworks treat it as a hard failure, never a skip)."""


_enabled = knobs.get_bool("FMT_RACECHECK")


def enabled() -> bool:
    """Whether the FMT_RACECHECK guards are armed."""
    return _enabled


def enable(on: bool) -> None:
    """Arm/disarm at runtime (tests; production uses the env var)."""
    global _enabled
    _enabled = bool(on)


@contextlib.contextmanager
def armed(on: bool = True):
    """Scoped enable/disable — the canary tests' toggle."""
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    try:
        yield
    finally:
        _enabled = prev


_tls = threading.local()


def held_locks() -> list:
    """This thread's stack of (rank_or_None, lock) acquisitions."""
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h
