"""Thread-ownership guards: whole-structure and field-level.

``ThreadOwnership`` (from the original utils/racecheck.py) pins a
whole structure to its FSM/worker thread.  ``OwnedState`` is the
field-level generalization the retrofits need: a small bag of fields
whose WRITES are pinned to one owning thread while reads stay open
(single-writer/multi-reader is the actual contract of the pipeline
timing counters, the puller's chain cursor, the election verdict) —
plus ``claim()``/``release()`` for scoped exclusivity, so "two
concurrent run() loops on one client" is a detected race instead of
silent double-submission.
"""
from __future__ import annotations

import threading
from typing import Optional

from fabric_mod_tpu.concurrency.core import RaceError, enabled


class ThreadOwnership:
    """Pins a structure to one owning thread.  `claim()` binds the
    current thread (the FSM/worker thread at startup); `guard()`
    raises when any OTHER thread enters a guarded section.  The
    raft FSM's whole design contract — all state transitions on the
    FSM thread (chain.go:533's single-threaded run loop) — becomes
    machine-checked instead of a docstring.

    Always armed once claimed (it predates the FMT_RACECHECK gate and
    production raft runs it live); `live_only=True` relaxes guard()
    to pass when the claimed owner thread has terminated — the
    teardown-then-reuse pattern of the pooled structures."""

    def __init__(self, name: str = "structure", live_only: bool = False):
        self.name = name
        self._owner: Optional[int] = None
        self._owner_thread: Optional[threading.Thread] = None
        self._live_only = live_only

    def claim(self) -> None:
        self._owner = threading.get_ident()
        self._owner_thread = threading.current_thread()

    def guard(self) -> None:
        if self._owner is None:
            return                        # not yet claimed (startup)
        me = threading.get_ident()
        if me != self._owner:
            if self._live_only and self._owner_thread is not None \
                    and not self._owner_thread.is_alive():
                return                    # owner terminated: handoff
            raise RaceError(
                f"thread-ownership violation: {self.name} touched "
                f"from thread {me}, owned by {self._owner}")


class OwnedState:
    """Field bag with single-writer thread ownership.

    Construct with the initial fields (``OwnedState("name", x=0)``) —
    construction does NOT claim ownership (builders routinely init on
    the caller thread and hand the state to a worker).  With the
    guards armed, the first post-construction write claims the writing
    thread; any later write from a different LIVE thread raises.
    Reads are deliberately unguarded: the retrofitted fields are
    monotonic counters/cursors whose cross-thread reads are benign,
    and guarding them would outlaw the metrics/bench surfaces.

    ``claim()``/``release()`` pin explicitly for scoped exclusivity
    (a second concurrent claim from a live thread raises — the
    double-run detector).
    """

    _INTERNAL = ("_os_name", "_os_owner", "_os_lock")

    def __init__(self, name: str, **fields):
        object.__setattr__(self, "_os_name", name)
        object.__setattr__(self, "_os_owner", None)
        # serializes check-then-adopt: without it two threads racing
        # claim() (or two first writes) could BOTH pass the owner
        # check — the detector missing exactly the concurrent entry
        # it exists to catch.  Armed-path only; disarmed claims skip it
        object.__setattr__(self, "_os_lock", threading.Lock())
        for k, v in fields.items():
            object.__setattr__(self, k, v)

    # -- explicit scope ----------------------------------------------------
    def claim(self) -> None:
        if enabled():
            with self._os_lock:
                self._check_claim()
                object.__setattr__(self, "_os_owner",
                                   threading.current_thread())
            return
        object.__setattr__(self, "_os_owner",
                           threading.current_thread())

    def release(self) -> None:
        object.__setattr__(self, "_os_owner", None)

    def _check_claim(self) -> None:
        owner = self._os_owner
        me = threading.current_thread()
        if owner is not None and owner is not me and owner.is_alive():
            raise RaceError(
                f"concurrent ownership of {self._os_name}: thread "
                f"{me.name!r} claiming while live thread "
                f"{owner.name!r} still owns it")

    # -- guarded writes ----------------------------------------------------
    def __setattr__(self, key, value):
        if key in self._INTERNAL:
            object.__setattr__(self, key, value)
            return
        if enabled():
            me = threading.current_thread()
            with self._os_lock:
                owner = self._os_owner
                if owner is me:
                    pass
                elif owner is None or not owner.is_alive():
                    object.__setattr__(self, "_os_owner", me)
                else:
                    raise RaceError(
                        f"field-ownership violation on "
                        f"{self._os_name}.{key}: written from thread "
                        f"{me.name!r}, owned by live thread "
                        f"{owner.name!r}")
        object.__setattr__(self, key, value)
