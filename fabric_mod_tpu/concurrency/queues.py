"""GuardedQueue: queue.Queue with asserted side ownership.

The threaded structures here are almost all staged pipelines whose
queues have exactly one legal consumer (a worker/sender/resolver
thread) and either one or many legal producers.  That contract is
what makes their lock-free field access safe — and it lives in
docstrings until something violates it.  GuardedQueue makes it
machine-checked: with FMT_RACECHECK armed, a ``get`` from a thread
other than the owning consumer (or a ``put`` from a second producer
on a single-producer queue) raises RaceError at the call site.

Ownership binds on first use and transfers only from a DEAD thread:
``close()`` paths that join the worker and then drain stragglers from
the caller are legal (the join is the happens-before edge, FastTrack
style); a live worker being bypassed is exactly the race the guard
exists to catch.
"""
from __future__ import annotations

import queue
import threading
from typing import Optional

from fabric_mod_tpu.concurrency.core import RaceError, enabled


class _SideOwner:
    """One side's (producer/consumer) thread pin."""

    __slots__ = ("queue_name", "role", "_owner", "_lock")

    def __init__(self, queue_name: str, role: str):
        self.queue_name = queue_name
        self.role = role
        self._owner: Optional[threading.Thread] = None
        # serializes check-then-adopt: two racing first-time callers
        # must not BOTH adopt the side — that concurrent entry is the
        # race the guard exists to catch.  Callers gate check() on
        # enabled(), so this lock costs nothing disarmed
        self._lock = threading.Lock()

    def check(self) -> None:
        me = threading.current_thread()
        with self._lock:
            owner = self._owner
            if owner is me:
                return
            if owner is None or not owner.is_alive():
                # unbound, or the old owner terminated: adopt (thread
                # teardown/join is the happens-before edge)
                self._owner = me
                return
        raise RaceError(
            f"{self.role}-side ownership violation on queue "
            f"'{self.queue_name}': touched from thread {me.name!r} "
            f"while owned by live thread {owner.name!r} — this queue "
            f"has a single legal {self.role}")

    def release(self) -> None:
        self._owner = None


class GuardedQueue:
    """queue.Queue with pinned consumer (and optional producer) side.

    `single_producer=True` additionally pins the put side to one
    thread.  The stdlib surface is preserved (put/get/*_nowait/empty/
    qsize) so it drops into every pipeline queue unchanged; with the
    guards off the overhead is one module-flag read per call.
    """

    def __init__(self, maxsize: int = 0, *, name: str,
                 single_producer: bool = False):
        self.name = name
        self._q: "queue.Queue" = queue.Queue(maxsize)
        self._consumer = _SideOwner(name, "consumer")
        self._producer = (_SideOwner(name, "producer")
                          if single_producer else None)

    # -- producer side -----------------------------------------------------
    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if enabled() and self._producer is not None:
            self._producer.check()
        self._q.put(item, block, timeout)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    # -- consumer side -----------------------------------------------------
    def get(self, block: bool = True,
            timeout: Optional[float] = None):
        if enabled():
            self._consumer.check()
        return self._q.get(block, timeout)

    def get_nowait(self):
        return self.get(block=False)

    # -- passthrough -------------------------------------------------------
    def empty(self) -> bool:
        return self._q.empty()

    def qsize(self) -> int:
        return self._q.qsize()

    def release_consumer(self) -> None:
        """Explicit ownership handoff (rare; prefer letting the old
        consumer thread terminate)."""
        self._consumer.release()
