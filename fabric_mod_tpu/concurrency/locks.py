"""Lock hierarchy + the process-wide lock-order registry.

Two detectors over one shared held-stack:

* ``OrderedLock`` — the static hierarchy from the original
  utils/racecheck.py: ranks must strictly increase down the stack.
  Always on (cheap enough for production commit paths).
* ``LockOrderRegistry`` + ``RegisteredLock`` — the dynamic detector
  for locks without a natural global rank: every observed acquisition
  "A held while acquiring B" adds an A→B edge to a process-wide
  graph; the FIRST acquisition that would close a cycle (some thread
  previously observed the reverse ordering, possibly through
  intermediate locks) raises ``RaceError`` with the offending path —
  the deadlock is reported on the first interleaving that *could*
  deadlock, not the one in a thousand that does (the lockset half of
  ThreadSanitizer's hybrid detector).

Registry edges are per lock INSTANCE (no false positives from two
unrelated instances of the same structure); nodes are weakly held and
pruned so a long-lived process does not accumulate dead locks.
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Set, Tuple

from fabric_mod_tpu.concurrency.core import (RaceError, enabled,
                                             held_locks)


class LockOrderRegistry:
    """Process-wide acquisition-order graph with cycle detection."""

    _PRUNE_EVERY = 256

    def __init__(self):
        self._mu = threading.Lock()
        # node id -> (weakref to lock, display name)
        self._nodes: Dict[int, Tuple[weakref.ref, str]] = {}
        # node id -> successor node ids (u -> v: u held while v taken)
        self._edges: Dict[int, Set[int]] = {}
        self._observes = 0

    def _name(self, nid: int) -> str:
        node = self._nodes.get(nid)
        return node[1] if node else f"<dead lock {nid}>"

    def _node(self, lock) -> int:
        nid = id(lock)
        node = self._nodes.get(nid)
        if node is None or node[0]() is not lock:
            # fresh lock (or the id of a GC'd one, reused): (re)bind
            # and drop any edges recorded against the dead tenant
            self._nodes[nid] = (weakref.ref(lock),
                                getattr(lock, "name", repr(lock)))
            self._edges.pop(nid, None)
            for succ in self._edges.values():
                succ.discard(nid)
        return nid

    def _alive(self, nid: int) -> bool:
        node = self._nodes.get(nid)
        return node is not None and node[0]() is not None

    def _prune(self) -> None:
        dead = [nid for nid, (ref, _) in self._nodes.items()
                if ref() is None]
        for nid in dead:
            self._nodes.pop(nid, None)
            self._edges.pop(nid, None)
        for succ in self._edges.values():
            succ.difference_update(dead)

    def _path(self, src: int, dst: int) -> Optional[List[int]]:
        """A directed path src -> ... -> dst, or None (iterative DFS;
        dead nodes are skipped — their orderings died with them)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            nid, path = stack.pop()
            for nxt in self._edges.get(nid, ()):
                if nxt in seen or not self._alive(nxt):
                    continue
                if nxt == dst:
                    return path + [nxt]
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
        return None

    def observe(self, held: List[tuple], acquiring) -> None:
        """Record "each held lock precedes `acquiring`"; raise on the
        first edge that closes a cycle.  Called with the guards armed,
        before the blocking acquire (so the report fires instead of
        the deadlock)."""
        with self._mu:
            self._observes += 1
            if self._observes % self._PRUNE_EVERY == 0:
                self._prune()
            new = self._node(acquiring)
            for _, lock in held:
                if lock is acquiring:
                    continue
                h = self._node(lock)
                if h == new:
                    continue
                path = self._path(new, h)
                if path is not None:
                    chain = " -> ".join(self._name(n) for n in path)
                    raise RaceError(
                        f"lock-order cycle: acquiring "
                        f"{self._name(new)} while holding "
                        f"{self._name(h)}, but the reverse ordering "
                        f"was already observed ({chain} -> "
                        f"{self._name(new)}) — the AB/BA deadlock "
                        f"shape")
                self._edges.setdefault(h, set()).add(new)

    def clear(self) -> None:
        with self._mu:
            self._nodes.clear()
            self._edges.clear()

    def edge_count(self) -> int:
        with self._mu:
            return sum(len(s) for s in self._edges.values())


_registry = LockOrderRegistry()


def lock_registry() -> LockOrderRegistry:
    """The process-wide registry (one graph for the whole suite)."""
    return _registry


class OrderedLock:
    """An RLock with a rank in a global hierarchy: a thread may only
    acquire ranks STRICTLY ABOVE the highest it already holds (re-
    entry on the same lock is fine).  Any inversion — the classic
    AB/BA deadlock shape — raises RaceError at acquire time, on the
    first interleaving that exhibits it, instead of deadlocking one
    run in a thousand.  The rank check is always on (production
    commit paths run it); under FMT_RACECHECK the acquisition also
    feeds the process-wide lock-order registry so cycles spanning
    ranked and rank-less locks are caught too."""

    def __init__(self, rank: int, name: str = ""):
        self.rank = rank
        self.name = name or f"lock@{rank}"
        self._lock = threading.RLock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        held = held_locks()
        # Re-entry of ANY already-held lock is always safe (RLock) and
        # exempt from the rank rule — scan the whole held stack, not
        # just its top: ledger(10) -> pvtstore(30) -> ledger(10) again
        # cannot deadlock, and the checker runs live on production
        # commit paths where a false positive would abort commits.
        # Fresh locks still check against the HIGHEST held rank (not
        # the stack top — after a re-entry the top can be a low rank
        # that would mask a real inversion against a lock in between).
        if held and not any(h[1] is self for h in held):
            ranked = [h for h in held if h[0] is not None]
            if ranked:
                top_rank, top_lock = max(ranked, key=lambda h: h[0])
                if top_rank >= self.rank:
                    raise RaceError(
                        f"lock-order violation: acquiring {self.name} "
                        f"(rank {self.rank}) while holding "
                        f"{top_lock.name} (rank {top_rank}) — the "
                        f"hierarchy requires strictly increasing ranks")
            if enabled():
                _registry.observe(held, self)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            held.append((self.rank, self))
        return ok

    def release(self):
        held = held_locks()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                del held[i]
                break
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()


class RegisteredLock:
    """A named re-entrant mutex feeding the lock-order registry.

    The drop-in replacement for the plain ``threading.Lock``/``RLock``
    mutexes of the threaded structures (gossip comm, the batching
    verify service, the commit pipeline, election, the gossip drain
    loop): with FMT_RACECHECK unset it is a bare RLock (no
    bookkeeping at all); armed, every nested acquisition records its
    ordering and the first observed inversion raises at acquire time.

    Works as the lock behind a ``threading.Condition`` too — the
    ``_is_owned``/``_release_save``/``_acquire_restore`` protocol
    delegates to the inner RLock and keeps the held-stack honest
    across ``cond.wait()``.
    """

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.RLock()

    # -- lock surface ------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if enabled():
            held = held_locks()
            if not any(h[1] is self for h in held):
                _registry.observe(held, self)
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                held.append((None, self))
            return ok
        return self._lock.acquire(blocking, timeout)

    def release(self):
        held = held_locks()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                del held[i]
                break
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    # -- Condition protocol (CPython delegation) ---------------------------
    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        # cond.wait() fully releases the lock: drop our bookkeeping so
        # the blocked thread does not appear to hold it (edges observed
        # while parked in wait() would be false orderings)
        held = held_locks()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] is self:
                del held[i]
                break
        return self._lock._release_save()

    def _acquire_restore(self, state):
        self._lock._acquire_restore(state)
        held_locks().append((None, self))
