"""Concurrency-correctness subsystem: guarded primitives + dynamic
race detection for every threaded structure in the framework.

(reference: scripts/run-unit-tests.sh:142-161 runs the WHOLE Go unit
suite under the race detector.  Python has no ``-race``; this package
is the library-level answer in the style of ThreadSanitizer's dynamic
annotations (Serebryany & Iskhodzhanov, 2009) and FastTrack's
ownership/happens-before discipline (Flanagan & Freund, 2009):
instrument the primitives once, retrofit the consumers, run the whole
suite under ``FMT_RACECHECK=1``.)

The primitive catalog:

* ``OrderedLock``      — ranked lock hierarchy (always on; the
                         original utils/racecheck.py detector), now
                         also feeding the lock-order registry.
* ``RegisteredLock``   — rank-less mutex that records every observed
                         acquisition ordering into a process-wide
                         graph; the moment a SECOND ordering closes a
                         cycle (the AB/BA deadlock shape, across any
                         number of locks and threads) it raises
                         ``RaceError`` at acquire time.
* ``GuardedQueue``     — queue.Queue whose consumer side (and
                         optionally producer side) is pinned to one
                         owning thread; ownership transfers only from
                         a DEAD thread (join is the happens-before
                         edge, as in FastTrack).
* ``OwnedState``       — field-level thread-ownership wrapper: writes
                         are pinned to the owning thread, reads stay
                         open; ``claim()/release()`` give scoped
                         exclusivity (two concurrent ``run()`` loops
                         on one client is a race, not a feature).
* ``ThreadOwnership``  — whole-structure pin (the raft FSM contract).
* ``RegisteredThread`` — named worker thread registered in a
                         process-wide set; ``assert_joined`` makes a
                         structure's teardown fail loudly when its
                         workers leak.

Cost model: with ``FMT_RACECHECK`` unset every guard is a single
module-flag read (the queues/locks degrade to their plain stdlib
behavior); with it set, the whole tier-1 suite runs with every guard
armed and tests/test_racecheck.py's injected-race canaries prove each
one bites.
"""
from fabric_mod_tpu.concurrency.cancel import CancellationEvent
from fabric_mod_tpu.concurrency.core import (RaceError, armed, enable,
                                             enabled)
from fabric_mod_tpu.concurrency.locks import (LockOrderRegistry,
                                              OrderedLock,
                                              RegisteredLock,
                                              lock_registry)
from fabric_mod_tpu.concurrency.ownership import (OwnedState,
                                                  ThreadOwnership)
from fabric_mod_tpu.concurrency.queues import GuardedQueue
from fabric_mod_tpu.concurrency.threads import (RegisteredThread,
                                                assert_joined,
                                                live_registered)

__all__ = [
    "RaceError", "enabled", "enable", "armed", "CancellationEvent",
    "OrderedLock", "RegisteredLock", "LockOrderRegistry",
    "lock_registry",
    "GuardedQueue", "OwnedState", "ThreadOwnership",
    "RegisteredThread", "assert_joined", "live_registered",
]
