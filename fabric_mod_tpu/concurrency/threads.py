"""RegisteredThread: named workers with teardown leak-checking.

Every long-lived worker in the framework (gossip senders, the verify
service's flusher/resolver, the commit pipeline's stage/commit loops,
election, the gossip drain loop) runs as a RegisteredThread: it
self-registers while alive, and a structure's ``close()`` calls
``assert_joined`` on its own workers — with FMT_RACECHECK armed, a
worker that outlives its structure's teardown raises RaceError naming
the leaked thread instead of silently parking a daemon forever (the
reference gets this from goroutine-leak checks in its test harness).

``live_registered()`` supports the suite-level sweep: the conftest
reports any still-alive registered threads at session end.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from fabric_mod_tpu.concurrency.core import RaceError, enabled

_mu = threading.Lock()
_live: "set[RegisteredThread]" = set()


class RegisteredThread(threading.Thread):
    """A named daemon worker registered for leak accounting.

    `structure` names the owning component (for the leak report);
    threads register at start() and deregister when run() returns.
    """

    def __init__(self, target, name: str, structure: str = "",
                 args: tuple = (), daemon: bool = True):
        super().__init__(target=target, name=name, args=args,
                         daemon=daemon)
        self.structure = structure or name

    def start(self) -> None:
        with _mu:
            _live.add(self)
        super().start()

    def run(self) -> None:
        try:
            super().run()
        finally:
            with _mu:
                _live.discard(self)


def live_registered() -> List[RegisteredThread]:
    """Registered threads that are currently alive."""
    with _mu:
        return [t for t in _live if t.is_alive()]


def assert_joined(threads: Sequence[threading.Thread], owner: str,
                  timeout: Optional[float] = 5.0) -> None:
    """Join `threads`; with the guards armed, raise RaceError naming
    any that are still alive — the structure's teardown leaked its
    workers.  With guards off this is just the joins (bounded; the
    caller's close() semantics are unchanged)."""
    for t in threads:
        if t is threading.current_thread():
            continue                      # self-join would deadlock
        t.join(timeout=timeout)
    if not enabled():
        return
    leaked = [t for t in threads
              if t is not threading.current_thread() and t.is_alive()]
    if leaked:
        names = ", ".join(repr(t.name) for t in leaked)
        raise RaceError(
            f"thread leak at teardown of {owner}: worker(s) {names} "
            f"still alive after join(timeout={timeout})")
