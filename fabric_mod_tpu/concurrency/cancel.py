"""CancellationEvent: a threading.Event whose set() also runs hooks.

The deliver paths park streams on a commit condition; a plain
``threading.Event`` used as the stream's stop signal cannot wake that
wait, which is why the pre-fanout loops ticked (0.25 s / 1.0 s slices
per parked stream — ISSUE 17's 10k-wakeups/s problem).  A
CancellationEvent closes the gap: ``on_set`` registers a wake hook
(notify a condition, set a waiter's event) that fires exactly when the
event is set, so a parked stream can wait full-length and still stop
promptly.

Hooks must be cheap and non-blocking (they run on the canceller's
thread — a gRPC callback or a client's ``stop()``); exceptions are
swallowed so one broken hook cannot mask the cancellation itself.
"""
from __future__ import annotations

import threading
from typing import Callable, List


class CancellationEvent(threading.Event):
    """An Event with set-time wake hooks (see module docstring)."""

    def __init__(self) -> None:
        super().__init__()
        # GIL-atomic list ops; hooks snapshot via list() before firing,
        # so a concurrent unsubscribe never mutates mid-iteration
        self._hooks: List[Callable[[], None]] = []

    def on_set(self, hook: Callable[[], None]) -> Callable[[], None]:
        """Register `hook` to run at set() time; fires immediately if
        already set (the canceller won).  Returns an unsubscribe."""
        self._hooks.append(hook)
        if self.is_set():
            try:
                hook()
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- a wake hook must never mask the cancellation
                pass

        def unsubscribe() -> None:
            try:
                self._hooks.remove(hook)
            except ValueError:
                pass
        return unsubscribe

    def set(self) -> None:
        super().set()
        for hook in list(self._hooks):
            try:
                hook()
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- a wake hook must never mask the cancellation
                pass
