"""Service discovery (reference: discovery/)."""
from fabric_mod_tpu.discovery.service import (   # noqa: F401
    DiscoveryService, EndorsementDescriptor, Layout)
