"""Service discovery: peers, config, endorsement descriptors.

(reference: discovery/ — service.go:294's query dispatch,
endorsement/endorsement.go:84 PeersForEndorsement computing LAYOUTS
(which peer combinations satisfy a chaincode's endorsement policy,
:160 computeEndorsementResponse), the auth cache at authcache.go:196,
and common/graph's combination utilities.)

The layout computation walks the compiled signature-policy tree and
enumerates the minimal principal multisets that satisfy it — the
combinatorics common/graph's tree/perm do in the reference — then
maps principals to orgs and orgs to alive peers.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from fabric_mod_tpu.channelconfig.bundle import Bundle
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos.protoutil import SignedData
from fabric_mod_tpu.concurrency.locks import RegisteredLock

MAX_LAYOUTS = 64                     # combinatorics cap (like reference)


class DiscoveryError(Exception):
    pass


# -- layout computation ------------------------------------------------------

def _principal_org(principal: m.MSPPrincipal) -> Optional[str]:
    """Principal -> owning MSP id (role/OU principals both carry it)."""
    if principal.principal_classification == \
            m.PrincipalClassification.ROLE:
        return m.MSPRole.decode(principal.principal).msp_identifier
    if principal.principal_classification == \
            m.PrincipalClassification.ORGANIZATION_UNIT:
        return m.OrganizationUnit.decode(
            principal.principal).msp_identifier
    return None


def _satisfying_sets(rule: m.SignaturePolicy,
                     principals: Sequence[m.MSPPrincipal]
                     ) -> List[Dict[int, int]]:
    """All minimal principal-index multisets satisfying `rule`
    ({principal_idx: count}), capped at MAX_LAYOUTS."""
    if rule.signed_by >= 0:
        return [{rule.signed_by: 1}]
    if rule.n_out_of is None:
        return []
    n = rule.n_out_of.n
    subs = rule.n_out_of.rules
    if n <= 0:
        return [{}]
    # choose every n-combination of sub-rules; cross-product their sets
    from itertools import combinations
    out: List[Dict[int, int]] = []
    for combo in combinations(range(len(subs)), n):
        partials: List[Dict[int, int]] = [{}]
        for i in combo:
            subsets = _satisfying_sets(subs[i], principals)
            partials = [_merge(a, b) for a in partials for b in subsets]
            if len(partials) > MAX_LAYOUTS:
                partials = partials[:MAX_LAYOUTS]
        out.extend(partials)
        if len(out) > MAX_LAYOUTS:
            return out[:MAX_LAYOUTS]
    # dedup
    seen, deduped = set(), []
    for s in out:
        key = tuple(sorted(s.items()))
        if key not in seen:
            seen.add(key)
            deduped.append(s)
    return deduped


def _merge(a: Dict[int, int], b: Dict[int, int]) -> Dict[int, int]:
    """AND-combine: counts ADD — evaluation consumes one signature per
    satisfied leaf (cauthdsl used-flags), so a principal appearing in
    two AND branches needs two endorsements."""
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


class Layout:
    """One way to satisfy the policy: org -> how many endorsements."""

    __slots__ = ("quantities_by_org",)

    def __init__(self, quantities_by_org: Dict[str, int]):
        self.quantities_by_org = quantities_by_org

    def __repr__(self):
        return f"Layout({self.quantities_by_org})"


class EndorsementDescriptor:
    """(reference: the discovery protocol's EndorsementDescriptor)"""

    def __init__(self, chaincode: str, layouts: List[Layout],
                 peers_by_org: Dict[str, List[m.GossipMember]]):
        self.chaincode = chaincode
        self.layouts = layouts
        self.peers_by_org = peers_by_org

    def usable_layouts(self) -> List[Layout]:
        """Layouts actually satisfiable by the known alive peers."""
        out = []
        for lo in self.layouts:
            if all(len(self.peers_by_org.get(org, [])) >= cnt
                   for org, cnt in lo.quantities_by_org.items()):
                out.append(lo)
        return out


# -- the service -------------------------------------------------------------

class DiscoveryService:
    """One channel's discovery endpoint (reference: service.go)."""

    def __init__(self, bundle_fn, vinfo, membership_fn,
                 verify_many=None):
        """`membership_fn() -> {org_mspid: [GossipMember]}` — the
        gossip view; `vinfo` resolves chaincode endorsement policies
        (the same provider the validator uses)."""
        self._bundle = bundle_fn
        self._vinfo = vinfo
        self._membership = membership_fn
        self._verify_many = verify_many
        self._auth_cache: Dict[bytes, bool] = {}
        self._auth_lock = RegisteredLock("discovery.service._auth_lock")

    # -- auth (reference: authcache.go:196) ------------------------------
    def check_access(self, sd: SignedData) -> bool:
        bundle = self._bundle()
        # cache keyed on the config sequence too: a config update that
        # changes Readers must invalidate prior verdicts (reference:
        # authcache keyed per config)
        key = hashlib.sha256(
            bundle.sequence.to_bytes(8, "big")
            + sd.identity + sd.data + sd.signature).digest()
        with self._auth_lock:
            if key in self._auth_cache:
                return self._auth_cache[key]
        pol = bundle.policy("/Channel/Application/Readers")
        ok = pol is not None and pol.evaluate_signed_data(
            [sd], self._verify_many)
        with self._auth_lock:
            if len(self._auth_cache) > 4096:
                self._auth_cache.clear()
            self._auth_cache[key] = ok
        return ok

    # -- queries ----------------------------------------------------------
    def peers(self) -> Dict[str, List[m.GossipMember]]:
        return self._membership()

    def config(self) -> Dict:
        """(reference: the config query: MSPs + orderer endpoints)"""
        bundle = self._bundle()
        out = {"msps": {}, "orderers": []}
        for msp in bundle.msp_manager.msps():
            from fabric_mod_tpu.msp.ca import cert_pem
            out["msps"][msp.mspid] = [cert_pem(c) for c in msp.roots]
        from fabric_mod_tpu.channelconfig.bundle import (
            ORDERER_ADDRESSES, values_of)
        vals = values_of(bundle.config.channel_group)
        if ORDERER_ADDRESSES in vals:
            out["orderers"] = list(m.OrdererAddresses.decode(
                vals[ORDERER_ADDRESSES].value).addresses)
        return out

    def peers_for_endorsement(self, chaincode: str
                              ) -> EndorsementDescriptor:
        """(reference: endorsement.go:84 PeersForEndorsement)"""
        _plugin, policy_bytes = self._vinfo.validation_info(chaincode)
        ap = m.ApplicationPolicy.decode(policy_bytes)
        bundle = self._bundle()
        if ap.signature_policy is not None:
            env = ap.signature_policy
        else:
            pol = bundle.policy(ap.channel_config_policy_reference)
            env = getattr(pol, "envelope", None)
            if env is None:
                # implicit meta over org Endorsement policies: treat as
                # MAJORITY of orgs (the standard default policy shape)
                orgs = sorted(bundle.application.org_mspids)
                need = len(orgs) // 2 + 1
                from fabric_mod_tpu.policy import policydsl
                env = policydsl.from_string("OutOf(%d, %s)" % (
                    need, ", ".join(f"'{o}.peer'" for o in orgs)))
        if env.rule is None:
            raise DiscoveryError("policy has no rule")
        sets = _satisfying_sets(env.rule, env.identities)
        layouts = []
        for s in sets:
            by_org: Dict[str, int] = {}
            ok = True
            for idx, cnt in s.items():
                if idx >= len(env.identities):
                    ok = False
                    break
                org = _principal_org(env.identities[idx])
                if org is None:
                    ok = False
                    break
                by_org[org] = by_org.get(org, 0) + cnt
            if ok and by_org:
                layouts.append(Layout(by_org))
        membership = self._membership()
        return EndorsementDescriptor(chaincode, layouts, membership)
