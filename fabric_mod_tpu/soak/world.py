"""The soak world: a full in-process network the churn plan perturbs.

Topology (the integration layer over everything PRs 3-7 built):

  N raft orderers (ManualClock-driven elections, one RaftTransport per
  channel) x M channels, each orderer a Registrar + Broadcast;
  K gossiping peers, each with its own ledger/channel per soak channel,
  composed exactly like production: GossipNode (push + anti-entropy
  pull) + GossipService (election-owned DeliverClient) over a
  failover deliver source that rotates across LIVE orderers;
  one EventDeliverServer (real gRPC socket) on peer p0 with the REAL
  bundle-backed ACLProvider, holding the audit org's standing
  BLOCK_UNTIL_READY subscription that an acl_revoke event must cut.

ManualClock acceleration: a pump thread advances fake time
continuously (default 2 fake-seconds per real second), so raft
elections/heartbeats run at fake speed while message passing, gossip,
and commit stay real-threaded — hours of election time compress into
a tier-1 budget, the PR 4 deterministic-clock tier writ large.

Orderer lifecycle primitives (`kill_orderer`, `add_consenter`,
`remove_consenter`) and config primitives (`revoke_audit_org`,
`set_batch_size`) are what the harness's event executor calls; each
goes through the REAL path: signed config updates through
Broadcast.submit -> msgprocessor -> chain.configure -> replicated
config blocks -> peer bundle swaps.
"""
from __future__ import annotations

import os
import random
import threading
import time
import zlib
from typing import Dict, List, Optional

from fabric_mod_tpu.bccsp.sw import SwCSP
from fabric_mod_tpu.bccsp.tpu import FakeBatchVerifier
from fabric_mod_tpu.channelconfig import (Bundle, compute_update, genesis,
                                          signed_update_envelope)
from fabric_mod_tpu.channelconfig.bundle import (BATCH_SIZE, CONSENSUS_TYPE,
                                                 ORDERER, APPLICATION,
                                                 groups_of, set_group,
                                                 set_value, values_of)
from fabric_mod_tpu.channelconfig.configtx import config_from_block
from fabric_mod_tpu.comm.grpc_comm import GRPCClient
from fabric_mod_tpu.concurrency import RegisteredThread, assert_joined
from fabric_mod_tpu.gossip import GossipNode, GossipService, InProcNetwork
from fabric_mod_tpu.ledger.kvledger import LedgerManager
from fabric_mod_tpu.msp import ca as calib
from fabric_mod_tpu.msp.identities import SigningIdentity
from fabric_mod_tpu.observability import get_logger
from fabric_mod_tpu.orderer import Broadcast, DeliverService
from fabric_mod_tpu.orderer.raft import RaftTransport
from fabric_mod_tpu.orderer.raftchain import RaftChain
from fabric_mod_tpu.orderer.registrar import Registrar
from fabric_mod_tpu.peer.aclmgmt import ACLProvider
from fabric_mod_tpu.peer.channel import Channel
from fabric_mod_tpu.peer.deliverevents import (EventDeliverClient,
                                               EventDeliverServer,
                                               EventStreamError)
from fabric_mod_tpu.peer.endorser import Endorser
from fabric_mod_tpu.peer.scc import build_default_registry
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.utils.fakeclock import ManualClock
from fabric_mod_tpu.concurrency.locks import RegisteredLock

log = get_logger("soak.world")

AUDIT_ORG = "AuditOrg"


def _seeded_rng(seed: int, *parts: str) -> random.Random:
    h = seed & 0xFFFFFFFF
    for p in parts:
        h = zlib.crc32(p.encode(), h)
    return random.Random(h)


class _FailoverSource:
    """In-process deliver failover: the `blocks()` generator contract
    of DeliverService/FailoverDeliverSource over whichever LIVE
    orderer currently has the blocks.  A stream that dies (killed
    orderer, idle timeout, or an injected `deliver.stream` fault — the
    PR 5 seam) rotates to another orderer and re-seeks from the next
    needed block; the consumer sees one gap-free sequence."""

    def __init__(self, world: "SoakWorld", channel_id: str):
        self._world = world
        self._cid = channel_id
        self.rotations = 0

    def blocks(self, start: int = 0, stop: Optional[int] = None,
               stop_event: Optional[threading.Event] = None,
               timeout_s: float = 30.0):
        num = start
        while stop is None or num <= stop:
            if stop_event is not None and stop_event.is_set():
                return
            sup = self._world.pick_deliver_support(self._cid, num)
            if sup is None:
                time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
                continue
            try:
                for blk in DeliverService(sup).blocks(
                        num, stop, stop_event=stop_event, timeout_s=1.0):
                    yield blk
                    num = blk.header.number + 1
            except Exception as e:
                # injected mid-stream fault or a dying orderer: the
                # rotation below is the tolerance mechanism under test
                log.debug("soak deliver stream rotating: %r", e)
            self.rotations += 1


class _Orderer:
    __slots__ = ("oid", "registrar", "broadcast", "signer", "dead",
                 "removed", "partitioned")

    def __init__(self, oid, registrar, broadcast, signer):
        self.oid = oid
        self.registrar = registrar
        self.broadcast = broadcast
        self.signer = signer
        self.dead = False
        self.removed = set()               # channels configured out
        # behind a network partition: raft messages black-holed and
        # clients route around it until the heal clears the flag
        self.partitioned = False


class SoakPeer:
    """One committing peer: a ledger + Channel + GossipNode +
    GossipService per soak channel."""

    def __init__(self, world: "SoakWorld", name: str, org: str):
        self.name = name
        self.org = org
        self.world = world
        self.crashed = False
        cert, key = world.cas[org].issue(
            f"{name}.{org.lower()}", org, ous=["peer"])
        self.signer = SigningIdentity(org, cert, calib.key_pem(key),
                                      world.csp)
        self.ledger_mgr = LedgerManager(
            os.path.join(world.root, "peers", name))
        self.channels: Dict[str, Channel] = {}
        self.nodes: Dict[str, GossipNode] = {}
        self.services: Dict[str, GossipService] = {}
        # opt-in sharded-channel mode (FMT_SOAK_SHARDED): this peer's
        # channels place onto host-mode slices behind one per-peer
        # ChannelShardRouter — gossip drains feed slice-pinned commit
        # pipes and every MCS/config verify rides the shared
        # cross-channel service, so the seeded churn (joins, config
        # swaps, leader kills, armed faults) exercises the sharding
        # subsystem's placement + isolation instead of the bare
        # synchronous path
        self.router = None
        if world.sharded:
            from fabric_mod_tpu.sharding import ChannelShardRouter
            self.router = ChannelShardRouter(
                n_slices=max(1, min(2, len(world.channel_ids))),
                verifier_factory=lambda i, mesh: FakeBatchVerifier(
                    world.csp))
        for cid in world.channel_ids:
            ledger = self.ledger_mgr.create_or_open(cid)
            _, config = config_from_block(world.genesis[cid])
            verifier = (self.router.add_channel(cid)
                        if self.router is not None
                        else FakeBatchVerifier(world.csp))
            channel = Channel(cid, ledger, verifier,
                              Bundle(cid, config, world.csp), world.csp)
            if self.router is not None:
                channel.use_shard_router(self.router)
            if ledger.height == 0:
                channel.init_from_genesis(world.genesis[cid])
            self.channels[cid] = channel
            node = GossipNode(f"{name}.{cid}:7051", self.signer, channel,
                              world.networks[cid],
                              rng=_seeded_rng(world.seed, name, cid))
            self.nodes[cid] = node
            relay = None
            if world.relay:
                from fabric_mod_tpu.dissemination import RelayService
                relay = RelayService(node)
            self.services[cid] = GossipService(
                node, lambda cid=cid: _FailoverSource(world, cid),
                election_interval_s=0.2, relay=relay)

    def height(self, cid: str) -> int:
        return self.channels[cid].ledger.height

    def fingerprint(self, cid: str) -> str:
        return self.channels[cid].ledger.state_fingerprint()

    def start(self) -> None:
        for svc in self.services.values():
            svc.start()

    def stop(self) -> None:
        if getattr(self, "crashed", False):
            return                         # already hard-dropped
        for svc in self.services.values():
            svc.stop()
        for node in self.nodes.values():
            node.stop()
        if self.router is not None:
            # after the services' final drains: the router close joins
            # every slice-pinned pipe and the shared flusher before
            # the ledgers they write go away
            self.router.close()
        self.ledger_mgr.close()

    def crash(self) -> None:
        """Hard-drop: every registered thread is torn down (the leak
        sweep must stay clean — a crashed process has no threads) but
        the durable ledgers are ABANDONED, not closed: no checkpoint,
        no flush.  Whatever the per-block fsyncs already made durable
        survives on disk; buffered frames and in-flight commits are
        lost by design, and `KvLedger._recover` on the rejoined peer's
        reopen is what repairs the statedb-behind-blockstore window.
        The world retains a strong reference to this object (see
        `SoakWorld.crashed_peers`) so the abandoned append-mode
        handles are never GC-finalized — a finalizer flush would write
        stale buffered bytes under the rejoined peer's feet."""
        for svc in self.services.values():
            svc.stop()
        for node in self.nodes.values():
            node.stop()
        if self.router is not None:
            self.router.close()
        self.crashed = True


class _Subscriber:
    """The audit org's standing event-deliver subscription: collects
    received block numbers until the stream ends; an acl_revoke event
    must end it FORBIDDEN without a single post-revocation block."""

    def __init__(self, port: int, channel_id: str, signer):
        self._client = GRPCClient(f"127.0.0.1:{port}")
        self._evc = EventDeliverClient(self._client, channel_id, signer)
        self.received: List[int] = []
        self.status: Optional[int] = None
        self.error: Optional[Exception] = None
        self._thread = RegisteredThread(target=self._run,
                                        name="soak-audit-subscriber",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            for blk in self._evc.blocks(start=0, stop=None,
                                        timeout_s=3600.0):
                self.received.append(blk.header.number)
        except EventStreamError as e:
            self.status = e.status
        except Exception as e:             # transport teardown at close
            self.error = e

    def done(self, timeout_s: float) -> bool:
        self._thread.join(timeout=timeout_s)
        return not self._thread.is_alive()

    def close(self) -> None:
        self._client.close()
        self._thread.join(timeout=10)


class SoakWorld:
    def __init__(self, root: str, seed: int, n_channels: int = 2,
                 n_peers: int = 2, orgs=("Org1", "Org2"),
                 orderer_ids=("o0", "o1", "o2"),
                 max_message_count: int = 8,
                 batch_timeout: str = "200ms",
                 clock_step: float = 0.01,
                 clock_interval: float = 0.005):
        self.root = str(root)
        self.seed = int(seed)
        self.csp = SwCSP()
        from fabric_mod_tpu.utils import knobs as _knobs
        self.sharded = _knobs.get_bool("FMT_SOAK_SHARDED")
        # opt-in dissemination-relay mode (FMT_SOAK_RELAY): every
        # peer's channels ship blocks down RelayTrees instead of the
        # sqrt-N epidemic push, so churn exercises reparenting and the
        # anti-entropy repair seam instead of redundant push paths
        self.relay = _knobs.get_bool("FMT_SOAK_RELAY")
        self.orgs = list(orgs)
        self.channel_ids = [f"soak{i}" for i in range(n_channels)]
        self.clock = ManualClock()
        self._clock_step = clock_step
        self._clock_interval = clock_interval
        self._pump_stop = threading.Event()
        self._pump: Optional[RegisteredThread] = None
        self._lock = RegisteredLock("soak.world._lock")
        self._batch_counts: Dict[str, int] = {}
        self._rr = 0

        # crypto material: app orgs + the revocable audit org + orderer
        self.cas = {org: calib.CA(f"ca.{org.lower()}", org)
                    for org in self.orgs + [AUDIT_ORG]}
        self.orderer_ca = calib.CA("ca.orderer", "OrdererOrg")
        self.admins: Dict[str, SigningIdentity] = {}
        for org in self.orgs + [AUDIT_ORG]:
            cert, key = self.cas[org].issue(
                f"admin@{org.lower()}", org, ous=["admin"])
            self.admins[org] = SigningIdentity(org, cert,
                                               calib.key_pem(key),
                                               self.csp)
        ocert, okey = self.orderer_ca.issue("admin@orderer", "OrdererOrg",
                                            ous=["admin"])
        self.orderer_admin = SigningIdentity(
            "OrdererOrg", ocert, calib.key_pem(okey), self.csp)
        ccert, ckey = self.cas[self.orgs[0]].issue(
            f"client@{self.orgs[0].lower()}", self.orgs[0],
            ous=["client"])
        self.client = SigningIdentity(self.orgs[0], ccert,
                                      calib.key_pem(ckey), self.csp)
        acert, akey = self.cas[AUDIT_ORG].issue(
            "auditor@audit", AUDIT_ORG, ous=["client"])
        self.audit_client = SigningIdentity(AUDIT_ORG, acert,
                                            calib.key_pem(akey), self.csp)

        # genesis per channel (multi-channel: one ledger per channel,
        # PAPER.md L3) — raft consenters declared in the config
        org_cas = {org: [calib.cert_pem(self.cas[org].cert)]
                   for org in self.orgs + [AUDIT_ORG]}
        ord_cas = {"OrdererOrg": [calib.cert_pem(self.orderer_ca.cert)]}
        self.genesis: Dict[str, m.Block] = {}
        self.transports: Dict[str, RaftTransport] = {}
        self.networks: Dict[str, InProcNetwork] = {}
        for cid in self.channel_ids:
            self.genesis[cid] = genesis.standard_network(
                cid, org_cas, ord_cas, consensus_type="etcdraft",
                consenters=list(orderer_ids),
                batch_timeout=batch_timeout,
                max_message_count=max_message_count)
            self.transports[cid] = RaftTransport()
            self.networks[cid] = InProcNetwork()
            self._batch_counts[cid] = max_message_count

        self.orderers: Dict[str, _Orderer] = {}
        self._bootstrap_ids = list(orderer_ids)
        # registrars replaced by restart_orderer: their stores' idle
        # handles are closed at world teardown, never mid-run
        self._retired_registrars: List[Registrar] = []
        for oid in orderer_ids:
            self._boot_orderer(oid)

        self.peers: List[SoakPeer] = []
        # hard-crashed SoakPeers, retained forever: dropping the last
        # reference would let GC finalize their abandoned append-mode
        # durable handles — a buffered-byte flush into files the
        # rejoined peer now owns
        self.crashed_peers: List[SoakPeer] = []
        # monotonically-issued peer names: a crash removes its victim
        # from self.peers, so len(self.peers) can no longer name
        # joiners without colliding with a crashed peer's dirs
        self._peer_seq = n_peers
        for i in range(n_peers):
            self.peers.append(SoakPeer(
                self, f"p{i}", self.orgs[i % len(self.orgs)]))

        # endorsers evaluate over p0's channel state (any replica
        # works — endorsement is a read-time act)
        self.endorsers: Dict[str, Dict[str, Endorser]] = {}
        p0 = self.peers[0]
        for cid in self.channel_ids:
            registry = build_default_registry(
                p0.channels[cid], p0.channels[cid].ledger)
            per_org = {}
            for org in self.orgs:
                cert, key = self.cas[org].issue(
                    f"endorser.{org.lower()}.{cid}", org, ous=["peer"])
                per_org[org] = Endorser(
                    p0.channels[cid], registry,
                    SigningIdentity(org, cert, calib.key_pem(key),
                                    self.csp))
            self.endorsers[cid] = per_org

        self.event_server: Optional[EventDeliverServer] = None
        self.subscriber: Optional[_Subscriber] = None

    # -- orderer lifecycle -------------------------------------------------

    def _boot_orderer(self, oid: str) -> _Orderer:
        ocert, okey = self.orderer_ca.issue(
            f"{oid}.orderer", "OrdererOrg", ous=["orderer"])
        signer = SigningIdentity("OrdererOrg", ocert,
                                 calib.key_pem(okey), self.csp)
        root = os.path.join(self.root, "ord", oid)

        def factory(support, oid=oid):
            cid = support.channel_id
            return RaftChain(
                oid, list(self._bootstrap_ids), self.transports[cid],
                os.path.join(self.root, "ord", oid, f"{cid}.wal"),
                support, clock=self.clock,
                rng=_seeded_rng(self.seed, oid, cid))

        reg = Registrar(root, signer, self.csp, chain_factory=factory)
        for cid in self.channel_ids:
            # a RESTART boots over existing dirs: the Registrar ctor
            # already recovered those channels (WAL replay + store
            # tip); only genuinely new dirs get the genesis block
            if reg.get_chain(cid) is None:
                reg.create_channel(self.genesis[cid])
        o = _Orderer(oid, reg, Broadcast(reg), signer)
        with self._lock:
            self.orderers[oid] = o
        return o

    def live_orderers(self) -> List[_Orderer]:
        with self._lock:
            return [o for o in self.orderers.values()
                    if not o.dead and not o.partitioned]

    def chains(self, cid: str) -> Dict[str, object]:
        """Live, still-configured-in chains for a channel."""
        out = {}
        for o in self.live_orderers():
            if cid in o.removed:
                continue
            sup = o.registrar.get_chain(cid)
            if sup is not None:
                out[o.oid] = sup.chain
        return out

    def supports(self, cid: str, voting_only: bool = True):
        out = {}
        for o in self.live_orderers():
            if voting_only and cid in o.removed:
                continue
            sup = o.registrar.get_chain(cid)
            if sup is not None:
                out[o.oid] = sup
        return out

    def leader_of(self, cid: str) -> Optional[str]:
        for oid, chain in self.chains(cid).items():
            if getattr(chain, "is_leader", False):
                return oid
        return None

    def pick_deliver_support(self, cid: str, at_least: int):
        """The failover source's selector: any live orderer, highest
        store first (a removed consenter's frozen store still serves
        history it has)."""
        best = None
        for o in self.live_orderers():
            sup = o.registrar.get_chain(cid)
            if sup is None:
                continue
            if best is None or sup.store.height > best.store.height:
                best = sup
        return best

    def pick_broadcast(self, cid: str) -> Broadcast:
        """Prefer the channel leader (no forward hop); else rotate
        through live orderers (the NOT_LEADER retry path)."""
        lead = self.leader_of(cid)
        with self._lock:
            if lead is not None and not self.orderers[lead].dead \
                    and not self.orderers[lead].partitioned:
                return self.orderers[lead].broadcast
            live = [o for o in self.orderers.values()
                    if not o.dead and not o.partitioned
                    and cid not in o.removed]
            self._rr += 1
            return live[self._rr % len(live)].broadcast

    def kill_orderer(self, oid: str) -> None:
        """SIGKILL analog: halt every chain, stop serving deliver."""
        with self._lock:
            o = self.orderers[oid]
            o.dead = True
        log.info("soak: killing orderer %s", oid)
        for cid in self.channel_ids:
            sup = o.registrar.get_chain(cid)
            if sup is not None:
                try:
                    sup.chain.halt()
                except Exception:  # fmtlint: allow[swallowed-exceptions] -- leader-kill chaos event: halting an already-dying chain is best-effort
                    pass

    def restart_orderer(self, oid: Optional[str] = None,
                        hold_s: float = 0.0) -> str:
        """Crash-restart an orderer: halt its chains mid-traffic (the
        kill_orderer SIGKILL analog), retire the old Registrar object,
        and boot a FRESH one over the same ord/<oid> dirs — the WAL
        replay crops any torn tail, the HardState keeps term/vote,
        `_tip_raft_index` skips blocks already in the store, and
        AppendEntries repair refills whatever the halt lost.  Nothing
        the old incarnation ever ACKED may go missing: every ack sat
        behind a WAL sync barrier, so the replayed log carries it into
        the final exactly-once audit.  Prefers a live, fully-voting
        non-leader (quorum holds while it is down — the planner's
        precondition)."""
        if oid is None:
            lead = self.leader_of(self.channel_ids[0])
            with self._lock:
                cands = sorted(o.oid for o in self.orderers.values()
                               if not o.dead and not o.partitioned
                               and not o.removed)
            if not cands:
                raise RuntimeError("no live orderer to restart")
            oid = next((x for x in cands if x != lead), cands[0])
        self.kill_orderer(oid)
        with self._lock:
            self._retired_registrars.append(self.orderers[oid].registrar)
        if hold_s > 0:
            # the down window: traffic keeps flowing through the
            # surviving quorum while this member is gone
            time.sleep(hold_s)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        log.info("soak: restarting orderer %s from its WAL dir", oid)
        self._boot_orderer(oid)
        return oid

    # -- config events -----------------------------------------------------

    def _submit_update(self, cid: str, desired: m.ConfigGroup,
                       signers, attempts: int = 8) -> None:
        """Sign + submit a config update through the REAL broadcast
        path, retrying transient failures (leaderless windows,
        injected `orderer.raft.submit` faults from the background
        chaos plan)."""
        last: Optional[Exception] = None
        for _ in range(attempts):
            sup = None
            for o in self.live_orderers():
                if cid not in o.removed:
                    sup = o.registrar.get_chain(cid)
                    break
            if sup is None:
                raise RuntimeError(f"no live orderer for {cid}")
            cur = sup.bundle().config
            update = compute_update(cid, cur, desired)
            env = signed_update_envelope(cid, update, list(signers))
            try:
                self.pick_broadcast(cid).submit(env)
                return
            except Exception as e:         # noqa: BLE001
                last = e
                time.sleep(0.25)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        raise RuntimeError(
            f"config update on {cid} failed after retries: {last}")

    def _wait_sequence(self, cid: str, seq: int,
                       timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            sups = self.supports(cid)
            if sups and all(s.bundle().sequence >= seq
                            for s in sups.values()):
                return
            time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        raise RuntimeError(
            f"config sequence {seq} did not propagate on {cid}: "
            f"{[(o, s.bundle().sequence) for o, s in self.supports(cid).items()]}")

    def consenter_ids(self, cid: str) -> List[str]:
        sups = self.supports(cid)
        any_sup = next(iter(sups.values()))
        return list(any_sup.bundle().orderer.consenters())

    def _consenter_update(self, cid: str, new_ids: List[str]) -> None:
        sup = next(iter(self.supports(cid).values()))
        cur = sup.bundle().config
        want_seq = sup.bundle().sequence + 1
        desired = m.ConfigGroup.decode(cur.channel_group.encode())
        osec = groups_of(desired)[ORDERER]
        ctv = values_of(osec)[CONSENSUS_TYPE]
        ct = m.ConsensusType.decode(ctv.value)
        ct.metadata = m.RaftMetadata(consenters=list(new_ids)).encode()
        ctv.value = ct.encode()
        set_value(osec, CONSENSUS_TYPE, ctv)
        set_group(desired, ORDERER, osec)
        self._submit_update(cid, desired, [self.orderer_admin])
        self._wait_sequence(cid, want_seq)

    def add_consenter(self) -> str:
        """Admit a NEW consenter on every channel, then boot its
        replica from genesis — it catches up through the replicated
        log and becomes a voting member (reference: the raft
        reconfiguration + onboarding flow)."""
        with self._lock:
            new_id = f"o{len(self.orderers)}"
        for cid in self.channel_ids:
            self._consenter_update(
                cid, self.consenter_ids(cid) + [new_id])
        log.info("soak: consenter %s admitted; booting replica", new_id)
        self._boot_orderer(new_id)
        return new_id

    def remove_consenter(self) -> str:
        """Configure a consenter out on every channel — preferring a
        DEAD member (the operator repair after a kill), else a live
        follower (it stays up as a non-voting observer)."""
        ids0 = self.consenter_ids(self.channel_ids[0])
        with self._lock:
            dead = [oid for oid in ids0
                    if oid in self.orderers and self.orderers[oid].dead]
        lead = self.leader_of(self.channel_ids[0])
        candidates = dead or [oid for oid in ids0 if oid != lead]
        victim = candidates[0]
        for cid in self.channel_ids:
            keep = [oid for oid in self.consenter_ids(cid)
                    if oid != victim]
            self._consenter_update(cid, keep)
            with self._lock:
                if victim in self.orderers:
                    self.orderers[victim].removed.add(cid)
        log.info("soak: consenter %s configured out (dead=%s)",
                 victim, bool(dead))
        return victim

    def revoke_audit_org(self) -> int:
        """Remove the audit org from the application group of the
        event channel: its standing deliver subscription must be cut
        FORBIDDEN by the mid-stream session re-check.  Returns the
        peer-ledger height BEFORE the update (the revocation block
        lands at or after it)."""
        cid = self.channel_ids[0]
        pre_h = self.peers[0].height(cid)
        sup = next(iter(self.supports(cid).values()))
        want_seq = sup.bundle().sequence + 1
        desired = m.ConfigGroup.decode(
            sup.bundle().config.channel_group.encode())
        app = groups_of(desired)[APPLICATION]
        app.groups = [e for e in app.groups if e.key != AUDIT_ORG]
        set_group(desired, APPLICATION, app)
        # majority of the CURRENT app admins (audit org's own admin
        # not among the signers — it is being expelled)
        n_orgs = len(self.orgs) + 1
        signers = [self.admins[o]
                   for o in self.orgs[:n_orgs // 2 + 1]]
        self._submit_update(cid, desired, signers)
        self._wait_sequence(cid, want_seq)
        return pre_h

    def set_batch_size(self, cid: str) -> int:
        """Flip the channel's BatchSize.max_message_count (8 <-> 12):
        an orderer config update landing under load re-shapes block
        cutting while txs flow."""
        sup = next(iter(self.supports(cid).values()))
        want_seq = sup.bundle().sequence + 1
        new_count = 12 if self._batch_counts[cid] == 8 else 8
        desired = m.ConfigGroup.decode(
            sup.bundle().config.channel_group.encode())
        osec = groups_of(desired)[ORDERER]
        bsv = values_of(osec)[BATCH_SIZE]
        bs = m.BatchSize.decode(bsv.value)
        bs.max_message_count = new_count
        bsv.value = bs.encode()
        set_value(osec, BATCH_SIZE, bsv)
        set_group(desired, ORDERER, osec)
        self._submit_update(cid, desired, [self.orderer_admin])
        self._wait_sequence(cid, want_seq)
        self._batch_counts[cid] = new_count
        return new_count

    # -- peers -------------------------------------------------------------

    def add_peer(self, snapshot: bool = False) -> SoakPeer:
        """A peer joining mid-run: fresh ledgers from genesis, gossip
        join, catch-up via anti-entropy state transfer (the
        GossipStateProvider.anti_entropy_tick -> node._pull_range path
        at scale).  With `snapshot=True` the join takes the PR 20 fast
        lane instead: the newcomer's ledger dirs are seeded from a
        snapshot of p0's state BEFORE the SoakPeer opens them, so it
        starts at the snapshot height and only gossips the tail —
        the convergence gate then proves its fingerprint matches the
        genesis-replay joiners' bit for bit."""
        org = self.orgs[self._peer_seq % len(self.orgs)]
        name = f"p{self._peer_seq}"
        self._peer_seq += 1
        if snapshot:
            self._seed_peer_from_snapshot(name)
        peer = SoakPeer(self, name, org)
        self.peers.append(peer)
        self._join_gossip(peer)
        peer.start()
        log.info("soak: peer %s joined (org %s, snapshot=%s)",
                 peer.name, org, snapshot)
        return peer

    def _join_gossip(self, peer: SoakPeer) -> None:
        for cid in self.channel_ids:
            eps = [p.nodes[cid].endpoint for p in self.peers]
            peer.nodes[cid].join(eps)
            # a couple of membership rounds so existing peers learn
            # the newcomer (and vice versa) promptly
            for _ in range(2):
                for p in self.peers:
                    p.nodes[cid].discovery.tick_send_alive()

    def _seed_peer_from_snapshot(self, name: str) -> Dict[str, int]:
        """Export p0's state per channel (consistent: under the commit
        lock) and bootstrap the newcomer's ledger dirs at the snapshot
        height.  Must run BEFORE SoakPeer construction — the bootstrap
        refuses dirs that already hold a ledger."""
        from fabric_mod_tpu.ledger.snapshot import bootstrap_from_snapshot
        heights: Dict[str, int] = {}
        for cid in self.channel_ids:
            src = self.peers[0].channels[cid].ledger
            snap = os.path.join(self.root, "snapshots", name, cid)
            meta = src.snapshot_to(snap)
            led = bootstrap_from_snapshot(
                snap, os.path.join(self.root, "peers", name, cid))
            heights[cid] = led.height
            led.close()                    # reopened by the SoakPeer
            log.info("soak: %s/%s snapshot-bootstrapped at height %d",
                     name, cid, meta["height"])
        return heights

    # -- crash/rejoin + partitions (PR 20) ---------------------------------

    def crash_peer(self, name: Optional[str] = None) -> SoakPeer:
        """Hard-crash a non-anchor peer (p0 anchors the endorsers, the
        event server, and the audit subscription — never crashed).
        The victim leaves `self.peers`, its threads die, its durable
        dirs stay on disk, and the object itself is retained in
        `crashed_peers` (see SoakPeer.crash for why).  Survivors then
        expire its endpoints so membership — and any relay tree built
        over it — genuinely re-forms."""
        with self._lock:
            candidates = self.peers[1:]
            if not candidates:
                raise RuntimeError("no crashable peer (p0 is anchored)")
            victim = (next(p for p in candidates if p.name == name)
                      if name is not None else candidates[-1])
            self.peers.remove(victim)
            self.crashed_peers.append(victim)
        log.info("soak: hard-crashing peer %s", victim.name)
        victim.crash()
        self._drive_expiry(
            {cid: {victim.nodes[cid].endpoint}
             for cid in self.channel_ids})
        return victim

    def rejoin_peer(self, crashed: SoakPeer) -> SoakPeer:
        """Rejoin after a crash: a FRESH SoakPeer over the SAME
        durable dirs.  `KvLedger._recover` replays any
        statedb-behind-blockstore window (rebuilding the incremental
        XOR fingerprint through the same `_apply_state_updates`
        funnel) and gossip/relay converge the tail — the same join
        choreography as add_peer, minus the genesis bootstrap its
        nonzero heights skip."""
        peer = SoakPeer(self, crashed.name, crashed.org)
        self.peers.append(peer)
        self._join_gossip(peer)
        peer.start()
        log.info("soak: peer %s rejoined its ledger dirs (heights %s)",
                 peer.name,
                 {cid: peer.height(cid) for cid in self.channel_ids})
        return peer

    def install_partition(self):
        """The symmetric partition: the highest-numbered non-anchor
        peer plus one fully-voting non-leader orderer drop off every
        channel's gossip network AND raft transport.  Each side
        expires the other (the victim peer elects itself and converges
        alone; survivors re-form their trees); clients route around
        the partitioned orderer, whose raft messages black-hole until
        the heal.  Returns (peer_names, orderer_ids) for
        heal_partition."""
        with self._lock:
            peer_victims = ([self.peers[-1]]
                            if len(self.peers) > 1 else [])
        lead = self.leader_of(self.channel_ids[0])
        with self._lock:
            ord_cands = sorted(o.oid for o in self.orderers.values()
                               if not o.dead and not o.partitioned
                               and not o.removed and o.oid != lead)
            # quorum guard (the planner's precondition, re-checked at
            # runtime): cutting a voting orderer to the minority side
            # must leave a majority of the voting set connected, else
            # ordering halts for the whole hold
            voting = [o for o in self.orderers.values()
                      if not o.removed]
            connected = sum(1 for o in voting
                            if not o.dead and not o.partitioned)
            ord_victims = (ord_cands[:1]
                           if connected - 1 >= len(voting) // 2 + 1
                           else [])
            for oid in ord_victims:
                self.orderers[oid].partitioned = True
        for cid in self.channel_ids:
            for p in peer_victims:
                self.networks[cid].partitioned.add(
                    p.nodes[cid].endpoint)
            for oid in ord_victims:
                # raft traffic AND forwarded submits address the two
                # registered transport identities
                self.transports[cid].partitioned.add(oid)
                self.transports[cid].partitioned.add(f"{oid}:chain")
        log.info("soak: partition installed (peers=%s orderers=%s)",
                 [p.name for p in peer_victims], ord_victims)
        if peer_victims:
            self._drive_expiry(
                {cid: {p.nodes[cid].endpoint for p in peer_victims}
                 for cid in self.channel_ids})
        return [p.name for p in peer_victims], ord_victims

    def heal_partition(self, peer_names: List[str],
                       orderer_ids: List[str]) -> None:
        """Remove the cut: membership re-merges over a few alive
        rounds, the deliver election re-converges, the partitioned
        orderer's raft log is repaired by AppendEntries, and every
        relay tree re-deals via an explicit epoch bump."""
        for cid in self.channel_ids:
            for name in peer_names:
                p = next(q for q in self.peers if q.name == name)
                self.networks[cid].partitioned.discard(
                    p.nodes[cid].endpoint)
            for oid in orderer_ids:
                self.transports[cid].partitioned.discard(oid)
                self.transports[cid].partitioned.discard(f"{oid}:chain")
        with self._lock:
            for oid in orderer_ids:
                self.orderers[oid].partitioned = False
        for _ in range(3):
            for cid in self.channel_ids:
                for p in self.peers:
                    p.nodes[cid].discovery.tick_send_alive()
            time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        self.bump_relay_epochs()
        log.info("soak: partition healed (peers=%s orderers=%s)",
                 peer_names, orderer_ids)

    def bump_relay_epochs(self) -> None:
        """Explicit tree rotation after a membership-shaped event
        (FMT_SOAK_RELAY mode): every peer's next tree() re-parents
        even where its alive set ends up identical to the pre-event
        view."""
        for p in self.peers:
            for svc in p.services.values():
                relay = getattr(svc, "relay", None)
                if relay is not None:
                    relay.bump_epoch()

    def _drive_expiry(self, targets: Dict[str, set],
                      timeout_s: float = 20.0) -> None:
        """Drive manual alive/expiry rounds (discovery is never
        background-ticked in the soak) under a temporarily tightened
        expiry until every endpoint in targets[cid] has dropped out of
        every OTHER live peer's membership view on cid.  Sends across
        a partition seam are dropped by the seam itself, so both sides
        of a cut expire each other in the same rounds."""
        deadline = time.monotonic() + timeout_s
        saved = {}
        for cid in targets:
            for p in self.peers:
                saved[(p.name, cid)] = p.nodes[cid].discovery.expiry_s
                p.nodes[cid].discovery.expiry_s = 0.6
        try:
            while time.monotonic() < deadline:
                gone = True
                for cid, eps in targets.items():
                    for p in self.peers:
                        d = p.nodes[cid].discovery
                        d.tick_send_alive()
                        d.tick_check_alive()
                        if p.nodes[cid].endpoint not in eps and \
                                eps & set(d.alive_endpoints()):
                            gone = False
                if gone:
                    return
                time.sleep(0.15)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        finally:
            for (pname, cid), v in saved.items():
                p = next((q for q in self.peers if q.name == pname),
                         None)
                if p is not None:
                    p.nodes[cid].discovery.expiry_s = v
        raise RuntimeError(
            f"endpoints never expired from live membership: {targets}")

    # -- dissemination relay (FMT_SOAK_RELAY) ------------------------------

    def gossip_leader(self, cid: str) -> Optional[str]:
        """The peer currently holding GOSSIP deliver leadership on a
        channel (distinct from the raft orderer leader)."""
        for p in self.peers:
            if p.services[cid].is_leader:
                return p.name
        return None

    def relay_stats(self) -> Dict[str, int]:
        """Aggregate BlockRelay counters across every peer/channel —
        the run-end proof that the tree actually carried blocks."""
        agg: Dict[str, int] = {}
        for p in self.peers:
            for svc in p.services.values():
                relay = getattr(svc, "relay", None)
                if relay is None:
                    continue
                for k, v in relay.stats.items():
                    agg[k] = agg.get(k, 0) + v
        return agg

    def partition_relay_leader(self, cid: str,
                               timeout_s: float = 20.0) -> str:
        """Cut the gossip relay ROOT off the channel's gossip network
        (the relay-mode churn amplifier riding leader_kill): survivors
        must expire it, elect a new root, and rebuild the tree.  The
        victim keeps its own DeliverClient and converges alone.
        Discovery is never background-ticked in the soak, so this
        drives the alive/expiry rounds itself under a temporarily
        tightened expiry.  Returns the victim peer's name."""
        victim = None
        deadline = time.monotonic() + timeout_s
        while victim is None:
            name = self.gossip_leader(cid)
            victim = next((p for p in self.peers if p.name == name),
                          None)
            if victim is None:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"no gossip leader to partition on {cid}")
                time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        ep = victim.nodes[cid].endpoint
        self.networks[cid].partitioned.add(ep)
        log.info("soak: partitioned relay root %s (%s)", victim.name, ep)
        survivors = [p for p in self.peers if p is not victim]
        saved = {p.name: p.nodes[cid].discovery.expiry_s
                 for p in survivors}
        for p in survivors:
            p.nodes[cid].discovery.expiry_s = 0.6
        try:
            while time.monotonic() < deadline:
                gone = True
                for p in survivors:
                    d = p.nodes[cid].discovery
                    d.tick_send_alive()
                    d.tick_check_alive()
                    if ep in d.alive_endpoints():
                        gone = False
                if gone:
                    return victim.name
                time.sleep(0.15)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        finally:
            for p in survivors:
                p.nodes[cid].discovery.expiry_s = saved[p.name]
        raise RuntimeError(
            f"partitioned relay root {ep} never expired from the "
            f"survivors' membership views on {cid}")

    def heal_relay_leader(self, cid: str, peer_name: str) -> None:
        """Reconnect a partitioned relay root: membership re-forms
        over a few alive rounds and the election re-converges (another
        reparent — the returning minimum reclaims the root)."""
        peer = next(p for p in self.peers if p.name == peer_name)
        self.networks[cid].partitioned.discard(
            peer.nodes[cid].endpoint)
        for _ in range(3):
            for p in self.peers:
                p.nodes[cid].discovery.tick_send_alive()
            time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        log.info("soak: healed relay root %s on %s", peer_name, cid)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._pump = RegisteredThread(target=self._pump_loop,
                                      name="soak-clock-pump",
                                      structure="SoakWorld")
        self._pump.start()
        for cid in self.channel_ids:
            for p in self.peers:
                p.nodes[cid].join(
                    [q.nodes[cid].endpoint for q in self.peers])
            for _ in range(2):
                for p in self.peers:
                    p.nodes[cid].discovery.tick_send_alive()
        for p in self.peers:
            p.start()
        # the audit org's standing subscription over a REAL socket,
        # gated by the REAL bundle-backed ACLProvider on p0
        cid0 = self.channel_ids[0]
        p0 = self.peers[0]
        acl = ACLProvider(p0.channels[cid0].bundle)
        self.event_server = EventDeliverServer(
            cid0, p0.channels[cid0].ledger, acl)
        self.event_server.start()
        self.subscriber = _Subscriber(self.event_server.port, cid0,
                                      self.audit_client)

    def _pump_loop(self) -> None:
        while not self._pump_stop.is_set():
            self.clock.advance(self._clock_step)
            self._pump_stop.wait(self._clock_interval)

    def orderer_tip(self, cid: str) -> int:
        return max((s.store.height
                    for s in self.supports(cid).values()), default=0)

    def close(self) -> None:
        if self.subscriber is not None:
            self.subscriber.close()
        if self.event_server is not None:
            self.event_server.stop()
        for p in self.peers:
            p.stop()
        self._pump_stop.set()
        if self._pump is not None:
            assert_joined((self._pump,), owner="SoakWorld", timeout=5)
        with self._lock:
            regs = ([o.registrar for o in self.orderers.values()]
                    + list(self._retired_registrars))
        # crashed peers are deliberately NOT closed: their ledgers were
        # abandoned mid-flight and stay abandoned (the refs in
        # self.crashed_peers outlive the world so no finalizer flush
        # ever runs against a rejoined peer's files)
        for reg in regs:
            try:
                reg.close()
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- world teardown after chaos: a dead orderer's close must not mask the run's result
                pass
