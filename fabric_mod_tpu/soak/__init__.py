"""Sustained soak-under-churn: the chaos-engineering integration layer.

Hours of mixed x509+idemix traffic while membership, config, and
faults move underneath — fingerprints converge after every event or
the run fails loudly with the seed + schedule needed to replay it.
See soak/harness.py for the run loop, soak/plan.py for the seeded
event catalog, soak/invariants.py for the steady-state contract.
"""
from fabric_mod_tpu.soak.harness import (SoakConfig, SoakHarness,
                                         background_fault_plan, run_soak)
from fabric_mod_tpu.soak.invariants import InvariantChecker, SoakError
from fabric_mod_tpu.soak.plan import (CORE_KINDS, EVENT_KINDS, ChurnEvent,
                                      ChurnPlan)
from fabric_mod_tpu.soak.workload import (MixedWorkload, committed_txids,
                                          load_idemix_fixture)
from fabric_mod_tpu.soak.world import SoakPeer, SoakWorld

__all__ = [
    "SoakConfig", "SoakHarness", "run_soak", "background_fault_plan",
    "InvariantChecker", "SoakError", "ChurnPlan", "ChurnEvent",
    "EVENT_KINDS", "CORE_KINDS", "MixedWorkload", "committed_txids",
    "load_idemix_fixture", "SoakWorld", "SoakPeer",
]
