"""Mixed soak workload: interleaved x509 + idemix signer lanes.

The x509 lane drives the full endorse -> broadcast -> order -> deliver
-> validate -> commit loop (the e2e pipeline) across every soak
channel, recording each ADMITTED envelope (broadcast returned success)
for the run-wide exactly-once audit — the broadcaststorm ledger-audit
invariant extended across hours of churn: a submit the ordering
service ACKED either commits exactly once or the retained envelope is
resubmitted at the quiesced tail until it does.

The idemix lane is the first scaled idemix scenario: anonymous BBS+
presentations signed and MSP-verified continuously alongside the x509
traffic.  Credentials come from a COMMITTED fixture
(soak/idemix_fixture.json) so the lane pays zero per-run issuer/
credential pairing setup — each unit of work is sign_message (fresh
unlinkable presentation) + IdemixMsp deserialize + verify (two host
pairings), with every 8th presentation tampered and required to
verify False so the lane proves the verdict path, not a
constant-True short circuit.

Both lanes park at a shared gate so the invariant checker can
quiesce traffic around convergence checks, and both survive transient
failures (leaderless windows, injected faults) by retrying — the
production client stance.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from fabric_mod_tpu.concurrency import RegisteredThread, assert_joined
from fabric_mod_tpu.observability import get_logger
from fabric_mod_tpu.peer.endorser import endorse_and_submit
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.concurrency.locks import RegisteredLock

log = get_logger("soak.workload")

_FIXTURE_PATH = os.path.join(os.path.dirname(__file__),
                             "idemix_fixture.json")
_fixture_cache: Optional[dict] = None
_fixture_lock = RegisteredLock("soak.workload._fixture_lock")


def load_idemix_fixture() -> dict:
    """Pre-built idemix material: issuer key + issued credentials,
    deserialized once per process.  Returns {"msp", "issuer_key",
    "signers": [IdemixSigningIdentity, ...]}."""
    global _fixture_cache
    with _fixture_lock:
        if _fixture_cache is not None:
            return _fixture_cache
        from fabric_mod_tpu.idemix import credential as idmx
        from fabric_mod_tpu.msp import idemixmsp
        with open(_FIXTURE_PATH) as f:
            raw = json.load(f)
        ik = idmx.IssuerKey.from_dict(raw["issuer"])
        msp = idemixmsp.IdemixMsp(raw["mspid"], ik)
        signers = []
        for u in raw["users"]:
            user = idemixmsp.IdemixUser(
                raw["mspid"], int(u["sk"]),
                idmx.Credential.from_dict(u["cred"]),
                u["ou"], int(u["role"]))
            signers.append(idemixmsp.IdemixSigningIdentity(user, ik))
        _fixture_cache = {"msp": msp, "issuer_key": ik,
                          "signers": signers}
        return _fixture_cache


class _Unit:
    """Busy-count guard around one unit of lane work: pause() waits
    until no unit is in flight before declaring the gate quiesced."""

    __slots__ = ("_wl",)

    def __init__(self, wl: "MixedWorkload"):
        self._wl = wl

    def __enter__(self):
        with self._wl._lock:
            self._wl._busy += 1

    def __exit__(self, *exc):
        with self._wl._lock:
            self._wl._busy -= 1


class MixedWorkload:
    """Two lanes over a SoakWorld, pausable for quiesce windows."""

    def __init__(self, world, x509_gap_s: float = 0.12,
                 idemix_gap_s: float = 1.0, tamper_every: int = 8):
        self.world = world
        self._x509_gap = x509_gap_s
        self._idemix_gap = idemix_gap_s
        self._tamper_every = max(2, tamper_every)
        self._gate = threading.Event()
        self._gate.set()
        self._stop = threading.Event()
        self._lock = RegisteredLock("soak.workload._lock")
        self._busy = 0
        # cid -> {txid: encoded envelope} — retained for the
        # resubmit-at-tail path of the exactly-once audit
        self.admitted: Dict[str, Dict[str, bytes]] = {
            cid: {} for cid in world.channel_ids}
        self.x509_count = 0
        self.idemix_count = 0
        self.idemix_tamper_rejects = 0
        self.submit_errors = 0
        self.errors: List[str] = []        # lane-fatal problems
        self._seq = 0
        self._threads: List[RegisteredThread] = []

    # -- gate --------------------------------------------------------------

    def _unit(self) -> "_Unit":
        """Context guard: one unit of lane work between gate checks."""
        return _Unit(self)

    def pause(self, timeout_s: float = 30.0) -> None:
        """Close the gate and wait for in-flight units to park."""
        self._gate.clear()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._busy == 0:
                    return
            time.sleep(0.01)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        raise RuntimeError("workload did not quiesce in time")

    def resume(self) -> None:
        self._gate.set()

    # -- lanes -------------------------------------------------------------

    def _x509_lane(self) -> None:
        world = self.world
        while not self._stop.is_set():
            if not self._gate.wait(timeout=0.25):
                continue
            if self._stop.is_set():
                return
            with self._unit():
                with self._lock:
                    i = self._seq
                    self._seq += 1
                cid = world.channel_ids[i % len(world.channel_ids)]
                try:
                    bcast = world.pick_broadcast(cid)
                    txid, env = self._make_and_submit(cid, i, bcast)
                    with self._lock:
                        self.admitted[cid][txid] = env
                        self.x509_count += 1
                except Exception as e:     # noqa: BLE001 — retry lane
                    with self._lock:
                        self.submit_errors += 1
                    log.debug("x509 submit retryable failure: %s", e)
                    time.sleep(0.1)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
            self._stop.wait(self._x509_gap)

    def _make_and_submit(self, cid: str, i: int, bcast):
        """Endorse + submit one put-tx; returns (txid, env_bytes) —
        the envelope is retained so a tx lost to a leader kill can be
        RESUBMITTED verbatim at the quiesced tail."""
        world = self.world
        sp, prop, tx_id = protoutil.create_chaincode_proposal(
            cid, "mycc",
            [b"put", b"soak-k%d" % i, b"soak-v%d" % i], world.client)
        endorsers = list(world.endorsers[cid].values())
        responses = [e.process_proposal(sp) for e in endorsers]
        env = protoutil.create_tx_from_responses(prop, responses,
                                                 world.client)
        bcast.submit(env)
        return tx_id, env.encode()

    def resubmit(self, cid: str, txid: str) -> None:
        env = m.Envelope.decode(self.admitted[cid][txid])
        self.world.pick_broadcast(cid).submit(env)

    def _idemix_lane(self) -> None:
        fx = load_idemix_fixture()
        msp, signers = fx["msp"], fx["signers"]
        n = 0
        while not self._stop.is_set():
            if not self._gate.wait(timeout=0.25):
                continue
            if self._stop.is_set():
                return
            with self._unit():
                try:
                    signer = signers[n % len(signers)]
                    msg = b"soak-idemix-%d" % n
                    sig = signer.sign_message(msg)
                    ident = msp.deserialize_identity(signer.serialize())
                    if n % self._tamper_every == self._tamper_every - 1:
                        ok = ident.verify(msg + b"-tampered", sig)
                        if ok:
                            self.errors.append(
                                "idemix accepted a tampered "
                                "presentation")
                            return
                        with self._lock:
                            self.idemix_tamper_rejects += 1
                    else:
                        if not ident.verify(msg, sig):
                            self.errors.append(
                                "idemix rejected an honest "
                                "presentation")
                            return
                    with self._lock:
                        self.idemix_count += 1
                except Exception as e:     # noqa: BLE001
                    self.errors.append(f"idemix lane died: {e!r}")
                    return
                n += 1
            self._stop.wait(self._idemix_gap)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        for name, target in (("soak-x509-lane", self._x509_lane),
                             ("soak-idemix-lane", self._idemix_lane)):
            t = RegisteredThread(target=target, name=name,
                                 structure="MixedWorkload")
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        self._stop.set()
        self._gate.set()
        assert_joined(self._threads, owner="MixedWorkload", timeout=15)

    # -- audit surface -----------------------------------------------------

    def admitted_txids(self, cid: str) -> List[str]:
        with self._lock:
            return list(self.admitted[cid])

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {"x509": self.x509_count,
                    "idemix": self.idemix_count,
                    "idemix_tamper_rejects": self.idemix_tamper_rejects,
                    "submit_errors": self.submit_errors}


def committed_txids(ledger) -> List[str]:
    """Every VALID ENDORSER_TRANSACTION txid committed on a ledger,
    in order, duplicates INCLUDED (the audit counts multiplicity — an
    admitted tx applying to state twice is as much a failure as
    zero).  Only VALID flags count: a legitimately re-ordered
    envelope (raft repropose/park-requeue after a leadership change,
    or the audit's own tail resubmission racing a late flush) commits
    with DUPLICATE_TXID and applies nothing — that is the dedup
    mechanism WORKING, not an exactly-once violation."""
    V = m.TxValidationCode
    out: List[str] = []
    for num in range(1, ledger.height):
        block = ledger.get_block_by_number(num)
        if block is None:
            continue
        flags = protoutil.block_txflags(block)
        for i, env in enumerate(protoutil.get_envelopes(block)):
            try:
                payload = protoutil.unmarshal_envelope_payload(env)
                ch = m.ChannelHeader.decode(payload.header.channel_header)
            except Exception:
                continue
            if ch.type != m.HeaderType.ENDORSER_TRANSACTION or \
                    not ch.tx_id:
                continue
            if i < len(flags) and flags[i] != V.VALID:
                continue
            out.append(ch.tx_id)
    return out
