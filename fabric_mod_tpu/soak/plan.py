"""Seeded churn planning: the deterministic event schedule of a soak.

(reference evaluation model: Basiri et al., "Chaos Engineering", IEEE
Software 2016 — steady-state invariants asserted while deliberately
perturbing the system — and the Jepsen test harness's generator of
nemesis operations interleaved with client traffic.  The schedule is
a pure function of the seed so a failed run can be REPLAYED: the
failure report prints the seed and the exact schedule, and
`ChurnPlan(seed)` regenerates it bit-for-bit.)

Event catalog (each kind exercises a different PR-5/PR-7 mechanism at
system scale):

  peer_join        a fresh peer joins mid-run and catches up through
                   gossip anti-entropy state transfer
  acl_revoke       a config update removes the audit org — its live
                   event-deliver subscription must be cut FORBIDDEN
                   mid-stream, never grandfathered
  batch_config     an orderer config update (BatchSize) lands under
                   load — block cutting re-shapes while txs flow
  consenter_add    a new consenter is admitted via config and a fresh
                   replica boots from genesis and catches up
  consenter_remove a consenter (preferring an already-dead one — the
                   operator repair) is configured out
  leader_kill      the raft leader is halted mid-traffic; the
                   survivors re-elect and ordering continues

Crash-shaped kinds (PR 20 — each is down-then-up WITHIN one event, so
the member/live bookkeeping is unchanged after it completes):

  peer_crash_rejoin  a peer is hard-crashed (no flush, no clean close)
                     and a fresh peer reopens the SAME durable ledger
                     dirs — KvLedger._recover replays statedb-behind-
                     blockstore and gossip/relay reconverges the tail
  orderer_restart    a live orderer is halted mid-traffic and a fresh
                     Registrar boots from its existing WAL dir — torn
                     tails cropped, HardState honored, catch-up via
                     AppendEntries repair; quorum must hold while it
                     is down (leader_kill's precondition)
  network_partition  a symmetric partition (peer group + minority
                     orderer group) is installed, traffic flows, then
                     the partition heals on schedule — convergence is
                     gated by the same fingerprint window

The planner tracks (members, live_members) so a generated schedule can
never break raft quorum: leader_kill / consenter_remove are only
scheduled while a majority of the post-event member set stays live,
and orderer_restart only while the restart window can be survived.
"""
from __future__ import annotations

import json
import random
from typing import List, Optional, Sequence, Tuple

EVENT_KINDS = ("peer_join", "acl_revoke", "batch_config",
               "consenter_add", "consenter_remove", "leader_kill",
               "peer_crash_rejoin", "orderer_restart",
               "network_partition")

# the kinds the acceptance gate requires every default run to execute
# (consenter_add and consenter_remove are one "membership change"
# family; both are in the default core so joins and repairs are each
# exercised; the three crash-shaped kinds are core since PR 20 so the
# recovery paths they exercise run on every default soak)
CORE_KINDS = ("peer_join", "acl_revoke", "batch_config",
              "consenter_add", "leader_kill", "consenter_remove",
              "peer_crash_rejoin", "orderer_restart",
              "network_partition")


class ChurnEvent:
    """One scheduled perturbation: fire after `gap_txs` more mixed
    workload transactions have been submitted."""

    __slots__ = ("kind", "gap_txs")

    def __init__(self, kind: str, gap_txs: int):
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown churn event kind {kind!r}")
        self.kind = kind
        self.gap_txs = gap_txs

    def to_dict(self) -> dict:
        return {"kind": self.kind, "gap_txs": self.gap_txs}

    def __eq__(self, other) -> bool:
        return (isinstance(other, ChurnEvent) and
                self.kind == other.kind and
                self.gap_txs == other.gap_txs)

    def __repr__(self) -> str:
        return f"ChurnEvent({self.kind!r}, gap_txs={self.gap_txs})"


def _majority(n: int) -> int:
    return n // 2 + 1


class _PlanState:
    """Safety bookkeeping while generating (mirrors what the harness
    will do at runtime, conservatively)."""

    def __init__(self, members: int, max_peer_joins: int):
        self.members = members             # configured consenter count
        self.live_members = members        # consenters not yet killed
        self.audit_revoked = False
        self.peer_joins_left = max_peer_joins

    def allowed(self, kind: str) -> bool:
        if kind in ("leader_kill", "orderer_restart",
                    "network_partition"):
            # after the kill (or during the restart's down window /
            # the partition's hold) a majority of the UNCHANGED
            # member set must remain live-and-connected or ordering
            # halts for good (the partition cuts one voting orderer
            # to the minority side)
            return self.live_members - 1 >= _majority(self.members)
        if kind == "consenter_remove":
            if self.members <= 2:
                return False
            dead = self.members - self.live_members
            live_after = (self.live_members if dead > 0
                          else self.live_members - 1)
            return live_after >= _majority(self.members - 1)
        if kind == "acl_revoke":
            return not self.audit_revoked
        if kind == "peer_join":
            return self.peer_joins_left > 0
        # batch_config, consenter_add, peer_crash_rejoin (down-then-up
        # on the ledger side only — never an ordering-quorum concern)
        return True

    def apply(self, kind: str) -> None:
        if kind == "leader_kill":
            self.live_members -= 1
        elif kind == "consenter_add":
            self.members += 1
            self.live_members += 1
        elif kind == "consenter_remove":
            dead = self.members - self.live_members
            self.members -= 1
            if dead == 0:
                # runtime prefers removing a dead member; with none,
                # a live one becomes an observer (still serving
                # deliver, no longer voting)
                self.live_members -= 1
        elif kind == "acl_revoke":
            self.audit_revoked = True
        elif kind == "peer_join":
            self.peer_joins_left -= 1
        # peer_crash_rejoin / orderer_restart / network_partition end
        # with the pre-event member and liveness sets restored — the
        # down window's safety is the allowed() precondition


class ChurnPlan:
    """A seeded, replayable schedule of churn events.

    `ChurnPlan(seed, n_events)` is a pure function: the same arguments
    produce the same schedule on every run and every host (the replay
    contract a failed soak's report relies on)."""

    def __init__(self, seed: int, n_events: int = 6,
                 gap_txs: Tuple[int, int] = (4, 9),
                 members: int = 3, max_peer_joins: int = 2,
                 kinds: Optional[Sequence[str]] = None):
        self.seed = int(seed)
        self.n_events = int(n_events)
        rng = random.Random(self.seed)
        state = _PlanState(members, max_peer_joins)
        core = [k for k in (kinds or CORE_KINDS)]
        rng.shuffle(core)
        pool = list(kinds or EVENT_KINDS)
        self.events: List[ChurnEvent] = []
        for _ in range(self.n_events):
            # cover every core kind first, then draw from the pool;
            # a kind whose safety precondition fails yields its slot
            # to the next candidate (deterministically)
            cand = ([k for k in core if state.allowed(k)] or
                    [k for k in pool if state.allowed(k)])
            if not cand:
                break                      # fully constrained: stop
            kind = cand[0] if core else rng.choice(cand)
            if core and kind in core:
                core.remove(kind)
            state.apply(kind)
            self.events.append(
                ChurnEvent(kind, rng.randint(*gap_txs)))

    def kinds(self) -> List[str]:
        return [e.kind for e in self.events]

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "events": [e.to_dict() for e in self.events]},
                          sort_keys=True)

    def describe(self) -> str:
        """The replay block a failed run prints (satellite contract:
        seed + exact schedule + the command that reruns it)."""
        return ("soak seed {s}: replay with `python bench.py --metric "
                "soak --soak-seed {s} --soak-events {n}`\n"
                "schedule: {j}").format(s=self.seed, n=self.n_events,
                                        j=self.to_json())

    def __eq__(self, other) -> bool:
        return (isinstance(other, ChurnPlan) and
                self.to_json() == other.to_json())
