"""SoakHarness: sustained mixed traffic while churn moves underneath.

One run = build the world, start the mixed x509+idemix workload, arm
the permanently-on background fault plan (seeded probability rules on
the PR 5 injection points), then walk the seeded ChurnPlan: traffic
phase -> fire event -> converge-or-fail -> next.  At the tail the
exactly-once ledger audit (with resubmission of kill-lost envelopes),
the subscriber-cutoff assertion, teardown, and the thread-leak sweep.

Every failure raises SoakError whose message carries the seed and the
full schedule — `python bench.py --metric soak --soak-seed N` replays
it, and ChurnPlan(N) regenerates the schedule bit-for-bit (asserted
by tests/test_soak.py).

Knobs (all env-overridable, the FMT_SOAK_* table in README):

  FMT_SOAK_SEED           schedule + rng seed          (default 8)
  FMT_SOAK_EVENTS         churn events per run         (default 6)
  FMT_SOAK_CHANNELS       soak channels                (default 2)
  FMT_SOAK_PEERS          peers at start (join events add more)  (2)
  FMT_SOAK_GAP_TXS        "lo:hi" txs between events   (default 4:9)
  FMT_SOAK_WINDOW_S       recovery window per event    (default 45)
  FMT_SOAK_RECOVERY_FRAC  post/pre throughput floor    (default 0.05)
  FMT_SOAK_X509_GAP_S     x509 lane inter-tx gap       (default 0.12)
  FMT_SOAK_IDEMIX_GAP_S   idemix lane inter-tx gap     (default 1.0)
  FMT_SOAK_FAULT_P        background fault probability (default 0.05)
  FMT_SOAK_RELAY          1 = dissemination-relay mode: blocks ship
                          down RelayTrees instead of epidemic push;
                          leader_kill additionally partitions the
                          relay root (recovery recorded under
                          kind=relay_reparent), and the run fails if
                          the relay never carried a block
  FMT_SOAK_NO_CRASH       1 = drop the crash-shaped kinds from the
                          default plan (in the pool since PR 20)
  FMT_SOAK_PARTITION_S    network_partition hold time   (default 2.0)
  FMT_SOAK_CRASH_HOLD_S   crash/restart down window     (default 1.0)
"""
from __future__ import annotations

import os
import random
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from fabric_mod_tpu import faults
from fabric_mod_tpu.observability import get_logger
from fabric_mod_tpu.protos import messages as m
from fabric_mod_tpu.protos import protoutil
from fabric_mod_tpu.soak.invariants import InvariantChecker, SoakError
from fabric_mod_tpu.soak.plan import CORE_KINDS, ChurnPlan
from fabric_mod_tpu.soak.workload import MixedWorkload
from fabric_mod_tpu.soak.world import SoakWorld
from fabric_mod_tpu.utils import knobs

log = get_logger("soak.harness")

# the crash-shaped PR 20 kinds FMT_SOAK_NO_CRASH=1 drops from the plan
CRASH_KINDS = ("peer_crash_rejoin", "orderer_restart",
               "network_partition")


class SoakConfig:
    def __init__(self, seed: Optional[int] = None,
                 n_events: Optional[int] = None,
                 n_channels: Optional[int] = None,
                 n_peers: Optional[int] = None,
                 gap_txs: Optional[Tuple[int, int]] = None,
                 recovery_window_s: Optional[float] = None,
                 min_recovery_frac: Optional[float] = None,
                 x509_gap_s: Optional[float] = None,
                 idemix_gap_s: Optional[float] = None,
                 fault_p: Optional[float] = None,
                 kinds: Optional[Tuple[str, ...]] = None,
                 partition_s: Optional[float] = None,
                 crash_hold_s: Optional[float] = None):
        gap_env = knobs.get_str("FMT_SOAK_GAP_TXS", "")
        if gap_txs is None and gap_env:
            try:
                lo, _, hi = gap_env.partition(":")
                gap_txs = (int(lo), int(hi or lo))
            except ValueError:
                gap_txs = None             # garbage knob: the default
        self.seed = seed if seed is not None else \
            knobs.get_int("FMT_SOAK_SEED")
        self.n_events = n_events if n_events is not None else \
            knobs.get_int("FMT_SOAK_EVENTS")
        self.n_channels = n_channels if n_channels is not None else \
            knobs.get_int("FMT_SOAK_CHANNELS")
        self.n_peers = n_peers if n_peers is not None else \
            knobs.get_int("FMT_SOAK_PEERS")
        self.gap_txs = gap_txs or (4, 9)
        self.recovery_window_s = recovery_window_s \
            if recovery_window_s is not None else \
            knobs.get_float("FMT_SOAK_WINDOW_S")
        self.min_recovery_frac = min_recovery_frac \
            if min_recovery_frac is not None else \
            knobs.get_float("FMT_SOAK_RECOVERY_FRAC")
        self.x509_gap_s = x509_gap_s if x509_gap_s is not None else \
            knobs.get_float("FMT_SOAK_X509_GAP_S")
        self.idemix_gap_s = idemix_gap_s if idemix_gap_s is not None \
            else knobs.get_float("FMT_SOAK_IDEMIX_GAP_S")
        self.fault_p = fault_p if fault_p is not None else \
            knobs.get_float("FMT_SOAK_FAULT_P")
        # event-kind selection: an explicit list (bench --soak-kinds)
        # wins; else the full 9-kind core, minus the crash-shaped
        # kinds when FMT_SOAK_NO_CRASH=1
        if kinds is not None:
            self.kinds: Optional[Tuple[str, ...]] = tuple(kinds)
        elif knobs.get_bool("FMT_SOAK_NO_CRASH"):
            self.kinds = tuple(k for k in CORE_KINDS
                               if k not in CRASH_KINDS)
        else:
            self.kinds = None              # plan default (CORE_KINDS)
        self.partition_s = partition_s if partition_s is not None \
            else knobs.get_float("FMT_SOAK_PARTITION_S")
        self.crash_hold_s = crash_hold_s if crash_hold_s is not None \
            else knobs.get_float("FMT_SOAK_CRASH_HOLD_S")


def background_fault_plan(seed: int, p: float) -> faults.FaultPlan:
    """The permanently-armed chaos rider: seeded probability rules on
    the PR 5 injection points, active for the WHOLE run.  gossip
    drops are repaired by redelivery/anti-entropy, deliver stream
    deaths by the failover source, raft submit faults by client
    retry — each fired fault exercises the mechanism built for it."""
    return (faults.FaultPlan()
            .add("gossip.comm.drop", mode="drop", p=p, seed=seed)
            .add("deliver.stream", p=p / 2, seed=seed + 1, kind="io")
            .add("orderer.raft.submit", p=p / 4, seed=seed + 2,
                 kind="io"))


def _first_config_block_at_or_after(ledger, start: int) -> Optional[int]:
    for num in range(max(1, start), ledger.height):
        block = ledger.get_block_by_number(num)
        if block is None:
            continue
        try:
            env = protoutil.get_envelopes(block)[0]
            payload = protoutil.unmarshal_envelope_payload(env)
            ch = m.ChannelHeader.decode(payload.header.channel_header)
            if ch.type == m.HeaderType.CONFIG:
                return num
        except Exception:
            continue
    return None


class SoakHarness:
    def __init__(self, config: Optional[SoakConfig] = None,
                 root: Optional[str] = None):
        self.cfg = config or SoakConfig()
        self._root = root
        self.plan = ChurnPlan(self.cfg.seed, self.cfg.n_events,
                              gap_txs=self.cfg.gap_txs,
                              kinds=self.cfg.kinds)
        self._rng = random.Random(self.cfg.seed ^ 0xC0FFEE)
        # satellite contract: exactly one join per run takes the
        # snapshot fast lane; the rest replay from genesis, and the
        # convergence gate proves both lanes land on one fingerprint
        self._snap_join_done = False

    # -- event execution ---------------------------------------------------

    def _wait_leaders(self, world: SoakWorld, timeout_s: float = 30.0):
        deadline = time.monotonic() + timeout_s
        for cid in world.channel_ids:
            while world.leader_of(cid) is None:
                if time.monotonic() > deadline:
                    raise SoakError(
                        f"no raft leader elected on {cid}", self.plan)
                time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design

    def _fire(self, world: SoakWorld, kind: str) -> Dict:
        """Execute one churn event; returns event-specific context the
        post-convergence assertions use."""
        ctx: Dict = {"kind": kind}
        if kind == "peer_join":
            snap = not self._snap_join_done
            self._snap_join_done = True
            ctx["peer"] = world.add_peer(snapshot=snap).name
            ctx["snapshot_join"] = snap
        elif kind == "peer_crash_rejoin":
            victim = world.crash_peer()
            ctx["peer"] = victim.name
            # the down window: traffic keeps flowing (the lanes run in
            # their own threads) so the rejoin has a real tail for
            # _recover + gossip to catch up
            time.sleep(self.cfg.crash_hold_s)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
            world.rejoin_peer(victim)
        elif kind == "orderer_restart":
            ctx["orderer"] = world.restart_orderer(
                hold_s=self.cfg.crash_hold_s)
        elif kind == "network_partition":
            peer_names, ord_ids = world.install_partition()
            ctx["peers"] = peer_names
            ctx["orderers"] = ord_ids
            # scheduled heal: hold the cut under live traffic, then
            # let the fingerprint-convergence window gate the merge
            time.sleep(self.cfg.partition_s)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
            world.heal_partition(peer_names, ord_ids)
        elif kind == "acl_revoke":
            ctx["pre_h"] = world.revoke_audit_org()
        elif kind == "batch_config":
            cid = world.channel_ids[
                self._rng.randrange(len(world.channel_ids))]
            ctx["channel"] = cid
            ctx["max_message_count"] = world.set_batch_size(cid)
        elif kind == "consenter_add":
            ctx["orderer"] = world.add_consenter()
        elif kind == "consenter_remove":
            ctx["orderer"] = world.remove_consenter()
        elif kind == "leader_kill":
            # leadership can flip between the wait and the read (the
            # clock pump keeps election timers moving): retry until a
            # victim is actually caught, with a bounded budget
            deadline = time.monotonic() + 30.0
            victim = None
            while victim is None:
                self._wait_leaders(world)
                victim = world.leader_of(world.channel_ids[0])
                if victim is None and time.monotonic() > deadline:
                    raise SoakError(
                        "leader_kill: no stable leader to kill on "
                        f"{world.channel_ids[0]}", self.plan)
            ctx["orderer"] = victim
            world.kill_orderer(victim)
            if world.relay:
                # relay-mode amplifier: cut the gossip relay ROOT off
                # the channel too — survivors must expire it, elect a
                # new root, and reparent the tree while the raft layer
                # is itself electing; the victim (still leader in its
                # own view) converges through its own deliver client
                ctx["relay_root"] = world.partition_relay_leader(
                    world.channel_ids[0])
        else:                              # pragma: no cover
            raise SoakError(f"unknown event kind {kind!r}", self.plan)
        log.info("soak: fired %s %s", kind, ctx)
        return ctx

    def _post_event(self, world: SoakWorld, checker: InvariantChecker,
                    ctx: Dict) -> None:
        """Event-specific steady-state assertions (after convergence)."""
        if ctx["kind"] == "acl_revoke":
            sub = world.subscriber
            cid0 = world.channel_ids[0]
            ledger = world.peers[0].channels[cid0].ledger
            cfg_num = _first_config_block_at_or_after(
                ledger, ctx["pre_h"])
            if cfg_num is None:
                raise SoakError(
                    "acl_revoke: no config block found on the event "
                    "channel after the update", self.plan)
            if not sub.done(timeout_s=checker.window_s):
                raise SoakError(
                    "acl_revoke: revoked subscriber still streaming "
                    "after the revocation block committed", self.plan)
            if sub.status != m.Status.FORBIDDEN:
                raise SoakError(
                    f"acl_revoke: subscriber ended with "
                    f"{sub.status!r}, not FORBIDDEN "
                    f"(error={sub.error!r})", self.plan)
            late = [n for n in sub.received if n >= cfg_num]
            if late:
                raise SoakError(
                    f"acl_revoke: subscriber received post-revocation "
                    f"block(s) {late} (revocation at {cfg_num})",
                    self.plan)
            ctx["cut_at_block"] = cfg_num
            ctx["received_before_cut"] = len(sub.received)
        elif ctx["kind"] == "leader_kill":
            # post-event traffic already committed, so the survivors
            # MUST have elected a new, different leader by now — a
            # None here means leadership wedged (leader_of can never
            # return the dead orderer, so only the None and != checks
            # are meaningful)
            cid0 = world.channel_ids[0]
            new_leader = world.leader_of(cid0)
            if new_leader is None or new_leader == ctx["orderer"]:
                raise SoakError(
                    f"leader_kill: no replacement leader on {cid0} "
                    f"after killing {ctx['orderer']} "
                    f"(leader_of={new_leader!r})", self.plan)
            ctx["new_leader"] = new_leader
        elif ctx["kind"] == "peer_crash_rejoin":
            # convergence already proved one fingerprint across every
            # peer INCLUDING the rejoin; make the replay explicit: the
            # rejoined ledger must match p0 on every channel.  The
            # post-event traffic phase ran between that gate and this
            # check, so quiesce and compare at identical heights —
            # an instantaneous read races in-flight commits and fakes
            # a divergence out of ordinary catch-up lag.
            peer = next(p for p in world.peers
                        if p.name == ctx["peer"])
            checker.workload.pause()
            try:
                deadline = time.monotonic() + checker.window_s
                while True:
                    lag = None
                    for cid in world.channel_ids:
                        if peer.height(cid) != \
                                world.peers[0].height(cid) or \
                                peer.fingerprint(cid) != \
                                world.peers[0].fingerprint(cid):
                            lag = cid
                            break
                    if lag is None:
                        break
                    if time.monotonic() >= deadline:
                        raise SoakError(
                            f"peer_crash_rejoin: {peer.name} diverged "
                            f"on {lag} after recovery replay "
                            f"(height {peer.height(lag)} vs p0 "
                            f"{world.peers[0].height(lag)})",
                            self.plan)
                    time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
            finally:
                checker.workload.resume()
            ctx["heights"] = {cid: peer.height(cid)
                              for cid in world.channel_ids}
        elif ctx["kind"] == "orderer_restart":
            oid = ctx["orderer"]
            o = next((x for x in world.live_orderers()
                      if x.oid == oid), None)
            if o is None:
                raise SoakError(
                    f"orderer_restart: {oid} not live after its "
                    "restart", self.plan)
            for cid in world.channel_ids:
                sup = o.registrar.get_chain(cid)
                if sup is None:
                    raise SoakError(
                        f"orderer_restart: {oid} lost channel {cid} "
                        "across the restart", self.plan)
            ctx["store_heights"] = {
                cid: o.registrar.get_chain(cid).store.height
                for cid in world.channel_ids}
        elif ctx["kind"] == "network_partition":
            for cid in world.channel_ids:
                if world.networks[cid].partitioned or \
                        world.transports[cid].partitioned:
                    raise SoakError(
                        f"network_partition: seam on {cid} still "
                        "holds a cut after the scheduled heal",
                        self.plan)

    def _run_traffic(self, workload: MixedWorkload, gap_txs: int,
                     label: str) -> float:
        """One mixed-traffic phase: wait until `gap_txs` more x509
        submissions succeeded; returns the phase's submit rate."""
        c0 = workload.counts()["x509"]
        t0 = time.monotonic()
        budget = max(30.0, gap_txs * (self.cfg.x509_gap_s + 2.0) * 4)
        while workload.counts()["x509"] < c0 + gap_txs:
            if workload.errors:
                raise SoakError(f"workload failed during {label}: "
                                f"{workload.errors}", self.plan)
            if time.monotonic() - t0 > budget:
                raise SoakError(
                    f"traffic stalled during {label}: "
                    f"{workload.counts()['x509'] - c0}/{gap_txs} txs "
                    f"in {budget:.0f}s", self.plan)
            time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        return gap_txs / max(1e-9, time.monotonic() - t0)

    # -- the run -----------------------------------------------------------

    def run(self) -> Dict:
        if self._root is not None:
            return self._run_in(self._root)
        with tempfile.TemporaryDirectory(prefix="fmt_soak_") as root:
            return self._run_in(root)

    def _run_in(self, root: str) -> Dict:
        from fabric_mod_tpu.observability import tracing
        cfg = self.cfg
        t_start = time.monotonic()
        trace_t0 = ({k: v["secs"]
                     for k, v in tracing.substage_totals().items()}
                    if tracing.armed() else None)
        world = SoakWorld(root, cfg.seed, n_channels=cfg.n_channels,
                          n_peers=cfg.n_peers)
        workload = MixedWorkload(world, x509_gap_s=cfg.x509_gap_s,
                                 idemix_gap_s=cfg.idemix_gap_s)
        checker = InvariantChecker(
            world, workload, self.plan,
            recovery_window_s=cfg.recovery_window_s,
            min_recovery_frac=cfg.min_recovery_frac)
        chaos = background_fault_plan(cfg.seed, cfg.fault_p)
        events_report: List[Dict] = []
        rates: List[float] = []
        try:
            with faults.active(chaos):
                world.start()
                self._wait_leaders(world)
                workload.start()
                checker.beat()
                # warmup phase: prove the steady state BEFORE churn
                rates.append(self._run_traffic(
                    workload, max(3, cfg.gap_txs[0]), "warmup"))
                checker.check_converged("warmup", record=False)
                for ev in self.plan.events:
                    rates.append(self._run_traffic(
                        workload, ev.gap_txs, f"pre-{ev.kind}"))
                    ctx = self._fire(world, ev.kind)
                    ctx["recovery_s"] = round(
                        checker.check_converged(ev.kind), 3)
                    post_rate = self._run_traffic(
                        workload, max(3, cfg.gap_txs[0]),
                        f"post-{ev.kind}")
                    checker.check_recovery_rate(ev.kind, rates[-1],
                                                post_rate)
                    ctx["pre_rate"] = round(rates[-1], 2)
                    ctx["post_rate"] = round(post_rate, 2)
                    rates.append(post_rate)
                    self._post_event(world, checker, ctx)
                    if ctx.get("relay_root") is not None:
                        # heal the partitioned root: the returning
                        # minimum reclaims leadership and the tree
                        # reparents AGAIN — that second transition is
                        # the recorded relay_reparent recovery
                        world.heal_relay_leader(world.channel_ids[0],
                                                ctx["relay_root"])
                        ctx["relay_reparent_s"] = round(
                            checker.check_converged("relay_reparent"),
                            3)
                    checker.check_lanes()
                    events_report.append(ctx)
                # tail: stop lanes, settle, audit the whole run
                workload.stop()
                checker.check_converged("final", record=False)
                audited = checker.audit_exactly_once()
                fault_fires = chaos.fires()
                if fault_fires == 0:
                    raise SoakError(
                        "background fault plan never fired — the "
                        "chaos rider is disconnected from its "
                        "injection points", self.plan)
                relay_report = None
                if world.relay:
                    relay_report = world.relay_stats()
                    if relay_report.get("received", 0) == 0:
                        raise SoakError(
                            "FMT_SOAK_RELAY: the dissemination relay "
                            "never carried a block — every peer "
                            "converged via fallback paths only, so "
                            "the tree under test did nothing",
                            self.plan)
        except SoakError:
            raise
        except Exception as e:
            raise SoakError(f"soak run failed: {e!r}", self.plan) from e
        finally:
            try:
                workload.stop()
            except Exception:  # fmtlint: allow[swallowed-exceptions] -- finally-block teardown: a stop() failure must not mask the run's SoakError
                pass
            world.close()
            checker.close_health()
        checker.check_thread_leaks()
        wall = time.monotonic() - t_start
        counts = workload.counts()
        report = {
            "seed": cfg.seed,
            "schedule": [e.to_dict() for e in self.plan.events],
            "events": events_report,
            "wall_secs": round(wall, 2),
            "x509_txs": counts["x509"],
            "idemix_txs": counts["idemix"],
            "idemix_tamper_rejects": counts["idemix_tamper_rejects"],
            "submit_errors": counts["submit_errors"],
            "mixed_tx_per_sec": round(
                (counts["x509"] + counts["idemix"]) / wall, 2),
            "x509_tx_per_sec": round(counts["x509"] / wall, 2),
            "idemix_tx_per_sec": round(counts["idemix"] / wall, 2),
            "audited_txs": audited,
            "fault_fires": fault_fires,
            "recovery_s_by_kind": {
                k: [round(x, 3) for x in v]
                for k, v in checker.recovery_by_kind.items()},
            "peers_final": len(world.peers),
            "channels": world.channel_ids,
            # FMT_SOAK_SHARDED: churn rode the per-peer shard routers
            "sharded": world.sharded,
            # FMT_SOAK_RELAY: blocks rode dissemination trees
            "relay_mode": world.relay,
        }
        if relay_report is not None:
            report["relay"] = relay_report
        if trace_t0 is not None:
            # commit-path stage attribution across the whole run (the
            # FMT_TRACE sub-span totals accumulated since t_start)
            report["stage_attribution"] = {
                k: round(v["secs"] - trace_t0.get(k, 0.0), 3)
                for k, v in tracing.substage_totals().items()}
        log.info("soak: PASS %s", report)
        return report


def run_soak(config: Optional[SoakConfig] = None) -> Dict:
    return SoakHarness(config).run()
