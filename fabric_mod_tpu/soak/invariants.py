"""Soak invariants: what must stay true under churn, checked loudly.

Steady-state hypotheses (the chaos-engineering contract — every one
is asserted after EVERY event and at run end, and a violation raises
SoakError carrying the seed + exact schedule needed to replay):

  convergence   within a bounded recovery window after an event, all
                peers' KvLedger.state_fingerprint() agree at the
                orderer tip on every channel (the PR 3 differential
                oracle, promoted to a fleet-wide invariant)
  exactly-once  every envelope the ordering service ACKED commits
                exactly once across the whole run (the broadcaststorm
                ledger audit, extended across churn: txs lost to a
                leader kill are resubmitted at the quiesced tail and
                still count once)
  no-leaks      no registered worker thread outlives the world's
                teardown (concurrency.assert_joined writ run-wide)
  recovery      post-event throughput recovers to at least
                `min_recovery_frac` of the pre-event rate

Observability: per-event-kind recovery-time histograms, an events
counter, and a soak heartbeat gauge on /metrics (the default
provider), so a long soak's liveness is visible from outside.
"""
from __future__ import annotations

import time
from collections import Counter
from typing import Dict, List, Optional

from fabric_mod_tpu.concurrency import live_registered
from fabric_mod_tpu.observability import get_logger
from fabric_mod_tpu.observability.metrics import (MetricOpts,
                                                  default_provider)
from fabric_mod_tpu.soak.workload import committed_txids

log = get_logger("soak.invariants")

_RECOVERY_HIST = default_provider().histogram(MetricOpts(
    "fabric", "soak", "recovery_seconds",
    "Per-churn-event recovery time until fingerprints reconverged",
    ("kind",)))
_EVENTS_TOTAL = default_provider().counter(MetricOpts(
    "fabric", "soak", "events_total",
    "Churn events executed by the soak harness", ("kind",)))
_HEARTBEAT = default_provider().gauge(MetricOpts(
    "fabric", "soak", "heartbeat",
    "Monotonic soak progress beat (events completed so far)", ()))


class SoakError(AssertionError):
    """A violated soak invariant.  The message always embeds the seed
    and the full event schedule (the replay contract) — and, with
    FMT_TRACE armed, the flight-recorder tail: the last block
    timelines and events around the failure, so the report says what
    the system was DOING, not just which invariant broke."""

    def __init__(self, msg: str, plan=None):
        if plan is not None:
            msg = f"{msg}\n{plan.describe()}"
        from fabric_mod_tpu.observability import tracing
        if tracing.armed():
            tracing.auto_dump("soak_error")
            msg = f"{msg}\n{tracing.flight_text()}"
        super().__init__(msg)


class InvariantChecker:
    def __init__(self, world, workload, plan,
                 recovery_window_s: float = 45.0,
                 min_recovery_frac: float = 0.05):
        self.world = world
        self.workload = workload
        self.plan = plan
        self.window_s = recovery_window_s
        self.min_recovery_frac = min_recovery_frac
        self.recovery_by_kind: Dict[str, List[float]] = {}
        self._events_done = 0
        # baseline by OBJECT identity, not name: registered-thread
        # names repeat across instances ("gossip-state-drain" etc.),
        # so a name-set baseline would mask every leaked thread that
        # shares a name with one alive at construction (strong refs,
        # so a recycled id() can never alias a baseline entry)
        self._thread_baseline = set(live_registered())
        # real health: a soak whose heartbeat goes stale (no event
        # completed for 2 recovery windows) flips /healthz so a
        # wedged long run is visible from outside the process
        self._last_beat_wall = time.monotonic()
        from fabric_mod_tpu.observability.opsserver import default_health
        default_health().register("soak-heartbeat", self._health_check)

    def _health_check(self) -> None:
        stale = time.monotonic() - self._last_beat_wall
        budget = max(2 * self.window_s, 90.0)
        if stale > budget:
            raise RuntimeError(
                f"soak heartbeat stale: {stale:.0f}s since the last "
                f"completed event (budget {budget:.0f}s)")

    def close_health(self) -> None:
        """Drop the heartbeat checker (harness teardown — a finished
        soak must not leave /healthz reporting staleness forever)."""
        from fabric_mod_tpu.observability.opsserver import default_health
        default_health().unregister("soak-heartbeat")

    def beat(self) -> None:
        self._last_beat_wall = time.monotonic()
        _HEARTBEAT.set(float(self._events_done))

    # -- convergence -------------------------------------------------------

    def _stable_tip(self, cid: str, deadline: float) -> int:
        """Wait until the live orderers' stores agree and stop
        growing (in-flight batches flushed by the batch timer)."""
        last, last_t = -1, time.monotonic()
        while time.monotonic() < deadline:
            sups = self.world.supports(cid)
            heights = {s.store.height for s in sups.values()}
            if len(heights) == 1:
                tip = heights.pop()
                if tip != last:
                    last, last_t = tip, time.monotonic()
                elif time.monotonic() - last_t >= 0.4:
                    return tip
            time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        sups = self.world.supports(cid)
        raise SoakError(
            f"orderer tips on {cid} did not stabilize within the "
            f"recovery window: "
            f"{[(o, s.store.height) for o, s in sups.items()]}",
            self.plan)

    def check_converged(self, kind: str,
                        window_s: Optional[float] = None,
                        record: bool = True) -> float:
        """Quiesce traffic, then require every peer at the stable
        orderer tip with a SINGLE state fingerprint per channel,
        within the recovery window.  Returns the recovery time and
        feeds the per-kind histogram.  The window bounds how long the
        checker WAITS for convergence; the returned recovery time can
        exceed it slightly when the straddling settle iteration (its
        own fingerprint computation included) succeeds at the
        boundary — only a deadline passing WITHOUT convergence
        fails.  `record=False` for the
        warmup/final/resubmit convergence checks: they are harness
        phases, not churn events, and must not pollute the
        events_total counter or the per-event-kind recovery report."""
        window = window_s if window_s is not None else self.window_s
        t0 = time.monotonic()
        deadline = t0 + window
        self.workload.pause()
        try:
            for cid in self.world.channel_ids:
                tip = self._stable_tip(cid, deadline)
                # fingerprints are only comparable at IDENTICAL,
                # settled heights: the digest covers the chain height,
                # and a block cut late (a parked raft submit
                # re-injected after the stability window) can put one
                # peer a block ahead of the rest for a moment — that
                # is catch-up, not divergence.  Heights are re-read
                # around the (slow) fingerprint computation so a
                # commit racing the reads voids the sample instead of
                # faking a divergence.
                while True:
                    h0 = [p.height(cid) for p in self.world.peers]
                    settled = (len(set(h0)) == 1 and
                               h0[0] >= self.world.orderer_tip(cid))
                    if settled:
                        fps = {p.name: p.fingerprint(cid)
                               for p in self.world.peers}
                        if h0 == [p.height(cid)
                                  for p in self.world.peers]:
                            if len(set(fps.values())) == 1:
                                break      # converged
                            # identical stable heights, different
                            # digests: the same chain prefix committed
                            # to different state — the REAL divergence
                            raise SoakError(
                                f"after {kind}: state fingerprints "
                                f"DIVERGED on {cid} at height {h0[0]}"
                                f": {fps}", self.plan)
                    if time.monotonic() >= deadline:
                        raise SoakError(
                            f"after {kind}: peers did not converge on "
                            f"{cid} within {window:.1f}s (tip {tip}): "
                            f"heights={[(p.name, p.height(cid)) for p in self.world.peers]}",
                            self.plan)
                    time.sleep(0.05)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        finally:
            self.workload.resume()
        rec = time.monotonic() - t0
        if record:
            self.recovery_by_kind.setdefault(kind, []).append(rec)
            _RECOVERY_HIST.with_labels(kind).observe(rec)
            _EVENTS_TOTAL.with_labels(kind).add(1)
            self._events_done += 1
            self.beat()
        log.info("soak: converged %.2fs after %s", rec, kind)
        return rec

    # -- throughput recovery -----------------------------------------------

    def check_recovery_rate(self, kind: str, pre_rate: float,
                            post_rate: float) -> None:
        if pre_rate <= 0:
            return
        if post_rate < self.min_recovery_frac * pre_rate:
            raise SoakError(
                f"after {kind}: throughput did not recover — "
                f"{post_rate:.2f} tx/s vs pre-event {pre_rate:.2f} "
                f"(floor {self.min_recovery_frac:.2f}x)", self.plan)

    # -- lane health -------------------------------------------------------

    def check_lanes(self) -> None:
        if self.workload.errors:
            raise SoakError(
                f"workload lane failure: {self.workload.errors}",
                self.plan)

    # -- exactly-once ------------------------------------------------------

    def audit_exactly_once(self, resubmit_rounds: int = 3) -> int:
        """Admitted => committed exactly once, per channel, across the
        whole run.  An admitted tx missing at the quiesced tail was
        lost to a leader kill (a broadcast ACK is not a commit — the
        client contract is watch-and-resubmit), so its RETAINED
        envelope is resubmitted and must then commit; any txid
        committing twice fails the run outright.  Returns total
        audited txs."""
        total = 0
        for cid in self.world.channel_ids:
            admitted = set(self.workload.admitted_txids(cid))
            for attempt in range(resubmit_rounds + 1):
                committed = committed_txids(
                    self.world.peers[0].channels[cid].ledger)
                counts = Counter(committed)
                dupes = {t for t, n in counts.items() if n > 1}
                if dupes:
                    raise SoakError(
                        f"txids committed MORE THAN ONCE on {cid}: "
                        f"{sorted(dupes)[:5]}", self.plan)
                missing = admitted - set(committed)
                if not missing:
                    break
                if attempt == resubmit_rounds:
                    raise SoakError(
                        f"{len(missing)} admitted txs never committed "
                        f"on {cid} after {resubmit_rounds} resubmit "
                        f"rounds: {sorted(missing)[:5]}", self.plan)
                log.info("soak: resubmitting %d lost txs on %s",
                         len(missing), cid)
                for txid in sorted(missing):
                    try:
                        self.workload.resubmit(cid, txid)
                    except Exception as e:  # noqa: BLE001
                        log.warning("resubmit %s failed: %s", txid, e)
                self.check_converged(f"resubmit[{cid}]", record=False)
            total += len(admitted)
        return total

    # -- teardown leaks ----------------------------------------------------

    def check_thread_leaks(self, grace_s: float = 5.0) -> None:
        """After world close: no registered worker this run started
        may still be alive (the concurrency subsystem's leak contract
        applied to the whole soak)."""
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            leaked = [t for t in live_registered()
                      if t not in self._thread_baseline]
            if not leaked:
                return
            time.sleep(0.1)  # fmtlint: allow[clocks] -- real OS-thread pacing: the soak's ManualClock accelerates raft only; harness waits are wall-time by design
        names = sorted(f"{t.structure}:{t.name}" for t in leaked)
        raise SoakError(
            f"{len(leaked)} worker thread(s) leaked at soak teardown: "
            f"{names}", self.plan)
