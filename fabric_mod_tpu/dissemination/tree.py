"""RelayTree: the dissemination tree as a pure function.

Every peer computes the tree from exactly three inputs — the sorted
alive-membership snapshot, the elected leader, and an epoch — so all
peers with a converged membership view derive the IDENTICAL tree with
zero coordination messages (the same trick the deterministic
min-PKI-ID election plays for leadership: agreement falls out of a
shared view plus a shared pure function, reference:
gossip/election/election.go's converged-view computation).

Layout: the BFS array ``[leader] + rotate(sorted(others), epoch)``
with fan-out degree d — node at index i parents indices
``d*i+1 .. d*i+d``.  The epoch rotation re-deals interior positions
across epochs so relay load does not pin to the lexicographically
smallest endpoints forever.

Reparenting is the same pure function over the shrunken membership:
``tree.without(dead)`` is what every survivor independently computes
when discovery expires a member, and :func:`reparent_plan` names
exactly which members moved (the soak's relay lane asserts recovery
after such a move).  A dead LEADER is the election's job — `without`
falls back to the deterministic minimum of the survivors, mirroring
what the election converges to.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from fabric_mod_tpu.utils import knobs


class RelayTree:
    """One channel's relay tree over opaque, orderable member ids
    (gossip endpoints in production)."""

    __slots__ = ("leader", "epoch", "degree", "order", "_index")

    def __init__(self, members: Iterable[str], leader: str,
                 epoch: int = 0, degree: Optional[int] = None):
        if degree is None:
            degree = knobs.get_int("FABRIC_MOD_TPU_RELAY_DEGREE")
        self.degree = max(1, int(degree))
        self.leader = leader
        self.epoch = int(epoch)
        others = sorted(mm for mm in set(members) if mm != leader)
        if others:
            r = self.epoch % len(others)
            others = others[r:] + others[:r]
        self.order: Tuple[str, ...] = (leader, *others)
        self._index: Dict[str, int] = {mm: i for i, mm
                                       in enumerate(self.order)}

    # -- pure queries ------------------------------------------------------
    def __contains__(self, member: str) -> bool:
        return member in self._index

    def __len__(self) -> int:
        return len(self.order)

    def children(self, member: str) -> List[str]:
        """The members `member` pushes frames to ([] for leaves and
        for members outside the tree — a peer whose view has not
        converged yet simply relays to nobody rather than guessing)."""
        i = self._index.get(member)
        if i is None:
            return []
        lo = i * self.degree + 1
        return list(self.order[lo:lo + self.degree])

    def parent(self, member: str) -> Optional[str]:
        i = self._index.get(member)
        if i is None or i == 0:
            return None
        return self.order[(i - 1) // self.degree]

    def depth(self, member: str) -> int:
        """Hops from the leader (-1 for a non-member)."""
        i = self._index.get(member)
        if i is None:
            return -1
        d = 0
        while i > 0:
            i = (i - 1) // self.degree
            d += 1
        return d

    # -- reparenting -------------------------------------------------------
    def without(self, dead: str) -> "RelayTree":
        """The tree every survivor derives once `dead` expires from
        the membership view.  Same leader/epoch/degree — unless the
        leader itself died, in which case the deterministic minimum of
        the survivors roots the new tree (the value the min-PKI
        election converges to, modulo the id space)."""
        members = [mm for mm in self.order if mm != dead]
        leader = self.leader
        if dead == leader:
            leader = min(members) if members else ""
        return RelayTree(members, leader, epoch=self.epoch,
                         degree=self.degree)


def reparent_plan(old: RelayTree,
                  new: RelayTree) -> Dict[str, Tuple[Optional[str],
                                                     Optional[str]]]:
    """member -> (old_parent, new_parent) for every member present in
    both trees whose parent changed — the exact set of peers that must
    start accepting frames from a new upstream after a membership
    change (pure bookkeeping: the relay needs no handshake, because
    frames are self-describing and commits are gated by the state
    buffer either way)."""
    plan: Dict[str, Tuple[Optional[str], Optional[str]]] = {}
    for member in new.order:
        if member not in old:
            continue
        was, now = old.parent(member), new.parent(member)
        if was != now:
            plan[member] = (was, now)
    return plan
